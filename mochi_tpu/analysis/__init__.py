"""Project-native static analysis for mochi-tpu.

``python -m mochi_tpu.analysis [paths...]`` runs five AST checkers tuned to
this codebase's failure modes (see docs/ANALYSIS.md):

* ``async-blocking``       — blocking calls inside coroutine bodies
* ``cancellation-hygiene`` — handlers that swallow asyncio cancellation
* ``jax-trace-safety``     — host sync / Python branching in traced code
* ``constant-time``        — timing-oracle comparisons on authenticators
* ``protocol-invariants``  — payload registration + quorum-math locality

Programmatic entry point: :func:`mochi_tpu.analysis.core.run`.  The pass is
wired into tier-1 (``tests/test_static_analysis.py``) and into the bench
gate (``scripts/standing_rules.py``), so a finding fails CI, not code
review.
"""

from .core import Finding, RunResult, all_rules, run

__all__ = ["Finding", "RunResult", "all_rules", "run"]

"""span-lazy-label: no eager string formatting in span-record arguments.

Head-based sampling means a span-record call on the drain hot loop is a
no-op for the overwhelming share of traffic (``Tracer.record`` returns
before touching its arguments when the context is unsampled) — but Python
evaluates ARGUMENTS before the call, so an f-string label or a
``"%s" % sid`` built at the call site is paid for every envelope whether
or not the span records.  That is the classic tracing perf bug: the
instrumentation's cost shows up exactly on the path it was supposed to be
free on, and the round-15 ≤3% overhead bound (config-7 A/B) depends on
not writing it.

The rule: a call to a tracer's span-recording surface (``record`` /
``span`` / ``mark_span`` on an object whose dotted path names a tracer)
must not format strings in its arguments — f-strings, ``%``, ``.format``,
or ``str1 + expr`` concatenation.  Pass constants and plain values; let
the tracer build labels after the sampling gate.

Exempt:

* ``force_mark(...)`` — the always-sample upgrade path (errors, sheds,
  convictions): it records unconditionally and fires rarely, so eager
  formatting is paid only when evidence is actually being written;
* call sites syntactically guarded by the sampling verdict — inside an
  ``if`` whose test consults ``.sampled`` or ``.wants(...)`` (the caller
  already established the span records before paying the formatting).

Scope (``scoped=True``): ``obs/``, ``net/``, ``server/`` — the tracing
module itself and the two packages whose code runs inside the drain hot
loop.  The tentpole's acceptance includes this pass running CLEAN over
``mochi_tpu/obs/`` with zero suppressions.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import Finding, dotted_name, snippet_at

RULE = "span-lazy-label"

# Span-recording method names subject to the rule; force_mark is the
# deliberate always-sampled exemption (see module docstring).
_SPAN_METHODS = {"record", "span", "mark_span"}


def _is_tracer_call(func: ast.AST) -> Optional[str]:
    """The method name when ``func`` is ``<something tracer-ish>.<span
    method>``; None otherwise.  "tracer-ish": some dotted segment names a
    tracer (``self.tracer``, ``tracer``, ``self._tracer`` ...) — precise
    enough to skip ``Timer.record`` / ``Metrics`` call sites entirely."""
    if not isinstance(func, ast.Attribute) or func.attr not in _SPAN_METHODS:
        return None
    dn = dotted_name(func.value)
    if dn is None:
        return None
    if any("tracer" in seg.lower() for seg in dn.split(".")):
        return func.attr
    return None


def _formats_string(expr: ast.AST) -> Optional[str]:
    """The kind of eager string formatting inside ``expr``, or None."""
    for node in ast.walk(expr):
        if isinstance(node, ast.JoinedStr):
            return "f-string"
        if isinstance(node, ast.BinOp):
            if isinstance(node.op, ast.Mod) and _is_str(node.left):
                return "%-format"
            if isinstance(node.op, ast.Add) and (
                _is_str(node.left) or _is_str(node.right)
            ):
                return "string concatenation"
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "format"
        ):
            return ".format()"
    return None


def _is_str(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, str)


def _test_gates_sampling(test: ast.AST) -> bool:
    """True when an ``if`` test consults the sampling verdict
    (``ctx.sampled`` / ``tracer.wants(ctx)``): formatting below it only
    runs for spans that actually record."""
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute) and node.attr == "sampled":
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "wants"
        ):
            return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, src_lines, path):
        self.src_lines = src_lines
        self.path = path
        self.findings: List[Finding] = []
        self._guard_depth = 0

    def visit_If(self, node: ast.If) -> None:
        if _test_gates_sampling(node.test):
            self._guard_depth += 1
            for child in node.body:
                self.visit(child)
            self._guard_depth -= 1
            for child in node.orelse:
                self.visit(child)
        else:
            self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        method = _is_tracer_call(node.func)
        if method is not None and self._guard_depth == 0:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                kind = _formats_string(arg)
                if kind is not None:
                    self.findings.append(
                        Finding(
                            RULE, self.path, node.lineno, node.col_offset,
                            f"eager {kind} in `.{method}(...)` argument: "
                            "evaluated per call even when the span is "
                            "unsampled — pass plain values (or guard with "
                            "`tracer.wants(ctx)` / `ctx.sampled`)",
                            snippet_at(self.src_lines, node.lineno),
                        )
                    )
                    break
        self.generic_visit(node)


def _in_scope(path: str) -> bool:
    parts = path.split("/")
    return "obs" in parts or "net" in parts or "server" in parts


def check(tree: ast.Module, src: str, path: str, scoped: bool = True) -> List[Finding]:
    if scoped and not _in_scope(path):
        return []
    visitor = _Visitor(src.splitlines(), path)
    visitor.visit(tree)
    return visitor.findings

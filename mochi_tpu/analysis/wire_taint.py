"""wire-taint: every protocol decision must be anchored to verified bytes.

ROADMAP item 1 commits to DSig-style fast paths (session-MAC shortcuts,
aggregated certificate attestations, speculative verify-behind-commit) that
deliberately remove per-message signature checks from the hot path.  The
protocol's safety argument is exactly "nothing unverified reaches a quorum
decision" — so before verification is carved out of the critical path, that
property must be machine-checked, not conventional.

This pass is an interprocedural taint analysis over the scanned tree:

* **Sources** — values born at a wire or disk seam are TAINTED: envelope
  decode (``codec.decode_env`` / ``messages.decode_envelope``), transport
  deliveries (``send_and_receive`` / ``fan_out`` responses), WAL records
  read at recovery (``wal.iter_log`` / ``scan_segment``), snapshot docs
  (``read_snapshot_doc``), and generic wire-object decode (``from_obj``).
  Replica batch entry points (``handle_batch`` & friends) are **entry**
  edges: their parameters arrive straight off the transport and start
  tainted too (the transport hands them over via dynamic callbacks the
  call graph cannot see).

* **Sanitizers** — sanctioned verifier edges confer verification *classes*
  on everything derived from the value they checked: the envelope MAC /
  Ed25519 check (``env``), grant/certificate signature verification
  (``cert``), the admin-key gate (``admin``), and WAL reclaim-record
  authentication (``wal``).  The pooled ``verify_batch`` bitmap engine
  confers ``env``+``cert`` (it carries both item kinds in one round trip).

* **Sinks** — protocol-decision points declare which classes must have
  been conferred (CNF: a tuple of any-of groups): store
  ``process_write1/write2`` apply, sync-entry adoption, config install,
  quorum tally acceptance, certificate-subset assembly, grant-ledger /
  ban-book / reclaimed-ledger writes, client eviction.

A tainted value reaching a sink with a required class missing is a finding
— including *across function boundaries*: per-function summaries record
which parameters flow to which sinks unverified, which classes a callee
confers on its arguments, and whether a return value is wire-tainted; a
bounded fixpoint propagates them over the (best-effort) call graph, so the
conviction lands at the call site where the unverified value actually
crossed into the decision path.

Precision posture (documented in docs/ANALYSIS.md): statement walks are
linear (a verifier call dominates everything textually after it — same
approximation as the await-races pass), derivation is root-coarse (checking
``env.signing_bytes()`` vindicates everything reached through ``env``), and
values assembled from several roots take the UNION of their roots' conferred
classes (sinks re-check structural binding — txn-hash match, quorum count —
internally).  Each choice trades soundness-in-the-limit for a clean,
actionable pass; the non-vacuity fixtures in tests/ prove every sanctioned
edge is still load-bearing (deleting it convicts the downstream sink).

Fast paths MUST register their new verifier edges here (see
:func:`register_verifier_edge`) instead of silently bypassing the lattice;
``expect_live`` edges double as harness-rot tripwires — a full-tree scan
that no longer observes a sanctioned edge at any call site reports
``registry-rot`` (the edge was renamed/bypassed without updating the
registry, exactly the drift the fast-path work could introduce).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .core import Finding, build_import_map, resolve_call, suffix_match

RULE = "wire-taint"

# Verification classes (the lattice's labels).  ``env`` = envelope
# authenticity (MAC or Ed25519 over the signed prefix); ``cert`` =
# grant/certificate signature + 2f+1 subset machinery; ``admin`` = admin-key
# signature; ``wal`` = WAL record authentication at recovery.
CLS_ENV = "env"
CLS_CERT = "cert"
CLS_ADMIN = "admin"
CLS_WAL = "wal"


@dataclass(frozen=True)
class Edge:
    """One declarative registry entry.

    ``kind``: "source" | "sanitizer" | "sink" | "entry".
    ``pattern``: dotted-suffix call pattern ("codec.decode_env",
    "_grant_ok"); for ``match="attr-store"`` sinks, the attribute name whose
    subscript/attribute assignment is the decision ("reclaimed").
    ``confers``: classes a sanitizer confers on its arguments' roots.
    ``requires``: sink CNF — every group must intersect the conferred set.
    ``expect_live``: full-tree scans must observe this edge at >= 1 site,
    else ``registry-rot`` fires (see module docstring).
    """

    name: str
    kind: str
    pattern: str
    confers: FrozenSet[str] = frozenset()
    requires: Tuple[FrozenSet[str], ...] = ()
    match: str = "call"
    note: str = ""
    expect_live: bool = False


def _grp(*alternatives: str) -> FrozenSet[str]:
    return frozenset(alternatives)


BUILTIN_EDGES: Tuple[Edge, ...] = (
    # ------------------------------------------------------------- sources
    Edge("env-decode", "source", "codec.decode_env",
         note="wire bytes -> Envelope (mcode codec)", expect_live=True),
    Edge("env-decode-fn", "source", "decode_env"),
    Edge("envelope-decode", "source", "decode_envelope"),
    Edge("wire-obj-decode", "source", "from_obj",
         note="wire/WAL object graph -> typed message"),
    Edge("wal-scan", "source", "scan_segment",
         note="CRC-framed WAL records (CRC is torn-tail detection, not "
              "authentication)", expect_live=True),
    Edge("wal-iter", "source", "iter_log"),
    Edge("snapshot-read", "source", "read_snapshot_doc"),
    Edge("rpc-response", "source", "send_and_receive",
         note="peer/server response envelope", expect_live=True),
    Edge("rpc-fanout", "source", "fan_out",
         note="fan-out response map (client quorum paths)"),
    # ------------------------------------------------------- entry points
    Edge("replica-batch-entry", "entry", "handle_batch",
         note="transport hands decoded envelopes in via callback"),
    Edge("replica-inline-entry", "entry", "handle_inline_batch"),
    Edge("replica-envelope-entry", "entry", "handle_envelope"),
    # ---------------------------------------------------------- sanitizers
    Edge("session-mac", "sanitizer", "_auth_mac", confers=_grp(CLS_ENV),
         note="replica session-MAC envelope check", expect_live=True),
    Edge("session-mac-fn", "sanitizer", "mac_ok", confers=_grp(CLS_ENV),
         note="session_crypto.mac_ok (MAC-session fast path registers "
              "through this edge)"),
    Edge("client-envelope-auth", "sanitizer", "_authentic",
         confers=_grp(CLS_ENV), note="client-side response MAC/signature "
         "gate", expect_live=True),
    Edge("server-signature", "sanitizer", "_server_signed",
         confers=_grp(CLS_ENV)),
    Edge("admin-signature", "sanitizer", "_admin_sig_ok",
         confers=_grp(CLS_ENV, CLS_ADMIN)),
    Edge("ed25519-verify", "sanitizer", "cpu_verify",
         confers=_grp(CLS_ENV, CLS_CERT),
         note="raw Ed25519 verify (host fallback)"),
    Edge("ed25519-verify-keys", "sanitizer", "keys.verify",
         confers=_grp(CLS_ENV, CLS_CERT)),
    Edge("batch-verify", "sanitizer", "verify_batch",
         confers=_grp(CLS_ENV, CLS_CERT),
         note="pooled bitmap engine: envelope + grant items share the "
              "round trip", expect_live=True),
    Edge("grant-verify", "sanitizer", "_grant_ok", confers=_grp(CLS_CERT),
         note="client per-grant Ed25519 + txn-hash content check",
         expect_live=True),
    Edge("certificate-finish", "sanitizer", "_finish_certificate",
         confers=_grp(CLS_CERT),
         note="bitmap consumption: drops unproven grants, re-checks "
              "quorum", expect_live=True),
    Edge("certificate-recheck", "sanitizer", "_check_certificate",
         confers=_grp(CLS_CERT),
         note="resync/anti-entropy certificate re-verification",
         expect_live=True),
    Edge("wal-reclaim-auth", "sanitizer", "_reclaim_auth_ok",
         confers=_grp(CLS_WAL),
         note="reclaim-record MAC re-verification at recovery",
         expect_live=True),
    # --------------------------------------------------------------- sinks
    Edge("write1-apply", "sink", "process_write1",
         requires=(_grp(CLS_ENV, CLS_ADMIN),)),
    Edge("write1-batch-apply", "sink", "process_write1_batch",
         requires=(_grp(CLS_ENV, CLS_ADMIN),), expect_live=True),
    Edge("write2-apply", "sink", "process_write2",
         requires=(_grp(CLS_ENV, CLS_ADMIN), _grp(CLS_CERT))),
    Edge("write2-batch-apply", "sink", "process_write2_batch",
         requires=(_grp(CLS_ENV, CLS_ADMIN), _grp(CLS_CERT)),
         expect_live=True),
    Edge("read-apply", "sink", "process_read",
         requires=(_grp(CLS_ENV, CLS_ADMIN),)),
    Edge("sync-adopt", "sink", "apply_sync_entry",
         requires=(_grp(CLS_CERT),),
         note="resync/recovery adoption: certificate-anchored, envelope "
              "auth deliberately not required (certs are self-certifying)",
         expect_live=True),
    Edge("config-install", "sink", "_install_config",
         requires=(_grp(CLS_CERT),)),
    Edge("write2-tally", "sink", "_tally_write2",
         requires=(_grp(CLS_ENV),),
         note="client commit acceptance: only authenticated responses may "
              "vote"),
    Edge("grant-subset", "sink", "_quorum_grant_subset",
         requires=(_grp(CLS_ENV), _grp(CLS_CERT)),
         note="certificate assembly: grants must be envelope-authenticated "
              "AND signature/content-checked before they vote"),
    Edge("client-evict", "sink", "evict_client",
         requires=(_grp(CLS_ENV, CLS_ADMIN, CLS_CERT),)),
    Edge("admission", "sink", "admit",
         requires=(_grp(CLS_ENV, CLS_ADMIN),),
         note="admission decisions may key on sizes/counts (len() is "
              "clean) but never on unverified payload content"),
    Edge("reclaimed-ledger", "sink", "reclaimed", match="attr-store",
         requires=(_grp(CLS_WAL),),
         note="InvariantChecker audit ledger: direct writes only happen at "
              "WAL replay and need authenticated reclaim records "
              "(operational reclaims route through the process_write2 sink, "
              "which shields its internals and demands env+cert itself)"),
    Edge("ban-book", "sink", "_client_bans", match="attr-store",
         requires=(_grp(CLS_ENV, CLS_ADMIN, CLS_CERT),)),
    Edge("grant-ledger", "sink", "_grant_ledger", match="attr-store",
         requires=(_grp(CLS_CERT),),
         note="equivocation evidence: only validly-signed grants may enter"),
    Edge("config-adopt", "sink", "config", match="attr-store",
         requires=(_grp(CLS_ENV, CLS_CERT, CLS_ADMIN),),
         note="cluster-config adoption from a wire value"),
)

# Fast-path edges registered at runtime (ROADMAP item 1: MAC-session,
# aggregated-attestation).  Additive only — the builtin lattice cannot be
# weakened from here.
_RUNTIME_EDGES: List[Edge] = []


def register_verifier_edge(
    name: str,
    pattern: str,
    confers: Sequence[str],
    note: str = "",
    expect_live: bool = False,
) -> Edge:
    """Register a new sanctioned verifier edge (a fast path's check).

    The fast-path contract: removing per-message verification is only
    legitimate if its replacement check is registered here, so the lattice
    knows the new edge confers verification — otherwise every decision
    downstream of the fast path becomes a finding (by design)."""
    edge = Edge(name, "sanitizer", pattern, confers=frozenset(confers),
                note=note, expect_live=expect_live)
    _RUNTIME_EDGES.append(edge)
    return edge


def register_edge(edge: Edge) -> Edge:
    """Register a full edge (source/sink/entry included) — the general
    form of :func:`register_verifier_edge` for new seams and decisions."""
    _RUNTIME_EDGES.append(edge)
    return edge


# Modules that register runtime edges at import time.  The analysis runs
# in whatever process invoked it (CLI, bench preflight, tests) — without
# loading these, a fast-path module's registered edges would be invisible
# to any scan that didn't happen to import the module first, and its
# sanctioned sanitizer would read as an unregistered check (a false
# finding) or, worse, its expect_live pin would silently not apply.
_EDGE_PROVIDERS = ("mochi_tpu.storage.paged", "mochi_tpu.server.replica")
_providers_loaded = False


def _load_edge_providers() -> None:
    global _providers_loaded
    if _providers_loaded:
        return
    _providers_loaded = True
    import importlib

    for mod in _EDGE_PROVIDERS:
        importlib.import_module(mod)


def registered_edges() -> Tuple[Edge, ...]:
    _load_edge_providers()
    return BUILTIN_EDGES + tuple(_RUNTIME_EDGES)


def _edges_by_kind() -> Dict[str, List[Edge]]:
    out: Dict[str, List[Edge]] = {"source": [], "sanitizer": [], "sink": [],
                                  "entry": []}
    for e in registered_edges():
        out.setdefault(e.kind, []).append(e)
    return out


# Calls whose result carries no taint even from tainted arguments: sizes,
# type predicates, identifiers.  Admission control legitimately sheds on
# len(batch) BEFORE auth — a size is not payload content.
_CLEAN_CALLS = {
    "len", "bool", "int", "float", "str", "repr", "hash", "id", "ord",
    "isinstance", "issubclass", "hasattr", "callable", "type", "range",
    "time.time", "time.monotonic", "time.perf_counter", "new_msg_id",
}

# Mutating container methods: taint flows from argument into the receiver.
_MUTATORS = {"append", "add", "extend", "insert", "update", "setdefault",
             "appendleft", "push"}

# --------------------------------------------------------------- extraction
#
# The per-file phase lowers every function to a picklable, registry-
# independent event IR (so analysis/core.py can cache it per file by mtime):
#
#   fn record: {module, path, cls, name, qual, params, line, events,
#               local_funcs}
#   event:  ("assign", targets, expr) | ("expr", expr) | ("ret", expr)
#   target: ("n", name) | ("store", attr_name, line, col, snippet)
#   expr:   ("name", id) | ("many", (expr, ...))
#         | ("call", dotted, (arg_exprs...), base_name, line, col, snippet)
#
# Control flow is linearized (if/else, loops, try arms concatenated in
# source order) — the same dominance-by-text-order approximation the
# await-races pass uses, which matches how verification code is actually
# written (check first, decide after).


def _module_name(path: str) -> str:
    p = path[:-3] if path.endswith(".py") else path
    return p.replace("/", ".")


class _ExprLower:
    def __init__(self, imports: Dict[str, str], src_lines: Sequence[str]):
        self.imports = imports
        self.src_lines = src_lines

    def snippet(self, node: ast.AST) -> str:
        line = getattr(node, "lineno", 0)
        if 1 <= line <= len(self.src_lines):
            return self.src_lines[line - 1].strip()
        return ""

    def lower(self, node: Optional[ast.AST]):
        if node is None or isinstance(node, (ast.Constant, ast.Ellipsis)):
            return None
        if isinstance(node, ast.Name):
            return ("name", node.id)
        if isinstance(node, ast.Await):
            return self.lower(node.value)
        if isinstance(node, ast.Call):
            dotted = resolve_call(node.func, self.imports)
            base = None
            recv = None
            if isinstance(node.func, ast.Attribute):
                # a method call reads its receiver: `results.items()` /
                # `rec.decode()` carry the receiver's taint into the result
                recv = self.lower(node.func.value)
                if isinstance(node.func.value, ast.Name):
                    base = node.func.value.id
            args = []
            for a in node.args:
                args.append(self.lower(a.value if isinstance(a, ast.Starred)
                                       else a))
            for kw in node.keywords:
                args.append(self.lower(kw.value))
            return (
                "call", dotted, tuple(args), base,
                node.lineno, node.col_offset, self.snippet(node), recv,
            )
        if isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
            # derivation is root-coarse: attribute/subscript access keeps
            # the base's roots (plus any index expression's)
            parts = [self.lower(node.value)]
            if isinstance(node, ast.Subscript):
                parts.append(self.lower(node.slice))
            return self._many(parts)
        if isinstance(node, ast.Lambda):
            # evaluate the body at the definition site: `run_in_executor(
            # None, lambda: list(iter_log(...)))` returns the body's value,
            # and a deferred wire read is still a wire read.  The lambda's
            # own params stay unbound (they carry the *caller's* data).
            return self.lower(node.body)
        # generic: union over child expressions (tuples, dicts, binops,
        # comparisons, comprehensions, f-strings, conditionals, ...)
        parts = [self.lower(c) for c in ast.iter_child_nodes(node)
                 if isinstance(c, ast.expr)]
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            parts = [self.lower(node.elt) if hasattr(node, "elt") else None]
            if isinstance(node, ast.DictComp):
                parts = [self.lower(node.key), self.lower(node.value)]
            for gen in node.generators:
                parts.append(self.lower(gen.iter))
                for cond in gen.ifs:
                    parts.append(self.lower(cond))
        return self._many(parts)

    @staticmethod
    def _many(parts):
        flat = tuple(p for p in parts if p is not None)
        if not flat:
            return None
        if len(flat) == 1:
            return flat[0]
        return ("many", flat)

    def lower_target(self, node: ast.AST):
        """Assignment targets: plain names bind roots; attribute /
        subscript stores surface as ("store", attr) events the attr-store
        sinks match on."""
        out: List = []
        if isinstance(node, ast.Name):
            out.append(("n", node.id))
        elif isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                out.extend(self.lower_target(elt))
        elif isinstance(node, ast.Starred):
            out.extend(self.lower_target(node.value))
        elif isinstance(node, ast.Attribute):
            out.append(("store", node.attr, node.lineno, node.col_offset,
                        self.snippet(node)))
        elif isinstance(node, ast.Subscript):
            if isinstance(node.value, ast.Attribute):
                out.append(("store", node.value.attr, node.lineno,
                            node.col_offset, self.snippet(node)))
            elif isinstance(node.value, ast.Name):
                # d[k] = v on a local: taint flows into the local
                out.append(("n", node.value.id))
        return out


class _FnLower:
    def __init__(self, lower: _ExprLower):
        self.lower = lower
        self.events: List = []
        self.nested: List = []  # defs buried in loops/with/try arms

    def stmt(self, node: ast.stmt) -> None:
        lw = self.lower
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.nested.append(node)
            return  # extracted as a separate function by the caller
        if isinstance(node, ast.ClassDef):
            return
        if isinstance(node, ast.Assign):
            targets: List = []
            for t in node.targets:
                targets.extend(lw.lower_target(t))
            self.events.append(("assign", tuple(targets),
                                lw.lower(node.value)))
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = tuple(lw.lower_target(node.target))
            value = lw.lower(node.value) if node.value is not None else None
            if isinstance(node, ast.AugAssign):
                # x += y keeps x's roots and gains y's
                value = lw._many([value, lw.lower(node.target)])
            self.events.append(("assign", targets, value))
        elif isinstance(node, ast.Return):
            self.events.append(("ret", lw.lower(node.value)))
        elif isinstance(node, ast.Expr):
            self.events.append(("expr", lw.lower(node.value)))
        elif isinstance(node, (ast.If, ast.While)):
            self.events.append(("expr", lw.lower(node.test)))
            for child in node.body:
                self.stmt(child)
            for child in node.orelse:
                self.stmt(child)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            self.events.append(
                ("assign", tuple(lw.lower_target(node.target)),
                 lw.lower(node.iter))
            )
            for child in node.body:
                self.stmt(child)
            for child in node.orelse:
                self.stmt(child)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                value = lw.lower(item.context_expr)
                if item.optional_vars is not None:
                    self.events.append(
                        ("assign", tuple(lw.lower_target(item.optional_vars)),
                         value)
                    )
                else:
                    self.events.append(("expr", value))
            for child in node.body:
                self.stmt(child)
        elif isinstance(node, ast.Try):
            for child in node.body:
                self.stmt(child)
            for handler in node.handlers:
                for child in handler.body:
                    self.stmt(child)
            for child in node.orelse:
                self.stmt(child)
            for child in node.finalbody:
                self.stmt(child)
        elif isinstance(node, (ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.events.append(("expr", lw.lower(child)))
        elif isinstance(node, ast.Match):
            self.events.append(("expr", lw.lower(node.subject)))
            for case in node.cases:
                for child in case.body:
                    self.stmt(child)
        # Pass/Import/Global/Nonlocal/Delete/Break/Continue: no dataflow


# Production packages the pass scopes to: the layers where wire bytes meet
# protocol decisions.  testing/ (byzantine harnesses bypass verification BY
# DESIGN), tools/, obs/, utils/, parallel/, native/ and the analysis
# package itself are out of scope.
_SCOPE_PREFIXES = (
    "mochi_tpu/client/", "mochi_tpu/server/", "mochi_tpu/storage/",
    "mochi_tpu/protocol/", "mochi_tpu/net/", "mochi_tpu/crypto/",
    "mochi_tpu/cluster/", "mochi_tpu/verifier/", "mochi_tpu/netsim/",
    "mochi_tpu/admin/",
)

# Full-tree anchor: registry-rot (expect_live) is only judged when the scan
# covered the replica — a single-file or fixture scan proves nothing about
# which edges are live.
_ANCHOR_PATH = "mochi_tpu/server/replica.py"


def in_scope(path: str, scoped: bool = True) -> bool:
    if not scoped:
        return True
    return any(path.startswith(p) or p.rstrip("/") + ".py" == path
               for p in _SCOPE_PREFIXES)


def extract(tree: ast.Module, src: str, path: str, scoped: bool = True):
    """Per-file phase: lower every function to the event IR.  Registry-
    independent and picklable — analysis/core.py caches it per file."""
    if not in_scope(path, scoped):
        return None
    imports = build_import_map(tree)
    src_lines = src.splitlines()
    lower = _ExprLower(imports, src_lines)
    module = _module_name(path)
    functions: List[Dict] = []
    classes: List[Dict] = []

    def visit_fn(node, cls_name: Optional[str], prefix: str) -> None:
        qual = f"{prefix}{node.name}"
        params = [a.arg for a in (
            node.args.posonlyargs + node.args.args + node.args.kwonlyargs
        )]
        fl = _FnLower(lower)
        local_funcs: Dict[str, str] = {}
        for child in node.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_funcs[child.name] = f"{qual}.{child.name}"
                visit_fn(child, cls_name, f"{qual}.")
            else:
                fl.stmt(child)
        for nested in fl.nested:
            local_funcs[nested.name] = f"{qual}.{nested.name}"
            visit_fn(nested, cls_name, f"{qual}.")
        functions.append({
            "module": module, "path": path, "cls": cls_name,
            "name": node.name, "qual": qual, "params": params,
            "line": node.lineno, "events": fl.events,
            "local_funcs": local_funcs,
        })

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            visit_fn(node, None, "")
        elif isinstance(node, ast.ClassDef):
            classes.append({
                "module": module, "name": node.name,
                "bases": [b for b in map(lambda x: resolve_call(x, imports),
                                         node.bases) if b],
            })
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    visit_fn(item, node.name, f"{node.name}.")
    return {"path": path, "module": module, "functions": functions,
            "classes": classes}


# ------------------------------------------------------------------ linking


class _Root:
    """One taint origin flowing through a function body.  ``applied`` is
    the set of verification classes sanctioned edges have conferred on any
    value derived from this root (root-coarse, see module docstring)."""

    __slots__ = ("origin", "applied")

    def __init__(self, origin: Tuple, applied: Optional[Set[str]] = None):
        self.origin = origin  # ("src", edge_name) | ("param", idx)
        self.applied: Set[str] = set(applied or ())


@dataclass
class _Summary:
    # ("param", idx, frozenset) passthroughs and ("taint", frozenset)
    # wire-taint returns, with the classes conferred by return time
    returns: Set[Tuple] = field(default_factory=set)
    # (param_idx, missing_groups, sink_name): param reaches a sink with
    # these CNF groups still unsatisfied inside the callee
    param_sink: Set[Tuple] = field(default_factory=set)
    # param_idx -> classes conferred on that argument by calling this fn
    sanitizes: Dict[int, FrozenSet[str]] = field(default_factory=dict)

    def key(self) -> Tuple:
        return (
            frozenset(self.returns), frozenset(self.param_sink),
            frozenset(self.sanitizes.items()),
        )


class _Linker:
    def __init__(self, facts: Sequence[Dict], scoped: bool = True):
        self.files = [f for f in facts if f]
        self.scoped = scoped
        edges = _edges_by_kind()
        self.sources = {e.pattern: e for e in edges["source"]}
        self.sanitizers = {e.pattern: e for e in edges["sanitizer"]}
        self.entries = {e.pattern: e for e in edges["entry"]}
        self.call_sinks = {e.pattern: e for e in edges["sink"]
                           if e.match == "call"}
        self.store_sinks = {e.pattern: e for e in edges["sink"]
                            if e.match == "attr-store"}
        self.fns: List[Dict] = []
        self.by_qual: Dict[Tuple[str, str], Dict] = {}
        self.methods: Dict[Tuple[str, str, str], Dict] = {}
        self.mod_fns: Dict[Tuple[str, str], Dict] = {}
        for f in self.files:
            for fn in f["functions"]:
                self.fns.append(fn)
                self.by_qual[(fn["module"], fn["qual"])] = fn
                if fn["cls"]:
                    self.methods[(fn["module"], fn["cls"], fn["name"])] = fn
                elif fn["qual"] == fn["name"]:
                    self.mod_fns[(fn["module"], fn["name"])] = fn
        self.summaries: Dict[Tuple[str, str], _Summary] = {
            (fn["module"], fn["qual"]): _Summary() for fn in self.fns
        }
        self.findings: List[Finding] = []
        self.live_edges: Set[str] = set()
        self._report_sites: Set[Tuple] = set()

    # ---------------------------------------------------------- resolution

    def _resolve_summary(self, fn: Dict, dotted: Optional[str]
                         ) -> Optional[Dict]:
        """Best-effort call-graph edge: self-methods (own class), local
        nested functions, module-level functions (same module or imported
        by dotted suffix).  Cross-object method calls resolve through the
        registry instead — that asymmetry is deliberate (a wrong summary
        is worse than no summary)."""
        if not dotted:
            return None
        parts = dotted.split(".")
        if parts[0] in ("self", "cls") and len(parts) == 2 and fn["cls"]:
            hit = self.methods.get((fn["module"], fn["cls"], parts[1]))
            if hit is not None:
                return hit
            # single-definition fallback: unique method name tree-wide
            cands = [f for (m, c, n), f in self.methods.items()
                     if n == parts[1]]
            return cands[0] if len(cands) == 1 else None
        if len(parts) == 1:
            local = fn["local_funcs"].get(parts[0])
            if local:
                return self.by_qual.get((fn["module"], local))
            return self.mod_fns.get((fn["module"], parts[0]))
        # imported module function: match by dotted suffix against known
        # (module, name) pairs
        name = parts[-1]
        want = ".".join(parts[:-1])
        cands = [f for (m, n), f in self.mod_fns.items()
                 if n == name and (m.endswith(want) or want.endswith(m))]
        return cands[0] if len(cands) == 1 else None

    @staticmethod
    def _callable_names(fn: Dict, dotted: str) -> List[str]:
        return [dotted]

    # -------------------------------------------------------- registry hits

    def _match(self, table: Dict[str, Edge], dotted: Optional[str]
               ) -> Optional[Edge]:
        if not dotted:
            return None
        pat = suffix_match(dotted, table.keys())
        return table[pat] if pat else None

    def _fn_matches(self, fn: Dict, table: Dict[str, Edge]
                    ) -> Optional[Edge]:
        qual = f"{fn['cls']}.{fn['name']}" if fn["cls"] else fn["name"]
        pat = suffix_match(qual, table.keys())
        return table[pat] if pat else None

    # --------------------------------------------------------- interpretation

    def _interp(self, fn: Dict, report: bool) -> None:
        key = (fn["module"], fn["qual"])
        summary = _Summary()
        san_edge = self._fn_matches(fn, self.sanitizers)
        if san_edge is not None:
            # Sanctioned verifier edge: its body IS the verification
            # implementation — trusted, not analyzed.  It confers its
            # classes on every argument and returns them verified.
            params = fn["params"]
            for idx, p in enumerate(params):
                if p in ("self", "cls"):
                    continue
                summary.sanitizes[idx] = san_edge.confers
                summary.returns.add(("param", idx, san_edge.confers))
            if self.summaries[key].key() != summary.key():
                self.summaries[key] = summary
                self.changed = True
            return

    # ------------------------------------------------------------------
        env: Dict[str, Set[_Root]] = {}
        param_roots: Dict[int, _Root] = {}
        entry_edge = self._fn_matches(fn, self.entries)
        for idx, p in enumerate(fn["params"]):
            if p in ("self", "cls"):
                continue
            if entry_edge is not None:
                root = _Root(("src", entry_edge.name))
            else:
                root = _Root(("param", idx))
            param_roots[idx] = root
            env[p] = {root}

        def eval_expr(expr) -> Set[_Root]:
            if expr is None:
                return set()
            kind = expr[0]
            if kind == "name":
                return set(env.get(expr[1], ()))
            if kind == "many":
                out: Set[_Root] = set()
                for part in expr[1]:
                    out |= eval_expr(part)
                return out
            if kind == "call":
                return eval_call(expr)
            return set()

        def check_sink(edge: Edge, roots: Set[_Root], line: int, col: int,
                       snippet: str, via: str) -> None:
            if edge.expect_live:
                self.live_edges.add(edge.name)
            if not roots:
                return
            applied: Set[str] = set()
            for r in roots:
                applied |= r.applied
            missing = tuple(g for g in edge.requires if not (g & applied))
            if not missing:
                return
            src_roots = [r for r in roots if r.origin[0] == "src"]
            if src_roots and report:
                site = (fn["path"], line, col, edge.name)
                if site not in self._report_sites:
                    self._report_sites.add(site)
                    origin = sorted({r.origin[1] for r in src_roots})
                    need = " AND ".join(
                        "|".join(sorted(g)) for g in missing
                    )
                    self.findings.append(Finding(
                        RULE, fn["path"], line, col,
                        f"wire-tainted value (from {', '.join(origin)}) "
                        f"reaches protocol decision '{edge.name}'{via} "
                        f"without a sanctioned verifier edge conferring "
                        f"[{need}] — route it through the registry's "
                        "sanitizers (docs/ANALYSIS.md §wire-taint) or "
                        "register the fast path's new verifier edge",
                        snippet=snippet, severity="high",
                    ))
            for r in roots:
                if r.origin[0] == "param":
                    still = tuple(g for g in edge.requires
                                  if not (g & (applied | r.applied)))
                    if still:
                        summary.param_sink.add(
                            (r.origin[1], still, edge.name)
                        )

        def eval_call(expr) -> Set[_Root]:
            _, dotted, args, base, line, col, snippet, recv = expr
            arg_roots = [eval_expr(a) for a in args]
            recv_roots = eval_expr(recv)
            all_roots: Set[_Root] = set(recv_roots)
            for rs in arg_roots:
                all_roots |= rs
            # 1. sanctioned sanitizer: confer classes on every arg root
            san = self._match(self.sanitizers, dotted)
            if san is not None:
                if san.expect_live:
                    self.live_edges.add(san.name)
                for r in all_roots:
                    r.applied |= san.confers
                return set(all_roots)
            # 2. source: fresh taint
            src = self._match(self.sources, dotted)
            if src is not None:
                if src.expect_live:
                    self.live_edges.add(src.name)
                return {_Root(("src", src.name))}
            # 3. sink
            sink = self._match(self.call_sinks, dotted)
            if sink is not None:
                check_sink(sink, all_roots, line, col, snippet, "")
                return set(all_roots)
            # 4. summarized callee
            callee = self._resolve_summary(fn, dotted)
            if callee is not None:
                ckey = (callee["module"], callee["qual"])
                csum = self.summaries.get(ckey)
                if csum is not None:
                    # parameter offset: method calls via self skip "self"
                    for pidx, classes in csum.sanitizes.items():
                        rs = self._args_for(callee, pidx, arg_roots)
                        for r in rs:
                            r.applied |= classes
                    for pidx, missing, sink_name in csum.param_sink:
                        rs = self._args_for(callee, pidx, arg_roots)
                        if rs:
                            pseudo = Edge(sink_name, "sink", sink_name,
                                          requires=missing)
                            check_sink(
                                pseudo, rs, line, col, snippet,
                                f" via {callee['qual']}()",
                            )
                    out: Set[_Root] = set()
                    for ret in csum.returns:
                        if ret[0] == "param":
                            rs = self._args_for(callee, ret[1], arg_roots)
                            for r in rs:
                                r.applied |= ret[2]
                            out |= rs
                        else:
                            out.add(_Root(("src", f"{callee['qual']}()"),
                                          ret[1]))
                    return out
            # 5. clean builtins
            if dotted in _CLEAN_CALLS or (
                dotted and suffix_match(dotted, _CLEAN_CALLS)
            ):
                return set()
            # 6. mutating container method: taint the receiver
            if base is not None and dotted and "." in dotted:
                method = dotted.rsplit(".", 1)[1]
                if method in _MUTATORS and all_roots:
                    env.setdefault(base, set()).update(all_roots)
            # unknown call: taint passes through
            return set(all_roots)

        for event in fn["events"]:
            kind = event[0]
            if kind == "expr":
                eval_expr(event[1])
            elif kind == "assign":
                targets, value = event[1], event[2]
                roots = eval_expr(value)
                for t in targets:
                    if t[0] == "n":
                        env[t[1]] = set(roots)
                    else:  # ("store", attr, line, col, snippet)
                        edge = self.store_sinks.get(
                            suffix_match(t[1], self.store_sinks.keys()) or ""
                        )
                        if edge is not None:
                            check_sink(edge, roots, t[2], t[3], t[4], "")
            elif kind == "ret":
                for r in eval_expr(event[1]):
                    if r.origin[0] == "param":
                        summary.returns.add(
                            ("param", r.origin[1], frozenset(r.applied))
                        )
                    else:
                        summary.returns.add(
                            ("taint", frozenset(r.applied))
                        )
        # classes conferred on parameters by this function's body
        if entry_edge is None:
            for idx, root in param_roots.items():
                if root.applied:
                    summary.sanitizes[idx] = frozenset(root.applied)
        if self.summaries[key].key() != summary.key():
            self.summaries[key] = summary
            self.changed = True

    @staticmethod
    def _args_for(callee: Dict, pidx: int, arg_roots: List[Set[_Root]]
                  ) -> Set[_Root]:
        """Map a callee parameter index onto the call's argument roots.
        Self/cls offset is handled by comparing positions past the
        receiver; keyword reordering degrades to the positional guess
        (root-coarse unions make this safe: worst case a class lands on a
        sibling argument's root of the same call)."""
        params = callee["params"]
        skip = 1 if params and params[0] in ("self", "cls") else 0
        pos = pidx - skip
        if 0 <= pos < len(arg_roots):
            return set(arg_roots[pos])
        out: Set[_Root] = set()
        for rs in arg_roots:
            out |= rs
        return out

    # -------------------------------------------------------------- driver

    def run(self) -> List[Finding]:
        self.changed = True
        rounds = 0
        order = sorted(self.fns, key=lambda f: (f["path"], f["line"]))
        while self.changed and rounds < 8:
            self.changed = False
            rounds += 1
            for fn in order:
                self._interp(fn, report=False)
        self._report_sites.clear()
        self.live_edges.clear()
        for fn in order:
            self._interp(fn, report=True)
        scanned = {f["path"] for f in self.files}
        if _ANCHOR_PATH in scanned:
            for e in registered_edges():
                if e.expect_live and e.name not in self.live_edges:
                    self.findings.append(Finding(
                        RULE, _ANCHOR_PATH, 1, 0,
                        f"registry-rot: sanctioned edge '{e.name}' "
                        f"(pattern '{e.pattern}') matched no call site in "
                        "a full-tree scan — the edge was renamed, removed "
                        "or bypassed without updating the wire-taint "
                        "registry (mochi_tpu/analysis/wire_taint.py)",
                        snippet=f"registry-rot:{e.name}", severity="medium",
                    ))
        return self.findings


def link(facts: Sequence[Optional[Dict]], scoped: bool = True
         ) -> List[Finding]:
    """Whole-tree phase: fixpoint the summaries, then report."""
    real = [f for f in facts if f]
    if not real:
        return []
    return _Linker(real, scoped=scoped).run()


def check(tree: ast.Module, src: str, path: str, scoped: bool = True
          ) -> List[Finding]:
    """Single-file convenience wrapper (fixtures, ad-hoc use).  Full runs
    go through extract()+link() so summaries cross file boundaries —
    analysis/core.py drives that path."""
    facts = extract(tree, src, path, scoped=scoped)
    return link([facts], scoped=scoped)

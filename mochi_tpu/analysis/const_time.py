"""constant-time: timing-oracle patterns on signature/digest/MAC bytes.

Python's ``bytes.__eq__`` short-circuits at the first differing byte, so a
``==`` on authenticator bytes leaks how long a forged prefix matched — the
classic HMAC timing oracle.  Everywhere an attacker-supplied authenticator
meets a locally computed one, the comparison must be
``hmac.compare_digest`` (``crypto/session.py::mac_ok`` is the exemplar).

Two sub-rules:

* **compare** (whole tree): ``==`` / ``!=`` where an operand's terminal
  identifier names an authenticator (``signature``/``sig``/``mac``/
  ``digest``/``hmac`` or ``*_signature``/``*_sig``/``*_mac``/``*_digest``).
  ALL-CAPS operands are exempt — ``FailType.BAD_SIGNATURE`` is an enum
  constant, not bytes — as are comparisons against ``None`` (identity
  checks are spelled ``is`` anyway, and ``== None`` has no byte content to
  leak).

* **secret-branch** (``crypto/`` files only): a ``return`` inside an ``if``
  whose condition reads a secret-named parameter (``private*``/``secret*``/
  ``*_seed``).  Early exit keyed on secret material is a timing channel in
  the primitive itself; the JAX data plane is branchless by construction
  (``crypto/field.py`` module docstring), and the host fallback must at
  least not *branch* on key bytes even where big-int timing is
  unavoidable.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from .core import Finding, dotted_name, snippet_at

RULE = "constant-time"

_AUTH_NAME = re.compile(
    r"(?:^|_)(signature|sig|mac|digest|hmac)$"
)
_SECRET_PARAM = re.compile(r"(?:^(?:private|secret)|(?:^|_)seed$)")


def _terminal_identifier(node: ast.AST) -> Optional[str]:
    dn = dotted_name(node)
    if dn is None:
        return None
    parts = dn.split(".")
    # Enum/constant access: any ALL-CAPS segment marks the chain constant.
    if any(p.isupper() and len(p) > 1 for p in parts):
        return None
    return parts[-1]


def _is_auth_operand(node: ast.AST) -> bool:
    ident = _terminal_identifier(node)
    return bool(ident) and bool(_AUTH_NAME.search(ident))


def _compare_findings(tree: ast.Module, src_lines, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        if not any(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
            continue
        operands = [node.left, *node.comparators]
        if any(
            isinstance(o, ast.Constant) and o.value is None for o in operands
        ):
            continue
        if any(_is_auth_operand(o) for o in operands):
            findings.append(
                Finding(
                    RULE, path, node.lineno, node.col_offset,
                    "variable-time `==` on authenticator bytes; use "
                    "hmac.compare_digest",
                    snippet_at(src_lines, node.lineno),
                )
            )
    return findings


class _SecretBranchVisitor(ast.NodeVisitor):
    def __init__(self, secrets, src_lines, path):
        self.secrets = secrets
        self.src_lines = src_lines
        self.path = path
        self.findings: List[Finding] = []

    def visit_FunctionDef(self, node):  # nested defs analyzed separately
        pass

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_If(self, node: ast.If) -> None:
        touches_secret = any(
            isinstance(n, ast.Name) and n.id in self.secrets
            for n in ast.walk(node.test)
        )
        if touches_secret:
            for stmt in ast.walk(node):
                if isinstance(stmt, ast.Return):
                    self.findings.append(
                        Finding(
                            RULE, self.path, node.lineno, node.col_offset,
                            "secret-dependent early return; restructure to "
                            "branch-free (select/arith) form",
                            snippet_at(self.src_lines, node.lineno),
                        )
                    )
                    break
        self.generic_visit(node)


def _secret_branch_findings(tree: ast.Module, src_lines, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        args = node.args
        secrets = {
            a.arg
            for a in args.posonlyargs + args.args + args.kwonlyargs
            if _SECRET_PARAM.search(a.arg)
        }
        if not secrets:
            continue
        visitor = _SecretBranchVisitor(secrets, src_lines, path)
        for stmt in node.body:
            visitor.visit(stmt)
        findings.extend(visitor.findings)
    return findings


def check(tree: ast.Module, src: str, path: str, scoped: bool = True) -> List[Finding]:
    src_lines = src.splitlines()
    findings = _compare_findings(tree, src_lines, path)
    if not scoped or "crypto" in path.split("/"):
        findings.extend(_secret_branch_findings(tree, src_lines, path))
    return findings

"""async-blocking: blocking calls inside ``async def`` bodies.

The whole data plane multiplexes on one event loop per process (replica
dispatch, transport framing, the verify batcher).  A single synchronous
stall — ``time.sleep``, unbuffered file IO, a subprocess, a host Ed25519
operation outside the metered fast paths — freezes every connection on that
loop and blows the 1-RT read / 2-RT write latency budget (BASELINE.json:
"<5% replica CPU in crypto" assumes crypto never *blocks* the loop, only
spends cycles on it).

Detection is lexical and conservative: a ``Call`` node whose resolved
dotted target is on the deny list, appearing inside an ``async def`` body —
not inside a nested synchronous ``def`` or ``lambda`` (those are routinely
shipped to ``run_in_executor``, which is exactly the sanctioned escape
hatch and takes *callables*, never call results).
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, build_import_map, resolve_call, snippet_at, suffix_match

RULE = "async-blocking"

# Resolved-by-suffix call targets that synchronously block (or monopolize)
# the event loop.  ``time.sleep`` is the classic; the IO entries cover the
# patterns a KV store grows (snapshots, config files, ad-hoc probes); the
# crypto entries are the host Ed25519 primitives — module-level sign/verify
# in a coroutine means an unmetered OpenSSL (or worse, pure-Python fallback)
# operation on the loop.
BLOCKING_CALLS = (
    "time.sleep",
    "os.system",
    "os.popen",
    "os.wait",
    "os.waitpid",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen.wait",
    "socket.create_connection",
    "socket.getaddrinfo",
    "urllib.request.urlopen",
    "requests.get",
    "requests.post",
    "requests.put",
    "requests.request",
    "shutil.copyfile",
    "shutil.copytree",
    "shutil.rmtree",
)

CRYPTO_CALLS = (
    "crypto.keys.sign",
    "crypto.keys.verify",
    "keys.sign",
    "keys.verify",
)

# Bare-name builtins that block.  ``open`` alone: ``os.open`` is resolved as
# a dotted call and (being a raw fd syscall) is left to the IO entries above.
BLOCKING_BUILTINS = ("open",)


class _AsyncBodyVisitor(ast.NodeVisitor):
    def __init__(self, imports, src_lines, path):
        self.imports = imports
        self.src_lines = src_lines
        self.path = path
        self.findings: List[Finding] = []
        self._async_depth = 0

    # -- scope management: only direct async bodies count ------------------
    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._async_depth += 1
        for child in node.body:
            self.visit(child)
        self._async_depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # A nested sync def inside a coroutine is (by idiom) executor fodder
        # or a callback — its body runs off-loop or is someone else's problem.
        saved = self._async_depth
        self._async_depth = 0
        for child in node.body:
            self.visit(child)
        self._async_depth = saved

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved = self._async_depth
        self._async_depth = 0
        self.visit(node.body)
        self._async_depth = saved

    def visit_Call(self, node: ast.Call) -> None:
        if self._async_depth > 0:
            qualified = resolve_call(node.func, self.imports)
            hit = None
            kind = None
            if qualified is not None:
                if suffix_match(qualified, BLOCKING_CALLS):
                    hit, kind = qualified, "blocking call"
                elif suffix_match(qualified, CRYPTO_CALLS):
                    hit, kind = qualified, "host crypto call"
            if (
                hit is None
                and isinstance(node.func, ast.Name)
                and node.func.id in BLOCKING_BUILTINS
                and node.func.id not in self.imports
            ):
                hit, kind = node.func.id, "blocking builtin"
            if hit is not None:
                self.findings.append(
                    Finding(
                        RULE,
                        self.path,
                        node.lineno,
                        node.col_offset,
                        f"{kind} `{hit}` inside `async def` body blocks the "
                        "event loop; use the async equivalent or "
                        "run_in_executor",
                        snippet_at(self.src_lines, node.lineno),
                    )
                )
        self.generic_visit(node)


def check(tree: ast.Module, src: str, path: str, scoped: bool = True) -> List[Finding]:
    del scoped  # a blocked event loop is a defect anywhere in the tree
    visitor = _AsyncBodyVisitor(build_import_map(tree), src.splitlines(), path)
    visitor.visit(tree)
    return visitor.findings

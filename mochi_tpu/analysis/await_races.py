"""await-races: stale shared state across ``await`` points (async TOCTOU).

The whole data plane is single-threaded-by-event-loop: the store has no
locks because "the replica's event loop is the lock".  That discipline has
one sharp edge — an ``await`` RELEASES the lock.  Any replica/client state
read before a suspension point and trusted after it may describe a world
that no longer exists: the last three PRs each shipped a hand-found fix of
exactly this class (SessionTable eviction between auth and response-seal,
in-flight msg-id eviction, grant-reclaim racing a slow Write2).  This
checker finds the pattern mechanically, per coroutine, with a linear
await-segment dataflow: every statement gets the index of awaits crossed
before it, and facts that pair across different segments are findings.

Four sub-rules, tiered by how directly each one corrupts protocol state:

* **check-then-act** (severity ``high``) — an ``if``/``while`` guard reads
  a ``self.``-rooted table (truthiness, ``in``, ``.get``, ``len``) and a
  MUTATION of the same table (``[k] = ``, ``del``, ``.pop/.add/.remove/
  .update/...``) executes in a LATER await segment with no re-read of that
  table after the last await before the act.  The guard's verdict is stale
  by the time the act runs — the SessionTable-eviction bug shape.  A
  re-validating read in the act's own segment clears the finding (the
  double-checked idiom), as does an enclosing ``with``/``async with``
  whose context expression names a lock.

* **stale-read** (``medium``) — a local bound from an ELEMENT read of
  shared state (``self.X[k]``, ``self.X.get(k)``, ``.items/.keys/
  .values/.copy``, including inside comprehensions) and first USED in a
  later await segment without rebinding.  The value may be an evicted
  session key, a reclaimed grant, a superseded config row.

* **shared-iter** (``medium``) — ``for``/``async for`` directly over a
  ``self.``-rooted container (not a ``list(...)``/``sorted(...)``/
  ``tuple(...)``/``set(...)``/``dict(...)`` snapshot) whose body awaits:
  any concurrent task that mutates the container mid-iteration raises
  ``RuntimeError: dictionary changed size during iteration`` — at runtime,
  under load, in whichever task loses the race.

* **tally-authority** (``high``) — a ``QuorumTally``/``GrantAssembler``
  liveness tracker's STATE (``.chosen``, ``.satisfied``, anything but
  ``.add``) consumed after an await.  The trackers exist only to decide
  when to stop WAITING (client/txn.py); consuming their verdict as the
  commit-path truth skips the authoritative re-tally that makes
  early-quorum safe ("quorum-tally results consumed without the
  authoritative re-check").

Scope: ``async def`` bodies anywhere in the tree (the bug class is not
path-local).  Shared state means attribute chains rooted at ``self`` —
module globals and closure cells are rarer here and excluded to keep the
signal clean.  Branches of ``if``/``try`` are threaded sequentially
(conservative: an await in either arm splits segments for what follows);
loop bodies containing an await are walked twice so a read late in the
body pairs with a use early in the next iteration.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, snippet_at

RULE = "await-races"

# element-read methods: the call reads a value OUT of the container
_READ_METHODS = {"get", "items", "keys", "values", "copy"}
# mutating-call methods on a shared container
_MUTATE_METHODS = {
    "pop", "popitem", "clear", "update", "setdefault", "add", "remove",
    "discard", "append", "extend", "insert",
}
# wrapping any of these around the iterable snapshots it — iteration is safe
_SNAPSHOT_CALLS = {"list", "tuple", "sorted", "set", "dict", "frozenset", "enumerate"}
# liveness trackers whose post-await state must never be authoritative
_TRACKER_TYPES = {"QuorumTally", "GrantAssembler"}


_LOCK_WORDS = {"lock", "locks", "mutex", "semaphore"}
_WORD_RE = re.compile(r"[A-Za-z][a-z0-9]*")


def _names_a_lock(expr: ast.AST) -> bool:
    """True when a ``with`` context expression names a lock: some
    IDENTIFIER in it (variable, attribute, called type) contains the word
    lock/mutex/semaphore under snake_case/CamelCase splitting.  Word-level,
    not substring — ``self.clock()`` and ``self.blocking_io()`` must NOT
    silence the checker (the substring "lock" inside an unrelated word
    would disable the high-severity check-then-act rule for the block)."""
    for node in ast.walk(expr):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and not _LOCK_WORDS.isdisjoint(
            w.lower() for w in _WORD_RE.findall(name)
        ):
            return True
    return False


def _self_chain(node: ast.AST) -> Optional[str]:
    """``self.a.b`` -> "self.a.b" for Attribute chains rooted at ``self``
    (Load context only matters to callers)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and parts:
        return ".".join(["self"] + list(reversed(parts)))
    return None


def _shared_reads(expr: ast.AST) -> Set[str]:
    """Every shared table a guard expression consults: bare truthiness
    (``self.X``), membership (``k in self.X``), ``self.X.get(...)``,
    ``len(self.X)``/``bool(self.X)``, ``self.X[k]``."""
    out: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute):
            chain = _self_chain(node)
            if chain is not None:
                out.add(chain)
        elif isinstance(node, ast.Name) and node.id == "self":
            continue
    return out


def _store_names(target: ast.AST) -> Set[str]:
    """Every plain Name a binding target (re)binds — Store-context only, so
    ``self.table[k] = v`` does not claim to rebind ``k``.  Tuple/list
    unpacks, starred elements, and for/with targets all hand the name a
    FRESH value, so any stale-read tracking on it must clear."""
    out: Set[str] = set()
    for node in ast.walk(target):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            out.add(node.id)
    return out


def _pattern_names(pattern: ast.AST) -> Set[str]:
    """Names a match-case pattern captures (``MatchAs``/``MatchStar`` binds,
    ``MatchMapping`` rest) — fresh bindings, same invalidation as
    assignment."""
    out: Set[str] = set()
    for node in ast.walk(pattern):
        if isinstance(node, (ast.MatchAs, ast.MatchStar)) and node.name:
            out.add(node.name)
        elif isinstance(node, ast.MatchMapping) and node.rest:
            out.add(node.rest)
    return out


def _element_read_keys(expr: ast.AST) -> Set[str]:
    """Shared chains the expression reads an ELEMENT (or live view) of —
    the narrower read set rule (a) tracks into locals.  Slice subscripts
    (``self.client_id[:8]``) are excluded: slicing COPIES, and in this tree
    it is how immutable ids/byte strings get abbreviated, not how table
    entries get read."""
    out: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Subscript):
            if isinstance(node.slice, ast.Slice):
                continue
            chain = _self_chain(node.value)
            if chain is not None:
                out.add(chain)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _READ_METHODS:
                chain = _self_chain(node.func.value)
                if chain is not None:
                    out.add(chain)
    return out


def _tracked_read_keys(value: ast.AST) -> Set[str]:
    """Shared chains a LOCAL BINDING inherits staleness from.

    Narrower than :func:`_element_read_keys` at the top level on purpose:
    only value shapes that *are* a read out of shared state taint the local
    — a direct element read (``self.X[k]``), a read-method call
    (``self.X.get(k)``), a comprehension whose body or iterable reads
    shared state, or a conditional/boolean combination of those.  An
    arbitrary call that merely TAKES an element read as an argument
    (``self._new_replica(self.config.servers[k].host)``) constructs a new
    value; flagging it taught the first dry run that constructor calls
    drown the signal."""
    if isinstance(value, ast.Subscript):
        if isinstance(value.slice, ast.Slice):
            return set()
        chain = _self_chain(value.value)
        return {chain} if chain is not None else set()
    if isinstance(value, ast.Call) and isinstance(value.func, ast.Attribute):
        if value.func.attr in _READ_METHODS:
            chain = _self_chain(value.func.value)
            return {chain} if chain is not None else set()
        return set()
    if isinstance(value, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
        return _element_read_keys(value)
    if isinstance(value, ast.BoolOp):
        out: Set[str] = set()
        for sub in value.values:
            out |= _tracked_read_keys(sub)
        return out
    if isinstance(value, ast.IfExp):
        return _tracked_read_keys(value.body) | _tracked_read_keys(value.orelse)
    return set()


def _contains_await(node: ast.AST) -> bool:
    """Await anywhere under ``node``, not counting nested function bodies
    (those suspend their own schedule, not this one)."""
    stack: List[ast.AST] = [node]
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            return True
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)
    return False


@dataclass
class _Guard:
    key: str          # shared chain the guard read
    seg: int          # await segment of the read
    line: int


@dataclass
class _LocalRead:
    keys: Set[str]
    seg: int
    line: int
    reported: bool = False


@dataclass
class _FnState:
    seg: int = 0                      # awaits crossed so far
    guards: List[_Guard] = field(default_factory=list)
    locals_: Dict[str, _LocalRead] = field(default_factory=dict)
    # shared chain -> latest segment it was (re-)read in
    last_read_seg: Dict[str, int] = field(default_factory=dict)
    # local name -> (tracker type, creation segment)
    trackers: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    lock_depth: int = 0               # inside `with <...lock...>:`


class _CoroutineChecker:
    def __init__(self, src_lines, path: str):
        self.src_lines = src_lines
        self.path = path
        self.findings: List[Finding] = []
        self._seen_sites: Set[Tuple[str, int, int]] = set()

    # ------------------------------------------------------------- reporting

    def _report(self, kind: str, severity: str, line: int, col: int, msg: str) -> None:
        site = (kind, line, col)
        if site in self._seen_sites:
            return  # loop second pass re-visits the same nodes
        self._seen_sites.add(site)
        self.findings.append(
            Finding(
                RULE, self.path, line, col, f"[{kind}] {msg}",
                snippet_at(self.src_lines, line), severity=severity,
            )
        )

    # ------------------------------------------------------------ fn driver

    def check_function(self, fn: ast.AsyncFunctionDef) -> None:
        st = _FnState()
        self._walk_body(fn.body, st)

    def _walk_body(self, body, st: _FnState) -> None:
        for stmt in body:
            self._walk_stmt(stmt, st)

    # ----------------------------------------------------------- statements

    def _walk_stmt(self, stmt: ast.stmt, st: _FnState) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs are their own schedules
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._handle_assign(stmt, st)
            return
        if isinstance(stmt, ast.Expr):
            self._scan_expr(stmt.value, st)
            return
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            self._scan_expr(stmt.value, st)
            return
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Subscript):
                    chain = _self_chain(tgt.value)
                    if chain is not None:
                        self._mutation(chain, stmt.lineno, stmt.col_offset, st)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            keys = _shared_reads(stmt.test)
            for k in keys:
                st.last_read_seg[k] = st.seg
                st.guards.append(_Guard(k, st.seg, stmt.lineno))
            self._scan_expr(stmt.test, st)
            repeat = isinstance(stmt, ast.While) and _contains_await(stmt)
            self._walk_body(stmt.body, st)
            self._walk_body(stmt.orelse, st)
            if repeat:
                # second pass: late-body facts pair with early-body uses
                for k in keys:
                    st.last_read_seg[k] = st.seg
                    st.guards.append(_Guard(k, st.seg, stmt.lineno))
                self._walk_body(stmt.body, st)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._handle_for(stmt, st)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            locked = any(
                _names_a_lock(item.context_expr) for item in stmt.items
            )
            for item in stmt.items:
                self._scan_expr(item.context_expr, st)
                if item.optional_vars is not None:
                    for name in _store_names(item.optional_vars):
                        st.locals_.pop(name, None)
                        st.trackers.pop(name, None)
            if isinstance(stmt, ast.AsyncWith):
                st.seg += 1  # __aenter__ suspends
            if locked:
                st.lock_depth += 1
            self._walk_body(stmt.body, st)
            if locked:
                st.lock_depth -= 1
            if isinstance(stmt, ast.AsyncWith):
                st.seg += 1  # __aexit__ suspends
            return
        if isinstance(stmt, ast.Try):
            self._walk_body(stmt.body, st)
            for handler in stmt.handlers:
                self._walk_body(handler.body, st)
            self._walk_body(stmt.orelse, st)
            self._walk_body(stmt.finalbody, st)
            return
        if isinstance(stmt, (ast.Raise, ast.Assert)):
            for sub in ast.iter_child_nodes(stmt):
                self._scan_expr(sub, st)
            return
        if isinstance(stmt, ast.Match):
            # the subject and case guards are guard-shaped reads, and case
            # bodies are real statement suites — the expression-only
            # fallback below would leave every sub-rule blind inside them
            for k in _shared_reads(stmt.subject):
                st.last_read_seg[k] = st.seg
                st.guards.append(_Guard(k, st.seg, stmt.lineno))
            self._scan_expr(stmt.subject, st)
            for case in stmt.cases:
                for name in _pattern_names(case.pattern):
                    st.locals_.pop(name, None)
                    st.trackers.pop(name, None)
                if case.guard is not None:
                    for k in _shared_reads(case.guard):
                        st.last_read_seg[k] = st.seg
                        st.guards.append(_Guard(k, st.seg, case.guard.lineno))
                    self._scan_expr(case.guard, st)
                self._walk_body(case.body, st)
            return
        # fallback: scan any embedded expressions generically
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, ast.expr):
                self._scan_expr(sub, st)

    def _handle_for(self, stmt, st: _FnState) -> None:
        # rule (c): iterating a live shared container with awaits inside
        is_async = isinstance(stmt, ast.AsyncFor)
        it = stmt.iter
        chain = _self_chain(it)
        if chain is None and isinstance(it, ast.Call):
            func = it.func
            # `.copy()` is the other standard snapshot idiom (a NEW dict/
            # set/list): iterating it cannot raise changed-size — only the
            # genuinely-live view methods (items/keys/values/get) count
            if isinstance(func, ast.Attribute) and func.attr in (
                _READ_METHODS - {"copy"}
            ):
                chain = _self_chain(func.value)
            elif isinstance(func, ast.Name) and func.id in _SNAPSHOT_CALLS:
                chain = None  # snapshot: safe
        body_awaits = is_async or any(_contains_await(s) for s in stmt.body)
        if chain is not None and body_awaits:
            self._report(
                "shared-iter", "medium", stmt.lineno, stmt.col_offset,
                f"iterating live shared container `{chain}` across an await: "
                "a concurrent task mutating it mid-suspension raises "
                "`RuntimeError: changed size during iteration` — iterate a "
                "`list(...)` snapshot",
            )
        self._scan_expr(it, st)
        # the loop target is a fresh binding each iteration
        for name in _store_names(stmt.target):
            st.locals_.pop(name, None)
            st.trackers.pop(name, None)
        if is_async:
            st.seg += 1
        repeat = body_awaits
        self._walk_body(stmt.body, st)
        self._walk_body(stmt.orelse, st)
        if repeat:
            self._walk_body(stmt.body, st)

    def _handle_assign(self, stmt, st: _FnState) -> None:
        value = getattr(stmt, "value", None)
        if value is None:
            return
        if isinstance(stmt, ast.AugAssign) and isinstance(stmt.target, ast.Name):
            # `n += ...` LOADS n before evaluating the RHS — that read is
            # subject to rule (a) like any other post-await use
            self._scan_ordered(
                ast.copy_location(
                    ast.Name(id=stmt.target.id, ctx=ast.Load()), stmt.target
                ),
                st,
            )
        self._scan_expr(value, st)
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        )
        # mutations through subscript/attr stores on shared chains
        for tgt in targets:
            if isinstance(tgt, ast.Subscript):
                chain = _self_chain(tgt.value)
                if chain is not None:
                    self._mutation(chain, stmt.lineno, stmt.col_offset, st)
            elif isinstance(tgt, ast.Tuple):
                for elt in tgt.elts:
                    if isinstance(elt, ast.Subscript):
                        chain = _self_chain(elt.value)
                        if chain is not None:
                            self._mutation(chain, stmt.lineno, stmt.col_offset, st)
        if isinstance(stmt, ast.AugAssign) and isinstance(stmt.target, ast.Attribute):
            chain = _self_chain(stmt.target)
            if chain is not None:
                self._mutation(chain, stmt.lineno, stmt.col_offset, st)
        if isinstance(stmt, ast.AugAssign) and isinstance(stmt.target, ast.Name):
            # rebinding ends tracking: the name now holds a derived value
            # (the stale load itself was already judged above)
            st.locals_.pop(stmt.target.id, None)
            st.trackers.pop(stmt.target.id, None)
        # rule (a) tracking: a simple Name bind from an element read starts
        # tracking; EVERY other Name bind (tuple unpack, annotated assign)
        # clears it — the name now holds a fresh value, and a live stale
        # entry would false-positive on its post-await use (which, under
        # the --changed-only PR gate, blocks the PR into a bogus
        # suppression).
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            tgt = stmt.target
        elif (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
        ):
            tgt = stmt.targets[0]
        else:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    for name in _store_names(t):
                        st.locals_.pop(name, None)
                        st.trackers.pop(name, None)
            return
        # rule (d) tracking: liveness trackers by construction
        if isinstance(value, ast.Call):
            fname = value.func
            name = (
                fname.id if isinstance(fname, ast.Name)
                else fname.attr if isinstance(fname, ast.Attribute)
                else None
            )
            if name in _TRACKER_TYPES:
                st.trackers[tgt.id] = (name, st.seg)
                st.locals_.pop(tgt.id, None)
                return
        keys = _tracked_read_keys(value)
        if keys and not _contains_await(value):
            st.locals_[tgt.id] = _LocalRead(keys, st.seg, stmt.lineno)
        else:
            st.locals_.pop(tgt.id, None)
        st.trackers.pop(tgt.id, None)

    # ---------------------------------------------------------- expressions

    def _mutation(self, chain: str, line: int, col: int, st: _FnState) -> None:
        """Rule (b): act on a shared table whose guard is in an older await
        segment with no re-read since the last await."""
        if st.lock_depth > 0:
            return  # double-checked-locking idiom: the lock serializes
        stale_guards = [g for g in st.guards if g.key == chain and g.seg < st.seg]
        if not stale_guards:
            return
        if st.last_read_seg.get(chain) == st.seg:
            return  # re-validated after the last await before the act
        g = stale_guards[-1]
        self._report(
            "check-then-act", "high", line, col,
            f"`{chain}` mutated here but its guard (line {g.line}) ran "
            f"{st.seg - g.seg} await(s) ago: the check's verdict is stale — "
            "re-validate after the await (or restructure so check and act "
            "share one loop turn)",
        )

    def _scan_expr(self, expr: ast.AST, st: _FnState) -> None:
        """Order-aware scan of one expression: visits Awaits (segment
        bumps), local uses (rule a), tracker reads (rule d), mutating calls
        (rule b feed), and re-reads (validation bookkeeping).  DFS in
        source order — for expressions, evaluation order is close enough
        that awaits split what syntactically follows them."""
        self._scan_ordered(expr, st)

    def _scan_ordered(self, node: ast.AST, st: _FnState) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.Await):
            self._scan_ordered(node.value, st)
            st.seg += 1
            return
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute):
                # mutating call on shared chain
                if func.attr in _MUTATE_METHODS:
                    chain = _self_chain(func.value)
                    if chain is not None:
                        # arguments (incl. keywords) evaluate BEFORE the
                        # mutation — an await inside either is a segment
                        # boundary the mutation must be judged after
                        for arg in node.args:
                            self._scan_ordered(arg, st)
                        for kw in node.keywords:
                            self._scan_ordered(kw.value, st)
                        self._mutation(chain, node.lineno, node.col_offset, st)
                        return
                # read-method call on shared chain refreshes validation
                if func.attr in _READ_METHODS:
                    chain = _self_chain(func.value)
                    if chain is not None:
                        st.last_read_seg[chain] = st.seg
                # rule (d): tracker state read (non-.add attribute access)
            self._scan_ordered(func, st)
            for arg in node.args:
                self._scan_ordered(arg, st)
            for kw in node.keywords:
                self._scan_ordered(kw.value, st)
            return
        if isinstance(node, ast.Attribute):
            chain = _self_chain(node)
            if chain is not None:
                st.last_read_seg[chain] = st.seg
            if (
                isinstance(node.value, ast.Name)
                and node.value.id in st.trackers
                and node.attr != "add"
            ):
                tname, created = st.trackers[node.value.id]
                if st.seg > created:
                    self._report(
                        "tally-authority", "high", node.lineno, node.col_offset,
                        f"`{node.value.id}.{node.attr}` consumes the {tname} "
                        "liveness tracker's state after an await: the tracker "
                        "only decides when to stop waiting — recompute the "
                        "authoritative quorum over the returned responses "
                        "(client/txn.py contract)",
                    )
            self._scan_ordered(node.value, st)
            return
        if isinstance(node, ast.Subscript):
            chain = _self_chain(node.value)
            if chain is not None:
                st.last_read_seg[chain] = st.seg
            for sub in ast.iter_child_nodes(node):
                self._scan_ordered(sub, st)
            return
        if isinstance(node, ast.Compare):
            # membership test refreshes validation for the container
            for cmp_op, comparator in zip(node.ops, node.comparators):
                if isinstance(cmp_op, (ast.In, ast.NotIn)):
                    chain = _self_chain(comparator)
                    if chain is not None:
                        st.last_read_seg[chain] = st.seg
            for sub in ast.iter_child_nodes(node):
                self._scan_ordered(sub, st)
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            lr = st.locals_.get(node.id)
            if lr is not None and st.seg > lr.seg and not lr.reported:
                lr.reported = True
                self._report(
                    "stale-read", "medium", node.lineno, node.col_offset,
                    f"`{node.id}` was read from shared state "
                    f"({', '.join(sorted(lr.keys))}) at line {lr.line}, "
                    f"{st.seg - lr.seg} await(s) ago: the value may describe "
                    "evicted/reclaimed/superseded state — re-read it after "
                    "the await or justify why staleness is safe",
                )
            return
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, ast.AST):
                self._scan_ordered(sub, st)


def check(tree: ast.Module, src: str, path: str, scoped: bool = True) -> List[Finding]:
    del scoped  # a stale read across an await is a defect anywhere
    src_lines = src.splitlines()
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            checker = _CoroutineChecker(src_lines, path)
            checker.check_function(node)
            findings.extend(checker.findings)
    return findings

"""Framework for the project-native static-analysis pass.

Why a bespoke pass instead of an off-the-shelf linter: the defects that
actually hurt this codebase are *protocol-specific* — a ``time.sleep`` inside
a replica coroutine stalls every connection multiplexed on that event loop
(the 1-RT read / 2-RT write budget is milliseconds); an ``except Exception``
that eats ``asyncio.CancelledError`` turns shutdown into a hang; a Python
``if`` on a traced value silently forces a host sync inside the batched
verifier; a ``==`` on signature bytes is a timing oracle.  Generic linters
know none of this vocabulary.

Architecture: each checker module exposes ``RULE`` (its name) and
``check(tree, src, path, scoped=True) -> list[Finding]``.  This module owns
the shared plumbing: the :class:`Finding` type, suppression comments,
baseline files, file walking, and the runner.

Suppression syntax (see docs/ANALYSIS.md): a finding on line N is suppressed
by a comment on line N or on line N-1 of the form::

    # mochi-lint: disable=<rule>[,<rule>...] -- <one-line justification>

(``all`` disables every rule; the justification after the rule list is
required by review etiquette, not the parser.  Written with a ``<rule>``
placeholder here so this docstring is not itself a live suppression — the
hygiene pass scans raw lines, docstrings included.)

Baseline: a JSON file ``{"fingerprints": [...]}``.  Findings whose
fingerprint appears in the baseline are reported as "baselined" and do not
fail the run — the mechanism that lets the pass land on an imperfect tree
and ratchet forward.  The shipped baseline is empty: every finding on the
current tree is either fixed or carries an explicit suppression.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import pickle
import re
import tempfile
from dataclasses import dataclass, field, replace
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


@dataclass(frozen=True)
class Finding:
    """One checker hit, addressable and stable enough to baseline."""

    rule: str
    path: str  # posix-style, package-anchored (see display_path)
    line: int
    col: int
    message: str
    snippet: str = ""
    # 0-based index among same-(rule, snippet) findings in this file, in
    # line order; assigned by run().  Without it, two textually identical
    # violations in one file would share a fingerprint and one baseline
    # entry would grandfather both — the ratchet could move backwards.
    occurrence: int = 0
    # Severity tier ("error" is the classic single-tier default).  Tiered
    # checkers (await-races) emit high/medium/advice so triage can rank a
    # check-then-act on a bounded table above an iteration hazard; every
    # tier still FAILS the run — tiers order the work, they don't excuse
    # it.  Not part of the fingerprint: re-tiering a rule must not
    # invalidate baselines or suppressions.
    severity: str = "error"

    @property
    def fingerprint(self) -> str:
        """Content-addressed id: survives line-number drift (the baseline
        must not churn when unrelated edits move code), breaks when the
        flagged code itself changes (a moved-AND-edited line is a new
        finding, as it should be)."""
        basis = f"{self.rule}|{self.path}|{self.snippet.strip()}|{self.occurrence}"
        return hashlib.sha256(basis.encode()).hexdigest()[:16]

    def render(self) -> str:
        sev = "" if self.severity == "error" else f"/{self.severity}"
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}{sev}] {self.message}"


# --------------------------------------------------------------- suppressions

# ``#`` for Python, ``//`` / ``/*`` for the native seam (analysis covers
# ``native/*.c`` since the const-time lexer pass landed).
_SUPPRESS_RE = re.compile(
    # rule tokens only (comma-separated); trailing prose is the REQUIRED
    # one-line justification and must not bleed into the rule list
    r"(?:#|//|/\*)\s*mochi-lint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
)


def suppressions_by_line(src: str) -> Dict[int, Set[str]]:
    """Map 1-based line number -> set of rule names disabled there.

    Regex over raw lines rather than the tokenize module: a suppression must
    keep working even in a file the tokenizer rejects (the parse-error path
    still reports, and half-edited files shouldn't crash the linter)."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def is_suppressed(finding: Finding, supp: Dict[int, Set[str]]) -> bool:
    for line in (finding.line, finding.line - 1):
        rules = supp.get(line)
        if rules and ("all" in rules or finding.rule in rules):
            return True
    return False


def suppression_line_for(finding: Finding, supp: Dict[int, Set[str]]) -> Optional[int]:
    """The comment line that suppressed ``finding`` (same-line wins), or
    None — the accounting the suppression-hygiene rule needs to tell a
    LOAD-BEARING comment from a stale one."""
    for line in (finding.line, finding.line - 1):
        rules = supp.get(line)
        if rules and ("all" in rules or finding.rule in rules):
            return line
    return None


# ------------------------------------------------------------------- baseline


def load_baseline(path: Optional[str]) -> Set[str]:
    if not path or not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    return set(doc.get("fingerprints", []))


def load_baseline_paths(path: Optional[str]) -> Optional[Set[str]]:
    """The display-path set the baseline was written against, or None for
    a legacy/absent baseline that never recorded one.  Staleness of a
    fingerprint can only be judged by a run that scanned AT LEAST these
    files — an unmatched entry on a narrower run may simply belong to a
    file that wasn't looked at."""
    if not path or not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    paths = doc.get("paths")
    return None if paths is None else set(paths)


def write_baseline(
    path: str,
    findings: Sequence[Finding],
    scanned: Optional[Sequence[str]] = None,
) -> None:
    doc = {
        "comment": (
            "mochi_tpu.analysis baseline: findings listed here are "
            "grandfathered and do not fail the run.  Regenerate with "
            "`python -m mochi_tpu.analysis --write-baseline`."
        ),
        "fingerprints": sorted({f.fingerprint for f in findings}),
    }
    if scanned is not None:
        # coverage record: the suppression-hygiene pass convicts stale
        # fingerprints only on runs that re-scan at least these files
        doc["paths"] = sorted(set(scanned))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")


# ----------------------------------------------------------------- AST helpers


def build_import_map(tree: ast.Module) -> Dict[str, str]:
    """Local name -> dotted origin, for resolving what a call really is.

    ``import numpy as np``          -> {"np": "numpy"}
    ``from time import sleep``      -> {"sleep": "time.sleep"}
    ``from ..crypto import keys``   -> {"keys": "crypto.keys"} (relative
    imports resolve to their suffix; matching is by dotted-suffix anyway).
    """
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                origin = f"{base}.{alias.name}" if base else alias.name
                out[alias.asname or alias.name] = origin
    return out


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_call(node: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    """Fully-ish qualified dotted name of a call target, via the import map."""
    dn = dotted_name(node)
    if dn is None:
        return None
    head, _, rest = dn.partition(".")
    origin = imports.get(head)
    if origin is None:
        return dn
    return f"{origin}.{rest}" if rest else origin


def suffix_match(qualified: str, patterns: Iterable[str]) -> Optional[str]:
    """Match ``a.b.c.d`` against patterns by dotted suffix (relative imports
    lose their package prefix, so ``crypto.keys.sign`` must match
    ``mochi_tpu.crypto.keys.sign`` and vice versa).

    A bare single-segment name only matches a single-segment pattern: a
    module-local ``wait()`` or ``verify()`` must not trip deny-list entries
    like ``os.wait`` just because the terminal segment collides — the name
    carries no evidence it is that module's function."""
    parts = qualified.split(".")
    for pat in patterns:
        pp = pat.split(".")
        if len(pp) <= len(parts) and parts[-len(pp):] == pp:
            return pat
        if 2 <= len(parts) <= len(pp) and pp[-len(parts):] == parts:
            return pat
    return None


def snippet_at(src_lines: Sequence[str], line: int) -> str:
    if 1 <= line <= len(src_lines):
        return src_lines[line - 1].strip()
    return ""


# --------------------------------------------------------------------- runner


def display_path(fp: str, scan_root: Optional[str] = None) -> str:
    """The path a finding carries (and its fingerprint hashes).

    Must be (a) CWD-independent — lint.sh scans from the repo root while
    scripts/standing_rules.py passes absolute paths from an arbitrary CWD,
    and a fingerprint mismatch silently un-baselines everything — and
    (b) scope-faithful — per-checker scoping looks for components like
    ``crypto/`` and suffixes like ``cluster/config.py``, so a bare-basename
    display would both drop checkers and break exemptions on single-file
    invocations.  For a file inside a package, anchor at the package root
    (walk up while ``__init__.py`` exists): ``keys.py`` displays as
    ``mochi_tpu/crypto/keys.py`` however it was named.  Otherwise anchor at
    the scan root (directory scans) or the containing directory (file args).
    """
    ap = os.path.abspath(fp)
    pkg_root = os.path.dirname(ap)
    while os.path.exists(os.path.join(pkg_root, "__init__.py")):
        pkg_root = os.path.dirname(pkg_root)
    if pkg_root != os.path.dirname(ap):
        return os.path.relpath(ap, pkg_root).replace(os.sep, "/")
    if scan_root is not None:
        root_name = os.path.basename(os.path.abspath(scan_root))
        rel = os.path.relpath(fp, scan_root)
        return os.path.join(root_name, rel).replace(os.sep, "/")
    parent = os.path.basename(os.path.dirname(ap))
    name = os.path.basename(ap)
    return f"{parent}/{name}" if parent else name


# Scanned source kinds: Python gets the AST checkers; .c gets the lexical
# native checkers (LANG = "c" modules).  display_path/fingerprint/baseline
# machinery is shared — a native finding baselines and suppresses exactly
# like a Python one.
SOURCE_EXTS = (".py", ".c")


def iter_python_files(
    paths: Sequence[str], exts: Sequence[str] = SOURCE_EXTS
) -> List[Tuple[str, str]]:
    """``(display_path, filesystem_path)`` pairs for every source file
    (``exts``) under paths.  (Name kept from the .py-only era — callers and
    tests use it directly.)"""
    out: Dict[str, Tuple[str, str]] = {}  # abspath -> (display, fs path)
    for path in paths:
        norm = os.path.normpath(path)
        if os.path.isfile(norm):
            if norm.endswith(tuple(exts)):
                out.setdefault(os.path.abspath(norm), (display_path(norm), norm))
            continue
        for dirpath, dirnames, filenames in os.walk(norm):
            dirnames[:] = [
                d for d in dirnames if not d.startswith(".") and d != "__pycache__"
            ]
            for fn in sorted(filenames):
                if fn.endswith(tuple(exts)):
                    fp = os.path.join(dirpath, fn)
                    out.setdefault(
                        os.path.abspath(fp), (display_path(fp, scan_root=norm), fp)
                    )
    return sorted(out.values())


def _checkers():
    # Imported here (not module top) so ``core`` stays importable from the
    # checker modules themselves without a cycle.
    from . import (
        async_blocking,
        await_races,
        cancellation,
        const_time,
        invariants,
        native_ct,
        span_lazy,
        trace_safety,
        unbounded_growth,
        wire_taint,
    )

    return [
        async_blocking,
        cancellation,
        trace_safety,
        const_time,
        invariants,
        await_races,
        native_ct,
        span_lazy,
        unbounded_growth,
        wire_taint,
    ]


def _split_checkers(mods):
    """(per-file, whole-tree) partition.  A *tree checker* exposes
    ``extract(tree, src, path, scoped) -> facts`` (picklable, registry-
    independent, cacheable per file) and ``link(facts_list) -> findings``
    (interprocedural, recomputed every run); everything else is the classic
    per-file ``check()`` contract."""
    per_file = [m for m in mods if not hasattr(m, "extract")]
    tree = [m for m in mods if hasattr(m, "extract")]
    return per_file, tree


def all_rules() -> List[str]:
    return [mod.RULE for mod in _checkers()]


@dataclass
class RunResult:
    """Everything a caller (CLI, test, bench gate) needs to render a run."""

    new: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    files_scanned: int = 0
    # display paths actually scanned — recorded into the baseline by
    # --write-baseline so later runs know the coverage staleness needs
    scanned: List[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.new


HYGIENE_RULE = "suppression-hygiene"


# ---------------------------------------------------------------------- cache
#
# The tree has roughly tripled since PR 1 and the full pass now runs inside
# tier-1 AND the bench pre-flight, so cold cost is paid constantly.  Two
# levers, both semantics-preserving:
#
#  * a per-file record cache keyed by (abspath, mtime_ns, size, scoped,
#    rule set, toolchain token): parse + per-file checkers + tree-checker
#    ``extract()`` facts are pure functions of file bytes, so a warm rerun
#    only re-executes the cheap interprocedural ``link()`` stage;
#  * a process pool over cache misses for cold runs (``--jobs`` /
#    MOCHI_ANALYSIS_JOBS; auto when the miss count is large).
#
# The cache is advisory: any I/O or unpickling trouble degrades to a
# recompute, never to a wrong answer.  MOCHI_ANALYSIS_CACHE=0 disables.

_CACHE_ENV = "MOCHI_ANALYSIS_CACHE"
_CACHE_DIR_ENV = "MOCHI_ANALYSIS_CACHE_DIR"
_JOBS_ENV = "MOCHI_ANALYSIS_JOBS"
_CACHE_ERRORS = (OSError, EOFError, ValueError, TypeError, AttributeError,
                 IndexError, KeyError, pickle.PickleError)


def _toolchain_token() -> str:
    """Version stamp: mtimes+sizes of the analysis package itself, so any
    checker edit invalidates every cached record (a stale record from an
    older checker would silently drop that checker's new findings)."""
    d = os.path.dirname(os.path.abspath(__file__))
    parts = []
    try:
        for fn in sorted(os.listdir(d)):
            if fn.endswith(".py"):
                st = os.stat(os.path.join(d, fn))
                parts.append(f"{fn}:{st.st_mtime_ns}:{st.st_size}")
    except OSError:
        return "no-token"
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def cache_dir() -> Optional[str]:
    if os.environ.get(_CACHE_ENV, "1").lower() in ("0", "off", "no", "false"):
        return None
    override = os.environ.get(_CACHE_DIR_ENV)
    if override:
        return override
    uid = getattr(os, "getuid", lambda: 0)()
    return os.path.join(tempfile.gettempdir(), f"mochi-analysis-cache-{uid}")


def _cache_path(cdir: str, filepath: str) -> str:
    key = hashlib.sha256(os.path.abspath(filepath).encode()).hexdigest()[:24]
    return os.path.join(cdir, f"{key}.pkl")


def _cache_load(cdir, filepath, token, scoped, rule_names):
    if not cdir:
        return None
    try:
        st = os.stat(filepath)
        with open(_cache_path(cdir, filepath), "rb") as fh:
            doc = pickle.load(fh)
        if (
            doc.get("token") == token
            and doc.get("mtime_ns") == st.st_mtime_ns
            and doc.get("size") == st.st_size
            and doc.get("scoped") == scoped
            and doc.get("rules") == rule_names
        ):
            return doc["record"]
    except _CACHE_ERRORS:
        return None
    return None


def _cache_store(cdir, filepath, token, scoped, rule_names, record) -> None:
    try:
        os.makedirs(cdir, exist_ok=True)
        st = os.stat(filepath)
        doc = {
            "token": token, "mtime_ns": st.st_mtime_ns, "size": st.st_size,
            "scoped": scoped, "rules": rule_names, "record": record,
        }
        target = _cache_path(cdir, filepath)
        tmp = f"{target}.tmp{os.getpid()}"
        with open(tmp, "wb") as fh:
            pickle.dump(doc, fh)
        os.replace(tmp, target)  # atomic: a concurrent reader sees old or new
    except _CACHE_ERRORS:
        pass


def _select_checkers(rule_names: Sequence[str]):
    by_rule = {mod.RULE: mod for mod in _checkers()}
    return [by_rule[r] for r in rule_names if r in by_rule]


def _compute_record(rel: str, filepath: str, scoped: bool,
                    rule_names: Tuple[str, ...]) -> Dict:
    """Everything the triage/link stages need from one file: per-file
    findings, tree-checker facts, the suppression map.  Pure in the file's
    bytes + rule set — the unit the cache stores and the worker pool maps."""
    record: Dict = {
        "error": None, "is_c": filepath.endswith(".c"), "findings": [],
        "facts": {}, "supp": {}, "supp_snippets": {},
    }
    try:
        with open(filepath, encoding="utf-8") as fh:
            src = fh.read()
    except (OSError, UnicodeDecodeError) as exc:
        record["error"] = Finding("parse-error", rel, 1, 0, f"unreadable: {exc}")
        return record
    tree = None
    if not record["is_c"]:
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as exc:
            record["error"] = Finding(
                "parse-error", rel, exc.lineno or 1, exc.offset or 0,
                f"syntax error: {exc.msg}",
            )
            return record
    supp = suppressions_by_line(src)
    record["supp"] = supp
    src_lines = src.splitlines()
    record["supp_snippets"] = {ln: snippet_at(src_lines, ln) for ln in supp}
    per_file_mods, tree_mods = _split_checkers(_select_checkers(rule_names))
    for mod in per_file_mods:
        if (getattr(mod, "LANG", "py") == "c") != record["is_c"]:
            continue
        record["findings"].extend(mod.check(tree, src, rel, scoped=scoped))
    if not record["is_c"]:
        for mod in tree_mods:
            record["facts"][mod.RULE] = mod.extract(tree, src, rel, scoped=scoped)
    return record


def _worker(item):
    rel, filepath, scoped, rule_names = item
    return rel, _compute_record(rel, filepath, scoped, rule_names)


def _resolve_jobs(jobs: Optional[int], miss_count: int) -> int:
    if jobs is None:
        try:
            jobs = int(os.environ.get(_JOBS_ENV, "0") or "0")
        except ValueError:
            jobs = 0
    if jobs and jobs > 0:
        return jobs
    # auto: parallelize only when the cold set is big enough to amortize
    # worker startup (warm runs are cache hits and never get here)
    return min(os.cpu_count() or 1, 4) if miss_count >= 24 else 1


def run(
    paths: Sequence[str],
    rules: Optional[Sequence[str]] = None,
    baseline: Optional[str] = None,
    scoped: bool = True,
    hygiene: bool = False,
    jobs: Optional[int] = None,
    cache: Optional[bool] = None,
) -> RunResult:
    """Run the pass over ``paths`` (files or directories).

    ``rules`` restricts to a subset of checkers; ``scoped=False`` drops the
    per-checker path scoping (used by the fixture tests, whose snippets live
    under tests/ where e.g. the trace-safety scope would never look).

    ``hygiene=True`` (the CLI default on full-rule runs) makes rot itself a
    finding: a ``mochi-lint: disable`` comment that suppressed nothing this
    run, and a baseline fingerprint no current finding matches, each report
    under ``suppression-hygiene`` — the mechanism that keeps the suppression
    surface and the baseline from quietly outliving the code they excused.
    Meaningless under a rule subset (every other rule's suppressions would
    look unused), so it is force-disabled there.

    ``jobs``/``cache`` control the scan machinery only (see the cache block
    above); results are byte-identical across every setting.

    Three stages: (1) per-file — parse, per-file checkers, tree-checker
    ``extract()`` facts, served from the cache or computed (possibly in a
    worker pool); (2) link — each tree checker's ``link()`` over all facts
    (interprocedural, always recomputed); (3) triage — occurrence indexing,
    suppressions, baseline, hygiene, per file exactly as the single-loop
    runner did.
    """
    checkers = _checkers()
    if rules is not None:
        wanted = set(rules)
        unknown = wanted - {mod.RULE for mod in checkers}
        if unknown:
            raise ValueError(f"unknown rules: {sorted(unknown)}")
        checkers = [mod for mod in checkers if mod.RULE in wanted]
        hygiene = False
    rule_names = tuple(mod.RULE for mod in checkers)
    per_file_mods, tree_mods = _split_checkers(checkers)
    known = load_baseline(baseline)
    matched_baseline: Set[str] = set()
    result = RunResult()
    files = iter_python_files(paths)

    # ---- stage 1: per-file records (cache -> pool -> serial)
    token = _toolchain_token()
    cdir = cache_dir() if cache in (None, True) else None
    records: Dict[str, Dict] = {}
    misses: List[Tuple[str, str, bool, Tuple[str, ...]]] = []
    for rel, filepath in files:
        cached = _cache_load(cdir, filepath, token, scoped, rule_names)
        if cached is not None:
            records[rel] = cached
        else:
            misses.append((rel, filepath, scoped, rule_names))
    n_jobs = _resolve_jobs(jobs, len(misses))
    pooled = False
    if n_jobs > 1 and len(misses) > 1:
        try:
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor

            # spawn, not fork: the caller may have JAX (or anything
            # multithreaded) loaded, and forking a threaded process can
            # deadlock the children
            with ProcessPoolExecutor(
                max_workers=n_jobs,
                mp_context=multiprocessing.get_context("spawn"),
            ) as pool:
                for rel, record in pool.map(_worker, misses, chunksize=8):
                    records[rel] = record
            pooled = True
        except _CACHE_ERRORS:
            # pool unavailable (sandbox, fork limits): records computed so
            # far are kept; the serial loop below fills the rest
            pooled = False
    if not pooled:
        for item in misses:
            if item[0] not in records:
                rel, record = _worker(item)
                records[rel] = record
    if cdir:
        for item in misses:
            _cache_store(cdir, item[1], token, scoped, rule_names,
                         records[item[0]])

    # ---- stage 2: link (interprocedural tree checkers)
    ordered = [rel for rel, _ in files]
    link_by_path: Dict[str, List[Finding]] = {}
    for mod in tree_mods:
        facts = [
            records[rel]["facts"].get(mod.RULE)
            for rel in ordered
            if records[rel]["error"] is None and not records[rel]["is_c"]
        ]
        for finding in mod.link([f for f in facts if f], scoped=scoped):
            link_by_path.setdefault(finding.path, []).append(finding)

    # ---- stage 3: per-file triage (semantics identical to the old loop)
    for rel, filepath in files:
        record = records[rel]
        if record["error"] is not None:
            result.new.append(record["error"])
            continue
        result.files_scanned += 1
        result.scanned.append(rel)
        supp = record["supp"]
        file_findings = list(record["findings"]) + link_by_path.pop(rel, [])
        # Occurrence indices in deterministic (line, col) order, so each of
        # N identical snippets gets its own fingerprint (see Finding).
        seen_snippets: Dict[Tuple[str, str], int] = {}
        used_supp_lines: Set[int] = set()
        for finding in sorted(file_findings, key=lambda f: (f.line, f.col)):
            key = (finding.rule, finding.snippet.strip())
            idx = seen_snippets.get(key, 0)
            seen_snippets[key] = idx + 1
            if idx:
                finding = replace(finding, occurrence=idx)
            supp_line = suppression_line_for(finding, supp)
            if supp_line is not None:
                used_supp_lines.add(supp_line)
                result.suppressed.append(finding)
            elif finding.fingerprint in known:
                matched_baseline.add(finding.fingerprint)
                result.baselined.append(finding)
            else:
                result.new.append(finding)
        if hygiene:
            for line, named in sorted(supp.items()):
                if line in used_supp_lines:
                    continue
                # Only convict a comment this run could have vindicated:
                # every named rule (or "all") must be among the checkers
                # that actually ran over this file kind (tree checkers are
                # Python-side).
                ran = {
                    mod.RULE
                    for mod in per_file_mods
                    if (getattr(mod, "LANG", "py") == "c") == record["is_c"]
                }
                if not record["is_c"]:
                    ran |= {mod.RULE for mod in tree_mods}
                if "all" not in named and not named <= ran:
                    continue
                result.new.append(
                    Finding(
                        HYGIENE_RULE, rel, line, 0,
                        f"unused suppression (disable={','.join(sorted(named))}): "
                        "no finding on this or the next line needs it — delete "
                        "the comment (or fix the drift that orphaned it)",
                        record["supp_snippets"].get(line, ""),
                    )
                )
    # link findings on paths outside the scanned set (defensive: an anchor
    # path the caller excluded) still fail rather than vanish
    for extras in link_by_path.values():
        result.new.extend(extras)
    if hygiene and known:
        # Staleness is only decidable with coverage: an unmatched entry on
        # a partial-path run may belong to a file this run never scanned
        # (convicting it — and the message's --write-baseline advice —
        # would silently amnesty every unscanned file's debt).  The
        # baseline records the display paths it was written against;
        # convict only when this run re-scanned ALL of them (display paths
        # are cwd-independent, so set containment is exact).  A legacy
        # baseline without the record — or one referencing a since-deleted
        # file — never convicts; regenerating once upgrades/heals it.
        recorded = load_baseline_paths(baseline)
        covered = recorded is not None and recorded <= set(result.scanned)
        if covered:
            stale = sorted(known - matched_baseline)
            base_rel = display_path(baseline) if baseline else "baseline"
            for fp in stale:
                result.new.append(
                    Finding(
                        HYGIENE_RULE, base_rel, 1, 0,
                        f"stale baseline entry {fp}: no current finding "
                        "matches it — prune it (python -m mochi_tpu.analysis "
                        "--write-baseline regenerates)",
                        snippet=fp,
                    )
                )
    return result

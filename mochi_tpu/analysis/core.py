"""Framework for the project-native static-analysis pass.

Why a bespoke pass instead of an off-the-shelf linter: the defects that
actually hurt this codebase are *protocol-specific* — a ``time.sleep`` inside
a replica coroutine stalls every connection multiplexed on that event loop
(the 1-RT read / 2-RT write budget is milliseconds); an ``except Exception``
that eats ``asyncio.CancelledError`` turns shutdown into a hang; a Python
``if`` on a traced value silently forces a host sync inside the batched
verifier; a ``==`` on signature bytes is a timing oracle.  Generic linters
know none of this vocabulary.

Architecture: each checker module exposes ``RULE`` (its name) and
``check(tree, src, path, scoped=True) -> list[Finding]``.  This module owns
the shared plumbing: the :class:`Finding` type, suppression comments,
baseline files, file walking, and the runner.

Suppression syntax (see docs/ANALYSIS.md): a finding on line N is suppressed
by a comment on line N or on line N-1 of the form::

    # mochi-lint: disable=<rule>[,<rule>...]
    # mochi-lint: disable=all

Baseline: a JSON file ``{"fingerprints": [...]}``.  Findings whose
fingerprint appears in the baseline are reported as "baselined" and do not
fail the run — the mechanism that lets the pass land on an imperfect tree
and ratchet forward.  The shipped baseline is empty: every finding on the
current tree is either fixed or carries an explicit suppression.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field, replace
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


@dataclass(frozen=True)
class Finding:
    """One checker hit, addressable and stable enough to baseline."""

    rule: str
    path: str  # posix-style, package-anchored (see display_path)
    line: int
    col: int
    message: str
    snippet: str = ""
    # 0-based index among same-(rule, snippet) findings in this file, in
    # line order; assigned by run().  Without it, two textually identical
    # violations in one file would share a fingerprint and one baseline
    # entry would grandfather both — the ratchet could move backwards.
    occurrence: int = 0

    @property
    def fingerprint(self) -> str:
        """Content-addressed id: survives line-number drift (the baseline
        must not churn when unrelated edits move code), breaks when the
        flagged code itself changes (a moved-AND-edited line is a new
        finding, as it should be)."""
        basis = f"{self.rule}|{self.path}|{self.snippet.strip()}|{self.occurrence}"
        return hashlib.sha256(basis.encode()).hexdigest()[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


# --------------------------------------------------------------- suppressions

_SUPPRESS_RE = re.compile(r"#\s*mochi-lint:\s*disable=([A-Za-z0-9_,\- ]+)")


def suppressions_by_line(src: str) -> Dict[int, Set[str]]:
    """Map 1-based line number -> set of rule names disabled there.

    Regex over raw lines rather than the tokenize module: a suppression must
    keep working even in a file the tokenizer rejects (the parse-error path
    still reports, and half-edited files shouldn't crash the linter)."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def is_suppressed(finding: Finding, supp: Dict[int, Set[str]]) -> bool:
    for line in (finding.line, finding.line - 1):
        rules = supp.get(line)
        if rules and ("all" in rules or finding.rule in rules):
            return True
    return False


# ------------------------------------------------------------------- baseline


def load_baseline(path: Optional[str]) -> Set[str]:
    if not path or not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    return set(doc.get("fingerprints", []))


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    doc = {
        "comment": (
            "mochi_tpu.analysis baseline: findings listed here are "
            "grandfathered and do not fail the run.  Regenerate with "
            "`python -m mochi_tpu.analysis --write-baseline`."
        ),
        "fingerprints": sorted({f.fingerprint for f in findings}),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")


# ----------------------------------------------------------------- AST helpers


def build_import_map(tree: ast.Module) -> Dict[str, str]:
    """Local name -> dotted origin, for resolving what a call really is.

    ``import numpy as np``          -> {"np": "numpy"}
    ``from time import sleep``      -> {"sleep": "time.sleep"}
    ``from ..crypto import keys``   -> {"keys": "crypto.keys"} (relative
    imports resolve to their suffix; matching is by dotted-suffix anyway).
    """
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                origin = f"{base}.{alias.name}" if base else alias.name
                out[alias.asname or alias.name] = origin
    return out


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_call(node: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    """Fully-ish qualified dotted name of a call target, via the import map."""
    dn = dotted_name(node)
    if dn is None:
        return None
    head, _, rest = dn.partition(".")
    origin = imports.get(head)
    if origin is None:
        return dn
    return f"{origin}.{rest}" if rest else origin


def suffix_match(qualified: str, patterns: Iterable[str]) -> Optional[str]:
    """Match ``a.b.c.d`` against patterns by dotted suffix (relative imports
    lose their package prefix, so ``crypto.keys.sign`` must match
    ``mochi_tpu.crypto.keys.sign`` and vice versa).

    A bare single-segment name only matches a single-segment pattern: a
    module-local ``wait()`` or ``verify()`` must not trip deny-list entries
    like ``os.wait`` just because the terminal segment collides — the name
    carries no evidence it is that module's function."""
    parts = qualified.split(".")
    for pat in patterns:
        pp = pat.split(".")
        if len(pp) <= len(parts) and parts[-len(pp):] == pp:
            return pat
        if 2 <= len(parts) <= len(pp) and pp[-len(parts):] == parts:
            return pat
    return None


def snippet_at(src_lines: Sequence[str], line: int) -> str:
    if 1 <= line <= len(src_lines):
        return src_lines[line - 1].strip()
    return ""


# --------------------------------------------------------------------- runner


def display_path(fp: str, scan_root: Optional[str] = None) -> str:
    """The path a finding carries (and its fingerprint hashes).

    Must be (a) CWD-independent — lint.sh scans from the repo root while
    scripts/standing_rules.py passes absolute paths from an arbitrary CWD,
    and a fingerprint mismatch silently un-baselines everything — and
    (b) scope-faithful — per-checker scoping looks for components like
    ``crypto/`` and suffixes like ``cluster/config.py``, so a bare-basename
    display would both drop checkers and break exemptions on single-file
    invocations.  For a file inside a package, anchor at the package root
    (walk up while ``__init__.py`` exists): ``keys.py`` displays as
    ``mochi_tpu/crypto/keys.py`` however it was named.  Otherwise anchor at
    the scan root (directory scans) or the containing directory (file args).
    """
    ap = os.path.abspath(fp)
    pkg_root = os.path.dirname(ap)
    while os.path.exists(os.path.join(pkg_root, "__init__.py")):
        pkg_root = os.path.dirname(pkg_root)
    if pkg_root != os.path.dirname(ap):
        return os.path.relpath(ap, pkg_root).replace(os.sep, "/")
    if scan_root is not None:
        root_name = os.path.basename(os.path.abspath(scan_root))
        rel = os.path.relpath(fp, scan_root)
        return os.path.join(root_name, rel).replace(os.sep, "/")
    parent = os.path.basename(os.path.dirname(ap))
    name = os.path.basename(ap)
    return f"{parent}/{name}" if parent else name


def iter_python_files(paths: Sequence[str]) -> List[Tuple[str, str]]:
    """``(display_path, filesystem_path)`` pairs for every .py under paths."""
    out: Dict[str, Tuple[str, str]] = {}  # abspath -> (display, fs path)
    for path in paths:
        norm = os.path.normpath(path)
        if os.path.isfile(norm):
            if norm.endswith(".py"):
                out.setdefault(os.path.abspath(norm), (display_path(norm), norm))
            continue
        for dirpath, dirnames, filenames in os.walk(norm):
            dirnames[:] = [
                d for d in dirnames if not d.startswith(".") and d != "__pycache__"
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    fp = os.path.join(dirpath, fn)
                    out.setdefault(
                        os.path.abspath(fp), (display_path(fp, scan_root=norm), fp)
                    )
    return sorted(out.values())


def _checkers():
    # Imported here (not module top) so ``core`` stays importable from the
    # checker modules themselves without a cycle.
    from . import (
        async_blocking,
        cancellation,
        const_time,
        invariants,
        trace_safety,
    )

    return [async_blocking, cancellation, trace_safety, const_time, invariants]


def all_rules() -> List[str]:
    return [mod.RULE for mod in _checkers()]


@dataclass
class RunResult:
    """Everything a caller (CLI, test, bench gate) needs to render a run."""

    new: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    files_scanned: int = 0

    @property
    def clean(self) -> bool:
        return not self.new


def run(
    paths: Sequence[str],
    rules: Optional[Sequence[str]] = None,
    baseline: Optional[str] = None,
    scoped: bool = True,
) -> RunResult:
    """Run the pass over ``paths`` (files or directories).

    ``rules`` restricts to a subset of checkers; ``scoped=False`` drops the
    per-checker path scoping (used by the fixture tests, whose snippets live
    under tests/ where e.g. the trace-safety scope would never look).
    """
    checkers = _checkers()
    if rules is not None:
        wanted = set(rules)
        unknown = wanted - {mod.RULE for mod in checkers}
        if unknown:
            raise ValueError(f"unknown rules: {sorted(unknown)}")
        checkers = [mod for mod in checkers if mod.RULE in wanted]
    known = load_baseline(baseline)
    result = RunResult()
    for rel, filepath in iter_python_files(paths):
        try:
            with open(filepath, encoding="utf-8") as fh:
                src = fh.read()
        except (OSError, UnicodeDecodeError) as exc:
            result.new.append(
                Finding("parse-error", rel, 1, 0, f"unreadable: {exc}")
            )
            continue
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as exc:
            result.new.append(
                Finding(
                    "parse-error", rel, exc.lineno or 1, exc.offset or 0,
                    f"syntax error: {exc.msg}",
                )
            )
            continue
        result.files_scanned += 1
        supp = suppressions_by_line(src)
        file_findings: List[Finding] = []
        for mod in checkers:
            file_findings.extend(mod.check(tree, src, rel, scoped=scoped))
        # Occurrence indices in deterministic (line, col) order, so each of
        # N identical snippets gets its own fingerprint (see Finding).
        seen_snippets: Dict[Tuple[str, str], int] = {}
        for finding in sorted(file_findings, key=lambda f: (f.line, f.col)):
            key = (finding.rule, finding.snippet.strip())
            idx = seen_snippets.get(key, 0)
            seen_snippets[key] = idx + 1
            if idx:
                finding = replace(finding, occurrence=idx)
            if is_suppressed(finding, supp):
                result.suppressed.append(finding)
            elif finding.fingerprint in known:
                result.baselined.append(finding)
            else:
                result.new.append(finding)
    return result

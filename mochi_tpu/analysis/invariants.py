"""protocol-invariants: structural rules the protocol layer depends on.

Two rules:

* **payload registration** — every message dataclass (``*ToServer`` /
  ``*FromServer``) defined in a module that also defines the
  ``_PAYLOAD_TYPES`` wire-tag tuple must appear in that tuple.  A class
  left out still type-checks, still constructs, and then dies at the first
  ``encode_envelope`` with a ``KeyError`` — at runtime, on a replica, under
  traffic.  Wire tags are positional, so registration is also where
  append-only tag stability is enforced; the checker makes "you added a
  message and forgot the tuple" a lint failure instead of an outage.

* **quorum literal** — arithmetic of the shape ``2 * f + 1`` (in either
  operand order) anywhere outside ``cluster/config.py``.  The quorum
  formula lives in exactly one place (``ClusterConfig.quorum``) because the
  reference got it wrong twice (``ClusterConfiguration.java:260-267``
  overstates f; the strict ``>`` in quorum checks, SURVEY.md §2.6) — an
  inline re-derivation is where the next divergence starts.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Finding, snippet_at

RULE = "protocol-invariants"

_MESSAGE_SUFFIXES = ("ToServer", "FromServer")
_REGISTRY_NAME = "_PAYLOAD_TYPES"


def _registry_names(tree: ast.Module) -> Optional[Set[str]]:
    """Names listed in the module's ``_PAYLOAD_TYPES`` tuple, or None if the
    module defines no such registry (rule does not apply there)."""
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        for target in targets:
            if isinstance(target, ast.Name) and target.id == _REGISTRY_NAME:
                names: Set[str] = set()
                if isinstance(value, (ast.Tuple, ast.List)):
                    for elt in value.elts:
                        if isinstance(elt, ast.Name):
                            names.add(elt.id)
                return names
    return None


def _registration_findings(tree: ast.Module, src_lines, path: str) -> List[Finding]:
    registered = _registry_names(tree)
    if registered is None:
        return []
    findings: List[Finding] = []
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        if not node.name.endswith(_MESSAGE_SUFFIXES):
            continue
        if node.name not in registered:
            findings.append(
                Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"message class `{node.name}` is not registered in "
                    f"{_REGISTRY_NAME}; it cannot ride an envelope "
                    "(append it — wire tags are positional)",
                    snippet_at(src_lines, node.lineno),
                )
            )
    return findings


def _is_two(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value == 2


def _is_one(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value == 1


def _is_f_ref(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "f"
    if isinstance(node, ast.Attribute):
        return node.attr == "f"
    return False


def _is_two_f(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.BinOp)
        and isinstance(node.op, ast.Mult)
        and (
            (_is_two(node.left) and _is_f_ref(node.right))
            or (_is_two(node.right) and _is_f_ref(node.left))
        )
    )


def _quorum_findings(tree: ast.Module, src_lines, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add)):
            continue
        if (_is_two_f(node.left) and _is_one(node.right)) or (
            _is_two_f(node.right) and _is_one(node.left)
        ):
            findings.append(
                Finding(
                    RULE, path, node.lineno, node.col_offset,
                    "inline quorum arithmetic `2*f + 1`; use "
                    "ClusterConfig.quorum (single source of BFT math)",
                    snippet_at(src_lines, node.lineno),
                )
            )
    return findings


def check(tree: ast.Module, src: str, path: str, scoped: bool = True) -> List[Finding]:
    src_lines = src.splitlines()
    findings = _registration_findings(tree, src_lines, path)
    if not scoped or not path.endswith("cluster/config.py"):
        findings.extend(_quorum_findings(tree, src_lines, path))
    return findings

"""jax-trace-safety: host sync points and Python branching on traced values.

The verifier's whole performance story is that one ``jax.jit`` traces the
complete Ed25519 pipeline into a single XLA program (SURVEY.md §7: "no
data-dependent Python control flow").  Inside traced code, a Python ``if``
on an array value raises ``TracerBoolConversionError`` at best — and at
worst silently *retraces per value* when the branch condition happens to be
weakly typed.  ``float(x)`` / ``int(x)`` / ``x.item()`` force a blocking
device->host transfer that serializes the XLA pipeline; ``np.*`` calls on
traced operands silently fall back to host numpy, dropping the operand out
of the fused program.

Scope (``scoped=True``): files under ``crypto/`` and ``parallel/`` — the
two packages whose code runs under trace.  A function is considered traced
if it is decorated with a jit-like decorator (``jit``, ``pjit``,
``pallas_call``, ``partial(jit, ...)``) or if any parameter is annotated as
a JAX array (``jnp.ndarray``, ``jax.Array``) — the convention this
codebase already follows throughout ``crypto/field.py`` / ``curve.py``.

Static-shape escapes are exempt: ``.shape`` / ``.ndim`` / ``.dtype`` /
``.size`` are trace-time constants, so branching on them is exactly how
this code selects kernel variants.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Finding, build_import_map, dotted_name, resolve_call, snippet_at

RULE = "jax-trace-safety"

_JIT_DECORATORS = {"jit", "pjit", "pallas_call", "custom_vjp", "checkpoint"}
_ARRAY_ANNOTATIONS = ("jnp.ndarray", "jax.Array", "jax.numpy.ndarray", "Array")
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_HOST_SYNC_CALLS = {"float", "int", "bool", "complex"}
_HOST_SYNC_METHODS = {"item", "tolist", "__array__"}


def _decorator_is_jit(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        # @partial(jax.jit, ...) / @jax.jit(static_argnums=...)
        inner = dotted_name(node.func)
        if inner and inner.split(".")[-1] == "partial" and node.args:
            return _decorator_is_jit(node.args[0])
        node = node.func
    dn = dotted_name(node)
    return bool(dn) and dn.split(".")[-1] in _JIT_DECORATORS


def _static_argnames(decorators) -> Set[str]:
    """Parameters declared static via ``static_argnames=(...)`` — they are
    Python values at trace time, so branching on them is exactly right."""
    static: Set[str] = set()
    for dec in decorators:
        if not isinstance(dec, ast.Call):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                value = kw.value
                elts = (
                    value.elts
                    if isinstance(value, (ast.Tuple, ast.List))
                    else [value]
                )
                for elt in elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        static.add(elt.value)
    return static


def _annotation_is_array(node: Optional[ast.AST]) -> bool:
    if node is None:
        return False
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on parsed trees
        return False
    return any(marker in text for marker in _ARRAY_ANNOTATIONS)


def _traced_params(func) -> Set[str]:
    args = func.args
    all_args = args.posonlyargs + args.args + args.kwonlyargs
    traced = {a.arg for a in all_args if _annotation_is_array(a.annotation)}
    if not traced and any(_decorator_is_jit(d) for d in func.decorator_list):
        # Un-annotated jitted function: every parameter is a tracer.
        traced = {a.arg for a in all_args if a.arg not in ("self", "cls")}
    return traced - _static_argnames(func.decorator_list)


def _static_exempt_names(expr: ast.AST) -> Set[int]:
    """ids of Name nodes under a static-attribute access (``x.shape[0]``)."""
    exempt: Set[int] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name):
                    exempt.add(id(sub))
    return exempt


def _traced_names_in(expr: ast.AST, traced: Set[str]) -> List[ast.Name]:
    exempt = _static_exempt_names(expr)
    return [
        node
        for node in ast.walk(expr)
        if isinstance(node, ast.Name)
        and node.id in traced
        and id(node) not in exempt
    ]


class _TracedBodyVisitor(ast.NodeVisitor):
    def __init__(self, traced, imports, src_lines, path):
        self.traced = traced
        self.imports = imports
        self.src_lines = src_lines
        self.path = path
        self.findings: List[Finding] = []

    def _flag(self, node: ast.AST, message: str) -> None:
        self.findings.append(
            Finding(
                RULE, self.path, node.lineno, node.col_offset, message,
                snippet_at(self.src_lines, node.lineno),
            )
        )

    # Nested defs get their own _traced_params treatment at the top level.
    def visit_FunctionDef(self, node):
        pass

    def visit_AsyncFunctionDef(self, node):
        pass

    def _check_branch(self, node, test: ast.AST, kind: str) -> None:
        hits = _traced_names_in(test, self.traced)
        if hits:
            self._flag(
                node,
                f"Python {kind} on traced value `{hits[0].id}`; use "
                "jnp.where / lax.select / lax.cond (shape/dtype branching "
                "is exempt)",
            )

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node, node.test, "`if`")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node, node.test, "`while`")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_branch(node, node.test, "conditional expression")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_branch(node, node.test, "`assert`")
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        hits = _traced_names_in(node.iter, self.traced)
        if hits:
            self._flag(
                node,
                f"Python `for` iterates over traced value `{hits[0].id}`; "
                "use lax.fori_loop / lax.scan (range(x.shape[i]) is exempt)",
            )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Name) and func.id in _HOST_SYNC_CALLS:
            hits = []
            for arg in node.args:
                hits.extend(_traced_names_in(arg, self.traced))
            if hits:
                self._flag(
                    node,
                    f"`{func.id}(...)` on traced value `{hits[0].id}` forces "
                    "a host sync inside the traced program",
                )
        elif isinstance(func, ast.Attribute) and func.attr in _HOST_SYNC_METHODS:
            if _traced_names_in(func.value, self.traced):
                self._flag(
                    node,
                    f"`.{func.attr}()` forces a blocking device->host "
                    "transfer inside the traced program",
                )
        else:
            qualified = resolve_call(func, self.imports)
            if qualified is not None and (
                qualified.startswith("numpy.") or qualified.startswith("np.")
            ):
                hits = []
                for arg in node.args:
                    hits.extend(_traced_names_in(arg, self.traced))
                if hits:
                    self._flag(
                        node,
                        f"host numpy op `{qualified}` on traced value "
                        f"`{hits[0].id}`; use jnp inside traced code",
                    )
        self.generic_visit(node)


def _in_scope(path: str) -> bool:
    parts = path.split("/")
    return "crypto" in parts or "parallel" in parts


def check(tree: ast.Module, src: str, path: str, scoped: bool = True) -> List[Finding]:
    if scoped and not _in_scope(path):
        return []
    imports = build_import_map(tree)
    src_lines = src.splitlines()
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            traced = _traced_params(node)
            if not traced:
                continue
            visitor = _TracedBodyVisitor(traced, imports, src_lines, path)
            for stmt in node.body:
                visitor.visit(stmt)
            findings.extend(visitor.findings)
    return findings

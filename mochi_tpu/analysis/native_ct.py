"""native-const-time: secret-dependent control flow across the C seam.

The ``constant-time`` AST rule stops at the Python boundary, but since the
native host engine landed (``native/hbatch.c``: SHA-512, GF(2^255-19),
Straus verify, RFC 8032 sign) the most timing-sensitive code in the tree is
C.  The engine's own discipline is documented at ``ge_mul_base`` ("64
unconditional additions, NO zero-digit skip — the signing scalars are
SECRET"); this pass makes that discipline checkable instead of a comment.

This is a LEXER pass, not a parser: C functions are found by brace
matching, comments/strings are blanked (line structure preserved), and two
patterns are flagged inside functions that handle secrets:

* **secret branch** (severity ``high``) — an ``if``/``while``/``switch``
  condition, or a ``for`` loop's condition clause, whose expression
  contains a secret identifier.  A branch taken per secret bit/nibble is
  the exact channel the zero-digit-skip comment forbids.

* **secret index** (``advice``) — an array subscript whose INDEX
  expression contains a secret identifier (``TAB[d]`` with ``d`` derived
  from key nibbles).  A cache-timing channel, weaker than a branch;
  the comb tables here are small and hot, so known-good sites carry a
  reviewed suppression rather than a restructure.

What counts as secret: parameters/locals matching ``secret``/``priv``/
``nonce``/``seed``/``*_scalar``, any name listed in a
``/* mochi-ct: secret(a, r) */`` annotation within three lines above the
function header, plus ONE level of local taint (``d = ...k...`` makes
``d`` secret when ``k`` is).  The verify path's digit loops (``ns``/
``nh`` from the PUBLIC signature/challenge bytes) stay clean by
construction — that contrast is pinned as the checker's fixture.

Scoped to ``native/`` (the rest of the tree has no C).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding

RULE = "native-const-time"
LANG = "c"

_SECRET_NAME = re.compile(r"(?:^|_)(?:secret|priv|private|nonce|seed)|_scalar$")
_ANNOTATION = re.compile(r"mochi-ct:\s*secret\(([^)]*)\)")
_IDENT = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")
# `type name(args) {` at top level.  The return-type class admits newlines
# so the GNU/kernel two-line style (`static void\nname(...)`) still scans —
# a C statement ends in `;`/`)` (excluded), so the match cannot leak across
# statements, and the trailing `{` requirement in _find_functions rejects
# calls and prototypes either way.
_FUNC_HEADER = re.compile(
    r"(?:^|\n)(?:static\s+)?[A-Za-z_][A-Za-z0-9_ *\n]*?\b([A-Za-z_][A-Za-z0-9_]*)\s*\(",
)
_C_KEYWORDS = {
    "if", "for", "while", "switch", "return", "sizeof", "int", "char",
    "void", "const", "static", "uint8_t", "uint64_t", "unsigned", "goto",
    "else", "do", "break", "continue", "case", "default", "struct",
}


def _blank_comments_and_strings(src: str) -> str:
    """Replace comment/string interiors with spaces, preserving newlines so
    every offset keeps its line number."""
    out = list(src)
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c == "/" and i + 1 < n and src[i + 1] == "*":
            j = src.find("*/", i + 2)
            j = n if j == -1 else j + 2
            for k in range(i, j):
                if out[k] != "\n":
                    out[k] = " "
            i = j
        elif c == "/" and i + 1 < n and src[i + 1] == "/":
            j = src.find("\n", i)
            j = n if j == -1 else j
            for k in range(i, j):
                out[k] = " "
            i = j
        elif c in "\"'":
            quote = c
            j = i + 1
            while j < n and src[j] != quote:
                j = j + 2 if src[j] == "\\" else j + 1
            j = min(j + 1, n)
            for k in range(i + 1, j - 1):
                if out[k] != "\n":
                    out[k] = " "
            i = j
        else:
            i += 1
    return "".join(out)


def _line_of(src: str, offset: int) -> int:
    return src.count("\n", 0, offset) + 1


def _find_functions(clean: str) -> List[Tuple[str, int, int, int]]:
    """(name, header_offset, body_start, body_end) for every brace-matched
    top-level function body."""
    out: List[Tuple[str, int, int, int]] = []
    for m in _FUNC_HEADER.finditer(clean):
        name = m.group(1)
        if name in _C_KEYWORDS:
            continue
        # find the parameter list's closing paren, then require `{`
        depth = 0
        i = m.end() - 1
        while i < len(clean):
            if clean[i] == "(":
                depth += 1
            elif clean[i] == ")":
                depth -= 1
                if depth == 0:
                    break
            i += 1
        j = i + 1
        while j < len(clean) and clean[j] in " \t\n":
            j += 1
        if j >= len(clean) or clean[j] != "{":
            continue  # prototype / macro call
        depth = 0
        k = j
        while k < len(clean):
            if clean[k] == "{":
                depth += 1
            elif clean[k] == "}":
                depth -= 1
                if depth == 0:
                    break
            k += 1
        out.append((name, m.start(1), j + 1, k))
    return out


def _secret_names(
    src: str, clean: str, header_off: int, body: str
) -> Set[str]:
    """Secret identifiers for one function: name-pattern matches among the
    parameter list + declared locals, ``mochi-ct: secret(...)`` annotations
    above the header, and one level of assignment taint."""
    secrets: Set[str] = set()
    # annotation within 3 raw-source lines above the header
    header_line = _line_of(clean, header_off)
    raw_lines = src.splitlines()
    for ln in range(max(0, header_line - 4), header_line):
        m = _ANNOTATION.search(raw_lines[ln]) if ln < len(raw_lines) else None
        if m:
            secrets.update(
                n.strip() for n in m.group(1).split(",") if n.strip()
            )
    # parameter list
    paren = clean.index("(", header_off)
    depth, i = 0, paren
    while i < len(clean):
        if clean[i] == "(":
            depth += 1
        elif clean[i] == ")":
            depth -= 1
            if depth == 0:
                break
        i += 1
    for ident in _IDENT.findall(clean[paren : i + 1]):
        if ident not in _C_KEYWORDS and _SECRET_NAME.search(ident):
            secrets.add(ident)
    # pattern-named locals + one level of taint.  Compound assignments
    # (|= ^= += &= -= *= /= %= <<= >>=) taint too — accumulate-into-`d`
    # is THE dominant constant-time C idiom, and missing it silently
    # un-flags the secret branch on the accumulator.  `==`/`<=`/`>=`/`!=`
    # cannot match: the operator class excludes bare < > ! and the RHS
    # must not start with `=`.
    for _ in range(2):  # second pass lets taint chain one extra hop
        for m in re.finditer(
            r"([A-Za-z_][A-Za-z0-9_]*)\s*(?:\[[^\]]*\])?\s*"
            r"(?:<<|>>|[|^&+\-*/%])?=([^=][^;]*);",
            body,
        ):
            lhs, rhs = m.group(1), m.group(2)
            if lhs in _C_KEYWORDS:
                continue
            if _SECRET_NAME.search(lhs):
                secrets.add(lhs)
                continue
            rhs_ids = set(_IDENT.findall(rhs))
            if rhs_ids & secrets:
                secrets.add(lhs)
    return secrets


def _branch_spans(body: str) -> List[Tuple[int, str, str]]:
    """(offset, kind, condition_text) for if/while/switch conditions and
    the middle clause of for(;;) loops."""
    out: List[Tuple[int, str, str]] = []
    for m in re.finditer(r"\b(if|while|switch|for)\s*\(", body):
        kind = m.group(1)
        depth, i = 0, m.end() - 1
        while i < len(body):
            if body[i] == "(":
                depth += 1
            elif body[i] == ")":
                depth -= 1
                if depth == 0:
                    break
            i += 1
        inner = body[m.end() : i]
        if kind == "for":
            parts = inner.split(";")
            inner = parts[1] if len(parts) >= 2 else ""
        out.append((m.start(), kind, inner))
    return out


def check(tree, src: str, path: str, scoped: bool = True) -> List[Finding]:
    del tree  # lexical pass; no AST for C
    if scoped and "native" not in path.split("/"):
        return []
    clean = _blank_comments_and_strings(src)
    src_lines = src.splitlines()
    findings: List[Finding] = []
    for name, header_off, body_start, body_end in _find_functions(clean):
        body = clean[body_start:body_end]
        secrets = _secret_names(src, clean, header_off, body)
        if not secrets:
            continue
        for off, kind, cond in _branch_spans(body):
            cond_ids = set(_IDENT.findall(cond))
            hit = sorted(cond_ids & secrets)
            if hit:
                line = _line_of(clean, body_start + off)
                findings.append(
                    Finding(
                        RULE, path, line, 0,
                        f"[secret-branch] `{kind}` in {name}() branches on "
                        f"secret value(s) {', '.join(hit)}: per-secret-bit "
                        "control flow is a timing channel — restructure "
                        "branch-free (ge_mul_base's unconditional-add comb "
                        "is the exemplar)",
                        src_lines[line - 1].strip() if line <= len(src_lines) else "",
                        severity="high",
                    )
                )
        # secret-dependent indexing: IDENT [ ... ][ ... ] — EVERY subscript
        # in a chained lookup is inspected (a secret in a leading dimension
        # is the same cache-line channel as one in the last)
        for m in re.finditer(r"[A-Za-z_][A-Za-z0-9_]*((?:\[[^\[\]]+\])+)", body):
            idx_ids: Set[str] = set()
            for idx in re.findall(r"\[([^\[\]]+)\]", m.group(1)):
                idx_ids.update(_IDENT.findall(idx))
            hit = sorted(idx_ids & secrets)
            if hit:
                line = _line_of(clean, body_start + m.start())
                findings.append(
                    Finding(
                        RULE, path, line, 0,
                        f"[secret-index] table lookup in {name}() indexed by "
                        f"secret-derived value(s) {', '.join(hit)}: a "
                        "cache-timing channel — mask-select across the table "
                        "or carry a reviewed suppression for small hot tables",
                        src_lines[line - 1].strip() if line <= len(src_lines) else "",
                        severity="advice",
                    )
                )
    # dedupe identical (line, message) pairs (regex passes can overlap)
    seen = set()
    unique: List[Finding] = []
    for f in findings:
        key = (f.line, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique

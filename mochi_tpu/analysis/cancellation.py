"""cancellation-hygiene: exception handlers that eat task cancellation.

asyncio delivers cancellation by raising ``asyncio.CancelledError`` at the
coroutine's current ``await``.  A handler that catches it and doesn't
re-raise turns ``task.cancel()`` into a no-op: replica ``close()`` hangs on
a worker that "handled" its own cancellation, reconfiguration leaves zombie
resync loops, test teardown deadlocks.  On Python >= 3.8 ``CancelledError``
derives from ``BaseException``, so a plain ``except Exception`` no longer
catches it — but bare ``except:``, ``except BaseException``, and tuples
that *name* ``CancelledError`` next to ``Exception`` still do, and a broad
handler around an ``await`` should make its cancellation story explicit
rather than rely on the reader knowing the 3.8 hierarchy change.

Flags, inside coroutine bodies only and only when the ``try`` body can
actually be cancelled (contains ``await`` / ``async for`` / ``async with``):

1. bare ``except:`` that doesn't re-raise;
2. ``except BaseException`` that doesn't re-raise;
3. a handler naming ``CancelledError`` *in the same tuple as* a broad
   exception class, without re-raise — the "deliberately swallow everything
   including cancellation" anti-pattern;
4. ``except Exception`` without re-raise and with no sibling handler taking
   ``CancelledError`` — fix by adding ``except asyncio.CancelledError:
   raise`` above it (free on >= 3.8, and it states the intent).

A *standalone* ``except asyncio.CancelledError: pass`` is allowed: it is
the canonical idiom for awaiting a task you just ``.cancel()``-ed.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from .core import Finding, dotted_name, snippet_at

RULE = "cancellation-hygiene"

_BROAD = {"Exception", "BaseException"}


def _handler_type_names(handler: ast.ExceptHandler) -> List[str]:
    """Terminal identifiers of the caught types ('' list for bare except)."""
    t = handler.type
    if t is None:
        return []
    nodes: Sequence[ast.AST] = t.elts if isinstance(t, ast.Tuple) else [t]
    names = []
    for node in nodes:
        dn = dotted_name(node)
        if dn:
            names.append(dn.split(".")[-1])
    return names


def _reraises(handler: ast.ExceptHandler) -> bool:
    """Any ``raise`` in the handler's own statement tree counts: a bare
    re-raise propagates the cancellation; a typed raise still transfers
    control out.  Raises inside nested function definitions do NOT count —
    ``except BaseException: register(lambda: (_ for _ in ()).throw(x))``
    never raises in the handler itself."""

    class S(ast.NodeVisitor):
        found = False

        def visit_FunctionDef(self, node):
            pass

        def visit_AsyncFunctionDef(self, node):
            pass

        def visit_Lambda(self, node):
            pass

        def visit_Raise(self, node):
            self.found = True

    s = S()
    for stmt in handler.body:
        s.visit(stmt)
    return s.found


class _BodyScanner(ast.NodeVisitor):
    """Find Try/Await/AsyncFor/AsyncWith in ONE function body, without
    descending into nested function definitions."""

    def __init__(self):
        self.tries: List[ast.Try] = []

    def visit_FunctionDef(self, node):  # don't descend
        pass

    def visit_AsyncFunctionDef(self, node):  # visited on its own
        pass

    def visit_Lambda(self, node):
        pass

    def visit_Try(self, node: ast.Try) -> None:
        self.tries.append(node)
        self.generic_visit(node)


def _contains_await(nodes: Sequence[ast.stmt]) -> bool:
    class S(ast.NodeVisitor):
        found = False

        def visit_FunctionDef(self, node):
            pass

        def visit_AsyncFunctionDef(self, node):
            pass

        def visit_Lambda(self, node):
            pass

        def visit_Await(self, node):
            self.found = True

        def visit_AsyncFor(self, node):
            self.found = True
            self.generic_visit(node)

        def visit_AsyncWith(self, node):
            self.found = True
            self.generic_visit(node)

    s = S()
    for n in nodes:
        s.visit(n)
    return s.found


def _check_coroutine(
    func: ast.AsyncFunctionDef, src_lines, path: str
) -> List[Finding]:
    scanner = _BodyScanner()
    for stmt in func.body:
        scanner.visit(stmt)
    findings: List[Finding] = []
    for try_node in scanner.tries:
        if not _contains_await(try_node.body):
            continue  # nothing in this try can raise CancelledError
        sibling_catches_cancel = any(
            "CancelledError" in _handler_type_names(h) for h in try_node.handlers
        )
        for handler in try_node.handlers:
            names = _handler_type_names(handler)
            reraises = _reraises(handler)
            line = handler.lineno
            snippet = snippet_at(src_lines, line)
            if handler.type is None and not reraises:
                findings.append(
                    Finding(
                        RULE, path, line, handler.col_offset,
                        "bare `except:` in coroutine swallows "
                        "asyncio.CancelledError; catch specific exceptions "
                        "or re-raise cancellation",
                        snippet,
                    )
                )
            elif "BaseException" in names and not reraises:
                findings.append(
                    Finding(
                        RULE, path, line, handler.col_offset,
                        "`except BaseException` in coroutine swallows "
                        "asyncio.CancelledError; re-raise it",
                        snippet,
                    )
                )
            elif (
                "CancelledError" in names
                and any(n in _BROAD for n in names)
                and not reraises
            ):
                findings.append(
                    Finding(
                        RULE, path, line, handler.col_offset,
                        "handler catches CancelledError together with a "
                        "broad exception class and never re-raises; split "
                        "the CancelledError case out explicitly",
                        snippet,
                    )
                )
            elif (
                names == ["Exception"]
                and not reraises
                and not sibling_catches_cancel
            ):
                findings.append(
                    Finding(
                        RULE, path, line, handler.col_offset,
                        "broad `except Exception` around an await with no "
                        "explicit cancellation handling; add `except "
                        "asyncio.CancelledError: raise` above it",
                        snippet,
                    )
                )
    return findings


def check(tree: ast.Module, src: str, path: str, scoped: bool = True) -> List[Finding]:
    del scoped  # swallowed cancellation is a defect anywhere in the tree
    src_lines = src.splitlines()
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            findings.extend(_check_coroutine(node, src_lines, path))
    return findings

from .metrics import Metrics, Timer

__all__ = ["Metrics", "Timer"]

"""Lightweight latency timers and counters.

Capability parity with the reference's Dropwizard ``MetricRegistry`` +
``JmxReporter`` (``MochiDBClient.java:52-70``: timers ``read-transactions``,
``read-transactions-step1-future-wait``, ``write-transactions``), kept
in-process with percentile snapshots instead of JMX.  The reference has no
server-side metrics (SURVEY.md §5); here replicas carry the same registry.
"""

from __future__ import annotations

import math
import time
from collections import defaultdict, deque
from typing import Dict


class Timer:
    """Records durations (seconds); reports count/mean/percentiles.

    Memory-bounded: keeps a sliding window of the most recent
    ``window`` samples for percentiles (the Dropwizard reservoir analog)
    plus exact lifetime count/sum for mean and throughput.
    """

    __slots__ = ("samples", "total_count", "total_seconds", "window")

    def __init__(self, window: int = 8192) -> None:
        self.window = window
        self.samples: deque = deque(maxlen=window)
        self.total_count = 0
        self.total_seconds = 0.0

    def record(self, seconds: float) -> None:
        self.samples.append(seconds)
        self.total_count += 1
        self.total_seconds += seconds

    def percentile(self, q: float) -> float:
        if not self.samples:
            return math.nan
        data = sorted(self.samples)
        idx = min(len(data) - 1, max(0, int(round(q / 100.0 * (len(data) - 1)))))
        return data[idx]

    @property
    def count(self) -> int:
        return self.total_count

    @property
    def mean(self) -> float:
        return self.total_seconds / self.total_count if self.total_count else math.nan

    def snapshot(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "mean_ms": self.mean * 1e3,
            "p50_ms": self.percentile(50) * 1e3,
            "p95_ms": self.percentile(95) * 1e3,
            "p99_ms": self.percentile(99) * 1e3,
        }


class Histogram:
    """Fixed-bucket histogram (Prometheus-style cumulative ``le`` buckets).

    Built for the batched hot path's occupancy/latency evidence: a mean
    batch size of 1.8 can hide a bimodal 1-frame-idle / 12-frame-burst
    distribution, which is exactly the difference between "the drain never
    batches" and "the drain batches whenever there is load" — the
    distribution, not the mean, is the observable.  O(1) observe (linear
    scan of ~a dozen upper bounds beats bisect at these sizes), exact
    count/sum for the mean.
    """

    __slots__ = ("bounds", "bucket_counts", "total_count", "total_sum")

    # Occupancy-shaped default: 1..multi-thousand in ~x2-x4 steps.
    DEFAULT_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 1024, 4096)

    def __init__(self, bounds=DEFAULT_BOUNDS) -> None:
        self.bounds = tuple(float(b) for b in bounds)
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # +inf tail
        self.total_count = 0
        self.total_sum = 0.0

    def observe(self, value: float) -> None:
        i = 0
        for bound in self.bounds:
            if value <= bound:
                break
            i += 1
        self.bucket_counts[i] += 1
        self.total_count += 1
        self.total_sum += value

    @property
    def mean(self) -> float:
        return self.total_sum / self.total_count if self.total_count else math.nan

    def snapshot(self) -> Dict[str, object]:
        return {
            "count": self.total_count,
            "sum": round(self.total_sum, 6),
            "mean": (round(self.mean, 3) if self.total_count else None),
            "buckets": {
                ("+Inf" if i == len(self.bounds) else f"{self.bounds[i]:g}"): n
                for i, n in enumerate(self.bucket_counts)
                if n
            },
        }


# Latency histograms want sub-ms resolution, not occupancy powers of two.
LATENCY_BOUNDS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
    0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
)

# Fan-out straggler lateness (milliseconds AFTER the quorum was already
# satisfied — not absolute RTT): sub-ms buckets catch loopback jitter,
# the top buckets catch a replica pinned behind a WAN hiccup or a stalled
# event loop (net/transport.fan_out early-quorum drain).
STRAGGLER_BOUNDS_MS = (0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 1000)


class _TimerCtx:
    """Hand-rolled timing context.

    This runs ~40x per transaction across client + replicas; the
    ``@contextmanager`` generator formulation costs a generator frame, two
    ``next()`` dispatches and a ``contextlib`` helper object per use —
    measured at ~6% of cluster CPU in the config-1 profile.  A plain
    two-method object is one attribute store and two perf_counter calls.
    """

    __slots__ = ("_timer", "_start")

    def __init__(self, timer: Timer) -> None:
        self._timer = timer

    def __enter__(self) -> "_TimerCtx":
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._timer.record(time.perf_counter() - self._start)


class Metrics:
    """Registry of named timers and counters."""

    def __init__(self) -> None:
        self.timers: Dict[str, Timer] = defaultdict(Timer)
        self.counters: Dict[str, int] = defaultdict(int)
        self.histograms: Dict[str, Histogram] = {}
        # Last-write-wins instantaneous values (queue depths, link states —
        # things that go *down* as well as up, which counters cannot).
        self.gauges: Dict[str, float] = {}

    def timer(self, name: str) -> _TimerCtx:
        return _TimerCtx(self.timers[name])

    def mark(self, name: str, n: int = 1) -> None:
        self.counters[name] += n

    def set_gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    def histogram(self, name: str, bounds=Histogram.DEFAULT_BOUNDS) -> Histogram:
        """Get-or-create; ``bounds`` only applies on first creation (a
        histogram's buckets are immutable once it has observations)."""
        h = self.histograms.get(name)
        if h is None:
            h = Histogram(bounds)
            self.histograms[name] = h
        return h

    def snapshot(self) -> Dict[str, Dict]:
        return {
            "timers": {name: t.snapshot() for name, t in self.timers.items()},
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "histograms": {
                name: h.snapshot() for name, h in self.histograms.items()
            },
        }

    def to_prometheus(self, labels: Dict[str, str]) -> str:
        """Prometheus text exposition (the Dropwizard/JMX-reporter analog
        for a modern scrape stack).  Metric identity goes into the ``name``
        label so arbitrary dotted timer names stay valid.

        Exposition hygiene (round-15 satellite): every family carries
        ``# HELP`` + ``# TYPE`` headers, and EVERY label value — including
        the ``name`` label, whose dotted metric names embed peer/client
        ids on the suspicion/fan-out counters, i.e. attacker-influenced
        strings — is escaped.  tests/test_metrics_prom.py parser-roundtrips
        the whole body."""

        def esc(v: str) -> str:
            return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")

        base = ",".join(f'{k}="{esc(v)}"' for k, v in sorted(labels.items()))
        lines = [
            "# HELP mochi_timer_count Lifetime sample count per named timer",
            "# TYPE mochi_timer_count counter",
            "# HELP mochi_timer_seconds_mean Lifetime mean duration per named timer",
            "# TYPE mochi_timer_seconds_mean gauge",
            "# HELP mochi_timer_seconds_p50 Sliding-window median duration per named timer",
            "# TYPE mochi_timer_seconds_p50 gauge",
            "# HELP mochi_timer_seconds_p99 Sliding-window p99 duration per named timer",
            "# TYPE mochi_timer_seconds_p99 gauge",
            "# HELP mochi_counter_total Monotonic event counters by name",
            "# TYPE mochi_counter_total counter",
        ]
        for name, t in sorted(self.timers.items()):
            lab = f'name="{esc(name)}"' + (f",{base}" if base else "")
            lines.append(f"mochi_timer_count{{{lab}}} {t.count}")
            if t.count:
                lines.append(f"mochi_timer_seconds_mean{{{lab}}} {t.mean:.9f}")
                lines.append(
                    f"mochi_timer_seconds_p50{{{lab}}} {t.percentile(50):.9f}"
                )
                lines.append(
                    f"mochi_timer_seconds_p99{{{lab}}} {t.percentile(99):.9f}"
                )
        for name, n in sorted(self.counters.items()):
            lab = f'name="{esc(name)}"' + (f",{base}" if base else "")
            lines.append(f"mochi_counter_total{{{lab}}} {n}")
        if self.gauges:
            lines.append("# HELP mochi_gauge Last-write-wins instantaneous values by name")
            lines.append("# TYPE mochi_gauge gauge")
            for name, v in sorted(self.gauges.items()):
                lab = f'name="{esc(name)}"' + (f",{base}" if base else "")
                lines.append(f"mochi_gauge{{{lab}}} {v:g}")
        if self.histograms:
            lines.append("# HELP mochi_histogram Fixed-bucket occupancy/latency histograms by name")
            lines.append("# TYPE mochi_histogram histogram")
            for name, h in sorted(self.histograms.items()):
                lab = f'name="{esc(name)}"' + (f",{base}" if base else "")
                cum = 0
                for i, bucket_n in enumerate(h.bucket_counts):
                    cum += bucket_n
                    le = (
                        "+Inf" if i == len(h.bounds) else f"{h.bounds[i]:g}"
                    )
                    lines.append(
                        f'mochi_histogram_bucket{{{lab},le="{le}"}} {cum}'
                    )
                lines.append(f"mochi_histogram_sum{{{lab}}} {h.total_sum:.9f}")
                lines.append(f"mochi_histogram_count{{{lab}}} {h.total_count}")
        return "\n".join(lines) + "\n"

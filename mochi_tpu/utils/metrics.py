"""Lightweight latency timers and counters.

Capability parity with the reference's Dropwizard ``MetricRegistry`` +
``JmxReporter`` (``MochiDBClient.java:52-70``: timers ``read-transactions``,
``read-transactions-step1-future-wait``, ``write-transactions``), kept
in-process with percentile snapshots instead of JMX.  The reference has no
server-side metrics (SURVEY.md §5); here replicas carry the same registry.
"""

from __future__ import annotations

import math
import time
from collections import defaultdict, deque
from typing import Dict


class Timer:
    """Records durations (seconds); reports count/mean/percentiles.

    Memory-bounded: keeps a sliding window of the most recent
    ``window`` samples for percentiles (the Dropwizard reservoir analog)
    plus exact lifetime count/sum for mean and throughput.
    """

    __slots__ = ("samples", "total_count", "total_seconds", "window")

    def __init__(self, window: int = 8192) -> None:
        self.window = window
        self.samples: deque = deque(maxlen=window)
        self.total_count = 0
        self.total_seconds = 0.0

    def record(self, seconds: float) -> None:
        self.samples.append(seconds)
        self.total_count += 1
        self.total_seconds += seconds

    def percentile(self, q: float) -> float:
        if not self.samples:
            return math.nan
        data = sorted(self.samples)
        idx = min(len(data) - 1, max(0, int(round(q / 100.0 * (len(data) - 1)))))
        return data[idx]

    @property
    def count(self) -> int:
        return self.total_count

    @property
    def mean(self) -> float:
        return self.total_seconds / self.total_count if self.total_count else math.nan

    def snapshot(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "mean_ms": self.mean * 1e3,
            "p50_ms": self.percentile(50) * 1e3,
            "p95_ms": self.percentile(95) * 1e3,
            "p99_ms": self.percentile(99) * 1e3,
        }


class _TimerCtx:
    """Hand-rolled timing context.

    This runs ~40x per transaction across client + replicas; the
    ``@contextmanager`` generator formulation costs a generator frame, two
    ``next()`` dispatches and a ``contextlib`` helper object per use —
    measured at ~6% of cluster CPU in the config-1 profile.  A plain
    two-method object is one attribute store and two perf_counter calls.
    """

    __slots__ = ("_timer", "_start")

    def __init__(self, timer: Timer) -> None:
        self._timer = timer

    def __enter__(self) -> "_TimerCtx":
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._timer.record(time.perf_counter() - self._start)


class Metrics:
    """Registry of named timers and counters."""

    def __init__(self) -> None:
        self.timers: Dict[str, Timer] = defaultdict(Timer)
        self.counters: Dict[str, int] = defaultdict(int)

    def timer(self, name: str) -> _TimerCtx:
        return _TimerCtx(self.timers[name])

    def mark(self, name: str, n: int = 1) -> None:
        self.counters[name] += n

    def snapshot(self) -> Dict[str, Dict]:
        return {
            "timers": {name: t.snapshot() for name, t in self.timers.items()},
            "counters": dict(self.counters),
        }

    def to_prometheus(self, labels: Dict[str, str]) -> str:
        """Prometheus text exposition (the Dropwizard/JMX-reporter analog
        for a modern scrape stack).  Metric identity goes into the ``name``
        label so arbitrary dotted timer names stay valid."""

        def esc(v: str) -> str:
            return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")

        base = ",".join(f'{k}="{esc(v)}"' for k, v in sorted(labels.items()))
        lines = [
            "# TYPE mochi_timer_count counter",
            "# TYPE mochi_timer_seconds_mean gauge",
            "# TYPE mochi_timer_seconds_p50 gauge",
            "# TYPE mochi_timer_seconds_p99 gauge",
            "# TYPE mochi_counter_total counter",
        ]
        for name, t in sorted(self.timers.items()):
            lab = f'name="{esc(name)}"' + (f",{base}" if base else "")
            lines.append(f"mochi_timer_count{{{lab}}} {t.count}")
            if t.count:
                lines.append(f"mochi_timer_seconds_mean{{{lab}}} {t.mean:.9f}")
                lines.append(
                    f"mochi_timer_seconds_p50{{{lab}}} {t.percentile(50):.9f}"
                )
                lines.append(
                    f"mochi_timer_seconds_p99{{{lab}}} {t.percentile(99):.9f}"
                )
        for name, n in sorted(self.counters.items()):
            lab = f'name="{esc(name)}"' + (f",{base}" if base else "")
            lines.append(f"mochi_counter_total{{{lab}}} {n}")
        return "\n".join(lines) + "\n"

"""Coalesced wakeups: one coarse timer services thousands of deadlines.

asyncio gives every ``sleep``/``wait_for`` its own ``TimerHandle`` on the
loop's heap.  At benchmark front-end scale (``benchmarks/config9_overload``:
thousands of concurrent client sessions, each with a request timeout and a
backoff sleep in flight) that is thousands of heap entries and — worse —
thousands of *distinct wakeups*: the loop gets scheduled once per expiring
timer, paying a full poll/dispatch cycle to fire one callback.

:class:`TimerWheel` rounds deadlines up to a coarse quantum (default 20 ms)
and keeps ONE pending loop timer — the earliest non-empty bucket.  A tick
fires every deadline of its bucket in one wakeup.  Cancellation is lazy and
O(1): entries carry a ``cancelled`` flag and are skipped at fire time, so
the hot path (schedule + cancel on completion, the fate of ~every request
timeout) never touches the loop's timer heap at all.

Coarseness is the contract: a wheel deadline fires up to ``quantum_s``
LATE, never early.  That is exactly right for timeouts and backoff jitter
(both already tolerate far larger skew) and exactly wrong for anything
needing sub-quantum precision — don't route benchmark timing through it.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Dict, List, Optional


class _Entry:
    __slots__ = ("callback", "cancelled")

    def __init__(self, callback: Callable[[], None]) -> None:
        self.callback = callback
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class TimerWheel:
    """``wheel.call_at(deadline, cb)`` / ``await wheel.sleep(delay)`` with
    one loop timer total.  Bound to the running loop on first use; a wheel
    must not be shared across loops (``asyncio.run`` per test creates a
    fresh loop — use :func:`wheel_for_loop` for a per-loop instance)."""

    def __init__(self, quantum_s: float = 0.02) -> None:
        self.quantum_s = quantum_s
        self._buckets: Dict[int, List[_Entry]] = {}
        self._handle: Optional[asyncio.TimerHandle] = None
        self._armed_tick: Optional[int] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # observability (admin "overload" surface / benchmark record)
        self.scheduled = 0
        self.fired = 0
        self.lapsed = 0  # entries already cancelled when their tick fired

    def _tick_of(self, deadline: float) -> int:
        # round UP: never fire early (a timeout firing before its deadline
        # would fail a healthy in-budget request)
        q = self.quantum_s
        return -int(-deadline // q)  # ceil(deadline / q) without float drift

    def call_at(self, deadline: float, callback: Callable[[], None]) -> _Entry:
        """Schedule ``callback`` for (at most one quantum after) ``deadline``
        (loop-monotonic seconds).  Returns a handle with ``.cancel()``."""
        loop = asyncio.get_running_loop()
        if self._loop is None:
            self._loop = loop
        entry = _Entry(callback)
        tick = self._tick_of(deadline)
        bucket = self._buckets.get(tick)
        if bucket is None:
            self._buckets[tick] = [entry]
            if self._armed_tick is None or tick < self._armed_tick:
                self._arm(tick)
        else:
            bucket.append(entry)
        self.scheduled += 1
        return entry

    def call_later(self, delay_s: float, callback: Callable[[], None]) -> _Entry:
        return self.call_at(
            asyncio.get_running_loop().time() + max(0.0, delay_s), callback
        )

    async def sleep(self, delay_s: float) -> None:
        """Coalesced ``asyncio.sleep`` (may oversleep by one quantum)."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        entry = self.call_at(
            loop.time() + max(0.0, delay_s),
            lambda: fut.done() or fut.set_result(None),
        )
        try:
            await fut
        finally:
            entry.cancel()

    def _arm(self, tick: int) -> None:
        if self._handle is not None:
            self._handle.cancel()
        self._armed_tick = tick
        assert self._loop is not None
        self._handle = self._loop.call_at(
            tick * self.quantum_s, self._fire, tick
        )

    def _fire(self, tick: int) -> None:
        self._handle = None
        self._armed_tick = None
        now_tick = self._tick_of(self._loop.time()) if self._loop else tick
        # fire every bucket that is due (a long loop stall may owe several)
        due = sorted(t for t in self._buckets if t <= max(tick, now_tick))
        for t in due:
            for entry in self._buckets.pop(t):
                if entry.cancelled:
                    self.lapsed += 1
                    continue
                self.fired += 1
                try:
                    entry.callback()
                except Exception:  # a timeout callback bug must not kill the wheel
                    import logging

                    logging.getLogger(__name__).exception("wheel callback failed")
        if self._buckets:
            self._arm(min(self._buckets))

    def close(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        self._armed_tick = None
        self._buckets.clear()

    def stats(self) -> Dict[str, int]:
        return {
            "pending": sum(len(b) for b in self._buckets.values()),
            "buckets": len(self._buckets),
            "scheduled": self.scheduled,
            "fired": self.fired,
            "lapsed": self.lapsed,
        }


_WHEELS: "Dict[int, TimerWheel]" = {}


def wheel_for_loop(quantum_s: float = 0.02) -> TimerWheel:
    """Per-event-loop shared wheel (keyed by loop id; the wheel's strong
    ``_loop`` reference keeps the id stable for its lifetime).  Wheels of
    CLOSED loops are pruned when the registry grows — never wheels of
    other live loops, whose armed timers and buckets must survive."""
    loop = asyncio.get_running_loop()
    key = id(loop)
    wheel = _WHEELS.get(key)
    if wheel is None or (wheel._loop is not None and wheel._loop is not loop):
        if len(_WHEELS) > 8:
            for k, w in list(_WHEELS.items()):
                if w._loop is not None and w._loop.is_closed():
                    w.close()
                    del _WHEELS[k]
        wheel = TimerWheel(quantum_s)
        _WHEELS[key] = wheel
    return wheel

"""Process-level runtime tuning for mochi server/verifier processes.

The asyncio request path allocates heavily (envelopes, futures, frames);
CPython's default generational GC thresholds (700, 10, 10) make gen-0/1
collections fire every few requests, and the collector walks the whole
young set each time.  Relaxing the thresholds and freezing the post-boot
heap into the permanent generation was measured at +15-20% cluster
throughput on the config-1 bench (5 replicas, 40 clients, single core);
20k/50k/200k gen-0 thresholds all measured within noise of each other,
so the value below is not delicate.

This is deliberately a *server-process* knob, called from process entry
points (``server/__main__.py``, ``verifier.service:main``, the benchmark
harnesses) — never on library import, which would impose our GC policy on
embedding applications.
"""

from __future__ import annotations

import gc
import hashlib
import os
import platform


def host_cache_dir(base: str) -> str:
    """CPU-backend XLA cache subdirectory keyed by this host's CPU features.

    XLA:CPU's persistent cache key does NOT include the CPU feature set its
    AOT code was specialized for; a cache directory populated on a machine
    with (say) AVX-512 feeds SIGILL-prone code to a host without it —
    MULTICHIP_r04.json's tail was full of exactly this machine-feature-
    mismatch warning (VERDICT r4 item 6).  Every CPU-backend cache site
    (driver dryrun, bench.py forced-CPU fallback, vpu_peak --allow-cpu)
    must use this instead of the shared TPU cache dir.
    """
    feat = platform.machine()
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith(("flags", "Features")):
                    feat += " " + " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    tag = hashlib.sha256(feat.encode()).hexdigest()[:12]
    return os.path.join(base, f"cpu-{tag}")


def tune_gc_for_server() -> None:
    """Relax GC for allocation-heavy serving; freeze the boot-time heap.

    Reference-cycle garbage still gets collected — only less often, with
    the (acyclic) steady-state request garbage reclaimed by refcounting as
    usual.  Call after imports/boot so ``gc.freeze`` captures module state.
    """
    gc.collect()
    gc.freeze()
    gc.set_threshold(50000, 50, 50)


def reset_gc_debt() -> None:
    """Collect torn-down cyclic graphs NOW and refreeze the survivors.

    Under the relaxed server thresholds a just-closed in-process cluster's
    object graph (replicas↔stores↔grant books, task callbacks — all
    cyclic) lingers uncollected; a workload that follows in the same
    process then pays repeated young-gen collections that trace the dead
    giant graph — the config-6 "GC debt" artifact that depressed an
    n16-after-n64 run ~45% (BASELINE.md).  Call between workloads that
    must not observe each other's teardown; a full collect + refreeze
    moves whatever legitimately survives out of the traced set entirely.
    """
    gc.collect()
    gc.freeze()

from .transport import RpcServer, RpcClientPool, ConnectionNotReady, fan_out

__all__ = ["RpcServer", "RpcClientPool", "ConnectionNotReady", "fan_out"]

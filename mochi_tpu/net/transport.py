"""Async TCP transport: length-prefixed frames, msg-id-correlated RPC.

Capability parity with the reference's messaging layer
(``server/messaging/``): connection pool per initiator
(``MochiMessaging.java:33-45``), lazy connect with retry
(``MochiClient.java:76-129``), request fan-out
(``Utils.sendMessageToServers``, ``Utils.java:113-123``), server listener with
restart-on-crash (``MochiServer.java:75-110``).  Two deliberate upgrades:

* responses are correlated by ``reply_to`` msg-id instead of the reference's
  FIFO promise queue ("TODO: that assumes that message order is correct",
  ``MochiClientHandler.java:67-75``) — out-of-order replies are fine;
* frames are 4-byte big-endian length + mcode envelope (the reference uses
  protobuf varint framing, ``MochiClientInitializer.java:14-26``).

Implementation: ``asyncio.Protocol`` on both sides (the reference's analog
is Netty's event-loop pipeline, ``MochiServer.java:83-96``) rather than the
stream API — framing is parsed synchronously out of ``data_received`` with
no per-read futures, and writes go straight to the transport buffer with
no per-response ``drain()``.  On this workload's single host core that is
worth ~15% cluster throughput over the stream-reader formulation.

Flow control: when a peer stops reading and the socket's write buffer
fills, ``pause_writing`` pauses the connection's *read* side too — a slow
consumer throttles its own request stream instead of growing our buffers
without bound.

The server runs MAC'd inline-type envelopes (reads, write1s — handlers
that never await external work, see ``INLINE_TYPES``) to completion
synchronously inside ``data_received``: no task, no scheduling, request
to response in one call frame.  ``_run_handler_sync`` enforces the
no-suspension contract loudly rather than silently degrading.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import struct
import time

from typing import Awaitable, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from ..cluster.config import ServerInfo
from ..obs import trace as obs_trace
from ..utils.metrics import LATENCY_BOUNDS_S, STRAGGLER_BOUNDS_MS
from ..protocol import (
    Envelope,
    HelloToServer,
    ReadToServer,
    Write1ToServer,
    decode_envelope,
    encode_envelope,
)

LOG = logging.getLogger(__name__)

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024

# Coalesced-write flush budget (server side).  A response frame first lands
# in its connection's output buffer; the buffer is flushed with ONE
# transport.write per drain unit.  With a non-zero delay budget and the
# server mid-burst (more work known in flight when a flush comes due), the
# flush may be deferred further — up to FLUSH_MAX_BYTES of buffered frames
# or FLUSH_MAX_DELAY_S of added latency, whichever lands first — so
# consecutive drain units merge their responses into one syscall.  The
# delay budget defaults to 0 (deferral OFF): cross-unit merging only pays
# when one CONNECTION carries several in-flight requests, and every
# measured workload here is strictly one-in-flight per connection (round-5
# histogram: 9320/9320 single-frame deliveries), where deferral is pure
# added latency.  Enable it for pipelined clients.  Both knobs env-tunable
# (docs/OPERATIONS.md "Batched hot path").
FLUSH_MAX_BYTES = int(os.environ.get("MOCHI_FLUSH_MAX_BYTES", str(64 * 1024)))
FLUSH_MAX_DELAY_S = float(os.environ.get("MOCHI_FLUSH_MAX_DELAY_MS", "0")) / 1e3

# Histogram bounds for flushed-bytes-per-write (powers of ~4 up to 1 MiB).
_BYTES_BOUNDS = (64, 256, 1024, 4096, 16384, 65536, 262144, 1048576)

# Reconnect policy (ref: MochiClient.checkChannelIsOpened retries 3×100ms,
# MochiClient.java:110-129), env-tunable so WAN-shaped deployments can
# widen the budget, with jittered exponential backoff so a cluster-wide
# blip doesn't thundering-herd every client's reconnect onto one instant.
# MOCHI_CONN_JITTER_SEED pins the jitter stream for reproducible runs
# (netsim/config-7); unset, each process draws its own stream — identical
# backoff schedules across processes would BE the herd.
CONN_RETRIES = int(os.environ.get("MOCHI_CONN_RETRIES", "3"))
CONN_BACKOFF_S = float(os.environ.get("MOCHI_CONN_BACKOFF_MS", "100")) / 1e3
_jitter_seed = os.environ.get("MOCHI_CONN_JITTER_SEED")
_CONN_RNG = random.Random(int(_jitter_seed)) if _jitter_seed else random.Random()


def _backoff_delay_s(attempt: int, base_s: float, rng: Optional[random.Random] = None) -> float:
    """attempt-th reconnect wait: ``base * 2^attempt * uniform(0.5, 1.5)``
    (exponent capped so a long outage never waits unboundedly)."""
    r = _CONN_RNG if rng is None else rng
    return base_s * (1 << min(attempt, 6)) * (0.5 + r.random())


# Request-timeout RTT floor: callers size timeouts for loopback (where a
# round trip is microseconds); under a 13 ms WAN link a tight budget times
# out a perfectly healthy request and the retry doubles the load.  With
# MOCHI_RTT_FLOOR_MS set, every send_and_receive/fan_out budget is raised
# to at least RTT_TIMEOUT_MULT round trips (connect + request + verify
# queueing all ride the same links).  Default 0: behavior unchanged.
RTT_FLOOR_S = float(os.environ.get("MOCHI_RTT_FLOOR_MS", "0")) / 1e3
RTT_TIMEOUT_MULT = float(os.environ.get("MOCHI_RTT_TIMEOUT_MULT", "8"))

# Per-connection send-queue watermarks (server side).  The transport's
# write buffer is the ONLY place response bytes for a slow reader can
# accumulate (the app-level ``_out`` buffer self-flushes at
# flush_max_bytes): past the high watermark asyncio calls pause_writing,
# which our protocol turns into pause_reading — a peer that won't drain
# responses stops being allowed to feed requests, so per-connection memory
# is bounded at ~high + flush_max_bytes regardless of peer behavior.
# Resume fires at the low mark (hysteresis: no pause/resume flapping at
# the boundary).
SENDQ_HIGH = int(os.environ.get("MOCHI_SENDQ_HIGH", str(256 * 1024)))
SENDQ_LOW = int(os.environ.get("MOCHI_SENDQ_LOW", str(64 * 1024)))

# Client-side pending-map bound: correlation futures per connection.  Every
# entry IS an in-flight request (resolved entries are popped), so the bound
# is a back-pressure valve, not an eviction policy — a send past the cap
# fails typed (the caller's retry/backoff path absorbs it) and NOTHING
# in-flight is ever evicted: evicting a live future would orphan its
# response and surface as a spurious timeout (pinned in
# tests/test_overload.py).
PENDING_MAX = int(os.environ.get("MOCHI_PENDING_MAX", "4096"))

# Request-timeout wakeup coalescing (utils/wakeup.TimerWheel): thousands of
# concurrent request timeouts share one coarse loop timer instead of one
# TimerHandle each.  Quantum = max added latency on a TIMEOUT (never on a
# response); 0 disables (per-request asyncio.wait_for, the old path).
TIMEOUT_WHEEL_QUANTUM_S = float(
    os.environ.get("MOCHI_TIMEOUT_WHEEL_MS", "20")
) / 1e3


class PendingLimitExceeded(ConnectionError):
    """Connection's in-flight correlation map is full (MOCHI_PENDING_MAX):
    the caller is outrunning the peer — back off, don't buffer more."""


def apply_rtt_floor(timeout_s: float) -> float:
    """Raise a caller's timeout to the configured multiple of the RTT
    floor (no-op at the default floor of 0).  A non-positive timeout means
    "no waiting" (ADVICE r3) and is never raised."""
    if timeout_s <= 0:
        return timeout_s
    floor = RTT_FLOOR_S * RTT_TIMEOUT_MULT
    return timeout_s if timeout_s >= floor else floor


class ConnectionNotReady(Exception):
    """Peer unreachable (ref: ``ConnectionNotReadyException.java``)."""


Handler = Callable[[Envelope], Awaitable[Optional[Envelope]]]


def _run_handler_sync(coro) -> Optional[Envelope]:
    """Run a handler coroutine that is contractually await-free.

    The MAC'd inline fast path (session auth + in-memory store op) never
    suspends, so one ``send(None)`` reaches ``StopIteration`` and yields the
    return value with zero event-loop involvement.  If a future edit makes
    the path suspend, this raises immediately (and the partially-run
    coroutine is closed) — a loud regression beats a silent hang.
    """
    try:
        coro.send(None)
    except StopIteration as stop:
        return stop.value
    coro.close()
    raise RuntimeError(
        "inline handler suspended; its payload type must not be in INLINE_TYPES"
    )


class _FramedProtocol(asyncio.Protocol):
    """Length-prefixed framing shared by both transport roles.

    History: per-SOCKET response coalescing was measured and rejected in
    round 5 — a frames-per-delivery histogram showed **9320 of 9320**
    deliveries carry exactly ONE complete frame, because every hot edge is
    strictly one-in-flight request-response (a client blocks on each txn
    phase; fan-out targets are distinct sockets), so a per-socket parse
    batch never has a second frame to merge.  The batched hot path
    therefore aggregates ACROSS connections instead: the server enqueues
    every decoded frame of one event-loop scheduling tick — 5 concurrent
    clients' Write2s land in one selector poll — into a per-tick drain
    (``RpcServer._drain``), and responses coalesce per connection in
    ``_RpcServerProtocol`` output buffers with one ``transport.write``
    per drain unit.  That cross-connection axis is what the round-5
    single-socket A/B could never see.
    """

    def __init__(self) -> None:
        self._buf = bytearray()
        self.transport: Optional[asyncio.Transport] = None
        # netsim seams (mochi_tpu.netsim.LinkPolicy or None).  The
        # INITIATOR of a connection owns both directions of its logical
        # link: egress conditions the frames we send (A->B), ingress
        # conditions the frames we receive (B->A) — so server responses
        # are WAN-shaped too, with zero server-side peer labeling.  None
        # (the default everywhere outside a conditioned cluster) keeps the
        # hot path a single attribute test.
        self.egress_link = None
        self.ingress_link = None

    # -- subclass surface
    def frame_received(self, frame: bytes) -> None:  # pragma: no cover
        raise NotImplementedError

    def connection_made(self, transport) -> None:
        self.transport = transport

    def send_frame(self, payload: bytes) -> None:
        assert self.transport is not None
        data = _LEN.pack(len(payload)) + payload
        link = self.egress_link
        if link is None:
            self.transport.write(data)
        else:
            link.send(self._conditioned_write, data)

    def _conditioned_write(self, data: bytes) -> bool:
        """Delayed-egress delivery: the link may fire after this
        connection died — a frame for a closed transport is simply lost
        (exactly what the modeled network would have done with it).
        Returns False for that case so the link counts it ``lost``, not
        ``delivered`` (the evidence records lean on delivered==frames)."""
        t = self.transport
        if t is None or t.is_closing():
            return False
        t.write(data)
        return True

    # -- flow control: a peer that won't read our responses stops being
    # allowed to feed us requests (bounded memory per connection).
    def pause_writing(self) -> None:
        if self.transport is not None:
            try:
                self.transport.pause_reading()
            except RuntimeError:  # already closing
                pass

    def resume_writing(self) -> None:
        if self.transport is not None:
            try:
                self.transport.resume_reading()
            except RuntimeError:
                pass

    def data_received(self, data: bytes) -> None:
        buf = self._buf
        buf += data
        pos = 0
        n = len(buf)
        ingress = self.ingress_link
        while n - pos >= 4:
            (length,) = _LEN.unpack_from(buf, pos)
            if length > MAX_FRAME:
                LOG.warning("oversized frame (%d bytes); closing connection", length)
                if self.transport is not None:
                    self.transport.close()
                return
            end = pos + 4 + length
            if end > n:
                break
            frame = bytes(buf[pos + 4 : end])
            pos = end
            # Ingress conditioning happens at FRAME granularity (parse
            # first, then delay/drop/reorder delivery): the sim sits above
            # a real ordered socket, so dropping raw bytes would corrupt
            # framing rather than model message loss.
            if ingress is None:
                self.frame_received(frame)
            else:
                ingress.send(self.frame_received, frame)
            if self.transport is None or self.transport.is_closing():
                break
        if pos:
            del buf[:pos]


class _RpcServerProtocol(_FramedProtocol):
    """Server-side connection: decoded frames enqueue into the server's
    per-tick drain; responses coalesce in ``_out`` and leave with one
    ``transport.write`` per drain unit (``queue_frame``/``flush_now``)."""

    def __init__(self, server: "RpcServer") -> None:
        super().__init__()
        self.server = server
        self._out = bytearray()
        self._flush_timer: Optional[asyncio.TimerHandle] = None
        # Per-envelope handler tasks owned by THIS connection (legacy
        # posture only): cancelled on connection_lost so a disconnected
        # client's expensive request stops computing for a dead socket.
        # Batch tasks span connections and outlive any one of them — they
        # are server-owned (RpcServer._tasks) by design.
        self._conn_tasks: set = set()
        self._flow_paused = False  # write-buffer high-water reached

    def connection_made(self, transport) -> None:
        super().connection_made(transport)
        # Backpressure watermarks: past `high` buffered response bytes the
        # loop calls pause_writing -> we pause_reading (base class), so a
        # slow reader self-throttles; resume at `low`.
        try:
            transport.set_write_buffer_limits(
                high=self.server.sendq_high, low=self.server.sendq_low
            )
        except (AttributeError, NotImplementedError):
            pass  # exotic transports keep their defaults
        self.server._protocols.add(self)

    # flow-control accounting rides the base class's pause/resume-reading
    # behavior: the server-wide paused count is the admission controller's
    # "peers not draining" load component.
    def pause_writing(self) -> None:
        if not self._flow_paused:
            self._flow_paused = True
            self.server._paused_conns += 1
            metrics = self.server.metrics
            if metrics is not None:
                metrics.mark("transport.sendq-paused")
        super().pause_writing()

    def resume_writing(self) -> None:
        if self._flow_paused:
            self._flow_paused = False
            self.server._paused_conns -= 1
        super().resume_writing()

    def frame_received(self, frame: bytes) -> None:
        try:
            env = decode_envelope(frame)
        except Exception:
            peer = self.transport.get_extra_info("peername") if self.transport else None
            LOG.exception("undecodable frame from %s; closing", peer)
            if self.transport is not None:
                self.transport.close()
            return
        self.server._enqueue(self, env)

    # -- coalesced response writes

    def queue_frame(self, payload: bytes, touched: List["_RpcServerProtocol"]) -> None:
        """Buffer one response frame; the caller flushes every touched
        protocol once at the end of its drain unit."""
        if self.transport is None or self.transport.is_closing():
            return
        if not self._out:
            touched.append(self)
        self._out += _LEN.pack(len(payload))
        self._out += payload
        self.server._sendq_out_bytes += len(payload) + 4
        if len(self._out) >= self.server.flush_max_bytes:
            self.flush_now()  # byte budget exceeded mid-unit: bound memory

    def flush_now(self) -> None:
        if self._flush_timer is not None:
            self._flush_timer.cancel()
            self._flush_timer = None
        if not self._out:
            return
        buf, self._out = self._out, bytearray()
        self.server._sendq_out_bytes -= len(buf)
        if self.transport is None or self.transport.is_closing():
            return
        metrics = self.server.metrics
        if metrics is not None:
            metrics.histogram("transport.flush-bytes", _BYTES_BOUNDS).observe(len(buf))
        self.transport.write(bytes(buf))

    def _arm_flush(self, delay_s: float) -> None:
        if self._flush_timer is None and self._out:
            self._flush_timer = asyncio.get_running_loop().call_later(
                delay_s, self.flush_now
            )

    def connection_lost(self, exc: Optional[Exception]) -> None:
        self.server._protocols.discard(self)
        if self._flow_paused:  # paused count must not leak a dead conn
            self._flow_paused = False
            self.server._paused_conns -= 1
        self.server._sendq_out_bytes -= len(self._out)
        for task in self._conn_tasks:
            task.cancel()
        if self._flush_timer is not None:
            self._flush_timer.cancel()
            self._flush_timer = None
        self._out.clear()
        self.transport = None


InlineBatchHandler = Callable[[Sequence[Envelope]], List[Optional[Envelope]]]
BatchHandler = Callable[[Sequence[Envelope]], Awaitable[List[Optional[Envelope]]]]


class RpcServer:
    """Accepts connections and feeds decoded envelopes to an async handler;
    the handler's response (if any) is written back on the same connection
    (ref: ``MochiServer`` + ``RequestHandlerDispatcher``).

    Batched hot path: every frame decoded during one event-loop scheduling
    tick — across ALL connections — lands in ``_ingress``, and a single
    ``call_soon`` drain processes the whole tick's worth together.  With
    batch handlers installed (the replica), the drain splits the batch into

    * an INLINE subset (MAC'd ``INLINE_TYPES``) handed synchronously to
      ``inline_batch_handler`` — zero tasks, request to response in the
      drain's call frame, store entry points invoked once per batch; and
    * the rest, shipped as ONE task to the async ``batch_handler`` —
      signature checks for the whole batch share one verifier round trip.

    Responses coalesce per connection and leave with one ``transport.write``
    per drain unit (adaptive: immediate when the server is idle, deferred up
    to a byte/deadline budget while more ingress is already queued).
    Without batch handlers (verifier service, bare tests) the drain
    degrades to the per-envelope semantics this class always had, keeping
    the write coalescing.
    """

    # Payload types whose handlers never block on external work (no device
    # batches, no peer RPC): handled synchronously inside the drain tick,
    # saving a Task allocation + schedule per message.  Only taken for
    # MAC'd envelopes — session-MAC auth is synchronous, while signed
    # envelopes may await the batch verifier (suspending there would raise
    # in _run_handler_sync).
    INLINE_TYPES = (ReadToServer, Write1ToServer, HelloToServer)

    def __init__(
        self,
        host: str,
        port: int,
        handler: Handler,
        inline_batch_handler: Optional[InlineBatchHandler] = None,
        batch_handler: Optional[BatchHandler] = None,
        metrics=None,
        flush_max_bytes: int = FLUSH_MAX_BYTES,
        flush_max_delay_s: float = FLUSH_MAX_DELAY_S,
        sendq_high: int = SENDQ_HIGH,
        sendq_low: int = SENDQ_LOW,
    ):
        self.host = host
        self.port = port
        self.handler = handler
        self.inline_batch_handler = inline_batch_handler
        self.batch_handler = batch_handler
        self.metrics = metrics
        self.flush_max_bytes = flush_max_bytes
        self.flush_max_delay_s = flush_max_delay_s
        self.sendq_high = sendq_high
        self.sendq_low = sendq_low
        # single source of the "unix:" scheme logic (code-review r4: the
        # prefix was sliced inline in three methods)
        self._unix_path: Optional[str] = (
            host[len("unix:"):] if host.startswith("unix:") else None
        )
        self._bound_ino: Optional[tuple] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._protocols: set = set()
        self._ingress: List[Tuple[_RpcServerProtocol, Envelope]] = []
        self._drain_scheduled = False
        self._tasks: set = set()
        # -- deterministic load signal (the admission controller's inputs;
        # all event-counted, none wall-clock: a loop stall can only inflate
        # these by the requests actually queued behind it, never by the
        # stall duration itself — the flake mode that kept the old
        # loop-lag shed OFF in every in-process harness).
        self._paused_conns = 0        # connections past the send-queue high-water
        self._sendq_out_bytes = 0     # response bytes buffered pre-flush
        self._inflight_envs = 0       # envelopes inside async batch tasks
        self._batch_ewma = 0.0        # EWMA of frames per drain tick
        self._last_drain_t = 0.0      # idle-gap detector for the EWMA reset

    # ------------------------------------------------------- per-tick drain

    def load_stats(self) -> Dict[str, float]:
        """O(1) snapshot of the transport-side load signal (the inputs to
        ``server.admission.AdmissionController``; also the /status
        "overload" surface)."""
        return {
            "ingress_depth": len(self._ingress),
            "inflight_envs": self._inflight_envs,
            "batch_ewma": round(self._batch_ewma, 2),
            "sendq_out_bytes": self._sendq_out_bytes,
            "paused_conns": self._paused_conns,
            "connections": len(self._protocols),
        }

    def send_queue_bytes(self) -> int:
        """Total buffered response bytes: pre-flush ``_out`` buffers plus
        the transports' own write buffers.  O(connections) — admin-surface
        freshness, not hot-path accounting (load_stats is the O(1) view)."""
        total = self._sendq_out_bytes
        for proto in self._protocols:
            t = proto.transport
            if t is not None:
                try:
                    total += t.get_write_buffer_size()
                except (AttributeError, NotImplementedError):
                    pass
        return total

    def _enqueue(self, proto: _RpcServerProtocol, env: Envelope) -> None:
        if env.trace is not None:
            # Traced (head-sampled) envelope: stamp ingress so the handler
            # can attribute queue/drain wait to the transaction.  Stashed in
            # __dict__ exactly like the payload's _mcode cache; untraced
            # traffic pays one attribute test.
            env.__dict__["_rx_perf"] = time.perf_counter()
        self._ingress.append((proto, env))
        if not self._drain_scheduled:
            self._drain_scheduled = True
            # call_soon lands AFTER every data_received callback of the
            # current selector poll (asyncio runs a len-snapshot of the
            # ready queue), so the drain sees the whole tick's frames.
            asyncio.get_running_loop().call_soon(self._drain)

    def _drain(self) -> None:
        self._drain_scheduled = False
        batch = self._ingress
        if not batch:
            return
        self._ingress = []
        t0 = time.perf_counter()
        # Congestion EWMA: frames-per-tick grows with backlog (arrivals
        # outpacing service stack up in kernel buffers and land together on
        # the next poll), and is bounded by what peers actually sent — the
        # deterministic load signal the shed controller reads.  The EWMA is
        # only folded when frames arrive, so it would otherwise FREEZE at
        # its last value across an idle gap and shed the first writes of
        # the next burst; an idle gap resets it.  Using wall time here is
        # safe in a way the retired lag signal was not: absence of traffic
        # can only decay the signal — a stall still cannot inflate it.
        if t0 - self._last_drain_t > 1.0:
            self._batch_ewma = 0.0
        self._last_drain_t = t0
        self._batch_ewma += 0.2 * (len(batch) - self._batch_ewma)
        metrics = self.metrics
        if metrics is not None:
            metrics.histogram("transport.drain-frames").observe(len(batch))
        touched: List[_RpcServerProtocol] = []
        try:
            if self.inline_batch_handler is not None or self.batch_handler is not None:
                inline: List[Tuple[_RpcServerProtocol, Envelope]] = []
                rest: List[Tuple[_RpcServerProtocol, Envelope]] = []
                take_inline = self.inline_batch_handler is not None
                for pe in batch:
                    env = pe[1]
                    if (
                        take_inline
                        and env.mac is not None
                        and isinstance(env.payload, self.INLINE_TYPES)
                    ):
                        inline.append(pe)
                    else:
                        rest.append(pe)
                if inline:
                    try:
                        responses = self.inline_batch_handler(
                            [env for _, env in inline]
                        )
                    except Exception:
                        LOG.exception(
                            "inline batch handler failed for %d envelopes",
                            len(inline),
                        )
                        responses = []
                    self._queue_responses(inline, responses, touched)
                if rest:
                    if self.batch_handler is not None:
                        task = asyncio.ensure_future(self._run_batch(rest))
                        self._tasks.add(task)
                        task.add_done_callback(self._tasks.discard)
                    else:
                        for proto, env in rest:
                            task = asyncio.ensure_future(
                                self._handle_async(proto, env)
                            )
                            self._track(proto, task)
            else:
                # Legacy posture (no batch handlers): per-envelope dispatch
                # with the original inline fast path, plus coalesced writes.
                for proto, env in batch:
                    if env.mac is not None and isinstance(
                        env.payload, self.INLINE_TYPES
                    ):
                        try:
                            response = _run_handler_sync(self.handler(env))
                        except Exception:
                            LOG.exception(
                                "handler failed for %s", type(env.payload).__name__
                            )
                            continue
                        if response is not None:
                            self._queue_responses(
                                [(proto, env)], [response], touched
                            )
                    else:
                        task = asyncio.ensure_future(self._handle_async(proto, env))
                        self._track(proto, task)
        finally:
            # always flush what was queued — see the invariant note in
            # _run_batch (a unit that dies pre-flush would strand frames)
            self._finish_unit(touched)
        if metrics is not None:
            metrics.histogram(
                "transport.drain-latency", LATENCY_BOUNDS_S
            ).observe(time.perf_counter() - t0)

    def _finish_unit(self, touched: List[_RpcServerProtocol]) -> None:
        """End of one drain unit: flush every touched connection — now when
        idle; deferred (up to the byte/deadline budget) while more work is
        known to be in flight, so back-to-back units share writes.

        "In flight" = undrained ingress exists (``_ingress`` non-empty: an
        async unit completed while new frames piled up) or a drain is
        already scheduled.  The SYNC drain itself always sees an empty
        ingress (its frames were snapshotted at entry and this poll's
        ``data_received`` callbacks ran before it), so MAC'd inline
        responses flush at unit end — the deadline budget engages on
        async-completion units under load, never on idle traffic.
        """
        if not touched:
            return
        defer = self.flush_max_delay_s > 0 and (
            bool(self._ingress) or self._drain_scheduled
        )
        for proto in touched:
            if defer and len(proto._out) < self.flush_max_bytes:
                proto._arm_flush(self.flush_max_delay_s)
            else:
                proto.flush_now()

    def _track(self, proto: _RpcServerProtocol, task) -> None:
        """Register a per-envelope handler task with BOTH owners: the
        server (close() sweep) and its connection (cancelled on
        connection_lost, so work for a dead socket stops)."""
        self._tasks.add(task)
        proto._conn_tasks.add(task)

        def _done(t, proto=proto):
            self._tasks.discard(t)
            proto._conn_tasks.discard(t)

        task.add_done_callback(_done)

    async def _run_batch(self, batch: List[Tuple[_RpcServerProtocol, Envelope]]) -> None:
        # counted from the coroutine's FIRST step, not the spawn site: a
        # task cancelled before it ever runs (connection churn, shutdown)
        # never enters this frame — increment-at-spawn would leak the
        # counter permanently and drift the admission load signal upward
        self._inflight_envs += len(batch)
        try:
            responses = await self.batch_handler([env for _, env in batch])
        except asyncio.CancelledError:
            raise  # server close() cancels us; don't treat it as a handler bug
        except Exception:
            # Per-envelope failures are the batch handler's business (one
            # bad envelope must not poison its batchmates); reaching here is
            # a handler BUG — log and drop, client timeouts recover.
            LOG.exception("batch handler failed for %d envelopes", len(batch))
            return
        finally:
            self._inflight_envs -= len(batch)
        touched: List[_RpcServerProtocol] = []
        try:
            self._queue_responses(batch, responses, touched)
        finally:
            # _finish_unit MUST run for every unit that queued anything:
            # queue_frame only registers a protocol in `touched` while its
            # buffer is empty, so a unit that dies between queueing and
            # flushing would strand those frames forever (no later unit
            # would re-register the connection).
            self._finish_unit(touched)

    @staticmethod
    def _queue_responses(batch, responses, touched) -> None:
        """Encode + buffer each response; one unencodable response (a
        handler bug) is dropped alone rather than aborting the unit."""
        for (proto, _), response in zip(batch, responses):
            if response is None:
                continue
            try:
                frame = encode_envelope(response)
            except Exception:
                LOG.exception(
                    "unencodable response %s", type(response.payload).__name__
                )
                continue
            proto.queue_frame(frame, touched)

    async def _handle_async(self, proto: _RpcServerProtocol, env: Envelope) -> None:
        self._inflight_envs += 1  # first-step counting; see _run_batch
        try:
            response = await self.handler(env)
        except asyncio.CancelledError:
            raise  # close() cancels us; don't treat it as a handler bug
        except Exception:
            # The reference swallows handler exceptions and sends nothing,
            # hanging the client future (RequestHandlerDispatcher.java:63-83).
            # We log and drop too — client timeouts are the recovery path —
            # but the failure taxonomy (RequestFailedFromServer) is preferred.
            LOG.exception("handler failed for %s", type(env.payload).__name__)
            return
        finally:
            self._inflight_envs -= 1
        if response is not None:
            touched: List[_RpcServerProtocol] = []
            try:
                self._queue_responses([(proto, env)], [response], touched)
            finally:
                self._finish_unit(touched)

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        if self._unix_path is not None:
            # Unix-domain socket: same framed protocol, no TCP/IP stack —
            # the kernel loopback send path is the measured cost floor for
            # single-host clusters (BASELINE.md round-4 note).  Only a DEAD
            # leftover socket is unlinked: stealing a live server's path
            # would strand it running-but-unreachable, where TCP fails
            # loudly with EADDRINUSE (code-review r4).
            import socket as _socket

            path = self._unix_path
            if os.path.exists(path):
                # Only a refused connect (or a path that vanished under us)
                # proves the previous owner is dead.  A timeout or EAGAIN can
                # mean a LIVE server with a momentarily full accept backlog —
                # unlinking then would steal its path and strand it
                # running-but-unreachable (ADVICE r4).
                probe = _socket.socket(_socket.AF_UNIX)
                probe.settimeout(0.2)
                stale = False
                try:
                    probe.connect(path)
                except (ConnectionRefusedError, FileNotFoundError):
                    stale = True
                except OSError:
                    pass  # timeout / EAGAIN / anything else: assume live
                finally:
                    probe.close()
                if not stale:
                    raise OSError(f"unix socket {path} is in use by a live server")
                try:
                    os.unlink(path)  # stale socket from a dead process
                except FileNotFoundError:
                    pass
            self._server = await loop.create_unix_server(
                lambda: _RpcServerProtocol(self), path
            )
            try:
                st = os.stat(path)
                self._bound_ino = (st.st_dev, st.st_ino)
            except OSError:
                self._bound_ino = None
        else:
            self._server = await loop.create_server(
                lambda: _RpcServerProtocol(self), self.host, self.port
            )

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        if self._unix_path is not None:
            return self.port  # UDS has no port; identity stays the path
        return self._server.sockets[0].getsockname()[1]

    async def quiesce(self, timeout_s: float = 5.0) -> None:
        """Graceful drain before ``close()``: stop accepting new
        connections, let in-flight handler/batch work finish (bounded by
        ``timeout_s``), then flush every connection's coalesced output
        buffer so responses already computed actually reach their clients.

        This is the SIGTERM path's half of deterministic teardown
        (``server/__main__.py``): ``close()`` alone cancels in-flight
        batches and aborts connections, which is right for a crash-style
        stop but loses the tail of admitted work on a supervisor's TERM.
        Past the deadline, whatever is still running is handed to
        ``close()``'s cancel sweep — drain bounds shutdown time, it does
        not wait forever on a wedged handler.
        """
        if self._server is not None:
            # Stop accepting; existing connections stay up for the drain.
            # asyncio's Server.close() is idempotent, so the later close()
            # call repeating it is harmless.
            self._server.close()
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        # _tasks empties as batches finish; undrained ingress respawns
        # tasks via call_soon, so poll both until quiet or deadline.
        while self._tasks or self._ingress or self._drain_scheduled:
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            tasks = list(self._tasks)
            if tasks:
                await asyncio.wait(tasks, timeout=remaining)
            else:
                await asyncio.sleep(0.005)  # let a scheduled drain run
        for proto in list(self._protocols):
            if proto.transport is not None:
                proto.flush_now()

    async def close(self) -> None:
        # In-flight drain batches die with the server (their connections are
        # about to be aborted anyway); ingress enqueued but never drained is
        # dropped the same way a killed per-connection task used to be.
        for task in list(self._tasks):
            task.cancel()
        self._ingress.clear()
        if self._server is not None:
            self._server.close()
            # Drop live connections first: Server.wait_closed() waits for
            # every connection to finish, and peers hold theirs open.
            # abort(), not close(): a graceful close waits to flush, and a
            # connection whose peer has stopped reading (e.g. another
            # replica's cancelled resync worker mid-state-transfer after a
            # reconfiguration) can keep the flush — and therefore
            # wait_closed() and the whole replica shutdown — pending
            # forever.  Shutdown wants connections DROPPED.
            for proto in list(self._protocols):
                if proto.transport is not None:
                    proto.transport.abort()
            # Belt-and-braces: a connection accepted between the snapshot
            # above and wait_closed() would hang us the same way, so sweep
            # until the server reports fully closed.
            while True:
                try:
                    await asyncio.wait_for(self._server.wait_closed(), timeout=1.0)
                    break
                except asyncio.TimeoutError:
                    for proto in list(self._protocols):
                        if proto.transport is not None:
                            proto.transport.abort()
            self._server = None
            if self._unix_path is not None:
                # a stale socket file accepts nothing but still looks alive
                # to path-probing consumers — ENOENT beats ECONNREFUSED.
                # Unlink ONLY our own inode: a newer server may have
                # (legitimately, after our socket died) bound a fresh
                # socket at this path (code-review r4).
                try:
                    st = os.stat(self._unix_path)
                    if (st.st_dev, st.st_ino) == self._bound_ino:
                        os.unlink(self._unix_path)
                except OSError:
                    pass


class _RpcClientProtocol(_FramedProtocol):
    def __init__(self, conn: "_Connection") -> None:
        super().__init__()
        self.conn = conn

    def frame_received(self, frame: bytes) -> None:
        try:
            env = decode_envelope(frame)
        except Exception:
            LOG.exception("undecodable response from %s; closing", self.conn.info.url)
            if self.transport is not None:
                self.transport.close()
            return
        fut = self.conn.pending.pop(env.reply_to or "", None)
        if fut is not None and not fut.done():
            fut.set_result(env)
        else:
            LOG.warning(
                "uncorrelated response reply_to=%s from %s", env.reply_to, self.conn.info.url
            )

    def connection_lost(self, exc: Optional[Exception]) -> None:
        self.transport = None
        self.conn._on_connection_lost()


class _Connection:
    def __init__(self, info: ServerInfo, links=None, pending_max: int = 0):
        self.info = info
        # (egress, ingress) LinkPolicy pair from NetSim.link_pair, or None:
        # attached to every protocol this connection (re)creates.
        self.links = links
        self.pending: Dict[str, asyncio.Future] = {}
        self.pending_max = pending_max if pending_max > 0 else PENDING_MAX
        self._proto: Optional[_RpcClientProtocol] = None
        self._connect_lock = asyncio.Lock()

    def register_pending(self, msg_id: str, fut: asyncio.Future) -> None:
        """Correlation-map insert behind the in-flight bound.  At the cap,
        already-resolved leftovers are swept (futures a raced caller never
        popped); live in-flight entries are NEVER evicted — past the cap
        the NEW request fails typed instead (the map's entries each back an
        awaiting caller; evicting one manufactures a spurious timeout)."""
        pending = self.pending
        if len(pending) >= self.pending_max:
            done = [mid for mid, f in pending.items() if f.done()]
            for mid in done:
                del pending[mid]
            if len(pending) >= self.pending_max:
                raise PendingLimitExceeded(
                    f"{self.info.url}: {len(pending)} requests in flight "
                    f"(MOCHI_PENDING_MAX={self.pending_max})"
                )
        pending[msg_id] = fut

    @property
    def connected(self) -> bool:
        return (
            self._proto is not None
            and self._proto.transport is not None
            and not self._proto.transport.is_closing()
        )

    async def ensure_connected(
        self, retries: Optional[int] = None, delay_s: Optional[float] = None
    ) -> None:
        # ref: MochiClient.checkChannelIsOpened retries 3×100ms then throws
        # (MochiClient.java:110-129); count/backoff env-tunable
        # (MOCHI_CONN_RETRIES / MOCHI_CONN_BACKOFF_MS) with jittered
        # exponential backoff — see _backoff_delay_s.
        if retries is None:
            retries = CONN_RETRIES
        base_s = CONN_BACKOFF_S if delay_s is None else delay_s
        async with self._connect_lock:
            if self.connected:
                return
            loop = asyncio.get_running_loop()
            last_exc: Optional[Exception] = None
            for attempt in range(retries):
                try:
                    if self.info.is_unix:
                        _, proto = await loop.create_unix_connection(
                            lambda: _RpcClientProtocol(self), self.info.unix_path
                        )
                    else:
                        _, proto = await loop.create_connection(
                            lambda: _RpcClientProtocol(self),
                            self.info.host,
                            self.info.port,
                        )
                    if self.links is not None:
                        proto.egress_link, proto.ingress_link = self.links
                    self._proto = proto
                    return
                except OSError as exc:
                    last_exc = exc
                    if attempt + 1 < retries:  # no dead-time sleep after the
                        # final attempt — the exception is the next step
                        await asyncio.sleep(_backoff_delay_s(attempt, base_s))
            raise ConnectionNotReady(f"cannot reach {self.info.url}") from last_exc

    def _on_connection_lost(self) -> None:
        self._fail_pending(ConnectionNotReady(f"connection to {self.info.url} lost"))

    def _fail_pending(self, exc: Exception) -> None:
        # ref: MochiClientHandler.channelInactive fails all pending promises
        # (MochiClientHandler.java:90-101).
        for fut in self.pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self.pending.clear()
        if self._proto is not None and self._proto.transport is not None:
            self._proto.transport.close()
            self._proto = None

    async def send_and_receive(self, env: Envelope, timeout_s: float) -> Envelope:
        await self.ensure_connected()
        assert self._proto is not None
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self.register_pending(env.msg_id, fut)
        timeout = apply_rtt_floor(timeout_s)
        try:
            self._proto.send_frame(encode_envelope(env))
            if TIMEOUT_WHEEL_QUANTUM_S > 0 and timeout > 0:
                # Coalesced timeout: one coarse wheel tick covers every
                # request expiring in the same quantum (a timeout may fire
                # up to one quantum late; responses are unaffected).
                from ..utils.wakeup import wheel_for_loop

                def _expire() -> None:
                    if not fut.done():
                        fut.set_exception(
                            asyncio.TimeoutError(
                                f"no response from {self.info.url} in {timeout}s"
                            )
                        )

                entry = wheel_for_loop(TIMEOUT_WHEEL_QUANTUM_S).call_at(
                    loop.time() + timeout, _expire
                )
                try:
                    return await fut
                finally:
                    entry.cancel()
            return await asyncio.wait_for(fut, timeout)
        finally:
            self.pending.pop(env.msg_id, None)

    async def close(self) -> None:
        if self._proto is not None and self._proto.transport is not None:
            self._proto.transport.close()
        self._fail_pending(ConnectionNotReady("closed"))
        self._proto = None


class RpcClientPool:
    """One connection per target server, created lazily
    (ref: ``MochiMessaging.java:33-45``).

    ``netsim``/``local_label``: when this pool belongs to a conditioned
    cluster (mochi_tpu.netsim), each new connection gets the (egress,
    ingress) policy pair for the directed links ``local_label ->
    info.server_id`` and back — WAN shaping, loss and partitions then
    apply to every request this pool sends and every response it awaits.
    """

    def __init__(
        self,
        default_timeout_s: float = 10.0,
        netsim=None,
        local_label: Optional[str] = None,
    ):
        self.default_timeout_s = default_timeout_s
        self.netsim = netsim
        self.local_label = local_label
        self._connections: Dict[str, _Connection] = {}
        # Background straggler drains spawned by early-quorum fan_outs:
        # pool-owned so close() can cancel them (and so the tasks hold a
        # strong reference — a GC'd drain would silently stop feeding the
        # straggler metrics and leak pending-future entries).
        self._straggler_tasks: set = set()

    def _track_straggler(self, task) -> None:
        self._straggler_tasks.add(task)
        task.add_done_callback(self._straggler_tasks.discard)

    def _conn(self, info: ServerInfo) -> _Connection:
        conn = self._connections.get(info.url)
        if conn is None:
            links = None
            if self.netsim is not None:
                links = self.netsim.link_pair(
                    self.local_label or "client", info.server_id
                )
            conn = _Connection(info, links=links)
            self._connections[info.url] = conn
        return conn

    async def send_and_receive(
        self, info: ServerInfo, env: Envelope, timeout_s: Optional[float] = None
    ) -> Envelope:
        return await self._conn(info).send_and_receive(
            env, self.default_timeout_s if timeout_s is None else timeout_s
        )

    async def close(self) -> None:
        # Drain to quiescence: a fan-out running concurrently with close()
        # can register a NEW straggler while the gather is suspended, and
        # a connection can appear in _connections mid-close the same way.
        # The old blanket clear() orphaned such a straggler un-cancelled
        # (and the live-dict iteration could raise "changed size during
        # iteration"); looping until empty closes late arrivals too.
        # Outer loop over BOTH tables: a straggler spawned while a
        # conn.close() below is suspended must still get a cancellation
        # round, so re-check stragglers after the connection phase too.
        while self._straggler_tasks or self._connections:
            while self._straggler_tasks:
                pending = list(self._straggler_tasks)
                for task in pending:
                    task.cancel()
                await asyncio.gather(*pending, return_exceptions=True)
                self._straggler_tasks.difference_update(pending)
            while self._connections:
                _, conn = self._connections.popitem()
                await conn.close()


_MSG_ID_POOL = bytearray()
_MSG_ID_POS = 0


def new_msg_id() -> str:
    # Pooled entropy: one os.urandom SYSCALL per 256 ids instead of per id
    # (hot path: one id per request per target; the per-call getrandom(2)
    # was ~30 us on this host — 4% of config-1 wall).  Correlation ids need
    # uniqueness, not forward secrecy, so buffering entropy is sound; the
    # pool is process-local (never survives a fork boundary: children
    # inherit COW copies only if forked mid-run, and every server/bench
    # entry point spawns, not forks, its workers).
    global _MSG_ID_POOL, _MSG_ID_POS
    if _MSG_ID_POS + 16 > len(_MSG_ID_POOL):
        _MSG_ID_POOL = bytearray(os.urandom(4096))
        _MSG_ID_POS = 0
    out = bytes(_MSG_ID_POOL[_MSG_ID_POS : _MSG_ID_POS + 16])
    _MSG_ID_POS += 16
    return out.hex()


async def _drain_stragglers(
    fut_info: Dict[asyncio.Future, Tuple[str, Optional[str], Optional[_Connection]]],
    deadline: float,
    metrics,
    t_quorum: float,
) -> None:
    """Background half of an early-quorum fan-out: keep awaiting the
    targets the caller no longer needs, so late responses are observed —
    never silently dropped.  Each arrival feeds the per-replica
    ``fanout-straggler-ms.<sid>`` histogram (lateness past the quorum
    point) and a ``fanout.late-response.<sid>`` counter; a target that
    never answers inside the original budget is cancelled and counted as
    ``fanout.straggler-timeout.<sid>``.  Keeping the futures registered in
    ``conn.pending`` until they resolve is also connection health: the
    eventual response frame correlates normally instead of tripping the
    uncorrelated-response warning path."""
    loop = asyncio.get_running_loop()
    pending = set(fut_info)
    cancelled = False
    try:
        while pending:
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            done, pending = await asyncio.wait(
                pending, timeout=remaining, return_when=asyncio.FIRST_COMPLETED
            )
            if not done:
                break
            late_ms = (loop.time() - t_quorum) * 1e3
            for fut in done:
                sid, msg_id, conn = fut_info[fut]
                if conn is not None:
                    conn.pending.pop(msg_id, None)
                if metrics is None:
                    continue
                exc = None if fut.cancelled() else fut.exception()
                if fut.cancelled() or exc is not None:
                    # connection died / leg failed — the failure already
                    # counts toward reconnect health; tag it here too so
                    # "slowest replica" vs "dead replica" is answerable
                    metrics.mark(f"fanout.straggler-error.{sid}")
                else:
                    metrics.mark(f"fanout.late-response.{sid}")
                    metrics.histogram(
                        f"fanout-straggler-ms.{sid}", STRAGGLER_BOUNDS_MS
                    ).observe(late_ms)
    except asyncio.CancelledError:
        cancelled = True  # pool.close() mid-drain, NOT a replica fault
        raise
    finally:
        for fut in pending:
            sid, msg_id, conn = fut_info[fut]
            if conn is not None:
                conn.pending.pop(msg_id, None)
            # a future that completed while the interrupted wait was
            # resuming is an answer, not a timeout
            answered = fut.done() and not fut.cancelled()
            answered_ok = answered and fut.exception() is None
            fut.cancel()
            if metrics is None:
                continue
            if answered_ok:
                metrics.mark(f"fanout.late-response.{sid}")
            elif cancelled and not answered:
                # clean shutdown cancelled the drain: in-flight targets
                # must NOT accrue "never answered in budget" evidence —
                # operators read straggler-timeout as replica health
                metrics.mark(f"fanout.straggler-drain-cancelled.{sid}")
            elif answered:
                metrics.mark(f"fanout.straggler-error.{sid}")
            else:
                metrics.mark(f"fanout.straggler-timeout.{sid}")


async def fan_out(
    pool: RpcClientPool,
    targets: Iterable[Tuple[str, ServerInfo]],
    make_envelope: Callable[..., Envelope],
    timeout_s: Optional[float] = None,
    metrics=None,
    quorum_done: Optional[Callable[[str, object], bool]] = None,
    tracer=None,
) -> Dict[str, "Envelope | Exception"]:
    """Send one envelope per target concurrently; gather results or exceptions
    per server id (ref: ``Utils.sendMessageToServers`` + ``busyWaitForFutures``,
    ``Utils.java:65-123`` — awaiting real futures instead of 5 ms poll loops).

    ``make_envelope`` is called as ``(msg_id, server_id)`` so callers can
    authenticate per target (session MACs).  ``metrics`` (a
    :class:`~mochi_tpu.utils.metrics.Metrics`) times the synchronous
    build+serialize+send loop as ``fanout-serialize-send`` — the "fan-out
    serialization" slice of the commit breakdown, distinct from the
    response wait that follows.

    ``quorum_done`` makes the fan-out QUORUM-bound instead of straggler-
    bound: it is called once per arrival as ``(server_id, envelope_or_
    exception)`` and when it returns True the fan-out returns immediately
    with everything received so far.  The still-outstanding targets are
    handed to a pool-owned background drain (:func:`_drain_stragglers`)
    that records their lateness — late responses feed metrics and resolve
    their pending-map entries; they are never silently dropped.  The
    predicate sees raw transport results (the caller authenticates inside
    it), and its verdict is advisory for LIVENESS only: callers re-tally
    the returned dict, so a buggy predicate can slow a caller down or
    return extra responses, never forge agreement.
    """
    targets = list(targets)
    # `is None` (not falsy-or): an explicit timeout_s=0 means "no waiting",
    # not "use the default" (ADVICE r3).  The RTT floor then raises
    # loopback-sized budgets to >= RTT_TIMEOUT_MULT round trips under
    # conditioned/WAN links (no-op at the default floor of 0).
    timeout = pool.default_timeout_s if timeout_s is None else timeout_s
    timeout = apply_rtt_floor(timeout)
    out: Dict[str, Envelope | Exception] = {}

    # Steady state: every target connection is open, so each request is a
    # synchronous frame write plus one bare future — the whole fan-out then
    # parks on asyncio.wait (no per-target task).  The per-target
    # task/wait_for formulation costs ~10 scheduled callbacks per
    # transaction at cluster rates.
    loop = asyncio.get_running_loop()
    # future/task -> (sid, msg_id or None, connection or None): msg_id+conn
    # only for fast-path bare futures, whose pending-map entry we own.
    fut_info: Dict[asyncio.Future, Tuple[str, Optional[str], Optional[_Connection]]] = {}
    slow: List[Tuple[str, ServerInfo]] = []
    # Per-txn wire accounting (round 15): only for a head-SAMPLED trace
    # context — the lazy-label discipline: no span bookkeeping, no arg
    # building, for the ~95% of traffic the sampler skips.
    ctx = None
    if tracer is not None and tracer.enabled:
        c = obs_trace.current_ctx()
        if c is not None and c.sampled:
            ctx = c
    wire_bytes = 0
    fan_wall0 = time.time() if ctx is not None else 0.0
    fan_t0 = time.perf_counter() if ctx is not None else 0.0
    send_t0 = time.perf_counter() if metrics is not None else 0.0
    for sid, info in targets:
        conn = pool._conn(info)
        if not conn.connected:
            slow.append((sid, info))
            continue
        env = make_envelope(new_msg_id(), sid)
        fut = loop.create_future()
        try:
            # the same in-flight bound as send_and_receive: a full map
            # fails THIS leg typed (the caller's quorum math sees one more
            # error) instead of growing without bound
            conn.register_pending(env.msg_id, fut)
            assert conn._proto is not None
            frame = encode_envelope(env)
            if ctx is not None:
                wire_bytes += len(frame) + 4  # + length prefix
            conn._proto.send_frame(frame)
        except Exception as exc:
            conn.pending.pop(env.msg_id, None)
            out[sid] = exc
            continue
        fut_info[fut] = (sid, env.msg_id, conn)
    if metrics is not None:
        metrics.timers["fanout-serialize-send"].record(
            time.perf_counter() - send_t0
        )

    async def one(sid: str, info: ServerInfo) -> Envelope:
        # wait_for bounds the WHOLE leg including the TCP connect inside
        # ensure_connected — a black-holed host (dropped SYNs) otherwise
        # holds create_connection for the kernel's ~2 min connect timeout,
        # far past this fan-out's budget.
        env = make_envelope(new_msg_id(), sid)
        if ctx is not None:
            # charge the slow-path leg's frame too (encode here is cheap:
            # the envelope's _six_bytes/payload caches make it pure
            # concatenation, and send_and_receive reuses the same caches)
            nonlocal wire_bytes
            wire_bytes += len(encode_envelope(env)) + 4
        return await asyncio.wait_for(
            pool.send_and_receive(info, env, timeout),
            timeout=timeout,
        )

    # Slow path (unconnected targets: dial + handshake + request, each leg
    # bounded by `timeout` inside send_and_receive) runs CONCURRENTLY with
    # the fast-path wait — serially, one down replica would stretch the
    # whole fan-out to ~2x the budget (ADVICE r3).
    for sid, info in slow:
        fut_info[asyncio.ensure_future(one(sid, info))] = (sid, None, None)

    # Results already in `out` (send failures) can satisfy a predicate too
    # — same exception posture as the arrival loop: a predicate bug must
    # never break the fan-out itself.
    early = False
    if quorum_done is not None:
        for sid, res in out.items():
            try:
                if quorum_done(sid, res):
                    early = True
                    break
            except Exception:
                LOG.exception("fan-out predicate failed for %s", sid)

    deadline = loop.time() + timeout
    pending = set(fut_info)
    handed_off = False
    try:
        while pending and not early:
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            done, pending = await asyncio.wait(
                pending, timeout=remaining, return_when=asyncio.FIRST_COMPLETED
            )
            if not done:
                break
            for fut in done:
                sid, msg_id, conn = fut_info[fut]
                if conn is not None:
                    conn.pending.pop(msg_id, None)
                if fut.cancelled():  # e.g. a concurrent connection close
                    res: object = ConnectionNotReady(f"request to {sid} cancelled")
                else:
                    exc = fut.exception()
                    res = exc if exc is not None else fut.result()
                out[sid] = res
                # Verify-as-arrived: the predicate runs per arrival, so
                # authentication overlaps the remaining targets' network
                # wait instead of queueing behind the full fan-out.
                if not early and quorum_done is not None:
                    try:
                        if quorum_done(sid, res):
                            early = True
                    except Exception:
                        LOG.exception("fan-out predicate failed for %s", sid)
        if pending and early:
            # Quorum satisfied: hand the stragglers to the background
            # drain and return what we have.
            if metrics is not None:
                metrics.mark("fanout.early-return")
            task = loop.create_task(
                _drain_stragglers(
                    {f: fut_info[f] for f in pending},
                    deadline,
                    metrics,
                    loop.time(),
                )
            )
            pool._track_straggler(task)
            handed_off = True
        else:
            for fut in pending:
                sid, msg_id, conn = fut_info[fut]
                if conn is not None:
                    conn.pending.pop(msg_id, None)
                fut.cancel()
                out[sid] = TimeoutError(f"no response from {sid} in {timeout}s")
        pending = set()
        if ctx is not None:
            # One span per fan-out = one protocol round trip: the cost
            # card's RTT counter and wire-byte ledger (obs/trace.py).
            tracer.record(
                "client.fanout",
                ctx,
                fan_wall0,
                time.perf_counter() - fan_t0,
                args={
                    "targets": len(targets),
                    "wire_bytes": wire_bytes,
                    "rtt": 1,
                    "early": early,
                },
            )
        return out
    finally:
        # Structured concurrency: if the fan-out itself is cancelled
        # (caller deadline, shutdown), outstanding sends must not keep
        # dialing replicas in the background — unless they were already
        # handed to the pool-owned straggler drain, which owns them now.
        if pending and not handed_off:
            for fut in pending:
                sid, msg_id, conn = fut_info[fut]
                if conn is not None:
                    conn.pending.pop(msg_id, None)
                fut.cancel()

"""Async TCP transport: length-prefixed frames, msg-id-correlated RPC.

Capability parity with the reference's messaging layer
(``server/messaging/``): connection pool per initiator
(``MochiMessaging.java:33-45``), lazy connect with retry
(``MochiClient.java:76-129``), request fan-out
(``Utils.sendMessageToServers``, ``Utils.java:113-123``), server listener with
restart-on-crash (``MochiServer.java:75-110``).  Two deliberate upgrades:

* responses are correlated by ``reply_to`` msg-id instead of the reference's
  FIFO promise queue ("TODO: that assumes that message order is correct",
  ``MochiClientHandler.java:67-75``) — out-of-order replies are fine;
* frames are 4-byte big-endian length + mcode envelope (the reference uses
  protobuf varint framing, ``MochiClientInitializer.java:14-26``).
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct

from typing import Awaitable, Callable, Dict, Iterable, List, Optional, Tuple

from ..cluster.config import ServerInfo
from ..protocol import (
    Envelope,
    HelloToServer,
    ReadToServer,
    Write1ToServer,
    decode_envelope,
    encode_envelope,
)

LOG = logging.getLogger(__name__)

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024


class ConnectionNotReady(Exception):
    """Peer unreachable (ref: ``ConnectionNotReadyException.java``)."""


async def _read_frame(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    return await reader.readexactly(length)


def _write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(_LEN.pack(len(payload)) + payload)


Handler = Callable[[Envelope], Awaitable[Optional[Envelope]]]


class RpcServer:
    """Accepts connections and feeds decoded envelopes to an async handler;
    the handler's response (if any) is written back on the same connection
    (ref: ``MochiServer`` + ``RequestHandlerDispatcher``)."""

    def __init__(self, host: str, port: int, handler: Handler):
        self.host = host
        self.port = port
        self.handler = handler
        self._server: Optional[asyncio.base_events.Server] = None
        self._conn_writers: set = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._serve_conn, self.host, self.port)

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    # Payload types whose handlers never block on external work (no device
    # batches, no peer RPC): handled INLINE on the connection's read loop,
    # saving a Task allocation + schedule per message.  Only taken for
    # MAC'd envelopes — session-MAC auth is synchronous, while signed
    # envelopes may await the batch verifier (blocking the read loop there
    # would serialize the very requests the batcher wants to coalesce).
    # Everything else (Write2's certificate batch, sync pulls) gets its own
    # task so a slow request can't head-of-line-block the channel.
    INLINE_TYPES = (ReadToServer, Write1ToServer, HelloToServer)

    async def _serve_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        write_lock = asyncio.Lock()
        tasks: set = set()
        self._conn_writers.add(writer)
        try:
            while True:
                frame = await _read_frame(reader)
                try:
                    env = decode_envelope(frame)
                except Exception:
                    LOG.exception("undecodable frame from %s; closing", peer)
                    break
                if env.mac is not None and isinstance(env.payload, self.INLINE_TYPES):
                    await self._handle_one(env, writer, write_lock)
                    continue
                # Handle concurrently so one slow request (e.g. awaiting a
                # verification batch) doesn't head-of-line-block the channel.
                task = asyncio.ensure_future(self._handle_one(env, writer, write_lock))
                tasks.add(task)
                task.add_done_callback(tasks.discard)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            self._conn_writers.discard(writer)
            for task in tasks:
                task.cancel()
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    async def _handle_one(
        self, env: Envelope, writer: asyncio.StreamWriter, write_lock: asyncio.Lock
    ) -> None:
        try:
            response = await self.handler(env)
        except Exception:
            # The reference swallows handler exceptions and sends nothing,
            # hanging the client future (RequestHandlerDispatcher.java:63-83).
            # We log and drop too — client timeouts are the recovery path —
            # but the failure taxonomy (RequestFailedFromServer) is preferred.
            LOG.exception("handler failed for %s", type(env.payload).__name__)
            return
        if response is not None:
            data = encode_envelope(response)
            async with write_lock:
                _write_frame(writer, data)
                await writer.drain()

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            # Drop live connections first: since 3.12, Server.wait_closed()
            # waits for every connection handler to finish, and ours loop
            # until the peer hangs up.
            for writer in list(self._conn_writers):
                writer.close()
            await self._server.wait_closed()
            self._server = None


class _Connection:
    def __init__(self, info: ServerInfo):
        self.info = info
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.pending: Dict[str, asyncio.Future] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._write_lock = asyncio.Lock()
        self._connect_lock = asyncio.Lock()

    @property
    def connected(self) -> bool:
        return self.writer is not None and not self.writer.is_closing()

    async def ensure_connected(self, retries: int = 3, delay_s: float = 0.1) -> None:
        # ref: MochiClient.checkChannelIsOpened retries 3×100ms then throws
        # (MochiClient.java:110-129).
        async with self._connect_lock:
            if self.connected:
                return
            last_exc: Optional[Exception] = None
            for _ in range(retries):
                try:
                    self.reader, self.writer = await asyncio.open_connection(
                        self.info.host, self.info.port
                    )
                    self._reader_task = asyncio.ensure_future(self._read_loop())
                    return
                except OSError as exc:
                    last_exc = exc
                    await asyncio.sleep(delay_s)
            raise ConnectionNotReady(f"cannot reach {self.info.url}") from last_exc

    async def _read_loop(self) -> None:
        assert self.reader is not None
        try:
            while True:
                frame = await _read_frame(self.reader)
                env = decode_envelope(frame)
                fut = self.pending.pop(env.reply_to or "", None)
                if fut is not None and not fut.done():
                    fut.set_result(env)
                else:
                    LOG.warning("uncorrelated response reply_to=%s from %s", env.reply_to, self.info.url)
        except (asyncio.IncompleteReadError, ConnectionResetError, asyncio.CancelledError):
            pass
        except Exception:
            LOG.exception("reader loop error for %s", self.info.url)
        finally:
            self._fail_pending(ConnectionNotReady(f"connection to {self.info.url} lost"))

    def _fail_pending(self, exc: Exception) -> None:
        # ref: MochiClientHandler.channelInactive fails all pending promises
        # (MochiClientHandler.java:90-101).
        for fut in self.pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self.pending.clear()
        if self.writer is not None:
            self.writer.close()
            self.writer = None

    async def send_and_receive(self, env: Envelope, timeout_s: float) -> Envelope:
        await self.ensure_connected()
        assert self.writer is not None
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self.pending[env.msg_id] = fut
        try:
            async with self._write_lock:
                _write_frame(self.writer, encode_envelope(env))
                await self.writer.drain()
            return await asyncio.wait_for(fut, timeout_s)
        finally:
            self.pending.pop(env.msg_id, None)

    async def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except asyncio.CancelledError:
                pass
        self._fail_pending(ConnectionNotReady("closed"))


class RpcClientPool:
    """One connection per target server, created lazily
    (ref: ``MochiMessaging.java:33-45``)."""

    def __init__(self, default_timeout_s: float = 10.0):
        self.default_timeout_s = default_timeout_s
        self._connections: Dict[str, _Connection] = {}

    def _conn(self, info: ServerInfo) -> _Connection:
        conn = self._connections.get(info.url)
        if conn is None:
            conn = _Connection(info)
            self._connections[info.url] = conn
        return conn

    async def send_and_receive(
        self, info: ServerInfo, env: Envelope, timeout_s: Optional[float] = None
    ) -> Envelope:
        return await self._conn(info).send_and_receive(
            env, timeout_s or self.default_timeout_s
        )

    async def close(self) -> None:
        for conn in self._connections.values():
            await conn.close()
        self._connections.clear()


def new_msg_id() -> str:
    # os.urandom directly: same entropy as uuid4().hex without UUID-object
    # construction (hot path: one id per request per target)
    return os.urandom(16).hex()


async def fan_out(
    pool: RpcClientPool,
    targets: Iterable[Tuple[str, ServerInfo]],
    make_envelope: Callable[..., Envelope],
    timeout_s: Optional[float] = None,
) -> Dict[str, "Envelope | Exception"]:
    """Send one envelope per target concurrently; gather results or exceptions
    per server id (ref: ``Utils.sendMessageToServers`` + ``busyWaitForFutures``,
    ``Utils.java:65-123`` — awaiting real futures instead of 5 ms poll loops).

    ``make_envelope`` is called as ``(msg_id, server_id)`` so callers can
    authenticate per target (session MACs).
    """
    targets = list(targets)

    async def one(sid: str, info: ServerInfo) -> Envelope:
        return await pool.send_and_receive(info, make_envelope(new_msg_id(), sid), timeout_s)

    results = await asyncio.gather(
        *(one(sid, info) for sid, info in targets), return_exceptions=True
    )
    out: Dict[str, Envelope | Exception] = {}
    for (sid, _), res in zip(targets, results):
        out[sid] = res
    return out

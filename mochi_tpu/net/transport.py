"""Async TCP transport: length-prefixed frames, msg-id-correlated RPC.

Capability parity with the reference's messaging layer
(``server/messaging/``): connection pool per initiator
(``MochiMessaging.java:33-45``), lazy connect with retry
(``MochiClient.java:76-129``), request fan-out
(``Utils.sendMessageToServers``, ``Utils.java:113-123``), server listener with
restart-on-crash (``MochiServer.java:75-110``).  Two deliberate upgrades:

* responses are correlated by ``reply_to`` msg-id instead of the reference's
  FIFO promise queue ("TODO: that assumes that message order is correct",
  ``MochiClientHandler.java:67-75``) — out-of-order replies are fine;
* frames are 4-byte big-endian length + mcode envelope (the reference uses
  protobuf varint framing, ``MochiClientInitializer.java:14-26``).

Implementation: ``asyncio.Protocol`` on both sides (the reference's analog
is Netty's event-loop pipeline, ``MochiServer.java:83-96``) rather than the
stream API — framing is parsed synchronously out of ``data_received`` with
no per-read futures, and writes go straight to the transport buffer with
no per-response ``drain()``.  On this workload's single host core that is
worth ~15% cluster throughput over the stream-reader formulation.

Flow control: when a peer stops reading and the socket's write buffer
fills, ``pause_writing`` pauses the connection's *read* side too — a slow
consumer throttles its own request stream instead of growing our buffers
without bound.

The server runs MAC'd inline-type envelopes (reads, write1s — handlers
that never await external work, see ``INLINE_TYPES``) to completion
synchronously inside ``data_received``: no task, no scheduling, request
to response in one call frame.  ``_run_handler_sync`` enforces the
no-suspension contract loudly rather than silently degrading.
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct

from typing import Awaitable, Callable, Dict, Iterable, List, Optional, Tuple

from ..cluster.config import ServerInfo
from ..protocol import (
    Envelope,
    HelloToServer,
    ReadToServer,
    Write1ToServer,
    decode_envelope,
    encode_envelope,
)

LOG = logging.getLogger(__name__)

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024


class ConnectionNotReady(Exception):
    """Peer unreachable (ref: ``ConnectionNotReadyException.java``)."""


Handler = Callable[[Envelope], Awaitable[Optional[Envelope]]]


def _run_handler_sync(coro) -> Optional[Envelope]:
    """Run a handler coroutine that is contractually await-free.

    The MAC'd inline fast path (session auth + in-memory store op) never
    suspends, so one ``send(None)`` reaches ``StopIteration`` and yields the
    return value with zero event-loop involvement.  If a future edit makes
    the path suspend, this raises immediately (and the partially-run
    coroutine is closed) — a loud regression beats a silent hang.
    """
    try:
        coro.send(None)
    except StopIteration as stop:
        return stop.value
    coro.close()
    raise RuntimeError(
        "inline handler suspended; its payload type must not be in INLINE_TYPES"
    )


class _FramedProtocol(asyncio.Protocol):
    """Length-prefixed framing shared by both transport roles.

    Measured and rejected (round 5): coalescing the responses of one
    ``data_received`` parse batch into a single ``transport.write`` — the
    envelope-coalescing candidate against the loopback-syscall wall
    (BASELINE.md).  A/B on config-1 at 5 and 20 clients: within noise both
    ways, and a frames-per-delivery histogram showed **9320 of 9320**
    deliveries carry exactly ONE complete frame — every hot edge here is
    strictly one-in-flight request-response (a client blocks on each txn
    phase; fan-out targets are distinct sockets), so a per-socket batch
    never has a second frame to merge.  The syscall wall is irreducible
    without multi-request pipelining on the client edge, which the 1-RT
    read / 2-RT write design deliberately avoids.
    """

    def __init__(self) -> None:
        self._buf = bytearray()
        self.transport: Optional[asyncio.Transport] = None

    # -- subclass surface
    def frame_received(self, frame: bytes) -> None:  # pragma: no cover
        raise NotImplementedError

    def connection_made(self, transport) -> None:
        self.transport = transport

    def send_frame(self, payload: bytes) -> None:
        assert self.transport is not None
        self.transport.write(_LEN.pack(len(payload)) + payload)

    # -- flow control: a peer that won't read our responses stops being
    # allowed to feed us requests (bounded memory per connection).
    def pause_writing(self) -> None:
        if self.transport is not None:
            try:
                self.transport.pause_reading()
            except RuntimeError:  # already closing
                pass

    def resume_writing(self) -> None:
        if self.transport is not None:
            try:
                self.transport.resume_reading()
            except RuntimeError:
                pass

    def data_received(self, data: bytes) -> None:
        buf = self._buf
        buf += data
        pos = 0
        n = len(buf)
        while n - pos >= 4:
            (length,) = _LEN.unpack_from(buf, pos)
            if length > MAX_FRAME:
                LOG.warning("oversized frame (%d bytes); closing connection", length)
                if self.transport is not None:
                    self.transport.close()
                return
            end = pos + 4 + length
            if end > n:
                break
            frame = bytes(buf[pos + 4 : end])
            pos = end
            self.frame_received(frame)
            if self.transport is None or self.transport.is_closing():
                break
        if pos:
            del buf[:pos]


class _RpcServerProtocol(_FramedProtocol):
    def __init__(self, server: "RpcServer") -> None:
        super().__init__()
        self.server = server
        self._tasks: set = set()

    def connection_made(self, transport) -> None:
        super().connection_made(transport)
        self.server._protocols.add(self)

    def frame_received(self, frame: bytes) -> None:
        try:
            env = decode_envelope(frame)
        except Exception:
            peer = self.transport.get_extra_info("peername") if self.transport else None
            LOG.exception("undecodable frame from %s; closing", peer)
            if self.transport is not None:
                self.transport.close()
            return
        if env.mac is not None and isinstance(env.payload, self.server.INLINE_TYPES):
            # Synchronous fast path: request to response in this call frame.
            try:
                response = _run_handler_sync(self.server.handler(env))
            except Exception:
                LOG.exception(
                    "handler failed for %s", type(env.payload).__name__
                )
                return
            if response is not None and self.transport is not None:
                self.send_frame(encode_envelope(response))
            return
        # Everything else (signed envelopes awaiting the verify batcher,
        # Write2 certificate checks, sync pulls) gets its own task so a slow
        # request can't head-of-line-block the channel.
        task = asyncio.ensure_future(self._handle_async(env))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _handle_async(self, env: Envelope) -> None:
        try:
            response = await self.server.handler(env)
        except asyncio.CancelledError:
            raise  # connection_lost cancels us; don't treat it as a handler bug
        except Exception:
            # The reference swallows handler exceptions and sends nothing,
            # hanging the client future (RequestHandlerDispatcher.java:63-83).
            # We log and drop too — client timeouts are the recovery path —
            # but the failure taxonomy (RequestFailedFromServer) is preferred.
            LOG.exception("handler failed for %s", type(env.payload).__name__)
            return
        if response is not None and self.transport is not None and not self.transport.is_closing():
            self.send_frame(encode_envelope(response))

    def connection_lost(self, exc: Optional[Exception]) -> None:
        self.server._protocols.discard(self)
        for task in self._tasks:
            task.cancel()
        self.transport = None


class RpcServer:
    """Accepts connections and feeds decoded envelopes to an async handler;
    the handler's response (if any) is written back on the same connection
    (ref: ``MochiServer`` + ``RequestHandlerDispatcher``)."""

    # Payload types whose handlers never block on external work (no device
    # batches, no peer RPC): handled synchronously inside data_received,
    # saving a Task allocation + schedule per message.  Only taken for
    # MAC'd envelopes — session-MAC auth is synchronous, while signed
    # envelopes may await the batch verifier (suspending there would raise
    # in _run_handler_sync).
    INLINE_TYPES = (ReadToServer, Write1ToServer, HelloToServer)

    def __init__(self, host: str, port: int, handler: Handler):
        self.host = host
        self.port = port
        self.handler = handler
        # single source of the "unix:" scheme logic (code-review r4: the
        # prefix was sliced inline in three methods)
        self._unix_path: Optional[str] = (
            host[len("unix:"):] if host.startswith("unix:") else None
        )
        self._bound_ino: Optional[tuple] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._protocols: set = set()

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        if self._unix_path is not None:
            # Unix-domain socket: same framed protocol, no TCP/IP stack —
            # the kernel loopback send path is the measured cost floor for
            # single-host clusters (BASELINE.md round-4 note).  Only a DEAD
            # leftover socket is unlinked: stealing a live server's path
            # would strand it running-but-unreachable, where TCP fails
            # loudly with EADDRINUSE (code-review r4).
            import socket as _socket

            path = self._unix_path
            if os.path.exists(path):
                # Only a refused connect (or a path that vanished under us)
                # proves the previous owner is dead.  A timeout or EAGAIN can
                # mean a LIVE server with a momentarily full accept backlog —
                # unlinking then would steal its path and strand it
                # running-but-unreachable (ADVICE r4).
                probe = _socket.socket(_socket.AF_UNIX)
                probe.settimeout(0.2)
                stale = False
                try:
                    probe.connect(path)
                except (ConnectionRefusedError, FileNotFoundError):
                    stale = True
                except OSError:
                    pass  # timeout / EAGAIN / anything else: assume live
                finally:
                    probe.close()
                if not stale:
                    raise OSError(f"unix socket {path} is in use by a live server")
                try:
                    os.unlink(path)  # stale socket from a dead process
                except FileNotFoundError:
                    pass
            self._server = await loop.create_unix_server(
                lambda: _RpcServerProtocol(self), path
            )
            try:
                st = os.stat(path)
                self._bound_ino = (st.st_dev, st.st_ino)
            except OSError:
                self._bound_ino = None
        else:
            self._server = await loop.create_server(
                lambda: _RpcServerProtocol(self), self.host, self.port
            )

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        if self._unix_path is not None:
            return self.port  # UDS has no port; identity stays the path
        return self._server.sockets[0].getsockname()[1]

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            # Drop live connections first: Server.wait_closed() waits for
            # every connection to finish, and peers hold theirs open.
            # abort(), not close(): a graceful close waits to flush, and a
            # connection whose peer has stopped reading (e.g. another
            # replica's cancelled resync worker mid-state-transfer after a
            # reconfiguration) can keep the flush — and therefore
            # wait_closed() and the whole replica shutdown — pending
            # forever.  Shutdown wants connections DROPPED.
            for proto in list(self._protocols):
                if proto.transport is not None:
                    proto.transport.abort()
            # Belt-and-braces: a connection accepted between the snapshot
            # above and wait_closed() would hang us the same way, so sweep
            # until the server reports fully closed.
            while True:
                try:
                    await asyncio.wait_for(self._server.wait_closed(), timeout=1.0)
                    break
                except asyncio.TimeoutError:
                    for proto in list(self._protocols):
                        if proto.transport is not None:
                            proto.transport.abort()
            self._server = None
            if self._unix_path is not None:
                # a stale socket file accepts nothing but still looks alive
                # to path-probing consumers — ENOENT beats ECONNREFUSED.
                # Unlink ONLY our own inode: a newer server may have
                # (legitimately, after our socket died) bound a fresh
                # socket at this path (code-review r4).
                try:
                    st = os.stat(self._unix_path)
                    if (st.st_dev, st.st_ino) == self._bound_ino:
                        os.unlink(self._unix_path)
                except OSError:
                    pass


class _RpcClientProtocol(_FramedProtocol):
    def __init__(self, conn: "_Connection") -> None:
        super().__init__()
        self.conn = conn

    def frame_received(self, frame: bytes) -> None:
        try:
            env = decode_envelope(frame)
        except Exception:
            LOG.exception("undecodable response from %s; closing", self.conn.info.url)
            if self.transport is not None:
                self.transport.close()
            return
        fut = self.conn.pending.pop(env.reply_to or "", None)
        if fut is not None and not fut.done():
            fut.set_result(env)
        else:
            LOG.warning(
                "uncorrelated response reply_to=%s from %s", env.reply_to, self.conn.info.url
            )

    def connection_lost(self, exc: Optional[Exception]) -> None:
        self.transport = None
        self.conn._on_connection_lost()


class _Connection:
    def __init__(self, info: ServerInfo):
        self.info = info
        self.pending: Dict[str, asyncio.Future] = {}
        self._proto: Optional[_RpcClientProtocol] = None
        self._connect_lock = asyncio.Lock()

    @property
    def connected(self) -> bool:
        return (
            self._proto is not None
            and self._proto.transport is not None
            and not self._proto.transport.is_closing()
        )

    async def ensure_connected(self, retries: int = 3, delay_s: float = 0.1) -> None:
        # ref: MochiClient.checkChannelIsOpened retries 3×100ms then throws
        # (MochiClient.java:110-129).
        async with self._connect_lock:
            if self.connected:
                return
            loop = asyncio.get_running_loop()
            last_exc: Optional[Exception] = None
            for _ in range(retries):
                try:
                    if self.info.is_unix:
                        _, proto = await loop.create_unix_connection(
                            lambda: _RpcClientProtocol(self), self.info.unix_path
                        )
                    else:
                        _, proto = await loop.create_connection(
                            lambda: _RpcClientProtocol(self),
                            self.info.host,
                            self.info.port,
                        )
                    self._proto = proto
                    return
                except OSError as exc:
                    last_exc = exc
                    await asyncio.sleep(delay_s)
            raise ConnectionNotReady(f"cannot reach {self.info.url}") from last_exc

    def _on_connection_lost(self) -> None:
        self._fail_pending(ConnectionNotReady(f"connection to {self.info.url} lost"))

    def _fail_pending(self, exc: Exception) -> None:
        # ref: MochiClientHandler.channelInactive fails all pending promises
        # (MochiClientHandler.java:90-101).
        for fut in self.pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self.pending.clear()
        if self._proto is not None and self._proto.transport is not None:
            self._proto.transport.close()
            self._proto = None

    async def send_and_receive(self, env: Envelope, timeout_s: float) -> Envelope:
        await self.ensure_connected()
        assert self._proto is not None
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self.pending[env.msg_id] = fut
        try:
            self._proto.send_frame(encode_envelope(env))
            return await asyncio.wait_for(fut, timeout_s)
        finally:
            self.pending.pop(env.msg_id, None)

    async def close(self) -> None:
        if self._proto is not None and self._proto.transport is not None:
            self._proto.transport.close()
        self._fail_pending(ConnectionNotReady("closed"))
        self._proto = None


class RpcClientPool:
    """One connection per target server, created lazily
    (ref: ``MochiMessaging.java:33-45``)."""

    def __init__(self, default_timeout_s: float = 10.0):
        self.default_timeout_s = default_timeout_s
        self._connections: Dict[str, _Connection] = {}

    def _conn(self, info: ServerInfo) -> _Connection:
        conn = self._connections.get(info.url)
        if conn is None:
            conn = _Connection(info)
            self._connections[info.url] = conn
        return conn

    async def send_and_receive(
        self, info: ServerInfo, env: Envelope, timeout_s: Optional[float] = None
    ) -> Envelope:
        return await self._conn(info).send_and_receive(
            env, self.default_timeout_s if timeout_s is None else timeout_s
        )

    async def close(self) -> None:
        for conn in self._connections.values():
            await conn.close()
        self._connections.clear()


def new_msg_id() -> str:
    # os.urandom directly: same entropy as uuid4().hex without UUID-object
    # construction (hot path: one id per request per target)
    return os.urandom(16).hex()


async def fan_out(
    pool: RpcClientPool,
    targets: Iterable[Tuple[str, ServerInfo]],
    make_envelope: Callable[..., Envelope],
    timeout_s: Optional[float] = None,
) -> Dict[str, "Envelope | Exception"]:
    """Send one envelope per target concurrently; gather results or exceptions
    per server id (ref: ``Utils.sendMessageToServers`` + ``busyWaitForFutures``,
    ``Utils.java:65-123`` — awaiting real futures instead of 5 ms poll loops).

    ``make_envelope`` is called as ``(msg_id, server_id)`` so callers can
    authenticate per target (session MACs).
    """
    targets = list(targets)
    # `is None` (not falsy-or): an explicit timeout_s=0 means "no waiting",
    # not "use the default" (ADVICE r3).
    timeout = pool.default_timeout_s if timeout_s is None else timeout_s
    out: Dict[str, Envelope | Exception] = {}

    # Steady state: every target connection is open, so each request is a
    # synchronous frame write plus one bare future — the whole fan-out then
    # parks on a single asyncio.wait (one timer, no per-target task).  The
    # per-target task/wait_for formulation costs ~10 scheduled callbacks per
    # transaction at cluster rates.
    loop = asyncio.get_running_loop()
    waiting: List[Tuple[str, asyncio.Future, str, _Connection]] = []
    slow: List[Tuple[str, ServerInfo]] = []
    for sid, info in targets:
        conn = pool._conn(info)
        if not conn.connected:
            slow.append((sid, info))
            continue
        env = make_envelope(new_msg_id(), sid)
        fut = loop.create_future()
        conn.pending[env.msg_id] = fut
        try:
            assert conn._proto is not None
            conn._proto.send_frame(encode_envelope(env))
        except Exception as exc:
            conn.pending.pop(env.msg_id, None)
            out[sid] = exc
            continue
        waiting.append((sid, fut, env.msg_id, conn))

    async def one(sid: str, info: ServerInfo) -> Envelope:
        # wait_for bounds the WHOLE leg including the TCP connect inside
        # ensure_connected — a black-holed host (dropped SYNs) otherwise
        # holds create_connection for the kernel's ~2 min connect timeout,
        # far past this fan-out's budget.
        return await asyncio.wait_for(
            pool.send_and_receive(info, make_envelope(new_msg_id(), sid), timeout),
            timeout=timeout,
        )

    # Slow path (unconnected targets: dial + handshake + request, each leg
    # bounded by `timeout` inside send_and_receive) runs CONCURRENTLY with
    # the fast-path wait below — serially, one down replica would stretch
    # the whole fan-out to ~2x the budget (ADVICE r3).
    slow_task = (
        asyncio.ensure_future(
            asyncio.gather(
                *(one(sid, info) for sid, info in slow), return_exceptions=True
            )
        )
        if slow
        else None
    )

    try:
        if waiting:
            await asyncio.wait([f for _, f, _, _ in waiting], timeout=timeout)
            for sid, fut, msg_id, conn in waiting:
                conn.pending.pop(msg_id, None)
                if fut.done():
                    exc = fut.exception()
                    out[sid] = exc if exc is not None else fut.result()
                else:
                    fut.cancel()
                    out[sid] = TimeoutError(f"no response from {sid} in {timeout}s")

        if slow_task is not None:
            # Already ran alongside the fast-path wait; each leg is
            # internally deadline-bounded, so this completes ~immediately
            # after it.
            slow_results = await slow_task
            for (sid, _), res in zip(slow, slow_results):
                out[sid] = res
        return out
    finally:
        # Structured concurrency: if the fan-out itself is cancelled (caller
        # deadline, shutdown) the detached slow-path task must not keep
        # dialing replicas and sending envelopes in the background.
        if slow_task is not None and not slow_task.done():
            slow_task.cancel()

/* Batched Ed25519 host engine: challenge scalars AND full verification.
 *
 * Two generations of host hot path live here:
 *
 * 1. h_batch — h_i = SHA-512(R_i || A_i || M_i) mod L, the per-item
 *    Python half of the device prepare path (the original purpose of this
 *    file).  The reference leans on JDK MessageDigest intrinsics for its
 *    hashing hot path (SURVEY.md §2.6 Utils.java:135-148); this moves the
 *    per-item loop (hashlib call + python-bignum mod-L + to_bytes) into
 *    one C call over the whole batch.
 *
 * 2. verify_batch — the full cofactorless check [S]B == R + [h]A on the
 *    host, evaluated as [S]B + [h](-A) == R with a shared-doubling Straus
 *    ladder over 4-bit windows.  This is the native-C engine behind
 *    crypto/keys.verify on hosts without the `cryptography` (OpenSSL)
 *    wheel: the pure-Python fallback costs ~1.2 ms/verify and has
 *    inflated every wheel-less benchmark record since r06 by ~20x;
 *    this engine is ~10x cheaper and verdict-identical (differential
 *    suite: tests/test_native_ed25519.py — forgeries, non-canonical
 *    encodings, low-order points all agree with hostfallback).
 *
 * Field arithmetic: GF(2^255-19) on 4x64-bit limbs with unsigned
 * __int128 products, values kept mod 2^256 (2^256 = 2p + 38, so a
 * lazy representation folds overflow as +38) and fully reduced only at
 * fe_tobytes.  Group law: the SAME complete unified addition
 * (add-2008-hwcd-3, a=-1) the repo's JAX data plane and pure-Python
 * fallback use — doubling runs through it too, so the C verdict can
 * never diverge on an exceptional point pair.
 *
 * Self-contained: SHA-512 per FIPS 180-4 (constants generated from the
 * prime cube/square roots, differentially tested against hashlib in
 * tests/test_native_hbatch.py) and a Barrett reduction mod the Ed25519
 * group order L = 2^252 + 27742317777372353535851937790883648493.
 * No OpenSSL headers on this image, so no libcrypto dependency.
 *
 * Build: mochi_tpu/native/__init__.py compiles this lazily (same model as
 * mcode.c); pure-Python verify is the automatic fallback.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* ------------------------------------------------------------- SHA-512 */

static const uint64_t K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL, 0xe9b5dba58189dbbcULL,
    0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL, 0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL,
    0xd807aa98a3030242ULL, 0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL, 0xc19bf174cf692694ULL,
    0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL, 0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL,
    0x2de92c6f592b0275ULL, 0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL, 0xbf597fc7beef0ee4ULL,
    0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL, 0x06ca6351e003826fULL, 0x142929670a0e6e70ULL,
    0x27b70a8546d22ffcULL, 0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL, 0x92722c851482353bULL,
    0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL, 0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL,
    0xd192e819d6ef5218ULL, 0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL, 0x34b0bcb5e19b48a8ULL,
    0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL, 0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL,
    0x748f82ee5defb2fcULL, 0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL, 0xc67178f2e372532bULL,
    0xca273eceea26619cULL, 0xd186b8c721c0c207ULL, 0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL,
    0x06f067aa72176fbaULL, 0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL, 0x431d67c49c100d4cULL,
    0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL, 0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL,
};
static const uint64_t H0[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
    0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL, 0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
};

#define ROTR(x, n) (((x) >> (n)) | ((x) << (64 - (n))))

static void sha512_compress(uint64_t st[8], const uint8_t block[128]) {
    uint64_t w[80];
    for (int t = 0; t < 16; t++) {
        const uint8_t *p = block + 8 * t;
        w[t] = ((uint64_t)p[0] << 56) | ((uint64_t)p[1] << 48) |
               ((uint64_t)p[2] << 40) | ((uint64_t)p[3] << 32) |
               ((uint64_t)p[4] << 24) | ((uint64_t)p[5] << 16) |
               ((uint64_t)p[6] << 8) | (uint64_t)p[7];
    }
    for (int t = 16; t < 80; t++) {
        uint64_t s0 = ROTR(w[t - 15], 1) ^ ROTR(w[t - 15], 8) ^ (w[t - 15] >> 7);
        uint64_t s1 = ROTR(w[t - 2], 19) ^ ROTR(w[t - 2], 61) ^ (w[t - 2] >> 6);
        w[t] = w[t - 16] + s0 + w[t - 7] + s1;
    }
    uint64_t a = st[0], b = st[1], c = st[2], d = st[3];
    uint64_t e = st[4], f = st[5], g = st[6], h = st[7];
    for (int t = 0; t < 80; t++) {
        uint64_t S1 = ROTR(e, 14) ^ ROTR(e, 18) ^ ROTR(e, 41);
        uint64_t ch = (e & f) ^ (~e & g);
        uint64_t t1 = h + S1 + ch + K[t] + w[t];
        uint64_t S0 = ROTR(a, 28) ^ ROTR(a, 34) ^ ROTR(a, 39);
        uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint64_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

/* Streaming SHA-512 over (R || A || M) without concatenating on the heap. */
typedef struct {
    uint64_t st[8];
    uint8_t buf[128];
    size_t buflen;
    uint64_t total;
} sha512_ctx;

static void sha512_init(sha512_ctx *c) {
    memcpy(c->st, H0, sizeof(H0));
    c->buflen = 0;
    c->total = 0;
}

static void sha512_update(sha512_ctx *c, const uint8_t *data, size_t len) {
    c->total += len;
    if (c->buflen) {
        size_t take = 128 - c->buflen;
        if (take > len) take = len;
        memcpy(c->buf + c->buflen, data, take);
        c->buflen += take;
        data += take;
        len -= take;
        if (c->buflen == 128) {
            sha512_compress(c->st, c->buf);
            c->buflen = 0;
        }
    }
    while (len >= 128) {
        sha512_compress(c->st, data);
        data += 128;
        len -= 128;
    }
    if (len) {
        memcpy(c->buf, data, len);
        c->buflen = len;
    }
}

static void sha512_final(sha512_ctx *c, uint8_t out[64]) {
    uint64_t bits = c->total * 8;
    c->buf[c->buflen++] = 0x80;
    if (c->buflen > 112) {
        memset(c->buf + c->buflen, 0, 128 - c->buflen);
        sha512_compress(c->st, c->buf);
        c->buflen = 0;
    }
    memset(c->buf + c->buflen, 0, 128 - c->buflen);
    /* 128-bit big-endian length; messages here are far below 2^64 bits */
    for (int i = 0; i < 8; i++)
        c->buf[120 + i] = (uint8_t)(bits >> (56 - 8 * i));
    sha512_compress(c->st, c->buf);
    for (int i = 0; i < 8; i++) {
        uint64_t v = c->st[i];
        for (int j = 0; j < 8; j++)
            out[8 * i + j] = (uint8_t)(v >> (56 - 8 * j));
    }
}

/* -------------------------------------------- Barrett reduction mod L */

/* L = 2^252 + 27742317777372353535851937790883648493 (4 LE 64-bit words)
 * mu = floor(2^512 / L) (5 words, 260 bits) — both computed with python
 * ints and embedded; tests cross-check against python '%' on random and
 * boundary inputs. */
static const uint64_t Lw[4] = {
    0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL, 0x0ULL, 0x1000000000000000ULL,
};
static const uint64_t MU[5] = {
    0xed9ce5a30a2c131bULL, 0x2106215d086329a7ULL, 0xffffffffffffffebULL,
    0xffffffffffffffffULL, 0xfULL,
};

/* out[0..4] = (x * y)[word 5 .. word 9] where x,y are 5-word LE values —
 * i.e. floor(x*y / 2^320), which is Barrett's q3 when x = q1, y = mu and
 * the shift is b^(k+1) with k=4.  Full 10-word product kept for clarity;
 * the compiler unrolls this fine. */
static void mul_5x5_hi(const uint64_t x[5], const uint64_t y[5], uint64_t out[5]) {
    uint64_t prod[10] = {0};
    for (int i = 0; i < 5; i++) {
        unsigned __int128 carry = 0;
        for (int j = 0; j < 5; j++) {
            unsigned __int128 cur = (unsigned __int128)x[i] * y[j] + prod[i + j] + carry;
            prod[i + j] = (uint64_t)cur;
            carry = cur >> 64;
        }
        prod[i + 5] = (uint64_t)carry;
    }
    memcpy(out, prod + 5, 5 * sizeof(uint64_t));
}

/* out[0..4] = (x * L) mod 2^320, x 5 words */
static void mul_5xL_lo(const uint64_t x[5], uint64_t out[5]) {
    uint64_t prod[5] = {0};
    for (int i = 0; i < 5; i++) {
        unsigned __int128 carry = 0;
        for (int j = 0; j < 4 && i + j < 5; j++) {
            unsigned __int128 cur = (unsigned __int128)x[i] * Lw[j] + prod[i + j] + carry;
            prod[i + j] = (uint64_t)cur;
            carry = cur >> 64;
        }
        if (i + 4 < 5) {
            unsigned __int128 cur = (unsigned __int128)prod[i + 4] + carry;
            prod[i + 4] = (uint64_t)cur;
        }
    }
    memcpy(out, prod, 5 * sizeof(uint64_t));
}

/* r -= s (5 words, mod 2^320); returns borrow */
static uint64_t sub_5(uint64_t r[5], const uint64_t s[5]) {
    unsigned __int128 borrow = 0;
    for (int i = 0; i < 5; i++) {
        unsigned __int128 cur = (unsigned __int128)r[i] - s[i] - (uint64_t)borrow;
        r[i] = (uint64_t)cur;
        borrow = (cur >> 64) ? 1 : 0;
    }
    return (uint64_t)borrow;
}

static int geq_5_L(const uint64_t r[5]) {
    if (r[4]) return 1;
    for (int i = 3; i >= 0; i--) {
        if (r[i] > Lw[i]) return 1;
        if (r[i] < Lw[i]) return 0;
    }
    return 1; /* equal */
}

/* digest (64 bytes, little-endian value) -> digest mod L as 32 LE bytes */
static void reduce512(const uint8_t digest[64], uint8_t out[32]) {
    uint64_t x[8];
    for (int i = 0; i < 8; i++) {
        uint64_t v = 0;
        for (int j = 7; j >= 0; j--) v = (v << 8) | digest[8 * i + j];
        x[i] = v;
    }
    /* q1 = floor(x / b^3): words 3..7 (5 words) */
    uint64_t q1[5] = {x[3], x[4], x[5], x[6], x[7]};
    uint64_t q3[5];
    mul_5x5_hi(q1, MU, q3);
    /* r = x mod b^5  -  (q3 * L mod b^5), then correct by subtracting L */
    uint64_t r[5] = {x[0], x[1], x[2], x[3], x[4]};
    uint64_t q3L[5];
    mul_5xL_lo(q3, q3L);
    sub_5(r, q3L); /* Barrett guarantees 0 <= true r < 3L < b^5: no wrap */
    while (geq_5_L(r)) {
        uint64_t Lx[5] = {Lw[0], Lw[1], Lw[2], Lw[3], 0};
        sub_5(r, Lx);
    }
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            out[8 * i + j] = (uint8_t)(r[i] >> (8 * j));
}

/* --------------------------------------------- GF(2^255-19) field ops */

/* 5x51-bit little-endian limbs (the curve25519-donna-64 radix): a 51x51
 * product times 19 times 5 summands tops out near 2^111, so whole mul
 * columns accumulate in one unsigned __int128 with no serialized carry
 * chain — measured ~2x over a 4x64 schoolbook at -O2 on this host.
 * Discipline: every add/sub weak-carries its result, so all values
 * entering fe_mul have limbs < 2^52 and the 4p bias in fe_sub can never
 * underflow a limb. */
typedef struct { uint64_t v[5]; } fe;

#define M51 0x7ffffffffffffULL

static const uint64_t Pw[4] = {
    0xffffffffffffffedULL, 0xffffffffffffffffULL,
    0xffffffffffffffffULL, 0x7fffffffffffffffULL,
};
static const fe FE_ONE = {{1, 0, 0, 0, 0}};
/* d = -121665/121666 mod p */
static const fe FE_D = {{
    0x34dca135978a3ULL, 0x1a8283b156ebdULL, 0x5e7a26001c029ULL,
    0x739c663a03cbbULL, 0x52036cee2b6ffULL,
}};
/* 2d */
static const fe FE_D2 = {{
    0x69b9426b2f159ULL, 0x35050762add7aULL, 0x3cf44c0038052ULL,
    0x6738cc7407977ULL, 0x2406d9dc56dffULL,
}};
/* sqrt(-1) = 2^((p-1)/4) */
static const fe FE_SQRTM1 = {{
    0x61b274a0ea0b0ULL, 0x0d5a5fc8f189dULL, 0x7ef5e9cbd0c60ULL,
    0x78595a6804c9eULL, 0x2b8324804fc1dULL,
}};
/* 4p limbwise — the fe_sub bias: big enough that subtracting any
 * weak-carried value (limbs < 2^52) keeps every limb non-negative. */
static const uint64_t FOURP[5] = {
    0x1fffffffffffb4ULL, 0x1ffffffffffffcULL, 0x1ffffffffffffcULL,
    0x1ffffffffffffcULL, 0x1ffffffffffffcULL,
};
/* (p-5)/8 = 2^252 - 3, little-endian bytes (fe_pow exponent) */
static const uint8_t EXP_P58[32] = {
    0xfd, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
    0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
    0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
    0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x0f,
};
/* compressed base point: y = 4/5, x even */
static const uint8_t B_ENC[32] = {
    0x58, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
};

static void fe_weak_carry(fe *r) {
    /* bring limbs back under ~2^51 (top limb's overflow folds as x19);
     * the final ripple leaves v[1] at most 2^51 + 1 — well under the
     * 2^52 bound every consumer assumes */
    uint64_t c;
    c = r->v[0] >> 51; r->v[0] &= M51; r->v[1] += c;
    c = r->v[1] >> 51; r->v[1] &= M51; r->v[2] += c;
    c = r->v[2] >> 51; r->v[2] &= M51; r->v[3] += c;
    c = r->v[3] >> 51; r->v[3] &= M51; r->v[4] += c;
    c = r->v[4] >> 51; r->v[4] &= M51; r->v[0] += c * 19;
    c = r->v[0] >> 51; r->v[0] &= M51; r->v[1] += c;
}

static void fe_add(fe *r, const fe *a, const fe *b) {
    for (int i = 0; i < 5; i++) r->v[i] = a->v[i] + b->v[i];
    fe_weak_carry(r);
}

static void fe_sub(fe *r, const fe *a, const fe *b) {
    /* a + 4p - b: with both inputs weak-carried (< 2^52 per limb) every
     * limb stays non-negative, so no borrows exist to track */
    for (int i = 0; i < 5; i++) r->v[i] = a->v[i] + FOURP[i] - b->v[i];
    fe_weak_carry(r);
}

static void fe_mul(fe *r, const fe *a, const fe *b) {
    typedef unsigned __int128 u128;
    const uint64_t a0 = a->v[0], a1 = a->v[1], a2 = a->v[2],
                   a3 = a->v[3], a4 = a->v[4];
    const uint64_t b0 = b->v[0], b1 = b->v[1], b2 = b->v[2],
                   b3 = b->v[3], b4 = b->v[4];
    const uint64_t a1_19 = 19 * a1, a2_19 = 19 * a2, a3_19 = 19 * a3,
                   a4_19 = 19 * a4;
    /* column sums: limbs < 2^52, so each column < 5*19*2^104 < 2^112 */
    u128 t0 = (u128)a0 * b0 + (u128)a1_19 * b4 + (u128)a2_19 * b3
            + (u128)a3_19 * b2 + (u128)a4_19 * b1;
    u128 t1 = (u128)a0 * b1 + (u128)a1 * b0 + (u128)a2_19 * b4
            + (u128)a3_19 * b3 + (u128)a4_19 * b2;
    u128 t2 = (u128)a0 * b2 + (u128)a1 * b1 + (u128)a2 * b0
            + (u128)a3_19 * b4 + (u128)a4_19 * b3;
    u128 t3 = (u128)a0 * b3 + (u128)a1 * b2 + (u128)a2 * b1
            + (u128)a3 * b0 + (u128)a4_19 * b4;
    u128 t4 = (u128)a0 * b4 + (u128)a1 * b3 + (u128)a2 * b2
            + (u128)a3 * b1 + (u128)a4 * b0;
    uint64_t r0, r1, r2, r3, r4, c;
    r0 = (uint64_t)t0 & M51; t1 += (uint64_t)(t0 >> 51);
    r1 = (uint64_t)t1 & M51; t2 += (uint64_t)(t1 >> 51);
    r2 = (uint64_t)t2 & M51; t3 += (uint64_t)(t2 >> 51);
    r3 = (uint64_t)t3 & M51; t4 += (uint64_t)(t3 >> 51);
    r4 = (uint64_t)t4 & M51;
    /* top carry < 2^61; x19 fits u128, folds into r0 with one ripple */
    u128 cc = (u128)r0 + (u128)(uint64_t)(t4 >> 51) * 19;
    r0 = (uint64_t)cc & M51;
    c = (uint64_t)(cc >> 51);
    r1 += c;
    r->v[0] = r0; r->v[1] = r1; r->v[2] = r2; r->v[3] = r3; r->v[4] = r4;
}

static void fe_sq(fe *r, const fe *a) { fe_mul(r, a, a); }

static void fe_neg(fe *r, const fe *a) {
    fe zero = {{0, 0, 0, 0, 0}};
    fe_sub(r, &zero, a);
}

static void fe_tobytes(uint8_t out[32], const fe *a) {
    /* canonical reduction (donna): weak-carry, then decide the final
     * conditional subtract of p by propagating (v + 19) >> 51 */
    fe t = *a;
    fe_weak_carry(&t);
    fe_weak_carry(&t);
    uint64_t q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51;
    t.v[0] += 19 * q;
    uint64_t c;
    c = t.v[0] >> 51; t.v[0] &= M51; t.v[1] += c;
    c = t.v[1] >> 51; t.v[1] &= M51; t.v[2] += c;
    c = t.v[2] >> 51; t.v[2] &= M51; t.v[3] += c;
    c = t.v[3] >> 51; t.v[3] &= M51; t.v[4] += c;
    t.v[4] &= M51; /* discard q * 2^255 */
    uint64_t w0 = t.v[0] | (t.v[1] << 51);
    uint64_t w1 = (t.v[1] >> 13) | (t.v[2] << 38);
    uint64_t w2 = (t.v[2] >> 26) | (t.v[3] << 25);
    uint64_t w3 = (t.v[3] >> 39) | (t.v[4] << 12);
    const uint64_t w[4] = {w0, w1, w2, w3};
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            out[8 * i + j] = (uint8_t)(w[i] >> (8 * j));
}

static uint64_t load_le64(const uint8_t *s) {
    uint64_t v = 0;
    for (int j = 7; j >= 0; j--) v = (v << 8) | s[j];
    return v;
}

static void fe_frombytes(fe *r, const uint8_t s[32]) {
    r->v[0] = load_le64(s) & M51;
    r->v[1] = (load_le64(s + 6) >> 3) & M51;
    r->v[2] = (load_le64(s + 12) >> 6) & M51;
    r->v[3] = (load_le64(s + 19) >> 1) & M51;
    r->v[4] = (load_le64(s + 24) >> 12) & M51;
}

static int fe_iszero(const fe *a) {
    uint8_t b[32];
    fe_tobytes(b, a);
    uint8_t acc = 0;
    for (int i = 0; i < 32; i++) acc |= b[i];
    return acc == 0;
}

/* y-coordinate canonicality: masked 255-bit value must be < p */
static int bytes_lt_p(const uint8_t s[32]) {
    uint64_t w[4];
    for (int i = 0; i < 4; i++) {
        uint64_t v = 0;
        for (int j = 7; j >= 0; j--) v = (v << 8) | s[8 * i + j];
        w[i] = v;
    }
    w[3] &= 0x7fffffffffffffffULL;
    for (int i = 3; i >= 0; i--) {
        if (w[i] < Pw[i]) return 1;
        if (w[i] > Pw[i]) return 0;
    }
    return 0; /* equal to p */
}

static void fe_pow(fe *r, const fe *a, const uint8_t e[32]) {
    /* MSB-first square-and-multiply; exponents here are public values
     * ((p-5)/8), so variable time is fine — same posture as the
     * pure-Python engine. */
    fe acc = FE_ONE, base = *a, t;
    for (int bit = 254; bit >= 0; bit--) {
        fe_sq(&t, &acc);
        acc = t;
        if ((e[bit >> 3] >> (bit & 7)) & 1) {
            fe_mul(&t, &acc, &base);
            acc = t;
        }
    }
    *r = acc;
}

/* --------------------------------------------------------------- X25519 */

/* RFC 7748 §5 scalar multiplication on the Montgomery form — the session
 * handshake's DH (crypto/session.py).  Pure-Python X25519 costs ~1.4 ms
 * per operation on this host, which puts a cold fan-out's worth of
 * handshakes at several blocked-loop milliseconds; this ladder reuses the
 * 5x51 field ops above and lands in the tens of microseconds.  The
 * private scalar IS secret (unlike the fe_pow exponents): the ladder is
 * fixed-length with masked constant-time conditional swaps. */

static void fe_cswap(fe *a, fe *b, uint64_t swap) {
    uint64_t mask = 0 - swap; /* swap in {0,1} */
    for (int i = 0; i < 5; i++) {
        uint64_t t = mask & (a->v[i] ^ b->v[i]);
        a->v[i] ^= t;
        b->v[i] ^= t;
    }
}

/* p - 2 = 2^255 - 21, little-endian bytes (fe_pow inversion exponent —
 * public constant, so fe_pow's variable time is fine here too; shared
 * with the ge section's inverse below). */
static const uint8_t EXP_PM2[32] = {
    0xeb, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
    0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
    0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
    0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x7f,
};

/* (A - 2) / 4 = 121665 as a field element (z2 step of the ladder) */
static const fe FE_A24 = {{121665, 0, 0, 0, 0}};

static void x25519_scalarmult(uint8_t out[32], const uint8_t scalar[32],
                              const uint8_t point[32]) {
    uint8_t k[32];
    memcpy(k, scalar, 32);
    k[0] &= 248; /* RFC 7748 clamp, in C: callers pass the raw seed */
    k[31] &= 127;
    k[31] |= 64;
    /* fe_frombytes masks bit 255 of the peer point by construction */
    fe x1, x2, z2, x3, z3;
    fe a, aa, b, bb, e, c, d, da, cb, t;
    fe_frombytes(&x1, point);
    x2 = FE_ONE;
    memset(&z2, 0, sizeof z2);
    x3 = x1;
    z3 = FE_ONE;
    uint64_t swap = 0;
    for (int bit = 254; bit >= 0; bit--) {
        uint64_t k_t = (k[bit >> 3] >> (bit & 7)) & 1;
        swap ^= k_t;
        fe_cswap(&x2, &x3, swap);
        fe_cswap(&z2, &z3, swap);
        swap = k_t;
        fe_add(&a, &x2, &z2);
        fe_sq(&aa, &a);
        fe_sub(&b, &x2, &z2);
        fe_sq(&bb, &b);
        fe_sub(&e, &aa, &bb);
        fe_add(&c, &x3, &z3);
        fe_sub(&d, &x3, &z3);
        fe_mul(&da, &d, &a);
        fe_mul(&cb, &c, &b);
        fe_add(&t, &da, &cb);
        fe_sq(&x3, &t);
        fe_sub(&t, &da, &cb);
        fe_sq(&t, &t);
        fe_mul(&z3, &t, &x1);
        fe_mul(&x2, &aa, &bb);
        fe_mul(&t, &e, &FE_A24);
        fe_add(&t, &aa, &t);
        fe_mul(&z2, &e, &t);
    }
    fe_cswap(&x2, &x3, swap);
    fe_cswap(&z2, &z3, swap);
    fe_pow(&t, &z2, EXP_PM2);
    fe_mul(&x2, &x2, &t);
    fe_tobytes(out, &x2);
}

/* ---------------------------------------------------- group operations */

/* Extended twisted-Edwards coordinates (X, Y, Z, T), x = X/Z, y = Y/Z,
 * T = XY/Z — the exact layout of curve.Point / hostfallback._Pt. */
typedef struct { fe X, Y, Z, T; } ge;

static const ge GE_ID = {
    {{0, 0, 0, 0, 0}}, {{1, 0, 0, 0, 0}}, {{1, 0, 0, 0, 0}}, {{0, 0, 0, 0, 0}},
};

/* Complete unified addition (add-2008-hwcd-3, a=-1) — hostfallback._pt_add
 * on limbs.  Complete: also serves as doubling and handles the identity,
 * so no input pair can route the C engine onto a different formula than
 * the Python engine evaluates. */
static void ge_add(ge *r, const ge *p, const ge *q) {
    fe a, b, c, d, e, f, g, h, t1, t2;
    fe_sub(&t1, &p->Y, &p->X);
    fe_sub(&t2, &q->Y, &q->X);
    fe_mul(&a, &t1, &t2);
    fe_add(&t1, &p->Y, &p->X);
    fe_add(&t2, &q->Y, &q->X);
    fe_mul(&b, &t1, &t2);
    fe_mul(&t1, &p->T, &FE_D2);
    fe_mul(&c, &t1, &q->T);
    fe_mul(&t1, &p->Z, &q->Z);
    fe_add(&d, &t1, &t1);
    fe_sub(&e, &b, &a);
    fe_sub(&f, &d, &c);
    fe_add(&g, &d, &c);
    fe_add(&h, &b, &a);
    fe_mul(&r->X, &e, &f);
    fe_mul(&r->Y, &g, &h);
    fe_mul(&r->Z, &f, &g);
    fe_mul(&r->T, &e, &h);
}

/* RFC 8032 §5.1.3 point decoding — hostfallback._decompress on limbs.
 * Returns 0 on success, -1 for a non-point / non-canonical encoding. */
static int ge_decompress(ge *out, const uint8_t s[32]) {
    int sign = s[31] >> 7;
    if (!bytes_lt_p(s)) return -1;
    fe y, yy, u, v, v3, v7, t, x, vxx, chk;
    fe_frombytes(&y, s);
    fe_sq(&yy, &y);
    fe_sub(&u, &yy, &FE_ONE);
    fe_mul(&v, &FE_D, &yy);
    fe_add(&v, &v, &FE_ONE);
    /* candidate root x = u * v^3 * (u*v^7)^((p-5)/8) */
    fe_sq(&t, &v);
    fe_mul(&v3, &t, &v);
    fe_sq(&t, &v3);
    fe_mul(&v7, &t, &v);
    fe_mul(&t, &u, &v7);
    fe_pow(&t, &t, EXP_P58);
    fe_mul(&x, &u, &v3);
    fe_mul(&x, &x, &t);
    fe_sq(&t, &x);
    fe_mul(&vxx, &v, &t);
    fe_sub(&chk, &vxx, &u);
    if (!fe_iszero(&chk)) {
        fe_add(&chk, &vxx, &u);
        if (!fe_iszero(&chk)) return -1;
        fe_mul(&x, &x, &FE_SQRTM1);
    }
    uint8_t xb[32];
    fe_tobytes(xb, &x);
    int x_zero = 1;
    for (int i = 0; i < 32; i++)
        if (xb[i]) { x_zero = 0; break; }
    if (x_zero && sign) return -1;
    if ((xb[0] & 1) != sign) fe_neg(&x, &x);
    out->X = x;
    out->Y = y;
    out->Z = FE_ONE;
    fe_mul(&out->T, &x, &y);
    return 0;
}

/* --------------------------------------------------- Ed25519 verify */

/* Btab[d] = [d]B for d in 1..15 (index 0 unused; verify's Straus ladder)
 * and BCOMB[w][d] = [d * 16^w]B (hostfallback._window_table on limbs;
 * signing's doubling-free fixed-base walk).  Built once under the GIL on
 * first use (~1k additions). */
static ge Btab[16];
static ge BCOMB[64][16];
static int tables_ready = 0;

static int ensure_tables(void) {
    if (tables_ready) return 0;
    ge b;
    if (ge_decompress(&b, B_ENC) != 0) return -1; /* unreachable */
    Btab[1] = b;
    for (int i = 2; i < 16; i++) ge_add(&Btab[i], &Btab[i - 1], &b);
    ge step = b, t;
    for (int w = 0; w < 64; w++) {
        BCOMB[w][0] = GE_ID;
        for (int d = 1; d < 16; d++) ge_add(&BCOMB[w][d], &BCOMB[w][d - 1], &step);
        ge_add(&t, &BCOMB[w][8], &BCOMB[w][8]); /* 16 * step */
        step = t;
    }
    tables_ready = 1;
    return 0;
}

/* [k]B via the comb table: 64 unconditional additions, NO zero-digit
 * skip — the signing scalars (nonce r, private a) are SECRET, and a skip
 * would correlate per-signature timing with their zero-nibble counts
 * (hostfallback._mul_base documents the same choice; the engine is
 * variable-time at the limb level regardless, but no extra
 * branch-per-secret-nibble on top). */
/* mochi-ct: secret(k) */
static void ge_mul_base(ge *r, const uint8_t k[32]) {
    ge acc = GE_ID, t;
    for (int w = 0; w < 64; w++) {
        int d = (k[w >> 1] >> ((w & 1) * 4)) & 15;
        /* mochi-lint: disable=native-const-time -- reviewed: 16-entry row of a hot comb table (one cache line's reach, touched 64x/sign); the branch-free add above it is the channel that matters and is pinned clean */
        ge_add(&t, &acc, &BCOMB[w][d]);
        acc = t;
    }
    *r = acc;
}

/* compress: (X/Z, Y/Z) -> 32-byte encoding (hostfallback._compress) */
static void ge_tobytes(uint8_t out[32], const ge *p) {
    fe zinv, x, y;
    fe_pow(&zinv, &p->Z, EXP_PM2);
    fe_mul(&x, &p->X, &zinv);
    fe_mul(&y, &p->Y, &zinv);
    uint8_t xb[32];
    fe_tobytes(xb, &x);
    fe_tobytes(out, &y);
    out[31] |= (xb[0] & 1) << 7;
}

static int fe_eq_scaled(const fe *affine, const fe *proj, const fe *z) {
    /* affine * z == proj (both canonicalized) */
    fe t;
    uint8_t b1[32], b2[32];
    fe_mul(&t, affine, z);
    fe_tobytes(b1, &t);
    fe_tobytes(b2, proj);
    return memcmp(b1, b2, 32) == 0;
}

/* Cofactorless [S]B == R + [h]A, evaluated as [S]B + [h](-A) == R with a
 * Straus shared-doubling ladder (4-bit windows over the full 256-bit
 * scalars, so a non-canonical S >= L computes the true multiple — the
 * exact verdict hostfallback's table walk produces for the same bytes).
 * h is the already-reduced challenge scalar (32 LE bytes, from
 * h_batch/reduce512).  Variable-time on public data only. */
static int verify_one(const uint8_t pub[32], const uint8_t sig[64],
                      const uint8_t h[32]) {
    ge A, R, acc, t;
    if (ge_decompress(&R, sig) != 0) return 0;
    if (ge_decompress(&A, pub) != 0) return 0;
    fe_neg(&A.X, &A.X);
    fe_neg(&A.T, &A.T);
    ge Atab[16];
    Atab[1] = A;
    for (int i = 2; i < 16; i++) ge_add(&Atab[i], &Atab[i - 1], &A);
    const uint8_t *s = sig + 32;
    acc = GE_ID;
    for (int w = 63; w >= 0; w--) {
        for (int k = 0; k < 4; k++) {
            ge_add(&t, &acc, &acc);
            acc = t;
        }
        int ns = (s[w >> 1] >> ((w & 1) * 4)) & 15;
        if (ns) {
            ge_add(&t, &acc, &Btab[ns]);
            acc = t;
        }
        int nh = (h[w >> 1] >> ((w & 1) * 4)) & 15;
        if (nh) {
            ge_add(&t, &acc, &Atab[nh]);
            acc = t;
        }
    }
    /* acc == R, R affine (Z=1): cross-multiplied projective equality */
    return fe_eq_scaled(&R.X, &acc.X, &acc.Z)
        && fe_eq_scaled(&R.Y, &acc.Y, &acc.Z);
}

/* ------------------------------------------------------------ binding */

/* h_batch(r: n*32 bytes, a: n*32 bytes, msgs: concatenated messages,
 *         lens: n little-endian uint64 byte lengths) -> n*32 bytes */
static PyObject *py_h_batch(PyObject *self, PyObject *args) {
    Py_buffer rbuf, abuf, mbuf, lbuf;
    if (!PyArg_ParseTuple(args, "y*y*y*y*", &rbuf, &abuf, &mbuf, &lbuf))
        return NULL;
    PyObject *result = NULL;
    Py_ssize_t n = lbuf.len / 8;
    if (lbuf.len % 8 || rbuf.len != n * 32 || abuf.len != n * 32) {
        PyErr_SetString(PyExc_ValueError, "h_batch: inconsistent buffer sizes");
        goto done;
    }
    const uint64_t *lens = (const uint64_t *)lbuf.buf;
    uint64_t total = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        /* overflow-safe: each length must fit the REMAINING buffer, so the
         * sum can never wrap (a wrapped total could equal mbuf.len and
         * smuggle an out-of-bounds read past the check below) */
        if (lens[i] > (uint64_t)mbuf.len - total) {
            PyErr_SetString(PyExc_ValueError,
                            "h_batch: msg lengths exceed buffer");
            goto done;
        }
        total += lens[i];
    }
    if (total != (uint64_t)mbuf.len) {
        PyErr_SetString(PyExc_ValueError, "h_batch: msg lengths do not sum to buffer");
        goto done;
    }
    result = PyBytes_FromStringAndSize(NULL, n * 32);
    if (!result) goto done;
    uint8_t *out = (uint8_t *)PyBytes_AS_STRING(result);
    const uint8_t *rs = (const uint8_t *)rbuf.buf;
    const uint8_t *as = (const uint8_t *)abuf.buf;
    const uint8_t *ms = (const uint8_t *)mbuf.buf;
    Py_BEGIN_ALLOW_THREADS
    size_t off = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        sha512_ctx c;
        uint8_t digest[64];
        sha512_init(&c);
        sha512_update(&c, rs + 32 * i, 32);
        sha512_update(&c, as + 32 * i, 32);
        sha512_update(&c, ms + off, (size_t)lens[i]);
        off += (size_t)lens[i];
        sha512_final(&c, digest);
        reduce512(digest, out + 32 * i);
    }
    Py_END_ALLOW_THREADS
done:
    PyBuffer_Release(&rbuf);
    PyBuffer_Release(&abuf);
    PyBuffer_Release(&mbuf);
    PyBuffer_Release(&lbuf);
    return result;
}

/* verify_batch(pubs: n*32 bytes, sigs: n*64 bytes, hs: n*32 bytes of
 * already-reduced challenge scalars) -> n verdict bytes (0/1).  The h
 * scalars come from h_batch/reduce512, so the full host pipeline is
 * "h_batch then verify_batch" — two C calls for a whole certificate. */
static PyObject *py_verify_batch(PyObject *self, PyObject *args) {
    Py_buffer pbuf, sbuf, hbuf;
    if (!PyArg_ParseTuple(args, "y*y*y*", &pbuf, &sbuf, &hbuf))
        return NULL;
    PyObject *result = NULL;
    Py_ssize_t n = hbuf.len / 32;
    if (hbuf.len % 32 || pbuf.len != n * 32 || sbuf.len != n * 64) {
        PyErr_SetString(PyExc_ValueError,
                        "verify_batch: inconsistent buffer sizes");
        goto done;
    }
    if (ensure_tables() != 0) {
        PyErr_SetString(PyExc_RuntimeError,
                        "verify_batch: base point decode failed");
        goto done;
    }
    result = PyBytes_FromStringAndSize(NULL, n);
    if (!result) goto done;
    uint8_t *out = (uint8_t *)PyBytes_AS_STRING(result);
    const uint8_t *ps = (const uint8_t *)pbuf.buf;
    const uint8_t *ss = (const uint8_t *)sbuf.buf;
    const uint8_t *hs = (const uint8_t *)hbuf.buf;
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t i = 0; i < n; i++)
        out[i] = (uint8_t)verify_one(ps + 32 * i, ss + 64 * i, hs + 32 * i);
    Py_END_ALLOW_THREADS
done:
    PyBuffer_Release(&pbuf);
    PyBuffer_Release(&sbuf);
    PyBuffer_Release(&hbuf);
    return result;
}

/* out = k*a + r as a 512-bit little-endian value (k, r: 32-byte reduced
 * scalars < L; a: the clamped secret scalar < 2^255) — the caller feeds
 * it back through reduce512 for s = (r + k*a) mod L. */
static void sc_muladd_512(uint8_t out[64], const uint8_t k[32],
                          const uint8_t a[32], const uint8_t r[32]) {
    uint64_t kw[4], aw[4], rw[4], t[8] = {0};
    for (int i = 0; i < 4; i++) {
        kw[i] = load_le64(k + 8 * i);
        aw[i] = load_le64(a + 8 * i);
        rw[i] = load_le64(r + 8 * i);
    }
    for (int i = 0; i < 4; i++) {
        unsigned __int128 carry = 0;
        for (int j = 0; j < 4; j++) {
            unsigned __int128 cur =
                (unsigned __int128)kw[i] * aw[j] + t[i + j] + carry;
            t[i + j] = (uint64_t)cur;
            carry = cur >> 64;
        }
        t[i + 4] = (uint64_t)carry;
    }
    unsigned __int128 c = 0;
    for (int i = 0; i < 8; i++) {
        c += t[i];
        if (i < 4) c += rw[i];
        t[i] = (uint64_t)c;
        c >>= 64;
    } /* k*a + r < L*2^255 + L << 2^512: no final carry */
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 8; j++)
            out[8 * i + j] = (uint8_t)(t[i] >> (8 * j));
}

/* sign_prepared(a, prefix, pub, msg) -> 64-byte signature.  RFC 8032
 * §5.1.6 with the key already expanded (a = clamped scalar, prefix =
 * second hash half, pub = compressed A) — the Python side caches that
 * expansion per seed.  Deterministic and bit-identical to
 * hostfallback.sign / OpenSSL (the replica's own-grant re-sign-and-
 * compare depends on byte equality across engines). */
static PyObject *py_sign_prepared(PyObject *self, PyObject *args) {
    Py_buffer abuf, pbuf, qbuf, mbuf;
    if (!PyArg_ParseTuple(args, "y*y*y*y*", &abuf, &pbuf, &qbuf, &mbuf))
        return NULL;
    PyObject *result = NULL;
    if (abuf.len != 32 || pbuf.len != 32 || qbuf.len != 32) {
        PyErr_SetString(PyExc_ValueError,
                        "sign_prepared: a/prefix/pub must be 32 bytes");
        goto done;
    }
    if (ensure_tables() != 0) {
        PyErr_SetString(PyExc_RuntimeError,
                        "sign_prepared: base point decode failed");
        goto done;
    }
    uint8_t sig[64];
    Py_BEGIN_ALLOW_THREADS
    {
        sha512_ctx c;
        uint8_t digest[64], r_scalar[32], k_scalar[32], t512[64];
        sha512_init(&c);
        sha512_update(&c, (const uint8_t *)pbuf.buf, 32);
        sha512_update(&c, (const uint8_t *)mbuf.buf, (size_t)mbuf.len);
        sha512_final(&c, digest);
        reduce512(digest, r_scalar);
        ge R;
        ge_mul_base(&R, r_scalar);
        ge_tobytes(sig, &R);
        sha512_init(&c);
        sha512_update(&c, sig, 32);
        sha512_update(&c, (const uint8_t *)qbuf.buf, 32);
        sha512_update(&c, (const uint8_t *)mbuf.buf, (size_t)mbuf.len);
        sha512_final(&c, digest);
        reduce512(digest, k_scalar);
        sc_muladd_512(t512, k_scalar, (const uint8_t *)abuf.buf, r_scalar);
        reduce512(t512, sig + 32);
    }
    Py_END_ALLOW_THREADS
    result = PyBytes_FromStringAndSize((const char *)sig, 64);
done:
    PyBuffer_Release(&abuf);
    PyBuffer_Release(&pbuf);
    PyBuffer_Release(&qbuf);
    PyBuffer_Release(&mbuf);
    return result;
}

/* test hooks: sha512(data) and reduce512(digest) for directed differential
 * tests against hashlib / python ints */
static PyObject *py_sha512(PyObject *self, PyObject *args) {
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
    sha512_ctx c;
    uint8_t digest[64];
    sha512_init(&c);
    sha512_update(&c, (const uint8_t *)buf.buf, (size_t)buf.len);
    sha512_final(&c, digest);
    PyBuffer_Release(&buf);
    return PyBytes_FromStringAndSize((const char *)digest, 64);
}

static PyObject *py_reduce512(PyObject *self, PyObject *args) {
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
    if (buf.len != 64) {
        PyBuffer_Release(&buf);
        PyErr_SetString(PyExc_ValueError, "reduce512 wants 64 bytes");
        return NULL;
    }
    uint8_t out[32];
    reduce512((const uint8_t *)buf.buf, out);
    PyBuffer_Release(&buf);
    return PyBytes_FromStringAndSize((const char *)out, 32);
}

/* x25519(private, peer_public) -> 32-byte shared secret.  Clamping runs
 * in C (RFC 7748 §5); the all-zero-output small-order rejection stays in
 * the Python wrapper (crypto/hostfallback.x25519) so both engines share
 * ONE policy seam. */
static PyObject *py_x25519(PyObject *self, PyObject *args) {
    Py_buffer kbuf, ubuf;
    if (!PyArg_ParseTuple(args, "y*y*", &kbuf, &ubuf)) return NULL;
    PyObject *result = NULL;
    if (kbuf.len != 32 || ubuf.len != 32) {
        PyErr_SetString(PyExc_ValueError, "x25519: keys must be 32 bytes");
        goto done;
    }
    uint8_t out[32];
    Py_BEGIN_ALLOW_THREADS
    x25519_scalarmult(out, (const uint8_t *)kbuf.buf,
                      (const uint8_t *)ubuf.buf);
    Py_END_ALLOW_THREADS
    result = PyBytes_FromStringAndSize((const char *)out, 32);
done:
    PyBuffer_Release(&kbuf);
    PyBuffer_Release(&ubuf);
    return result;
}

static PyMethodDef methods[] = {
    {"h_batch", py_h_batch, METH_VARARGS,
     "h_batch(r, a, msgs, lens) -> concatenated 32-byte h scalars"},
    {"verify_batch", py_verify_batch, METH_VARARGS,
     "verify_batch(pubs, sigs, hs) -> one verdict byte (0/1) per item"},
    {"sign_prepared", py_sign_prepared, METH_VARARGS,
     "sign_prepared(a, prefix, pub, msg) -> 64-byte Ed25519 signature"},
    {"x25519", py_x25519, METH_VARARGS,
     "x25519(private, peer_public) -> 32-byte shared secret (RFC 7748)"},
    {"sha512", py_sha512, METH_VARARGS, "test hook: one-shot SHA-512"},
    {"reduce512", py_reduce512, METH_VARARGS,
     "test hook: 64-byte LE value mod L as 32 LE bytes"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_hbatch", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit__hbatch(void) { return PyModule_Create(&moduledef); }

/* Batched Ed25519 challenge-scalar computation: h_i = SHA-512(R_i || A_i
 * || M_i) mod L, the per-item Python half of the host prepare path.
 *
 * The reference leans on JDK MessageDigest intrinsics for its hashing hot
 * path (SURVEY.md §2.6 Utils.java:135-148); this framework's analog moves
 * the per-item loop (hashlib call + python-bignum mod-L + to_bytes) into
 * one C call over the whole batch.  Measured motivation: at 8192-item
 * buckets the python h-loop is ~2.1 us/item of the ~4.5 us/item prepare
 * cost, capping the host at ~224k items/s in front of a device pipeline
 * the comb path pushes well past that (crypto/comb.py).
 *
 * Self-contained: SHA-512 per FIPS 180-4 (constants generated from the
 * prime cube/square roots, differentially tested against hashlib in
 * tests/test_native_hbatch.py) and a Barrett reduction mod the Ed25519
 * group order L = 2^252 + 27742317777372353535851937790883648493.
 * No OpenSSL headers on this image, so no libcrypto dependency.
 *
 * Build: mochi_tpu/native/__init__.py compiles this lazily (same model as
 * mcode.c); pure-Python prepare is the automatic fallback.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* ------------------------------------------------------------- SHA-512 */

static const uint64_t K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL, 0xe9b5dba58189dbbcULL,
    0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL, 0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL,
    0xd807aa98a3030242ULL, 0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL, 0xc19bf174cf692694ULL,
    0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL, 0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL,
    0x2de92c6f592b0275ULL, 0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL, 0xbf597fc7beef0ee4ULL,
    0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL, 0x06ca6351e003826fULL, 0x142929670a0e6e70ULL,
    0x27b70a8546d22ffcULL, 0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL, 0x92722c851482353bULL,
    0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL, 0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL,
    0xd192e819d6ef5218ULL, 0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL, 0x34b0bcb5e19b48a8ULL,
    0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL, 0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL,
    0x748f82ee5defb2fcULL, 0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL, 0xc67178f2e372532bULL,
    0xca273eceea26619cULL, 0xd186b8c721c0c207ULL, 0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL,
    0x06f067aa72176fbaULL, 0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL, 0x431d67c49c100d4cULL,
    0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL, 0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL,
};
static const uint64_t H0[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
    0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL, 0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
};

#define ROTR(x, n) (((x) >> (n)) | ((x) << (64 - (n))))

static void sha512_compress(uint64_t st[8], const uint8_t block[128]) {
    uint64_t w[80];
    for (int t = 0; t < 16; t++) {
        const uint8_t *p = block + 8 * t;
        w[t] = ((uint64_t)p[0] << 56) | ((uint64_t)p[1] << 48) |
               ((uint64_t)p[2] << 40) | ((uint64_t)p[3] << 32) |
               ((uint64_t)p[4] << 24) | ((uint64_t)p[5] << 16) |
               ((uint64_t)p[6] << 8) | (uint64_t)p[7];
    }
    for (int t = 16; t < 80; t++) {
        uint64_t s0 = ROTR(w[t - 15], 1) ^ ROTR(w[t - 15], 8) ^ (w[t - 15] >> 7);
        uint64_t s1 = ROTR(w[t - 2], 19) ^ ROTR(w[t - 2], 61) ^ (w[t - 2] >> 6);
        w[t] = w[t - 16] + s0 + w[t - 7] + s1;
    }
    uint64_t a = st[0], b = st[1], c = st[2], d = st[3];
    uint64_t e = st[4], f = st[5], g = st[6], h = st[7];
    for (int t = 0; t < 80; t++) {
        uint64_t S1 = ROTR(e, 14) ^ ROTR(e, 18) ^ ROTR(e, 41);
        uint64_t ch = (e & f) ^ (~e & g);
        uint64_t t1 = h + S1 + ch + K[t] + w[t];
        uint64_t S0 = ROTR(a, 28) ^ ROTR(a, 34) ^ ROTR(a, 39);
        uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint64_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

/* Streaming SHA-512 over (R || A || M) without concatenating on the heap. */
typedef struct {
    uint64_t st[8];
    uint8_t buf[128];
    size_t buflen;
    uint64_t total;
} sha512_ctx;

static void sha512_init(sha512_ctx *c) {
    memcpy(c->st, H0, sizeof(H0));
    c->buflen = 0;
    c->total = 0;
}

static void sha512_update(sha512_ctx *c, const uint8_t *data, size_t len) {
    c->total += len;
    if (c->buflen) {
        size_t take = 128 - c->buflen;
        if (take > len) take = len;
        memcpy(c->buf + c->buflen, data, take);
        c->buflen += take;
        data += take;
        len -= take;
        if (c->buflen == 128) {
            sha512_compress(c->st, c->buf);
            c->buflen = 0;
        }
    }
    while (len >= 128) {
        sha512_compress(c->st, data);
        data += 128;
        len -= 128;
    }
    if (len) {
        memcpy(c->buf, data, len);
        c->buflen = len;
    }
}

static void sha512_final(sha512_ctx *c, uint8_t out[64]) {
    uint64_t bits = c->total * 8;
    c->buf[c->buflen++] = 0x80;
    if (c->buflen > 112) {
        memset(c->buf + c->buflen, 0, 128 - c->buflen);
        sha512_compress(c->st, c->buf);
        c->buflen = 0;
    }
    memset(c->buf + c->buflen, 0, 128 - c->buflen);
    /* 128-bit big-endian length; messages here are far below 2^64 bits */
    for (int i = 0; i < 8; i++)
        c->buf[120 + i] = (uint8_t)(bits >> (56 - 8 * i));
    sha512_compress(c->st, c->buf);
    for (int i = 0; i < 8; i++) {
        uint64_t v = c->st[i];
        for (int j = 0; j < 8; j++)
            out[8 * i + j] = (uint8_t)(v >> (56 - 8 * j));
    }
}

/* -------------------------------------------- Barrett reduction mod L */

/* L = 2^252 + 27742317777372353535851937790883648493 (4 LE 64-bit words)
 * mu = floor(2^512 / L) (5 words, 260 bits) — both computed with python
 * ints and embedded; tests cross-check against python '%' on random and
 * boundary inputs. */
static const uint64_t Lw[4] = {
    0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL, 0x0ULL, 0x1000000000000000ULL,
};
static const uint64_t MU[5] = {
    0xed9ce5a30a2c131bULL, 0x2106215d086329a7ULL, 0xffffffffffffffebULL,
    0xffffffffffffffffULL, 0xfULL,
};

/* out[0..4] = (x * y)[word 5 .. word 9] where x,y are 5-word LE values —
 * i.e. floor(x*y / 2^320), which is Barrett's q3 when x = q1, y = mu and
 * the shift is b^(k+1) with k=4.  Full 10-word product kept for clarity;
 * the compiler unrolls this fine. */
static void mul_5x5_hi(const uint64_t x[5], const uint64_t y[5], uint64_t out[5]) {
    uint64_t prod[10] = {0};
    for (int i = 0; i < 5; i++) {
        unsigned __int128 carry = 0;
        for (int j = 0; j < 5; j++) {
            unsigned __int128 cur = (unsigned __int128)x[i] * y[j] + prod[i + j] + carry;
            prod[i + j] = (uint64_t)cur;
            carry = cur >> 64;
        }
        prod[i + 5] = (uint64_t)carry;
    }
    memcpy(out, prod + 5, 5 * sizeof(uint64_t));
}

/* out[0..4] = (x * L) mod 2^320, x 5 words */
static void mul_5xL_lo(const uint64_t x[5], uint64_t out[5]) {
    uint64_t prod[5] = {0};
    for (int i = 0; i < 5; i++) {
        unsigned __int128 carry = 0;
        for (int j = 0; j < 4 && i + j < 5; j++) {
            unsigned __int128 cur = (unsigned __int128)x[i] * Lw[j] + prod[i + j] + carry;
            prod[i + j] = (uint64_t)cur;
            carry = cur >> 64;
        }
        if (i + 4 < 5) {
            unsigned __int128 cur = (unsigned __int128)prod[i + 4] + carry;
            prod[i + 4] = (uint64_t)cur;
        }
    }
    memcpy(out, prod, 5 * sizeof(uint64_t));
}

/* r -= s (5 words, mod 2^320); returns borrow */
static uint64_t sub_5(uint64_t r[5], const uint64_t s[5]) {
    unsigned __int128 borrow = 0;
    for (int i = 0; i < 5; i++) {
        unsigned __int128 cur = (unsigned __int128)r[i] - s[i] - (uint64_t)borrow;
        r[i] = (uint64_t)cur;
        borrow = (cur >> 64) ? 1 : 0;
    }
    return (uint64_t)borrow;
}

static int geq_5_L(const uint64_t r[5]) {
    if (r[4]) return 1;
    for (int i = 3; i >= 0; i--) {
        if (r[i] > Lw[i]) return 1;
        if (r[i] < Lw[i]) return 0;
    }
    return 1; /* equal */
}

/* digest (64 bytes, little-endian value) -> digest mod L as 32 LE bytes */
static void reduce512(const uint8_t digest[64], uint8_t out[32]) {
    uint64_t x[8];
    for (int i = 0; i < 8; i++) {
        uint64_t v = 0;
        for (int j = 7; j >= 0; j--) v = (v << 8) | digest[8 * i + j];
        x[i] = v;
    }
    /* q1 = floor(x / b^3): words 3..7 (5 words) */
    uint64_t q1[5] = {x[3], x[4], x[5], x[6], x[7]};
    uint64_t q3[5];
    mul_5x5_hi(q1, MU, q3);
    /* r = x mod b^5  -  (q3 * L mod b^5), then correct by subtracting L */
    uint64_t r[5] = {x[0], x[1], x[2], x[3], x[4]};
    uint64_t q3L[5];
    mul_5xL_lo(q3, q3L);
    sub_5(r, q3L); /* Barrett guarantees 0 <= true r < 3L < b^5: no wrap */
    while (geq_5_L(r)) {
        uint64_t Lx[5] = {Lw[0], Lw[1], Lw[2], Lw[3], 0};
        sub_5(r, Lx);
    }
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            out[8 * i + j] = (uint8_t)(r[i] >> (8 * j));
}

/* ------------------------------------------------------------ binding */

/* h_batch(r: n*32 bytes, a: n*32 bytes, msgs: concatenated messages,
 *         lens: n little-endian uint64 byte lengths) -> n*32 bytes */
static PyObject *py_h_batch(PyObject *self, PyObject *args) {
    Py_buffer rbuf, abuf, mbuf, lbuf;
    if (!PyArg_ParseTuple(args, "y*y*y*y*", &rbuf, &abuf, &mbuf, &lbuf))
        return NULL;
    PyObject *result = NULL;
    Py_ssize_t n = lbuf.len / 8;
    if (lbuf.len % 8 || rbuf.len != n * 32 || abuf.len != n * 32) {
        PyErr_SetString(PyExc_ValueError, "h_batch: inconsistent buffer sizes");
        goto done;
    }
    const uint64_t *lens = (const uint64_t *)lbuf.buf;
    uint64_t total = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        /* overflow-safe: each length must fit the REMAINING buffer, so the
         * sum can never wrap (a wrapped total could equal mbuf.len and
         * smuggle an out-of-bounds read past the check below) */
        if (lens[i] > (uint64_t)mbuf.len - total) {
            PyErr_SetString(PyExc_ValueError,
                            "h_batch: msg lengths exceed buffer");
            goto done;
        }
        total += lens[i];
    }
    if (total != (uint64_t)mbuf.len) {
        PyErr_SetString(PyExc_ValueError, "h_batch: msg lengths do not sum to buffer");
        goto done;
    }
    result = PyBytes_FromStringAndSize(NULL, n * 32);
    if (!result) goto done;
    uint8_t *out = (uint8_t *)PyBytes_AS_STRING(result);
    const uint8_t *rs = (const uint8_t *)rbuf.buf;
    const uint8_t *as = (const uint8_t *)abuf.buf;
    const uint8_t *ms = (const uint8_t *)mbuf.buf;
    Py_BEGIN_ALLOW_THREADS
    size_t off = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        sha512_ctx c;
        uint8_t digest[64];
        sha512_init(&c);
        sha512_update(&c, rs + 32 * i, 32);
        sha512_update(&c, as + 32 * i, 32);
        sha512_update(&c, ms + off, (size_t)lens[i]);
        off += (size_t)lens[i];
        sha512_final(&c, digest);
        reduce512(digest, out + 32 * i);
    }
    Py_END_ALLOW_THREADS
done:
    PyBuffer_Release(&rbuf);
    PyBuffer_Release(&abuf);
    PyBuffer_Release(&mbuf);
    PyBuffer_Release(&lbuf);
    return result;
}

/* test hooks: sha512(data) and reduce512(digest) for directed differential
 * tests against hashlib / python ints */
static PyObject *py_sha512(PyObject *self, PyObject *args) {
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
    sha512_ctx c;
    uint8_t digest[64];
    sha512_init(&c);
    sha512_update(&c, (const uint8_t *)buf.buf, (size_t)buf.len);
    sha512_final(&c, digest);
    PyBuffer_Release(&buf);
    return PyBytes_FromStringAndSize((const char *)digest, 64);
}

static PyObject *py_reduce512(PyObject *self, PyObject *args) {
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
    if (buf.len != 64) {
        PyBuffer_Release(&buf);
        PyErr_SetString(PyExc_ValueError, "reduce512 wants 64 bytes");
        return NULL;
    }
    uint8_t out[32];
    reduce512((const uint8_t *)buf.buf, out);
    PyBuffer_Release(&buf);
    return PyBytes_FromStringAndSize((const char *)out, 32);
}

static PyMethodDef methods[] = {
    {"h_batch", py_h_batch, METH_VARARGS,
     "h_batch(r, a, msgs, lens) -> concatenated 32-byte h scalars"},
    {"sha512", py_sha512, METH_VARARGS, "test hook: one-shot SHA-512"},
    {"reduce512", py_reduce512, METH_VARARGS,
     "test hook: 64-byte LE value mod L as 32 LE bytes"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_hbatch", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit__hbatch(void) { return PyModule_Create(&moduledef); }

/* _mcode: native canonical codec for the mochi-tpu wire format.
 *
 * C re-implementation of mochi_tpu/protocol/codec.py (the "mcode" canonical
 * structural encoding: 1-byte tag, varint lengths, bytewise-sorted map keys).
 * The reference's hot wire path was native (Netty NIO + protobuf,
 * MochiClientInitializer.java:14-26); this is the TPU-framework analog so the
 * Python replicas never bottleneck on serialization (profiled at ~40% of
 * in-process cluster wall time under the pure-Python codec).
 *
 * Semantics are bit-for-bit identical to codec.py: same tags, same guards
 * (depth 32, 64 MiB lengths, 64-bit varints), same error taxonomy
 * (TypeError on unencodable input, ValueError on malformed bytes).
 * tests/test_codec.py runs differentially against the Python path.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

#define T_NONE 0x00
#define T_FALSE 0x01
#define T_TRUE 0x02
#define T_UINT 0x03
#define T_NINT 0x04
#define T_BYTES 0x05
#define T_STR 0x06
#define T_LIST 0x07
#define T_DICT 0x08

#define MAX_DEPTH 32
#define MAX_LEN (64 * 1024 * 1024)

/* ---------------------------------------------------------------- buffer */

typedef struct {
    unsigned char *data;
    Py_ssize_t len;
    Py_ssize_t cap;
} Buf;

static int buf_init(Buf *b) {
    b->cap = 256;
    b->len = 0;
    b->data = PyMem_Malloc(b->cap);
    return b->data ? 0 : -1;
}

static int buf_reserve(Buf *b, Py_ssize_t extra) {
    if (b->len + extra <= b->cap) return 0;
    Py_ssize_t cap = b->cap;
    while (cap < b->len + extra) cap *= 2;
    unsigned char *nd = PyMem_Realloc(b->data, cap);
    if (!nd) return -1;
    b->data = nd;
    b->cap = cap;
    return 0;
}

static int buf_put1(Buf *b, unsigned char c) {
    if (buf_reserve(b, 1) < 0) return -1;
    b->data[b->len++] = c;
    return 0;
}

static int buf_put(Buf *b, const void *src, Py_ssize_t n) {
    if (buf_reserve(b, n) < 0) return -1;
    memcpy(b->data + b->len, src, n);
    b->len += n;
    return 0;
}

static int buf_varint(Buf *b, unsigned long long n) {
    do {
        unsigned char byte = n & 0x7F;
        n >>= 7;
        if (n) byte |= 0x80;
        if (buf_put1(b, byte) < 0) return -1;
    } while (n);
    return 0;
}

/* ---------------------------------------------------------------- encode */

static int encode_value(Buf *b, PyObject *v, int depth);

typedef struct {
    const char *utf8;
    Py_ssize_t len;
    PyObject *key; /* borrowed from keys list */
} KeyEnt;

static int keyent_cmp(const void *pa, const void *pb) {
    const KeyEnt *a = pa, *kb = pb;
    Py_ssize_t n = a->len < kb->len ? a->len : kb->len;
    int c = memcmp(a->utf8, kb->utf8, (size_t)n);
    if (c) return c;
    return a->len < kb->len ? -1 : (a->len > kb->len ? 1 : 0);
}

static int encode_dict(Buf *b, PyObject *v, int depth) {
    Py_ssize_t n = PyDict_Size(v);
    if (buf_put1(b, T_DICT) < 0 || buf_varint(b, (unsigned long long)n) < 0)
        return -1;
    KeyEnt *ents = PyMem_Malloc(sizeof(KeyEnt) * (n ? n : 1));
    if (!ents) {
        PyErr_NoMemory();
        return -1;
    }
    Py_ssize_t pos = 0, i = 0;
    PyObject *key, *val;
    while (PyDict_Next(v, &pos, &key, &val)) {
        if (!PyUnicode_Check(key)) {
            PyErr_Format(PyExc_TypeError, "mcode dict keys must be str, got %.100s",
                         Py_TYPE(key)->tp_name);
            PyMem_Free(ents);
            return -1;
        }
        ents[i].utf8 = PyUnicode_AsUTF8AndSize(key, &ents[i].len);
        if (!ents[i].utf8) {
            PyMem_Free(ents);
            return -1;
        }
        ents[i].key = key;
        i++;
    }
    qsort(ents, (size_t)n, sizeof(KeyEnt), keyent_cmp);
    for (i = 0; i < n; i++) {
        if (buf_put1(b, T_STR) < 0 ||
            buf_varint(b, (unsigned long long)ents[i].len) < 0 ||
            buf_put(b, ents[i].utf8, ents[i].len) < 0) {
            PyMem_Free(ents);
            return -1;
        }
        /* dict may be mutated by __eq__ during PyDict_GetItem in pathological
         * cases; use the captured key object directly */
        PyObject *item = PyDict_GetItemWithError(v, ents[i].key);
        if (!item) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_RuntimeError, "mcode: dict changed during encode");
            PyMem_Free(ents);
            return -1;
        }
        if (encode_value(b, item, depth + 1) < 0) {
            PyMem_Free(ents);
            return -1;
        }
    }
    PyMem_Free(ents);
    return 0;
}

static int encode_value(Buf *b, PyObject *v, int depth) {
    if (depth > MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "mcode: structure too deep");
        return -1;
    }
    if (v == Py_None) return buf_put1(b, T_NONE);
    if (v == Py_True) return buf_put1(b, T_TRUE);
    if (v == Py_False) return buf_put1(b, T_FALSE);
    if (PyLong_Check(v)) {
        /* sign via comparison: Py_SIZE() is invalid for PyLong in 3.12 */
        PyObject *zero = PyLong_FromLong(0);
        if (!zero) return -1;
        int neg = PyObject_RichCompareBool(v, zero, Py_LT);
        Py_DECREF(zero);
        if (neg < 0) return -1;
        unsigned long long u;
        if (!neg) {
            u = PyLong_AsUnsignedLongLong(v);
            if (u == (unsigned long long)-1 && PyErr_Occurred()) {
                PyErr_Clear();
                PyErr_Format(PyExc_TypeError, "mcode int out of range");
                return -1;
            }
            if (buf_put1(b, T_UINT) < 0) return -1;
        } else {
            PyObject *inv = PyNumber_Invert(v); /* ~n == -1 - n >= 0 */
            if (!inv) return -1;
            u = PyLong_AsUnsignedLongLong(inv);
            Py_DECREF(inv);
            if (u == (unsigned long long)-1 && PyErr_Occurred()) {
                PyErr_Clear();
                PyErr_Format(PyExc_TypeError, "mcode int out of range");
                return -1;
            }
            if (buf_put1(b, T_NINT) < 0) return -1;
        }
        return buf_varint(b, u);
    }
    if (PyBytes_Check(v) || PyByteArray_Check(v) || PyMemoryView_Check(v)) {
        Py_buffer view;
        if (PyObject_GetBuffer(v, &view, PyBUF_SIMPLE) < 0) return -1;
        int rc = (buf_put1(b, T_BYTES) < 0 ||
                  buf_varint(b, (unsigned long long)view.len) < 0 ||
                  buf_put(b, view.buf, view.len) < 0)
                     ? -1
                     : 0;
        PyBuffer_Release(&view);
        return rc;
    }
    if (PyUnicode_Check(v)) {
        Py_ssize_t len;
        const char *utf8 = PyUnicode_AsUTF8AndSize(v, &len);
        if (!utf8) return -1;
        if (buf_put1(b, T_STR) < 0 ||
            buf_varint(b, (unsigned long long)len) < 0 ||
            buf_put(b, utf8, len) < 0)
            return -1;
        return 0;
    }
    if (PyList_Check(v) || PyTuple_Check(v)) {
        PyObject *fast = PySequence_Fast(v, "mcode: sequence");
        if (!fast) return -1;
        Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
        if (buf_put1(b, T_LIST) < 0 || buf_varint(b, (unsigned long long)n) < 0) {
            Py_DECREF(fast);
            return -1;
        }
        for (Py_ssize_t i = 0; i < n; i++) {
            if (encode_value(b, PySequence_Fast_GET_ITEM(fast, i), depth + 1) < 0) {
                Py_DECREF(fast);
                return -1;
            }
        }
        Py_DECREF(fast);
        return 0;
    }
    if (PyDict_Check(v)) return encode_dict(b, v, depth);
    PyErr_Format(PyExc_TypeError, "mcode cannot encode %.100s", Py_TYPE(v)->tp_name);
    return -1;
}

static PyObject *mcode_encode(PyObject *self, PyObject *arg) {
    Buf b;
    if (buf_init(&b) < 0) return PyErr_NoMemory();
    if (encode_value(&b, arg, 0) < 0) {
        PyMem_Free(b.data);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize((const char *)b.data, b.len);
    PyMem_Free(b.data);
    return out;
}

/* ---------------------------------------------------------------- decode */

typedef struct {
    const unsigned char *data;
    Py_ssize_t len;
    Py_ssize_t pos;
} Rd;

static int rd_varint(Rd *r, unsigned long long *out) {
    int shift = 0;
    unsigned long long result = 0;
    for (;;) {
        if (r->pos >= r->len) {
            PyErr_SetString(PyExc_ValueError, "mcode: truncated varint");
            return -1;
        }
        unsigned char byte = r->data[r->pos++];
        result |= (unsigned long long)(byte & 0x7F) << shift;
        if (!(byte & 0x80)) {
            /* match python: reject >= 2**64; shift<=63 plus final byte can
             * overflow only if shift==63 and byte>1 */
            if (shift == 63 && (byte & 0x7F) > 1) {
                PyErr_SetString(PyExc_ValueError, "mcode: varint out of 64-bit range");
                return -1;
            }
            /* canonical-only: a multi-byte varint ending in 0x00 carries no
             * bits in its last byte => non-minimal encoding.  The encoder
             * only emits minimal forms; accepting others would let two
             * distinct frames decode identically (signed-slice attacks). */
            if (shift > 0 && byte == 0) {
                PyErr_SetString(PyExc_ValueError, "mcode: non-canonical varint");
                return -1;
            }
            *out = result;
            return 0;
        }
        shift += 7;
        if (shift > 63) {
            PyErr_SetString(PyExc_ValueError, "mcode: varint too long");
            return -1;
        }
    }
}

static PyObject *rd_value(Rd *r, int depth) {
    if (depth > MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "mcode: structure too deep");
        return NULL;
    }
    if (r->pos >= r->len) {
        PyErr_SetString(PyExc_ValueError, "mcode: truncated input");
        return NULL;
    }
    unsigned char tag = r->data[r->pos++];
    unsigned long long n;
    switch (tag) {
    case T_NONE:
        Py_RETURN_NONE;
    case T_TRUE:
        Py_RETURN_TRUE;
    case T_FALSE:
        Py_RETURN_FALSE;
    case T_UINT:
        if (rd_varint(r, &n) < 0) return NULL;
        return PyLong_FromUnsignedLongLong(n);
    case T_NINT: {
        if (rd_varint(r, &n) < 0) return NULL;
        PyObject *pos_val = PyLong_FromUnsignedLongLong(n);
        if (!pos_val) return NULL;
        PyObject *out = PyNumber_Invert(pos_val); /* -1 - n */
        Py_DECREF(pos_val);
        return out;
    }
    case T_BYTES:
    case T_STR: {
        if (rd_varint(r, &n) < 0) return NULL;
        if (n > MAX_LEN) {
            PyErr_SetString(PyExc_ValueError, "mcode: length guard exceeded");
            return NULL;
        }
        if (r->pos + (Py_ssize_t)n > r->len) {
            PyErr_SetString(PyExc_ValueError, "mcode: truncated value");
            return NULL;
        }
        const char *p = (const char *)r->data + r->pos;
        r->pos += (Py_ssize_t)n;
        if (tag == T_BYTES) return PyBytes_FromStringAndSize(p, (Py_ssize_t)n);
        return PyUnicode_DecodeUTF8(p, (Py_ssize_t)n, NULL);
    }
    case T_LIST: {
        if (rd_varint(r, &n) < 0) return NULL;
        if (n > MAX_LEN) {
            PyErr_SetString(PyExc_ValueError, "mcode: list guard exceeded");
            return NULL;
        }
        /* Every element costs >= 1 byte (its tag), so a count larger than
         * the remaining input is guaranteed truncated.  Fail fast instead of
         * preallocating attacker-controlled (pre-authentication) memory. */
        if ((Py_ssize_t)n > r->len - r->pos) {
            PyErr_SetString(PyExc_ValueError, "mcode: truncated list");
            return NULL;
        }
        PyObject *list = PyList_New((Py_ssize_t)n);
        if (!list) return NULL;
        for (Py_ssize_t i = 0; i < (Py_ssize_t)n; i++) {
            PyObject *item = rd_value(r, depth + 1);
            if (!item) {
                Py_DECREF(list);
                return NULL;
            }
            PyList_SET_ITEM(list, i, item);
        }
        return list;
    }
    case T_DICT: {
        if (rd_varint(r, &n) < 0) return NULL;
        if (n > MAX_LEN) {
            PyErr_SetString(PyExc_ValueError, "mcode: dict guard exceeded");
            return NULL;
        }
        PyObject *dict = PyDict_New();
        if (!dict) return NULL;
        for (unsigned long long i = 0; i < n; i++) {
            PyObject *key = rd_value(r, depth + 1);
            if (!key) {
                Py_DECREF(dict);
                return NULL;
            }
            if (!PyUnicode_Check(key)) {
                Py_DECREF(key);
                Py_DECREF(dict);
                PyErr_SetString(PyExc_ValueError, "mcode: dict key must be str");
                return NULL;
            }
            PyObject *val = rd_value(r, depth + 1);
            if (!val) {
                Py_DECREF(key);
                Py_DECREF(dict);
                return NULL;
            }
            if (PyDict_SetItem(dict, key, val) < 0) {
                Py_DECREF(key);
                Py_DECREF(val);
                Py_DECREF(dict);
                return NULL;
            }
            Py_DECREF(key);
            Py_DECREF(val);
        }
        return dict;
    }
    default:
        PyErr_Format(PyExc_ValueError, "mcode: unknown tag 0x%x", (int)tag);
        return NULL;
    }
}

static PyObject *mcode_decode(PyObject *self, PyObject *arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
    Rd r = {(const unsigned char *)view.buf, view.len, 0};
    PyObject *out = rd_value(&r, 0);
    if (out && r.pos != r.len) {
        Py_DECREF(out);
        out = NULL;
        PyErr_SetString(PyExc_ValueError, "mcode: trailing bytes after value");
    }
    PyBuffer_Release(&view);
    return out;
}

/* Envelope fast path: decode a wire envelope (top-level 8-element list) and
 * additionally report the stream offset just past element 6.  The signing
 * bytes are "mochi.env\0" + T_LIST + varint(6) + wire[2:off6] — the signed
 * prefix is a contiguous slice of the wire encoding — so the receiver can
 * authenticate without re-encoding the payload tree (the dominant cost of
 * the Python signing_bytes() path, see protocol/messages.py).  Returns
 * (list_of_8_values, off6). */
static PyObject *mcode_decode_env(PyObject *self, PyObject *arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
    Rd r = {(const unsigned char *)view.buf, view.len, 0};
    PyObject *list = NULL;
    Py_ssize_t off6 = 0;
    if (r.len < 2 || r.data[0] != T_LIST) {
        PyErr_SetString(PyExc_ValueError, "mcode: envelope must be a list");
        goto fail;
    }
    r.pos = 1;
    unsigned long long n;
    if (rd_varint(&r, &n) < 0) goto fail;
    if (n != 8) {
        PyErr_Format(PyExc_ValueError, "mcode: envelope needs 8 elements, got %llu", n);
        goto fail;
    }
    list = PyList_New(8);
    if (!list) goto fail;
    for (Py_ssize_t i = 0; i < 8; i++) {
        PyObject *item = rd_value(&r, 1);
        if (!item) goto fail;
        PyList_SET_ITEM(list, i, item);
        if (i == 5) off6 = r.pos;
    }
    if (r.pos != r.len) {
        PyErr_SetString(PyExc_ValueError, "mcode: trailing bytes after value");
        goto fail;
    }
    PyBuffer_Release(&view);
    return Py_BuildValue("(Nn)", list, off6);
fail:
    Py_XDECREF(list);
    PyBuffer_Release(&view);
    return NULL;
}

static PyMethodDef mcode_methods[] = {
    {"encode", mcode_encode, METH_O, "Canonically encode a structural value to bytes."},
    {"decode", mcode_decode, METH_O, "Decode mcode bytes; rejects trailing garbage."},
    {"decode_env", mcode_decode_env, METH_O,
     "Decode an 8-element envelope list; returns (values, offset_past_elem6)."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef mcode_module = {
    PyModuleDef_HEAD_INIT, "_mcode", "Native mcode canonical codec.", -1, mcode_methods,
};

PyMODINIT_FUNC PyInit__mcode(void) { return PyModule_Create(&mcode_module); }

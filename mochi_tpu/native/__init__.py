"""Native (C) runtime components, lazily built with the system toolchain.

The reference's performance-critical host path is native (Netty's epoll loop,
JDK MessageDigest intrinsics — SURVEY.md §2.9); the TPU framework keeps its
Python control plane but moves hot host loops to C extensions:

* ``_mcode`` — the canonical wire/signing codec (mcode.c).
* ``_hbatch`` — batched SHA-512(R||A||M) mod L (the per-item half of the
  verifier's host prepare) AND the full host Ed25519 verification engine
  (``verify_batch``: Straus ladder on 51-bit limbs) that
  ``crypto/keys.verify`` routes to on hosts without the OpenSSL wheel
  (hbatch.c).

Build model: compiled on first use into this package directory with the
system compiler (cc/gcc), cached by source mtime; if no toolchain is
available the callers fall back to the pure-Python implementations, so the
framework never *requires* the native path — it only gets faster with one.
"""

from __future__ import annotations

import importlib.util
import logging
import os
import subprocess
import sys
import sysconfig
import tempfile
from types import ModuleType
from typing import Optional

LOG = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_cached: dict = {}
_build_attempted: set = set()


def _so_path(name: str) -> str:
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_DIR, f"{name}{suffix}")


def _needs_build(so: str, src: str) -> bool:
    try:
        return os.path.getmtime(so) < os.path.getmtime(src)
    except OSError:
        return True


def _build(src: str, so: str) -> bool:
    cc = os.environ.get("CC", "cc")
    include = sysconfig.get_paths()["include"]
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
    os.close(fd)
    cmd = [cc, "-O2", "-shared", "-fPIC", f"-I{include}", src, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            LOG.warning("native build of %s failed:\n%s", src, proc.stderr)
            os.unlink(tmp)
            return False
        os.replace(tmp, so)  # atomic: concurrent builders race benignly
        return True
    except (OSError, subprocess.SubprocessError) as exc:
        LOG.warning("native build unavailable (%s): %s", src, exc)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _get_native(name: str) -> Optional[ModuleType]:
    """The compiled ``_<name>`` module, building <name>.c if needed; None
    if no toolchain (callers then use the pure-Python implementations)."""
    if name in _cached:
        return _cached[name]
    if os.environ.get("MOCHI_NO_NATIVE"):
        return None
    src = os.path.join(_DIR, f"{name[1:]}.c")  # _mcode -> mcode.c
    so = _so_path(name)
    if _needs_build(so, src):
        if name in _build_attempted:
            return None
        _build_attempted.add(name)
        if not _build(src, so):
            return None
    try:
        spec = importlib.util.spec_from_file_location(
            f"mochi_tpu.native.{name}", so
        )
        assert spec is not None and spec.loader is not None
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sys.modules[f"mochi_tpu.native.{name}"] = mod
        _cached[name] = mod
        return mod
    except Exception:
        LOG.exception("native %s failed to load; using pure-Python path", name)
        return None


def get_mcode() -> Optional[ModuleType]:
    return _get_native("_mcode")


def get_hbatch() -> Optional[ModuleType]:
    return _get_native("_hbatch")

"""Pallas TPU kernel for batched Ed25519 verification — EVALUATED AND SHELVED.

Round-2 verdict (the VERDICT.md "prove or kill" item, measured on a real
v5e): **the XLA path wins; this kernel is not the default and should not
be.**  The evidence:

* The round-1 kernel's reason to exist — the limbs-on-lanes layout — was
  folded into the XLA path (:mod:`curve`/:mod:`field` are limbs-leading
  ``(17, B)`` everywhere), which then hit 23.4k sigs/s at batch 4096,
  5.5x the OpenSSL baseline, with a 40 s cold compile.
* Getting THIS kernel through Mosaic lowering required three rounds of
  workarounds (tables as operands instead of closure constants; scalar
  const materialization; masked digit extraction + unrolled table build —
  Mosaic TC has no ``dynamic_slice``/``scatter`` on values), after which it
  lowers — but the Mosaic compile of the resulting ~10k-op kernel did not
  finish within **15 minutes** at block 128 or 256 (two timed attempts).
  A >15-minute compile for a <1-minute XLA alternative is an operational
  non-starter, independent of eventual runtime.
* The pipeline's intermediates for one 256-lane block are a few MB — XLA's
  own fusion already keeps the hot loop VMEM-resident (the batch-4096 peak
  and its >4096 spill cliff show VMEM, not HBM streaming, is the binding
  constraint either way).

The kernel stays for (a) differential documentation of the Mosaic-safe
op-set (``curve.MOSAIC_SAFE``), (b) interpret-mode tests that pin the
shared ``verify_core`` semantics, (c) a baseline if Mosaic's compile times
improve.  Enable in benchmarks with ``MOCHI_BENCH_PALLAS=1``.

Host-side prep (SHA-512, mod-L, canonicity, bit->digit packing) is shared
with the XLA path; semantics are bit-identical (differential test:
``tests/test_pallas_verify.py``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import curve
from . import field as F

BLOCK = 256  # signatures per grid program (multiple of 128 lanes)


def _kernel(
    y_a_ref, sign_a_ref, y_r_ref, sign_r_ref, s_dig_ref, h_dig_ref,
    ypx_ref, ymx_ref, xy2d_ref, out_ref,
):
    prev_const, prev_safe = F.CONST_MODE, curve.MOSAIC_SAFE
    # Mosaic-safe modes: no closure-captured array constants (limb constants
    # materialize as per-limb scalar fills; the Niels basepoint tables arrive
    # as kernel operands ypx/ymx/xy2d instead of literals) and no
    # dynamic_slice (masked digit extraction + unrolled table build —
    # curve.MOSAIC_SAFE).  The pad/reshape column skew stays: the reshapes
    # only touch leading (untiled) axes plus lane-dim splits Mosaic accepts.
    F.CONST_MODE = "scalars"
    curve.MOSAIC_SAFE = True
    try:
        b_tab = (
            ypx_ref[:, :][..., None],
            ymx_ref[:, :][..., None],
            xy2d_ref[:, :][..., None],
        )
        bitmap = curve.verify_core(
            y_a_ref[:, :],
            sign_a_ref[0, :],
            y_r_ref[:, :],
            sign_r_ref[0, :],
            s_dig_ref[:, :],
            h_dig_ref[:, :],
            b_tab=b_tab,
        )
    finally:
        F.CONST_MODE = prev_const
        curve.MOSAIC_SAFE = prev_safe
    out_ref[0, :] = bitmap.astype(jnp.int32)


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def verify_prepared_pallas(
    y_a, sign_a, y_r, sign_r, s_bits, h_bits,
    block: int = BLOCK,
    interpret: Optional[bool] = None,
):
    """Drop-in for ``curve.verify_prepared`` running the Pallas kernel.

    Accepts the same host-prepared ``(batch, ...)`` tensors; transposes to
    the limbs-leading layout in XLA (one fused transpose each way), pads the
    batch to a multiple of ``block`` and grids over blocks.
    """
    if interpret is None:
        interpret = _use_interpret()
    n = y_a.shape[0]
    m = ((n + block - 1) // block) * block
    pad = m - n
    if pad:
        y_a = jnp.pad(y_a, ((0, pad), (0, 0)))
        y_r = jnp.pad(y_r, ((0, pad), (0, 0)))
        sign_a = jnp.pad(sign_a, ((0, pad),))
        sign_r = jnp.pad(sign_r, ((0, pad),))
        s_bits = jnp.pad(s_bits, ((0, pad), (0, 0)))
        h_bits = jnp.pad(h_bits, ((0, pad), (0, 0)))

    # (batch, 17) -> (17, batch); bits -> (64, batch) digits
    y_a_t = y_a.T
    y_r_t = y_r.T
    s_dig = curve.digits4_from_bits(s_bits.T)
    h_dig = curve.digits4_from_bits(h_bits.T)
    sign_a_t = sign_a[None, :]
    sign_r_t = sign_r[None, :]

    grid = (m // block,)
    limb_spec = pl.BlockSpec(
        (F.NLIMBS, block), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    dig_spec = pl.BlockSpec((64, block), lambda i: (0, i), memory_space=pltpu.VMEM)
    sign_spec = pl.BlockSpec((1, block), lambda i: (0, i), memory_space=pltpu.VMEM)
    # Niels basepoint tables: same (9, 17) block for every grid program
    # (signed 4-bit windows, curve.N_TABLE entries).
    tab_spec = pl.BlockSpec(
        (curve.N_TABLE, F.NLIMBS), lambda i: (0, 0), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((1, m), jnp.int32),
        grid=grid,
        in_specs=[limb_spec, sign_spec, limb_spec, sign_spec, dig_spec, dig_spec,
                  tab_spec, tab_spec, tab_spec],
        out_specs=pl.BlockSpec((1, block), lambda i: (0, i), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(
        y_a_t, sign_a_t, y_r_t, sign_r_t, s_dig, h_dig,
        jnp.asarray(curve._B_TAB_YPX),
        jnp.asarray(curve._B_TAB_YMX),
        jnp.asarray(curve._B_TAB_XY2D),
    )
    return out[0, :n].astype(bool)

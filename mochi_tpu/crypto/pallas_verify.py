"""Pallas TPU kernel for batched Ed25519 verification.

The XLA path (:mod:`curve`/:mod:`field`) lays field elements out as
``(batch, 16)`` — limbs on the 128-wide lane axis, wasting 7/8 of each VPU
lane group and letting XLA decide fusion boundaries (HBM round-trips between
them).  This kernel owns the layout instead (pallas_guide.md: tiling is
(sublane, lane) with lane=128):

* a field element is ``(16, BLOCK)`` int32 — **limbs on sublanes, batch on
  lanes**, so every elementwise op runs on full 128-lane vectors;
* the whole pipeline (decompress x2 + 64-window double-scalar-mul) runs in
  ONE kernel: every intermediate stays in VMEM/registers, nothing spills to
  HBM between "ops";
* the per-item table lookups of the windowed ladder become branchless
  masked-select sums (data-dependent per-lane gathers don't vectorize on the
  VPU; 16 masked adds do);
* grid = batch/BLOCK, each program verifying one block of signatures.

Host-side prep (SHA-512, mod-L, canonicity, bit->digit packing) is shared
with the XLA path; semantics are bit-identical (differential test:
``tests/test_pallas_verify.py``).  On CPU the kernel runs in interpret mode
— slow but exact — so the TPU path is testable anywhere (SURVEY.md §7
build-plan step (e)).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import curve
from . import field as F

BLOCK = 256  # signatures per grid program (multiple of 128 lanes)

MASK = F.MASK
RADIX = F.RADIX
NLIMBS = F.NLIMBS

# ------------------------------------------------------------------ field ops
# Limbs-leading variants of mochi_tpu.crypto.field: arrays are (16, ...lanes).
# Same radix-2^16 schoolbook + signed sequential carry semantics, so results
# match the XLA path limb for limb.


def _carry_chain_ll(cols):
    c = jnp.zeros(cols.shape[1:], dtype=jnp.int32)
    out = []
    for k in range(NLIMBS):
        t = cols[k] + c
        out.append(t & MASK)
        c = t >> RADIX
    return jnp.stack(out, axis=0), c


def _fold_carry_ll(limbs, cout):
    cols = limbs.at[0].add(38 * cout)
    limbs2, cout2 = _carry_chain_ll(cols)
    cols3 = limbs2.at[0].add(38 * cout2)
    limbs3, _ = _carry_chain_ll(cols3)
    return limbs3


def add_ll(a, b):
    limbs, cout = _carry_chain_ll(a + b)
    return _fold_carry_ll(limbs, cout)


_TWO_P = np.full(NLIMBS, MASK, dtype=np.int32)
_TWO_P[0] = MASK - 37


def sub_ll(a, b):
    # 2p is added limb-wise as python-int scalars: pallas kernels cannot
    # capture array constants, but scalar literals are fine.
    cols = jnp.stack([a[k] + int(_TWO_P[k]) for k in range(NLIMBS)], axis=0) - b
    limbs, cout = _carry_chain_ll(cols)
    return _fold_carry_ll(limbs, cout)


def neg_ll(a):
    return sub_ll(jnp.zeros_like(a), a)


def mul_ll(a, b):
    """Schoolbook multiply, anti-diagonal accumulation by slice-shift (the
    axis-0 mirror of ``field.mul`` — keeps the traced graph ~300 ops/mul so
    the whole-ladder kernel stays compilable)."""
    au = a.astype(jnp.uint32)
    bu = b.astype(jnp.uint32)
    lanes = a.shape[1:]
    prod = au[:, None] * bu[None, :]  # (16, 16, lanes)
    lo = (prod & MASK).astype(jnp.int32)
    hi = (prod >> RADIX).astype(jnp.int32)
    cols = jnp.zeros((2 * NLIMBS, *lanes), dtype=jnp.int32)
    for i in range(NLIMBS):
        cols = lax.dynamic_update_slice_in_dim(
            cols,
            lax.dynamic_slice_in_dim(cols, i, NLIMBS, axis=0) + lo[i],
            i,
            axis=0,
        )
        cols = lax.dynamic_update_slice_in_dim(
            cols,
            lax.dynamic_slice_in_dim(cols, i + 1, NLIMBS, axis=0) + hi[i],
            i + 1,
            axis=0,
        )
    folded = cols[:NLIMBS] + 38 * cols[NLIMBS:]
    limbs, cout = _carry_chain_ll(folded)
    return _fold_carry_ll(limbs, cout)


def square_ll(a):
    return mul_ll(a, a)


def mul_small_ll(a, k: int):
    cols = a * k
    limbs, cout = _carry_chain_ll(cols)
    return _fold_carry_ll(limbs, cout)


_P_LIMBS = [int(v) for v in F.int_to_limbs(F.P_INT)]


def canonical_ll(a):
    """Unique representative < p (mirrors field.canonical).  The modulus
    enters as scalar literals (no array constants in pallas kernels)."""
    limbs, cout = _carry_chain_ll(a)
    limbs = _fold_carry_ll(limbs, cout)

    def cond_sub_p(x):
        ge = _ge_p_ll(x)
        diff, _ = _carry_chain_ll(
            jnp.stack([x[k] - _P_LIMBS[k] for k in range(NLIMBS)], axis=0)
        )
        # ignore borrow: only applied when x >= p
        return jnp.where(ge, diff, x)

    limbs = cond_sub_p(limbs)
    limbs = cond_sub_p(limbs)
    return limbs


def _ge_p_ll(x):
    """x >= p, comparing limbs most-significant first (scalar p limbs)."""
    gt = jnp.zeros(x.shape[1:], dtype=bool)
    lt = jnp.zeros(x.shape[1:], dtype=bool)
    for k in range(NLIMBS - 1, -1, -1):
        limb_gt = (x[k] > _P_LIMBS[k]) & ~gt & ~lt
        limb_lt = (x[k] < _P_LIMBS[k]) & ~gt & ~lt
        gt = gt | limb_gt
        lt = lt | limb_lt
    return ~lt


def eq_ll(a, b):
    diff = sub_ll(a, b)
    can = canonical_ll(diff)
    return jnp.all(can == 0, axis=0)


def is_zero_ll(a):
    can = canonical_ll(a)
    return jnp.all(can == 0, axis=0)


def select_ll(cond, a, b):
    return jnp.where(cond[None, ...], a, b)


def _square_n_ll(a, n: int):
    """a^(2^n): n squarings as one fori_loop (graph stays one body)."""
    return lax.fori_loop(0, n, lambda i, x: square_ll(x), a)


def pow_p58_ll(a):
    """a^((p-5)/8) = a^(2^252 - 3) via the standard ed25519 addition chain
    (ref10 pow22523 structure): ~12 multiplies + fori_loop squaring runs."""
    z2 = square_ll(a)  # 2
    z8 = _square_n_ll(z2, 2)  # 8
    z9 = mul_ll(a, z8)  # 9
    z11 = mul_ll(z2, z9)  # 11
    z22 = square_ll(z11)  # 22
    z_5_0 = mul_ll(z9, z22)  # 2^5 - 1
    z_10_0 = mul_ll(_square_n_ll(z_5_0, 5), z_5_0)  # 2^10 - 1
    z_20_0 = mul_ll(_square_n_ll(z_10_0, 10), z_10_0)  # 2^20 - 1
    z_40_0 = mul_ll(_square_n_ll(z_20_0, 20), z_20_0)  # 2^40 - 1
    z_50_0 = mul_ll(_square_n_ll(z_40_0, 10), z_10_0)  # 2^50 - 1
    z_100_0 = mul_ll(_square_n_ll(z_50_0, 50), z_50_0)  # 2^100 - 1
    z_200_0 = mul_ll(_square_n_ll(z_100_0, 100), z_100_0)  # 2^200 - 1
    z_250_0 = mul_ll(_square_n_ll(z_200_0, 50), z_50_0)  # 2^250 - 1
    return mul_ll(_square_n_ll(z_250_0, 2), a)  # 2^252 - 3


# ------------------------------------------------------------------ curve ops
# Extended twisted-Edwards (X:Y:Z:T), same formulas as mochi_tpu.crypto.curve
# but over the limbs-leading field ops.  A point is a 4-tuple of (16, lanes).

_D2 = (2 * F.D_INT) % F.P_INT


def const_ll(c_int: int, lanes) -> jnp.ndarray:
    """Field constant materialized from scalar literals (pallas kernels
    cannot capture array constants; 16 broadcast fills are fine)."""
    c_limbs = [int(v) for v in F.int_to_limbs(c_int)]
    return jnp.stack(
        [jnp.full(lanes, l, dtype=jnp.int32) for l in c_limbs], axis=0
    )


def mul_const_ll(a, c_int: int):
    return mul_ll(a, const_ll(c_int, a.shape[1:]))


def identity_ll(lanes):
    zero = jnp.zeros((NLIMBS, *lanes), jnp.int32)
    one = zero.at[0].set(1)
    return (zero, one, one, zero)


def add_pt_ll(p, q):
    px, py, pz, pt = p
    qx, qy, qz, qt = q
    a = mul_ll(sub_ll(py, px), sub_ll(qy, qx))
    b = mul_ll(add_ll(py, px), add_ll(qy, qx))
    c = mul_ll(mul_const_ll(pt, _D2), qt)
    d = mul_ll(add_ll(pz, pz), qz)
    e = sub_ll(b, a)
    f = sub_ll(d, c)
    g = add_ll(d, c)
    h = add_ll(b, a)
    return (mul_ll(e, f), mul_ll(g, h), mul_ll(f, g), mul_ll(e, h))


def double_pt_ll(p):
    px, py, pz, _ = p
    a = square_ll(px)
    b = square_ll(py)
    c = mul_small_ll(square_ll(pz), 2)
    h = add_ll(a, b)
    e = sub_ll(h, square_ll(add_ll(px, py)))
    g = sub_ll(a, b)
    f = add_ll(c, g)
    return (mul_ll(e, f), mul_ll(g, h), mul_ll(f, g), mul_ll(e, h))


def negate_pt_ll(p):
    px, py, pz, pt = p
    return (neg_ll(px), py, pz, neg_ll(pt))


def madd_niels_ll(p, ypx, ymx, xy2d):
    px, py, pz, pt = p
    a = mul_ll(sub_ll(py, px), ymx)
    b = mul_ll(add_ll(py, px), ypx)
    c = mul_ll(xy2d, pt)
    d = add_ll(pz, pz)
    e = sub_ll(b, a)
    f = sub_ll(d, c)
    g = add_ll(d, c)
    h = add_ll(b, a)
    return (mul_ll(e, f), mul_ll(g, h), mul_ll(f, g), mul_ll(e, h))


def decompress_ll(y, sign):
    """RFC 8032 decoding, limbs-leading (mirrors curve.decompress)."""
    lanes = y.shape[1:]
    yy = square_ll(y)
    one = jnp.zeros((NLIMBS, *lanes), jnp.int32).at[0].set(1)
    u = sub_ll(yy, one)
    v = add_ll(mul_const_ll(yy, F.D_INT), one)
    v3 = mul_ll(square_ll(v), v)
    v7 = mul_ll(square_ll(v3), v)
    x = mul_ll(mul_ll(u, v3), pow_p58_ll(mul_ll(u, v7)))
    vxx = mul_ll(v, square_ll(x))
    root_ok = eq_ll(vxx, u)
    root_neg = eq_ll(vxx, neg_ll(u))
    x = select_ll(root_neg, mul_const_ll(x, F.SQRT_M1_INT), x)
    ok = root_ok | root_neg
    x_can = canonical_ll(x)
    x_is_zero = is_zero_ll(x)
    ok = ok & ~(x_is_zero & (sign == 1))
    flip = (x_can[0] & 1) != sign
    x = select_ll(flip, neg_ll(x), x)
    return (x, y, one, mul_ll(x, y)), ok


def _select_entry(table, idx, n_entries: int):
    """Branchless per-lane table lookup: sum of masked entries.

    ``table``: tuple of arrays with entry axis 0 — each ``(n_entries, 16,
    lanes)``; ``idx``: (lanes,) int32.  Data-dependent per-lane gathers don't
    vectorize on the VPU; n_entries masked adds do.
    """
    out = []
    for coord in table:
        acc = jnp.zeros_like(coord[0])
        for e in range(n_entries):
            acc = acc + jnp.where((idx == e)[None, ...], coord[e], 0)
        out.append(acc)
    return tuple(out)


def _small_multiples_ll(p):
    """[0..15]P stacked on axis 0 — built by 15 chained additions inside ONE
    fori_loop body (vs 14 unrolled point ops: ~10x smaller traced graph)."""
    lanes = p[0].shape[1:]
    table = tuple(
        jnp.zeros((16, NLIMBS, *lanes), jnp.int32) for _ in range(4)
    )
    ident = identity_ll(lanes)
    table = tuple(
        t.at[0].set(c) for t, c in zip(table, ident)
    )

    # chain: entry[k] = entry[k-1] + P, carried as (table, prev_point)
    def chain(k, carry):
        table, prev = carry
        cur = add_pt_ll(prev, p)
        table = tuple(
            lax.dynamic_update_index_in_dim(t, c, k, axis=0)
            for t, c in zip(table, cur)
        )
        return (table, cur)

    table, _ = lax.fori_loop(1, 16, chain, (table, ident))
    return table


def verify_block_ll(y_a, sign_a, y_r, sign_r, s_dig, h_dig):
    """Verify one block: limbs-leading tensors -> bool (lanes,) bitmap.

    Same pipeline as ``curve.verify_prepared`` + ``double_scalar_mul_windowed``:
    decompress A and R, Q = [S]B + [h](-A) via 64 4-bit windows, compare Q to
    R projectively.
    """
    lanes = y_a.shape[1:]
    a_point, ok_a = decompress_ll(y_a, sign_a)
    r_point, ok_r = decompress_ll(y_r, sign_r)
    na = negate_pt_ll(a_point)
    a_tab = _small_multiples_ll(na)
    # basepoint Niels tables, materialized from scalar literals once
    # (outside the window loop): (16 entries, 16 limbs, lanes)
    def _b_table(rows):
        return jnp.stack(
            [const_ll(F.limbs_to_int(row), lanes) for row in rows], axis=0
        )

    b_tab = (
        _b_table(curve._B_TAB_YPX),
        _b_table(curve._B_TAB_YMX),
        _b_table(curve._B_TAB_XY2D),
    )

    def body(i, q):
        w = 63 - i
        q = double_pt_ll(double_pt_ll(double_pt_ll(double_pt_ll(q))))
        hd = lax.dynamic_index_in_dim(h_dig, w, axis=0, keepdims=False)
        entry = _select_entry(a_tab, hd, 16)
        q = add_pt_ll(q, entry)
        sd = lax.dynamic_index_in_dim(s_dig, w, axis=0, keepdims=False)
        nypx, nymx, nxy2d = _select_entry(b_tab, sd, 16)
        return madd_niels_ll(q, nypx, nymx, nxy2d)

    q = lax.fori_loop(0, 64, body, identity_ll(lanes))
    qx, qy, qz, _ = q
    rx, ry, _, _ = r_point
    eq_x = eq_ll(qx, mul_ll(rx, qz))
    eq_y = eq_ll(qy, mul_ll(ry, qz))
    return ok_a & ok_r & eq_x & eq_y


# ------------------------------------------------------------------ kernel


def _kernel(y_a_ref, sign_a_ref, y_r_ref, sign_r_ref, s_dig_ref, h_dig_ref, out_ref):
    bitmap = verify_block_ll(
        y_a_ref[:, :],
        sign_a_ref[0, :],
        y_r_ref[:, :],
        sign_r_ref[0, :],
        s_dig_ref[:, :],
        h_dig_ref[:, :],
    )
    out_ref[0, :] = bitmap.astype(jnp.int32)


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def verify_prepared_pallas(
    y_a, sign_a, y_r, sign_r, s_bits, h_bits,
    block: int = BLOCK,
    interpret: Optional[bool] = None,
):
    """Drop-in for ``curve.verify_prepared`` running the Pallas kernel.

    Accepts the same host-prepared ``(batch, ...)`` tensors; transposes to
    the kernel's limbs-leading layout in XLA (one fused transpose each way),
    pads the batch to a multiple of ``block`` and grids over blocks.
    """
    if interpret is None:
        interpret = _use_interpret()
    n = y_a.shape[0]
    m = ((n + block - 1) // block) * block
    pad = m - n
    if pad:
        y_a = jnp.pad(y_a, ((0, pad), (0, 0)))
        y_r = jnp.pad(y_r, ((0, pad), (0, 0)))
        sign_a = jnp.pad(sign_a, ((0, pad),))
        sign_r = jnp.pad(sign_r, ((0, pad),))
        s_bits = jnp.pad(s_bits, ((0, pad), (0, 0)))
        h_bits = jnp.pad(h_bits, ((0, pad), (0, 0)))

    # (batch, 16) -> (16, batch); digits (batch, 64) -> (64, batch)
    y_a_t = y_a.T
    y_r_t = y_r.T
    s_dig = curve.digits4_from_bits(s_bits).T
    h_dig = curve.digits4_from_bits(h_bits).T
    sign_a_t = sign_a[None, :]
    sign_r_t = sign_r[None, :]

    grid = (m // block,)
    limb_spec = pl.BlockSpec(
        (NLIMBS, block), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    dig_spec = pl.BlockSpec((64, block), lambda i: (0, i), memory_space=pltpu.VMEM)
    sign_spec = pl.BlockSpec((1, block), lambda i: (0, i), memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((1, m), jnp.int32),
        grid=grid,
        in_specs=[limb_spec, sign_spec, limb_spec, sign_spec, dig_spec, dig_spec],
        out_specs=pl.BlockSpec((1, block), lambda i: (0, i), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(y_a_t, sign_a_t, y_r_t, sign_r_t, s_dig, h_dig)
    return out[0, :n].astype(bool)

"""Authenticated session keys: X25519 ECDH bound to Ed25519 identities.

Why this exists (the trust-model argument, VERDICT r1 task 7): envelope
authentication is a *channel* property — each hop only needs the receiver to
know the bytes came from the claimed peer.  A MAC under a session key gives
exactly that at ~2 µs/message.  Ed25519 signatures (~120 µs verify on this
host) are reserved for the protocol artifacts that must be *transferable* —
MultiGrants inside write certificates, which replicas hand to other replicas
as third-party-checkable quorum evidence.  A MAC could never serve there
(anyone holding the session key can forge one); a channel doesn't need a
signature.  The reference has neither (it never authenticates anything —
SURVEY.md preamble); this completes its declared design the fast way.

Handshake (piggybacked on the normal envelope transport, see
``protocol.messages.SessionInitToServer``): initiator sends a fresh X25519
public key + nonce in an Ed25519-signed envelope; responder replies with its
own, also signed.  Both derive

    key = HKDF-SHA256(X25519(a, B), info = ids || nonces || "mochi.session")

A MITM cannot substitute X25519 keys without breaking the Ed25519 envelope
signatures, and the nonces bind the key to this handshake.  Session MACs
then cover the same canonical envelope bytes the signature would have
(``Envelope.signing_bytes``), so replay characteristics are identical to the
signature scheme (msg_id/timestamp are covered; the store's idempotency
handles redelivery either way).
"""

from __future__ import annotations

import hashlib
import hmac
import os
from dataclasses import dataclass

try:  # optional accelerator — see crypto/keys.py; fallback is pure Python
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.serialization import Encoding, PublicFormat

    _HAVE_HOST_CRYPTO = True
except ImportError:  # pragma: no cover - exercised on bare environments
    _HAVE_HOST_CRYPTO = False

_INFO = b"mochi.session.v1"


@dataclass(frozen=True)
class Handshake:
    """One side's ephemeral handshake state.

    ``private`` is an ``X25519PrivateKey`` handle when OpenSSL is available,
    or the raw 32-byte scalar when running on the pure-Python fallback —
    :func:`derive_key` dispatches on the type, so mixed clusters (one side
    with OpenSSL, one without) derive the same session key.
    """

    private: object
    public_bytes: bytes
    nonce: bytes


def new_handshake() -> Handshake:
    if not _HAVE_HOST_CRYPTO:
        from . import hostfallback

        seed = os.urandom(32)
        return Handshake(seed, hostfallback.x25519_public(seed), os.urandom(16))
    priv = X25519PrivateKey.generate()
    pub = priv.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
    return Handshake(priv, pub, os.urandom(16))


def derive_key(
    hs: Handshake,
    peer_public: bytes,
    peer_nonce: bytes,
    initiator_id: str,
    responder_id: str,
    initiated: bool,
) -> bytes:
    """Both sides call this with the SAME (initiator, responder) ordering."""
    if isinstance(hs.private, (bytes, bytearray)):
        from . import hostfallback

        shared = hostfallback.x25519(hs.private, peer_public)
    else:
        shared = hs.private.exchange(X25519PublicKey.from_public_bytes(peer_public))
    if initiated:
        nonces = hs.nonce + peer_nonce
    else:
        nonces = peer_nonce + hs.nonce
    material = (
        shared
        + initiator_id.encode()
        + b"\x00"
        + responder_id.encode()
        + b"\x00"
        + nonces
    )
    # Single-block HKDF-extract/expand (output = one SHA256 block)
    prk = hmac.new(_INFO, material, hashlib.sha256).digest()
    return hmac.new(prk, b"\x01" + _INFO, hashlib.sha256).digest()


def mac(session_key: bytes, data: bytes) -> bytes:
    # hmac.digest is the one-shot C path (no HMAC-object construction);
    # on the cluster hot path this runs four times per message hop.
    return hmac.digest(session_key, data, "sha256")


def seal(envelope, session_key: bytes):
    """Attach the session MAC over an envelope's canonical auth bytes —
    the one sealing idiom shared by clients, replicas and the verifier
    service."""
    return envelope.with_mac(mac(session_key, envelope.signing_bytes()))


def mac_ok(session_key: bytes, data: bytes, tag: bytes) -> bool:
    return hmac.compare_digest(mac(session_key, data), tag)

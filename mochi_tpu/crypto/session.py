"""Authenticated session keys: X25519 ECDH bound to Ed25519 identities.

Why this exists (the trust-model argument, VERDICT r1 task 7): envelope
authentication is a *channel* property — each hop only needs the receiver to
know the bytes came from the claimed peer.  A MAC under a session key gives
exactly that at ~2 µs/message.  Ed25519 signatures (~120 µs verify on this
host) are reserved for the protocol artifacts that must be *transferable* —
MultiGrants inside write certificates, which replicas hand to other replicas
as third-party-checkable quorum evidence.  A MAC could never serve there
(anyone holding the session key can forge one); a channel doesn't need a
signature.  The reference has neither (it never authenticates anything —
SURVEY.md preamble); this completes its declared design the fast way.

Handshake (piggybacked on the normal envelope transport, see
``protocol.messages.SessionInitToServer``): initiator sends a fresh X25519
public key + nonce in an Ed25519-signed envelope; responder replies with its
own, also signed.  Both derive

    key = HKDF-SHA256(X25519(a, B), info = ids || nonces || "mochi.session")

A MITM cannot substitute X25519 keys without breaking the Ed25519 envelope
signatures, and the nonces bind the key to this handshake.  Session MACs
then cover the same canonical envelope bytes the signature would have
(``Envelope.signing_bytes``), so replay characteristics are identical to the
signature scheme (msg_id/timestamp are covered; the store's idempotency
handles redelivery either way).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

try:  # optional accelerator — see crypto/keys.py; fallback is pure Python
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.serialization import Encoding, PublicFormat

    _HAVE_HOST_CRYPTO = True
except ImportError:  # pragma: no cover - exercised on bare environments
    _HAVE_HOST_CRYPTO = False

_INFO = b"mochi.session.v1"


@dataclass(frozen=True)
class Handshake:
    """One side's ephemeral handshake state.

    ``private`` is an ``X25519PrivateKey`` handle when OpenSSL is available,
    or the raw 32-byte scalar when running on the pure-Python fallback —
    :func:`derive_key` dispatches on the type, so mixed clusters (one side
    with OpenSSL, one without) derive the same session key.
    """

    private: object
    public_bytes: bytes
    nonce: bytes


def new_handshake() -> Handshake:
    if not _HAVE_HOST_CRYPTO:
        from . import hostfallback

        seed = os.urandom(32)
        return Handshake(seed, hostfallback.x25519_public(seed), os.urandom(16))
    priv = X25519PrivateKey.generate()
    pub = priv.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
    return Handshake(priv, pub, os.urandom(16))


def derive_key(
    hs: Handshake,
    peer_public: bytes,
    peer_nonce: bytes,
    initiator_id: str,
    responder_id: str,
    initiated: bool,
) -> bytes:
    """Both sides call this with the SAME (initiator, responder) ordering."""
    if isinstance(hs.private, (bytes, bytearray)):
        from . import hostfallback

        shared = hostfallback.x25519(hs.private, peer_public)
    else:
        shared = hs.private.exchange(X25519PublicKey.from_public_bytes(peer_public))
    if initiated:
        nonces = hs.nonce + peer_nonce
    else:
        nonces = peer_nonce + hs.nonce
    material = (
        shared
        + initiator_id.encode()
        + b"\x00"
        + responder_id.encode()
        + b"\x00"
        + nonces
    )
    # Single-block HKDF-extract/expand (output = one SHA256 block)
    prk = hmac.new(_INFO, material, hashlib.sha256).digest()
    return hmac.new(prk, b"\x01" + _INFO, hashlib.sha256).digest()


def mac(session_key: bytes, data: bytes) -> bytes:
    # hmac.digest is the one-shot C path (no HMAC-object construction);
    # on the cluster hot path this runs four times per message hop.
    return hmac.digest(session_key, data, "sha256")


def seal(envelope, session_key: bytes):
    """Attach the session MAC over an envelope's canonical auth bytes —
    the one sealing idiom shared by clients, replicas and the verifier
    service."""
    return envelope.with_mac(mac(session_key, envelope.signing_bytes()))


def mac_ok(session_key: bytes, data: bytes, tag: bytes) -> bool:
    return hmac.compare_digest(mac(session_key, data), tag)


# --------------------------------------------------------------------------
# Fast-path posture + signed checkpoints (round 18).
#
# The session MAC authenticates each envelope to the RECEIVER; it proves
# nothing to a third party, and if the session key ever leaks (or a session
# table is corrupted) the whole MAC window is unattributable.  Periodic
# signed checkpoints close that: every CHECKPOINT_MSGS messages (or
# CHECKPOINT_MS, whichever first) the sender Ed25519-signs the list of
# digests of every MAC'd envelope it sent since the last verified
# checkpoint.  The receiver, which recorded the digest of every MAC'd
# envelope it ACCEPTED, demands its accepted multiset be covered by the
# signed declaration — so any message the receiver accepted that the sender
# never signed for (a MAC forgery, a replay within or across windows) is
# convicted retroactively with transferable evidence, while messages the
# sender sent but the receiver never saw (drops, in-flight) are simply
# carried forward.  Declarations are ~DIGEST_LEN bytes per message,
# amortized: the fast path keeps per-message crypto at one HMAC while the
# identity binding arrives one signature per window.

#: Messages per checkpoint window (sender-side trigger).
CHECKPOINT_MSGS = int(os.environ.get("MOCHI_CHECKPOINT_MSGS", "512"))
#: Milliseconds per checkpoint window (sender-side trigger).
CHECKPOINT_MS = float(os.environ.get("MOCHI_CHECKPOINT_MS", "2000"))
#: Receiver-side hard cap: a sender this many accepted-but-unattested
#: messages behind gets typed refusals until it re-handshakes — a Byzantine
#: sender must not ride the MAC discount indefinitely while dodging the
#: signed audit trail.  4x the window tolerates ack loss + clock skew.
OVERDUE_FACTOR = 4

_CKPT_DOMAIN = b"mochi.ckpt.v1\x00"
DIGEST_LEN = 16  # 128-bit collision resistance: plenty for an audit ledger


def fast_path_enabled(explicit: Optional[bool] = None) -> bool:
    """Resolve the fast-path posture: an explicit constructor argument wins;
    otherwise the MOCHI_FAST_PATH env knob (default ON).  OFF means every
    envelope rides per-message Ed25519 and certificates verify grant by
    grant — the pre-round-18 posture, kept as the A/B and rollback leg."""
    if explicit is not None:
        return bool(explicit)
    return os.environ.get("MOCHI_FAST_PATH", "1") != "0"


def window_digest(signing_bytes: bytes) -> bytes:
    """Digest of one MAC'd envelope's canonical auth bytes as it appears in
    a checkpoint declaration (domain-separated from every other hash in the
    protocol)."""
    return hashlib.sha256(_CKPT_DOMAIN + signing_bytes).digest()[:DIGEST_LEN]


class SessionWindow:
    """Sender-side checkpoint state for ONE session: the digests of every
    MAC'd envelope sealed since the last VERIFIED checkpoint.

    ``take`` snapshots the current declaration without clearing it —
    concurrent sends during the checkpoint round trip simply append, and a
    lost checkpoint (or refused ack) keeps everything for the next attempt
    — and ``committed`` retires exactly the snapshot after a positive ack.
    """

    __slots__ = ("pending", "started_at", "window", "sent", "last_error")

    def __init__(self) -> None:
        self.pending: List[bytes] = []
        self.started_at = time.monotonic()
        self.window = 0
        self.sent = 0  # lifetime MAC'd envelopes noted (observability)
        self.last_error: Optional[str] = None

    def note(self, signing_bytes: bytes) -> None:
        self.pending.append(window_digest(signing_bytes))
        self.sent += 1

    def due(self) -> bool:
        if not self.pending:
            return False
        return (
            len(self.pending) >= CHECKPOINT_MSGS
            or (time.monotonic() - self.started_at) * 1e3 >= CHECKPOINT_MS
        )

    def overdue_risk(self) -> bool:
        """True when the unattested backlog nears the receiver's hard cap —
        the sender must checkpoint NOW or start eating typed refusals."""
        return len(self.pending) >= (OVERDUE_FACTOR - 1) * CHECKPOINT_MSGS

    def take(self) -> Tuple[int, Tuple[bytes, ...]]:
        """Snapshot (window, declaration) for a checkpoint attempt."""
        return self.window, tuple(self.pending)

    def committed(self, n: int) -> None:
        """Positive ack for a ``take`` snapshot of length ``n``."""
        del self.pending[:n]
        self.window += 1
        self.started_at = time.monotonic()


class CheckpointLedger:
    """Receiver-side audit ledger for ONE session: the digest multiset of
    every MAC'd envelope accepted since the last verified checkpoint, plus
    the carry of declared-but-not-yet-seen digests (messages signed for by
    the sender that are still in flight — or dropped — when the checkpoint
    lands).

    ``verify`` returns None when the signed declaration covers everything
    accepted; otherwise a human-readable reason — an accepted message the
    sender never signed for is exactly a MAC forgery or a replay, and the
    caller convicts with the declaration as transferable evidence.
    """

    __slots__ = ("accepted", "carry", "count_since", "verified_windows",
                 "mismatches")

    #: Declared-but-unseen digests carried across windows; past this the
    #: session state is no longer reconcilable (pathological loss) and the
    #: receiver demands a fresh handshake rather than risk convicting an
    #: honest sender on evicted carry.
    CARRY_MAX = 8192

    def __init__(self) -> None:
        self.accepted: Dict[bytes, int] = {}
        self.carry: Dict[bytes, int] = {}
        self.count_since = 0
        self.verified_windows = 0
        self.mismatches = 0

    def note(self, signing_bytes: bytes) -> bool:
        """Record one accepted MAC'd envelope.  False = the sender is past
        the overdue cap (caller refuses the envelope, typed)."""
        if self.count_since >= OVERDUE_FACTOR * CHECKPOINT_MSGS:
            return False
        h = window_digest(signing_bytes)
        c = self.carry.get(h)
        if c:  # already attested by a prior declaration: covered
            if c == 1:
                del self.carry[h]
            else:
                self.carry[h] = c - 1
            return True
        self.accepted[h] = self.accepted.get(h, 0) + 1
        self.count_since += 1
        return True

    def verify(self, declared: Sequence[bytes]) -> Optional[str]:
        """Check a signed declaration against the accepted multiset.  None
        on success (ledger advances); else the conviction reason (ledger
        state is kept — the evidence must outlive the verdict)."""
        remaining: Dict[bytes, int] = {}
        for h in declared:
            h = bytes(h)
            remaining[h] = remaining.get(h, 0) + 1
        for h, n in self.accepted.items():
            if remaining.get(h, 0) < n:
                self.mismatches += 1
                return (
                    "accepted MAC'd message absent from the signed "
                    "checkpoint declaration (forged or replayed in the "
                    "MAC window)"
                )
        for h, n in self.accepted.items():
            left = remaining[h] - n
            if left:
                remaining[h] = left
            else:
                del remaining[h]
        # declared-but-unseen: carry forward so late arrivals stay covered
        for h, n in remaining.items():
            self.carry[h] = self.carry.get(h, 0) + n
        if len(self.carry) > self.CARRY_MAX:
            return "carry overflow"  # caller resets the session, no conviction
        self.accepted.clear()
        self.count_since = 0
        self.verified_windows += 1
        return None

    def stats(self) -> Dict[str, int]:
        return {
            "unattested": self.count_since,
            "carry": sum(self.carry.values()),
            "verified_windows": self.verified_windows,
            "mismatches": self.mismatches,
        }

"""Host-side Ed25519 signing and verification.

The reference *declares* signatures and never implements them
(``MochiProtocol.proto:123`` "TODO: add signature"; ``mochiDB.tex:202`` "Our
implementation lacks PKI support").  Here they are first-class: replicas and
clients hold Ed25519 keypairs; signing and the default CPU verify path use the
host ``cryptography`` library (OpenSSL) — the "BouncyCastle analog" of
BASELINE.json — while the TPU batch-verify path lives in
:mod:`mochi_tpu.crypto.batch_verify`.
"""

from __future__ import annotations

from dataclasses import dataclass

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
)
from cryptography.hazmat.primitives.serialization import (
    Encoding,
    NoEncryption,
    PrivateFormat,
    PublicFormat,
)


@dataclass(frozen=True)
class KeyPair:
    """Raw Ed25519 keypair: 32-byte seed + 32-byte compressed public key."""

    private_seed: bytes
    public_key: bytes

    def sign(self, message: bytes) -> bytes:
        return sign(self.private_seed, message)


def generate_keypair() -> KeyPair:
    priv = Ed25519PrivateKey.generate()
    seed = priv.private_bytes(Encoding.Raw, PrivateFormat.Raw, NoEncryption())
    pub = priv.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
    return KeyPair(seed, pub)


def keypair_from_seed(seed: bytes) -> KeyPair:
    priv = Ed25519PrivateKey.from_private_bytes(seed)
    pub = priv.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
    return KeyPair(seed, pub)


def sign(private_seed: bytes, message: bytes) -> bytes:
    return Ed25519PrivateKey.from_private_bytes(private_seed).sign(message)


def verify(public_key: bytes, message: bytes, signature: bytes) -> bool:
    """Single-signature CPU verify; returns False on any malformed input."""
    try:
        Ed25519PublicKey.from_public_bytes(public_key).verify(signature, message)
        return True
    except (InvalidSignature, ValueError):
        return False

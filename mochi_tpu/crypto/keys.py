"""Host-side Ed25519 signing and verification.

The reference *declares* signatures and never implements them
(``MochiProtocol.proto:123`` "TODO: add signature"; ``mochiDB.tex:202`` "Our
implementation lacks PKI support").  Here they are first-class: replicas and
clients hold Ed25519 keypairs; signing and the default CPU verify path use the
host ``cryptography`` library (OpenSSL) — the "BouncyCastle analog" of
BASELINE.json — while the TPU batch-verify path lives in
:mod:`mochi_tpu.crypto.batch_verify`.

``cryptography`` is optional: on a bare ``numpy+jax+pytest`` environment the
import below fails soft and every operation routes to the pure-Python
fallback (:mod:`mochi_tpu.crypto.hostfallback`, built on the repo's own
curve arithmetic).  Verdicts are identical either way — strict canonical
prechecks here, then the cofactorless check — so mixed clusters agree on
every signature.  The fallback import is deferred to first use: with OpenSSL
present it never loads (and never pays the JAX import it pulls in).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

try:  # optional accelerator; see module docstring
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        NoEncryption,
        PrivateFormat,
        PublicFormat,
    )

    _HAVE_HOST_CRYPTO = True
except ImportError:  # pragma: no cover - exercised on bare environments
    import logging

    _HAVE_HOST_CRYPTO = False
    logging.getLogger(__name__).warning(
        "cryptography (OpenSSL) not installed: Ed25519/X25519 use the "
        "pure-Python fallback (~100x slower, variable-time). Production "
        "deployments should `pip install mochi-tpu[host-crypto]`."
    )


def _fallback():
    from . import hostfallback

    return hostfallback


@dataclass(frozen=True)
class KeyPair:
    """Raw Ed25519 keypair: 32-byte seed + 32-byte compressed public key."""

    private_seed: bytes
    public_key: bytes

    def sign(self, message: bytes) -> bytes:
        return sign(self.private_seed, message)


def generate_keypair() -> KeyPair:
    if not _HAVE_HOST_CRYPTO:
        import os

        seed = os.urandom(32)
        return KeyPair(seed, _fallback().public_from_seed(seed))
    priv = Ed25519PrivateKey.generate()
    seed = priv.private_bytes(Encoding.Raw, PrivateFormat.Raw, NoEncryption())
    pub = priv.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
    return KeyPair(seed, pub)


def keypair_from_seed(seed: bytes) -> KeyPair:
    if not _HAVE_HOST_CRYPTO:
        return KeyPair(seed, _fallback().public_from_seed(seed))
    priv = Ed25519PrivateKey.from_private_bytes(seed)
    pub = priv.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
    return KeyPair(seed, pub)


# Parsing raw bytes into OpenSSL key handles costs as much as the crypto op
# itself; replicas/clients reuse the same few keys for every message, so the
# parsed handles are cached (bounded: a cluster touches n_servers + clients).
@lru_cache(maxsize=4096)
def _private_key(private_seed: bytes) -> "Ed25519PrivateKey":
    return Ed25519PrivateKey.from_private_bytes(private_seed)


@lru_cache(maxsize=65536)
def _public_key(public_key: bytes) -> "Ed25519PublicKey":
    return Ed25519PublicKey.from_public_bytes(public_key)


def sign(private_seed: bytes, message: bytes) -> bytes:
    if not _HAVE_HOST_CRYPTO:
        return _fallback().sign(private_seed, message)
    return _private_key(private_seed).sign(message)


# Strict RFC 8032 canonical-encoding prechecks.  OpenSSL's ref10 decode
# accepts a handful of non-canonical point encodings (y >= p reduced mod p);
# the TPU path rejects them.  For BFT safety every replica must reach the
# SAME verdict on the same bytes regardless of which backend it runs, so the
# CPU path applies the identical strict prechecks before OpenSSL.
_P = (1 << 255) - 19
_L = (1 << 252) + 27742317777372353535851937790883648493


def _canonical(public_key: bytes, signature: bytes) -> bool:
    if len(public_key) != 32 or len(signature) != 64:
        return False
    y_a = int.from_bytes(public_key, "little") & ((1 << 255) - 1)
    y_r = int.from_bytes(signature[:32], "little") & ((1 << 255) - 1)
    s = int.from_bytes(signature[32:], "little")
    return y_a < _P and y_r < _P and s < _L


def host_crypto_engine() -> str:
    """Which engine :func:`verify` routes to on THIS host: ``"openssl"``
    (the ``cryptography`` wheel), ``"native-c"`` (the lazily-built
    ``native/hbatch.c`` verification engine), or ``"pure-python"`` (the
    :mod:`~mochi_tpu.crypto.hostfallback` bignum engine).  Benchmark
    records stamp this so the recurring "wheel-less host inflates write
    latency" caveat is machine-readable provenance, not prose
    (benchmarks/run_all.py, bench.py)."""
    if _HAVE_HOST_CRYPTO:
        return "openssl"
    try:
        return "native-c" if _fallback().has_native() else "pure-python"
    except Exception:  # pragma: no cover - loader breakage
        return "pure-python"


def register_known_signers(pubs) -> bool:
    """Pre-promote known signers (cluster replica identities) in the host
    verify engine; returns whether the hint reached an engine that uses it.

    With OpenSSL present this is a no-op (its verify has no per-signer
    state worth warming), and likewise with the native-C engine (its
    Straus ladder rebuilds the per-item table in-call; no signer state).
    On toolchain-less wheel-less hosts the pure-Python engine keeps
    per-signer fixed-window tables (:mod:`~mochi_tpu.crypto.hostfallback`,
    the host analog of the device comb) that normally require two verified
    signatures to earn; pre-promotion makes the FIRST certificate check
    from a cluster identity run combed.  O(1) per key — table builds stay
    lazy (first verify), so boot cost is nil.
    """
    if _HAVE_HOST_CRYPTO:
        return False
    fb = _fallback()
    if fb.has_native():
        return False
    return fb.prime_signers(pubs)


def verify(public_key: bytes, message: bytes, signature: bytes) -> bool:
    """Single-signature CPU verify; returns False on any malformed input.

    Verdict is bit-for-bit identical to the TPU batch path
    (:mod:`mochi_tpu.crypto.batch_verify`): strict canonical-encoding
    prechecks, then the cofactorless check (OpenSSL, or the pure-Python
    fallback when ``cryptography`` is absent).
    """
    if not _canonical(public_key, signature):
        return False
    if not _HAVE_HOST_CRYPTO:
        return _fallback().verify(public_key, message, signature)
    try:
        _public_key(public_key).verify(signature, message)
        return True
    except (InvalidSignature, ValueError):
        return False

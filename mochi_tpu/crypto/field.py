"""GF(2^255-19) arithmetic in 16x16-bit limbs, pure int32 — TPU-native.

Design notes (why this representation):

* TPU VPU/MXU have native int32 multiply; int64 is emulated by XLA.  SURVEY.md
  §7 calls for limb decomposition so everything stays in int32 ops.  We use
  **16 limbs x 16 bits** (radix 2^16, little-endian).  A 16x16-bit product
  fits uint32 exactly ((2^16-1)^2 < 2^32), and after splitting each partial
  product into lo/hi 16-bit halves, a schoolbook column accumulates at most
  32 terms < 2^16, i.e. < 2^21 — comfortably inside int32.
* All functions are shape-polymorphic over leading batch dims: a field element
  is an int32 array ``(..., 16)`` with limbs in ``[0, 2^16)`` ("loosely
  reduced": the represented value is < 2^256, congruent mod p to the true
  value).  :func:`canonical` produces the unique representative < p.
* No data-dependent control flow — everything is branchless select/arithmetic
  so the whole verifier jits into one XLA program (SURVEY.md §7 "no
  data-dependent Python control flow inside jit").

The reference implements no field arithmetic anywhere (it never signs:
``MochiProtocol.proto:123`` TODO, SURVEY.md preamble); this module is part of
the north-star TPU verifier that completes the reference's declared design.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

NLIMBS = 16
RADIX = 16
MASK = (1 << RADIX) - 1

# p = 2^255 - 19
P_INT = (1 << 255) - 19
# curve constant d = -121665/121666 mod p
D_INT = 37095705934669439343138083508754565189542113879843219016388785533085940283555
# sqrt(-1) mod p (2^((p-1)/4))
SQRT_M1_INT = 19681161376707505956807079304988542015446066515923890162744021073123829784752
# group order L = 2^252 + 27742317777372353535851937790883648493
L_INT = (1 << 252) + 27742317777372353535851937790883648493

# Ed25519 basepoint (affine)
BX_INT = 15112221349535400772501151409588531511454012693041857206046113283949847762202
BY_INT = 46316835694926478169428394003475163141307993866256225615783033603165251855960


def int_to_limbs(x: int) -> np.ndarray:
    """Host-side: python int -> 16 int32 limbs (little-endian, radix 2^16)."""
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMBS)], dtype=np.int32)


def limbs_to_int(limbs) -> int:
    """Host-side: 1-D limb array -> python int (no reduction)."""
    arr = np.asarray(limbs).reshape(NLIMBS)
    return sum(int(arr[i]) << (RADIX * i) for i in range(NLIMBS))


def limbs_to_int_batch(limbs) -> list:
    """Host-side: (..., 16) limb array -> list of python ints over last axis."""
    arr = np.asarray(limbs).reshape(-1, NLIMBS)
    out = []
    for row in arr:
        out.append(sum(int(row[i]) << (RADIX * i) for i in range(NLIMBS)))
    return out


def bytes32_to_limbs(b: bytes) -> np.ndarray:
    """32 little-endian bytes -> limbs (full 256 bits, no masking)."""
    assert len(b) == 32
    x = int.from_bytes(b, "little")
    return int_to_limbs(x)


# Device-resident constants (built lazily so importing this module doesn't
# touch a backend).
def const(x: int) -> jnp.ndarray:
    return jnp.asarray(int_to_limbs(x))


def zeros_like_batch(batch_shape) -> jnp.ndarray:
    return jnp.zeros((*batch_shape, NLIMBS), dtype=jnp.int32)


def _carry_chain(cols: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Signed sequential carry over 16 columns -> (canonical limbs, carry-out).

    ``cols`` is int32 (..., 16) with |col| < 2^27 or so; returns limbs in
    [0, 2^16) and the signed carry out of limb 15 (value = limbs + cout*2^256).
    Unrolled python loop: 16 iterations, traced once under jit.
    """
    c = jnp.zeros(cols.shape[:-1], dtype=jnp.int32)
    out = []
    for k in range(NLIMBS):
        t = cols[..., k] + c
        out.append(t & MASK)
        c = t >> RADIX  # arithmetic shift: correct for negative t
    return jnp.stack(out, axis=-1), c


def _fold_carry(limbs: jnp.ndarray, cout: jnp.ndarray) -> jnp.ndarray:
    """Fold carry-out: 2^256 === 38 (mod p). Adds 38*cout to limb 0, re-carries."""
    cols = limbs.at[..., 0].add(38 * cout)
    limbs2, cout2 = _carry_chain(cols)
    # A second fold can only produce cout2 in {-1,0,1}; one more pass settles it
    # (see module docstring bounds analysis; third carry-out is provably 0).
    cols3 = limbs2.at[..., 0].add(38 * cout2)
    limbs3, _ = _carry_chain(cols3)
    return limbs3


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    limbs, cout = _carry_chain(a + b)
    return _fold_carry(limbs, cout)


# 2^256 - 38 == 2*p, as limbs: all 0xFFFF except limb0 = 0xFFDA.
_TWO_P_LIMBS = np.full(NLIMBS, MASK, dtype=np.int32)
_TWO_P_LIMBS[0] = MASK - 37


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b mod p.  Adds 2p so columns stay > -2^16 before the signed chain."""
    cols = a + jnp.asarray(_TWO_P_LIMBS) - b
    limbs, cout = _carry_chain(cols)
    return _fold_carry(limbs, cout)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return sub(jnp.zeros_like(a), a)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook 16x16-limb multiply with lo/hi split, fold at 2^256===38.

    Partial products p[i,j] = a[i]*b[j] (< 2^32, computed in uint32 then
    bit-split so every accumulated term is < 2^16).  Column k of the 32-column
    product gets lo-halves with i+j==k and hi-halves with i+j==k-1: <= 32
    terms < 2^16 -> column < 2^21.  High 16 columns fold back as 38*col
  (2^256 === 38 mod p): columns < 38*2^21 + 2^21 < 2^27, safely int32.
    """
    au = a.astype(jnp.uint32)
    bu = b.astype(jnp.uint32)
    # (..., 16, 16) outer products
    prod = au[..., :, None] * bu[..., None, :]
    lo = (prod & MASK).astype(jnp.int32)
    hi = (prod >> RADIX).astype(jnp.int32)

    batch_shape = prod.shape[:-2]
    cols = jnp.zeros((*batch_shape, 2 * NLIMBS), dtype=jnp.int32)
    # Accumulate anti-diagonals. Unrolled: 16 scatter-adds of shifted rows.
    for i in range(NLIMBS):
        cols = lax.dynamic_update_slice_in_dim(
            cols,
            lax.dynamic_slice_in_dim(cols, i, NLIMBS, axis=-1) + lo[..., i, :],
            i,
            axis=-1,
        )
        cols = lax.dynamic_update_slice_in_dim(
            cols,
            lax.dynamic_slice_in_dim(cols, i + 1, NLIMBS, axis=-1) + hi[..., i, :],
            i + 1,
            axis=-1,
        )
    low, high = cols[..., :NLIMBS], cols[..., NLIMBS:]
    folded = low + 38 * high
    limbs, cout = _carry_chain(folded)
    return _fold_carry(limbs, cout)


def square(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small python constant.

    For k < 2^14 the limbwise product stays inside int32 (2^16 * 2^14 = 2^30)
    and a single carry chain suffices; larger constants route through the full
    multiply with a constant operand (XLA folds the broadcast).
    """
    if 0 <= k < (1 << 14):
        limbs, cout = _carry_chain(a * k)
        return _fold_carry(limbs, cout)
    return mul(a, const(k % P_INT))


def canonical(a: jnp.ndarray) -> jnp.ndarray:
    """Reduce a loosely-reduced element (< 2^256) to the unique rep < p.

    Value < 2^256 = 2p + 38, so at most two conditional subtractions of p.
    Branchless: compute a - p with borrow; keep if nonnegative.
    """
    p_limbs = const(P_INT)

    def cond_sub_p(x):
        cols = x - p_limbs
        limbs, cout = _carry_chain(cols)
        nonneg = cout >= 0  # x >= p
        return jnp.where(nonneg[..., None], limbs, x)

    return cond_sub_p(cond_sub_p(a))


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field equality (canonicalizes both sides). Returns bool (...,)."""
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == 0, axis=-1)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Branchless limb select: cond (...,) bool -> a or b (..., 16)."""
    return jnp.where(cond[..., None], a, b)


def _pow_const(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e for a fixed public exponent, via lax.scan square-and-multiply.

    The loop body (1 square + 1 branchless multiply) compiles once; the bit
    sequence rides along as a scanned constant array.  Exponents here are
    public protocol constants, so non-constant-time is fine (this is verify,
    not sign — SURVEY.md §7).
    """
    bits_str = bin(e)[2:]  # MSB first
    bits = jnp.asarray([int(c) for c in bits_str[1:]], dtype=jnp.int32)

    def body(acc, bit):
        acc = square(acc)
        acc = select((bit == 1), mul(acc, a), acc)
        return acc, None

    acc, _ = lax.scan(body, a, bits)
    return acc


def pow_p58(a: jnp.ndarray) -> jnp.ndarray:
    """a^((p-5)/8) = a^(2^252 - 3): the sqrt-ratio exponentiation."""
    return _pow_const(a, (1 << 252) - 3)


def invert(a: jnp.ndarray) -> jnp.ndarray:
    """a^(p-2) (Fermat)."""
    return _pow_const(a, P_INT - 2)

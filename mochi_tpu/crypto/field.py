"""GF(2^255-19) arithmetic in 17x15-bit limbs, pure int32 — TPU-native.

Design notes (why this representation — round-2 rework):

* **Radix 2^15, 17 limbs, limbs-leading layout.**  A field element is an
  int32 array ``(17, ...lanes)`` — limbs on the *leading* axis, batch on the
  trailing axes.  On TPU the last dim maps to the 128-wide lane axis, so a
  batch of field elements ``(17, B)`` runs every elementwise op on full
  128-lane vectors (the round-1 ``(B, 16)`` layout wasted 7/8 of each lane
  group; the round-1 Pallas kernel existed solely to fix that — now the XLA
  path has the good layout natively and the Pallas kernel shares this code).
* **Why radix 15, not 16:** 17*15 = 255 exactly, so the fold constant is 19
  (2^255 === 19 mod p) and — the big one — products of *loosely reduced*
  limbs stay inside int32: with limbs <= 2^15+96, a product is < 2^31, so
  multiplication needs no uint32 casts and, crucially, limbs never need to
  be carried all the way down to < 2^15 between operations.  Radix 16 sits
  exactly at the uint32 boundary and forces a full sequential carry chain
  (16 data-dependent steps, x3 per multiply) after every op.
* **Loose-carry discipline.**  Invariant: every field element has limbs in
  ``[0, LOOSE]`` with ``LOOSE = 2^15 + 96``.  After an op, one or two
  *vectorized* carry passes (shift-add over all limbs at once, no sequential
  chain) restore the invariant.  Bounds, proven per-op in the docstrings:
  products <= LOOSE^2 < 2^31; schoolbook columns < 2^21; folded columns
  < 2^26; ``_carry2`` output <= 32786 <= LOOSE.  Exact canonical reduction
  (sequential chain + conditional subtract) happens only in :func:`canonical`
  — i.e. a handful of times per verify, not thousands.
* **Column accumulation is 17 shifted pad+adds.**  The 17x17 partial-
  product anti-diagonal sums ("columns") are built by padding each row to
  its shifted position and summing (:func:`_skew_cols_pad`) — ~35 fusable
  elementwise ops, no relayout.  The round-2a "reshape" variant (3 XLA
  ops via a flatten/reshape skew) compiles equally fast but runs 3.4x
  slower on v5e: the reshape is a relayout + fusion barrier, so the
  (17, 34, B) intermediates stream through HBM (~27 us/mul at B=4096,
  consistent with HBM bandwidth on ~40 MB of intermediates) where the
  pad form stays VMEM-fused (~7.9 us/mul) — scripts/mul_microbench.py.
  Either way the traced graph is ~25-35 HLO ops per multiply vs round-1's
  ~300 (32 dynamic-slice updates), which is what cut XLA-CPU compile of
  the full verifier from minutes to seconds (VERDICT.md round-1 item 4).
  Inside Pallas/Mosaic kernels (where sublane-dim reshapes are
  restricted) the same columns are built by unrolled static-slice adds —
  select with :data:`SKEW_IMPL`.
* No data-dependent control flow — everything is branchless select/arith
  so the whole verifier jits into one XLA program (SURVEY.md §7).

The reference implements no field arithmetic anywhere (it never signs:
``MochiProtocol.proto:123`` TODO, SURVEY.md preamble); this module is part of
the north-star TPU verifier that completes the reference's declared design.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

NLIMBS = 17
RADIX = 15
MASK = (1 << RADIX) - 1
LOOSE = (1 << RADIX) + 96  # loose-limb bound, see module docstring

# p = 2^255 - 19
P_INT = (1 << 255) - 19
# curve constant d = -121665/121666 mod p
D_INT = 37095705934669439343138083508754565189542113879843219016388785533085940283555
# sqrt(-1) mod p (2^((p-1)/4))
SQRT_M1_INT = 19681161376707505956807079304988542015446066515923890162744021073123829784752
# group order L = 2^252 + 27742317777372353535851937790883648493
L_INT = (1 << 252) + 27742317777372353535851937790883648493

# Ed25519 basepoint (affine)
BX_INT = 15112221349535400772501151409588531511454012693041857206046113283949847762202
BY_INT = 46316835694926478169428394003475163141307993866256225615783033603165251855960

# How to build schoolbook columns: "pad" (17 shifted pad+adds — no
# relayout, fuses into the partial-product computation; measured 3.4x
# faster than "reshape" on v5e at (17, 4096): 7.9 vs 27.0 us/mul,
# scripts/mul_microbench.py), "reshape" (3 XLA ops but the flatten/
# reshape is a relayout + fusion barrier on TPU), "shift" (unrolled
# static-slice adds — required inside Mosaic kernels, where reshapes
# that touch the sublane dim are restricted), or "mxu" (column reduction
# as one f32 matmul against a constant 0/1 shift matrix — moves the
# reduction off the VPU onto the MXU; see ``_mul_mxu``).  Env-overridable
# for the measurement battery's A/B (MOCHI_SKEW_IMPL).
import os as _os

SKEW_IMPL = _os.environ.get("MOCHI_SKEW_IMPL", "pad")


def available_skews():
    return ("pad", "reshape", "shift", "mxu")

# How to materialize limb constants: "array" (one XLA literal — default) or
# "scalars" (per-limb jnp.full from python ints — required inside Pallas
# kernels, which cannot capture array constants from the closure).
CONST_MODE = "array"


def int_to_limbs(x: int) -> np.ndarray:
    """Host-side: python int -> 17 int32 limbs (little-endian, radix 2^15).

    The representation covers [0, 2^255) — every protocol input (y
    coordinates with bit 255 masked off, scalars < L, field constants) fits;
    larger values would silently truncate, so they are rejected.
    """
    assert 0 <= x < (1 << 255), "value out of 255-bit limb range"
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMBS)], dtype=np.int32)


def limbs_to_int(limbs) -> int:
    """Host-side: limb array (17,) or (17, 1) -> python int (no reduction)."""
    arr = np.asarray(limbs).reshape(NLIMBS)
    return sum(int(arr[i]) << (RADIX * i) for i in range(NLIMBS))


def limbs_to_int_batch(limbs) -> list:
    """Host-side: (17, B) limb array -> list of B python ints."""
    arr = np.asarray(limbs).reshape(NLIMBS, -1)
    out = []
    for j in range(arr.shape[1]):
        out.append(sum(int(arr[i, j]) << (RADIX * i) for i in range(NLIMBS)))
    return out


def bytes32_to_limbs(b: bytes) -> np.ndarray:
    """32 little-endian bytes -> limbs (full 256 bits would not fit 255;
    callers mask bit 255 first — this helper asserts the value fits)."""
    assert len(b) == 32
    x = int.from_bytes(b, "little")
    assert x < (1 << 255)
    return int_to_limbs(x)


def const(x: int, lanes=()) -> jnp.ndarray:
    """Device constant: (17, *lanes) int32, broadcast over trailing lanes."""
    if CONST_MODE == "scalars":
        limbs = [int(v) for v in int_to_limbs(x)]
        return jnp.stack([jnp.full(lanes, l, dtype=jnp.int32) for l in limbs], axis=0)
    c = jnp.asarray(int_to_limbs(x))
    if lanes:
        c = jnp.broadcast_to(c.reshape(NLIMBS, *([1] * len(lanes))), (NLIMBS, *lanes))
    return c


def _limb_vec(np_limbs: np.ndarray, lanes=()) -> jnp.ndarray:
    """A fixed limb vector (e.g. p or 2p) as (17, *lanes or broadcastable)."""
    if CONST_MODE == "scalars":
        return jnp.stack(
            [jnp.full(lanes, int(l), dtype=jnp.int32) for l in np_limbs], axis=0
        )
    return jnp.asarray(np_limbs).reshape(NLIMBS, *([1] * len(lanes)))


def zeros(lanes) -> jnp.ndarray:
    return jnp.zeros((NLIMBS, *lanes), dtype=jnp.int32)


def one(lanes) -> jnp.ndarray:
    # concat, not .at[0].set: an indexed update lowers to scatter, which
    # Mosaic has no TC lowering for; XLA folds both forms identically.
    return jnp.concatenate(
        [jnp.ones((1, *lanes), jnp.int32), jnp.zeros((NLIMBS - 1, *lanes), jnp.int32)],
        axis=0,
    )


# ------------------------------------------------------------------- carries


def _shift_in(c: jnp.ndarray, fold: int) -> jnp.ndarray:
    """Carries (17, ...) -> what each limb receives: limb k+1 gets c[k],
    limb 0 gets fold*c[16] (2^255 === fold mod p, fold = 19)."""
    return jnp.concatenate([fold * c[NLIMBS - 1 :], c[: NLIMBS - 1]], axis=0)


def _carry1(cols: jnp.ndarray) -> jnp.ndarray:
    """One vectorized carry pass.  Valid for 0 <= cols <= 2^17.

    r = cols & MASK < 2^15; c = cols >> 15 <= 4; out[k] = r[k] + c[k-1],
    out[0] = r[0] + 19*c[16] <= 32767 + 76 = 32843 <= LOOSE.
    """
    r = cols & MASK
    c = cols >> RADIX
    return r + _shift_in(c, 19)


def _carry2(cols: jnp.ndarray) -> jnp.ndarray:
    """Two vectorized carry passes.  Valid for 0 <= cols < 2^26.

    Pass 1: c <= 2^11, t[0] <= 32767 + 19*2^11 = 71679, t[k] <= 34815.
    Pass 2: c2[0] <= 2, c2[k] <= 1 -> out[k] <= 32769, out[0] <= 32786.
    Output <= 32786 <= LOOSE.
    """
    t = (cols & MASK) + _shift_in(cols >> RADIX, 19)
    return (t & MASK) + _shift_in(t >> RADIX, 19)


def _carry_chain(cols: jnp.ndarray):
    """Exact sequential carry (17 steps) -> (limbs < 2^15, signed carry-out).

    Only used inside :func:`canonical`; value = limbs + cout * 2^255.
    Arithmetic shift keeps negative columns correct (borrow propagation).
    """
    c = jnp.zeros(cols.shape[1:], dtype=jnp.int32)
    out = []
    for k in range(NLIMBS):
        t = cols[k] + c
        out.append(t & MASK)
        c = t >> RADIX
    return jnp.stack(out, axis=0), c


# ------------------------------------------------------------------- add/sub


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a + b mod p.  Columns <= 2*LOOSE = 65728 <= 2^17 -> one carry pass."""
    return _carry1(a + b)


# 2p as a NON-normalized limb vector: each canonical p-limb doubled, so every
# limb (65498, 65534 x16) dominates any loose limb (<= LOOSE) — the standard
# "add 2p before subtracting" trick without leaving the limb domain.
_P_LIMBS_NP = np.array(
    [(P_INT >> (RADIX * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
)
_TWO_P_LIMBS = (2 * _P_LIMBS_NP).astype(np.int32)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b mod p.  t = a + 2p - b: limbwise 32634 <= t <= 98398 <= 2^17,
    nonnegative because every 2p limb (>= 65498) exceeds any loose limb."""
    two_p = _limb_vec(_TWO_P_LIMBS, a.shape[1:])
    return _carry1(a + (two_p - b))


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return sub(jnp.zeros_like(a), a)


# ------------------------------------------------------------------- multiply


def _skew_cols_reshape(x: jnp.ndarray) -> jnp.ndarray:
    """Anti-diagonal sums of (..leading.., 17, 17, ...lanes) on axes (-2-L,..)?

    Layout here: x is (17, 17, *lanes) — axis 0 = a-limb i, axis 1 = b-limb j.
    Returns cols (33, *lanes): cols[k] = sum_{i+j=k} x[i,j].

    Trick: pad rows to width 2n (34), flatten, pad to (n+1)(2n-1) = 594,
    reshape (18, 33): element (i,j) lands at p = 34i+j, and p mod 33 =
    (i+j) mod 33 = i+j (since i+j <= 32); summing the 18 rows gives the
    column sums.  3 XLA ops instead of 32 dynamic-slice updates.
    """
    n = NLIMBS
    lanes = x.shape[2:]
    lane_pad = [(0, 0)] * len(lanes)
    x2 = jnp.pad(x, [(0, 0), (0, n), *lane_pad])  # (17, 34, lanes)
    flat = x2.reshape(n * 2 * n, *lanes)  # 578
    flat = jnp.pad(flat, [(0, (n + 1) * (2 * n - 1) - n * 2 * n), *lane_pad])  # 594
    return flat.reshape(n + 1, 2 * n - 1, *lanes).sum(axis=0)  # (33, lanes)


def _skew_cols_shift(x: jnp.ndarray) -> jnp.ndarray:
    """Same columns via unrolled static-slice adds (Mosaic-safe)."""
    n = NLIMBS
    lanes = x.shape[2:]
    cols = jnp.zeros((2 * n - 1, *lanes), dtype=jnp.int32)
    for i in range(n):
        cols = lax.dynamic_update_slice_in_dim(
            cols, lax.dynamic_slice_in_dim(cols, i, n, axis=0) + x[i], i, axis=0
        )
    return cols


def _skew_cols_pad(x: jnp.ndarray) -> jnp.ndarray:
    """Same columns via 17 shifted pad+adds: cols += pad(x[i], (i, 16-i)).

    Each term is an elementwise add of a sublane-shifted (17->33, lanes)
    slice — no flatten/reshape relayout, so XLA can fuse the whole column
    accumulation into the partial-product computation instead of
    materializing the (17, 34, lanes) skew intermediates in HBM.
    """
    n = NLIMBS
    lanes = x.shape[2:]
    lane_pad = [(0, 0)] * len(lanes)
    cols = jnp.pad(x[0], [(0, n - 1), *lane_pad])
    for i in range(1, n):
        cols = cols + jnp.pad(x[i], [(i, n - 1 - i), *lane_pad])
    return cols


def _skew_cols(x: jnp.ndarray) -> jnp.ndarray:
    if SKEW_IMPL == "reshape":
        return _skew_cols_reshape(x)
    if SKEW_IMPL == "shift":
        return _skew_cols_shift(x)
    # "pad" — also the fallback for "mxu" ranks the matmul path declines
    return _skew_cols_pad(x)


def _fold_carry(cols_lo: jnp.ndarray, cols_hi: jnp.ndarray) -> jnp.ndarray:
    """Combine lo/hi column sums (hi shifted one limb up), fold the high 17
    columns at 2^255 === 19, and restore the loose-limb invariant.
    Precondition (both callers prove it): columns < 2^21, so the folded
    columns are < 20 * 2^21 < 2^26 -> :func:`_carry2`."""
    pad_lane = [(0, 0)] * (cols_lo.ndim - 1)
    cols = jnp.pad(cols_lo, [(0, 1), *pad_lane]) + jnp.pad(
        cols_hi, [(1, 0), *pad_lane]
    )  # (34, lanes)
    folded = cols[:NLIMBS] + 19 * cols[NLIMBS:]
    return _carry2(folded)


# Constant reduction matrix for the "mxu" multiply: row k sums the lo
# products with i+j == k and the hi products with i+j+1 == k (hi is the
# product's high half, one limb up).  Shape (34, 2*289) f32 0/1.
def _build_mxu_matrix() -> np.ndarray:
    m = np.zeros((2 * NLIMBS, 2 * NLIMBS * NLIMBS), dtype=np.float32)
    for i in range(NLIMBS):
        for j in range(NLIMBS):
            m[i + j, i * NLIMBS + j] = 1.0
            m[i + j + 1, NLIMBS * NLIMBS + i * NLIMBS + j] = 1.0
    return m


_MXU_M = _build_mxu_matrix()


def _mul_mxu(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Column reduction on the MXU: the 17x17 partial-product anti-diagonal
    sums are one (34, 578) x (578, B) f32 matmul against a constant 0/1
    shift matrix.

    Exactness: lo < 2^15 and hi <= 32965 < 2^16 are f32-exact; each output
    column sums <= 34 such terms -> < 2^21 < 2^24, still exact; folded
    < 20 * 2^21 < 2^26 -> :func:`_carry2` precondition holds.  The VPU
    still computes the 289 int32 products; what moves to the MXU is the
    33-way reduction tree, the schedule-heavy half of the pad-skew form.
    Requires 1-D lanes (the batched verifier path); other ranks fall back.
    """
    lanes = a.shape[1:]
    prod = a[:, None] * b[None, :]  # (17, 17, B) int32
    lo = (prod & MASK).astype(jnp.float32).reshape(NLIMBS * NLIMBS, *lanes)
    hi = (prod >> RADIX).astype(jnp.float32).reshape(NLIMBS * NLIMBS, *lanes)
    p = jnp.concatenate([lo, hi], axis=0)  # (578, B)
    # precision=HIGHEST: TPU's default f32 matmul decomposes operands
    # through bf16 passes whose 8-bit mantissa would truncate the 16-bit
    # product halves — exactly on the hardware this path targets (CPU's
    # full-f32 default would mask it in tests).
    cols = lax.dot_general(
        jnp.asarray(_MXU_M),
        p,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,
    )  # (34, B), exact integers < 2^21
    # Fold in int32: 19 * col + col can reach ~21.7M > 2^24, past f32's
    # exact-integer range (the cols themselves, < 2^21, convert exactly).
    cols_i = cols.astype(jnp.int32)
    folded = cols_i[:NLIMBS] + 19 * cols_i[NLIMBS:]
    return _carry2(folded)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook 17x17-limb multiply, radix 2^15, fold at 2^255 === 19.

    Bounds: loose limbs <= LOOSE -> products <= LOOSE^2 = 1.080e9 < 2^31
    (int32-safe, no uint32 casts).  lo < 2^15, hi = prod >> 15 <= 32965.
    Columns: <= 17 terms each for lo and hi -> < 2^21 -> :func:`_fold_carry`.
    """
    if (
        SKEW_IMPL == "mxu"
        and CONST_MODE != "scalars"  # Mosaic kernels: no sublane reshape/dot
        and len(a.shape) == 2 == len(b.shape)
    ):
        return _mul_mxu(a, b)
    prod = a[:, None] * b[None, :]  # (17, 17, lanes) int32
    lo = prod & MASK
    hi = prod >> RADIX
    cols_lo = _skew_cols(lo)  # (33, lanes), cols of sum lo[i,j] at i+j
    cols_hi = _skew_cols(hi)  # hi contributes at i+j+1
    return _fold_carry(cols_lo, cols_hi)


def square(a: jnp.ndarray) -> jnp.ndarray:
    """a^2 via the symmetric schoolbook: only the upper triangle of the
    partial products is computed (153 int32 multiplies vs mul's 289),
    off-diagonal terms doubled.  Doubling happens AFTER the lo/hi split —
    doubling a product first would overflow int32 (2*LOOSE^2 > 2^31).

    Column values are exactly those of ``mul(a, a)`` (the doubled upper
    triangle equals the full ordered sum), so the mul bounds apply
    unchanged: columns < 2^21, folded < 2^26 -> :func:`_carry2`.
    Roughly 36% of the verifier's field muls are squarings (the ladder's
    doublings and the decompression power chains), so the ~47% product
    saving here is a measurable slice of the whole pipeline
    (scripts/mul_microbench.py).

    Inside Mosaic kernels the sublane-axis pad/concatenate chain below has
    no validated lowering — route through :func:`mul`, whose column skews
    are the Mosaic-vetted forms.  (The kernel is recognizable by either
    Mosaic-mode flag: ``SKEW_IMPL == "shift"`` or ``CONST_MODE ==
    "scalars"`` — :mod:`mochi_tpu.crypto.pallas_verify` sets the latter.)
    """
    if (
        SKEW_IMPL == "shift"
        or CONST_MODE == "scalars"
        # mxu takes rank-2 squarings through the matmul reduction (the
        # 153-product saving applies to VPU work the matmul replaces);
        # other ranks keep the specialized symmetric schoolbook.
        or (SKEW_IMPL == "mxu" and len(a.shape) == 2)
    ):
        return mul(a, a)
    n = NLIMBS
    lanes = a.shape[1:]
    lane_pad = [(0, 0)] * len(lanes)
    cols_lo = None
    cols_hi = None
    for i in range(n):
        prod = a[i] * a[i:]  # (n-i, lanes), <= LOOSE^2 < 2^31
        lo = prod & MASK
        hi = prod >> RADIX
        # double off-diagonal (j > i) terms; diagonal stays single
        lo = jnp.concatenate([lo[:1], lo[1:] * 2], axis=0)
        hi = jnp.concatenate([hi[:1], hi[1:] * 2], axis=0)
        # row i covers columns k = i+j for j in [i, n): left pad 2i,
        # right pad (2n-2) - (i+n-1) = n-1-i
        lo = jnp.pad(lo, [(2 * i, n - 1 - i), *lane_pad])
        hi = jnp.pad(hi, [(2 * i, n - 1 - i), *lane_pad])
        cols_lo = lo if cols_lo is None else cols_lo + lo
        cols_hi = hi if cols_hi is None else cols_hi + hi
    return _fold_carry(cols_lo, cols_hi)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small python constant.

    k <= 3: columns <= 3*LOOSE < 2^17 -> one pass.  k < 2^10: columns
    < 2^26 -> two passes.  Larger constants route through the full multiply.
    """
    if 0 <= k <= 3:
        return _carry1(a * k)
    if 0 <= k < (1 << 10):
        return _carry2(a * k)
    return mul(a, const(k % P_INT, a.shape[1:]))


# ------------------------------------------------------------------- exact


def canonical(a: jnp.ndarray) -> jnp.ndarray:
    """Reduce a loose element to the unique representative < p.

    Loose value <= LOOSE * (2^255-1)/(2^15-1) < 1.003 * 2^255 < 2p.
    Exact chain -> limbs < 2^15 + cout in {0,1}; fold 19*cout -> value
    < 2^255 < p + 20; one conditional subtract of p settles it.
    """
    limbs, cout = _carry_chain(a)
    # limb-0 += 19*cout via concat (scatter-free: see one())
    limbs = limbs + jnp.concatenate(
        [(19 * cout)[None], jnp.zeros((NLIMBS - 1, *cout.shape), jnp.int32)], axis=0
    )
    limbs, _ = _carry_chain(limbs)

    p_vec = _limb_vec(_P_LIMBS_NP, a.shape[1:])
    diff, borrow = _carry_chain(limbs - p_vec)
    return jnp.where((borrow >= 0), diff, limbs)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field equality (canonicalizes both sides). Returns bool (lanes,)."""
    return jnp.all(canonical(a) == canonical(b), axis=0)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == 0, axis=0)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Branchless limb select: cond (lanes,) bool -> a or b (17, lanes)."""
    return jnp.where(cond[None], a, b)


# --------------------------------------------------------------- powering


def _square_n(a: jnp.ndarray, n: int) -> jnp.ndarray:
    """a^(2^n): n squarings as one fori_loop (graph stays one mul body)."""
    return lax.fori_loop(0, n, lambda i, x: square(x), a, unroll=False)


def pow_p58(a: jnp.ndarray) -> jnp.ndarray:
    """a^((p-5)/8) = a^(2^252 - 3), ref10's pow22523 addition chain:
    254 squarings (fori_loops) + 12 multiplies — vs 255 squarings *and*
    255 muls for naive bit-scan square-and-multiply."""
    z2 = square(a)  # 2
    z8 = _square_n(z2, 2)  # 8
    z9 = mul(a, z8)  # 9
    z11 = mul(z2, z9)  # 11
    z22 = square(z11)  # 22
    z_5_0 = mul(z9, z22)  # 2^5 - 1
    z_10_0 = mul(_square_n(z_5_0, 5), z_5_0)  # 2^10 - 1
    z_20_0 = mul(_square_n(z_10_0, 10), z_10_0)  # 2^20 - 1
    z_40_0 = mul(_square_n(z_20_0, 20), z_20_0)  # 2^40 - 1
    z_50_0 = mul(_square_n(z_40_0, 10), z_10_0)  # 2^50 - 1
    z_100_0 = mul(_square_n(z_50_0, 50), z_50_0)  # 2^100 - 1
    z_200_0 = mul(_square_n(z_100_0, 100), z_100_0)  # 2^200 - 1
    z_250_0 = mul(_square_n(z_200_0, 50), z_50_0)  # 2^250 - 1
    return mul(_square_n(z_250_0, 2), a)  # 2^252 - 3


def invert(a: jnp.ndarray) -> jnp.ndarray:
    """a^(p-2) (Fermat), via the pow22523 chain: p-2 = 2^255 - 21 and
    2^255 - 21 = 8*(2^252 - 3) + 3, so a^(p-2) = (a^(2^252-3))^8 * a^3."""
    t = pow_p58(a)  # a^(2^252 - 3)
    t = _square_n(t, 3)  # a^(2^255 - 24)
    return mul(t, mul(square(a), a))  # * a^3

"""Ed25519 group operations in extended twisted-Edwards coordinates — JAX.

Everything is branchless and fixed-shape so the whole verify lowers to one
XLA program: the addition law used here is the *complete* law for twisted
Edwards curves with a = -1 (a is a square mod p since p === 1 mod 4, d is a
non-square), so identity/doubling/degenerate cases need no case analysis —
exactly the property that makes Ed25519 verification map cleanly onto a
vector machine (SURVEY.md §7: "no data-dependent Python control flow").

A point is a 4-tuple (X, Y, Z, T) of field elements (int32 ``(..., 16)``
limb arrays, :mod:`mochi_tpu.crypto.field`), with x = X/Z, y = Y/Z,
T = XY/Z.  Scalars arrive as little-endian bit arrays ``(..., 256)``
precomputed on the host (the host also does SHA-512 and the mod-L
reduction: variable-length hashing is host work; the device sees only
fixed-shape integer tensors).

The reference never implements any of this (it never signs — SURVEY.md
preamble); this is the north-star TPU verifier path of BASELINE.json.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import numpy as np

import jax.numpy as jnp
from jax import lax

from . import field as F


class Point(NamedTuple):
    """Extended coordinates (X : Y : Z : T), x=X/Z, y=Y/Z, T=XY/Z."""

    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


def identity(batch_shape) -> Point:
    zero = F.zeros_like_batch(batch_shape)
    one = zero.at[..., 0].set(1)
    return Point(zero, one, one, zero)


def basepoint(batch_shape) -> Point:
    """The Ed25519 basepoint B, broadcast over a batch."""
    bx = jnp.broadcast_to(F.const(F.BX_INT), (*batch_shape, F.NLIMBS))
    by = jnp.broadcast_to(F.const(F.BY_INT), (*batch_shape, F.NLIMBS))
    one = F.zeros_like_batch(batch_shape).at[..., 0].set(1)
    return Point(bx, by, one, F.mul(bx, by))


# 2*d mod p, a trace-time constant
_D2_INT = (2 * F.D_INT) % F.P_INT


def add(p: Point, q: Point) -> Point:
    """Complete unified addition (add-2008-hwcd-3, a=-1). ~9 field muls."""
    a = F.mul(F.sub(p.y, p.x), F.sub(q.y, q.x))
    b = F.mul(F.add(p.y, p.x), F.add(q.y, q.x))
    c = F.mul(F.mul(p.t, F.const(_D2_INT)), q.t)
    d = F.mul(F.add(p.z, p.z), q.z)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def double(p: Point) -> Point:
    """Doubling (dbl-2008-hwcd, a=-1). ~4 muls + 4 squares."""
    a = F.square(p.x)
    b = F.square(p.y)
    c = F.mul_small(F.square(p.z), 2)
    h = F.add(a, b)
    e = F.sub(h, F.square(F.add(p.x, p.y)))
    g = F.sub(a, b)
    f = F.add(c, g)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def negate(p: Point) -> Point:
    return Point(F.neg(p.x), p.y, p.z, F.neg(p.t))


def select_point(cond: jnp.ndarray, p: Point, q: Point) -> Point:
    return Point(*(F.select(cond, a, b) for a, b in zip(p, q)))


def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray) -> Tuple[Point, jnp.ndarray]:
    """RFC 8032 §5.1.3 point decoding, batched and branchless.

    ``y_limbs``: (..., 16) with y < p (host prechecks canonicity);
    ``sign``: (...,) int32 in {0,1} — the x-parity bit from byte 31.
    Returns (point with Z=1, ok) where ok=False marks non-points
    (x^2 = u/v has no root, or x=0 with sign=1).
    """
    yy = F.square(y_limbs)
    one = F.zeros_like_batch(y_limbs.shape[:-1]).at[..., 0].set(1)
    u = F.sub(yy, one)  # y^2 - 1
    v = F.add(F.mul(yy, F.const(F.D_INT)), one)  # d*y^2 + 1

    # candidate root x = u * v^3 * (u*v^7)^((p-5)/8)
    v3 = F.mul(F.square(v), v)
    v7 = F.mul(F.square(v3), v)
    x = F.mul(F.mul(u, v3), F.pow_p58(F.mul(u, v7)))

    vxx = F.mul(v, F.square(x))
    root_ok = F.eq(vxx, u)
    root_neg = F.eq(vxx, F.neg(u))
    x = F.select(root_neg, F.mul(x, F.const(F.SQRT_M1_INT)), x)
    ok = root_ok | root_neg

    x_can = F.canonical(x)
    x_is_zero = F.is_zero(x)
    ok = ok & ~(x_is_zero & (sign == 1))
    # flip sign to match the encoded parity bit
    flip = (x_can[..., 0] & 1) != sign
    x = F.select(flip, F.neg(x), x)

    return Point(x, y_limbs, one, F.mul(x, y_limbs)), ok


def double_scalar_mul(
    s_bits: jnp.ndarray, p_bits: jnp.ndarray, p_point: Point
) -> Point:
    """[s]B + [p]P by joint 1-bit Straus: 256 x (double + complete add).

    ``s_bits``/``p_bits``: (..., 256) little-endian bits.  The 4-entry
    table {O, B, P, B+P} is gathered per item per iteration — data-dependent
    *gathers* are fine under jit; only control flow must be static.
    """
    batch_shape = s_bits.shape[:-1]
    bp = basepoint(batch_shape)
    tab_o = identity(batch_shape)
    tab_bp = add(bp, p_point)
    # per coordinate: (..., 4, limbs) — table entries stacked on a new axis
    table = [
        jnp.stack([o, b, p, s], axis=-2)
        for o, b, p, s in zip(tab_o, bp, p_point, tab_bp)
    ]

    def body(i, q):
        bit_idx = 255 - i
        sb = s_bits[..., bit_idx]
        pb = p_bits[..., bit_idx]
        q = double(q)
        idx = (sb + 2 * pb).astype(jnp.int32)
        entry = Point(
            *(
                jnp.take_along_axis(t, idx[..., None, None], axis=-2).squeeze(-2)
                for t in table
            )
        )
        return add(q, entry)

    q0 = identity(batch_shape)
    q = lax.fori_loop(0, 256, body, q0)
    return q


# --------------------------------------------------------------------------
# Windowed double-scalar-mul: 4-bit digits.  vs the 1-bit Straus ladder:
# same 256 doublings but 64+64 windowed additions instead of 256 complete
# additions, and the base-point additions use a precomputed constant table
# in Niels form (y+x, y-x, 2dxy) which saves 2 muls per addition.
# ~3200 field muls/signature vs ~4900 for the 1-bit ladder.

_NIELS_IDENTITY = (1, 1, 0)  # (y+x, y-x, 2dxy) of the neutral element


def _py_edwards_add(p, q):
    """Affine Edwards addition on python ints (host, table precompute only)."""
    P, D = F.P_INT, F.D_INT
    x1, y1 = p
    x2, y2 = q
    k = D * x1 * x2 * y1 * y2 % P
    x3 = (x1 * y2 + x2 * y1) * pow(1 + k, P - 2, P) % P
    y3 = (y1 * y2 + x1 * x2) * pow(1 - k, P - 2, P) % P
    return (x3, y3)


def _basepoint_niels_table() -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """[d]B for d in 0..15 in Niels form, as three (16, NLIMBS) int32 arrays."""
    b = (F.BX_INT, F.BY_INT)
    pts = [(0, 1)]  # identity
    for _ in range(15):
        pts.append(_py_edwards_add(pts[-1], b))
    ypx = np.stack([F.int_to_limbs((y + x) % F.P_INT) for x, y in pts])
    ymx = np.stack([F.int_to_limbs((y - x) % F.P_INT) for x, y in pts])
    xy2d = np.stack(
        [F.int_to_limbs(2 * F.D_INT * x * y % F.P_INT) for x, y in pts]
    )
    return ypx, ymx, xy2d


_B_TAB_YPX, _B_TAB_YMX, _B_TAB_XY2D = _basepoint_niels_table()


def madd_niels(
    p: Point, ypx: jnp.ndarray, ymx: jnp.ndarray, xy2d: jnp.ndarray
) -> Point:
    """Mixed addition with a precomputed Niels-form point (madd-2008-hwcd-3).

    7 field muls; complete for the same reason as :func:`add` (a = -1,
    d non-square), and the identity entry (1, 1, 0) is handled uniformly.
    """
    a = F.mul(F.sub(p.y, p.x), ymx)
    b = F.mul(F.add(p.y, p.x), ypx)
    c = F.mul(xy2d, p.t)
    d = F.add(p.z, p.z)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def digits4_from_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(..., 256) little-endian bits -> (..., 64) base-16 digits."""
    w = jnp.asarray([1, 2, 4, 8], dtype=jnp.int32)
    return jnp.einsum(
        "...wb,b->...w", bits.reshape(*bits.shape[:-1], 64, 4), w
    ).astype(jnp.int32)


def _small_multiples_table(p: Point) -> list:
    """[0..15]P in extended coords, per coordinate stacked on axis -2."""
    pts = [identity(p.x.shape[:-1]), p]
    for k in range(2, 16):
        pts.append(double(pts[k // 2]) if k % 2 == 0 else add(pts[k - 1], p))
    return [jnp.stack(coord, axis=-2) for coord in zip(*pts)]


def double_scalar_mul_windowed(
    s_bits: jnp.ndarray, p_bits: jnp.ndarray, p_point: Point
) -> Point:
    """[s]B + [p]P with 4-bit windows, msb-first over 64 windows.

    Per window: 4 doublings, one complete addition from the per-item
    [0..15]P table (data-dependent gather), one Niels mixed addition from
    the constant [0..15]B table (shared gather).
    """
    batch_shape = s_bits.shape[:-1]
    s_dig = digits4_from_bits(s_bits)
    p_dig = digits4_from_bits(p_bits)
    a_tab = _small_multiples_table(p_point)
    b_ypx = jnp.asarray(_B_TAB_YPX)
    b_ymx = jnp.asarray(_B_TAB_YMX)
    b_xy2d = jnp.asarray(_B_TAB_XY2D)

    def body(i, q):
        w = 63 - i
        q = double(double(double(double(q))))
        pd = p_dig[..., w]
        entry = Point(
            *(
                jnp.take_along_axis(t, pd[..., None, None], axis=-2).squeeze(-2)
                for t in a_tab
            )
        )
        q = add(q, entry)
        sd = s_dig[..., w]
        q = madd_niels(
            q,
            jnp.take(b_ypx, sd, axis=0),
            jnp.take(b_ymx, sd, axis=0),
            jnp.take(b_xy2d, sd, axis=0),
        )
        return q

    return lax.fori_loop(0, 64, body, identity(batch_shape))


def verify_prepared(
    y_a: jnp.ndarray,
    sign_a: jnp.ndarray,
    y_r: jnp.ndarray,
    sign_r: jnp.ndarray,
    s_bits: jnp.ndarray,
    h_bits: jnp.ndarray,
) -> jnp.ndarray:
    """Core batched verify on host-prepared tensors -> validity bitmap.

    Checks the cofactorless equation [S]B == R + [h]A (as OpenSSL/the CPU
    path does), rearranged to Q := [S]B + [h](-A), Q == R, compared
    projectively (X_Q == x_R * Z_Q, Y_Q == y_R * Z_Q) to avoid an inversion.
    SHA-512, mod-L reduction, and canonical-encoding prechecks (y < p, S < L)
    happen on the host (:mod:`mochi_tpu.crypto.batch_verify`).
    """
    a_point, ok_a = decompress(y_a, sign_a)
    r_point, ok_r = decompress(y_r, sign_r)
    q = double_scalar_mul_windowed(s_bits, h_bits, negate(a_point))
    eq_x = F.eq(q.x, F.mul(r_point.x, q.z))
    eq_y = F.eq(q.y, F.mul(r_point.y, q.z))
    return ok_a & ok_r & eq_x & eq_y

"""Ed25519 group operations in extended twisted-Edwards coordinates — JAX.

Everything is branchless and fixed-shape so the whole verify lowers to one
XLA program: the addition law used here is the *complete* law for twisted
Edwards curves with a = -1 (a is a square mod p since p === 1 mod 4, d is a
non-square), so identity/doubling/degenerate cases need no case analysis —
exactly the property that makes Ed25519 verification map cleanly onto a
vector machine (SURVEY.md §7: "no data-dependent Python control flow").

Layout (round-2 rework): **limbs-leading**.  A point is a 4-tuple
(X, Y, Z, T) of field elements shaped ``(17, ...lanes)``
(:mod:`mochi_tpu.crypto.field`), with x = X/Z, y = Y/Z, T = XY/Z — batch on
the trailing lane axis, which is the TPU's 128-wide vector axis, so every
field op runs on dense lane vectors.  This is the layout the round-1 Pallas
kernel introduced; now it *is* the XLA path, and the Pallas kernel
(:mod:`mochi_tpu.crypto.pallas_verify`) wraps the same :func:`verify_core`.

Scalars arrive as little-endian bit arrays precomputed on the host (the host
also does SHA-512 and the mod-L reduction: variable-length hashing is host
work; the device sees only fixed-shape integer tensors).

Table lookups in the windowed ladder are branchless masked-select sums
(:func:`select_entry`): data-dependent per-lane gathers don't vectorize on
the TPU VPU; 9 masked adds (signed windows) do.

The reference never implements any of this (it never signs — SURVEY.md
preamble); this is the north-star TPU verifier path of BASELINE.json.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import numpy as np

import jax.numpy as jnp
from jax import lax

from . import field as F

# Mosaic-safe mode (set by the Pallas kernel wrapper): Mosaic TC lowering has
# no dynamic_slice on values, so the two data-dependent indexing sites in the
# ladder (per-window digit extraction, per-slot table write) switch to
# branchless masked forms, and the small-multiples table is built by 15
# unrolled additions instead of a fori_loop of dynamic updates.
MOSAIC_SAFE = False

# Ladder fori_loop unroll factor (1 = loop 64 window bodies; higher trades
# compile time for a larger per-iteration fusion scope on the VPU).
# Measured on v5e with scripts/unroll_bench.py before changing.
LADDER_UNROLL = 1

# Table-select formulation (MOCHI_SELECT_IMPL):
#   "per-coord" — round-2 form (one masked sum per coordinate array) —
#                 the DEFAULT: it produced the measured 111k sigs/s
#                 headline, and the headline capture must run the proven
#                 config (chip time is scarce; a regressing default could
#                 burn the only live window).
#   "stacked"   — ONE masked 9-entry sum per table over the coords
#                 concatenated on the limb axis ((9, 68|51, lanes)): 9 adds
#                 + 9 selects per lookup instead of 63 per-coordinate op
#                 chains; fewer HLO ops for the scheduler to place.
#                 Candidate, A/B'd by the measurement battery step 3b.
import os as _os

SELECT_IMPL = _os.environ.get("MOCHI_SELECT_IMPL", "per-coord")


class Point(NamedTuple):
    """Extended coordinates (X : Y : Z : T), x=X/Z, y=Y/Z, T=XY/Z.
    Each coordinate is a (17, ...lanes) limb array."""

    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


def identity(lanes) -> Point:
    zero = F.zeros(lanes)
    one = F.one(lanes)
    return Point(zero, one, one, zero)


def basepoint(lanes) -> Point:
    """The Ed25519 basepoint B, broadcast over the lane shape."""
    bx = F.const(F.BX_INT, lanes)
    by = F.const(F.BY_INT, lanes)
    return Point(bx, by, F.one(lanes), F.mul(bx, by))


# 2*d mod p, a trace-time constant
_D2_INT = (2 * F.D_INT) % F.P_INT


def add(p: Point, q: Point) -> Point:
    """Complete unified addition (add-2008-hwcd-3, a=-1). ~9 field muls."""
    a = F.mul(F.sub(p.y, p.x), F.sub(q.y, q.x))
    b = F.mul(F.add(p.y, p.x), F.add(q.y, q.x))
    c = F.mul(F.mul(p.t, F.const(_D2_INT, p.t.shape[1:])), q.t)
    d = F.mul(F.add(p.z, p.z), q.z)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def double(p: Point) -> Point:
    """Doubling (dbl-2008-hwcd, a=-1). ~4 muls + 4 squares."""
    a = F.square(p.x)
    b = F.square(p.y)
    c = F.mul_small(F.square(p.z), 2)
    h = F.add(a, b)
    e = F.sub(h, F.square(F.add(p.x, p.y)))
    g = F.sub(a, b)
    f = F.add(c, g)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def negate(p: Point) -> Point:
    return Point(F.neg(p.x), p.y, p.z, F.neg(p.t))


def select_point(cond: jnp.ndarray, p: Point, q: Point) -> Point:
    return Point(*(F.select(cond, a, b) for a, b in zip(p, q)))


def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray) -> Tuple[Point, jnp.ndarray]:
    """RFC 8032 §5.1.3 point decoding, batched and branchless.

    ``y_limbs``: (17, lanes) with y < p (host prechecks canonicity);
    ``sign``: (lanes,) int32 in {0,1} — the x-parity bit from byte 31.
    Returns (point with Z=1, ok) where ok=False marks non-points
    (x^2 = u/v has no root, or x=0 with sign=1).
    """
    lanes = y_limbs.shape[1:]
    yy = F.square(y_limbs)
    one = F.one(lanes)
    u = F.sub(yy, one)  # y^2 - 1
    v = F.add(F.mul(yy, F.const(F.D_INT, lanes)), one)  # d*y^2 + 1

    # candidate root x = u * v^3 * (u*v^7)^((p-5)/8)
    v3 = F.mul(F.square(v), v)
    v7 = F.mul(F.square(v3), v)
    x = F.mul(F.mul(u, v3), F.pow_p58(F.mul(u, v7)))

    vxx = F.mul(v, F.square(x))
    root_ok = F.eq(vxx, u)
    root_neg = F.eq(vxx, F.neg(u))
    x = F.select(root_neg, F.mul(x, F.const(F.SQRT_M1_INT, lanes)), x)
    ok = root_ok | root_neg

    x_can = F.canonical(x)
    x_is_zero = jnp.all(x_can == 0, axis=0)
    ok = ok & ~(x_is_zero & (sign == 1))
    # flip sign to match the encoded parity bit
    flip = (x_can[0] & 1) != sign
    x = F.select(flip, F.neg(x), x)

    return Point(x, y_limbs, one, F.mul(x, y_limbs)), ok


# --------------------------------------------------------------------------
# Windowed double-scalar-mul: 4-bit digits, msb-first over 64 windows.
# Per window: 4 doublings, one complete addition from the per-item [0..8]P
# table (signed digits), one Niels mixed addition from the constant [0..8]B
# table (saves
# 2 muls per addition).  ~3200 field muls/signature.


def _py_edwards_add(p, q):
    """Affine Edwards addition on python ints (host, table precompute only)."""
    P, D = F.P_INT, F.D_INT
    x1, y1 = p
    x2, y2 = q
    k = D * x1 * x2 * y1 * y2 % P
    x3 = (x1 * y2 + x2 * y1) * pow(1 + k, P - 2, P) % P
    y3 = (y1 * y2 + x1 * x2) * pow(1 - k, P - 2, P) % P
    return (x3, y3)


def _basepoint_niels_table() -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """[d]B for d in 0..8 in Niels form, as three (9, NLIMBS) int32 arrays.

    9 entries, not 16: the ladder uses SIGNED 4-bit windows (digits in
    [-8, 8]) — negative digits reuse entry |d| with the cheap Niels
    negation (swap y+x / y-x, negate xy2d).
    """
    b = (F.BX_INT, F.BY_INT)
    pts = [(0, 1)]  # identity
    for _ in range(8):
        pts.append(_py_edwards_add(pts[-1], b))
    ypx = np.stack([F.int_to_limbs((y + x) % F.P_INT) for x, y in pts])
    ymx = np.stack([F.int_to_limbs((y - x) % F.P_INT) for x, y in pts])
    xy2d = np.stack(
        [F.int_to_limbs(2 * F.D_INT * x * y % F.P_INT) for x, y in pts]
    )
    return ypx, ymx, xy2d


_B_TAB_YPX, _B_TAB_YMX, _B_TAB_XY2D = _basepoint_niels_table()


def madd_niels(
    p: Point, ypx: jnp.ndarray, ymx: jnp.ndarray, xy2d: jnp.ndarray
) -> Point:
    """Mixed addition with a precomputed Niels-form point (madd-2008-hwcd-3).

    7 field muls; complete for the same reason as :func:`add` (a = -1,
    d non-square), and the identity entry (1, 1, 0) is handled uniformly.
    """
    a = F.mul(F.sub(p.y, p.x), ymx)
    b = F.mul(F.add(p.y, p.x), ypx)
    c = F.mul(xy2d, p.t)
    d = F.add(p.z, p.z)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def digits4_from_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(256, lanes) little-endian bits -> (64, lanes) base-16 digits."""
    lanes = bits.shape[1:]
    w = jnp.asarray([1, 2, 4, 8], dtype=jnp.int32).reshape(1, 4, *([1] * len(lanes)))
    return (bits.reshape(64, 4, *lanes) * w).sum(axis=1).astype(jnp.int32)


def recode_signed4(dig: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Base-16 digits [0,15] -> signed digits: (magnitude [0,8], neg flag).

    Exact carry recode: d + c_in = d' + 16*c_out with d' in [-7, 8]
    (t = d + c_in in [0, 16]; t > 8 maps to t - 16 in [-7, 0] with carry).
    The final window of an Ed25519 scalar < 2^253 is <= 1, so the last
    carry never overflows.  Signed windows HALVE the per-item table — the VMEM
    limiter that capped throughput at batch 4096 — and cut the masked
    lookup from 16 to 9 terms.
    """
    mags, negs = [], []
    c = jnp.zeros(dig.shape[1:], dtype=jnp.int32)
    for k in range(dig.shape[0]):
        t = dig[k] + c
        carry = (t > 8).astype(jnp.int32)
        d = t - 16 * carry  # in [-8, 8]
        c = carry
        neg = d < 0
        mags.append(jnp.where(neg, -d, d))
        negs.append(neg)
    return jnp.stack(mags, axis=0), jnp.stack(negs, axis=0)


def select_entry(table, idx: jnp.ndarray, n_entries: int):
    """Branchless per-lane table lookup: sum of masked entries.

    ``table``: sequence of arrays with entry axis 0, every coordinate the
    SAME shape ``(n_entries, 17, lanes)`` or the same lane-constant shape
    ``(n_entries, 17, 1)`` (the stacked path concatenates them on axis 1,
    so lane dims may not mix within one table); ``idx``: (lanes,) int32.
    Data-dependent per-lane gathers don't vectorize on the VPU; n_entries
    masked adds do.

    With ``SELECT_IMPL == "stacked"`` the coordinate arrays are
    concatenated on the limb axis first, so the whole lookup is ONE
    9-term masked sum over a (n_entries, n_coords*17, lanes) array —
    9 where+add pairs instead of 9*n_coords — then split back.
    """
    if SELECT_IMPL == "stacked" and len(table) > 1:
        stacked, widths = stack_table(table, n_entries, idx.shape)
        return select_entry_stacked(stacked, widths, idx, n_entries)
    out = []
    for coord in table:
        acc = jnp.zeros_like(coord[0] + jnp.zeros_like(idx))
        for e in range(n_entries):
            acc = acc + jnp.where((idx == e)[None], coord[e], 0)
        out.append(acc)
    return tuple(out)


def stack_table(table, n_entries: int, lanes):
    """Concatenate a table's coordinate arrays on the limb axis (hoist this
    OUTSIDE the ladder loop — the concat would otherwise re-materialize
    every iteration).

    No lane broadcast here: the basepoint table's coords are (9, 17, 1)
    lane-constants, and broadcasting them to (9, 17, B) before the concat
    would materialize a per-lane copy of a constant table (~15 MB at
    B=8192) that the per-coordinate form never built.  The masked select
    broadcasts against ``idx`` lazily, exactly as before.
    """
    del n_entries, lanes  # shapes come from the coords themselves
    widths = [c.shape[1] for c in table]
    return jnp.concatenate(list(table), axis=1), widths


def select_entry_stacked(stacked, widths, idx: jnp.ndarray, n_entries: int):
    """One masked n_entries-term sum over the stacked array, then split."""
    acc = jnp.zeros(stacked.shape[1:], stacked.dtype)
    for e in range(n_entries):
        acc = acc + jnp.where((idx == e)[None], stacked[e], 0)
    out = []
    off = 0
    for w in widths:
        out.append(acc[off : off + w])
        off += w
    return tuple(out)


N_TABLE = 9  # [0..8]P — signed 4-bit windows need magnitudes 0..8 only


def _small_multiples_table(p: Point):
    """[0..8]P stacked on axis 0 — built by 8 chained additions inside ONE
    fori_loop body (vs unrolled point ops: much smaller traced graph).

    Mosaic-safe mode unrolls the chain and stacks at the end (no dynamic
    updates); the extra ~70 traced muls are acceptable inside the kernel.
    """
    lanes = p.x.shape[1:]
    ident = identity(lanes)
    if MOSAIC_SAFE:
        pts = [ident]
        for _ in range(N_TABLE - 1):
            pts.append(add(pts[-1], p))
        return tuple(
            jnp.stack([getattr(pt, c) for pt in pts], axis=0)
            for c in ("x", "y", "z", "t")
        )
    table = tuple(
        jnp.zeros((N_TABLE, F.NLIMBS, *lanes), jnp.int32).at[0].set(c) for c in ident
    )

    def chain(k, carry):
        table, prev = carry
        cur = add(Point(*prev), p)
        table = tuple(
            lax.dynamic_update_index_in_dim(t, c, k, axis=0)
            for t, c in zip(table, cur)
        )
        return (table, tuple(cur))

    table, _ = lax.fori_loop(1, N_TABLE, chain, (table, tuple(ident)))
    return table


def double_scalar_mul_windowed(
    s_dig: jnp.ndarray, p_dig: jnp.ndarray, p_point: Point, b_tab=None
) -> Point:
    """[s]B + [p]P with 4-bit windows, msb-first over 64 windows.

    ``s_dig``/``p_dig``: (64, lanes) base-16 digits (little-endian windows)
    — recoded internally to signed digits (:func:`recode_signed4`).
    ``b_tab``: optional externally-supplied Niels basepoint tables — three
    (9, 17[, 1]) arrays.  Pallas kernels pass them as operands (Mosaic
    rejects closure-captured array constants); the XLA path leaves this None
    and embeds them as literals.
    """
    lanes = s_dig.shape[1:]
    s_mag, s_neg = recode_signed4(s_dig)
    p_mag, p_neg = recode_signed4(p_dig)
    a_tab = _small_multiples_table(p_point)
    if b_tab is None:
        b_tab = tuple(
            jnp.asarray(t)[..., None] if lanes else jnp.asarray(t)
            for t in (_B_TAB_YPX, _B_TAB_YMX, _B_TAB_XY2D)
        )

    if MOSAIC_SAFE:
        # No dynamic_slice in Mosaic: extract window w's digits with a
        # branchless masked reduce over the window axis.
        win_iota = lax.broadcasted_iota(jnp.int32, (64, *lanes), 0)

        def digit_at(dig, w):
            return jnp.sum(jnp.where(win_iota == w, dig, 0), axis=0)

    else:

        def digit_at(dig, w):
            return lax.dynamic_index_in_dim(dig, w, axis=0, keepdims=False)

    if SELECT_IMPL == "stacked":
        # Hoisted once; each ladder iteration then does ONE 9-term masked
        # sum per table instead of one per coordinate array.
        a_stacked, a_widths = stack_table(a_tab, N_TABLE, lanes)
        b_stacked, b_widths = stack_table(b_tab, N_TABLE, lanes)

        def a_select(idx):
            return select_entry_stacked(a_stacked, a_widths, idx, N_TABLE)

        def b_select(idx):
            return select_entry_stacked(b_stacked, b_widths, idx, N_TABLE)

    else:

        def a_select(idx):
            return select_entry(a_tab, idx, N_TABLE)

        def b_select(idx):
            return select_entry(b_tab, idx, N_TABLE)

    def body(i, q):
        w = 63 - i
        q = double(double(double(double(Point(*q)))))
        ex, ey, ez, et = a_select(digit_at(p_mag, w))
        pn = digit_at(p_neg.astype(jnp.int32), w).astype(bool)
        # negative digit: -(x, y, z, t) = (-x, y, z, -t), branchless
        entry = Point(
            F.select(pn, F.neg(ex), ex), ey, ez, F.select(pn, F.neg(et), et)
        )
        q = add(q, entry)
        nypx, nymx, nxy2d = b_select(digit_at(s_mag, w))
        sn = digit_at(s_neg.astype(jnp.int32), w).astype(bool)
        # Niels negation: swap (y+x)/(y-x), negate xy2d
        nypx, nymx = (
            F.select(sn, nymx, nypx),
            F.select(sn, nypx, nymx),
        )
        nxy2d = F.select(sn, F.neg(nxy2d), nxy2d)
        return tuple(madd_niels(q, nypx, nymx, nxy2d))

    q = lax.fori_loop(0, 64, body, tuple(identity(lanes)), unroll=LADDER_UNROLL)
    return Point(*q)


def verify_core(
    y_a: jnp.ndarray,
    sign_a: jnp.ndarray,
    y_r: jnp.ndarray,
    sign_r: jnp.ndarray,
    s_dig: jnp.ndarray,
    h_dig: jnp.ndarray,
    b_tab=None,
) -> jnp.ndarray:
    """Limbs-leading batched verify -> validity bitmap (lanes,) bool.

    Inputs: ``y_a``/``y_r`` (17, lanes) limb tensors; ``sign_*`` (lanes,);
    ``s_dig``/``h_dig`` (64, lanes) base-16 scalar digits; ``b_tab`` see
    :func:`double_scalar_mul_windowed`.

    Checks the cofactorless equation [S]B == R + [h]A (as OpenSSL/the CPU
    path does), rearranged to Q := [S]B + [h](-A), Q == R, compared
    projectively (X_Q == x_R * Z_Q, Y_Q == y_R * Z_Q) to avoid an inversion.
    This function is the shared core of the XLA path
    (:func:`verify_prepared`) and the Pallas kernel
    (:mod:`mochi_tpu.crypto.pallas_verify`).
    """
    # (Measured and rejected: fusing the A/R decompressions into one
    # (17, 2B) call to halve the pow_p58 sequential depth — 108.7k vs
    # ~110k sigs/s at batch 8192 depth-8; the doubled lane width during
    # decompress cancels the depth win at the production bucket size.)
    a_point, ok_a = decompress(y_a, sign_a)
    r_point, ok_r = decompress(y_r, sign_r)
    q = double_scalar_mul_windowed(s_dig, h_dig, negate(a_point), b_tab=b_tab)
    eq_x = F.eq(q.x, F.mul(r_point.x, q.z))
    eq_y = F.eq(q.y, F.mul(r_point.y, q.z))
    return ok_a & ok_r & eq_x & eq_y


def verify_prepared_packed(
    y_a: jnp.ndarray,
    sign_a: jnp.ndarray,
    y_r: jnp.ndarray,
    sign_r: jnp.ndarray,
    s_bytes: jnp.ndarray,
    h_bytes: jnp.ndarray,
) -> jnp.ndarray:
    """Like :func:`verify_prepared` but scalars arrive as (B, 32) uint8
    little-endian BYTES and are bit-unpacked on device — 32x less
    host->device transfer per scalar, which is the binding cost for
    end-to-end batches shipped through a remote-device tunnel
    (measured: the (B, 256) int32 bit tensors are ~8 MB per 8192-chunk
    each; the byte forms are 256 KB)."""
    return verify_prepared(
        y_a, sign_a, y_r, sign_r, unpack_bits(s_bytes), unpack_bits(h_bytes)
    )


def unpack_bits(b: jnp.ndarray) -> jnp.ndarray:
    """(B, 32) uint8 packed scalar bytes -> (B, 256) int32 LE bits — the
    on-device half of the packed-transfer format (shared with the comb
    path, :mod:`mochi_tpu.crypto.comb`)."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (b[:, :, None] >> shifts[None, None, :]) & 1
    return bits.reshape(b.shape[0], 256).astype(jnp.int32)


def verify_prepared(
    y_a: jnp.ndarray,
    sign_a: jnp.ndarray,
    y_r: jnp.ndarray,
    sign_r: jnp.ndarray,
    s_bits: jnp.ndarray,
    h_bits: jnp.ndarray,
) -> jnp.ndarray:
    """Batched verify on host-prepared batch-leading tensors -> (B,) bitmap.

    External API (unchanged from round 1 modulo limb count): ``y_a``/``y_r``
    (B, 17), ``sign_*`` (B,), ``s_bits``/``h_bits`` (B, 256) little-endian
    bits.  SHA-512, mod-L reduction, and canonical-encoding prechecks
    (y < p, S < L) happen on the host
    (:mod:`mochi_tpu.crypto.batch_verify`).  Internally transposes to the
    limbs-leading layout (one fused transpose each way in XLA).
    """
    s_dig = digits4_from_bits(s_bits.T)
    h_dig = digits4_from_bits(h_bits.T)
    return verify_core(y_a.T, sign_a, y_r.T, sign_r, s_dig, h_dig)

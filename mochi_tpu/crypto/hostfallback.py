"""Pure-Python Ed25519 + X25519 host fallback (no ``cryptography`` needed).

The host ``cryptography`` wheel (OpenSSL) is an *optional* accelerator: on a
bare ``numpy+jax+pytest`` environment :mod:`mochi_tpu.crypto.keys` and
:mod:`mochi_tpu.crypto.session` fall back to this module, so every layer —
replicas, clients, the verifier service, the whole test tree — runs without
it.  Built on the repo's own curve arithmetic: the constants and the
extended-coordinate a=-1 addition law below are the integer originals of
:mod:`mochi_tpu.crypto.field` / :mod:`mochi_tpu.crypto.curve` (same
add-2008-hwcd-3 formulas the JAX data plane traces; see ``curve.add``),
evaluated on Python ints instead of limb tensors.

Semantics match :func:`mochi_tpu.crypto.keys.verify` exactly: callers run
the strict canonical-encoding prechecks first, then this module applies the
cofactorless check ``[S]B == R + [h]A`` — the same verdict OpenSSL and the
TPU batch path produce, so a mixed cluster (some nodes with OpenSSL, some
without) still agrees on every signature, which BFT safety requires.

NOT constant-time: Python big-int arithmetic is variable-time by nature and
the double-and-add ladder branches on scalar bits.  That is an accepted
property of the *fallback* (same posture as the reference's total absence
of crypto); production deployments install ``cryptography``.  The
variable-time operations are confined to this module so the constant-time
checker's scope stays meaningful everywhere else.

Native-C engine: on hosts with a toolchain but no ``cryptography`` wheel,
:func:`verify` and :func:`sign` route through ``native/hbatch.c`` — full
Ed25519 verification (Straus ladder on 51-bit limbs, the same cofactorless
check) and deterministic RFC 8032 signing (doubling-free fixed-base comb,
bit-identical output to this module and OpenSSL — the replica's own-grant
re-sign-and-compare depends on that).  Differential suite:
``tests/test_native_ed25519.py`` (forgeries, non-canonical encodings,
low-order points, sign determinism).  That cuts the ~1.2 ms pure-Python
verify / ~0.4 ms sign to tens of microseconds; key expansion, X25519 and
the curve math below stay pure Python, the automatic fallback when no C
toolchain exists and the single implementation the native engine is
tested against.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache
from typing import Optional, Tuple

from .field import BX_INT, BY_INT, D_INT, L_INT, P_INT, SQRT_M1_INT

_P = P_INT
_L = L_INT
_D2 = (2 * D_INT) % _P

# Extended twisted-Edwards coordinates (X, Y, Z, T): x = X/Z, y = Y/Z,
# T = XY/Z — the exact layout of ``curve.Point``, on ints.
_Pt = Tuple[int, int, int, int]
_IDENT: _Pt = (0, 1, 1, 0)
_BASE: _Pt = (BX_INT, BY_INT, 1, (BX_INT * BY_INT) % _P)


def _pt_add(p: _Pt, q: _Pt) -> _Pt:
    """Complete unified addition (add-2008-hwcd-3, a=-1) — ``curve.add``
    on ints.  Complete: also serves as doubling and handles the identity."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % _P
    b = (y1 + x1) * (y2 + x2) % _P
    c = t1 * _D2 % _P * t2 % _P
    d = 2 * z1 * z2 % _P
    e = b - a
    f = d - c
    g = d + c
    h = b + a
    return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)


def _scalar_mul(k: int, p: _Pt) -> _Pt:
    """Variable-base [k]P, MSB-first double-and-add (variable-time — see
    module docstring)."""
    acc = _IDENT
    for bit in bin(k)[2:] if k else "":
        acc = _pt_add(acc, acc)
        if bit == "1":
            acc = _pt_add(acc, p)
    return acc


@lru_cache(maxsize=1)
def _base_table() -> Tuple[Tuple[_Pt, ...], ...]:
    """Fixed-base window table: TB[w][d] = [d * 16^w]B for d in 0..15
    (shared builder: ``_window_table``, defined with the signer comb).

    Makes a base-point multiply <=64 additions instead of ~256 doubles +
    ~128 adds — signing is the fallback's hot path (every envelope and
    every MultiGrant a replica issues goes through it)."""
    return _window_table(_BASE)


def _mul_base(k: int) -> _Pt:
    # NO zero-digit skip here, unlike _mul_signer: base multiplies run with
    # SECRET scalars (the signing nonce r, the private scalar a), and a
    # skip would make per-signature timing correlate with the nonce's
    # zero-nibble count — partial-nonce leakage is a known lattice vector
    # for key recovery.  The fallback is variable-time at the bignum level
    # regardless (module docstring), but there is no reason to add a
    # branch-per-secret-nibble on top for a ~6% saving.  _mul_signer's
    # skip is verify-only, where k = h is public.
    acc = _IDENT
    for w, row in enumerate(_base_table()):
        acc = _pt_add(acc, row[(k >> (4 * w)) & 15])
    return acc


# ---------------------------------------------------------------- signer comb
#
# Per-SIGNER window tables: the host analog of the device comb registry
# (crypto/comb.py) — a replica set re-verifies the same few cluster
# identities forever, so the variable-base half of every verify ([h]A, a
# ~380-addition double-and-add ladder) collapses to <=63 table additions
# once A's table exists.  Tables are built on a key's SECOND appearance
# (a counter, not the table, is the first-touch cost), so a one-shot
# forgery under a random key can never make us pay the ~960-addition
# build — repeat signers amortize it in two verifies.

_TABLE_PROMOTE_AFTER = 2
# The promotion tracker is bounded by the TABLE cache size: letting more
# keys reach promoted state than the LRU can hold would thrash it — cyclic
# access over more promoted signers than entries rebuilds the table every
# verify, inverting the comb into a ~2.7x slowdown.  When the tracker
# fills, it resets and keys re-earn promotion, so only the hot subset ever
# holds tables.
_TABLE_CACHE_SIZE = 128
_seen_signers: dict = {}  # compressed key -> VERIFIED count (bounded)
# Keys promoted by explicit registration (cluster identities via
# prime_signers).  They survive the reset-when-full below: organic signers
# re-earn promotion after a reset, but a boot-time-registered replica
# identity must never silently fall back to the ladder because 128 client
# keys verified in between.  Insertion-ordered and bounded by the table
# cache size: when full, the OLDEST primed key is evicted — a long-lived
# process that re-registers across reconfigurations (or a test session
# booting many clusters) keeps hints for the CURRENT membership, not the
# first 128 identities it ever saw.  Values are unused (dict-as-ordered-set).
_primed_signers: dict = {}


def _reset_signer_tracker() -> None:
    """Reset-when-full, preserving primed (registered) identities."""
    _seen_signers.clear()
    for pk in _primed_signers:
        _seen_signers[pk] = _TABLE_PROMOTE_AFTER


def _window_table(point: _Pt) -> Tuple[Tuple[_Pt, ...], ...]:
    """TB[w][d] = [d * 16^w]P — the shared 4-bit fixed-window builder
    behind both the basepoint table and per-signer tables."""
    table = []
    step = point
    for _ in range(64):
        row = [_IDENT]
        for _ in range(15):
            row.append(_pt_add(row[-1], step))
        table.append(tuple(row))
        step = _pt_add(row[8], row[8])  # 16 * step
    return tuple(table)


# Cache covers the north-star n=64 membership plus clients/headroom
# (worst-case memory ~50 MB at ≈400 KB/table, only ever paid on wheel-less
# fallback hosts); the promotion tracker above guarantees the promoted set
# never exceeds it.
@lru_cache(maxsize=_TABLE_CACHE_SIZE)
def _signer_table(compressed: bytes) -> Optional[Tuple[Tuple[_Pt, ...], ...]]:
    """Window table for the signer's point A (None: not a point)."""
    point = _decompress(compressed)
    if point is None:
        return None
    return _window_table(point)


def prime_signers(pubs) -> bool:
    """Pre-promote known signers (cluster identities) so their FIRST verify
    runs on the windowed comb table instead of earning promotion with two
    ~380-addition ladder verifies.  Called via
    :func:`mochi_tpu.crypto.keys.register_known_signers` from the verifier
    SPI's ``register_signers`` at replica boot and on reconfiguration.

    O(1) per key: only the promotion counter is touched — the ~960-addition
    table build stays lazy (first verify), and keys that are not canonical
    curve points are skipped (they can never verify, so a table would be
    wasted).  Primed keys are REMEMBERED across the organic tracker's
    reset-when-full (``_reset_signer_tracker``): a flood of distinct
    client signers must not demote boot-registered cluster identities back
    to the ladder.  Bounded at the table-cache size with oldest-first
    eviction (re-registration refreshes recency), so the promoted set can
    never outgrow the LRU (which would invert the comb into table-rebuild
    thrash — see ``_TABLE_CACHE_SIZE``) and the hints always describe the
    most recently registered memberships.
    """
    primed = False
    for pk in pubs:
        pk = bytes(pk)
        if pk in _primed_signers:
            # refresh recency (dict preserves insertion order = LRU order)
            _primed_signers[pk] = _primed_signers.pop(pk)
            primed = True
            continue
        if len(pk) != 32 or _decompress(pk) is None:
            continue
        # Cap at HALF the table cache (= the n=64 design-size membership):
        # a primed set that filled the whole tracker would re-fill it on
        # every reset and starve organic client signers of promotion
        # forever, while primed + organic together overflowed the table
        # LRU into rebuild thrash.  Oldest-first eviction keeps the hints
        # on the most recently registered memberships.
        while len(_primed_signers) >= _TABLE_CACHE_SIZE // 2:
            _primed_signers.pop(next(iter(_primed_signers)))  # evict oldest
        _primed_signers[pk] = True
        _seen_signers[pk] = max(_seen_signers.get(pk, 0), _TABLE_PROMOTE_AFTER)
        primed = True
    return primed


def _mul_signer(k: int, table: Tuple[Tuple[_Pt, ...], ...]) -> _Pt:
    acc = _IDENT
    for w, row in enumerate(table):
        digit = (k >> (4 * w)) & 15
        if digit:
            acc = _pt_add(acc, row[digit])
    return acc


def _compress(p: _Pt) -> bytes:
    x, y, z, _ = p
    zinv = pow(z, _P - 2, _P)
    x = x * zinv % _P
    y = y * zinv % _P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _decompress(b: bytes) -> Optional[_Pt]:
    """RFC 8032 §5.1.3 point decoding — ``curve.decompress`` on ints.
    Returns None for non-points (callers already rejected y >= p)."""
    if len(b) != 32:
        return None
    enc = int.from_bytes(b, "little")
    sign = enc >> 255
    y = enc & ((1 << 255) - 1)
    if y >= _P:
        return None
    yy = y * y % _P
    u = (yy - 1) % _P
    v = (D_INT * yy + 1) % _P
    # candidate root x = u * v^3 * (u*v^7)^((p-5)/8)
    x = u * pow(v, 3, _P) % _P * pow(u * pow(v, 7, _P) % _P, (_P - 5) // 8, _P) % _P
    vxx = v * x % _P * x % _P
    if vxx == u:
        pass
    elif u != 0 and vxx == _P - u:
        x = x * SQRT_M1_INT % _P
    else:
        return None
    if x == 0 and sign:
        return None
    if (x & 1) != sign:
        x = _P - x
    return (x, y, 1, x * y % _P)


def _pt_eq(p: _Pt, q: _Pt) -> bool:
    """Projective equality: X1*Z2 == X2*Z1 and Y1*Z2 == Y2*Z1."""
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % _P == 0 and (y1 * z2 - y2 * z1) % _P == 0


# ------------------------------------------------------------------ Ed25519


@lru_cache(maxsize=4096)
def _expand_seed(seed: bytes) -> Tuple[int, bytes, bytes]:
    """RFC 8032 §5.1.5 key expansion -> (clamped scalar, prefix, public).
    Cached for the same reason keys.py caches OpenSSL key handles: a node
    signs thousands of messages under the same few seeds."""
    if len(seed) != 32:
        # contract parity with Ed25519PrivateKey.from_private_bytes
        raise ValueError("An Ed25519 private key is 32 bytes long")
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:], _compress(_mul_base(a))


def public_from_seed(seed: bytes) -> bytes:
    return _expand_seed(bytes(seed))[2]


@lru_cache(maxsize=4096)
def _native_sign_material(seed: bytes) -> Tuple[bytes, bytes, bytes]:
    """(clamped scalar LE bytes, prefix, public) for the native signer —
    the byte-form twin of _expand_seed's cached int expansion."""
    a, prefix, pub = _expand_seed(seed)
    return a.to_bytes(32, "little"), prefix, pub


def sign(private_seed: bytes, message: bytes) -> bytes:
    """RFC 8032 §5.1.6 — bit-compatible with OpenSSL's deterministic sign
    (the replica's own-grant re-sign-and-compare depends on determinism).
    Routed through the native engine when built; the pure-Python path
    below is the reference implementation it must match byte-for-byte."""
    seed = bytes(private_seed)
    mod = _native_engine()
    if mod is not None:
        a_bytes, prefix, pub = _native_sign_material(seed)
        return mod.sign_prepared(a_bytes, prefix, pub, bytes(message))
    a, prefix, pub = _expand_seed(seed)
    r = int.from_bytes(hashlib.sha512(prefix + message).digest(), "little") % _L
    r_bytes = _compress(_mul_base(r))
    k = int.from_bytes(
        hashlib.sha512(r_bytes + pub + message).digest(), "little"
    ) % _L
    s = (r + k * a) % _L
    return r_bytes + s.to_bytes(32, "little")


# Verification is a pure function of its three byte inputs, so memoizing is
# sound — and in-process clusters (testing/virtual_cluster.py) make it pay
# hard: all rf replicas share one interpreter and each independently checks
# the SAME 2f+1 certificate grants per Write2, so the cache turns rf*(2f+1)
# multi-millisecond pure-Python verifies into (2f+1) plus dict hits.
# Cached on (key, sig, H(R||A||M)) rather than the message itself so the
# cache holds 160 bytes per entry, not arbitrarily large envelope bodies;
# the SHA-512 recomputed per call is noise next to the EC math it skips.
@lru_cache(maxsize=4096)
def _verify_cached(public_key: bytes, signature: bytes, h_digest: bytes) -> bool:
    r_point = _decompress(signature[:32])
    if r_point is None:
        return False
    s = int.from_bytes(signature[32:], "little")
    h = int.from_bytes(h_digest, "little") % _L
    # Repeat VERIFIED signers (cluster identities) get the windowed comb
    # table — [h]A in <=63 additions instead of the ~380-addition ladder.
    # Only successful verifications count toward promotion: forged-
    # signature floods under rotating bogus keys must neither pay the
    # ~960-addition table build nor evict legitimate signers' tables.
    if _seen_signers.get(public_key, 0) >= _TABLE_PROMOTE_AFTER:
        table = _signer_table(public_key)
        if table is None:
            return False
        ha = _mul_signer(h, table)
    else:
        a_point = _decompress(public_key)
        if a_point is None:
            return False
        ha = _scalar_mul(h, a_point)
    ok = _pt_eq(_mul_base(s), _pt_add(r_point, ha))
    if ok:
        if len(_seen_signers) >= _TABLE_CACHE_SIZE and public_key not in _seen_signers:
            # promoted set must fit the table cache; primed (registered)
            # identities are re-seeded by the reset, organic keys re-earn
            _reset_signer_tracker()
        _seen_signers[public_key] = _seen_signers.get(public_key, 0) + 1
    return ok


# ------------------------------------------------------- native-C engine
#
# native/hbatch.c's verify_batch: the full cofactorless check in C, built
# lazily with the system toolchain (same discipline as the codec/mcode and
# the batched-h path).  None when no toolchain / MOCHI_NO_NATIVE=1 — the
# pure-Python engine above then carries verification as before.

_NATIVE_UNSET = object()
_native = _NATIVE_UNSET


def _native_engine():
    global _native
    if _native is _NATIVE_UNSET:
        try:
            from ..native import get_hbatch

            mod = get_hbatch()
        except Exception:  # pragma: no cover - import-cycle/loader breakage
            mod = None
        # an older prebuilt _hbatch.so (pre-engine) lacks the symbols;
        # treat it as no native engine rather than failing calls
        _native = (
            mod
            if mod is not None
            and hasattr(mod, "verify_batch")
            and hasattr(mod, "sign_prepared")
            else None
        )
    return _native


def has_native() -> bool:
    """Whether :func:`verify` routes through the native-C engine."""
    return _native_engine() is not None


# Same cache shape and key as _verify_cached (the two engines are verdict-
# identical, but separate caches keep "which engine answered" honest for
# the differential suite): 160 bytes per entry, and in-process clusters
# dedup the rf-way re-check of identical certificate grants.
@lru_cache(maxsize=4096)
def _native_verify_cached(public_key: bytes, signature: bytes, h_digest: bytes) -> bool:
    mod = _native_engine()
    return mod.verify_batch(
        public_key, signature, mod.reduce512(h_digest)
    ) == b"\x01"


def verify(public_key: bytes, message: bytes, signature: bytes) -> bool:
    """Cofactorless ``[S]B == R + [h]A``.  Callers (``keys.verify``) have
    already enforced canonical encodings (y < p, S < L, lengths)."""
    public_key = bytes(public_key)
    signature = bytes(signature)
    h_digest = hashlib.sha512(signature[:32] + public_key + bytes(message)).digest()
    if (
        len(public_key) == 32
        and len(signature) == 64
        and _native_engine() is not None
    ):
        return _native_verify_cached(public_key, signature, h_digest)
    return _verify_cached(public_key, signature, h_digest)


# ------------------------------------------------------------------ X25519

_A24 = 121665


def x25519(private_bytes: bytes, peer_public: bytes) -> bytes:
    """RFC 7748 §5 X25519 on Python ints (Montgomery ladder).

    Same contract as ``X25519PrivateKey.exchange``: raises ``ValueError``
    on an all-zero shared secret (small-order peer point) and on
    wrong-length key material — a mixed cluster must reject the same
    malformed handshake bytes on both backends."""
    if len(private_bytes) != 32:
        raise ValueError("An X25519 private key is 32 bytes long")
    if len(peer_public) != 32:
        raise ValueError("An X25519 public key is 32 bytes long")
    mod = _native_engine()
    # hasattr-gated per call, not folded into _native_engine's own gate: a
    # stale prebuilt _hbatch.so with Ed25519 but no x25519 must keep the
    # Ed25519 fast path while THIS function falls back to Python.
    if mod is not None and hasattr(mod, "x25519"):
        shared = mod.x25519(bytes(private_bytes), bytes(peer_public))
        if not any(shared):
            raise ValueError(
                "X25519 shared secret is all zeros (small-order point)"
            )
        return shared
    k = int.from_bytes(bytes(private_bytes), "little")
    k &= (1 << 254) - 8
    k |= 1 << 254
    u = int.from_bytes(bytes(peer_public), "little") & ((1 << 255) - 1)
    x1, x2, z2, x3, z3 = u, 1, 0, u, 1
    swap = 0
    for t in reversed(range(255)):
        k_t = (k >> t) & 1
        swap ^= k_t
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = z3 * z3 % _P * x1 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + _A24 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    shared = x2 * pow(z2, _P - 2, _P) % _P
    if shared == 0:
        raise ValueError("X25519 shared secret is all zeros (small-order point)")
    return shared.to_bytes(32, "little")


def x25519_public(private_bytes: bytes) -> bytes:
    """Public key = X25519(private, 9) — the Montgomery basepoint."""
    return x25519(private_bytes, (9).to_bytes(32, "little"))

"""Batched Ed25519 verification: host preprocessing + jitted device kernel.

Division of labor (the TPU-first design, SURVEY.md §7):

* **Host** — everything variable-length or trivially cheap: SHA-512 of
  R||A||M (hashlib -> OpenSSL C, ~GB/s), the mod-L scalar reduction
  (python bignum), RFC 8032 canonical-encoding prechecks (y < p, S < L),
  and packing into fixed-shape tensors (scalars as bytes — 32x less H2D
  transfer than bit tensors; the device unpacks).
* **Device** — all the modular heavy lifting (~4400 field muls per
  signature): point decompression (two fixed exponentiation chains) and the
  256-step double-scalar-mul, batched over the leading axis.  Oversized
  requests chunk at MAX_BUCKET with every chunk launched before any is
  read back, so chunk k+1's host prepare and transfer overlap chunk k's
  device execution (measured end-to-end on 64k items: 19.1k sequential ->
  69.8k sigs/s pipelined+packed, config-2 artifact).

Batches are padded to power-of-two buckets so XLA compiles a handful of
program shapes, then caches (SURVEY.md §7: static shapes; first compile
20-40s, later calls cached).

The verdict matches the CPU path (OpenSSL cofactorless verify) bit-for-bit;
``tests/test_crypto_jax.py`` checks this differentially including forged and
malformed inputs.

Considered and rejected: random-linear-combination batch verification
(one multi-scalar-mul checking sum_i z_i*(S_i*B - R_i - h_i*A_i) = 0, as
surveyed for committee consensus in arXiv:2302.00418).  It cuts device
FLOPs ~2x, but (a) its cofactored acceptance can DISAGREE with
cofactorless per-signature verification on adversarial mixed-order /
non-canonical inputs — breaking this module's bit-for-bit differential
contract with OpenSSL, which the cluster's Byzantine tests rely on; and
(b) a failed batch yields no per-item verdicts, forcing bisection retries
exactly when an attacker salts batches with one bad signature.  Batching
here means SIMD over independent per-signature checks: same verdicts as
serial verification, per-item bitmaps, no degradation under attack.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from . import curve
from . import field as F
from ..verifier.spi import VerifyItem

LOG = logging.getLogger(__name__)

MIN_BUCKET = 16
# Largest single device launch.  Measured on v5e (bench.py, round 2): 8192
# lanes is the throughput peak (91k sigs/s sequential, 111k with 4 batches
# in flight) after the signed-window ladder halved the per-item
# small-multiples tables and the pad-skew multiply removed the
# HBM-streaming intermediates; 16384 still spills VMEM, 4096 underfills.
# Bigger requests are chunked at this size behind a bounded launch window,
# so rate stays flat instead of regressing.  Tune via MOCHI_MAX_BUCKET
# without a code change.
def _max_bucket() -> int:
    """MOCHI_MAX_BUCKET, sanitized: >= MIN_BUCKET and a power of two (a
    non-power would chunk at sizes _bucket_size pads PAST the VMEM cap the
    knob exists to enforce; 0/negative would break the chunk loop)."""
    try:
        v = int(os.environ.get("MOCHI_MAX_BUCKET", "8192"))
    except ValueError:
        return 8192
    v = max(v, MIN_BUCKET)
    return 1 << (v.bit_length() - 1)  # round DOWN to a power of two


MAX_BUCKET = _max_bucket()

# Bounded launch-ahead for verify_batch's chunked path: live memory stays
# O(_PIPELINE_DEPTH * MAX_BUCKET) while chunk k+1's host prepare/transfer
# overlaps chunk k's device execution.
_PIPELINE_DEPTH = 4


def _impl() -> str:
    """Device implementation: "xla" (default) or "pallas"
    (``MOCHI_VERIFY_IMPL=pallas`` — the hand-tiled kernel,
    :mod:`mochi_tpu.crypto.pallas_verify`)."""
    return os.environ.get("MOCHI_VERIFY_IMPL", "xla")


def _bucket_size(n: int) -> int:
    m = MIN_BUCKET
    while m < n:
        m *= 2
    return m


def _hbatch():
    """The native batched-h module (lazy; None -> pure-python fallback)."""
    from ..native import get_hbatch

    return get_hbatch()


# 15-bit limb weights and the uint64-word forms of p and L, for the
# vectorized prechecks below.
_W15 = (1 << np.arange(15, dtype=np.int32)).astype(np.int32)
_P_WORDS = [(F.P_INT >> (64 * k)) & ((1 << 64) - 1) for k in range(4)]
_L_WORDS = [(F.L_INT >> (64 * k)) & ((1 << 64) - 1) for k in range(4)]


def _bits_le(rows: np.ndarray) -> np.ndarray:
    """(n, 32) uint8 -> (n, 256) little-endian bits (uint8)."""
    return np.unpackbits(rows, axis=1, bitorder="little")


def _bits_to_limbs(bits: np.ndarray) -> np.ndarray:
    """(n, 256) LE bits -> (n, 17) int32 limbs (bit 255 never read: only
    bits 0..254 enter the limbs, which is exactly the & (2^255-1) mask)."""
    return (
        bits[:, :255].reshape(-1, F.NLIMBS, F.RADIX).astype(np.int32) @ _W15
    )


def _words_le(rows: np.ndarray) -> np.ndarray:
    """(n, 32) uint8 -> (n, 4) uint64 little-endian words."""
    return rows.view("<u8")


def _lt_p(words: np.ndarray) -> np.ndarray:
    """value < p = 2^255-19, for values already masked below 2^255.
    p's words are (0xff..ed, ff.., ff.., 0x7fff..): >= p requires the top
    three words saturated and word0 >= 0xff..ed."""
    w0, w1, w2, w3 = (words[:, k] for k in range(4))
    ge = (
        (w3 == _P_WORDS[3]) & (w2 == _P_WORDS[2]) & (w1 == _P_WORDS[1])
        & (w0 >= _P_WORDS[0])
    )
    return ~ge


def _lt_l(words: np.ndarray) -> np.ndarray:
    """value < L (group order), lexicographic compare from the top word."""
    w0, w1, w2, w3 = (words[:, k] for k in range(4))
    ge = (w3 > _L_WORDS[3]) | (
        (w3 == _L_WORDS[3])
        & ((w2 > _L_WORDS[2]) | ((w2 == _L_WORDS[2]) & (
            (w1 > _L_WORDS[1]) | ((w1 == _L_WORDS[1]) & (w0 >= _L_WORDS[0]))
        )))
    )
    return ~ge


def prepare(items: Sequence[VerifyItem]):
    """Host-side packing: items -> fixed-shape numpy tensors + precheck
    bitmap, scalars as (n, 256) int32 bit tensors (the
    :func:`~mochi_tpu.crypto.curve.verify_prepared` input format)."""
    y_a, sign_a, y_r, sign_r, s_bytes, h_bytes, pre_ok = prepare_packed(items)
    s_bits = _bits_le(s_bytes).astype(np.int32)
    h_bits = _bits_le(h_bytes).astype(np.int32)
    return y_a, sign_a, y_r, sign_r, s_bits, h_bits, pre_ok


def prepare_packed(items: Sequence[VerifyItem]):
    """Host-side packing with scalars as (n, 32) uint8 LE bytes.

    Vectorized over the batch (numpy byte/bit ops; the only per-item Python
    is SHA-512 — hashlib's C — and the mod-L bignum): ~6 us/item vs the
    round-2a per-item loop's ~114 us/item, which capped the end-to-end
    service at ~9k items/s in front of a >100k items/s device pipeline.
    The byte (not bit) scalar form keeps the host->device transfer 32x
    smaller (device unpacks — curve.verify_prepared_packed).
    Semantics: malformed lengths, non-canonical y (>= p) and S >= L are
    rejected on host exactly as RFC 8032 decode / OpenSSL do.
    """
    n = len(items)
    y_a = np.zeros((n, F.NLIMBS), dtype=np.int32)
    y_r = np.zeros((n, F.NLIMBS), dtype=np.int32)
    sign_a = np.zeros(n, dtype=np.int32)
    sign_r = np.zeros(n, dtype=np.int32)
    s_bytes = np.zeros((n, 32), dtype=np.uint8)
    h_bytes = np.zeros((n, 32), dtype=np.uint8)
    pre_ok = np.zeros(n, dtype=bool)

    idx = [
        i
        for i, it in enumerate(items)
        if len(it.public_key) == 32 and len(it.signature) == 64
    ]
    if not idx:
        return y_a, sign_a, y_r, sign_r, s_bytes, h_bytes, pre_ok
    m = len(idx)

    a_rows = np.frombuffer(
        b"".join(bytes(items[i].public_key) for i in idx), dtype=np.uint8
    ).reshape(m, 32)
    sig_rows = np.frombuffer(
        b"".join(bytes(items[i].signature) for i in idx), dtype=np.uint8
    ).reshape(m, 64)
    r_rows = np.ascontiguousarray(sig_rows[:, :32])
    s_rows = np.ascontiguousarray(sig_rows[:, 32:])

    a_bits = _bits_le(a_rows)
    r_bits = _bits_le(r_rows)
    sa = a_bits[:, 255].astype(np.int32)
    sr = r_bits[:, 255].astype(np.int32)

    # canonicity: y < p on the masked value, S < L on the raw scalar
    a_masked = a_rows.copy()
    a_masked[:, 31] &= 0x7F
    r_masked = r_rows.copy()
    r_masked[:, 31] &= 0x7F
    ok = _lt_p(_words_le(a_masked)) & _lt_p(_words_le(r_masked))
    ok &= _lt_l(_words_le(s_rows))

    # h = SHA-512(R || A || M) mod L — ONLY for items that passed the
    # prechecks (a flood of non-canonical signatures over big messages
    # must not buy host hashing work; rejected lanes are masked by pre_ok
    # regardless).  The native batch path (native/hbatch.c: one C call,
    # embedded SHA-512 + Barrett mod-L) cuts the per-item python loop
    # (~2.1 of ~4.5 us/item at bucket 8192) that capped host prepare at
    # ~224k items/s; hashlib + python bignum is the automatic fallback.
    idx_arr = np.asarray(idx)
    ok_idx = idx_arr[ok]
    if len(ok_idx):
        hb = _hbatch()
        if hb is not None:
            msgs = b"".join(bytes(items[i].message) for i in ok_idx)
            lens = np.fromiter(
                (len(items[i].message) for i in ok_idx),
                dtype=np.uint64,
                count=len(ok_idx),
            )
            h_cat = hb.h_batch(
                np.ascontiguousarray(r_rows[ok]).tobytes(),
                np.ascontiguousarray(a_rows[ok]).tobytes(),
                msgs,
                lens.tobytes(),
            )
            h_bytes[ok_idx] = np.frombuffer(h_cat, dtype=np.uint8).reshape(-1, 32)
        else:
            h_parts = []
            for i in ok_idx:
                it = items[i]
                h_int = (
                    int.from_bytes(
                        hashlib.sha512(
                            bytes(it.signature[:32])
                            + bytes(it.public_key)
                            + bytes(it.message)
                        ).digest(),
                        "little",
                    )
                    % F.L_INT
                )
                h_parts.append(h_int.to_bytes(32, "little"))
            h_rows = np.frombuffer(b"".join(h_parts), dtype=np.uint8).reshape(-1, 32)
            h_bytes[ok_idx] = h_rows

    y_a[idx_arr] = _bits_to_limbs(a_bits)
    y_r[idx_arr] = _bits_to_limbs(r_bits)
    sign_a[idx_arr] = sa
    sign_r[idx_arr] = sr
    s_bytes[idx_arr] = s_rows
    pre_ok[idx_arr] = ok
    return y_a, sign_a, y_r, sign_r, s_bytes, h_bytes, pre_ok


_verify_jit = jax.jit(curve.verify_prepared)
_verify_packed_jit = jax.jit(curve.verify_prepared_packed)


def verify_batch(
    items: Sequence[VerifyItem],
    device: Optional[jax.Device] = None,
    bucket: Optional[int] = None,
    registry=None,
    comb_gen: Optional[int] = None,
) -> List[bool]:
    """Verify a batch of Ed25519 signatures on the default JAX device.

    Returns a python bool list (the SPI bitmap).  Invalid encodings are
    rejected on host; padding lanes carry pre_ok=False and are sliced away.
    ``bucket`` forces a specific padded size (callers that know which program
    shapes are already compiled use it to avoid a fresh compile).

    ``registry`` (a :class:`mochi_tpu.crypto.comb.SignerRegistry`) enables
    the known-signer comb path: items whose public key is registered run
    the doubling-free comb kernel (~3x fewer field muls — comb.py
    docstring); the rest take the general ladder below.  Verdicts are
    identical either way (``tests/test_comb.py``); disable with
    ``MOCHI_COMB=0``.  ``comb_gen`` pins the registry generation the
    CALLER checked comb-readiness against: keys registered after that
    generation route to the general ladder (their table rows may not be
    in the pinned device table), and the device table keeps the pinned
    generation's shape so no retrace can hit this call.
    """
    if not items:
        return []
    if registry is not None and len(registry) and comb_enabled():
        from . import comb

        comb_pos: List[int] = []
        kidx: List[int] = []
        gen_pos: List[int] = []
        for i, it in enumerate(items):
            k = registry.index_of(it.public_key)
            if k is None or (comb_gen is not None and k >= comb_gen):
                gen_pos.append(i)
            else:
                comb_pos.append(i)
                kidx.append(k)
        _note_routing(len(comb_pos), len(gen_pos))
        if comb_pos:
            comb_items = [items[i] for i in comb_pos]
            key_arr = np.asarray(kidx, dtype=np.int32)
            if not gen_pos:
                return comb.verify_stream(
                    comb_items, key_arr, registry, device, bucket, comb_gen
                )
            gen_items = [items[i] for i in gen_pos]
            if len(comb_items) <= MAX_BUCKET and len(gen_items) <= MAX_BUCKET:
                # Mixed batch, both subsets single-chunk: DISPATCH both
                # programs before reading either back, so the two device
                # launches overlap instead of serializing on the first
                # readback (JAX dispatch is async).
                comb_launched = comb._dispatch_comb(
                    comb._prepare_comb(comb_items, key_arr, bucket),
                    registry,
                    device,
                    registry.device_table(device, comb_gen),
                )
                gen_launched = _launch(gen_items, device, bucket)
                comb_out = _readback(comb_launched, len(comb_items))
                gen_out = _readback(gen_launched, len(gen_items))
            else:
                comb_out = comb.verify_stream(
                    comb_items, key_arr, registry, device, bucket, comb_gen
                )
                gen_out = verify_batch(gen_items, device, bucket)
            out: List[bool] = [False] * len(items)
            for i, v in zip(comb_pos, comb_out):
                out[i] = v
            for i, v in zip(gen_pos, gen_out):
                out[i] = v
            return out
    if len(items) > MAX_BUCKET and bucket is None:
        # Two-level pipeline behind a bounded window, live memory
        # O(depth * MAX_BUCKET) instead of O(request):
        #   * a single worker thread runs chunk k+1's host PREPARE while the
        #     main thread blocks on chunk k-depth's readback (the device-
        #     wait releases the GIL, and prepare is numpy/hashlib C that
        #     mostly does too) — prepare cost ~6 us/item no longer
        #     serializes against the device;
        #   * launches stay ahead of readbacks by _PIPELINE_DEPTH, so
        #     transfer + device execution overlap across chunks (JAX
        #     dispatch is async).
        # Sequential chunking measured 19.1k sigs/s end-to-end on 64k
        # items; pipelined+packed ~70k; adding the prepare thread closes
        # most of the remaining gap to the same-buffer pipelined steady
        # state (config-2 artifact).
        window: deque = deque()
        out: List[bool] = []
        chunks = [items[i : i + MAX_BUCKET] for i in range(0, len(items), MAX_BUCKET)]
        prep_fut = _prep_pool().submit(_prepare_padded, chunks[0], None)
        for k, chunk in enumerate(chunks):
            prepared = prep_fut.result()
            if k + 1 < len(chunks):
                prep_fut = _prep_pool().submit(_prepare_padded, chunks[k + 1], None)
            window.append((_dispatch(prepared, device), len(chunk)))
            if len(window) >= _PIPELINE_DEPTH:
                out.extend(_readback(*window.popleft()))
        while window:
            out.extend(_readback(*window.popleft()))
        return out
    return _readback((_launch(items, device, bucket)), len(items))


# One persistent prepare worker: verify_batch is called from the verifier's
# flush executor, so a single overlap thread is enough and avoids per-call
# thread churn.
_PREP_POOL: Optional[ThreadPoolExecutor] = None
_PREP_POOL_LOCK = threading.Lock()


def _prep_pool() -> ThreadPoolExecutor:
    global _PREP_POOL
    if _PREP_POOL is None:
        with _PREP_POOL_LOCK:
            if _PREP_POOL is None:
                _PREP_POOL = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="mochi-prep"
                )
    return _PREP_POOL


def _readback(launched, n: int) -> List[bool]:
    """Block on one launched chunk and combine with its host prechecks."""
    bitmap_dev, pre_ok = launched
    if bitmap_dev is None:  # all-rejected chunk: no device work was done
        return [False] * n
    bitmap = np.asarray(bitmap_dev)[:n]
    return [bool(b) for b in np.logical_and(bitmap, pre_ok)]


def _prepare_padded(items: Sequence[VerifyItem], bucket: Optional[int]):
    """Host half of a launch: pack + pad one chunk (pure numpy/hashlib —
    safe on the prepare worker thread, no JAX calls)."""
    use_pallas = _impl() == "pallas"
    if use_pallas:
        # The (shelved) Pallas kernel consumes the bit-tensor format;
        # the XLA path takes packed bytes (scalars decode on device).
        y_a, sign_a, y_r, sign_r, s_sc, h_sc, pre_ok = prepare(items)
    else:
        y_a, sign_a, y_r, sign_r, s_sc, h_sc, pre_ok = prepare_packed(items)
    n = len(items)
    m = _bucket_size(n) if bucket is None else bucket
    assert m >= n
    if m != n:
        pad = ((0, m - n), (0, 0))
        y_a = np.pad(y_a, pad)
        y_r = np.pad(y_r, pad)
        s_sc = np.pad(s_sc, pad)
        h_sc = np.pad(h_sc, pad)
        sign_a = np.pad(sign_a, ((0, m - n),))
        sign_r = np.pad(sign_r, ((0, m - n),))
    return use_pallas, (y_a, sign_a, y_r, sign_r, s_sc, h_sc), pre_ok


def _dispatch(prepared, device: Optional[jax.Device] = None):
    """Device half of a launch: transfer + async dispatch (main thread).

    A chunk whose prechecks rejected EVERY item (e.g. a flood of
    non-canonical garbage) skips the device entirely — an attacker must
    spend real signing-grade work (canonical encodings) to buy device
    time; byte noise is absorbed at host precheck rates
    (scripts/forgery_bench.py measures both)."""
    use_pallas, args, pre_ok = prepared
    if not pre_ok.any():
        return None, pre_ok
    _note_dispatch()
    if device is not None:
        args = tuple(jax.device_put(a, device) for a in args)
    if use_pallas:
        from . import pallas_verify

        return pallas_verify.verify_prepared_pallas(*args), pre_ok
    return _verify_packed_jit(*args), pre_ok


# Monotone count of real device dispatches.  JaxBatchBackend uses it to
# tell "this call compiled/ran the bucket's program" from "the all-rejected
# fast path skipped the device" — marking a bucket ready without a compile
# would park the NEXT legitimate batch behind a synchronous 20-60 s compile
# (the stall the ready/chunking machinery exists to prevent).
_device_dispatches = 0

# Per-thread dispatch counters: readiness attribution must not observe
# OTHER threads' dispatches (BatchingVerifier runs up to max_inflight
# backend calls concurrently; a global-delta read would let thread B's
# dispatch mark thread A's bucket ready without a compile — code-review
# r4).  All dispatching happens on the calling thread (the prepare worker
# only packs), so thread-local deltas attribute exactly.
_tls = threading.local()


_comb_device_dispatches = 0

# Guards the module-global totals only: BatchingVerifier runs up to
# max_inflight backend calls concurrently and a bare += can drop counts
# (ADVICE r4).  The thread-local counters need no lock.
_dispatch_count_lock = threading.Lock()


def _note_dispatch(comb: bool = False) -> None:
    global _device_dispatches, _comb_device_dispatches
    with _dispatch_count_lock:
        _device_dispatches += 1
        if comb:
            _comb_device_dispatches += 1
    if comb:
        _tls.comb = getattr(_tls, "comb", 0) + 1
    else:
        _tls.general = getattr(_tls, "general", 0) + 1


def thread_dispatch_counts() -> tuple:
    """(general, comb) dispatches made by THIS thread (monotone)."""
    return (getattr(_tls, "general", 0), getattr(_tls, "comb", 0))


# Router occupancy (process-global, monotone): how many items the known-
# signer router sent down each leg, and how often one verify_batch call
# carried BOTH programs in a single merged-bitmap round trip.  Only counted
# at the routing decision (registry present), so the general-only fast path
# costs nothing; surfaced by verifier_stats → admin /status and
# /metrics.prom (docs/OPERATIONS.md §"Comb-first verification").
_comb_items_routed = 0
_ladder_items_routed = 0
_mixed_batches = 0


def _note_routing(n_comb: int, n_ladder: int) -> None:
    global _comb_items_routed, _ladder_items_routed, _mixed_batches
    with _dispatch_count_lock:
        _comb_items_routed += n_comb
        _ladder_items_routed += n_ladder
        if n_comb and n_ladder:
            _mixed_batches += 1


def comb_routing_counts() -> dict:
    """Snapshot of the router's occupancy counters (monotone totals)."""
    with _dispatch_count_lock:
        return {
            "comb_items": _comb_items_routed,
            "ladder_items": _ladder_items_routed,
            "mixed_batches": _mixed_batches,
        }


def device_dispatch_count() -> int:
    return _device_dispatches


def comb_enabled() -> bool:
    """Operator kill switch for the known-signer comb path.  Checked by
    routing AND by every comb compile site — MOCHI_COMB=0 must not keep
    paying 20-60 s comb compiles for programs that will never run."""
    return os.environ.get("MOCHI_COMB", "1") != "0"


def _launch(
    items: Sequence[VerifyItem],
    device: Optional[jax.Device] = None,
    bucket: Optional[int] = None,
):
    """Prepare, pad and DISPATCH one chunk; no result readback.

    Returns ``(device_bitmap, pre_ok)`` — the caller reads the bitmap back
    with ``np.asarray`` when it needs the verdicts, which is what lets
    multiple chunks pipeline on the device.  Scalars travel as packed
    bytes (32x smaller H2D transfer; the device unpacks).
    """
    return _dispatch(_prepare_padded(items, bucket), device)


class JaxBatchBackend:
    """``BatchBackend`` for :class:`mochi_tpu.verifier.spi.BatchingVerifier`.

    The replica's async batcher calls this from a thread executor; each call
    is one device program launch (cached compile per bucket shape).

    Compile-stall avoidance: XLA compiles one program per batch-size bucket
    (20-60s each).  A batch whose bucket isn't compiled yet is served in
    chunks of the largest *already-compiled* bucket, while the bigger
    bucket's compile is kicked off on a background thread — so ramping load
    never parks live traffic behind a compile (it would blow client
    timeouts; see the batching discipline in SURVEY.md §7).
    """

    def __init__(
        self,
        device: Optional[jax.Device] = None,
        min_device_items: Optional[int] = None,
        verify_fn=None,
        registry=None,
    ):
        self.device = device
        # Known-signer comb registry (crypto/comb.py); None = ladder only.
        self.registry = registry
        # Hook for alternative device paths (the mesh-sharded backend in
        # verifier/tpu.py) so they inherit the crossover + warmup +
        # compile-stall machinery below instead of re-implementing it.
        # Same contract as verify_batch(items, device=..., bucket=...).
        # None means "the module's verify_batch", resolved at CALL time so
        # tests that monkeypatch the module function still intercept.
        self._verify_fn = verify_fn
        # CPU/device crossover: a device launch costs a fixed round trip
        # (~100 ms through the axon tunnel; ~1 ms on an attached chip),
        # while OpenSSL verifies ~0.18 ms/sig on this host — so batches
        # below the crossover are faster (and lower-latency for the
        # cluster's commit path) on CPU.  Tunable: MOCHI_DEVICE_MIN_BATCH.
        if min_device_items is None:
            try:
                min_device_items = int(
                    os.environ.get("MOCHI_DEVICE_MIN_BATCH", "384")
                )
            except ValueError:
                min_device_items = 384
        self.min_device_items = max(0, min_device_items)
        self._ready: set[int] = set()
        self._compiling: set[int] = set()
        self._failed: set[int] = set()
        # Comb readiness is per (bucket, registry generation): capacity
        # growth changes the device-table SHAPE, invalidating every comb
        # compile, and live traffic must never park behind the recompile —
        # a stale bucket routes through the (compiled) general ladder
        # while the comb program re-warms in the background.
        self._ready_comb: dict = {}  # bucket -> generation compiled at
        self._comb_compiling: set = set()  # (bucket, generation)
        # Buckets whose comb compile FAILED — latched like _failed so a
        # persistently failing shape doesn't re-attempt a 20-60 s compile
        # on every batch; such buckets serve on the general path.
        self._comb_failed: set = set()
        self._lock = threading.Lock()
        self._registry_mutex = threading.Lock()

    def _comb_capable(self) -> bool:
        """Whether this backend's device path can route through the comb
        kernel (the sharded subclass overrides with its own comb program)."""
        return self._verify_fn is None

    def _registry_device(self):
        """Placement for the registry's device tables (the sharded subclass
        returns a replicated NamedSharding instead of a single device)."""
        return self.device

    def register_signers(self, pubs: Sequence[bytes], extra_buckets=()) -> None:
        """Register known signers (cluster replica identities), creating
        the registry on first use.  Thread-safe; growth never stalls live
        traffic (already-registered signers keep comb at their pinned
        generation, new keys ride the general path until the background
        re-warms here finish)."""
        with self._registry_mutex:
            if self.registry is None:
                from .comb import SignerRegistry

                self.registry = SignerRegistry(device=self._registry_device())
            before = self.registry.generation
            self.registry.register_all(pubs)
            grew = self.registry.generation != before
        if grew and self._comb_capable():
            with self._lock:
                buckets = set(self._ready) | set(self._ready_comb)
            buckets |= {_bucket_size(int(b)) for b in extra_buckets}
            for bucket in sorted(buckets):
                self._comb_compile_in_background(bucket)

    def _comb_pinned_gen(self, bucket: int) -> Optional[int]:
        """Generation a comb program is provably compiled for at this
        bucket, or None.  An OLD generation stays valid forever: keys
        registered after it simply route to the general ladder (the
        ``comb_gen`` clamp in :func:`verify_batch`), so registry growth
        never interrupts comb service — it only leaves the new keys on
        the ladder until the background re-warm lands."""
        if (
            self.registry is None
            or not len(self.registry)
            or not comb_enabled()
            or not self._comb_capable()
        ):
            return None
        return self._ready_comb.get(bucket)

    def comb_ready_buckets(self) -> list:
        """Sorted buckets with a compiled comb program — snapshot taken
        under the backend lock so stats readers never race the background
        comb-warm threads' dict inserts (ADVICE r4)."""
        with self._lock:
            return sorted(self._ready_comb)

    def _call_verify(
        self,
        items,
        bucket: Optional[int] = None,
        use_comb=False,
        comb_gen: Optional[int] = None,
    ):
        fn = self._verify_fn if self._verify_fn is not None else verify_batch
        if use_comb and self._comb_capable():
            return fn(
                items,
                device=self.device,
                bucket=bucket,
                registry=self.registry,
                comb_gen=comb_gen,
            )
        return fn(items, device=self.device, bucket=bucket)

    def _warm_comb(self, bucket: int) -> None:
        """Compile the comb program for one bucket (synchronous; callers
        choose the thread) and record the generation it covers.  The
        compile runs against THAT generation's table shape — later
        dispatches pin the same generation, so the compiled program is
        exactly the one they hit."""
        from . import comb

        gen = self.registry.generation
        comb.warmup(self.registry, [bucket], self.device, gen=gen)
        with self._lock:
            # monotone: never regress a bucket below a generation another
            # warm already covered
            self._ready_comb[bucket] = max(gen, self._ready_comb.get(bucket, 0))

    def warmup(self, batch_sizes: Sequence[int]) -> None:
        """Synchronously pre-compile the given bucket sizes (boot path).

        With a registry attached this warms BOTH programs per bucket: the
        dummy items exercise the general ladder (their throwaway key is
        never registered), and ``comb.warmup`` compiles the comb program —
        otherwise the first live batch of registered-signer traffic would
        park behind a synchronous compile, exactly the stall the
        ready-bucket machinery exists to prevent."""
        for n in batch_sizes:
            bucket = _bucket_size(n)
            self._call_verify(_dummy_items(bucket))
            with self._lock:
                self._ready.add(bucket)
            if (
                self.registry is not None
                and len(self.registry)
                and self._comb_capable()
                and comb_enabled()
            ):
                self._warm_comb(bucket)

    def _compile_in_background(self, bucket: int) -> None:
        def run():
            try:
                items = _dummy_items(bucket)
                self._call_verify(items)
                with self._lock:
                    self._ready.add(bucket)
                if (
                    self.registry is not None
                    and len(self.registry)
                    and self._comb_capable()
                    and comb_enabled()
                ):
                    self._warm_comb(bucket)
            except Exception:
                LOG.exception(
                    "background compile of verify bucket %d failed; "
                    "bucket disabled (batches keep chunking at smaller sizes)",
                    bucket,
                )
                with self._lock:
                    self._failed.add(bucket)
            finally:
                with self._lock:
                    self._compiling.discard(bucket)

        threading.Thread(target=run, name=f"verify-warm-{bucket}", daemon=True).start()

    def _comb_compile_in_background(self, bucket: int) -> None:
        """Re-warm a stale comb program (new bucket or registry growth)
        without blocking the caller's traffic (which keeps serving: comb
        at its pinned older generation, new keys on the ladder)."""
        if not comb_enabled() or self.registry is None or not len(self.registry):
            return
        gen = self.registry.generation
        with self._lock:
            if (bucket, gen) in self._comb_compiling or bucket in self._comb_failed:
                return
            self._comb_compiling.add((bucket, gen))

        def run():
            try:
                self._warm_comb(bucket)
            except Exception:
                LOG.exception(
                    "comb compile (bucket %d) failed; bucket latched — its "
                    "traffic stays on the general path",
                    bucket,
                )
                with self._lock:
                    self._comb_failed.add(bucket)
            finally:
                with self._lock:
                    self._comb_compiling.discard((bucket, gen))

        threading.Thread(
            target=run, name=f"comb-warm-{bucket}", daemon=True
        ).start()

    def __call__(self, items: Sequence[VerifyItem]) -> Sequence[bool]:
        if len(items) < self.min_device_items:
            from . import keys as _keys

            return [
                _keys.verify(it.public_key, it.message, it.signature)
                for it in items
            ]
        bucket = _bucket_size(len(items))
        registry_active = self.registry is not None and len(self.registry)
        pinned = self._comb_pinned_gen(bucket)
        with self._lock:
            general_ready = bucket in self._ready
            ready = sorted(self._ready)
            comb_ready_buckets = sorted(self._ready_comb)
            anything = bool(ready) or bool(comb_ready_buckets)
            schedule = (
                not general_ready
                and anything
                and bucket not in self._compiling
                and bucket not in self._failed
            )
            if schedule:
                self._compiling.add(bucket)
        if schedule:
            # Kick the background GENERAL compile here — before any serve
            # path returns.  The comb direct-serve branch below returns
            # without reaching the chunked fallback, and scheduling-
            # without-starting would leak the bucket in _compiling forever,
            # permanently disabling background compiles for it
            # (code-review r4).
            self._compile_in_background(bucket)
        use_comb = pinned is not None
        if (
            registry_active
            and comb_enabled()
            and anything
            and (pinned is None or pinned < self.registry.generation)
        ):
            # Comb program missing for this bucket, or compiled before the
            # latest registrations: serve THIS batch as-is (ladder, or
            # comb at the pinned older generation) and re-warm off the
            # hot path so the new keys join the comb path shortly.  Not
            # gated on general readiness: comb-only traffic never
            # populates _ready at all (code-review r4).
            self._comb_compile_in_background(bucket)
        # Direct serve when this bucket has a compiled program for its
        # traffic: the general program, or — for registered-signer traffic
        # — the comb program alone (an unregistered leftover in that
        # posture is rare enough to accept its one-off compile), or when
        # NOTHING is compiled yet (first ever call eats the compile;
        # servers avoid it via boot warmup).
        ready_now = general_ready or (use_comb and registry_active)
        if ready_now or not anything:
            # Bucket compiled, or nothing compiled yet (first ever call):
            # run directly (the latter eats one synchronous compile — servers
            # avoid it via boot-time warmup).  Only a call that actually
            # dispatched the device program proves the bucket is compiled;
            # the all-rejected fast path skips the device and must not mark
            # readiness.  With a registry, comb and general dispatches are
            # counted separately — each program proves only ITS OWN
            # readiness (a comb-only dispatch must not green-light the
            # general program, or a later mixed batch stalls on a
            # "ready" bucket).
            if registry_active and not anything and comb_enabled():
                # first-ever call, nothing compiled: pin the current
                # generation and eat both compiles synchronously
                use_comb = True
                pinned = self.registry.generation
            gen = pinned if use_comb else None
            # bucket is passed explicitly (when it is a single launch) so
            # a MIXED batch's subsets pad to this compiled shape instead
            # of their own smaller, never-compiled natural buckets; an
            # oversize batch keeps bucket=None so verify_batch's bounded
            # MAX_BUCKET launch-window pipeline still applies
            # (code-review r4, both directions).
            explicit = bucket if bucket <= MAX_BUCKET else None
            general_before, comb_before = thread_dispatch_counts()
            out = self._call_verify(
                items, bucket=explicit, use_comb=use_comb, comb_gen=gen
            )
            general_after, comb_after = thread_dispatch_counts()
            comb_n = comb_after - comb_before
            general_n = general_after - general_before
            if explicit is not None:
                with self._lock:
                    if general_n > 0:
                        self._ready.add(bucket)
                    if comb_n > 0 and gen is not None:
                        self._ready_comb[bucket] = max(
                            gen, self._ready_comb.get(bucket, 0)
                        )
            return out
        # Serve via already-compiled shapes only: chunk at the largest
        # compiled bucket and pad each chunk up to the smallest compiled
        # bucket that fits, so no chunk can trigger a synchronous compile.
        # General-program buckets serve any traffic; with comb-only
        # history (registered-signer service without boot warmup) the
        # comb buckets serve instead — an unregistered leftover there is
        # the rare accept-one-compile case documented above.
        targets = ready if ready else comb_ready_buckets
        largest_ready = targets[-1]
        out: List[bool] = []
        for i in range(0, len(items), largest_ready):
            chunk = items[i : i + largest_ready]
            target = next(b for b in targets if b >= len(chunk))
            tgt_gen = self._comb_pinned_gen(target)
            out.extend(
                self._call_verify(
                    chunk,
                    bucket=target,
                    use_comb=tgt_gen is not None,
                    comb_gen=tgt_gen,
                )
            )
        return out


def _dummy_items(n: int) -> List[VerifyItem]:
    from .keys import generate_keypair

    kp = generate_keypair()
    msg = b"mochi-tpu warmup"
    sig = kp.sign(msg)
    return [VerifyItem(kp.public_key, msg, sig)] * n

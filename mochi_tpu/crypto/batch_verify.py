"""Batched Ed25519 verification: host preprocessing + jitted device kernel.

Division of labor (the TPU-first design, SURVEY.md §7):

* **Host** — everything variable-length or trivially cheap: SHA-512 of
  R||A||M (hashlib -> OpenSSL C, ~GB/s), the mod-L scalar reduction
  (python bignum), RFC 8032 canonical-encoding prechecks (y < p, S < L),
  and packing into fixed-shape int32 tensors.
* **Device** — all the modular heavy lifting (~4400 field muls per
  signature): point decompression (two fixed exponentiation chains) and the
  256-step double-scalar-mul, batched over the leading axis.

Batches are padded to power-of-two buckets so XLA compiles a handful of
program shapes, then caches (SURVEY.md §7: static shapes; first compile
20-40s, later calls cached).

The verdict matches the CPU path (OpenSSL cofactorless verify) bit-for-bit;
``tests/test_crypto_jax.py`` checks this differentially including forged and
malformed inputs.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from . import curve
from . import field as F
from ..verifier.spi import VerifyItem

LOG = logging.getLogger(__name__)

MIN_BUCKET = 16
# Largest single device launch.  Measured on v5e (bench.py, round 2): 8192
# lanes is the throughput peak (63.6k sigs/s) after the signed-window
# ladder halved the per-item small-multiples tables and the pad-skew
# multiply removed the HBM-streaming intermediates; 16384 still spills
# VMEM and runs ~15% slower, 4096 underfills (42.5k).  Bigger requests are
# chunked at this size, so rate stays flat instead of regressing.  Tune
# via MOCHI_MAX_BUCKET without a code change.
def _max_bucket() -> int:
    """MOCHI_MAX_BUCKET, sanitized: >= MIN_BUCKET and a power of two (a
    non-power would chunk at sizes _bucket_size pads PAST the VMEM cap the
    knob exists to enforce; 0/negative would break the chunk loop)."""
    try:
        v = int(os.environ.get("MOCHI_MAX_BUCKET", "8192"))
    except ValueError:
        return 8192
    v = max(v, MIN_BUCKET)
    return 1 << (v.bit_length() - 1)  # round DOWN to a power of two


MAX_BUCKET = _max_bucket()


def _impl() -> str:
    """Device implementation: "xla" (default) or "pallas"
    (``MOCHI_VERIFY_IMPL=pallas`` — the hand-tiled kernel,
    :mod:`mochi_tpu.crypto.pallas_verify`)."""
    return os.environ.get("MOCHI_VERIFY_IMPL", "xla")


def _bucket_size(n: int) -> int:
    m = MIN_BUCKET
    while m < n:
        m *= 2
    return m


def prepare(items: Sequence[VerifyItem]):
    """Host-side packing: items -> dict of numpy tensors + precheck bitmap."""
    n = len(items)
    y_a = np.zeros((n, F.NLIMBS), dtype=np.int32)
    y_r = np.zeros((n, F.NLIMBS), dtype=np.int32)
    sign_a = np.zeros(n, dtype=np.int32)
    sign_r = np.zeros(n, dtype=np.int32)
    s_bits = np.zeros((n, 256), dtype=np.int32)
    h_bits = np.zeros((n, 256), dtype=np.int32)
    pre_ok = np.zeros(n, dtype=bool)

    for i, it in enumerate(items):
        if len(it.public_key) != 32 or len(it.signature) != 64:
            continue
        a_bytes = bytes(it.public_key)
        r_bytes = bytes(it.signature[:32])
        s_int = int.from_bytes(it.signature[32:], "little")
        ya = int.from_bytes(a_bytes, "little") & ((1 << 255) - 1)
        yr = int.from_bytes(r_bytes, "little") & ((1 << 255) - 1)
        # RFC 8032 decode rejects non-canonical y and S >= L (as OpenSSL does)
        if ya >= F.P_INT or yr >= F.P_INT or s_int >= F.L_INT:
            continue
        h_int = (
            int.from_bytes(
                hashlib.sha512(r_bytes + a_bytes + bytes(it.message)).digest(),
                "little",
            )
            % F.L_INT
        )
        y_a[i] = F.int_to_limbs(ya)
        y_r[i] = F.int_to_limbs(yr)
        sign_a[i] = a_bytes[31] >> 7
        sign_r[i] = r_bytes[31] >> 7
        s_bits[i] = np.unpackbits(
            np.frombuffer(s_int.to_bytes(32, "little"), dtype=np.uint8),
            bitorder="little",
        )
        h_bits[i] = np.unpackbits(
            np.frombuffer(h_int.to_bytes(32, "little"), dtype=np.uint8),
            bitorder="little",
        )
        pre_ok[i] = True
    return y_a, sign_a, y_r, sign_r, s_bits, h_bits, pre_ok


_verify_jit = jax.jit(curve.verify_prepared)


def verify_batch(
    items: Sequence[VerifyItem],
    device: Optional[jax.Device] = None,
    bucket: Optional[int] = None,
) -> List[bool]:
    """Verify a batch of Ed25519 signatures on the default JAX device.

    Returns a python bool list (the SPI bitmap).  Invalid encodings are
    rejected on host; padding lanes carry pre_ok=False and are sliced away.
    ``bucket`` forces a specific padded size (callers that know which program
    shapes are already compiled use it to avoid a fresh compile).
    """
    if not items:
        return []
    if len(items) > MAX_BUCKET and bucket is None:
        out: List[bool] = []
        for i in range(0, len(items), MAX_BUCKET):
            out.extend(verify_batch(items[i : i + MAX_BUCKET], device=device))
        return out
    y_a, sign_a, y_r, sign_r, s_bits, h_bits, pre_ok = prepare(items)
    n = len(items)
    m = _bucket_size(n) if bucket is None else bucket
    assert m >= n
    if m != n:
        pad = ((0, m - n), (0, 0))
        y_a = np.pad(y_a, pad)
        y_r = np.pad(y_r, pad)
        s_bits = np.pad(s_bits, pad)
        h_bits = np.pad(h_bits, pad)
        sign_a = np.pad(sign_a, ((0, m - n),))
        sign_r = np.pad(sign_r, ((0, m - n),))
    args = (y_a, sign_a, y_r, sign_r, s_bits, h_bits)
    if device is not None:
        args = tuple(jax.device_put(a, device) for a in args)
    if _impl() == "pallas":
        from . import pallas_verify

        bitmap = np.asarray(pallas_verify.verify_prepared_pallas(*args))[:n]
    else:
        bitmap = np.asarray(_verify_jit(*args))[:n]
    return [bool(b) for b in np.logical_and(bitmap, pre_ok)]


class JaxBatchBackend:
    """``BatchBackend`` for :class:`mochi_tpu.verifier.spi.BatchingVerifier`.

    The replica's async batcher calls this from a thread executor; each call
    is one device program launch (cached compile per bucket shape).

    Compile-stall avoidance: XLA compiles one program per batch-size bucket
    (20-60s each).  A batch whose bucket isn't compiled yet is served in
    chunks of the largest *already-compiled* bucket, while the bigger
    bucket's compile is kicked off on a background thread — so ramping load
    never parks live traffic behind a compile (it would blow client
    timeouts; see the batching discipline in SURVEY.md §7).
    """

    def __init__(self, device: Optional[jax.Device] = None):
        self.device = device
        self._ready: set[int] = set()
        self._compiling: set[int] = set()
        self._failed: set[int] = set()
        self._lock = threading.Lock()

    def warmup(self, batch_sizes: Sequence[int]) -> None:
        """Synchronously pre-compile the given bucket sizes (boot path)."""
        for n in batch_sizes:
            bucket = _bucket_size(n)
            verify_batch(_dummy_items(bucket), device=self.device)
            with self._lock:
                self._ready.add(bucket)

    def _compile_in_background(self, bucket: int) -> None:
        def run():
            try:
                items = _dummy_items(bucket)
                verify_batch(items, device=self.device)
                with self._lock:
                    self._ready.add(bucket)
            except Exception:
                LOG.exception(
                    "background compile of verify bucket %d failed; "
                    "bucket disabled (batches keep chunking at smaller sizes)",
                    bucket,
                )
                with self._lock:
                    self._failed.add(bucket)
            finally:
                with self._lock:
                    self._compiling.discard(bucket)

        threading.Thread(target=run, name=f"verify-warm-{bucket}", daemon=True).start()

    def __call__(self, items: Sequence[VerifyItem]) -> Sequence[bool]:
        bucket = _bucket_size(len(items))
        with self._lock:
            ready_now = bucket in self._ready
            ready = sorted(self._ready)
            schedule = (
                not ready_now
                and bool(ready)
                and bucket not in self._compiling
                and bucket not in self._failed
            )
            if schedule:
                self._compiling.add(bucket)
        if ready_now or not ready:
            # Bucket compiled, or nothing compiled yet (first ever call):
            # run directly (the latter eats one synchronous compile — servers
            # avoid it via boot-time warmup).
            out = verify_batch(items, device=self.device)
            with self._lock:
                self._ready.add(bucket)
            return out
        if schedule:
            self._compile_in_background(bucket)
        # Serve via already-compiled shapes only: chunk at the largest ready
        # bucket and pad each chunk up to the smallest ready bucket that fits,
        # so no chunk can trigger a synchronous compile.
        largest_ready = ready[-1]
        out: List[bool] = []
        for i in range(0, len(items), largest_ready):
            chunk = items[i : i + largest_ready]
            target = next(b for b in ready if b >= len(chunk))
            out.extend(verify_batch(chunk, device=self.device, bucket=target))
        return out


def _dummy_items(n: int) -> List[VerifyItem]:
    from .keys import generate_keypair

    kp = generate_keypair()
    msg = b"mochi-tpu warmup"
    sig = kp.sign(msg)
    return [VerifyItem(kp.public_key, msg, sig)] * n

from .keys import KeyPair, generate_keypair, sign, verify

__all__ = ["KeyPair", "generate_keypair", "sign", "verify"]

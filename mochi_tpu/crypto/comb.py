"""Known-signer comb verification: doubling-free Ed25519 for registered keys.

The cluster's verification traffic is not random-key: every grant
certificate, session handshake, and view-change vote is signed by one of
the n replica identities in the cluster configuration (SURVEY.md §2.4's
verify seam; BASELINE.json's n=64 f=21 north star).  The general ladder
(:func:`mochi_tpu.crypto.curve.double_scalar_mul_windowed`) cannot exploit
that: with an arbitrary public key A it must interleave 256 sequential
doublings with its window additions, and those doublings are both the FLOP
majority (~2048 of ~3600 field muls/signature) and the dependency chain
that keeps the VPU pipeline shallow.

For a REGISTERED key the doublings can be precomputed away entirely —
the classic fixed-base windowing idea (Lim-Lee comb / Pippenger
precomputation, as in ed25519 ref10's basepoint tables), applied here to
the SIGNER set rather than the curve basepoint.  On registration the
host computes, once per signer, the Niels-form table

    T[w][d] = [d * 16^w](-A)      w in 0..63, d in 0..8

(and the module keeps the analogous constant table for the basepoint B),
so the device-side check [S]B + [h](-A) == R becomes a pure sum of 128
table points — 64 constant-table selects for the B comb plus 64 per-lane
gathers from the signer table — with ZERO doublings:

    Q = sum_w  B_tab[w][s_w]  +  T[key][w][h_w]      (signed 4-bit digits)

Per item that is ~260 field muls for the R decompression plus 128 Niels
mixed additions (~900 muls): **~3x fewer field muls than the ladder and a
~3x shallower sequential chain** (128 dependent madds vs 256 doublings
interleaved with 128 additions).  The per-window signer lookup is a row
gather from a (K*576, 51) int32 table resident on device (~117 KB per
signer: 64 windows x 9 entries x 3 coords x 17 limbs), so even n=64
clusters stay ~7.5 MB.

Verdict semantics are IDENTICAL to the general path: the same cofactorless
equation with exact limb arithmetic, the same host prechecks
(:func:`mochi_tpu.crypto.batch_verify.prepare_packed`), the same RFC 8032
decompression checks — registration itself performs the host-side decode
and refuses non-canonical or non-point keys, which then simply fall
through to the general path.  ``tests/test_comb.py`` checks the bitmap
differentially against OpenSSL and the ladder, including forgeries,
wrong-key and malformed items, and mixed registered/unregistered batches.

This module is pure compute + registry; routing lives in
:func:`mochi_tpu.crypto.batch_verify.verify_batch` (``registry=`` arg) so
callers keep one entry point.  The reference has no counterpart for any of
this (it never signs — ``MochiProtocol.proto:123``).

Considered and not built: 5-bit windows (51 windows x 17 entries).  They
cut the madd count ~20% (128 -> 102) but the basepoint masked-select grows
from 9x64 to 17x51 terms (+50% select work), tables grow 1.5x, and the
signed recode needs base-32 carries — net model estimate <10% either way,
so the A/B budget went to the chain-vs-tree formulation instead
(``COMB_IMPL``), which attacks the dependency DEPTH the roofline keeps
flagging rather than the op count.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import curve
from . import field as F

LOG = logging.getLogger(__name__)

N_WINDOWS = 64
N_ENTRIES = 9  # signed 4-bit digits: magnitudes 0..8
ROW_WIDTH = 3 * F.NLIMBS  # ypx | ymx | xy2d


# --------------------------------------------------------------------------
# Host point arithmetic (python ints, extended coordinates).  Table builds
# run one batched inversion at the end (Montgomery trick) instead of two
# modular inversions per affine addition — ~10 ms per signer instead of
# ~100 ms, which matters when a service registers a 64-replica identity
# set at boot.


def _ext_add(p, q):
    """Complete unified addition (same add-2008-hwcd-3 law as the device)
    on python-int extended coordinates (X, Y, Z, T)."""
    P = F.P_INT
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * F.D_INT * t1 % P * t2 % P
    d = 2 * z1 * z2 % P
    e = b - a
    f = d - c
    g = d + c
    h = b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


_EXT_IDENTITY = (0, 1, 1, 0)


def _batch_affine(points) -> List[Tuple[int, int]]:
    """Extended -> affine for a list of points with ONE modular inversion."""
    P = F.P_INT
    zs = [pt[2] for pt in points]
    prefix = [1]
    for z in zs:
        prefix.append(prefix[-1] * z % P)
    inv_all = pow(prefix[-1], P - 2, P)
    out: List[Optional[Tuple[int, int]]] = [None] * len(points)
    for i in range(len(points) - 1, -1, -1):
        z_inv = prefix[i] * inv_all % P
        inv_all = inv_all * zs[i] % P
        x, y = points[i][0] * z_inv % P, points[i][1] * z_inv % P
        out[i] = (x, y)
    return out  # type: ignore[return-value]


def decompress_host(pub: bytes) -> Optional[Tuple[int, int]]:
    """RFC 8032 §5.1.3 point decoding on host ints.

    Mirrors :func:`mochi_tpu.crypto.curve.decompress` exactly (same
    candidate-root construction, same rejects: y >= p, no root, x = 0 with
    sign bit set).  Returns affine (x, y) or None.
    """
    if len(pub) != 32:
        return None
    P = F.P_INT
    enc = int.from_bytes(pub, "little")
    sign = enc >> 255
    y = enc & ((1 << 255) - 1)
    if y >= P:
        return None
    u = (y * y - 1) % P
    v = (F.D_INT * y * y + 1) % P
    x = u * pow(v, 3, P) % P * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P) % P
    vxx = v * x * x % P
    if vxx == u:
        pass
    elif vxx == (-u) % P:
        x = x * F.SQRT_M1_INT % P
    else:
        return None
    if x == 0 and sign == 1:
        return None
    if (x & 1) != sign:
        x = P - x
    return (x, y)


def _comb_table_rows(x: int, y: int) -> np.ndarray:
    """(64, 9, 51) int32 Niels comb table for the affine point (x, y):
    row [w][d] = [d * 16^w](x, y) as (y+x | y-x | 2dxy) limbs."""
    P = F.P_INT
    base = (x, y, 1, x * y % P)
    pts = []
    for _ in range(N_WINDOWS):
        acc = _EXT_IDENTITY
        for _d in range(N_ENTRIES - 1):
            acc = _ext_add(acc, base)
            pts.append(acc)
        # next window's base = [16] * base: 4 doublings of the current base
        for _ in range(4):
            base = _ext_add(base, base)
    affine = _batch_affine(pts)
    rows = np.zeros((N_WINDOWS, N_ENTRIES, ROW_WIDTH), dtype=np.int32)
    # d = 0 is the identity: Niels (1, 1, 0)
    one = F.int_to_limbs(1)
    rows[:, 0, : F.NLIMBS] = one
    rows[:, 0, F.NLIMBS : 2 * F.NLIMBS] = one
    k = 0
    for w in range(N_WINDOWS):
        for d in range(1, N_ENTRIES):
            ax, ay = affine[k]
            k += 1
            rows[w, d, : F.NLIMBS] = F.int_to_limbs((ay + ax) % P)
            rows[w, d, F.NLIMBS : 2 * F.NLIMBS] = F.int_to_limbs((ay - ax) % P)
            rows[w, d, 2 * F.NLIMBS :] = F.int_to_limbs(2 * F.D_INT * ax % P * ay % P)
    return rows


def signer_table(pub: bytes) -> Optional[np.ndarray]:
    """(64, 9, 51) comb table for -A (the verify equation uses [h](-A)),
    or None if ``pub`` is not a canonical curve-point encoding."""
    aff = decompress_host(pub)
    if aff is None:
        return None
    x, y = aff
    return _comb_table_rows((F.P_INT - x) % F.P_INT if x else 0, y)


_B_COMB: Optional[np.ndarray] = None
_B_COMB_LOCK = threading.Lock()


def _b_comb() -> np.ndarray:
    """(64, 9, 51) comb table for +B, built lazily once (import-time build
    would add ~10 ms to every process that merely imports the package)."""
    global _B_COMB
    if _B_COMB is None:
        with _B_COMB_LOCK:
            if _B_COMB is None:
                _B_COMB = _comb_table_rows(F.BX_INT, F.BY_INT)
    return _B_COMB


# --------------------------------------------------------------------------
# Registry


class SignerRegistry:
    """Pubkey -> device comb table registry.

    Capacity is padded to powers of two (min 8) so XLA compiles at most a
    handful of table shapes as the signer set grows; unused slots hold
    identity rows.  Thread-safe: the cluster registers replica identities
    at boot and (rarely) on live reconfiguration while the verifier's
    flush executor reads concurrently.
    """

    def __init__(self, device: Optional[jax.Device] = None):
        self._device = device
        self._idx: Dict[bytes, int] = {}
        self._tables: List[np.ndarray] = []
        self._rejected: set[bytes] = set()
        self._lock = threading.Lock()
        # (device, capacity) -> (device array, rows filled at build time)
        self._dev_tables: Dict[tuple, tuple] = {}

    def __len__(self) -> int:
        return len(self._idx)

    @property
    def generation(self) -> int:
        """Monotone registration count.  A comb program compiled against an
        older generation may be stale (capacity growth changes the table
        SHAPE and forces a recompile); the backend gates comb routing on
        generation match so live traffic never parks behind that compile."""
        return len(self._tables)

    def register(self, pub: bytes) -> Optional[int]:
        """Add a signer; returns its index, or None for invalid encodings
        (which the caller simply leaves to the general verify path)."""
        pub = bytes(pub)
        with self._lock:
            if pub in self._idx:
                return self._idx[pub]
            if pub in self._rejected:
                return None
        tab = signer_table(pub)  # outside the lock: ~10 ms of host math
        with self._lock:
            if pub in self._idx:  # raced with another registrar
                return self._idx[pub]
            if tab is None:
                self._rejected.add(pub)
                return None
            idx = len(self._tables)
            self._tables.append(tab)
            self._idx[pub] = idx
            # device tables invalidate via the generation check in
            # device_table() (gen = table count at build time)
            return idx

    def register_all(self, pubs: Sequence[bytes]) -> None:
        for p in pubs:
            self.register(p)

    def index_of(self, pub: bytes) -> Optional[int]:
        return self._idx.get(bytes(pub))

    @staticmethod
    def _capacity_for(gen: int) -> int:
        cap = 8
        while cap < gen:
            cap *= 2
        return cap

    def device_table(
        self, device: Optional[jax.Device] = None, gen: Optional[int] = None
    ) -> jax.Array:
        """(capacity * 576, 51) int32 flat table on the target device.

        ``gen`` pins the GENERATION the caller's compiled program (and its
        key-index routing) was checked against: the returned table has the
        capacity of that generation — so a registration that crossed a
        capacity boundary concurrently cannot change the table SHAPE under
        a ready-checked dispatch and force a synchronous retrace
        (code-review r4).  Content newer than ``gen`` is harmless (rows
        beyond the caller's generation are never indexed); content is
        always at least ``gen`` rows (tables only append).  Cached per
        (device, capacity), rebuilt when registrations outgrow the cache.
        """
        device = device if device is not None else self._device
        with self._lock:
            cur = len(self._tables)
            g = cur if gen is None else gen
            cap = self._capacity_for(max(1, g))
            key = (device, cap)
            cached = self._dev_tables.get(key)
            if cached is not None and cached[1] >= min(cur, cap):
                return cached[0]
            n_rows = min(cur, cap)
            flat = np.zeros((cap, N_WINDOWS, N_ENTRIES, ROW_WIDTH), np.int32)
            if n_rows:
                flat[:n_rows] = np.stack(self._tables[:n_rows])
            else:
                # even an empty registry ships well-formed identity rows
                one = F.int_to_limbs(1)
                flat[:, :, :, : F.NLIMBS] = one
                flat[:, :, :, F.NLIMBS : 2 * F.NLIMBS] = one
            flat = flat.reshape(cap * N_WINDOWS * N_ENTRIES, ROW_WIDTH)
            arr = (
                jax.device_put(flat, device)
                if device is not None
                else jnp.asarray(flat)
            )
            self._dev_tables[key] = (arr, n_rows)
            return arr


# --------------------------------------------------------------------------
# Device kernel

# Accumulation formulation (MOCHI_COMB_IMPL):
#   "chain" — DEFAULT: fori_loop of 128 Niels mixed additions (fewest
#             field muls: 64 iterations x 2 madds x 7 muls ~ 900).
#   "tree"  — all 128 window points materialized at once (signer rows
#             gathered, basepoint rows selected with ONE one-hot f32
#             matmul that XLA places on the MXU), converted Niels ->
#             extended (3 const-muls each, vectorized over the point
#             axis), then a 7-level balanced reduction with the complete
#             addition — ~40% more field muls but an ~18x shallower
#             sequential chain (7 wide adds vs 128 dependent madds) and
#             ~10x fewer dispatched ops for the scheduler.  Candidate for
#             the schedule-bound regime the roofline probe keeps
#             indicating; A/B'd on chip by scripts/comb_bench.py.
import os as _os

COMB_IMPL = _os.environ.get("MOCHI_COMB_IMPL", "chain")

# (p+1)/2: multiplying by it halves a field element (Niels y+x/y-x -> x,y)
_INV2_INT = (F.P_INT + 1) // 2
# 1/(2d): recovers t = x*y from the Niels xy2d coordinate
_INV_2D_INT = pow(2 * F.D_INT % F.P_INT, F.P_INT - 2, F.P_INT)


def _tree_accumulate(ypx, ymx, xy2d, n_points: int, lanes_n):
    """Balanced-tree sum of ``n_points`` Niels points per lane.

    Coordinates arrive limbs-leading over a fused point*lane axis —
    (17, P*B) with the point axis MAJOR — signs already applied.  Converts
    Niels -> extended with 3 constant muls per point (vectorized over the
    whole P*B width) and reduces with log2(P) wide complete additions.
    """
    inv2 = F.const(_INV2_INT, ypx.shape[1:])
    inv2d = F.const(_INV_2D_INT, ypx.shape[1:])
    x = F.mul(F.sub(ypx, ymx), inv2)
    y = F.mul(F.add(ypx, ymx), inv2)
    t = F.mul(xy2d, inv2d)
    z = F.one(ypx.shape[1:])
    pt = curve.Point(x, y, z, t)
    P = n_points
    while P > 1:
        half = P // 2
        lo = curve.Point(*(
            c.reshape(F.NLIMBS, P, lanes_n)[:, :half].reshape(
                F.NLIMBS, half * lanes_n
            )
            for c in pt
        ))
        hi = curve.Point(*(
            c.reshape(F.NLIMBS, P, lanes_n)[:, half:].reshape(
                F.NLIMBS, half * lanes_n
            )
            for c in pt
        ))
        pt = curve.add(lo, hi)
        P = half
    return pt


def verify_comb_prepared(
    table_flat: jnp.ndarray,
    key_idx: jnp.ndarray,
    y_r: jnp.ndarray,
    sign_r: jnp.ndarray,
    s_bytes: jnp.ndarray,
    h_bytes: jnp.ndarray,
    impl: Optional[str] = None,
) -> jnp.ndarray:
    """Batched comb verify -> (B,) validity bitmap.

    ``table_flat``: (K*576, 51) int32 signer tables (row [k*576 + w*9 + d]
    = Niels [d*16^w](-A_k)); ``key_idx``: (B,) int32 registry indices;
    ``y_r``/``sign_r``: R encodings as in
    :func:`mochi_tpu.crypto.curve.verify_prepared`; scalars as (B, 32)
    packed LE bytes.  Public-key validity is the REGISTRY's invariant
    (registration performs the host-side RFC 8032 decode), so the kernel
    checks only R's decode and the group equation.  ``impl`` selects the
    accumulation (``chain``/``tree`` — see ``COMB_IMPL``); a STATIC jit
    arg so A/B runs don't collide in the trace cache.
    """
    impl = COMB_IMPL if impl is None else impl
    s_dig = curve.digits4_from_bits(curve.unpack_bits(s_bytes).T)
    h_dig = curve.digits4_from_bits(curve.unpack_bits(h_bytes).T)
    s_mag, s_neg = curve.recode_signed4(s_dig)
    h_mag, h_neg = curve.recode_signed4(h_dig)

    r_point, ok_r = curve.decompress(y_r.T, sign_r)
    lanes = y_r.shape[:1]

    # One upfront row gather for all 64 windows — (64, B, 51) — instead of
    # 64 small in-loop gathers: XLA schedules a single fused gather and the
    # loop body stays pure VPU work on contiguous slices.
    win = jnp.arange(N_WINDOWS, dtype=jnp.int32)[:, None]
    flat_idx = key_idx[None, :] * (N_WINDOWS * N_ENTRIES) + win * N_ENTRIES + h_mag
    a_rows = jnp.take(table_flat, flat_idx, axis=0, mode="clip")

    b_tab = jnp.asarray(_b_comb())  # (64, 9, 51) trace-time constant

    if impl == "tree":
        B = a_rows.shape[1]

        def signed_niels(flat, neg):
            # flat: (51, N); neg: (N,) bool -> Niels negation applied
            ypx = flat[: F.NLIMBS]
            ymx = flat[F.NLIMBS : 2 * F.NLIMBS]
            xy2d = flat[2 * F.NLIMBS :]
            return (
                F.select(neg, ymx, ypx),
                F.select(neg, ypx, ymx),
                F.select(neg, F.neg(xy2d), xy2d),
            )

        a_flat = a_rows.reshape(N_WINDOWS * B, ROW_WIDTH).T  # (51, 64B)
        aypx, aymx, axy2d = signed_niels(a_flat, h_neg.reshape(-1))
        # basepoint rows for ALL windows with ONE one-hot matmul: the 0/1
        # selector and <2^15 limb values are exact in f32, and XLA places
        # the (64, B, 9) x (64, 9, 51) batched matmul on the MXU.
        # precision=HIGHEST: TPU's default f32 matmul decomposes through
        # bf16 passes (8-bit mantissa) that would truncate the 15-bit limb
        # values — same hazard _mul_mxu (field.py) documents; CPU's
        # full-f32 default masks it in tests.
        onehot = (
            s_mag[:, :, None] == jnp.arange(N_ENTRIES, dtype=jnp.int32)
        ).astype(jnp.float32)
        b_rows = jnp.einsum(
            "wbe,wec->wbc",
            onehot,
            b_tab.astype(jnp.float32),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32)
        b_flat = b_rows.reshape(N_WINDOWS * B, ROW_WIDTH).T
        bypx, bymx, bxy2d = signed_niels(b_flat, s_neg.reshape(-1))
        q = _tree_accumulate(
            jnp.concatenate([aypx, bypx], axis=1),
            jnp.concatenate([aymx, bymx], axis=1),
            jnp.concatenate([axy2d, bxy2d], axis=1),
            2 * N_WINDOWS,
            B,
        )
        eq_x = F.eq(q.x, F.mul(r_point.x, q.z))
        eq_y = F.eq(q.y, F.mul(r_point.y, q.z))
        return ok_r & eq_x & eq_y

    h_neg_i = h_neg.astype(jnp.int32)
    s_neg_i = s_neg.astype(jnp.int32)

    def body(w, q):
        q = curve.Point(*q)
        # --- signer-table point (gathered rows, limbs last -> limbs first)
        row = lax.dynamic_index_in_dim(a_rows, w, axis=0, keepdims=False).T
        aypx = row[: F.NLIMBS]
        aymx = row[F.NLIMBS : 2 * F.NLIMBS]
        axy2d = row[2 * F.NLIMBS :]
        hn = lax.dynamic_index_in_dim(h_neg_i, w, axis=0, keepdims=False).astype(bool)
        aypx, aymx = F.select(hn, aymx, aypx), F.select(hn, aypx, aymx)
        axy2d = F.select(hn, F.neg(axy2d), axy2d)
        q = curve.madd_niels(q, aypx, aymx, axy2d)
        # --- basepoint comb entry (constant table, masked 9-entry select)
        bw = lax.dynamic_index_in_dim(b_tab, w, axis=0, keepdims=False)  # (9, 51)
        sd = lax.dynamic_index_in_dim(s_mag, w, axis=0, keepdims=False)  # (B,)
        acc = jnp.zeros((ROW_WIDTH, *sd.shape), jnp.int32)
        for e in range(N_ENTRIES):
            acc = acc + jnp.where((sd == e)[None], bw[e][:, None], 0)
        bypx = acc[: F.NLIMBS]
        bymx = acc[F.NLIMBS : 2 * F.NLIMBS]
        bxy2d = acc[2 * F.NLIMBS :]
        sn = lax.dynamic_index_in_dim(s_neg_i, w, axis=0, keepdims=False).astype(bool)
        bypx, bymx = F.select(sn, bymx, bypx), F.select(sn, bypx, bymx)
        bxy2d = F.select(sn, F.neg(bxy2d), bxy2d)
        q = curve.madd_niels(q, bypx, bymx, bxy2d)
        return tuple(q)

    q = lax.fori_loop(
        0, N_WINDOWS, body, tuple(curve.identity(lanes)), unroll=curve.LADDER_UNROLL
    )
    q = curve.Point(*q)
    eq_x = F.eq(q.x, F.mul(r_point.x, q.z))
    eq_y = F.eq(q.y, F.mul(r_point.y, q.z))
    return ok_r & eq_x & eq_y


_verify_comb_jit = jax.jit(verify_comb_prepared, static_argnames=("impl",))


# --------------------------------------------------------------------------
# Launch machinery — mirrors batch_verify's prepare/dispatch/readback split
# (same pipelining discipline, same padding/precheck semantics), with the
# signer index as an extra lane tensor and y_a dropped (the registry IS the
# pubkey check).


def _prepare_comb(items, key_idx: np.ndarray, bucket: Optional[int]):
    """Host half: pack + pad one chunk (numpy/hashlib only — safe on the
    prepare worker thread)."""
    from . import batch_verify as BV

    _, _, y_r, sign_r, s_sc, h_sc, pre_ok = BV.prepare_packed(items)
    n = len(items)
    m = BV._bucket_size(n) if bucket is None else bucket
    assert m >= n
    if m != n:
        pad2 = ((0, m - n), (0, 0))
        y_r = np.pad(y_r, pad2)
        s_sc = np.pad(s_sc, pad2)
        h_sc = np.pad(h_sc, pad2)
        sign_r = np.pad(sign_r, ((0, m - n),))
        key_idx = np.pad(key_idx, ((0, m - n),))
    return (key_idx.astype(np.int32), y_r, sign_r, s_sc, h_sc), pre_ok


def comb_dispatch_count() -> int:
    """Monotone process-global count of real comb-program device dispatches
    (tests and stats; the backend's readiness marking uses the
    THREAD-LOCAL counters in ``batch_verify.thread_dispatch_counts``)."""
    from . import batch_verify as BV

    return BV._comb_device_dispatches


def _dispatch_comb(prepared, registry: SignerRegistry, device, table=None):
    """Device half: transfer + async dispatch (main thread — device_table
    may device_put on first use).  Shares batch_verify's all-rejected fast
    path and its dispatch counter (the backend's compile-readiness
    tracking counts REAL device dispatches, comb or general).

    ``table``: the device table PINNED at routing time.  Callers that
    checked comb-readiness must pass the table they checked against — a
    concurrent registration can grow the live registry's table SHAPE
    between the check and this dispatch, and fetching it here would
    retrace + compile synchronously on the hot path (code-review r4).
    Verdicts with a pinned older table stay exact: the items were
    index-mapped against that table's generation."""
    from . import batch_verify as BV

    args, pre_ok = prepared
    if not pre_ok.any():
        return None, pre_ok
    BV._note_dispatch(comb=True)
    if table is None:
        table = registry.device_table(device)
    if device is not None:
        args = tuple(jax.device_put(a, device) for a in args)
    key_idx, y_r, sign_r, s_sc, h_sc = args
    return _verify_comb_jit(table, key_idx, y_r, sign_r, s_sc, h_sc), pre_ok


def verify_stream(
    items,
    key_idx: np.ndarray,
    registry: SignerRegistry,
    device: Optional[jax.Device] = None,
    bucket: Optional[int] = None,
    gen: Optional[int] = None,
) -> List[bool]:
    """Comb-verify ``items`` (all with registered signers; ``key_idx``
    aligned, every index < ``gen`` when pinned) -> bool list.  Oversized
    requests chunk at ``batch_verify.MAX_BUCKET`` behind the same bounded
    launch window and prepare-thread overlap as the general path."""
    from . import batch_verify as BV

    if not items:
        return []
    # Pin the device table ONCE for the whole stream (at the caller's
    # checked generation when given): concurrent registration must not
    # swap in a new-shaped table mid-stream — every later chunk would
    # retrace (see _dispatch_comb docstring).
    table = registry.device_table(device, gen)
    if len(items) > BV.MAX_BUCKET and bucket is None:
        from collections import deque

        window: deque = deque()
        out: List[bool] = []
        chunks = [
            (items[i : i + BV.MAX_BUCKET], key_idx[i : i + BV.MAX_BUCKET])
            for i in range(0, len(items), BV.MAX_BUCKET)
        ]
        pool = BV._prep_pool()
        prep_fut = pool.submit(_prepare_comb, chunks[0][0], chunks[0][1], None)
        for k, (chunk, _) in enumerate(chunks):
            prepared = prep_fut.result()
            if k + 1 < len(chunks):
                nxt = chunks[k + 1]
                prep_fut = pool.submit(_prepare_comb, nxt[0], nxt[1], None)
            window.append(
                (_dispatch_comb(prepared, registry, device, table), len(chunk))
            )
            if len(window) >= BV._PIPELINE_DEPTH:
                out.extend(BV._readback(*window.popleft()))
        while window:
            out.extend(BV._readback(*window.popleft()))
        return out
    launched = _dispatch_comb(
        _prepare_comb(items, key_idx, bucket), registry, device, table
    )
    return BV._readback(launched, len(items))


def warmup(
    registry: SignerRegistry,
    batch_sizes,
    device: Optional[jax.Device] = None,
    gen: Optional[int] = None,
) -> None:
    """Pre-compile the comb program for the given bucket sizes against the
    table shape of generation ``gen`` (default: current).  Compiles are
    keyed on shapes only, so zero-filled operands suffice — no signing
    needed.  Callers that record readiness for a generation must pass that
    generation so the compiled shape is the one later dispatches pin."""
    from . import batch_verify as BV

    table = registry.device_table(device, gen)
    for n in batch_sizes:
        m = BV._bucket_size(int(n))
        bm = _verify_comb_jit(
            table,
            jnp.zeros((m,), jnp.int32),
            jnp.zeros((m, F.NLIMBS), jnp.int32),
            jnp.zeros((m,), jnp.int32),
            jnp.zeros((m, 32), jnp.uint8),
            jnp.zeros((m, 32), jnp.uint8),
        )
        np.asarray(bm)  # force compile + execute through any relay

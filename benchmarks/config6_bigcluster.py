"""Config 6: the north-star cluster SHAPE — n=64 replicas, f=21, rf=64.

BASELINE.json's headline metric is "signed PUT ops/sec at n=64, f=21", and
through round 4 no cluster larger than 6 replicas had ever booted: config 4
simulates the n=64 signature *burst* at the crypto layer, but a 64-grant
Write1 fan-out, 43-grant quorum certificate assembly/trimming, and Write2
cert verification across 64 replicas had never run through the actual
protocol (VERDICT r4 missing #1).  This config boots the real thing —
64 full replicas (real loopback TCP, real Ed25519 signing, real quorum
state machines) in one process — and measures signed PUTs end-to-end,
plus an n=16 f=5 record (the CI-sized shape, config-3 grounding).

The reference supports RF up to n (``ClusterConfiguration.java:167-186``)
but publishes no number at this scale; the paper's WAN table stops at
rf=4 (``paper/MochiDB/mochiDB.tex:204-220``).  So the record here is a
first, not a comparison: the committed evidence that the protocol stack
holds its quorum math, cert sizes, and timeout budgets at the shape the
crypto stack was designed for.

Measured per shape:
- signed PUT commit latency p50/p95 and txn/s (concurrent writers)
- certificate size: grants kept after quorum-cover trimming (must be
  exactly 2f+1 — the client shaves the rf-quorum surplus) and wire bytes
- cluster-wide grant-verify rate implied by the txn rate (each replica
  checks every grant in each Write2 cert: txn/s x n x (2f+1) verifies/s)
- boot wall time for the 64-replica set

Verifier postures: "cpu" = per-replica inline OpenSSL (reference-analog);
"service" = all replicas ship cert checks to ONE shared verifier service
(the TPU-owner topology; on a TPU backend the known-signer comb registry
holds all 64 cluster identities — its design size, crypto/comb.py:34).
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import Dict, List


from benchmarks.config1_cluster import _pct  # one percentile rule for all configs


def _pooled_ms(metrics_list, name: str) -> Dict:
    """Pool one named timer's sample windows across many registries
    (worker clients, or all n replicas) into p50/p95/mean ms + count.

    Pooling the raw reservoirs (not averaging per-registry percentiles)
    keeps the statistic honest when registries carry very different sample
    counts — e.g. 8 worker clients with 5 commits each."""
    samples: List[float] = []
    count = 0
    total = 0.0
    for m in metrics_list:
        t = m.timers.get(name)
        if t is None:
            continue
        samples.extend(t.samples)
        count += t.total_count
        total += t.total_seconds
    if not samples:
        return {"count": 0}
    return {
        "count": count,
        "mean_ms": round(total / count * 1e3, 2),
        "p50_ms": round(_pct(samples, 0.50) * 1e3, 2),
        "p95_ms": round(_pct(samples, 0.95) * 1e3, 2),
    }


def _commit_breakdown(clients, replicas, txns: int) -> Dict:
    """Decompose the signed-PUT commit path from the stage timers the
    client and replicas already recorded during the workload (VERDICT r5
    weak #5: the 755 ms n=64 p50 had no decomposition).

    Stages (client clock): ``write1_phase`` = grant fan-out round trip;
    ``write2_fanout_wait`` = certificate fan-out through last response
    (CONTAINS each replica's verify wait + store apply + wire/loop time);
    ``write2_tally`` = client-side quorum tally after the last response.
    ``envelope_encode_sign`` is per TARGET (n per fan-out), so its mean ×
    2n approximates the client's total serialization share per txn.
    Replica clock: ``verify_wait`` = the SPI round trip a batch's auth +
    cert checks share; ``store_apply_write2``/``grant_issue_write1`` = the
    batched store entries; ``replica_crypto_local`` = synchronous host
    crypto (MACs, grant signs)."""
    client_ms = {
        "write1_phase": _pooled_ms(clients, "write1-phase"),
        "write2_fanout_wait": _pooled_ms(clients, "write2-fanout-wait"),
        "write2_tally": _pooled_ms(clients, "write2-tally"),
        "envelope_encode_sign": _pooled_ms(clients, "envelope-encode-sign"),
        # synchronous build+serialize+send loop per fan-out (one sample per
        # fan-out, covering all n targets) — net/transport.py fan_out
        "fanout_serialize_send": _pooled_ms(clients, "fanout-serialize-send"),
    }
    replica_ms = {
        "verify_wait": _pooled_ms(replicas, "replica.auth-verify"),
        "store_apply_write2": _pooled_ms(replicas, "replica.write2"),
        "grant_issue_write1": _pooled_ms(replicas, "replica.write1"),
        "replica_crypto_local": _pooled_ms(replicas, "replica.crypto-local"),
    }
    out: Dict = {"client_ms": client_ms, "replica_ms": replica_ms, "txns": txns}
    w1 = client_ms["write1_phase"].get("p50_ms")
    w2 = client_ms["write2_fanout_wait"].get("p50_ms")
    tally = client_ms["write2_tally"].get("p50_ms")
    if None not in (w1, w2, tally):
        stages = {
            "write1_phase": w1,
            "write2_fanout_wait": w2,
            "write2_tally": tally,
        }
        out["dominant_stage"] = max(stages, key=stages.get)  # type: ignore[arg-type]
        out["stage_p50_sum_ms"] = round(sum(stages.values()), 1)
    return out


async def _run_shape(
    n: int, writers: int, writes_per_writer: int, verifier: str
) -> Dict:
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    service = None
    factory = None
    if verifier == "service":
        from mochi_tpu.verifier.service import RemoteVerifier, VerifierService
        from mochi_tpu.verifier.spi import CoalescingVerifier, CpuVerifier

        inner = None
        try:
            import jax

            if jax.default_backend() == "tpu":
                from mochi_tpu.verifier.tpu import TpuBatchVerifier

                inner = TpuBatchVerifier(max_delay_s=0.001, warmup_buckets=(16,))
        except Exception:
            inner = None
        if inner is None:
            inner = CpuVerifier()
        service = VerifierService(port=0, verifier=inner)
        await service.start()
        factory = lambda: CoalescingVerifier(
            RemoteVerifier("127.0.0.1", service.bound_port)
        )

    try:
        t_boot = time.perf_counter()
        # shed_lag_ms=0: in this in-process harness all n replicas share ONE
        # event loop, so the lag every replica sheds on is the whole
        # cluster's congestion, not its own (the config-1 open_loop_note
        # effect, amplified 13x at n=64) — admission control would refuse
        # the very load this record exists to measure.  The per-process
        # posture keeps the production default.
        async with VirtualCluster(
            n, rf=n, verifier_factory=factory, shed_lag_ms=0.0
        ) as vc:
            boot_s = time.perf_counter() - t_boot
            cfg = vc.config
            # Register the full identity set with a comb-capable service
            # backend (the n=64 registry is the comb's design size).
            comb_registration = None
            if service is not None and hasattr(service.verifier, "register_signers"):
                try:
                    service.verifier.register_signers(
                        [kp.public_key for kp in vc.keypairs.values()]
                    )
                    comb_registration = "ok"
                except Exception as exc:
                    # Recorded, not swallowed: a silent fallback here would
                    # publish a "comb design-size" record that actually
                    # measured the non-comb path (review r5).
                    comb_registration = f"FAILED: {type(exc).__name__}: {exc}"[:200]

            write_lat: List[float] = []
            cert_grants: List[int] = []
            cert_bytes: List[int] = []

            async def worker(wi: int) -> None:
                client = vc.client(timeout_s=60.0)
                for k in range(writes_per_writer):
                    key = f"big-{wi}-{k}"
                    t0 = time.perf_counter()
                    await client.execute_write_transaction(
                        TransactionBuilder().write(key, b"v%d" % k).build()
                    )
                    write_lat.append(time.perf_counter() - t0)

            t0 = time.perf_counter()
            await asyncio.gather(*[worker(i) for i in range(writers)])
            wall = time.perf_counter() - t0
            # Stage decomposition from the timers the workload just filled
            # (clients captured before the cert read-back client joins).
            workload_clients = list(vc._clients)
            breakdown = _commit_breakdown(
                [c.metrics for c in workload_clients],
                [r.metrics for r in vc.replicas],
                len(write_lat),
            )

            # Certificate shape evidence from a read-back: grants kept
            # after quorum-cover trimming + wire size of the signed cert.
            client = vc.client(timeout_s=60.0)
            res = await client.execute_read_transaction(
                TransactionBuilder().read("big-0-0").build()
            )
            cert = res.operations[0].current_certificate
            if cert is not None:
                cert_grants.append(len(cert.grants))
                # exact canonical mcode bytes of the certificate
                # object — exactly what each Write2 envelope carries to
                # every replica in the set
                from mochi_tpu.protocol.codec import _encode_py

                cert_bytes.append(len(_encode_py(cert.to_obj())))

        txn = len(write_lat)
        txn_s = txn / wall if wall > 0 else 0.0
        f = cfg.f
        quorum = cfg.quorum
        rec = {
            "n": n,
            "f": f,
            "quorum": quorum,
            "boot_s": round(boot_s, 2),
            "txns": txn,
            "txn_per_s": round(txn_s, 2),
            "commit_p50_ms": round(_pct(write_lat, 0.50) * 1e3, 1),
            "commit_p95_ms": round(_pct(write_lat, 0.95) * 1e3, 1),
            "cert_grants_after_trim": cert_grants[0] if cert_grants else None,
            # every replica in the set verifies every grant of every cert
            "grant_verifies_per_s_cluster": round(txn_s * n * quorum, 1),
            "writers": writers,
            "commit_breakdown_ms": breakdown,
        }
        if cert_bytes:
            rec["cert_wire_bytes"] = cert_bytes[0]
        if service is not None:
            rec["service_items"] = service.items
        if comb_registration is not None:
            rec["comb_registration"] = comb_registration
        return rec
    finally:
        if service is not None:
            await service.close()


def run(
    writers: int = 8,
    writes_per_writer: int = 5,
    verifier: str = "service",
    shapes: tuple = (16, 64),
) -> Dict:
    """Default posture is the production topology (as config 1): one shared
    verifier service for the whole cluster.  At n=64 every replica checks
    the SAME 43 grant signatures per cert, so the service's single-flight
    memoization collapses ~2752 submitted verifies/txn to 43 unique ones —
    measured ~4x txn/s over inline per-replica OpenSSL on one host core
    (authoritative numbers live in benchmarks/results_r05.json, not here),
    and the effect is the whole thesis of the shared TPU-verifier design
    at this scale."""
    from mochi_tpu.utils.runtime import reset_gc_debt, tune_gc_for_server

    tune_gc_for_server()
    # Shapes are GC-isolated by reset_gc_debt(), not by ordering: the r5
    # "n64-first depresses a following n16 ~45%" artifact was GC DEBT — the
    # torn-down 64-replica object graph is cyclic (replicas↔stores↔grant
    # books, task callbacks), so under the relaxed server thresholds
    # (50000/50/50) it sits uncollected while the next shape's allocations
    # repeatedly trigger young-gen collections that trace the dead giant
    # graph; collect-and-refreeze between shapes returns the small shape to
    # within noise of its standalone rate in either order (root cause +
    # measurements: BASELINE.md; regression: tests/test_bigcluster.py
    # run-order-independence test).
    # `shapes` exists for the --smoke harness-rot pass (run_all.py), which
    # runs one tiny shape; the battery default is the published (16, 64).
    shape_recs: Dict[int, Dict] = {}
    for shape_n in shapes:
        shape_recs[shape_n] = asyncio.run(
            _run_shape(shape_n, writers, writes_per_writer, verifier)
        )
        reset_gc_debt()
    big_n = max(shapes)
    big = shape_recs[big_n]
    mid = shape_recs.get(16) if big_n != 16 else None
    # Detected backend platform, so records merged from OUTSIDE run_all's
    # battery loop (which stamps it post-hoc) carry the same schema as
    # every other config (ADVICE r5).
    try:
        import jax

        platform = jax.devices()[0].platform
    except Exception:
        platform = "unknown"
    big_f = (big_n - 1) // 3
    rec = {
        "metric": f"signed_put_north_star_shape_n{big_n}_f{big_f}",
        "value": big["txn_per_s"],
        "unit": "txns/sec",
        "platform": platform,
        "verifier": verifier,
        "note": (
            "single-host in-process cluster: all 64 replicas + clients share "
            "one core, so txn/s is a protocol-correctness-at-scale record "
            "(real 43-grant certs, real 64-way fan-out), not a deployment "
            "throughput claim; per-txn cluster-wide work is n*quorum grant "
            "verifies = 2752 Ed25519 checks at n=64"
        ),
    }
    # keyed AFTER the literal so a 16-max shapes run can't have the
    # mid-shape literal (None) clobber the measured record
    rec[f"n{big_n}_f{big_f}"] = big
    if mid is not None:
        rec["n16_f5"] = mid
    if verifier == "service" and os.environ.get("MOCHI_BENCH_FULL"):
        # Battery posture: attach the inline-OpenSSL comparison leg so the
        # published record carries the memoization A/B alongside.
        inline = asyncio.run(_run_shape(64, writers, writes_per_writer, "cpu"))
        rec["n64_f21_inline_cpu"] = inline
        if inline["txn_per_s"]:
            rec["service_vs_inline"] = round(
                big["txn_per_s"] / inline["txn_per_s"], 2
            )
    return rec


if __name__ == "__main__":
    import json

    print(json.dumps(run()))

"""Config 3: n=16 f=5 quorum-cert aggregation under YCSB-A.

YCSB workload A: 50% reads / 50% updates over a zipfian key popularity
distribution.  Each update's Write2 carries a certificate of 2f+1 = 11
MultiGrants whose signatures are checked through the batch verifier; each
read response is server-signed.  The measured number is certificate-
aggregation throughput: how many (verify 11-grant certificate + tally) ops
the verifier sustains per second, with signatures batched across concurrent
transactions — the reference's quorum tally (``InMemoryDataStore.java:590``,
``MochiDBClient.java:378-382``) plus the signature checks it never had.
"""

from __future__ import annotations

import time
from typing import Dict


def _zipf_keys(rng, n_keys: int, n_ops: int, s: float = 0.99, prefix: str = "key"):
    ranks = (
        rng.zipf(1.0 + s, size=n_ops * 2) - 1
    )  # oversample, clip to key space
    ranks = ranks[ranks < n_keys][:n_ops]
    while len(ranks) < n_ops:
        more = rng.zipf(1.0 + s, size=n_ops) - 1
        ranks = list(ranks) + list(more[more < n_keys])
        ranks = ranks[:n_ops]
    return [f"{prefix}-{r}" for r in ranks]


def run(n: int = 16, f: int = 5, n_ops: int = 2048, batch: int = 4096) -> Dict:
    import numpy as np

    import jax

    from mochi_tpu.crypto import batch_verify, keys
    from mochi_tpu.crypto.curve import verify_prepared
    from mochi_tpu.parallel.sharded import make_mesh, make_quorum_step
    from mochi_tpu.verifier.spi import VerifyItem

    assert n >= 3 * f + 1
    quorum = 2 * f + 1
    rng = np.random.default_rng(99)
    server_keys = [keys.generate_keypair() for _ in range(n)]
    ycsb_keys = _zipf_keys(rng, n_keys=256, n_ops=n_ops)

    # Build the signature stream: updates contribute `quorum` grant
    # signatures, reads one response signature (50/50 split).
    items = []
    group_ids = []
    group = 0
    for i, key in enumerate(ycsb_keys):
        if i % 2 == 0:  # update: a Write2 certificate of 2f+1 signed grants
            payload = b"grant|%s|ts=%d" % (key.encode(), i)
            for s in range(quorum):
                items.append(
                    VerifyItem(
                        server_keys[s].public_key,
                        payload,
                        server_keys[s].sign(payload),
                    )
                )
                group_ids.append(group)
        else:  # read: one signed response from a random replica
            payload = b"read|%s|rid=%d" % (key.encode(), i)
            sidx = int(rng.integers(0, n))
            items.append(
                VerifyItem(
                    server_keys[sidx].public_key, payload, server_keys[sidx].sign(payload)
                )
            )
            group_ids.append(group)
        group += 1

    n_groups = group
    prep = batch_verify.prepare(items)
    dev = jax.devices()[0]

    mesh = make_mesh(len(jax.devices()[:1]))  # single device: still exercises the step
    step = make_quorum_step(mesh, n_groups)
    # pad to mesh multiple
    from mochi_tpu.parallel.sharded import pad_to_multiple

    arrays, m = pad_to_multiple(
        tuple(prep[:6]) + (np.asarray(group_ids, np.int32),),
        len(items),
        mesh.devices.size,
        dead_group=0,
    )
    args = tuple(jax.device_put(a, dev) for a in arrays)
    thr = np.int32(quorum)

    out = jax.block_until_ready(step(*args, thr))  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        # readback inside the timed region: true sync through the axon relay
        out = tuple(np.asarray(x) for x in step(*args, thr))
        best = min(best, time.perf_counter() - t0)
    bitmap, counts, committed = out
    assert bitmap[: len(items)].all()

    rec = {
        "metric": "ycsb_a_quorum_cert_aggregation",
        "value": round(n_groups / best, 1),
        "unit": "certs/sec",
        "sigs_per_sec": round(len(items) / best, 1),
        "n": n,
        "f": f,
        "quorum": quorum,
        "ops": n_groups,
        "sigs": len(items),
        "ms": round(best * 1e3, 2),
    }
    try:
        rec["cluster_ycsb_a"] = run_cluster_ycsb()
    except Exception as exc:  # the device microbench result stands alone
        rec["cluster_ycsb_a"] = {"error": f"{type(exc).__name__}: {exc}"[:200]}
    return rec


def run_cluster_ycsb(
    n_clients: int = 5, n_ops_per_client: int = 60, n_keys: int = 64
):
    """YCSB-A through the REAL cluster in the production verify posture:
    50% reads / 50% updates over a zipfian key distribution, 5 concurrent
    clients against a 5-replica virtual cluster (rf=4, full signing), with
    every replica shipping its signature batches to ONE shared verifier
    service over the mcode RPC — the service runs the TPU batch verifier
    when a chip is present (client→replica→service→device end-to-end,
    VERDICT r2 item 6), and the batching+memoizing CPU path otherwise."""
    import asyncio
    import time as _time

    import numpy as np

    try:
        from benchmarks.config1_cluster import _pct
    except ImportError:  # direct-script run: benchmarks/ is sys.path[0]
        from config1_cluster import _pct
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.virtual_cluster import VirtualCluster
    from mochi_tpu.verifier.service import RemoteVerifier, VerifierService
    from mochi_tpu.verifier.spi import CoalescingVerifier, CpuVerifier

    rng = np.random.default_rng(4242)

    async def amain():
        inner = None
        platform = "cpu-service"
        try:
            import jax

            if jax.default_backend() == "tpu":
                from mochi_tpu.verifier.tpu import TpuBatchVerifier

                inner = TpuBatchVerifier(max_delay_s=0.001, warmup_buckets=(16,))
                platform = "tpu-service"
        except Exception:
            inner = None
        if inner is None:
            inner = CpuVerifier()
        service = VerifierService(port=0, verifier=inner)
        await service.start()
        factory = lambda: CoalescingVerifier(RemoteVerifier("127.0.0.1", service.bound_port))
        try:
            return await _ycsb_cluster(factory, platform, service)
        finally:
            await service.close()

    async def _ycsb_cluster(factory, platform, service):
        async with VirtualCluster(5, rf=4, verifier_factory=factory) as vc:
            # Register the replica identities with the service's comb
            # registry: grant-certificate traffic is signed exclusively by
            # these n keys, so it takes the doubling-free comb path
            # (crypto/comb.py) — the production posture for cluster verify.
            if hasattr(service.verifier, "register_signers"):
                service.verifier.register_signers(
                    list(vc.config.public_keys.values())
                )
            # preload the keyspace so reads hit existing keys — batched
            # into multi-write transactions (16 keys each) instead of 64
            # sequential round trips of untimed setup
            seed_client = vc.client()
            for base in range(0, n_keys, 16):
                tb = TransactionBuilder()
                for i in range(base, min(base + 16, n_keys)):
                    tb.write(f"y-{i}", b"init")
                await seed_client.execute_write_transaction(tb.build())
            read_lat: list = []
            update_lat: list = []

            async def worker(ci: int):
                client = vc.client()
                klist = _zipf_keys(
                    rng, n_keys=n_keys, n_ops=n_ops_per_client, prefix="y"
                )
                for j, key in enumerate(klist):
                    t0 = _time.perf_counter()
                    if j % 2 == 0:
                        await client.execute_write_transaction(
                            TransactionBuilder().write(key, b"u%d-%d" % (ci, j)).build()
                        )
                        update_lat.append(_time.perf_counter() - t0)
                    else:
                        await client.execute_read_transaction(
                            TransactionBuilder().read(key).build()
                        )
                        read_lat.append(_time.perf_counter() - t0)
                await client.close()

            t0 = _time.perf_counter()
            await asyncio.gather(*[worker(i) for i in range(n_clients)])
            wall = _time.perf_counter() - t0
            await seed_client.close()

            ops = n_clients * n_ops_per_client
            return {
                "txn_s": round(ops / wall, 1),
                "read_p50_ms": round(_pct(read_lat, 0.5) * 1e3, 2),
                "read_p95_ms": round(_pct(read_lat, 0.95) * 1e3, 2),
                "update_p50_ms": round(_pct(update_lat, 0.5) * 1e3, 2),
                "update_p95_ms": round(_pct(update_lat, 0.95) * 1e3, 2),
                "clients": n_clients,
                "ops": ops,
                "zipf_keys": n_keys,
                # provenance emitted by the harness so --publish republishes
                # it instead of dropping hand-edits
                "platform": platform,
                "service_items": service.items,
                "topology": (
                    "client -> replica -> shared verifier service -> device; "
                    "5-replica virtual cluster, rf=4, full signing"
                ),
            }

    return asyncio.run(amain())


if __name__ == "__main__":
    import json

    print(json.dumps(run()))

"""Config 9: open-loop front-end overload — the service test.

Every prior cluster benchmark drove <= 5 fat CLOSED-loop clients: each
waits for its transaction before issuing the next, so offered load
self-throttles to whatever the cluster serves and overload is structurally
impossible.  This config is ARRIVAL-RATE-driven: an open-loop generator
fires transactions at a configured rate across >= 1,000 concurrent
in-process client sessions (each a full ``MochiDBClient`` with its own
keypair, MAC sessions, and netsim-conditioned connections) against a
5-replica signed cluster, whether or not the cluster keeps up — the
workload shape under which the difference between a benchmark and a
service shows (ROADMAP item 5; DSig/Handel motivate keeping the critical
path useful while crypto work queues).

Three phases, one record (``results_r12.json``):

1. **capacity probe** — short closed-loop burst; its throughput seeds the
   offered-load ladder so the knee lands mid-curve on any host;
2. **knee curve** — open-loop legs at rising offered load; the knee is the
   last rung whose goodput keeps up with offered (>= ``knee_keepup``);
3. **overload legs** — offered load at 1.5x and 2x the knee: goodput,
   shed rate, client-surfaced refusals, latency of survivors, RSS and
   bounded-table sizes (session table, msg-id maps, grant ledgers).

Safety rides along: the PR-7 ``InvariantChecker`` samples the replicas'
stores throughout, every acked write is recorded, and the record embeds
the final re-read verdict — shedding may refuse NEW work, but an
acknowledged write lost under overload would be a safety failure, not a
performance number.

Acceptance (ISSUE 8): knee located at >= 1,000 concurrent sessions; at 2x
past the knee the cluster sustains >= 70% of knee goodput with zero
acked-write loss and capped, reported table sizes.
"""

from __future__ import annotations

import asyncio
import gc
import math
import os
import time
from typing import Dict, List, Optional

from .config7_wan import _pcts

RTT_MS = 10.0
JITTER_MS = 2.0
SEED = 9


def _rss_mb() -> float:
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except OSError:
        pass
    return float("nan")


def _table_sizes(vc, sessions) -> Dict[str, int]:
    """Bounded-state snapshot: every table the tentpole bounds, at its
    current size — the record's no-unbounded-growth evidence."""
    return {
        "replica_sessions_max": max(len(r._sessions) for r in vc.replicas),
        "replica_sessions_cap": vc.replicas[0]._sessions.max_entries,
        "replica_session_evictions": sum(
            r._sessions.evictions for r in vc.replicas
        ),
        "grant_ledger_max": max(len(r._grant_ledger) for r in vc.replicas),
        "own_grant_sigs_max": max(len(r._own_grant_sigs) for r in vc.replicas),
        "client_pending_total": sum(
            len(conn.pending)
            for s in sessions
            for conn in s.client.pool._connections.values()
        ),
        "client_pending_cap_per_conn": int(
            os.environ.get("MOCHI_PENDING_MAX", "4096")
        ),
    }


class _Session:
    __slots__ = ("client", "key", "busy", "seq")

    def __init__(self, client, key: str):
        self.client = client
        self.key = key
        self.busy = False
        self.seq = 0


async def _run_leg(
    sessions: List[_Session],
    rate: float,
    leg_s: float,
    vc,
    checker,
    tick_s: float = 0.005,
    rotor0: int = 0,
) -> Dict:
    """One open-loop leg: fire arrivals at ``rate`` txn/s round-robin over
    the sessions for ``leg_s`` seconds; a still-busy session skips its
    arrival (counted — at 1-in-flight per session the open loop is
    'partly open', which is what bounds harness memory at n_sessions
    in-flight transactions instead of an unbounded task pile)."""
    from mochi_tpu.client.errors import RequestRefused
    from mochi_tpu.client.txn import TransactionBuilder

    loop = asyncio.get_running_loop()
    lat: List[float] = []
    counts = {"offered": 0, "ok": 0, "refused": 0, "errors": 0, "busy_skips": 0}
    inflight: set = set()
    shed0 = sum(r.metrics.counters.get("replica.write1-shed", 0) for r in vc.replicas)
    max_shed_p = 0.0
    max_load = 0.0

    async def one(session: _Session) -> None:
        # busy was claimed at SPAWN time (two same-tick arrivals must not
        # race one session: concurrent writes to one key would interleave
        # acks and manufacture false "acked write lost" verdicts)
        session.seq += 1
        value = f"{session.key}:{session.seq}".encode()
        t0 = loop.time()
        try:
            await session.client.execute_write_transaction(
                TransactionBuilder().write(session.key, value).build()
            )
        except RequestRefused:
            counts["refused"] += 1
            # the hard-overload surface can follow partial dispatches
            # (e.g. certificate retries): outcome indeterminate
            checker.record_attempt(session.key, value)
        except Exception:
            counts["errors"] += 1
            checker.record_attempt(session.key, value)
        else:
            done = loop.time()
            if done <= end:
                counts["ok"] += 1
                lat.append(done - t0)
            checker.record_ack(session.key, value)
        finally:
            session.busy = False

    start = loop.time()
    end = start + leg_s
    fired = 0
    # rotor continues from the previous leg (rotor0) so arrivals spread
    # over the WHOLE session population across the run, not the first
    # rate*leg_s sessions of each leg
    rotor = rotor0
    n = len(sessions)
    while True:
        now = loop.time()
        if now >= end:
            break
        due = int((now - start) * rate) - fired
        for _ in range(max(0, due)):
            fired += 1
            counts["offered"] += 1
            # round-robin; skip busy sessions (bounded probing)
            for _probe in range(8):
                s = sessions[rotor % n]
                rotor += 1
                if not s.busy:
                    s.busy = True  # claimed before the task first runs
                    task = asyncio.ensure_future(one(s))
                    inflight.add(task)
                    task.add_done_callback(inflight.discard)
                    break
            else:
                counts["busy_skips"] += 1
        for r in vc.replicas:
            adm = r._admission
            if adm.shed_p > max_shed_p:
                max_shed_p = adm.shed_p
            if adm.load > max_load:
                max_load = adm.load
        await asyncio.sleep(tick_s)
    # drain stragglers off the clock (their completions no longer count
    # toward goodput; their latencies are not recorded)
    if inflight:
        await asyncio.wait(set(inflight), timeout=10.0)
    shed = (
        sum(r.metrics.counters.get("replica.write1-shed", 0) for r in vc.replicas)
        - shed0
    )
    duration = loop.time() - start
    return {
        "offered_per_s": round(rate, 1),
        "goodput_per_s": round(counts["ok"] / leg_s, 1),
        "latency_ms": _pcts(lat),
        "completed_ok": counts["ok"],
        "offered": counts["offered"],
        "refused_hard_overload": counts["refused"],
        "errors": counts["errors"],
        "busy_skips": counts["busy_skips"],
        "write1_shed_responses": shed,
        "max_shed_p": round(max_shed_p, 3),
        "max_load_factor": round(max_load, 3),
        "duration_s": round(duration, 2),
        "rss_mb": _rss_mb(),
        "rotor_end": rotor,
    }


async def _capacity_probe(sessions: List[_Session], probe_s: float, workers: int) -> float:
    """Closed-loop saturation burst: ``workers`` sessions write
    back-to-back for ``probe_s``; completions/second seeds the ladder."""
    from mochi_tpu.client.txn import TransactionBuilder

    loop = asyncio.get_running_loop()
    end = loop.time() + probe_s
    done = 0

    async def worker(session: _Session) -> None:
        nonlocal done
        while loop.time() < end:
            session.seq += 1
            try:
                await session.client.execute_write_transaction(
                    TransactionBuilder()
                    .write(session.key, f"probe:{session.seq}".encode())
                    .build()
                )
                done += 1
            except Exception:
                pass

    await asyncio.gather(*(worker(s) for s in sessions[:workers]))
    return done / probe_s


async def _amain(
    n_sessions: int,
    leg_s: float,
    probe_s: float,
    probe_workers: int,
    ladder: tuple,
    overload_factors: tuple,
    knee_keepup: float,
    rtt_ms: float,
    jitter_ms: float,
    timeout_s: float,
    handshake_rate: float,
    ramp_batch: int,
    shed_batch_hw: float,
    shed_inflight_hw: float,
    shed_verify_hw: float,
) -> Dict:
    from mochi_tpu.netsim import NetSim
    from mochi_tpu.server.admission import TokenBucket
    from mochi_tpu.testing.invariants import InvariantChecker
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    sim = NetSim.mesh(seed=SEED, rtt_ms=rtt_ms, jitter_ms=jitter_ms)
    async with VirtualCluster(5, rf=4, netsim=sim) as vc:
        # Front-end-scale handshake budget: the DEFAULT bucket (512/s) is
        # sized against storms, and n_sessions * 4 legitimate handshakes
        # arrive in the ramp — a real deployment sizes the knob to its
        # fleet (docs/OPERATIONS.md §4g); the ramp below still spreads it.
        # The shed high-water marks are likewise deployment knobs: the
        # library defaults are sized never to trip in a 5-client test
        # harness, and THIS cluster's capacity (one shared loop, 5
        # replicas) is known — the tuned values ride the record.
        for r in vc.replicas:
            r._handshakes = TokenBucket(rate_per_s=handshake_rate, burst=handshake_rate)
            r._admission.batch_hw = shed_batch_hw
            r._admission.inflight_hw = shed_inflight_hw
            r._admission.verify_hw = shed_verify_hw
        checker = InvariantChecker(vc.replicas)
        sessions: List[_Session] = []
        for i in range(n_sessions):
            client = vc.client(timeout_s=timeout_s)
            sessions.append(_Session(client, f"ovl-{i}"))
        # connection + MAC-session ramp (batched: ramp_batch sessions per
        # round): each session primes with one read so the timed legs
        # measure traffic, not cold dials
        from mochi_tpu.client.txn import TransactionBuilder

        for i in range(0, n_sessions, ramp_batch):
            batch = sessions[i : i + ramp_batch]
            await asyncio.gather(
                *(
                    s.client.execute_read_transaction(
                        TransactionBuilder().read(s.key).build()
                    )
                    for s in batch
                ),
                return_exceptions=True,
            )
        gc.collect()
        rss_baseline = _rss_mb()
        sessions_established = sum(
            r.metrics.counters.get("replica.sessions-established", 0)
            for r in vc.replicas
        )

        capacity = await _capacity_probe(sessions, probe_s, probe_workers)
        checker.start(interval_s=0.5)

        curve: List[Dict] = []
        rotor = 0
        for frac in ladder:
            rate = max(10.0, capacity * frac)
            leg = await _run_leg(sessions, rate, leg_s, vc, checker, rotor0=rotor)
            rotor = leg["rotor_end"]
            leg["ladder_fraction"] = frac
            leg["tables"] = _table_sizes(vc, sessions)
            curve.append(leg)
            await asyncio.sleep(0.5)  # settle between rungs

        # knee: the last rung whose goodput keeps up with offered load
        knee = None
        for leg in curve:
            if leg["goodput_per_s"] >= knee_keepup * leg["offered_per_s"]:
                knee = leg
        if knee is None:
            knee = max(curve, key=lambda leg: leg["goodput_per_s"])

        overload: Dict[str, Dict] = {}
        for factor in overload_factors:
            rate = knee["offered_per_s"] * factor
            leg = await _run_leg(sessions, rate, leg_s, vc, checker, rotor0=rotor)
            rotor = leg["rotor_end"]
            leg["tables"] = _table_sizes(vc, sessions)
            leg["vs_knee_goodput"] = (
                round(leg["goodput_per_s"] / knee["goodput_per_s"], 4)
                if knee["goodput_per_s"]
                else None
            )
            overload[f"{factor}x"] = leg
            await asyncio.sleep(0.5)

        await checker.stop()
        # acked-durability re-read through a FRESH client (the system's
        # contract, including its recovery machinery)
        final_client = vc.client(timeout_s=max(timeout_s, 10.0))
        await checker.final_check(final_client)

        from mochi_tpu.utils.wakeup import wheel_for_loop

        wheel = wheel_for_loop()
        # headline: the DEEPEST overload leg's sustained fraction
        deepest = (
            overload[max(overload, key=lambda k: float(k[:-1]))]
            if overload
            else None
        )
        return {
            "metric": "overload_goodput_fraction_past_knee",
            "value": (deepest or {}).get("vs_knee_goodput"),
            "unit": (
                "fraction of knee goodput sustained at "
                f"{max(overload_factors)}x offered load"
            ),
            "topology": {
                "replicas": 5,
                "rf": 4,
                "f": 1,
                "n_sessions": n_sessions,
                "mesh_rtt_ms": rtt_ms,
                "mesh_jitter_ms": jitter_ms,
                "netsim_seed": SEED,
                "client_timeout_s": timeout_s,
                "leg_s": leg_s,
                "handshake_rate": handshake_rate,
            },
            "capacity_probe_per_s": round(capacity, 1),
            "sessions_established": sessions_established,
            "rss_baseline_mb": rss_baseline,
            "curve": curve,
            "knee": {
                "offered_per_s": knee["offered_per_s"],
                "goodput_per_s": knee["goodput_per_s"],
                "latency_ms": knee["latency_ms"],
                "n_sessions": n_sessions,
            },
            "overload": overload,
            "invariants": checker.report(),
            "wakeup_wheel": wheel.stats(),
            "admission": {
                "enabled": True,
                "high_water": {
                    "batch": vc.replicas[0]._admission.batch_hw,
                    "inflight": vc.replicas[0]._admission.inflight_hw,
                    "verify": vc.replicas[0]._admission.verify_hw,
                    "sendq": vc.replicas[0]._admission.sendq_hw,
                },
            },
            "netsim_totals": sim.totals(),
        }


def run(
    n_sessions: int = 1200,
    leg_s: float = 10.0,
    probe_s: float = 4.0,
    probe_workers: int = 64,
    ladder: tuple = (0.4, 0.6, 0.8, 1.0, 1.2),
    overload_factors: tuple = (1.5, 2.0),
    knee_keepup: float = 0.75,
    rtt_ms: float = RTT_MS,
    jitter_ms: float = JITTER_MS,
    timeout_s: float = 5.0,
    handshake_rate: float = 4096.0,
    ramp_batch: int = 64,
    shed_batch_hw: float = 16.0,
    shed_inflight_hw: float = 96.0,
    shed_verify_hw: float = 192.0,
) -> Dict:
    from mochi_tpu.net import transport
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()
    # FD headroom: n_sessions * rf client sockets + the server-side
    # accepts.  Raise the soft limit to the hard cap before dialing.
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft < hard:
            resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
    except (ImportError, ValueError, OSError):
        pass
    prev_floor = transport.RTT_FLOOR_S
    transport.RTT_FLOOR_S = max(prev_floor, rtt_ms / 1e3)
    try:
        return asyncio.run(
            _amain(
                n_sessions=n_sessions,
                leg_s=leg_s,
                probe_s=probe_s,
                probe_workers=probe_workers,
                ladder=ladder,
                overload_factors=overload_factors,
                knee_keepup=knee_keepup,
                rtt_ms=rtt_ms,
                jitter_ms=jitter_ms,
                timeout_s=timeout_s,
                handshake_rate=handshake_rate,
                ramp_batch=ramp_batch,
                shed_batch_hw=shed_batch_hw,
                shed_inflight_hw=shed_inflight_hw,
                shed_verify_hw=shed_verify_hw,
            )
        )
    finally:
        transport.RTT_FLOOR_S = prev_floor


# ------------------------------------------- handshake-storm rung (r18)


async def _handshake_storm_leg(
    n_sessions: int, ramp_batch: int = 64, timeout_s: float = 5.0
) -> Dict:
    """One storm leg at this config's shape: ``n_sessions`` fresh SDK
    clients dial the 5-replica signed cluster and each primes with one
    read — exactly the connection + X25519/MAC-session ramp the open-loop
    phases above run before their clock starts.  Here the ramp IS the
    measurement: sessions established per second of ramp wall time."""
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.netsim import NetSim
    from mochi_tpu.server.admission import TokenBucket
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    sim = NetSim.mesh(seed=SEED, rtt_ms=RTT_MS, jitter_ms=JITTER_MS)
    async with VirtualCluster(5, rf=4, netsim=sim) as vc:
        # the rung measures handshake CPU, not the admission knob: open
        # the bucket so no leg spends its wall time in refusal/retry
        for r in vc.replicas:
            r._handshakes = TokenBucket(rate_per_s=1e6, burst=1e6)
        clients = [vc.client(timeout_s=timeout_s) for _ in range(n_sessions)]
        t0 = time.perf_counter()
        for i in range(0, n_sessions, ramp_batch):
            await asyncio.gather(
                *(
                    c.execute_read_transaction(
                        TransactionBuilder().read(f"storm-{i}").build()
                    )
                    for c in clients[i : i + ramp_batch]
                ),
                return_exceptions=True,
            )
        wall = time.perf_counter() - t0
        established = sum(
            r.metrics.counters.get("replica.sessions-established", 0)
            for r in vc.replicas
        )
    return {
        "n_sessions": n_sessions,
        "sessions_established": established,
        "ramp_wall_s": round(wall, 3),
        "handshakes_per_s": round(established / wall, 1) if wall else None,
    }


def run_handshake_storm(n_sessions: int = 256, pairs: int = 3) -> Dict:
    """Round-18 rung: the handshake storm re-measured with the native-C
    X25519 ladder vs the pure-Python ladder it replaced (the 2.5 ms/side
    DH that dominated this ramp since PR 8).  Interleaved paired legs,
    per-pair handshakes/s ratio; only the X25519 entry point is swapped —
    Ed25519 keeps its native engine in BOTH legs, so the ratio isolates
    the handshake's share."""
    from mochi_tpu.crypto import hostfallback as hf
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()
    native = hf._native_engine() is not None and hasattr(
        hf._native_engine(), "x25519"
    )

    _routed = hf.x25519

    def _pure_x25519(private: bytes, peer_public: bytes) -> bytes:
        # per-call save/restore: the swap window never spans an await, so
        # concurrent Ed25519 callers keep their native engine
        saved = hf._native
        hf._native = None
        try:
            return _routed(private, peer_public)
        finally:
            hf._native = saved

    def _leg(pure: bool) -> Dict:
        if pure:
            hf.x25519 = _pure_x25519  # session layer resolves at call time
        try:
            return asyncio.run(_handshake_storm_leg(n_sessions))
        finally:
            hf.x25519 = _routed

    rows = []
    for i in range(pairs):
        if i % 2 == 0:
            nat = _leg(False)
            pure = _leg(True)
        else:
            pure = _leg(True)
            nat = _leg(False)
        rows.append(
            {
                "native_handshakes_per_s": nat["handshakes_per_s"],
                "pure_handshakes_per_s": pure["handshakes_per_s"],
                "native_ramp_wall_s": nat["ramp_wall_s"],
                "pure_ramp_wall_s": pure["ramp_wall_s"],
                "sessions_established": nat["sessions_established"],
                "speedup": round(
                    nat["handshakes_per_s"] / pure["handshakes_per_s"], 2
                )
                if nat["handshakes_per_s"] and pure["handshakes_per_s"]
                else None,
            }
        )
    import statistics

    speedups = [r["speedup"] for r in rows if r["speedup"]]
    return {
        "n_sessions": n_sessions,
        "pairs": pairs,
        "native_x25519_available": native,
        "shape": {
            "replicas": 5, "rf": 4, "mesh_rtt_ms": RTT_MS,
            "mesh_jitter_ms": JITTER_MS, "netsim_seed": SEED,
        },
        "per_pair": rows,
        "median_storm_speedup_native_over_pure": (
            round(statistics.median(speedups), 2) if speedups else None
        ),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run(), indent=2))

"""Config 12: durability — SIGKILL recovery, priced and adversarially audited.

Rounds 1-13 ran the paper's posture: every byte of cluster state in memory,
so the benchmark story ended at the first process restart.  Round 14's
``mochi_tpu/storage`` engine (self-certifying WAL + snapshots + verified
replay + delta anti-entropy) makes kill-and-recover-with-state a measurable
scenario, and this config measures all four of its claims:

* **zero acked-write loss across a real SIGKILL** — a 4-process
  ProcessCluster under live load, EVERY replica killed mid-stream (no
  drain, no snapshot; the only durability is the flush-before-ack WAL
  append), restarted from disk, and every acknowledged write read back;
  per-replica recovery stats scraped over the admin surface;
* **recovery-time-vs-store-size curve** — verified replay (every logged
  certificate's grants re-verify through the batch signature path) timed
  at >= 3 store sizes on one growing cluster;
* **tampered-log conviction** — three Byzantine-restart legs (mutated
  certificate value, forged grant signatures, reordered records): each
  must be convicted with per-entry attribution and the tampered state
  never served (InvariantChecker invariant 5);
* **fsync-policy cost table** — the same write workload under
  ``MOCHI_WAL_FSYNC=always|group|off`` and the in-memory baseline: what
  each durability level costs at the ack path.

The headline value is the largest curve point's replay time — the number
an operator needs for "how long is a replica down after a crash?".
"""

from __future__ import annotations

import asyncio
import json
import os
import shutil
import time
import urllib.request
from typing import Dict, List, Optional

from .config7_wan import _pcts


# ------------------------------------------------ SIGKILL -> recover leg


async def _kill_recover_leg(min_acked: int, timeout_s: float) -> Dict:
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.process_cluster import ProcessCluster, _free_tcp_ports

    admin_base = _free_tcp_ports(1)[0]
    async with ProcessCluster(
        4, rf=4, n_processes=4, storage_dir=True, wal_fsync="group",
        admin_base_port=admin_base,
    ) as pc:
        client = pc.client(timeout_s=timeout_s)
        acked: Dict[str, bytes] = {}

        async def load():
            i = 0
            while True:
                key, value = f"dk{i}", b"v%d" % i
                try:
                    await client.execute_write_transaction(
                        TransactionBuilder().write(key, value).build()
                    )
                except Exception:
                    return  # in flight at the kill: indeterminate
                acked[key] = value
                i += 1

        writer = asyncio.ensure_future(load())
        while len(acked) < min_acked:
            await asyncio.sleep(0.02)
        kill_at = len(acked)
        for i in range(4):
            pc.kill_replica(f"server-{i}")
        await writer
        await client.close()

        t0 = time.perf_counter()
        for i in range(4):
            await pc.restart_replica(f"server-{i}")
        restart_wall_ms = (time.perf_counter() - t0) * 1e3

        reader = pc.client(timeout_s=timeout_s)
        lost: List[str] = []
        t0 = time.perf_counter()
        for key, value in sorted(acked.items()):
            res = await reader.execute_read_transaction(
                TransactionBuilder().read(key).build()
            )
            if res.operations[0].value != value:
                lost.append(key)
        readback_ms = (time.perf_counter() - t0) * 1e3
        pc.check_alive()

        # per-replica recovery evidence over the admin surface (the same
        # /status "storage" key an operator's dashboard reads)
        loop = asyncio.get_running_loop()
        replay = {}
        for pi in range(4):
            port = admin_base + pi * 4
            try:
                raw = await loop.run_in_executor(
                    None,
                    lambda p=port: urllib.request.urlopen(
                        f"http://127.0.0.1:{p}/status", timeout=5
                    ).read(),
                )
                st = json.loads(raw)["storage"]
                replay[f"server-{pi}"] = {
                    "entries": st["replay"]["entries"],
                    "convicted": st["replay"]["convicted"],
                    "ms": st["replay"]["ms"],
                    "torn_tail": st["replay"]["torn_tail"],
                }
            except Exception as exc:  # admin scrape is evidence, not a gate
                replay[f"server-{pi}"] = {"error": f"{type(exc).__name__}"}
    return {
        "acked_before_kill": kill_at,
        "acked_total": len(acked),
        "lost": len(lost),
        "lost_keys": lost[:8],
        "restart_wall_ms": round(restart_wall_ms, 1),
        "readback_ms": round(readback_ms, 1),
        "replay_per_replica": replay,
    }


# ------------------------------------------------- recovery curve + delta


async def _recovery_curve(
    sizes, gap_writes: int, timeout_s: float
) -> Dict:
    """One growing durable VirtualCluster: restart + time verified replay
    at each cumulative size; at the largest size also run the delta-resync
    leg (writes committed while the victim is down must ship as deltas)."""
    import tempfile

    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    points: List[Dict] = []
    delta_evidence: Optional[Dict] = None
    with tempfile.TemporaryDirectory() as td:
        async with VirtualCluster(4, rf=4, storage_dir=td) as vc:
            client = vc.client(timeout_s=timeout_s)
            written = 0
            for target in sorted(sizes):
                while written < target:
                    await client.execute_write_transaction(
                        TransactionBuilder()
                        .write(f"cv{written}", b"v%d" % written)
                        .build()
                    )
                    written += 1
                last = target == max(sizes)
                # Freeze the victim's LIVE disk state (WAL, no snapshot):
                # the graceful restart below would otherwise snapshot +
                # truncate on close, and the point of the curve is the
                # crash shape — full verified WAL replay.
                victim = vc.replica("server-1")
                await victim.storage.flush()
                frozen = os.path.join(td, "server-1") + ".crash"
                shutil.copytree(os.path.join(td, "server-1"), frozen)

                async def crash_then_gap(sid, frozen=frozen, last=last):
                    dst = os.path.join(td, sid)
                    shutil.rmtree(dst)
                    shutil.move(frozen, dst)
                    for g in range(gap_writes if last else 0):
                        await client.execute_write_transaction(
                            TransactionBuilder()
                            .write(f"gap{g}", b"late")
                            .build()
                        )

                fresh = await vc.restart_replica(
                    "server-1", resync=True, before_boot=crash_then_gap
                )
                report = fresh.storage.replay_report()
                points.append(
                    {
                        "keys": target,
                        "replay_entries": report["entries"],
                        "replay_ms": report["ms"],
                        "convicted": report["convicted"],
                    }
                )
                if last:
                    ae = fresh.storage_stats()["anti_entropy"]
                    delta_evidence = {
                        "gap_writes": gap_writes,
                        "shards_matched": ae["shards_matched"],
                        "keys_matched": ae["keys_matched"],
                        "delta_keys_pulled": ae["delta_keys_pulled"],
                        "full_keys_pulled": ae["full_keys_pulled"],
                        "digest_pages": ae["digest_pages"],
                    }
    return {"points": points, "delta_resync": delta_evidence}


# ------------------------------------------------------ tamper conviction


def _rewrite_last_segment(directory: str, server_id: str, mutate) -> None:
    from mochi_tpu.storage import wal

    _index, path = wal.list_segments(directory)[-1]
    with open(path, "rb") as fh:
        data = fh.read()
    start = wal.read_segment_header(data, server_id)
    scan = wal.scan_segment(data, server_id)
    records = [[r.seq, r.rtype, r.body] for r in scan.records]
    mutate(records)
    with open(path, "wb") as fh:
        fh.write(
            data[:start]
            + b"".join(wal.encode_record(s, t, b) for s, t, b in records)
        )


async def _tamper_leg(timeout_s: float) -> Dict:
    """Three Byzantine restarts on one cluster: each victim's frozen
    mid-life disk state is adversarially rewritten (correct CRCs — the
    framing is not the defense) and the verified replay must convict."""
    import tempfile

    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.invariants import InvariantChecker
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    def last_data_commit(records):
        from mochi_tpu.storage import wal

        for rec in reversed(records):
            if rec[1] == wal.RT_COMMIT and rec[2][0][0].startswith("tp"):
                return rec
        raise AssertionError("no data commit in segment")

    def mutate_value(records):
        last_data_commit(records)[2][1][0][2] = b"EVIL"

    def forge_sigs(records):
        for mg_obj in last_data_commit(records)[2][2].values():
            mg_obj[3] = b"\x00" * 64

    def reorder(records):
        records[-1], records[-2] = records[-2], records[-1]

    legs = (
        ("mutated_value", "server-1", mutate_value),
        ("forged_grant_sigs", "server-2", forge_sigs),
        ("reordered_records", "server-3", reorder),
    )
    out: Dict[str, Dict] = {}
    with tempfile.TemporaryDirectory() as td:
        async with VirtualCluster(4, rf=4, storage_dir=td) as vc:
            client = vc.client(timeout_s=timeout_s)
            for i in range(10):
                await client.execute_write_transaction(
                    TransactionBuilder().write(f"tp{i}", b"v%d" % i).build()
                )
            for name, sid, mutate in legs:
                victim = vc.replica(sid)
                await victim.storage.flush()
                frozen = os.path.join(td, sid) + ".crash"
                shutil.copytree(os.path.join(td, sid), frozen)
                _rewrite_last_segment(frozen, sid, mutate)

                def restore(s, frozen=frozen):
                    dst = os.path.join(td, s)
                    shutil.rmtree(dst)
                    shutil.move(frozen, dst)

                fresh = await vc.restart_replica(sid, before_boot=restore)
                report = fresh.storage.replay_report()
                served_evil = any(
                    sv is not None and sv.value == b"EVIL"
                    for sv in (fresh.store._get(f"tp{i}") for i in range(10))
                )
                checker = InvariantChecker([fresh])
                checker.check_now()
                crep = checker.report()
                out[name] = {
                    "convicted": report["convicted"],
                    "convictions": report["convictions"][:4],
                    "tampered_state_served": served_evil,
                    "invariants_ok": crep["ok"],
                    "checker_convictions": crep["storage_replay_convictions"],
                }
    return out


# ------------------------------------------------- fsync-policy cost table


async def _fsync_leg(
    policy: Optional[str], n_writes: int, timeout_s: float
) -> Dict:
    """One write workload under one fsync policy (None = the in-memory
    engine: the durability-overhead baseline)."""
    import tempfile

    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    prev = os.environ.get("MOCHI_WAL_FSYNC")
    if policy is not None:
        os.environ["MOCHI_WAL_FSYNC"] = policy
    try:
        with tempfile.TemporaryDirectory() as td:
            async with VirtualCluster(
                4, rf=4, storage_dir=(td if policy is not None else None)
            ) as vc:
                client = vc.client(timeout_s=timeout_s)
                lat: List[float] = []
                for i in range(n_writes):
                    t0 = time.perf_counter()
                    await client.execute_write_transaction(
                        TransactionBuilder().write(f"fp{i}", b"x" * 64).build()
                    )
                    lat.append(time.perf_counter() - t0)  # _pcts wants s
                fsyncs = wal_entries = 0
                for r in vc.replicas:
                    st = r.storage.stats()
                    fsyncs += st.get("fsyncs", 0)
                    wal_entries += st.get("wal_entries", 0)
        return {
            "write_ms": _pcts(lat),
            "writes": n_writes,
            "fsyncs_total": fsyncs,
            "wal_entries_total": wal_entries,
        }
    finally:
        if policy is not None:
            if prev is None:
                os.environ.pop("MOCHI_WAL_FSYNC", None)
            else:
                os.environ["MOCHI_WAL_FSYNC"] = prev


# ------------------------------------------------------------------- run


def run(
    min_acked: int = 40,
    curve_sizes=(64, 256, 1024),
    gap_writes: int = 6,
    fsync_policies=("always", "group", "off"),
    fsync_writes: int = 60,
    timeout_s: float = 8.0,
) -> Dict:
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()

    kill = asyncio.run(_kill_recover_leg(min_acked, timeout_s))
    curve = asyncio.run(_recovery_curve(curve_sizes, gap_writes, timeout_s))
    tamper = asyncio.run(_tamper_leg(timeout_s))
    fsync_cost: Dict[str, Dict] = {
        "memory-baseline": asyncio.run(
            _fsync_leg(None, fsync_writes, timeout_s)
        )
    }
    for policy in fsync_policies:
        fsync_cost[policy] = asyncio.run(
            _fsync_leg(policy, fsync_writes, timeout_s)
        )
    base_p50 = fsync_cost["memory-baseline"]["write_ms"]["p50"]
    for policy in fsync_policies:
        p50 = fsync_cost[policy]["write_ms"]["p50"]
        fsync_cost[policy]["write_p50_vs_memory"] = (
            round(p50 / base_p50, 3) if base_p50 and p50 == p50 else None
        )

    delta = curve["delta_resync"] or {}
    acceptance = {
        "zero_acked_write_loss": kill["lost"] == 0,
        "tamper_convicted_all_legs": all(
            leg["convicted"] >= 1 and not leg["tampered_state_served"]
            and leg["invariants_ok"]
            for leg in tamper.values()
        ),
        "resync_after_recovery_ships_deltas": bool(
            delta.get("delta_keys_pulled", 0) > 0
            and delta.get("full_keys_pulled", 1) == 0
        ),
        "replay_convictions_zero_on_honest_logs": all(
            p["convicted"] == 0 for p in curve["points"]
        ),
    }
    top = curve["points"][-1] if curve["points"] else {}
    return {
        "metric": "durable_recovery_replay_ms",
        "value": top.get("replay_ms"),
        "unit": (
            f"ms of verified replay to recover {top.get('keys')} keys "
            "(snapshot-less worst case; grants re-verified via the batch "
            "signature path)"
        ),
        "acceptance": acceptance,
        "topology": {
            "replicas": 4,
            "rf": 4,
            "f": 1,
            "kill_leg": "ProcessCluster, 4 processes, SIGKILL all mid-load",
            "wal_fsync_kill_leg": "group",
            "client_timeout_s": timeout_s,
        },
        "kill_recover": kill,
        "recovery_curve": curve["points"],
        "delta_resync": curve["delta_resync"],
        "tamper": tamper,
        "fsync_policy_cost": fsync_cost,
        "notes": (
            "kill leg: acked = client saw the Write2 quorum answer; the "
            "flush-before-ack WAL append (group policy: OS page cache) is "
            "the only durability a SIGKILL leaves, so lost=0 is the "
            "round-14 contract.  Curve points restore a frozen mid-life disk "
            "image (WAL only, no snapshot) before each restart — the "
            "snapshot-less worst case; an operator with default "
            "snapshotting replays only the post-snapshot tail.  Tamper "
            "legs rewrite the log with CORRECT CRCs: conviction comes "
            "from certificate re-verification, not framing."
        ),
    }


if __name__ == "__main__":
    print(json.dumps(run(), indent=2))

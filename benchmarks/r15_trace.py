"""Round-15 evidence driver: config 7 re-run WITH causal tracing, the
trace-derived commit breakdown cross-checked against (a) the stage timers
measured in the SAME run and (b) the committed r09 hand-timer
decomposition, the live verifies/txn meter at the BASELINE n=64 shape, and
an interleaved paired A/B bounding tracing overhead at the default sample
rate.  Writes ``benchmarks/results_r15.json``.

    JAX_PLATFORMS=cpu python -m benchmarks.r15_trace

Acceptance bars (ISSUE 14): trace breakdown vs r09 within 15% per stage
(stages above a 0.5 ms floor — the sub-0.1 ms tally/encode stages are
cross-checked against the same-run timers instead, where both sides see
the same host), live unique verifies/txn within 15% of the BASELINE
43-unique figure, tracing overhead ≤3% on config-7 write p50.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys

from benchmarks.config7_wan import (
    R08_PRIOR,
    RTT_MS,
    SEED,
    _wan_run,
    run_trace_ab,
    run_verify_meter,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(_REPO, "benchmarks", "results_r15.json")

# The committed r09 stage-timer decomposition this round's trace-derived
# breakdown is cross-checked against (benchmarks/results_r09.json config 7,
# measured with hand timers around the same client stages).
R09_BREAKDOWN_MS = {
    "write1-phase": 22.26,
    "write2-fanout-wait": 24.83,
    "write2-tally": 0.03,
}
# Stages below this are pure-CPU microseconds-scale: their r09 values are
# host-speed artifacts, so the 15% cross-check holds them against the
# SAME-RUN stage timers (identical host, identical run) instead.
CROSSCHECK_FLOOR_MS = 0.5


def _traced_wan_leg() -> dict:
    prev = {
        k: os.environ.get(k)
        for k in ("MOCHI_TRACE", "MOCHI_TRACE_SAMPLE", "MOCHI_TRACE_SEED",
                  "MOCHI_TRACE_RING")
    }
    # sample 1.0 for the EVIDENCE leg: every transaction carries a card, so
    # the breakdown medians come from the full population (the overhead
    # bound is the separate A/B below, at the default rate).
    os.environ["MOCHI_TRACE_SAMPLE"] = "1.0"
    os.environ["MOCHI_TRACE_SEED"] = str(SEED)
    os.environ["MOCHI_TRACE_RING"] = "16384"
    try:
        return asyncio.run(_wan_run(5, 40, 2))
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _crosscheck(trace_ms: dict, timer_ms: dict) -> dict:
    """Per-stage agreement: trace-derived vs r09 (stages above the floor)
    and vs the same-run stage timers (all stages)."""
    out = {"per_stage": {}, "vs_r09_ok": True, "vs_same_run_ok": True}
    for stage, r09 in R09_BREAKDOWN_MS.items():
        tr = trace_ms.get(stage)
        tm = timer_ms.get(stage)
        row = {"trace_ms": tr, "same_run_timer_ms": tm, "r09_ms": r09}
        if tr is None or tm is None:
            row["error"] = "stage missing"
            out["vs_r09_ok"] = out["vs_same_run_ok"] = False
            out["per_stage"][stage] = row
            continue
        if r09 >= CROSSCHECK_FLOOR_MS:
            row["vs_r09_ratio"] = round(tr / r09, 4)
            row["vs_r09_within_15pct"] = abs(tr / r09 - 1.0) <= 0.15
            out["vs_r09_ok"] = out["vs_r09_ok"] and row["vs_r09_within_15pct"]
        else:
            row["vs_r09_note"] = (
                "below 0.5 ms floor: r09 value is host-speed-bound; "
                "cross-checked against the same-run timer instead"
            )
        # same-run: both measurements of the same executions — 15% relative
        # or 0.05 ms absolute (timer vs span clock granularity at the floor)
        close = abs(tr - tm) <= max(0.15 * max(tr, tm), 0.05)
        row["vs_same_run_within_15pct"] = close
        out["vs_same_run_ok"] = out["vs_same_run_ok"] and close
        out["per_stage"][stage] = row
    return out


def run(ab_pairs: int = 7, meter_writes: int = 4) -> dict:
    from mochi_tpu.crypto.keys import host_crypto_engine
    from mochi_tpu.net import transport
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()
    prev_floor = transport.RTT_FLOOR_S
    transport.RTT_FLOOR_S = max(prev_floor, RTT_MS / 1e3)
    try:
        wan = _traced_wan_leg()
        trace_ab = run_trace_ab(pairs=ab_pairs)
    finally:
        transport.RTT_FLOOR_S = prev_floor
    meter = run_verify_meter(writes=meter_writes)
    crosscheck = _crosscheck(
        wan.get("trace", {}).get("commit_breakdown_ms", {}),
        wan.get("commit_breakdown_ms", {}),
    )
    rec = {
        "config": "7",
        "metric": "wan_traced_causal_accounting",
        "value": wan["write_ms"]["p50"],
        "unit": "ms (write p50 at 13 ms RTT, tracing sample=1.0)",
        "host_crypto_engine": host_crypto_engine(),
        "round": 15,
        "write_ms": wan["write_ms"],
        "read_ms": wan["read_ms"],
        "write_samples": wan["write_samples"],
        "netsim_totals": wan["netsim_totals"],
        "commit_breakdown_timer_ms": wan["commit_breakdown_ms"],
        "trace": wan.get("trace"),
        "breakdown_crosscheck": crosscheck,
        "trace_overhead_ab": trace_ab,
        "verify_meter": meter,
        "prior_r09": {
            "write_p50_ms": 46.07,
            "breakdown_ms": R09_BREAKDOWN_MS,
            "source": "benchmarks/results_r09.json config 7",
        },
        "prior_r08": R08_PRIOR,
        "acceptance": {
            "breakdown_vs_r09_within_15pct": crosscheck["vs_r09_ok"],
            "breakdown_vs_same_run_within_15pct": crosscheck["vs_same_run_ok"],
            "verifies_per_txn_matches_baseline_43": meter["matches_baseline_43"],
            "trace_overhead_le_3pct": trace_ab["acceptance_le_3pct"],
        },
    }
    return rec


def main(argv) -> int:
    ab_pairs = int(argv[0]) if argv else 7
    rec = run(ab_pairs=ab_pairs)
    with open(OUT, "w") as fh:
        json.dump([rec], fh, indent=2)
        fh.write("\n")
    print(json.dumps(rec["acceptance"], indent=2))
    print(f"wrote {OUT}", file=sys.stderr)
    return 0 if all(rec["acceptance"].values()) else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""Config 14: paged value engine — outgrow RAM, priced.

Rounds 14-16 made durability real but kept the RAM statement: the WAL
engine's recovery curve tops out around 10^3 keys because every committed
value lives in the store dict AND replays through the full verified path at
boot.  Round 17's ``mochi_tpu/storage/paged.py`` moves values into
immutable self-certifying pages behind a bounded CLOCK cache, and this
config prices the four claims that make "millions of keys" a storage
statement:

* **keyspace ≫ resident cap** — load rungs at 10^4 / 10^5 / 10^6 keys with
  the cache cap pinned to 1/8 of the total value bytes; measured RSS per
  rung shows the machine holds the page INDEX, not the values;
* **steady-state read/write curves** — per-rung write latency under
  page-flush pressure, then uniform random reads where most touches fault
  a page entry in through the verified sink (hit/miss/eviction counters
  reported straight from the engine);
* **recovery time** — restart = manifest + page-footer index rebuild + WAL
  tail, values NOT loaded (the DSig move: signatures re-verify in batch at
  audit/compaction, off the boot path), vs the WAL engine's full verified
  replay on the same host at the A/B rungs;
* **the same crash/Byzantine contracts as the WAL engine** — a real
  SIGKILL-all ProcessCluster leg on ``storage_engine="paged"`` with zero
  acked-write loss, and a page-tamper leg (every CRC and the footer hash
  recomputed by the adversary) convicting per entry with the honest value
  still served from the replica quorum.

The headline value is the top rung's recovery time — what a 10^6-key
replica costs to bring back, now that boot cost is index rebuild rather
than value replay.
"""

from __future__ import annotations

import asyncio
import os
import random
import shutil
import tempfile
import time
from typing import Dict, List, Optional

from .config7_wan import _pcts


def _rss_mb() -> Optional[float]:
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except OSError:
        pass
    return None


# ------------------------------------------------- direct-engine curve leg


def _make_cert(key: str, ts: int, cfg, keypairs, txn):
    """A quorum-complete certificate signed by 2f+1 real replica keys —
    the bench plays both client and granting servers so the curve prices
    the STORAGE engine, not the wire protocol."""
    from mochi_tpu.protocol import (
        Grant,
        MultiGrant,
        WriteCertificate,
        transaction_hash,
    )

    txh = transaction_hash(txn)
    grants = {}
    for sid, kp in list(keypairs.items())[: cfg.quorum]:
        g = Grant(key, ts, cfg.configstamp, txh)
        mg = MultiGrant({key: g}, "bench-client", sid)
        grants[sid] = mg.with_signature(kp.sign(mg.signing_bytes()))
    return WriteCertificate(grants)


async def _curve_leg(
    engine: str, n_keys: int, value_bytes: int, reads: int, seed: int
) -> Dict:
    """Load ``n_keys`` through the real Write2 apply path into one engine,
    price steady-state writes/reads, then a cold recovery."""
    from mochi_tpu.cluster.config import ClusterConfig
    from mochi_tpu.crypto.keys import generate_keypair
    from mochi_tpu.protocol import Action, Operation, Transaction, Write2ToServer
    from mochi_tpu.server.store import DataStore
    from mochi_tpu.storage.durable import DurableStorage
    from mochi_tpu.storage.paged import PagedStorage

    sid = "server-0"
    keypairs = {f"server-{i}": generate_keypair() for i in range(4)}
    cfg = ClusterConfig.build(
        {s: f"127.0.0.1:{1 + i}" for i, s in enumerate(sorted(keypairs))},
        rf=4,
        public_keys={s: kp.public_key for s, kp in keypairs.items()},
    )
    total_value_bytes = n_keys * value_bytes
    cache_cap = max(1 << 16, total_value_bytes // 8)
    memtable_cap = min(8 << 20, max(1 << 16, cache_cap // 2))

    td = tempfile.mkdtemp(prefix=f"mochi-c14-{engine}-")
    # rung recoveries price the index rebuild; the signature sweep is the
    # audit/compaction path and is priced by its own counters elsewhere
    saved_audit = os.environ.get("MOCHI_PAGE_AUDIT")
    os.environ["MOCHI_PAGE_AUDIT"] = "off"
    try:
        def build(directory):
            if engine == "paged":
                return PagedStorage(
                    directory, sid, fsync="off",
                    cache_bytes=cache_cap, memtable_bytes=memtable_cap,
                )
            return DurableStorage(directory, sid, fsync="off")

        store = DataStore(sid, cfg)
        storage = build(td)
        await storage.recover(store)
        await storage.start()
        store.storage = storage
        storage.store = store

        rss0 = _rss_mb()
        value = bytes(value_bytes)
        timed_n = min(n_keys, 2000)
        write_ms: List[float] = []
        flushes = 0
        last_ckpt = 0
        t_load0 = time.perf_counter()
        for i in range(n_keys):
            key = f"bk{i:08d}"
            txn = Transaction((Operation(Action.WRITE, key, value),))
            req = Write2ToServer(_make_cert(key, 1, cfg, keypairs, txn), txn)
            t0 = time.perf_counter()
            res = store.process_write2(req)
            if i >= n_keys - timed_n:
                write_ms.append(time.perf_counter() - t0)
            if not hasattr(res, "result"):
                raise AssertionError(f"write refused at {key}: {res}")
            # each engine checkpoints on ITS OWN default trigger — the
            # paged memtable cap vs the WAL snapshot threshold.  Forcing
            # the WAL engine onto the memtable cadence would charge it a
            # whole-store rewrite every few MB of staged bytes (quadratic
            # in store size); its honest posture is the rarer, bigger
            # snapshot — and the recovery contrast prices what that buys.
            trigger = (
                memtable_cap if engine == "paged"
                else storage.snapshot_trigger_bytes
            )
            if storage.wal_bytes - last_ckpt >= trigger:
                await storage.snapshot(store)
                last_ckpt = storage.wal_bytes
                flushes += 1
        load_s = time.perf_counter() - t_load0
        await storage.snapshot(store)
        flushes += 1
        rss_loaded = _rss_mb()

        resident_keys = sum(
            1 for i in range(0, n_keys, max(1, n_keys // 512))
            if store.data.get(f"bk{i:08d}") is not None
        )
        rng = random.Random(seed)
        read_ms: List[float] = []
        bad = 0
        for _ in range(reads):
            key = f"bk{rng.randrange(n_keys):08d}"
            t0 = time.perf_counter()
            sv = store._get(key)
            read_ms.append(time.perf_counter() - t0)
            if sv is None or sv.value != value:
                bad += 1
        st = storage.stats()
        await storage.close()

        # cold recovery on a fresh store: the boot-cost contrast
        store2 = DataStore(sid, cfg)
        storage2 = build(td)
        t0 = time.perf_counter()
        report = await storage2.recover(store2)
        recovery_ms = (time.perf_counter() - t0) * 1e3
        store2.storage = storage2
        storage2.store = store2
        rss_recovered = _rss_mb()
        recovered_ok = 0
        for _ in range(min(reads, 64)):
            key = f"bk{rng.randrange(n_keys):08d}"
            sv = store2._get(key)
            if sv is not None and sv.value == value:
                recovered_ok += 1
        await storage2.close()

        rec = {
            "engine": engine,
            "keys": n_keys,
            "value_bytes": value_bytes,
            "total_value_mb": round(total_value_bytes / 2**20, 1),
            "cache_cap_mb": round(cache_cap / 2**20, 2),
            "cap_over_value_bytes": round(cache_cap / total_value_bytes, 4),
            "load_s": round(load_s, 2),
            "load_writes_per_s": round(n_keys / load_s, 1) if load_s else None,
            "checkpoint_flushes": flushes,
            "write_ms": _pcts(write_ms),
            "read_ms": _pcts(read_ms),
            "bad_reads": bad,
            "resident_sample_frac": round(
                resident_keys / max(1, len(range(0, n_keys, max(1, n_keys // 512)))), 3
            ),
            "rss_mb": {
                "baseline": rss0,
                "loaded": rss_loaded,
                "recovered": rss_recovered,
            },
            "recovery_ms": round(recovery_ms, 1),
            "recovery_convicted": report.get("convicted"),
            "recovery_readback_ok": recovered_ok,
        }
        if engine == "paged":
            rec["pages"] = st["pages"]
            rec["cache"] = st["cache"]
            rec["compaction"] = st["compaction"]
        return rec
    finally:
        if saved_audit is None:
            os.environ.pop("MOCHI_PAGE_AUDIT", None)
        else:
            os.environ["MOCHI_PAGE_AUDIT"] = saved_audit
        shutil.rmtree(td, ignore_errors=True)


# ------------------------------------------------ SIGKILL -> recover leg


async def _kill_recover_leg(min_acked: int, timeout_s: float) -> Dict:
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.process_cluster import ProcessCluster

    async with ProcessCluster(
        4, rf=4, n_processes=4, storage_dir=True, wal_fsync="group",
        storage_engine="paged",
    ) as pc:
        client = pc.client(timeout_s=timeout_s)
        acked: Dict[str, bytes] = {}

        async def load():
            i = 0
            while True:
                key, value = f"ck{i}", b"v%d" % i
                try:
                    await client.execute_write_transaction(
                        TransactionBuilder().write(key, value).build()
                    )
                except Exception:
                    return  # in flight at the kill: indeterminate
                acked[key] = value
                i += 1

        writer = asyncio.ensure_future(load())
        while len(acked) < min_acked:
            await asyncio.sleep(0.02)
        for i in range(4):
            pc.kill_replica(f"server-{i}")
        await writer
        await client.close()

        t0 = time.perf_counter()
        for i in range(4):
            await pc.restart_replica(f"server-{i}")
        restart_wall_ms = (time.perf_counter() - t0) * 1e3

        reader = pc.client(timeout_s=timeout_s)
        lost: List[str] = []
        for key, value in sorted(acked.items()):
            res = await reader.execute_read_transaction(
                TransactionBuilder().read(key).build()
            )
            if res.operations[0].value != value:
                lost.append(key)
        pc.check_alive()
    return {
        "engine": "paged",
        "acked": len(acked),
        "lost": len(lost),
        "lost_keys": lost[:5],
        "restart_wall_ms": round(restart_wall_ms, 1),
    }


# ------------------------------------------------------ page-tamper leg


async def _tamper_leg(td: str) -> Dict:
    """The Byzantine-restart arc one layer down: mutate a committed value
    inside an on-disk page with every CRC and the footer transaction hash
    recomputed; the per-entry recheck must convict it at fault-in and the
    honest value must still answer from the quorum."""
    import zlib

    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.protocol import Transaction, transaction_hash
    from mochi_tpu.protocol.codec import encode
    from mochi_tpu.storage.paged import (
        _write_page,
        read_page_entry,
        scan_page_footer,
    )
    from mochi_tpu.testing.invariants import InvariantChecker
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    vc = VirtualCluster(4, rf=4, storage_dir=td, storage_engine="paged")
    await vc.start()
    try:
        client = vc.client()
        for i in range(8):
            await client.execute_write_transaction(
                TransactionBuilder().write(f"tk{i}", b"honest-%d" % i).build()
            )
        victim = vc.replica("server-1")
        await victim.storage.flush()
        await victim.storage.snapshot(victim.store)
        vdir = os.path.join(td, "server-1")
        frozen = vdir + ".crash"
        shutil.copytree(vdir, frozen)

        tampered = None
        for name in sorted(os.listdir(frozen)):
            if not name.startswith("page-") or not name.endswith(".pg"):
                continue
            path = os.path.join(frozen, name)
            page_id, rows, _size = scan_page_footer(path, "server-1")
            entries = []
            for key, off, length, crc, _txh, epoch in rows:
                obj = read_page_entry(path, off, length, crc)
                if tampered is None and key.startswith("tk"):
                    for op in obj[1]:
                        if op[1] == key and op[2] is not None:
                            op[2] = b"EVIL"
                            tampered = key
                blob = encode(obj)
                txh = transaction_hash(Transaction.from_obj(obj[1]))
                entries.append((key, blob, zlib.crc32(blob), txh, int(epoch)))
            if tampered is not None:
                _write_page(path, "server-1", page_id, entries)
                break

        def restore(sid: str) -> None:
            shutil.rmtree(vdir)
            shutil.move(frozen, vdir)

        fresh = await vc.restart_replica("server-1", before_boot=restore)
        sv = fresh.store._get(tampered)  # fault-in -> per-entry recheck
        report = fresh.storage.replay_report()
        checker = InvariantChecker([fresh])
        checker.check_now()
        rep = checker.report()
        idx = int(tampered[len("tk"):])
        res = await client.execute_read_transaction(
            TransactionBuilder().read(tampered).build()
        )
        return {
            "tampered_key": tampered,
            "convicted": int(report["convicted"]),
            "attributed": any(
                c["key"] == tampered for c in report["convictions"]
            ),
            "tampered_state_served": bool(sv is not None and sv.value == b"EVIL"),
            "invariants_ok": bool(rep["ok"]),
            "quorum_read_honest": bool(
                res.operations[0].value == b"honest-%d" % idx
            ),
        }
    finally:
        await vc.close()


# ---------------------------------------------------------------- harness


def run(
    rungs=(10_000, 100_000, 1_000_000),
    ab_rungs=(10_000, 100_000),
    value_bytes: int = 1024,
    reads: int = 2000,
    min_acked: int = 40,
    timeout_s: float = 8.0,
    seed: int = 17,
) -> Dict:
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()

    curve: List[Dict] = []
    for n in rungs:
        curve.append(
            asyncio.run(_curve_leg("paged", n, value_bytes, reads, seed))
        )
    wal_ab: List[Dict] = []
    for n in ab_rungs:
        wal_ab.append(
            asyncio.run(_curve_leg("wal", n, value_bytes, reads, seed))
        )

    kill = asyncio.run(_kill_recover_leg(min_acked, timeout_s))
    with tempfile.TemporaryDirectory() as td:
        tamper = asyncio.run(_tamper_leg(td))

    top = curve[-1]
    acceptance = {
        "cap_le_eighth_of_value_bytes": all(
            p["cap_over_value_bytes"] <= 0.125 + 1e-9 for p in curve
        ),
        # "bounded" means the VALUE residency bound held at every rung
        # (the CLOCK cache never exceeded its cap and a random key sample
        # was resident at ~the cap fraction, not fully resident) AND the
        # top rung's whole-process RSS delta stayed below the total value
        # bytes — the RSS floor is the O(keys) page index, not the values.
        # (Whole-process RSS alone is a bad per-rung proxy: at 10^4 keys
        # the interpreter/WAL-buffer fixed overhead dwarfs 10 MB of values.)
        "rss_bounded_per_rung": all(
            p["cache"]["resident_bytes"] <= p["cache"]["cap_bytes"]
            and p["resident_sample_frac"] <= p["cap_over_value_bytes"] + 0.05
            for p in curve
        )
        and (
            top["rss_mb"]["loaded"] is None
            or top["rss_mb"]["loaded"] - top["rss_mb"]["baseline"]
            < top["total_value_mb"]
        ),
        "zero_bad_reads": all(
            p["bad_reads"] == 0 and p["recovery_convicted"] == 0 for p in curve
        ),
        "zero_acked_write_loss": kill["lost"] == 0,
        "paged_tamper_convicted": bool(
            tamper["convicted"] >= 1 and tamper["attributed"]
            and not tamper["tampered_state_served"]
            and tamper["invariants_ok"] and tamper["quorum_read_honest"]
        ),
    }
    return {
        "metric": "paged_recovery_ms_top_rung",
        "value": top["recovery_ms"],
        "unit": (
            f"ms to recover a {top['keys']}-key paged replica "
            "(manifest + page-footer index + WAL tail; values fault in on "
            "demand, signatures re-verify at audit/compaction)"
        ),
        "acceptance": acceptance,
        "topology": {
            "curve": "single-store direct Write2 apply (rf=4, f=1, "
                     "2f+1-signed certificates)",
            "cache_cap": "total value bytes / 8 per rung",
            "kill_leg": "ProcessCluster, 4 processes, paged engine, "
                        "SIGKILL all mid-load",
            "value_bytes": value_bytes,
        },
        "paged_curve": curve,
        "wal_ab": wal_ab,
        "kill_recover": kill,
        "tamper": tamper,
        "notes": (
            "curve legs play both client and granting server (real "
            "signatures, real Write2 quorum checks) so the number prices "
            "the storage engine, not the wire.  Each engine checkpoints "
            "on its own default trigger: the paged engine flushes its "
            "memtable every few MB while the WAL engine writes a rarer "
            "whole-store snapshot — the A/B is that asymmetry plus "
            "recovery (full verified value replay vs index rebuild).  "
            "rss_mb is "
            "VmRSS; the paged engine's floor is the page index "
            "(O(keys) PageEntry tuples), not the values."
        ),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run(), indent=2))

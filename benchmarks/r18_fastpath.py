"""Round 18: the fast-path record — verifies/txn meter A/B, config-7 WAN
fast-path A/B, the X25519 engine micro-timings, and the handshake storm
re-measured on the native ladder.

Standalone by design: ``run_all``'s battery is pinned to configs 1-14
(``tests/test_bench_smoke.py``), so this driver is invoked directly —

    python -m benchmarks.r18_fastpath            # full record
    python -m benchmarks.r18_fastpath --smoke    # seconds, numbers junk
    python -m benchmarks.r18_fastpath --out benchmarks/results_r18.json

The record answers the ISSUE-20 acceptance bars in one file:

* ``verify_meter_ab`` — the live 43-checks/txn meter (config 7's
  ``run_verify_meter``, BASELINE n=64 shape) with the fast path on vs
  off, runs interleaved: on-posture unique checks/txn must be ≤ 9
  (measured ~1 — the cert rides ONE memoized aggregate), off-posture
  must reproduce the 43.0 baseline or the A/B proves nothing.
* ``wan_fastpath_ab`` — config 7's full WAN shape, fast path on vs off,
  interleaved paired; the on-leg write p50 must be ≤ 33 ms (≈ RTT-bound)
  and the commit-breakdown deltas show WHERE the time came from.
* ``x25519`` — per-engine µs/op (native-C ladder vs pure Python vs
  OpenSSL when the wheel exists); native must be ≥ 5× the pure ladder.
* ``handshake_storm_ab`` — config 9's session ramp, native vs
  pure-Python X25519, interleaved paired (the PR-8 storm path).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

# RFC 7748 §5.2 vector 1 — the same operands the differential suite times
_K = bytes.fromhex(
    "a546e36bf0527c9d3b16154b82465edd62144c0ac1fc5a18506a2244ba449ac4"
)
_U = bytes.fromhex(
    "e6db6867583030db3594c1a424b15f7c726624ec26b3353b10a903a6d0ab1c4c"
)


def _x25519_engines(iters_native: int = 200, iters_pure: int = 20) -> Dict:
    """Per-engine X25519 µs/op on the RFC 7748 vector — the in-record
    measurement behind the ≥ 5× acceptance bar (the tier-1 twin lives in
    tests/test_native_x25519.py)."""
    from mochi_tpu.crypto import hostfallback as hf

    def _timed(fn, iters: int) -> float:
        fn(_K, _U)  # warm (native: first-call dlopen; pure: int cache)
        t0 = time.perf_counter()
        for _ in range(iters):
            fn(_K, _U)
        return (time.perf_counter() - t0) / iters * 1e6

    def _pure(private: bytes, peer: bytes) -> bytes:
        saved = hf._native
        hf._native = None
        try:
            return hf.x25519(private, peer)
        finally:
            hf._native = saved

    rec: Dict = {"pure_python_us": round(_timed(_pure, iters_pure), 1)}
    mod = hf._native_engine()
    if mod is not None and hasattr(mod, "x25519"):
        rec["native_c_us"] = round(_timed(mod.x25519, iters_native), 1)
        rec["speedup_native_over_pure"] = round(
            rec["pure_python_us"] / rec["native_c_us"], 1
        )
        rec["acceptance_ge_5x"] = rec["speedup_native_over_pure"] >= 5.0
    else:
        rec["native_c_us"] = None
        rec["note"] = "no native x25519 engine on this host"
    try:
        from cryptography.hazmat.primitives.asymmetric.x25519 import (
            X25519PrivateKey,
            X25519PublicKey,
        )

        def _openssl(private: bytes, peer: bytes) -> bytes:
            return X25519PrivateKey.from_private_bytes(private).exchange(
                X25519PublicKey.from_public_bytes(peer)
            )

        rec["openssl_us"] = round(_timed(_openssl, iters_native), 1)
    except ImportError:
        rec["openssl_us"] = None
    return rec


def run_meter_ab(n: int = 64, writes: int = 4, pairs: int = 2) -> Dict:
    """The verifies/txn meter, fast path on vs off, runs interleaved
    (on,off / off,on ...).  The meter is a causal-trace COUNT, so the
    pairs are a stability check, not noise averaging: every on-run and
    every off-run must report the same unique-checks figure."""
    from .config7_wan import run_verify_meter

    runs = {"on": [], "off": []}
    for i in range(pairs):
        order = (True, False) if i % 2 == 0 else (False, True)
        for fp in order:
            rec = run_verify_meter(n=n, writes=writes, fast_path=fp)
            runs["on" if fp else "off"].append(rec)
    on, off = runs["on"][0], runs["off"][0]
    uniq = lambda rs: [r["verifies_unique_per_txn_mean"] for r in rs]  # noqa: E731
    return {
        "pairs": pairs,
        "cluster": on["cluster"],
        "on": on,
        "off": off,
        "on_unique_per_txn_all_runs": uniq(runs["on"]),
        "off_unique_per_txn_all_runs": uniq(runs["off"]),
        "stable_across_pairs": (
            len(set(uniq(runs["on"]))) == 1 and len(set(uniq(runs["off"]))) == 1
        ),
        "acceptance_on_le_9": on["verifies_unique_per_txn_mean"] <= 9.0,
        "off_reproduces_baseline_43": off["matches_baseline_43"],
    }


def run(
    wan_pairs: int = 3,
    meter_pairs: int = 2,
    meter_n: int = 64,
    meter_writes: int = 4,
    storm_pairs: int = 3,
    storm_sessions: int = 256,
) -> Dict:
    from mochi_tpu.crypto.keys import host_crypto_engine

    from .config7_wan import run_fastpath_ab
    from .config9_overload import run_handshake_storm

    meter = run_meter_ab(n=meter_n, writes=meter_writes, pairs=meter_pairs)
    wan = run_fastpath_ab(pairs=wan_pairs)
    x = _x25519_engines()
    storm = run_handshake_storm(n_sessions=storm_sessions, pairs=storm_pairs)
    rec = {
        "metric": "fastpath_unique_verifies_per_txn",
        "value": meter["on"]["verifies_unique_per_txn_mean"],
        "unit": (
            "unique Ed25519 checks per write txn at the BASELINE n=64 "
            "shape, fast path on (43.0 = the pre-r18 floor, published.6)"
        ),
        "host_crypto_engine": host_crypto_engine(),
        "verify_meter_ab": meter,
        "wan_fastpath_ab": wan,
        "x25519": x,
        "handshake_storm_ab": storm,
        "acceptance": {
            "meter_on_le_9": meter["acceptance_on_le_9"],
            "meter_off_reproduces_43": meter["off_reproduces_baseline_43"],
            "wan_on_write_p50_le_33ms": wan["acceptance_on_write_p50_le_33ms"],
            "x25519_ge_5x": bool(x.get("acceptance_ge_5x")),
        },
        "platform_note": (
            "config-7 runs the whole 5-replica cluster plus 5 clients on ONE "
            "event loop in a 2-core container, so per-write protocol compute "
            "(~6 ms cluster-wide, measured: loopback write p50 is ~15.5 ms "
            "with zero simulated RTT) serialises instead of running on five "
            "machines in parallel; the 33 ms bar assumes the RTT-bound "
            "regime, and the residual miss (if any) is that serialisation, "
            "not verification cost — the meter A/B above shows the checks "
            "themselves collapsed 43 -> ~1."
        ),
    }
    return rec


def _main() -> None:
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny counts; rot check only, numbers meaningless")
    ap.add_argument("--out", help="also write the record to this path")
    args = ap.parse_args()
    if args.smoke:
        rec = run(wan_pairs=1, meter_pairs=1, meter_n=16, meter_writes=2,
                  storm_pairs=1, storm_sessions=32)
        rec["smoke"] = True
    else:
        rec = run()
    text = json.dumps(rec, indent=2)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    print(text)


if __name__ == "__main__":
    _main()

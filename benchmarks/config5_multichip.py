"""Config 5: multi-shard batch verify over a device mesh (shard_map/ICI).

BASELINE.json sketched this as "pmap across 4 TPU chips"; the modern
equivalent is ``shard_map`` over a ``jax.sharding.Mesh``
(``mochi_tpu.parallel``).  On single-chip hardware this still runs (1-device
mesh); to exercise a real 8-way mesh on CPU set
``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8``.
"""

from __future__ import annotations

import time
from typing import Dict


def run(batch_per_device: int = 2048, n_groups: int = 64, iters: int = 3) -> Dict:
    import numpy as np

    import jax

    from mochi_tpu.crypto import batch_verify, keys
    from mochi_tpu.parallel.sharded import (
        make_mesh,
        make_quorum_step,
        pad_to_multiple,
    )
    from mochi_tpu.verifier.spi import VerifyItem

    mesh = make_mesh()
    n_dev = mesh.devices.size
    b = batch_per_device * n_dev

    kp = keys.generate_keypair()
    items = []
    for i in range(b):
        msg = b"shard %d" % i
        items.append(VerifyItem(kp.public_key, msg, kp.sign(msg)))
    prep = batch_verify.prepare(items)
    group_ids = (np.arange(b, dtype=np.int32) % n_groups).astype(np.int32)
    arrays, m = pad_to_multiple(
        tuple(prep[:6]) + (group_ids,), b, n_dev, dead_group=0
    )

    step = make_quorum_step(mesh, n_groups)
    thr = np.int32(1)
    out = jax.block_until_ready(step(*arrays, thr))  # compile
    bitmap = np.asarray(out[0])
    assert bitmap[:b].all()

    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        # readback inside the timed region: true sync through the axon relay
        out = tuple(np.asarray(x) for x in step(*arrays, thr))
        best = min(best, time.perf_counter() - t0)

    rec = {
        "metric": "multichip_sharded_verify_throughput",
        "value": round(b / best, 1),
        "unit": "sigs/sec",
        "devices": n_dev,
        "platform": jax.devices()[0].platform,
        "batch_total": b,
        "ms": round(best * 1e3, 2),
    }

    # Same-batch A/B (VERDICT r2 weak #6/item 7): decompose the round-2
    # gap (7.8k sigs/s sharded@2048 vs 91k unsharded@8192 on one chip)
    # into its two factors —
    #   batch-size effect:      unsharded@2048 vs unsharded@8192
    #   shard_map/psum tax:     sharded@B vs unsharded@B, same B
    try:
        from mochi_tpu.crypto.curve import verify_prepared

        fn = jax.jit(verify_prepared)
        ab: Dict = {}
        for bsz in sorted({b, 8192}):
            kp2_items = items
            while len(kp2_items) < bsz:
                msg = b"ab %d" % len(kp2_items)
                kp2_items = kp2_items + [
                    VerifyItem(kp.public_key, msg, kp.sign(msg))
                ]
            prep_b = batch_verify.prepare(kp2_items[:bsz])
            args_u = tuple(prep_b[:6])
            jax.block_until_ready(fn(*args_u))  # compile
            t_u = float("inf")
            for _ in range(iters):
                t0 = time.perf_counter()
                np.asarray(fn(*args_u))
                t_u = min(t_u, time.perf_counter() - t0)
            gid = (np.arange(bsz, dtype=np.int32) % n_groups).astype(np.int32)
            arr_s, _ = pad_to_multiple(
                tuple(prep_b[:6]) + (gid,), bsz, n_dev, dead_group=0
            )
            step_b = make_quorum_step(mesh, n_groups)
            jax.block_until_ready(step_b(*arr_s, thr))  # compile
            t_s = float("inf")
            for _ in range(iters):
                t0 = time.perf_counter()
                _o = tuple(np.asarray(x) for x in step_b(*arr_s, thr))
                t_s = min(t_s, time.perf_counter() - t0)
            ab[str(bsz)] = {
                "unsharded_sigs_per_sec": round(bsz / t_u, 1),
                "sharded_sigs_per_sec": round(bsz / t_s, 1),
                "shard_machinery_tax": round(t_s / t_u, 2),
            }
        rec["same_batch_ab"] = ab
    except Exception as exc:
        rec["same_batch_ab"] = {"error": f"{type(exc).__name__}: {exc}"[:200]}
    return rec


if __name__ == "__main__":
    import json

    print(json.dumps(run()))

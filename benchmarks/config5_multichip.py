"""Config 5: multi-shard batch verify over a device mesh (shard_map/ICI).

BASELINE.json sketched this as "pmap across 4 TPU chips"; the modern
equivalent is ``shard_map`` over a ``jax.sharding.Mesh``
(``mochi_tpu.parallel``).  On single-chip hardware this still runs
(1-device mesh); to exercise a real 8-way mesh on CPU set
``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8``.

Round-4 rework (VERDICT r3 item 3): the headline now measures what
production runs — :class:`mochi_tpu.verifier.tpu.ShardedJaxBatchBackend`
(packed (B, 32)-byte scalar transfers) — instead of the bit-tensor
``make_quorum_step`` the round-2 capture used (32x larger H2D transfers,
the prime suspect for its 12x sharded-vs-unsharded gap).  The published
record decomposes the gap into its three candidate factors:

* ``transfer_form``: packed-sharded vs bits-sharded at the same batch —
  isolates the H2D transfer form;
* ``shard_tax``: packed-sharded vs packed-unsharded at the same batch —
  isolates the shard_map/psum machinery;
* ``batch_size``: per-device batch 8192 vs the round-2 2048 — isolates
  underfilled devices.
"""

from __future__ import annotations

import time
from typing import Dict


def _timed_rate(fn, batch: int, iters: int) -> float:
    """Best-of-iters sigs/s; np.asarray readback inside the timed region
    (the only trustworthy sync through the axon relay)."""
    import numpy as np

    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn()
        if isinstance(out, tuple):
            out = tuple(np.asarray(x) for x in out)
        else:
            np.asarray(out)
        best = min(best, time.perf_counter() - t0)
    return batch / best


def run(batch_per_device: int = 0, n_groups: int = 64, iters: int = 3) -> Dict:
    import numpy as np

    import jax

    from mochi_tpu.crypto import batch_verify, keys
    from mochi_tpu.parallel.sharded import (
        make_mesh,
        make_quorum_step,
        make_sharded_verify_packed,
        pad_to_multiple,
    )
    from mochi_tpu.verifier.spi import VerifyItem
    from mochi_tpu.verifier.tpu import ShardedJaxBatchBackend

    mesh = make_mesh()
    n_dev = mesh.devices.size
    platform = jax.devices()[0].platform
    if batch_per_device <= 0:
        # 8192/device is the measured single-chip peak; CPU test meshes use
        # a small batch (the CPU backend compiles/runs ~75x slower).
        batch_per_device = 8192 if platform == "tpu" else 256
    b = batch_per_device * n_dev

    kp = keys.generate_keypair()
    items = []
    for i in range(b):
        msg = b"shard %d" % i
        items.append(VerifyItem(kp.public_key, msg, kp.sign(msg)))

    # ---- headline: the PRODUCTION sharded backend, end to end -----------
    # (host prepare_packed + pad + packed H2D + shard_map verify + readback
    # — exactly what ShardedTpuBatchVerifier runs per flush)
    backend = ShardedJaxBatchBackend(mesh=mesh, min_device_items=0)
    out = backend._sharded_verify(items)  # compile + warm
    assert all(out)
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        out = backend._sharded_verify(items)
        best = min(best, time.perf_counter() - t0)
    assert all(out)
    rec: Dict = {
        "metric": "multichip_sharded_verify_throughput",
        "value": round(b / best, 1),
        "unit": "sigs/sec",
        "devices": n_dev,
        "platform": platform,
        "batch_total": b,
        "batch_per_device": batch_per_device,
        "ms": round(best * 1e3, 2),
        "path": "ShardedJaxBatchBackend (packed production path, end-to-end)",
    }

    # ---- comb leg: registered-signer traffic on the same backend --------
    # (the production posture — service --signers-file; ~3x fewer device
    # FLOPs per item, comb.py)
    backend.register_signers([kp.public_key])
    out = backend._sharded_verify(
        items, registry=backend.registry, comb_gen=backend.registry.generation
    )  # compile + warm
    assert all(out)
    comb_best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        out = backend._sharded_verify(
            items,
            registry=backend.registry,
            comb_gen=backend.registry.generation,
        )
        comb_best = min(comb_best, time.perf_counter() - t0)
    assert all(out)
    rec["comb_sigs_per_sec"] = round(b / comb_best, 1)
    rec["comb_vs_ladder"] = round(best / comb_best, 2)

    # ---- decomposition at the same total batch --------------------------
    y_a, sign_a, y_r, sign_r, s_sc, h_sc, pre_ok = batch_verify.prepare_packed(items)
    assert pre_ok.all()
    packed_np = (y_a, sign_a, y_r, sign_r, s_sc, h_sc)

    sharded_packed = make_sharded_verify_packed(mesh)
    jax.block_until_ready(sharded_packed(*packed_np))  # compile
    rate_sharded_packed = _timed_rate(lambda: sharded_packed(*packed_np), b, iters)

    unsharded = batch_verify._verify_packed_jit
    jax.block_until_ready(unsharded(*packed_np))  # compile
    rate_unsharded_packed = _timed_rate(lambda: unsharded(*packed_np), b, iters)

    # bits form + quorum tally (the round-2 capture's path)
    prep_bits = batch_verify.prepare(items)
    group_ids = (np.arange(b, dtype=np.int32) % n_groups).astype(np.int32)
    arrays, _m = pad_to_multiple(
        tuple(prep_bits[:6]) + (group_ids,), b, n_dev, dead_group=0
    )
    step = make_quorum_step(mesh, n_groups)
    thr = np.int32(1)
    out = jax.block_until_ready(step(*arrays, thr))  # compile
    assert np.asarray(out[0])[:b].all()
    rate_bits_quorum = _timed_rate(lambda: step(*arrays, thr), b, iters)

    rec["decomposition"] = {
        "sharded_packed_sigs_per_sec": round(rate_sharded_packed, 1),
        "unsharded_packed_sigs_per_sec": round(rate_unsharded_packed, 1),
        "bits_quorum_step_sigs_per_sec": round(rate_bits_quorum, 1),
        "shard_tax": round(rate_unsharded_packed / rate_sharded_packed, 2),
        "transfer_form_gain": round(rate_sharded_packed / rate_bits_quorum, 2),
        "note": "shard_tax ~1 => shard_map/psum machinery is free; "
        "transfer_form_gain >1 => the round-2 gap was the bit-tensor H2D "
        "form, not the mesh",
    }

    # ---- batch-size effect (the round-2 capture ran 2048/device) --------
    small = batch_per_device // 4
    if small >= 16:
        items_small = items[: small * n_dev]
        prep_small = batch_verify.prepare_packed(items_small)[:6]
        jax.block_until_ready(sharded_packed(*prep_small))  # compile
        rate_small = _timed_rate(
            lambda: sharded_packed(*prep_small), small * n_dev, iters
        )
        rec["batch_size_effect"] = {
            "per_device": {
                str(small): round(rate_small, 1),
                str(batch_per_device): round(rate_sharded_packed, 1),
            },
            "gain": round(rate_sharded_packed / rate_small, 2),
        }

    # quorum tally remains the distributed-step capability proof: every
    # signature is valid, so the cross-device psum must reproduce the exact
    # per-group membership counts (a mis-tally would be invisible to a
    # weaker >=0 check).
    counts = np.asarray(out[1])
    expected = np.bincount(group_ids, minlength=n_groups)
    rec["quorum_step"] = {
        "n_groups": n_groups,
        "psum_counts_ok": bool((counts[:n_groups] == expected[:n_groups]).all()),
        "sigs_per_sec": round(rate_bits_quorum, 1),
    }
    return rec


if __name__ == "__main__":
    import json

    print(json.dumps(run()))

"""Config 5: multi-shard batch verify over a device mesh (shard_map/ICI).

BASELINE.json sketched this as "pmap across 4 TPU chips"; the modern
equivalent is ``shard_map`` over a ``jax.sharding.Mesh``
(``mochi_tpu.parallel``).  On single-chip hardware this still runs (1-device
mesh); to exercise a real 8-way mesh on CPU set
``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8``.
"""

from __future__ import annotations

import time
from typing import Dict


def run(batch_per_device: int = 2048, n_groups: int = 64, iters: int = 3) -> Dict:
    import numpy as np

    import jax

    from mochi_tpu.crypto import batch_verify, keys
    from mochi_tpu.parallel.sharded import (
        make_mesh,
        make_quorum_step,
        pad_to_multiple,
    )
    from mochi_tpu.verifier.spi import VerifyItem

    mesh = make_mesh()
    n_dev = mesh.devices.size
    b = batch_per_device * n_dev

    kp = keys.generate_keypair()
    items = []
    for i in range(b):
        msg = b"shard %d" % i
        items.append(VerifyItem(kp.public_key, msg, kp.sign(msg)))
    prep = batch_verify.prepare(items)
    group_ids = (np.arange(b, dtype=np.int32) % n_groups).astype(np.int32)
    arrays, m = pad_to_multiple(
        tuple(prep[:6]) + (group_ids,), b, n_dev, dead_group=0
    )

    step = make_quorum_step(mesh, n_groups)
    thr = np.int32(1)
    out = jax.block_until_ready(step(*arrays, thr))  # compile
    bitmap = np.asarray(out[0])
    assert bitmap[:b].all()

    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        # readback inside the timed region: true sync through the axon relay
        out = tuple(np.asarray(x) for x in step(*arrays, thr))
        best = min(best, time.perf_counter() - t0)

    return {
        "metric": "multichip_sharded_verify_throughput",
        "value": round(b / best, 1),
        "unit": "sigs/sec",
        "devices": n_dev,
        "platform": jax.devices()[0].platform,
        "batch_total": b,
        "ms": round(best * 1e3, 2),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run()))

"""Config 1 variant: replicas as separate OS processes, with CPU accounting.

The in-process config-1 bench (``config1_cluster.py``) time-slices 5
replicas + clients + the verifier service over ONE event loop, so its
txn/s ceiling conflates protocol cost with host-core scarcity.  This
variant runs the production posture — each replica a real
``python -m mochi_tpu.server`` process, one shared verifier service, the
client workload in its own process space — and reads each process's
utime+stime from ``/proc/<pid>/stat`` across the measured window.

On a single-core host (this build environment) the throughput number
itself stays core-bound, but the per-role CPU accounting is the point:

    replica_cpu_s_per_txn       — one replica's CPU cost per transaction
    core_saturation_txn_s       — 1 / that: what one replica sustains on
                                  a dedicated core (the protocol's own
                                  per-node limit, the number a multi-core
                                  deployment scales from)

i.e. the honest decomposition of "is the protocol or the host the
bottleneck" (VERDICT r2 item 3) when no multi-core host is available.
The reference's analog posture is one JVM per server on EC2
(``/root/reference/config/aws_5_config``, ``start_mochi.sh``).
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Tuple

_CLK = os.sysconf("SC_CLK_TCK")


def _cpu_s(pid: int) -> float:
    """utime+stime (+ reaped children) of a pid, in seconds."""
    with open(f"/proc/{pid}/stat", "rb") as f:
        fields = f.read().rsplit(b")", 1)[1].split()
    # fields[11]=utime fields[12]=stime (0-based after comm)
    return (int(fields[11]) + int(fields[12])) / _CLK


async def _workload(config_path: str, n_clients: int, keys_per_client: int, sweeps: int):
    from mochi_tpu.client.client import MochiDBClient
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.server.__main__ import load_config

    config = load_config(config_path)
    read_lat: List[float] = []
    write_lat: List[float] = []
    ops = 0

    async def worker(ci: int) -> None:
        nonlocal ops
        client = MochiDBClient(config)
        try:
            for s in range(sweeps):
                for k in range(keys_per_client):
                    key = f"mp-{ci}-{k}"
                    val = f"v{s}".encode()
                    t0 = time.perf_counter()
                    await client.execute_write_transaction(
                        TransactionBuilder().write(key, val).build()
                    )
                    write_lat.append(time.perf_counter() - t0)
                    t0 = time.perf_counter()
                    res = await client.execute_read_transaction(
                        TransactionBuilder().read(key).build()
                    )
                    read_lat.append(time.perf_counter() - t0)
                    assert res.operations[0].value == val
                    t0 = time.perf_counter()
                    await client.execute_write_transaction(
                        TransactionBuilder().delete(key).build()
                    )
                    write_lat.append(time.perf_counter() - t0)
                    ops += 3
        finally:
            await client.close()

    t0 = time.perf_counter()
    await asyncio.gather(*[worker(i) for i in range(n_clients)])
    wall = time.perf_counter() - t0
    return ops, wall, read_lat, write_lat


def run(
    n_servers: int = 5,
    rf: int = 4,
    n_clients: int = 8,
    keys_per_client: int = 12,
    sweeps: int = 2,
    uds: bool = False,
) -> Dict:
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    procs: List[Tuple[str, subprocess.Popen]] = []
    with tempfile.TemporaryDirectory(prefix="mochi-mp-") as out:
        subprocess.run(
            [
                sys.executable, "-m", "mochi_tpu.tools.gen_cluster",
                "--out-dir", out, "--servers", str(n_servers), "--rf", str(rf),
                "--base-port", "9301",
            ]
            + (["--uds"] if uds else []),
            check=True, env=env, capture_output=True,
        )
        cfg = os.path.join(out, "cluster_config.json")
        try:
            vport = 11311
            procs.append((
                "verifier",
                subprocess.Popen(
                    [
                        sys.executable, "-m", "mochi_tpu.verifier.service",
                        "--port", str(vport), "--backend", "cpu", "--warmup", "",
                    ],
                    env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                ),
            ))
            for i in range(n_servers):
                procs.append((
                    f"server-{i}",
                    subprocess.Popen(
                        [
                            sys.executable, "-m", "mochi_tpu.server",
                            "--config", cfg,
                            "--server-id", f"server-{i}",
                            "--seed-file", os.path.join(out, f"server-{i}.seed"),
                            "--verifier", f"remote:127.0.0.1:{vport}",
                        ],
                        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                    ),
                ))
            # wait for ports to accept
            from mochi_tpu.server.__main__ import load_config

            config = load_config(cfg)
            deadline = time.time() + 30
            for info in config.servers.values():
                while time.time() < deadline:
                    try:
                        import socket

                        if info.is_unix:
                            s = socket.socket(socket.AF_UNIX)
                            s.settimeout(0.5)
                            try:
                                s.connect(info.unix_path)
                                break
                            finally:
                                s.close()
                        with socket.create_connection((info.host, info.port), 0.5):
                            break
                    except OSError:
                        time.sleep(0.2)
                else:
                    raise RuntimeError("cluster did not come up")

            # warm (sessions, connects), then measure with CPU deltas
            asyncio.run(_workload(cfg, 2, 2, 1))
            cpu0 = {name: _cpu_s(p.pid) for name, p in procs}
            self0 = time.process_time()
            ops, wall, read_lat, write_lat = asyncio.run(
                _workload(cfg, n_clients, keys_per_client, sweeps)
            )
            cpu = {name: _cpu_s(p.pid) - cpu0[name] for name, p in procs}
            client_cpu = time.process_time() - self0
        finally:
            for _, p in procs:
                p.send_signal(signal.SIGTERM)
            for _, p in procs:
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()

    def pct(samples: List[float], q: float) -> float:
        s = sorted(samples)
        return s[min(len(s) - 1, int(q * len(s)))] if s else float("nan")

    replica_cpu = [v for n, v in cpu.items() if n.startswith("server-")]
    mean_replica_cpu_per_txn = sum(replica_cpu) / len(replica_cpu) / ops
    return {
        "metric": "signed_txn_throughput_multiproc",
        "value": round(ops / wall, 1),
        "unit": "txns/sec",
        # Round-4 battery's multiproc record (benchmarks/results_r04.json,
        # OpenSSL-wheel host) — same caveat as config1_cluster.PRIOR_TXN_S_R04.
        "prior_txn_s": 367.9,
        "vs_prior": round(ops / wall / 367.9, 3),
        "topology": f"{n_servers} server procs + verifier proc + client proc, 1 host core",
        "ops": ops,
        "wall_s": round(wall, 2),
        "read_p50_ms": round(pct(read_lat, 0.5) * 1e3, 2),
        "write_p50_ms": round(pct(write_lat, 0.5) * 1e3, 2),
        "cpu_s": {k: round(v, 3) for k, v in cpu.items()},
        "client_cpu_s": round(client_cpu, 3),
        "host_cores": os.cpu_count(),
        "replica_cpu_us_per_txn": round(mean_replica_cpu_per_txn * 1e6, 1),
        # What ONE replica process sustains given a dedicated core — the
        # protocol's per-node ceiling, independent of this host's core count.
        "core_saturation_txn_s": round(1.0 / mean_replica_cpu_per_txn, 1),
        "note": (
            "single-core host: absolute txn/s is host-bound; "
            "core_saturation_txn_s is the protocol-limit estimate a "
            "multi-core deployment scales from"
        ),
    }


if __name__ == "__main__":
    print(json.dumps(run()))


async def _open_loop_client(config_path: str, rate: float, duration_s: float):
    """Poisson write arrivals from THIS process against an external cluster
    (absolute-schedule pacing: a congested client loop fires missed
    arrivals in a burst, keeping the load open-loop)."""
    import random

    from mochi_tpu.client.client import MochiDBClient
    from mochi_tpu.client.errors import RequestRefused
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.server.__main__ import load_config

    config = load_config(config_path)
    client = MochiDBClient(config, timeout_s=5.0, write_attempts=8)
    await client.execute_write_transaction(
        TransactionBuilder().write("warm", b"w").build()
    )
    lat: List[float] = []
    decision: List[float] = []  # time to completion OR typed give-up
    gave_up = 0
    tasks: set = set()
    rng = random.Random(11)

    async def one(i: int) -> None:
        nonlocal gave_up
        t0 = time.perf_counter()
        try:
            await client.execute_write_transaction(
                TransactionBuilder().write(f"ol-{i}", b"v").build()
            )
            lat.append(time.perf_counter() - t0)
        except (RequestRefused, TimeoutError, Exception):
            gave_up += 1
        decision.append(time.perf_counter() - t0)

    t0 = time.perf_counter()
    next_t = t0
    i = 0
    while time.perf_counter() - t0 < duration_s:
        next_t += rng.expovariate(rate)
        delay = next_t - time.perf_counter()
        if delay > 0:
            await asyncio.sleep(delay)
        elif i % 32 == 0:
            await asyncio.sleep(0)
        task = asyncio.ensure_future(one(i))
        tasks.add(task)
        task.add_done_callback(tasks.discard)
        i += 1
    if tasks:
        await asyncio.wait(tasks, timeout=20.0)
    wall = time.perf_counter() - t0
    await client.close()

    def pct(samples, q):
        s = sorted(samples)
        return s[min(len(s) - 1, int(q * len(s)))] if s else float("nan")

    return {
        "offered_rate": rate,
        "offered": i,
        "completed": len(lat),
        "gave_up": gave_up,
        "goodput_per_s": round(len(lat) / wall, 1),
        "write_p50_ms": round(pct(lat, 0.50) * 1e3, 2),
        "write_p95_ms": round(pct(lat, 0.95) * 1e3, 2),
        "decision_p95_ms": round(pct(decision, 0.95) * 1e3, 2),
    }


def run_open_loop_ab(rate: float = 500.0, duration_s: float = 10.0) -> Dict:
    """Overload A/B in the production posture: separate replica processes
    (each with its OWN event loop — loop lag is then a truthful per-replica
    congestion signal, unlike the in-process harness where one loop carries
    the whole cluster), external Poisson write load from this process.
    Shedding off (--shed-lag-ms 0) vs on (30 ms)."""
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()
    out: Dict = {"metric": "open_loop_overload_ab_multiproc", "unit": "ms (write p95)"}
    for label, shed in (("unprotected", 0.0), ("protected", 30.0)):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        procs: List[Tuple[str, subprocess.Popen]] = []
        with tempfile.TemporaryDirectory(prefix="mochi-ol-") as outdir:
            subprocess.run(
                [
                    sys.executable, "-m", "mochi_tpu.tools.gen_cluster",
                    "--out-dir", outdir, "--servers", "5", "--rf", "4",
                    "--base-port", "9501",
                ],
                check=True, env=env, capture_output=True,
            )
            cfg = os.path.join(outdir, "cluster_config.json")
            try:
                vport = 11511
                procs.append((
                    "verifier",
                    subprocess.Popen(
                        [
                            sys.executable, "-m", "mochi_tpu.verifier.service",
                            "--port", str(vport), "--backend", "cpu", "--warmup", "",
                        ],
                        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                    ),
                ))
                for i in range(5):
                    procs.append((
                        f"server-{i}",
                        subprocess.Popen(
                            [
                                sys.executable, "-m", "mochi_tpu.server",
                                "--config", cfg,
                                "--server-id", f"server-{i}",
                                "--seed-file", os.path.join(outdir, f"server-{i}.seed"),
                                "--verifier", f"remote:127.0.0.1:{vport}",
                                "--shed-lag-ms", str(shed),
                            ],
                            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                        ),
                    ))
                from mochi_tpu.server.__main__ import load_config

                config = load_config(cfg)
                import socket

                deadline = time.time() + 30
                for info in config.servers.values():
                    while time.time() < deadline:
                        try:
                            with socket.create_connection((info.host, info.port), 0.5):
                                break
                        except OSError:
                            time.sleep(0.2)
                    else:
                        raise RuntimeError("cluster did not come up")
                out[label] = asyncio.run(_open_loop_client(cfg, rate, duration_s))
            finally:
                for _, p in procs:
                    p.send_signal(signal.SIGTERM)
                for _, p in procs:
                    try:
                        p.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        p.kill()
    out["value"] = out["protected"]["write_p95_ms"]
    return out

"""Config 1: 5-replica local cluster, signed PUT/GET, f=1.

The BASELINE.md workload shape (5 concurrent clients, write -> read-verify ->
delete sweeps over disjoint keys — the reference's
``testWriteOperationConcurrentStressTest``, SURVEY.md §6) on an in-process
virtual cluster with real loopback TCP and full Ed25519 signing/verification.
Reports ops/sec plus read/write latency percentiles to compare against the
reference's WAN table (which had ~13 ms RTT; loopback removes the WAN leg so
the comparable number is protocol+crypto overhead).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List


def _pct(samples: List[float], q: float) -> float:
    if not samples:
        return float("nan")
    s = sorted(samples)
    idx = min(len(s) - 1, int(q * len(s)))
    return s[idx]


async def _run(n_clients: int, keys_per_client: int, sweeps: int) -> Dict:
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async with VirtualCluster(5, rf=4) as vc:
        read_lat: List[float] = []
        write_lat: List[float] = []
        ops = 0

        async def worker(ci: int):
            nonlocal ops
            client = vc.client()
            for s in range(sweeps):
                for k in range(keys_per_client):
                    key = f"bench-{ci}-{k}"
                    val = f"v{s}".encode()
                    t0 = time.perf_counter()
                    await client.execute_write_transaction(
                        TransactionBuilder().write(key, val).build()
                    )
                    write_lat.append(time.perf_counter() - t0)
                    t0 = time.perf_counter()
                    res = await client.execute_read_transaction(
                        TransactionBuilder().read(key).build()
                    )
                    read_lat.append(time.perf_counter() - t0)
                    assert res.operations[0].value == val
                    t0 = time.perf_counter()
                    await client.execute_write_transaction(
                        TransactionBuilder().delete(key).build()
                    )
                    write_lat.append(time.perf_counter() - t0)
                    ops += 3

        t0 = time.perf_counter()
        await asyncio.gather(*[worker(i) for i in range(n_clients)])
        wall = time.perf_counter() - t0

    return {
        "metric": "signed_txn_throughput_5replica_f1",
        "value": round(ops / wall, 1),
        "unit": "txns/sec",
        "read_p50_ms": round(_pct(read_lat, 0.50) * 1e3, 2),
        "read_p95_ms": round(_pct(read_lat, 0.95) * 1e3, 2),
        "write_p50_ms": round(_pct(write_lat, 0.50) * 1e3, 2),
        "write_p95_ms": round(_pct(write_lat, 0.95) * 1e3, 2),
        "ops": ops,
        "wall_s": round(wall, 2),
    }


def run(n_clients: int = 5, keys_per_client: int = 8, sweeps: int = 2) -> Dict:
    return asyncio.run(_run(n_clients, keys_per_client, sweeps))


if __name__ == "__main__":
    import json

    print(json.dumps(run()))

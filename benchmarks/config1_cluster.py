"""Config 1: 5-replica local cluster, signed PUT/GET, f=1.

The BASELINE.md workload shape (5 concurrent clients, write -> read-verify ->
delete sweeps over disjoint keys — the reference's
``testWriteOperationConcurrentStressTest``, SURVEY.md §6) on an in-process
virtual cluster with real loopback TCP and full Ed25519 signing/verification.
Reports ops/sec plus read/write latency percentiles to compare against the
reference's WAN table (which had ~13 ms RTT; loopback removes the WAN leg so
the comparable number is protocol+crypto overhead).
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import Dict, List


def _pct(samples: List[float], q: float) -> float:
    if not samples:
        return float("nan")
    s = sorted(samples)
    idx = min(len(s) - 1, int(q * len(s)))
    return s[idx]


# The published config-1 prior this record is judged against: the round-4
# battery's 572.5 txns/sec (benchmarks/results_r04.json) — measured on the
# round-4 host, which had the OpenSSL `cryptography` wheel (~30 us Ed25519
# ops).  A host WITHOUT the wheel runs the pure-Python fallback (~650 us
# signs) and lands far below it regardless of protocol efficiency, so the
# record carries both deltas: vs this published prior AND — in the
# committed results file — vs the pre-batching tree re-measured on the
# same host (`baseline_same_host_txn_s`, captured by checking out the
# parent commit and running this same config interleaved with the new
# tree, best-of-N against the host's ±30% tenancy noise).
PRIOR_TXN_S_R04 = 572.5


_EVIDENCE_HISTOGRAMS = {
    "transport.drain-frames": "drain_frames",
    "replica.batch-occupancy": "batch_occupancy",
    "transport.flush-bytes": "flush_bytes",
}


def _batching_evidence(replicas) -> Dict:
    """Batch-occupancy / drain evidence aggregated over the replicas —
    the observable that says whether the per-tick drain actually batched
    (docs/OPERATIONS.md "Batched hot path")."""
    acc: Dict[str, Dict] = {}
    for r in replicas:
        for hist_name, out_name in _EVIDENCE_HISTOGRAMS.items():
            h = r.metrics.histograms.get(hist_name)
            if h is None:
                continue
            snap = h.snapshot()
            cur = acc.setdefault(out_name, {"count": 0, "sum": 0.0, "buckets": {}})
            cur["count"] += snap["count"]
            cur["sum"] += snap["sum"]
            for k, v in snap["buckets"].items():
                cur["buckets"][k] = cur["buckets"].get(k, 0) + v
    for cur in acc.values():
        cur["mean"] = round(cur["sum"] / cur["count"], 2) if cur["count"] else None
        del cur["sum"]
    return {name: cur for name, cur in acc.items() if cur["count"]}


async def _run(
    n_clients: int, keys_per_client: int, sweeps: int, verifier: str = "service"
) -> Dict:
    # The measured topology mirrors a real deployment (VERDICT r1 weak #5):
    # every replica ships signature batches to ONE shared verifier service
    # (the TPU owner) over the mcode transport; the service batches across
    # the whole cluster and memoizes duplicate grant checks.  "cpu" keeps
    # the reference-analog inline path for comparison.
    service = None
    factory = None
    if verifier == "service":
        from mochi_tpu.verifier.service import RemoteVerifier, VerifierService
        from mochi_tpu.verifier.spi import CoalescingVerifier
        from mochi_tpu.verifier.spi import CpuVerifier

        inner = None
        try:
            import jax

            if jax.default_backend() == "tpu":
                from mochi_tpu.verifier.tpu import TpuBatchVerifier

                inner = TpuBatchVerifier(max_delay_s=0.001, warmup_buckets=(16,))
        except Exception:
            inner = None
        if inner is None:
            # No TPU: the service still batches + memoizes over OpenSSL
            inner = CpuVerifier()
        service = VerifierService(port=0, verifier=inner)
        await service.start()
        port = service.bound_port
        factory = lambda: CoalescingVerifier(RemoteVerifier("127.0.0.1", port))

    try:
        return await _run_cluster(
            n_clients, keys_per_client, sweeps, verifier, factory, service
        )
    finally:
        if service is not None:
            await service.close()


async def _run_cluster(n_clients, keys_per_client, sweeps, verifier, factory, service):
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async with VirtualCluster(5, rf=4, verifier_factory=factory) as vc:
        read_lat: List[float] = []
        write_lat: List[float] = []
        ops = 0

        async def worker(ci: int):
            nonlocal ops
            client = vc.client()
            for s in range(sweeps):
                for k in range(keys_per_client):
                    key = f"bench-{ci}-{k}"
                    val = f"v{s}".encode()
                    t0 = time.perf_counter()
                    await client.execute_write_transaction(
                        TransactionBuilder().write(key, val).build()
                    )
                    write_lat.append(time.perf_counter() - t0)
                    t0 = time.perf_counter()
                    res = await client.execute_read_transaction(
                        TransactionBuilder().read(key).build()
                    )
                    read_lat.append(time.perf_counter() - t0)
                    assert res.operations[0].value == val
                    t0 = time.perf_counter()
                    await client.execute_write_transaction(
                        TransactionBuilder().delete(key).build()
                    )
                    write_lat.append(time.perf_counter() - t0)
                    ops += 3

        t0 = time.perf_counter()
        await asyncio.gather(*[worker(i) for i in range(n_clients)])
        wall = time.perf_counter() - t0
        # capture before __aexit__ clears vc.replicas; mean over replicas so
        # the number reads as "one replica's crypto share of its CPU time"
        # (the 5 in-process replicas share one wall clock — summing would
        # report a 5-replica aggregate that can exceed 100%)
        crypto_s = (
            sum(
                r.metrics.timers["replica.crypto-local"].total_seconds
                for r in vc.replicas
            )
            / len(vc.replicas)
            if vc.replicas
            else None
        )
        batching = _batching_evidence(vc.replicas)

    # BASELINE.json target "<5% replica CPU in crypto": one replica's
    # synchronous crypto time (session MACs, grant/envelope Ed25519 signs
    # — the "replica.crypto-local" timer), averaged over replicas, as a
    # share of the run's wall clock.  Ed25519 *verification* rides the
    # shared verifier service, so it never lands on a replica's own CPU.
    rec = {
        "metric": "signed_txn_throughput_5replica_f1",
        "value": round(ops / wall, 1),
        "unit": "txns/sec",
        "verifier": verifier,
        "prior_txn_s": PRIOR_TXN_S_R04,
        "vs_prior": round(ops / wall / PRIOR_TXN_S_R04, 3),
        "batching": batching,
        "replica_crypto_cpu_pct_of_wall_mean": (
            round(100.0 * crypto_s / wall, 2) if crypto_s is not None else None
        ),
        "read_p50_ms": round(_pct(read_lat, 0.50) * 1e3, 2),
        "read_p95_ms": round(_pct(read_lat, 0.95) * 1e3, 2),
        "write_p50_ms": round(_pct(write_lat, 0.50) * 1e3, 2),
        "write_p95_ms": round(_pct(write_lat, 0.95) * 1e3, 2),
        "ops": ops,
        "wall_s": round(wall, 2),
    }
    if service is not None:
        cache = getattr(service.verifier, "hits", None)
        if cache is not None:
            rec["service_cache_hits"] = service.verifier.hits
            rec["service_cache_misses"] = service.verifier.misses
        rec["service_items"] = service.items
    return rec


def run(
    n_clients: int = 5,
    keys_per_client: int = 8,
    sweeps: int = 2,
    verifier: str = "service",
) -> Dict:
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()  # same GC posture the real server processes run with
    rec = asyncio.run(_run(n_clients, keys_per_client, sweeps, verifier))
    if os.environ.get("MOCHI_BENCH_FULL"):
        # Battery posture (run_all): attach the closed-loop concurrency sweep
        # (admission control on — the production default) and the open-loop
        # overload A/B, so the published record carries the saturation and
        # backpressure evidence alongside the headline number.
        sweep = []
        for nc in (5, 10, 20, 40):
            r = asyncio.run(_run(nc, max(4, 200 // nc), 2, verifier))
            sweep.append(
                {
                    "clients": nc,
                    "txn_s": r["value"],
                    "write_p50_ms": r["write_p50_ms"],
                    "write_p95_ms": r["write_p95_ms"],
                }
            )
        rec["concurrency_sweep"] = sweep
        rec["open_loop_overload_ab"] = {
            k: v
            for k, v in run_open_loop(rate=500.0, duration_s=8.0).items()
            if k in ("unprotected", "protected")
        }
        try:
            from benchmarks import config1_multiproc

            rec["multiproc"] = config1_multiproc.run(5, 4, 8, 20, 3)
        except Exception as exc:
            rec["multiproc"] = {"error": f"{type(exc).__name__}: {exc}"[:200]}
        rec["open_loop_note"] = (
            "in-process harness: ONE event loop carries all 5 replicas + "
            "clients + service, so the lag signal every replica sheds on is "
            "the whole cluster's congestion, not its own — the controller "
            "oversheds and completed-write latency absorbs retry backoff. "
            "The closed-loop sweep above (flat txn/s 5->40 clients, vs "
            "collapse to 372 with shedding off) is the meaningful admission-"
            "control artifact in this posture; config1_multiproc."
            "run_open_loop_ab is the per-process-loop posture, where the "
            "signal is truthful and no false sheds fire when the host "
            "scheduler (not a replica) is the bottleneck."
        )
    return rec


if __name__ == "__main__":
    import json

    print(json.dumps(run()))


async def _open_loop(rate: float, duration_s: float, shed_lag_ms: float) -> Dict:
    """Open-loop overload posture: writes ARRIVE at a fixed rate regardless
    of completions (unlike the closed-loop sweep, whose latency is bounded
    below by Little's law at any admission policy).  Above capacity, an
    unprotected server's queue — and thus p95 — grows without bound until
    client timeouts; with admission control the replica sheds new txns
    while lag is high, so accepted work keeps a bounded tail and goodput
    holds at capacity (VERDICT r2 item 9)."""
    import random

    from mochi_tpu.client.errors import RequestRefused
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    async with VirtualCluster(5, rf=4, shed_lag_ms=shed_lag_ms) as vc:
        client = vc.client(timeout_s=3.0, write_attempts=6)
        # pre-establish sessions off the clock
        await client.execute_write_transaction(
            TransactionBuilder().write("warm", b"w").build()
        )
        lat: List[float] = []
        gave_up = 0
        tasks: set = set()
        rng = random.Random(7)

        async def one(i: int) -> None:
            nonlocal gave_up
            t0 = time.perf_counter()
            try:
                await client.execute_write_transaction(
                    TransactionBuilder().write(f"ol-{i}", b"v").build()
                )
                lat.append(time.perf_counter() - t0)
            except (RequestRefused, TimeoutError, Exception):
                gave_up += 1

        t0 = time.perf_counter()
        i = 0
        # Absolute-schedule pacing: inter-arrival draws advance a target
        # clock, and a congested event loop that delays this coroutine makes
        # it fire the missed arrivals immediately in a burst — that is what
        # keeps the load OPEN-loop (a plain sleep(interarrival) loop would
        # self-throttle to the cluster's completion rate under congestion).
        next_t = t0
        while True:
            now = time.perf_counter()
            if now - t0 >= duration_s:
                break
            next_t += rng.expovariate(rate)
            delay = next_t - time.perf_counter()
            if delay > 0:
                await asyncio.sleep(delay)
            elif i % 32 == 0:
                await asyncio.sleep(0)  # keep yielding while bursting
            task = asyncio.ensure_future(one(i))
            tasks.add(task)
            task.add_done_callback(tasks.discard)
            i += 1
        if tasks:
            await asyncio.wait(tasks, timeout=15.0)
        wall = time.perf_counter() - t0
        shed_total = sum(
            r.metrics.counters.get("replica.write1-shed", 0) for r in vc.replicas
        )
    return {
        "offered_rate": rate,
        "shed_lag_ms": shed_lag_ms,
        "offered": i,
        "completed": len(lat),
        "gave_up": gave_up,
        "goodput_per_s": round(len(lat) / wall, 1),
        "write_p50_ms": round(_pct(lat, 0.50) * 1e3, 2),
        "write_p95_ms": round(_pct(lat, 0.95) * 1e3, 2),
        "write_p99_ms": round(_pct(lat, 0.99) * 1e3, 2),
        "replica_sheds": shed_total,
    }


def run_open_loop(rate: float = 400.0, duration_s: float = 6.0) -> Dict:
    """A/B: the same over-capacity Poisson write load with admission
    control off vs on.  Returns both records under one metric."""
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()
    unprotected = asyncio.run(_open_loop(rate, duration_s, shed_lag_ms=0.0))
    protected = asyncio.run(_open_loop(rate, duration_s, shed_lag_ms=30.0))
    return {
        "metric": "open_loop_overload_ab",
        "unit": "ms (write p95)",
        "value": protected["write_p95_ms"],
        "unprotected": unprotected,
        "protected": protected,
    }

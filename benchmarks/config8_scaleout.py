"""Config 8: shard-per-core scale-out — aggregate signed-PUT ops/s vs processes.

Every cluster number before round 10 came out of ONE process (the
``VirtualCluster`` posture: all replicas + clients time-sliced over one
event loop, i.e. one core).  This config runs the deployment the token-ring
L2 layer exists for: the same cluster spread over 1, 2, ..., N real server
processes (``testing/process_cluster.ProcessCluster`` →
``python -m mochi_tpu.server``), a signed PUT-only workload driven from the
parent process, and the aggregate throughput ladder measured per rung.

Measurement discipline (the committed-A/B house rules since r06, tightened
for this container's measured minute-scale tenancy swings of ±2-3x):

* the acceptance A/B boots BOTH postures once — the single-process
  VirtualCluster (replicas + clients on one event loop: the posture of
  every previously published cluster number) and the N = cores-1 process
  deployment — and alternates timed one-sweep chunks between them with
  order flipping per round, so each per-round ratio compares the postures
  inside the same ~2-second host window;
* the ladder curve (single-process rung + every process rung, including
  the oversubscribed N = cores one) runs as separated full legs, several
  rounds, medians reported with all samples;
* per-process CPU (utime+stime from /proc) is read across each timed
  window, so the record carries the cluster-wide replica CPU cost per
  transaction — the constant the 100k-ops/s extrapolation scales from;
* every sequence is preceded by a full-core warmup spin: this container's
  effective CPU speed ramps ~3x over the first ~10 s of sustained load.

The ``scaling_refit`` section re-derives the BASELINE.json scaling model
with every host-side constant from this run (dedicated 1-op-txn legs in
the sidecar posture split replica base+sign from memoized verify work);
the single inherited anchor — the TPU chip's sigs/s, unmeasurable on this
host — is flagged in-record.
"""

from __future__ import annotations

import asyncio
import os
import statistics
import time
from typing import Dict, List, Optional, Sequence

SEED = 10
NORTH_STAR = {"n": 64, "rf": 64, "f": 21, "quorum": 43, "target_ops_s": 100_000}


def _host_warmup(seconds: float = 8.0) -> None:
    """Spin every core for a few seconds before the timed sequence: this
    container's effective CPU speed ramps ~3x over the first ~10 s of
    sustained load (burst scheduling / frequency ramp, measured r10), so
    an unwarmed first leg measures the slow ramp, not the system."""
    if os.environ.get("MOCHI_BENCH_SMOKE") == "1":
        return  # smoke is a harness-rot pass; its numbers are meaningless
    import subprocess
    import sys as _sys

    body = (
        "import time\nt = time.perf_counter()\n"
        f"while time.perf_counter() - t < {seconds}:\n"
        "    x = sum(i * i for i in range(1000))\n"
    )
    procs = [
        subprocess.Popen([_sys.executable, "-c", body])
        for _ in range(os.cpu_count() or 1)
    ]
    for p in procs:
        p.wait()


def _crypto_microbench(iters: int = 64) -> Dict[str, float]:
    """Fresh-input host Ed25519 verify/sign cost (us/op) on THIS host —
    the crypto constants of the refit, measured in the same run they
    parameterize (lru caches defeated by distinct messages)."""
    from mochi_tpu.crypto import keys

    kp = keys.generate_keypair()
    msgs = [b"c8-%d" % i for i in range(iters)]
    sigs = [kp.sign(m) for m in msgs]
    t0 = time.perf_counter()
    for m, s in zip(msgs, sigs):
        keys.verify(kp.public_key, m, s)
    verify_us = (time.perf_counter() - t0) / iters * 1e6
    fresh = [b"c8s-%d" % i for i in range(iters)]
    t0 = time.perf_counter()
    for m in fresh:
        kp.sign(m)
    sign_us = (time.perf_counter() - t0) / iters * 1e6
    return {"verify_us": round(verify_us, 1), "sign_us": round(sign_us, 1)}


def _shard_local_keys(
    config, n_clients: int, keys_per_client: int, seed: int
) -> List[List[str]]:
    """Per-client key lists, every client's keys inside ONE token-ring
    replica set, clients dealt round-robin over the distinct shards.

    This is the shard-aware batching shape the L2 layer rewards: a
    client's batched PUT touches exactly its shard's rf replicas (one
    certificate, quorum MultiGrant signatures REGARDLESS of batch size),
    while different clients' shards land on different replica subsets —
    so aggregate load spreads over every server and per-op cost amortizes
    identically in both postures.  Keys are found by probing the stable
    hash (a few sha512s per key), so the assignment is deterministic
    given the seed."""
    shards: List[frozenset] = []
    seen = set()
    for t in range(len(config.token_owners)):
        s = frozenset(config.replica_set_for_token(t))
        if s not in seen:
            seen.add(s)
            shards.append(s)
    out: List[List[str]] = []
    for ci in range(n_clients):
        target = shards[ci % len(shards)]
        keys: List[str] = []
        probe = 0
        while len(keys) < keys_per_client:
            k = f"c8-{seed}-{ci}-{probe}"
            probe += 1
            if frozenset(config.replica_set_for_key(k)) == target:
                keys.append(k)
        out.append(keys)
    return out


async def _drive_puts(
    clients, keys_by_client: List[List[str]], sweeps: int, ops_per_txn: int,
    on_timed_start=None,
) -> Dict:
    """The shared signed-PUT workload: every transaction is a batched PUT
    of ``ops_per_txn`` shard-local keys; warm sweep off the clock, then
    the timed sweeps.  Returns ops (PUTs)/wall/p50; callers add their
    posture's CPU view."""
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.utils.runtime import reset_gc_debt

    write_lat: List[float] = []

    def txns_for(ci: int, val: bytes):
        keys = keys_by_client[ci]
        for i in range(0, len(keys), ops_per_txn):
            tb = TransactionBuilder()
            for k in keys[i : i + ops_per_txn]:
                tb.write(k, val)
            yield tb.build()

    # Warm off the clock: sessions, connections, first-contact key
    # material, and the keys' first certificates.
    async def warm(ci: int):
        for txn in txns_for(ci, b"warm"):
            await clients[ci].execute_write_transaction(txn)

    await asyncio.gather(*[warm(i) for i in range(len(clients))])
    reset_gc_debt()  # GC over the live graph must not land in the window
    if on_timed_start is not None:
        on_timed_start()  # CPU baselines read here exclude the warm phase

    async def worker(ci: int):
        client = clients[ci]
        for s in range(sweeps):
            val = b"v%d" % s
            for txn in txns_for(ci, val):
                t0 = time.perf_counter()
                await client.execute_write_transaction(txn)
                write_lat.append(time.perf_counter() - t0)

    t0 = time.perf_counter()
    await asyncio.gather(*[worker(i) for i in range(len(clients))])
    wall = time.perf_counter() - t0
    ops = sum(len(k) for k in keys_by_client) * sweeps
    write_lat.sort()
    return {
        "ops": ops,
        "txns": len(write_lat),
        "ops_per_txn": ops_per_txn,
        "wall_s": round(wall, 3),
        "put_ops_s": round(ops / wall, 1),
        "txn_p50_ms": round(write_lat[len(write_lat) // 2] * 1e3, 2)
        if write_lat
        else None,
    }


async def _single_process_leg(
    n_servers: int, rf: int, n_clients: int, keys_per_client: int, sweeps: int,
    ops_per_txn: int, seed: int = SEED,
) -> Dict:
    """The historical posture every published cluster number ran in: ONE
    process, one event loop, replicas AND clients time-sliced together
    (VirtualCluster).  This is the A/B baseline the scale-out acceptance
    is judged against."""
    from mochi_tpu.testing import VirtualCluster

    self0 = time.process_time()
    async with VirtualCluster(n_servers, rf=rf) as vc:
        clients = [vc.client(timeout_s=20.0) for _ in range(n_clients)]
        keys = _shard_local_keys(vc.config, n_clients, keys_per_client, seed)
        rec = await _drive_puts(clients, keys, sweeps, ops_per_txn)
    rec.update(
        {
            "posture": "single-process (VirtualCluster: replicas + clients "
            "on one event loop)",
            "processes": 0,  # ladder x-axis: 0 = everything in this process
            "process_cpu_s": round(time.process_time() - self0, 3),
        }
    )
    return rec


async def _put_leg(
    n_processes: int,
    n_servers: int,
    rf: int,
    n_clients: int,
    keys_per_client: int,
    sweeps: int,
    ops_per_txn: int,
    seed: int = SEED,
    verifier: str = "cpu",
) -> Dict:
    """One process-posture ladder leg: boot the cluster at ``n_processes``
    server processes, drive signed PUTs from this (client) process, return
    aggregate ops/s + per-process CPU over the timed window.

    ``verifier="cpu"`` (ladder default) keeps verification inline on each
    replica's core — the best-aggregate deployment on a small host.
    ``verifier="service"`` adds the shared memoizing sidecar process (the
    production verifier-pool topology): the refit legs use it because its
    CPU split measures the offload model's constants directly — replica
    processes then carry base+sign (+RPC client cost, real in the sidecar
    posture), the service carries the memoized unique-signature work."""
    from mochi_tpu.testing import ProcessCluster

    async with ProcessCluster(
        n_servers=n_servers, rf=rf, n_processes=n_processes, verifier=verifier,
        pin_cores=True,
    ) as pc:
        clients = [pc.client(timeout_s=20.0) for _ in range(n_clients)]
        keys = _shard_local_keys(pc.config, n_clients, keys_per_client, seed)
        window = {}

        def mark():  # CPU baseline at the timed window's start, post-warm
            window["cpu0"] = pc.cpu_seconds()
            window["self0"] = time.process_time()

        rec = await _drive_puts(clients, keys, sweeps, ops_per_txn, on_timed_start=mark)
        cpu0, self0 = window["cpu0"], window["self0"]
        cpu1 = pc.cpu_seconds()
        client_cpu = time.process_time() - self0
        pc.check_alive()
    txns = rec["txns"]
    deltas = {k: round(cpu1[k] - cpu0.get(k, 0.0), 3) for k in cpu1}
    service_cpu = deltas.pop("verifier-service", None)
    rec.update(
        {
            "posture": f"process (client process + server processes, {verifier})",
            "processes": n_processes,
            "verifier": verifier,
            "replica_cpu_s": deltas,
            "replica_cpu_s_total": round(sum(deltas.values()), 3),
            "replica_cpu_us_per_txn_cluster": round(
                sum(deltas.values()) / txns * 1e6, 1
            ),
            "client_cpu_s": round(client_cpu, 3),
            "client_cpu_us_per_txn": round(client_cpu / txns * 1e6, 1),
        }
    )
    if service_cpu is not None:
        rec["service_cpu_s"] = service_cpu
        rec["service_cpu_us_per_txn"] = round(service_cpu / txns * 1e6, 1)
    return rec


def _refit(
    ladder: List[Dict],
    ab: Dict,
    crypto: Dict[str, float],
    rf: int,
    quorum: int,
    ab_n: int,
) -> Dict:
    """Re-derive the 100k-ops/s scaling model with every constant from
    THIS run (the r10 acceptance: no inherited anchors on the host side).

    The measured deployment is the sidecar posture: replica processes
    keep base protocol work + their own grant SIGN on-core, and all grant
    VERIFICATION rides the shared memoizing service — so the ladder's CPU
    deltas measure the two model constants directly:

    * ``replica_cpu_us_per_txn_cluster`` (service posture) = rf x
      (base + sign), with no verify inside — the per-replica core cost
      the n=64 projection scales;
    * ``service_cpu_us_per_txn`` = the memoized unique-signature work of
      one transaction (expected ~quorum verifies + RPC framing; the
      measured/verify_us ratio is the in-record memoization evidence).

    The n=64 projection keeps the replica base per-process (each replica
    still handles two requests per txn; the certificate's size growth
    with quorum is NOT modeled — the projection is a floor and says so),
    swaps in the n=64 quorum for verification demand, and de-rates ideal
    cores by the parallel efficiency this ladder actually measured.
    """
    max_legs = [r for r in ladder if r.get("processes") == ab_n]
    t_cluster_us = statistics.median(
        r["replica_cpu_us_per_txn_cluster"] for r in max_legs
    )
    service_us = statistics.median(r["service_cpu_us_per_txn"] for r in max_legs)
    replica_us = t_cluster_us / rf  # base + sign, verify offloaded
    base_us_per_replica = max(0.0, replica_us - crypto["sign_us"])
    ns = NORTH_STAR
    # Parallel efficiency: measured aggregate speedup over the ideal —
    # the process posture can use every host core where the single-process
    # posture had one, so ideal = host cores / 1.
    ideal_speedup = min(ab_n + 1, os.cpu_count() or (ab_n + 1))
    efficiency = min(1.0, ab["median_speedup"] / ideal_speedup)

    def cores_for(us: float) -> Dict[str, float]:
        cluster_us = ns["rf"] * us
        ideal = ns["target_ops_s"] * cluster_us / 1e6
        return {
            "replica_us_per_txn_n64_floor": round(us, 1),
            "ideal_replica_cores_at_100k": round(ideal, 0),
            "replica_cores_at_100k_derated": round(
                ideal / max(efficiency, 1e-9), 0
            ),
        }

    # Posture A — verifier-offload (MEASURED here; roadmap items 1+3
    # composed): replica cores carry base + sign only.  Verification
    # demand is protocol arithmetic (unique sigs/s = rate x quorum); the
    # verification pool is sized in host cores from THIS run's verify_us,
    # or in chips from the r04 capture — that chip rate is the single
    # inherited anchor and is flagged as such.
    offload = cores_for(replica_us)
    offload["device_unique_sigs_per_s_at_100k"] = (
        ns["target_ops_s"] * ns["quorum"]
    )
    offload["verify_pool_host_cores_at_100k"] = round(
        ns["target_ops_s"] * ns["quorum"] * crypto["verify_us"] / 1e6, 0
    )
    offload["verify_pool_chip_anchor"] = (
        "chip rate NOT re-measurable on this host: 105,099.5 ladder "
        "sigs/s/chip (r04 witnessed capture) x comb 2.474x (r07 A/B) "
        "-> ~17-41 chips replaces the host verify pool; every other "
        "constant in this refit is r10-measured"
    )
    # Posture B — host-inline verify (no sidecar): each replica pays
    # quorum-1 foreign grant verifies on its own core (own grant is the
    # _own_grant_sigs byte-compare), and the cluster re-verifies each
    # signature at every replica (no cross-process memo).
    inline = cores_for(
        base_us_per_replica
        + crypto["sign_us"]
        + (ns["quorum"] - 1) * crypto["verify_us"]
    )
    return {
        "shape": dict(ns),
        "anchors": {
            "replica_cpu_us_per_txn_cluster_rf4": round(t_cluster_us, 1),
            "replica_us_per_txn": round(replica_us, 1),
            "base_us_per_replica": round(base_us_per_replica, 1),
            "service_cpu_us_per_txn_rf4": round(service_us, 1),
            "service_effective_verifies_per_txn": round(
                service_us / crypto["verify_us"], 2
            ),
            "host_verify_us": crypto["verify_us"],
            "host_sign_us": crypto["sign_us"],
            "parallel_efficiency_measured": round(efficiency, 3),
            "source": "this record (benchmarks/results_r10.json): every "
            "constant measured in-run, multi-process posture",
        },
        "formula": "replica cores = 100k txn/s x rf x replica_us / 1e6 / "
        "efficiency; verify pool = 100k x quorum x verify_us / 1e6 host "
        "cores (or the flagged chip anchor); inline posture adds "
        "(quorum-1) x verify_us to replica_us instead",
        "posture_verifier_offload_measured": offload,
        "posture_host_inline": inline,
        "limitation": "certificate size grows with quorum (43 grants ~9.8KB "
        "at n=64): codec/framing growth is not in the base constant, so "
        "replica-core projections are floors",
    }


async def _interleaved_ab(
    n_servers: int,
    rf: int,
    ab_n: int,
    n_clients: int,
    keys_per_client: int,
    rounds: int,
    ops_per_txn: int,
) -> Dict:
    """The acceptance A/B at second-scale pairing: BOTH postures boot once
    and stay up — the single-process VirtualCluster (in this process) and
    the N=ab_n ProcessCluster — then timed one-sweep chunks alternate
    between them, order flipping every round.  Only one posture carries
    traffic at a time (the other's processes sleep at zero CPU), so each
    per-round ratio compares the two postures inside the same ~2-second
    host window — the separated-leg design measured this container's
    minute-scale tenancy swings (±2x) instead of the architecture."""
    from mochi_tpu.testing import ProcessCluster, VirtualCluster
    from mochi_tpu.utils.runtime import reset_gc_debt

    async with VirtualCluster(n_servers, rf=rf) as vc:
        async with ProcessCluster(
            n_servers=n_servers, rf=rf, n_processes=ab_n, pin_cores=True
        ) as pc:
            vc_clients = [vc.client(timeout_s=20.0) for _ in range(n_clients)]
            pc_clients = [pc.client(timeout_s=20.0) for _ in range(n_clients)]
            vc_keys = _shard_local_keys(vc.config, n_clients, keys_per_client, SEED)
            pc_keys = _shard_local_keys(
                pc.config, n_clients, keys_per_client, SEED + 1
            )

            all_cores = (
                os.sched_getaffinity(0) if hasattr(os, "sched_getaffinity") else None
            )
            # ProcessCluster pinned its server processes to cores 0..ab_n-1;
            # the complement is the driver's territory during process
            # chunks (a floating driver preempts the pinned replicas —
            # the separation a real deployment gets from distinct hosts).
            driver_cores = (
                {c for c in all_cores if c >= ab_n} or all_cores
                if all_cores
                else None
            )

            async def chunk(clients, keys_by_client, val: bytes, pin=None) -> float:
                async def worker(ci: int):
                    from mochi_tpu.client.txn import TransactionBuilder

                    keys = keys_by_client[ci]
                    for i in range(0, len(keys), ops_per_txn):
                        tb = TransactionBuilder()
                        for k in keys[i : i + ops_per_txn]:
                            tb.write(k, val)
                        await clients[ci].execute_write_transaction(tb.build())

                if pin:
                    try:
                        os.sched_setaffinity(0, pin)
                    except OSError:
                        pass
                try:
                    t0 = time.perf_counter()
                    await asyncio.gather(*[worker(i) for i in range(n_clients)])
                    ops = sum(len(k) for k in keys_by_client)
                    return ops / (time.perf_counter() - t0)
                finally:
                    if pin and all_cores:
                        try:
                            os.sched_setaffinity(0, all_cores)
                        except OSError:
                            pass

            # Warm both postures off the clock (sessions, certificates).
            await chunk(vc_clients, vc_keys, b"warm")
            await chunk(pc_clients, pc_keys, b"warm")
            reset_gc_debt()
            rows = []
            for r in range(rounds):
                single_first = r % 2 == 0
                val = b"v%d" % r
                if single_first:
                    s = await chunk(vc_clients, vc_keys, val)
                    p = await chunk(pc_clients, pc_keys, val, pin=driver_cores)
                else:
                    p = await chunk(pc_clients, pc_keys, val, pin=driver_cores)
                    s = await chunk(vc_clients, vc_keys, val)
                rows.append(
                    {
                        "order": "single-first" if single_first else "max-first",
                        "single_process_ops_s": round(s, 1),
                        "max_ops_s": round(p, 1),
                        "speedup": round(p / s, 4),
                    }
                )
                pc.check_alive()
    speedups = sorted(x["speedup"] for x in rows)
    return {
        "rounds": rounds,
        "per_round": rows,
        "median_speedup": round(statistics.median(speedups), 4),
        "min_speedup": speedups[0],
        "acceptance_ge_1p6": statistics.median(speedups) >= 1.6,
    }


def run(
    n_servers: int = 6,
    rf: int = 4,
    process_counts: Optional[Sequence[int]] = None,
    n_clients: int = 32,
    keys_per_client: int = 32,
    sweeps: int = 2,
    pairs: int = 7,
    ops_per_txn: int = 32,
) -> Dict:
    """The ladder + paired A/B.  ``process_counts`` defaults to every
    power of two up to min(host cores, n_servers), plus both endpoints —
    on a 2-core host that is (1, 2); on a 64-core host (1, 2, 4, ...)."""
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()
    cores = os.cpu_count() or 1
    max_local = min(cores, n_servers)
    if process_counts is None:
        counts = sorted(
            {1, max_local}
            | {n for n in (2, 4, 8, 16, 32) if n < max_local}
        )
    else:
        counts = sorted(set(int(c) for c in process_counts))
    if len(counts) < 2:
        counts = sorted(set(counts) | {1})
    # The acceptance rung: cores-1 server processes — the client driver
    # needs the remaining core.  Rungs beyond it oversubscribe the host
    # (3+ runnable processes on `cores` cores; the driver preempts pinned
    # replicas) and are kept in the curve as oversubscription evidence,
    # not as the headline.  On a big host this is the process-per-core
    # posture (63 replica processes + driver on 64 cores).
    ab_n = max(1, min(cores - 1, n_servers))
    if ab_n not in counts:
        # The acceptance rung must be MEASURED: add it to the ladder
        # rather than silently degrading to the 1-process rung (which
        # would make the headline A/B measure no scale-out at all on
        # hosts whose cores-1 is not a power of two).
        counts = sorted(set(counts) | {ab_n})
    _host_warmup()

    # ---- acceptance A/B: interleaved one-sweep chunks, both postures up —
    # second-scale pairing (the separated-leg design measured this
    # container's minute-scale tenancy swings, not the architecture).
    ab = asyncio.run(
        _interleaved_ab(
            n_servers, rf, ab_n, n_clients, keys_per_client, 2 * pairs,
            ops_per_txn,
        )
    )
    ab["posture"] = (
        "single-process posture (VirtualCluster, replicas+clients one "
        f"loop) vs N={ab_n} server processes + client process (acceptance "
        f"rung: cores-1; host has {cores} cores); both postures booted "
        "once, timed one-sweep chunks alternating between them with order "
        "flipping per round, same workload seed"
    )

    # ---- ladder curve + CPU constants: separated full legs per rung.
    ladder: List[Dict] = []
    for i in range(max(2, pairs // 2)):
        if i:
            _host_warmup(4.0)  # re-anchor the host's ramp state per round
        ladder.append(
            asyncio.run(
                _single_process_leg(
                    n_servers, rf, n_clients, keys_per_client, sweeps, ops_per_txn
                )
            )
        )
        for n in counts:
            ladder.append(
                asyncio.run(
                    _put_leg(
                        n, n_servers, rf, n_clients, keys_per_client, sweeps,
                        ops_per_txn,
                    )
                )
            )
    curve = []
    for n in [0] + counts:  # 0 = the single-process (VirtualCluster) rung
        runs = [r["put_ops_s"] for r in ladder if r["processes"] == n]
        if not runs:
            continue
        entry = {
            "processes": n,
            "posture": "single-process" if n == 0 else "process",
            "runs": len(runs),
            "put_ops_s_median": round(statistics.median(runs), 1),
            "put_ops_s_all": runs,
        }
        cpus = [
            r["replica_cpu_us_per_txn_cluster"]
            for r in ladder
            if r["processes"] == n and "replica_cpu_us_per_txn_cluster" in r
        ]
        if cpus:
            entry["replica_cpu_us_per_txn_cluster_median"] = round(
                statistics.median(cpus), 1
            )
        curve.append(entry)
    crypto = _crypto_microbench()
    q = 2 * ((rf - 1) // 3) + 1
    # Refit legs: the model's constants are per 1-op transaction (the
    # north-star shape) under the SIDECAR posture, whose process split
    # measures replica base+sign and memoized verify work separately —
    # run at N=max, off the A/B clock.
    refit_legs = [
        asyncio.run(
            _put_leg(
                ab_n, n_servers, rf, n_clients, keys_per_client, 1, 1,
                verifier="service",
            )
        )
        for _ in range(max(1, pairs // 2))
    ]
    rec = {
        "metric": "aggregate_signed_put_scaleout",
        "value": next(
            c["put_ops_s_median"] for c in curve if c["processes"] == ab_n
        ),
        "unit": "signed PUT ops/s (aggregate, max local process count)",
        "topology": {
            "n_servers": n_servers,
            "rf": rf,
            "f": (rf - 1) // 3,
            "quorum": q,
            "process_counts": counts,
            "host_cores": os.cpu_count(),
            "transport": "unix-domain sockets",
            "ladder_verifier": "inline cpu (native host engine) per replica "
            "— best-aggregate posture on a small host; the refit legs run "
            "the sidecar service posture to split the model's constants",
            "clients": n_clients,
            "keys_per_client": keys_per_client,
            "sweeps": sweeps,
            "ops_per_txn": ops_per_txn,
            "workload": "batched shard-local signed PUTs (each client's "
            "keys pin to one token-ring replica set; clients dealt over "
            "the distinct shards)",
            "workload_seed": SEED,
        },
        "ladder": curve,
        "single_vs_max_ab": ab,
        "host_crypto_us": crypto,
        "refit_legs": refit_legs,
        "scaling_refit": _refit(refit_legs, ab, crypto, rf, q, ab_n),
        "note": (
            "client drives from the parent process and shares the host's "
            "cores with the replica processes: the ladder measures the "
            "whole-deployment aggregate on this host, and the refit's "
            "efficiency constant carries that interference honestly "
            "instead of assuming dedicated cores"
        ),
    }
    return rec


if __name__ == "__main__":
    import json

    print(json.dumps(run(), indent=2))

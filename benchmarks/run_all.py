"""Run all five BASELINE.json benchmark configs; one JSON line each.

Usage: python -m benchmarks.run_all [config-number ...]
"""

from __future__ import annotations

import json
import sys


def main(argv) -> None:
    from . import (  # noqa: WPS347
        config1_cluster,
        config2_microbench,
        config3_ycsb,
        config4_viewchange,
        config5_multichip,
    )

    configs = {
        "1": config1_cluster,
        "2": config2_microbench,
        "3": config3_ycsb,
        "4": config4_viewchange,
        "5": config5_multichip,
    }
    wanted = argv or list(configs)
    for key in wanted:
        mod = configs[str(key)]
        try:
            rec = mod.run()
        except Exception as exc:  # keep the sweep going; record the failure
            rec = {"metric": mod.__name__, "error": f"{type(exc).__name__}: {exc}"}
        rec["config"] = str(key)
        print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main(sys.argv[1:])

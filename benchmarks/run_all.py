"""Run all BASELINE.json benchmark configs; one JSON line each.

Usage:
    python -m benchmarks.run_all [config-number ...]
    python -m benchmarks.run_all --publish    # also commit artifacts:
        writes benchmarks/results_r<N>.json and fills BASELINE.json
        "published" (VERDICT r1 task 6)

Each config runs in a FRESH subprocess with the persistent XLA compile
cache wired in, so one wedged config can't poison the rest and repeat runs
skip the 20-60 s per-bucket compiles.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CACHE_DIR = os.path.join(_REPO, ".jax_cache")

CONFIG_NAMES = {
    "1": "config1_cluster",
    "2": "config2_microbench",
    "3": "config3_ycsb",
    "4": "config4_viewchange",
    "5": "config5_multichip",
    "6": "config6_bigcluster",
    "7": "config7_wan",
    "8": "config8_scaleout",
    "9": "config9_overload",
    "10": "config10_byzantine",
    "11": "config11_byzclient",
    "12": "config12_durability",
    "13": "config13_scenario",
    "14": "config14_pagedstore",
}

# --smoke: tiny-count kwargs per config — a seconds-scale pass whose only
# purpose is catching benchmark-harness rot at PR time (import errors,
# schema drift, APIs the benchmarks call that the tree no longer has).
# Wired into tier-1 as tests/test_bench_smoke.py; numbers produced under
# these counts are MEANINGLESS and are never published (main() refuses
# --smoke --publish).
SMOKE_KWARGS = {
    "1": dict(n_clients=2, keys_per_client=2, sweeps=1, verifier="cpu"),
    "2": dict(batch_sizes=(256,), iters=1, big_batch=0),
    "3": dict(n=4, f=1, n_ops=64, batch=256),
    "4": dict(n=4, f=1, rounds=1, wan_rounds=1, wan_clients=1, wan_keys=2),
    "5": dict(batch_per_device=256, n_groups=8, iters=1),
    "6": dict(writers=2, writes_per_writer=1, verifier="cpu", shapes=(4,)),
    "7": dict(n_clients=2, keys_per_client=2, sweeps=1, ab_pairs=0),
    # 2 real server processes, 1 interleaved pair: exercises the whole
    # ProcessCluster spawn/READY/drain surface in seconds (the children
    # run the REAL engines — the parent's smoke stubs don't cross the
    # process boundary, and don't need to: child boot cost is import, not
    # XLA compiles, with the inline cpu verifier).
    "8": dict(
        n_servers=4, rf=4, process_counts=(1, 2), n_clients=2,
        keys_per_client=4, sweeps=1, pairs=1, ops_per_txn=2,
    ),
    # the whole open-loop harness in seconds: a handful of sessions, one
    # short knee rung + one overload leg, invariants + table bounds — the
    # numbers are noise; the surface (ramp, generator, knee pick, record
    # schema) is what smoke pins
    "9": dict(
        n_sessions=24, leg_s=0.8, probe_s=0.5, probe_workers=8,
        ladder=(0.6, 1.0), overload_factors=(1.5,), rtt_ms=2.0,
        jitter_ms=0.5, timeout_s=2.0, ramp_batch=12,
    ),
    # one honest + one adversarial leg end-to-end (live ByzantineReplica,
    # invariant checker, evidence aggregation): the whole config-10
    # harness surface in seconds
    "10": dict(
        n_clients=1, keys_per_client=2, sweeps=1, attacks=("silent",),
        timeout_s=1.0, loss_attacks=(), trim_ab=False,
    ),
    # one honest + one byzantine-CLIENT leg + a tiny wedge duel: the whole
    # config-11 harness surface (ByzantineClient driver, defense knobs,
    # wedge probe, record schema) in seconds — a 24-seed sweep can't
    # actually wedge, so the probe numbers are noise by construction
    "11": dict(
        n_clients=1, keys_per_client=2, sweeps=1, attacks=("withhold",),
        timeout_s=1.0, ttl_ms=300.0, wedge_trials=1, wedge_ttl_ms=300.0,
        wedge_deadline_s=2.0, wedge_seeds=24, wedge_seeds_cost=16,
    ),
    # the whole durability surface in seconds: one real-process SIGKILL ->
    # restart -> readback pass (the children run the real engines), a
    # 2-point recovery curve, all three tamper-conviction legs, and one
    # fsync policy vs the memory baseline — curve/latency numbers at these
    # counts are noise; the record schema + acceptance booleans are what
    # smoke pins
    "12": dict(
        min_acked=6, curve_sizes=(6, 12), gap_writes=2,
        fsync_policies=("group",), fsync_writes=6, timeout_s=4.0,
    ),
    # the whole scenario-engine surface in seconds: 2 drawn seeds soaked
    # in-process, the ×2 determinism probe on a cheap seed, and the full
    # injected-violation detect→replay→minimize arc — soak numbers at
    # this count are meaningless; the generator/engine/minimizer APIs and
    # the record schema are what smoke pins
    "13": dict(
        count=2, start=0, workers=1, determinism_seed=4,
        determinism_runs=2, violation_seed=4,
    ),
    # the whole paged-engine surface in seconds: one tiny direct-engine
    # rung per engine (load -> flush -> fault-in reads -> cold recovery),
    # one real-process SIGKILL -> restart -> readback pass on the paged
    # engine, and the page-tamper conviction leg — curve numbers at these
    # counts are noise; the record schema + acceptance booleans are what
    # smoke pins
    "14": dict(
        rungs=(64,), ab_rungs=(64,), value_bytes=64, reads=32,
        min_acked=6, timeout_s=4.0,
    ),
}


def _run_child(key: str) -> None:
    import jax

    cache_dir = _CACHE_DIR
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # The axon TPU plugin force-sets jax_platforms via sitecustomize;
        # honor an explicit CPU request by overriding the config knob.
        jax.config.update("jax_platforms", "cpu")
        # CPU backend: host-fingerprint-keyed cache (foreign-host XLA:CPU
        # AOT code can SIGILL — utils/runtime.host_cache_dir docstring)
        from mochi_tpu.utils.runtime import host_cache_dir

        cache_dir = host_cache_dir(_CACHE_DIR)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    import importlib

    smoke = os.environ.get("MOCHI_BENCH_SMOKE") == "1"
    if smoke:
        # tiny-count harness-rot pass: never publish full-evidence legs
        os.environ["MOCHI_BENCH_FULL"] = ""
        # Tracing rides every smoke leg (round 15) at sample rate 1.0 —
        # FORCED, not defaulted, and full-rate rather than the 5% default:
        # at smoke's tiny counts a 5% head sample could legitimately mint
        # zero traces, and an inherited MOCHI_TRACE_SAMPLE=0 must not
        # silently hollow out the probe.  The trace_summary stamp below is
        # the tier-1 check that the tracer plumbing still reaches the
        # cluster paths each config drives (tests/test_bench_smoke.py
        # asserts spans were actually recorded on the cluster configs).
        os.environ["MOCHI_TRACE_SAMPLE"] = "1.0"
    else:
        os.environ.setdefault("MOCHI_BENCH_FULL", "1")  # battery: full evidence
    mod = importlib.import_module(f"benchmarks.{CONFIG_NAMES[key]}")
    if smoke:
        # Two levers make "all 7 configs in seconds" possible on a fresh
        # host: (1) jax.disable_jit() — jitted wrappers run their python
        # bodies, so nothing pays an XLA:CPU compile; (2) the DEVICE
        # Ed25519 program (curve.verify_prepared*) is stubbed to all-true
        # — its eager evaluation is a ~100k-dispatch curve ladder, and
        # smoke is a harness-rot detector (imports, prepare packing,
        # quorum plumbing, record schema), not a verdict test: the real
        # engines are differentially tested in tier-1 proper
        # (tests/test_native_ed25519.py, test_crypto_jax.py).
        import jax.numpy as jnp

        from mochi_tpu.crypto import comb, curve

        def _stub_verify(*args, **kwargs):
            return jnp.ones((args[0].shape[0],), dtype=jnp.bool_)

        def _stub_comb(table_flat, key_idx, *args, **kwargs):
            return jnp.ones((key_idx.shape[0],), dtype=jnp.bool_)

        curve.verify_prepared = _stub_verify
        curve.verify_prepared_packed = _stub_verify
        comb.verify_comb_prepared = _stub_comb
        comb._verify_comb_jit = _stub_comb  # the import-time jit wrapper
        with jax.disable_jit():
            rec = mod.run(**SMOKE_KWARGS.get(key, {}))
    else:
        rec = mod.run()
    rec["config"] = key
    # Host-crypto provenance on EVERY record (ISSUE 5 satellite): which
    # engine served host-side Ed25519 during this run — the difference
    # between a comparable write row and a ~20x-inflated one is no longer
    # a prose caveat.
    try:
        from mochi_tpu.crypto.keys import host_crypto_engine

        rec["host_crypto_engine"] = host_crypto_engine()
    except Exception:
        pass
    try:
        rec["platform"] = jax.devices()[0].platform
    except Exception:
        pass
    # Causal-tracing provenance on EVERY record (round 15): the aggregate
    # over this child's tracers — posture knobs plus span/trace counters.
    # Always a non-empty dict (the knobs are always known), so a missing
    # or empty key means the obs plumbing itself rotted — exactly what
    # tests/test_bench_smoke.py pins.
    try:
        from mochi_tpu.obs.trace import global_summary

        rec["trace_summary"] = global_summary()
    except Exception:
        pass
    print("RESULT_JSON " + json.dumps(rec), flush=True)


def run_one(key: str, timeout_s: float = 1500.0) -> dict:
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "benchmarks.run_all", "--child", key],
            cwd=_REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return {"config": key, "metric": CONFIG_NAMES[key], "error": "timeout"}
    out = proc.stdout.decode(errors="replace")
    for line in reversed(out.splitlines()):
        if line.startswith("RESULT_JSON "):
            return json.loads(line[len("RESULT_JSON "):])
    return {
        "config": key,
        "metric": CONFIG_NAMES[key],
        "error": f"rc={proc.returncode} tail={out[-800:]}",
    }


def _wire_taint_preflight() -> None:
    """Harness-rot pin (PR 16): --smoke runs in tier-1, so a fast-path PR
    that renames or bypasses a sanctioned verifier edge without updating
    the wire-taint registry fails HERE at PR time — the registry-rot
    finding (or a fresh unverified flow) turns the smoke leg red before
    any benchmark child spawns.  Same escape hatch as the standing-rules
    lint gate: MOCHI_SKIP_LINT=1 for forensic re-runs."""
    if os.environ.get("MOCHI_SKIP_LINT"):
        return
    sys.path.insert(0, _REPO)
    from mochi_tpu.analysis import core as analysis_core

    result = analysis_core.run(
        [os.path.join(_REPO, "mochi_tpu")], rules=["wire-taint"]
    )
    if not result.clean:
        for finding in result.new:
            print(" !", finding.render(), file=sys.stderr)
        print(
            f"--smoke: wire-taint pass failed ({len(result.new)} finding(s))"
            " — a fast path must register its verifier edge "
            "(mochi_tpu/analysis/wire_taint.py; MOCHI_SKIP_LINT=1 overrides)",
            file=sys.stderr,
        )
        sys.exit(4)


def main(argv) -> None:
    if argv and argv[0] == "--child":
        _run_child(argv[1])
        return
    publish = "--publish" in argv
    if "--smoke" in argv:
        if publish:
            print("--smoke numbers are meaningless; refusing --publish",
                  file=sys.stderr)
            sys.exit(2)
        os.environ["MOCHI_BENCH_SMOKE"] = "1"  # children read it
        argv = [a for a in argv if a != "--smoke"]
        _wire_taint_preflight()
    # --require-tpu: exit 3 unless every config ran on the chip.  The
    # battery banks this step as done-for-the-round on rc==0; without the
    # flag a CPU-fallback run exits 0 (the publish guard only skips
    # overwriting TPU records, it does not fail the run) and the real TPU
    # publish never happens (code-review r4 finding).
    require_tpu = "--require-tpu" in argv
    wanted = [
        a for a in argv if a not in ("--publish", "--require-tpu")
    ] or list(CONFIG_NAMES)
    results = []
    for key in wanted:
        rec = run_one(str(key))
        results.append(rec)
        print(json.dumps(rec), flush=True)
    if publish:
        round_n = os.environ.get("MOCHI_BENCH_ROUND", "02")
        out_path = os.path.join(_REPO, "benchmarks", f"results_r{round_n}.json")
        # merge by config key — a partial invocation (e.g. "run_all 1 2")
        # must not clobber the other configs' records in the results file
        merged = {}
        if os.path.exists(out_path):
            try:
                with open(out_path) as fh:
                    merged = {r.get("config"): r for r in json.load(fh)}
            except (ValueError, OSError):
                merged = {}
        merged.update({r.get("config"): r for r in results})
        with open(out_path, "w") as fh:
            json.dump(
                [merged[k] for k in sorted(merged, key=str)], fh, indent=2
            )
        baseline_path = os.path.join(_REPO, "BASELINE.json")
        with open(baseline_path) as fh:
            baseline = json.load(fh)
        for skipped in merge_published(baseline, results, round_n):
            print(skipped, file=sys.stderr)
        with open(baseline_path, "w") as fh:
            json.dump(baseline, fh, indent=2)
        print(f"published -> {out_path} and BASELINE.json", file=sys.stderr)
    if require_tpu:
        bad = [r.get("config") for r in results if r.get("platform") != "tpu"]
        if bad:
            print(
                f"--require-tpu: configs {bad} did not run on TPU "
                "(fallback or error); failing so the battery does not bank "
                "this step", file=sys.stderr,
            )
            sys.exit(3)


def merge_published(baseline: dict, results: list, round_n: str) -> list:
    """Merge run records into ``baseline["published"]``; returns skip notes.

    Merge, don't replace: re-publishing one config must not erase the
    others' entries.  Two guards (round-4 lesson — the scoreboard is what
    the driver and judge read):

    * an errored run never overwrites a good entry;
    * a CPU-fallback run never overwrites a live TPU capture — configs
      2-5 are device benchmarks and the honest best is the committed TPU
      number until a fresh chip run replaces it.
    """
    skipped = []
    published = baseline.setdefault("published", {})
    for r in results:
        prev = published.get(r["config"])
        if r.get("error") and prev and not prev.get("error"):
            skipped.append(
                f"config {r['config']}: errored run NOT published over "
                f"existing good entry"
            )
            continue
        if prev and prev.get("platform") == "tpu" and r.get("platform") != "tpu":
            skipped.append(
                f"config {r['config']}: CPU-fallback run NOT published "
                f"over TPU capture"
            )
            continue
        entry = {
            k: v
            for k, v in r.items()
            if k in ("metric", "value", "unit", "vs_baseline", "error",
                     "platform", "host_crypto_engine",
                     "read_p50_ms", "write_p50_ms")
            and v is not None
        }
        entry["source"] = f"benchmarks/results_r{round_n}.json"
        published[r["config"]] = entry
    return skipped


if __name__ == "__main__":
    main(sys.argv[1:])

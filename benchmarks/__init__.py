"""Benchmark harness for the five BASELINE.json configs.

Each module exposes ``run(**kwargs) -> dict`` returning one JSON-able record
``{"metric", "value", "unit", ...}``; ``run_all.py`` drives them and prints
one JSON line per config.  The reference ships no benchmark harness at all —
its only numbers are the paper's WAN table (BASELINE.md); these harnesses
produce the new framework's measurements on the same workload shapes.
"""

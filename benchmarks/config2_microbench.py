"""Config 2: Ed25519 batch-verify microbench, 1k-64k msgs/batch.

Device throughput vs batch size, plus the same-host single-thread
CPU/OpenSSL baseline (the reference-analog BouncyCastle path) — the measured
denominator BASELINE.json's ">=100k ops/s, <5% CPU" targets need
(SURVEY.md §7 "no reference crypto numbers exist").
"""

from __future__ import annotations

import time
from typing import Dict, List


def run(batch_sizes=(1024, 2048, 4096, 8192), iters: int = 3,
        big_batch: int = 65536) -> Dict:
    import jax
    import numpy as np

    from mochi_tpu.crypto import batch_verify, keys
    from mochi_tpu.crypto.curve import verify_prepared
    from mochi_tpu.verifier.spi import VerifyItem

    dev = jax.devices()[0]
    fn = jax.jit(verify_prepared)

    # one keypair, distinct messages (hashing happens host-side in prepare)
    kp = keys.generate_keypair()

    points: List[Dict] = []
    items: List[VerifyItem] = []
    for b in batch_sizes:
        items = []
        for i in range(b):
            msg = b"micro %d" % i
            items.append(VerifyItem(kp.public_key, msg, kp.sign(msg)))
        prep = batch_verify.prepare(items)
        args = tuple(jax.device_put(a, dev) for a in prep[:6])
        out = jax.block_until_ready(fn(*args))  # compile + warmup
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            # np.asarray forces D2H readback — the only reliable sync through
            # the axon relay (block_until_ready can return pre-completion)
            out = np.asarray(fn(*args))
            best = min(best, time.perf_counter() - t0)
        assert out.all()
        points.append(
            {"batch": b, "sigs_per_sec": round(b / best, 1), "ms": round(best * 1e3, 2)}
        )

    # 64k msgs via the production path (verify_batch chunks at the
    # MAX_BUCKET VMEM peak with every chunk launched before any readback —
    # raw 16k+/64k programs spill VMEM and regress, which is why the
    # chunking exists; BASELINE config 2 range still covered).
    big = big_batch
    if not big:  # --smoke harness pass: skip the 64k production-path leg
        return _record(points, items, keys, batch_sizes)
    items64 = []
    for i in range(big):
        msg = b"micro64k %d" % i
        items64.append(VerifyItem(kp.public_key, msg, kp.sign(msg)))
    # warm the production chunk bucket (the packed-scalar program is a
    # DIFFERENT executable from the bit-tensor one warmed above — without
    # this the 64k row times a cold compile)
    batch_verify.verify_batch(items64[: batch_verify.MAX_BUCKET], device=dev)
    t0 = time.perf_counter()
    bitmap = batch_verify.verify_batch(items64, device=dev)
    chunked_s = time.perf_counter() - t0
    assert all(bitmap)
    points.append(
        {
            "batch": big,
            "sigs_per_sec": round(big / chunked_s, 1),
            "ms": round(chunked_s * 1e3, 2),
            "path": "verify_batch (chunked, incl. host prepare)",
        }
    )

    return _record(points, items, keys, batch_sizes)


def _record(points, items, keys, batch_sizes) -> Dict:
    # CPU baseline (sampled)
    sample = items[:512]
    t0 = time.perf_counter()
    for it in sample:
        keys.verify(it.public_key, it.message, it.signature)
    cpu_rate = len(sample) / (time.perf_counter() - t0)

    peak = max(p["sigs_per_sec"] for p in points)
    return {
        "metric": "ed25519_batch_verify_peak_throughput",
        "value": peak,
        "unit": "sigs/sec",
        "vs_baseline": round(peak / cpu_rate, 2),
        "cpu_openssl_sigs_per_sec": round(cpu_rate, 1),
        "points": points,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run()))

"""Config 10: Byzantine replica harness — the published cost of each attack.

Reruns the config-7 WAN shape (5-replica rf=4 f=1 signed cluster under
``NetSim.mesh(seed=8, rtt_ms=13, jitter_ms=1)``) with ONE live adversary in
the serving path (``testing/byzantine.py``), once per attack strategy, plus
an in-run honest leg as the paired baseline.  Three artifacts per attack:

* **latency cost** — read/write p50/p95/p999 and the ratio vs the in-run
  honest leg (and the committed r09 honest capture as the external anchor);
* **safety verdict** — ``testing/invariants.InvariantChecker`` samples the
  honest replicas' stores throughout (certificate agreement + epoch
  monotonicity) and re-reads every acked write through a real client at
  leg end; its report is embedded in-record (``invariants.ok`` must be
  true for every attack — that IS the paper's safety claim under f=1);
* **detection evidence** — what the honest side *noticed*: per-peer
  suspicion on the clients (bad grants, outvoted answers, straggler
  timeouts) and the replicas' Byzantine ledgers (proven equivocations,
  bad-grant attribution).

The equivocate leg ends with a deterministic evidence-presentation probe
(two same-seed Write1s for conflicting transactions against the adversary,
both resulting grants shown to every honest replica) — equivocation is only
*provable* when both sides of the lie meet, and the probe guarantees the
published record demonstrates the detector, not just the attack.

The storm leg additionally drives a netsim partition of one HONEST replica
during the timed phase (view-change-storm shape: adversarial refusals +
transient quorum loss + nudge floods).
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import time
from typing import Dict, List, Optional

from .config7_wan import JITTER_MS, RTT_MS, SEED, _pcts

ATTACKS = ("equivocate", "forge-cert", "stale-replay", "silent", "storm")
BYZ_SID = "server-1"

# The committed honest config-7 capture (write-path round): the external
# anchor for "what does this cluster cost with NO adversary".  Shape caveat:
# r09 ran 5 clients x 40 keys; this config's legs are smaller (6 legs must
# fit one battery child), so the IN-RUN honest leg is the paired baseline
# and r09 is provenance.
R09_HONEST = {
    "read_ms": {"p50": 18.5, "p95": 21.54, "p999": 24.42},
    "write_ms": {"p50": 46.07, "p95": 57.65, "p999": 106.41},
    "source": "benchmarks/results_r09.json (config 7, honest cluster)",
}


async def _present_equivocation_evidence(vc, client) -> None:
    """Deterministically extract both sides of an equivocation from the
    adversary (same seed, two transactions, one key) and present each —
    validly signed — to every honest replica, so the grant ledgers can
    convict (replica._note_grant_evidence)."""
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.protocol import (
        Write1OkFromServer,
        Write1ToServer,
        Write2ToServer,
        WriteCertificate,
        transaction_hash,
    )

    txns = [
        TransactionBuilder().write("byz-evidence", b"side-a").build(),
        TransactionBuilder().write("byz-evidence", b"side-b").build(),
    ]
    byz_info = vc.config.servers[BYZ_SID]
    grants = []
    for i, txn in enumerate(txns):
        blind = client._write1_transaction(txn)
        env = client._envelope(
            Write1ToServer(client.client_id, blind, 77, transaction_hash(txn)),
            f"evidence-w1-{i}",
        )
        try:
            resp = await client.pool.send_and_receive(byz_info, env, timeout_s=2.0)
        except Exception:
            return  # a silent/partitioned adversary yields no evidence
        if isinstance(resp.payload, Write1OkFromServer):
            grants.append((txn, resp.payload.multi_grant))
    if len(grants) < 2:
        return
    for sid, info in sorted(vc.config.servers.items()):
        if sid == BYZ_SID:
            continue
        for i, (txn, mg) in enumerate(grants):
            env = client._envelope(
                Write2ToServer(WriteCertificate({BYZ_SID: mg}), txn),
                f"evidence-w2-{sid}-{i}",
            )
            try:
                await client.pool.send_and_receive(info, env, timeout_s=2.0)
            except Exception:
                pass  # the presentation is best-effort evidence delivery


async def _crash_recover_probe(vc, checker, storage_dir: str) -> Dict:
    """Round-16 durable posture (ROADMAP item 2 leftover), shared by
    configs 10 and 11: inside every adversarial leg — hostile replica or
    hostile coordinator — one HONEST replica additionally crashes and
    recovers WITH STATE through the round-14 storage engine: flush,
    freeze the live disk image (WAL, no shutdown snapshot: the crash
    shape), restart from it with verified replay, delta-resync the gap.
    The replay conviction counters land in-record (an adversary's
    validly-signed traffic must never make an honest replica's own log
    convict on recovery)."""
    import shutil

    victim = "server-2"  # honest in every leg of both configs
    old = vc.replica(victim)
    await old.storage.flush()
    src = os.path.join(storage_dir, victim)
    frozen = src + ".crash"
    shutil.copytree(src, frozen)

    def restore(sid: str) -> None:
        dst = os.path.join(storage_dir, sid)
        shutil.rmtree(dst)
        shutil.move(frozen, dst)

    t0 = time.perf_counter()
    fresh = await vc.restart_replica(victim, resync=True, before_boot=restore)
    recover_ms = (time.perf_counter() - t0) * 1e3
    checker.note_restart(fresh)
    report = fresh.storage.replay_report()
    return {
        "restarted": victim,
        "replay_entries": report["entries"],
        "replay_convicted": report["convicted"],
        "replay_ms": report["ms"],
        "recover_wall_ms": round(recover_ms, 1),
    }


def _durable_posture_summary(legs) -> Dict:
    """Roll the per-leg ``durability`` evidence into the record-level
    verdict (shared by configs 10 and 11)."""
    durable_legs = [
        leg for leg in legs if leg.get("durability") is not None
    ]
    return {
        "enabled": bool(durable_legs),
        "legs_recovered": len(durable_legs),
        # an adversary's traffic must never make an honest replica's own
        # WAL convict on recovery — and recovery must actually replay
        "recovery_convictions_zero_all_legs": all(
            leg["durability"]["replay_convicted"] == 0 for leg in durable_legs
        ),
        "replay_entries_min": min(
            (leg["durability"]["replay_entries"] for leg in durable_legs),
            default=0,
        ),
    }


async def _leg(
    attack: Optional[str],
    n_clients: int,
    keys_per_client: int,
    sweeps: int,
    timeout_s: float,
    drop: float = 0.0,
    trim_write1: bool = False,
    durable: bool = True,
) -> Dict:
    import tempfile

    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.netsim import NetSim
    from mochi_tpu.testing.invariants import InvariantChecker
    from mochi_tpu.testing.virtual_cluster import VirtualCluster
    from mochi_tpu.utils.runtime import reset_gc_debt

    sim = NetSim.mesh(seed=SEED, rtt_ms=RTT_MS, jitter_ms=JITTER_MS, drop=drop)
    byzantine = {BYZ_SID: attack} if attack else None
    storage_ctx = (
        tempfile.TemporaryDirectory() if durable else contextlib.nullcontext()
    )
    with storage_ctx as storage_dir:
      async with VirtualCluster(
          5, rf=4, netsim=sim, byzantine=byzantine, storage_dir=storage_dir
      ) as vc:
        checker = InvariantChecker(
            vc.honest_replicas(), [BYZ_SID] if attack else []
        )
        read_lat: List[float] = []
        write_lat: List[float] = []
        write_failures = 0
        read_failures = 0
        clients = []

        async def populate(ci: int):
            client = vc.client(timeout_s=timeout_s, trim_write1=trim_write1)
            clients.append(client)
            for k in range(keys_per_client):
                key = f"byz-{ci}-{k}"
                # app-level retry: on a lossy mesh a seed write can lose
                # two answer frames and fail its tally — the retry IS the
                # loss recovery (the timed workers count such failures;
                # the seed phase just needs the keys to exist)
                for attempt in range(4):
                    try:
                        await client.execute_write_transaction(
                            TransactionBuilder().write(key, b"seed").build()
                        )
                        break
                    except Exception:
                        if attempt == 3:
                            raise
                checker.record_ack(key, b"seed")

        await asyncio.gather(*[populate(i) for i in range(n_clients)])
        reset_gc_debt()  # same GC discipline as every committed WAN record
        checker.start(0.05)

        partition_task = None
        if attack == "storm":
            # View-change-storm shape: the adversary's refusals + nudge
            # floods PLUS a transient partition of one honest replica —
            # quorum dips to exactly the adversarial edge mid-phase.
            async def drive_partition():
                await asyncio.sleep(0.4)
                for ev in NetSim.partition("server-3", 0.0):
                    sim.apply_event(ev)
                await asyncio.sleep(0.5)
                for ev in NetSim.heal("server-3"):
                    sim.apply_event(ev)

            partition_task = asyncio.ensure_future(drive_partition())

        async def worker(ci: int):
            nonlocal write_failures, read_failures
            client = clients[ci]
            for s in range(sweeps):
                for k in range(keys_per_client):
                    key = f"byz-{ci}-{k}"
                    val = b"v%d" % s
                    t0 = time.perf_counter()
                    try:
                        await client.execute_write_transaction(
                            TransactionBuilder().write(key, val).build()
                        )
                    except Exception:
                        # liveness cost, counted honestly; safety is the
                        # checker's department.  The failed write's outcome
                        # is INDETERMINATE (under loss its Write2 frames
                        # may have applied) — record it so final_check can
                        # tell "superseded by an in-doubt later write"
                        # apart from real acked-write loss.
                        checker.record_attempt(key, val)
                        write_failures += 1
                        continue
                    write_lat.append(time.perf_counter() - t0)
                    checker.record_ack(key, val)
                for k in range(keys_per_client):
                    t0 = time.perf_counter()
                    try:
                        await client.execute_read_transaction(
                            TransactionBuilder().read(f"byz-{ci}-{k}").build()
                        )
                    except Exception:
                        # counted, not hidden: excluded samples would make
                        # the published percentiles survivor-biased
                        # (durability is re-checked at final_check)
                        read_failures += 1
                        continue
                    read_lat.append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        await asyncio.gather(*[worker(i) for i in range(n_clients)])
        wall = time.perf_counter() - t0
        if partition_task is not None:
            await partition_task

        if attack == "equivocate":
            await _present_equivocation_evidence(vc, clients[0])

        # Durable posture (round 16): kill-and-recover-with-state for one
        # honest replica inside THIS adversarial leg, conviction counters
        # in-record, before the acked-durability final check (which then
        # also covers the recovered replica's serving path).
        durability = (
            await _crash_recover_probe(vc, checker, storage_dir)
            if durable
            else None
        )

        # Invariant 3 through a workload client: its accrued suspicion is
        # part of the system under test (a fresh client would pay the
        # silent replica's full trim-timeout once per key before its own
        # suspicion converges to the same routing — measured, not useful
        # to re-pay 30x at leg end).
        await checker.final_check(clients[0])
        await checker.stop()

        # Detection evidence, honest side only.
        suspicion: Dict[str, Dict[str, int]] = {}
        straggler_timeouts = 0
        for c in clients:
            for sid, kinds in c.suspicion_stats().items():
                agg = suspicion.setdefault(sid, {})
                for kind, n in kinds.items():
                    agg[kind] = agg.get(kind, 0) + n
            straggler_timeouts += c.metrics.counters.get(
                f"fanout.straggler-timeout.{BYZ_SID}", 0
            )
        equivocations = 0
        bad_grants = 0
        for r in vc.honest_replicas():
            bz = r.byzantine_stats()
            equivocations += bz["equivocations"].get(BYZ_SID, 0)
            bad_grants += bz["bad_grants"].get(BYZ_SID, 0)

        return {
            "attack": attack or "honest",
            "mesh_drop": drop,
            "trim_write1": trim_write1,
            "read_ms": _pcts(read_lat),
            "write_ms": _pcts(write_lat),
            "read_samples": len(read_lat),
            "write_samples": len(write_lat),
            "write_failures": write_failures,
            "read_failures": read_failures,
            "wall_s": round(wall, 2),
            "durability": durability,
            "invariants": checker.report(),
            "evidence": {
                "suspicion_by_peer": suspicion,
                "straggler_timeouts_vs_adversary": straggler_timeouts,
                "equivocations_proven": equivocations,
                "bad_grants_attributed": bad_grants,
            },
        }


def run(
    n_clients: int = 3,
    keys_per_client: int = 10,
    sweeps: int = 3,
    attacks=ATTACKS,
    timeout_s: float = 2.0,
    # ROADMAP item 4 remainder: the adversarial strategies whose cost is
    # ALSO measured under packet loss (the clean 13 ms mesh flatters an
    # attacker whose damage compounds with retries) — one extra leg each
    # at ``loss_drop`` per-frame drop on every link.  Round 12 covered
    # storm+silent; round 13 extended the default to the full catalog
    # (equivocate / forge-cert / stale-replay were clean-mesh-only).
    loss_attacks=ATTACKS,
    loss_drop: float = 0.02,
    # trim_write1 suspicion-steering A/B (ISSUE 8 satellite): re-measure
    # the off-by-default quorum-trimmed first Write1 attempt now that
    # _quorum_targets deprioritizes suspects — against the SILENT
    # adversary, where the trim historically wasted a full timeout per
    # fan-out on the dead replica until suspicion converged.
    trim_ab: bool = True,
) -> Dict:
    from mochi_tpu.net import transport
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()
    prev_floor = transport.RTT_FLOOR_S
    transport.RTT_FLOOR_S = max(prev_floor, RTT_MS / 1e3)

    def _vs_honest(leg: Dict, honest: Dict) -> Dict:
        return {
            "write_p50_ratio": _ratio(
                leg["write_ms"]["p50"], honest["write_ms"]["p50"]
            ),
            "write_p95_ratio": _ratio(
                leg["write_ms"]["p95"], honest["write_ms"]["p95"]
            ),
            "read_p50_ratio": _ratio(
                leg["read_ms"]["p50"], honest["read_ms"]["p50"]
            ),
            "read_p95_ratio": _ratio(
                leg["read_ms"]["p95"], honest["read_ms"]["p95"]
            ),
        }

    try:
        honest = asyncio.run(_leg(None, n_clients, keys_per_client, sweeps, timeout_s))
        per_attack: Dict[str, Dict] = {}
        for attack in attacks:
            leg = asyncio.run(
                _leg(attack, n_clients, keys_per_client, sweeps, timeout_s)
            )
            leg["vs_honest"] = _vs_honest(leg, honest)
            per_attack[attack] = leg
        for attack in loss_attacks:
            leg = asyncio.run(
                _leg(
                    attack, n_clients, keys_per_client, sweeps, timeout_s,
                    drop=loss_drop,
                )
            )
            # paired against the CLEAN honest leg: the ratio then carries
            # loss + adversary together — the deployment-facing number
            # ("what does this attack cost me on a real lossy WAN")
            leg["vs_honest"] = _vs_honest(leg, honest)
            per_attack[f"{attack}+loss"] = leg
        trim_ab_rec: Optional[Dict] = None
        if trim_ab:
            trim_legs = {}
            # the clean-mesh silent leg (when it ran above) IS the
            # trim=False side — parameter-identical; don't pay it twice
            if "silent" in per_attack:
                trim_legs["full"] = per_attack["silent"]
            for trim in (False, True):
                key = "trim" if trim else "full"
                if key in trim_legs:
                    continue
                trim_legs[key] = asyncio.run(
                    _leg(
                        "silent", n_clients, keys_per_client, sweeps,
                        timeout_s, trim_write1=trim,
                    )
                )
            trim_ab_rec = {
                "scenario": "silent adversary, clean mesh",
                "full_fanout_write_ms": trim_legs["full"]["write_ms"],
                "trim_write1_write_ms": trim_legs["trim"]["write_ms"],
                "trim_vs_full_write_p50": _ratio(
                    trim_legs["trim"]["write_ms"]["p50"],
                    trim_legs["full"]["write_ms"]["p50"],
                ),
                "trim_leg_write_failures": trim_legs["trim"]["write_failures"],
                "notes": (
                    "suspicion-steered _quorum_targets now routes the "
                    "trimmed first Write1 attempt away from suspect peers "
                    "(client.py trim_write1); ratio < 1 means the trim "
                    "wins under an unresponsive in-set replica"
                ),
            }
    finally:
        transport.RTT_FLOOR_S = prev_floor

    all_safe = honest["invariants"]["ok"] and all(
        leg["invariants"]["ok"] for leg in per_attack.values()
    )
    durable_posture = _durable_posture_summary((honest, *per_attack.values()))
    worst = max(
        (leg["vs_honest"]["write_p50_ratio"] or 1.0)
        for leg in per_attack.values()
    ) if per_attack else 1.0
    return {
        "metric": "byzantine_f1_wan_attack_cost",
        # Headline: the worst attack's write-p50 multiplier over the
        # paired honest leg — "what does one live adversary cost you".
        "value": worst,
        "unit": "x honest write p50 (worst attack, 13 ms WAN mesh)",
        "safety_invariants_hold_under_all_attacks": all_safe,
        "durable_posture": durable_posture,
        "topology": {
            "replicas": 5,
            "rf": 4,
            "f": 1,
            "byzantine": BYZ_SID,
            "clients": n_clients,
            "keys_per_client": keys_per_client,
            "sweeps": sweeps,
            "client_timeout_s": timeout_s,
            "mesh_rtt_ms": RTT_MS,
            "mesh_jitter_ms": JITTER_MS,
            "netsim_seed": SEED,
        },
        "honest": honest,
        "attacks": per_attack,
        "loss_drop": loss_drop,
        "trim_write1_ab": trim_ab_rec,
        "r09_reference": R09_HONEST,
        "notes": (
            "per-attack vs_honest ratios are paired against the in-run "
            "honest leg (same shape, same host window); r09_reference is "
            "the committed full-shape honest capture for provenance. "
            "invariants.ok=false in ANY leg is a safety failure of the "
            "protocol, not a latency regression."
        ),
    }


def _ratio(a: float, b: float) -> Optional[float]:
    if not a or not b or a != a or b != b:  # NaN-safe (empty sample sets)
        return None
    return round(a / b, 4)


if __name__ == "__main__":
    import json

    print(json.dumps(run(), indent=2))

"""Config 7: WAN-shaped latency — the reference's only published table,
finally apples-to-apples.

The reference's numbers come from a real 5-machine WAN deployment at
~13 ms RTT (read p50/p95/p99.9 = 26.6/31.1/33.9 ms, write = 56/98/145 ms);
every cluster benchmark in this repo was single-host loopback until now.
This config reruns the reference workload shape — 5 clients × 40 keys on a
5-replica signed cluster — under netsim's seeded 13 ms ± 1 ms full mesh
(``NetSim.mesh(seed=8, rtt_ms=13, jitter_ms=1)``), publishing the first
side-by-side p50/p95/p99.9 table.  The conditioning plan (per-frame
delay/drop/reorder draws) is fully deterministic given the seed.

Also carries the passthrough bound the tentpole promises: an interleaved
paired A/B of the same throughput workload with netsim
attached-but-disabled vs absent entirely — the ``link is None`` fast path
must be free (acceptance: ≤2% median delta).

Round 9 (write-path latency attack): the record additionally stamps
``host_crypto_engine``, aggregates the early-quorum fan-out straggler
evidence (``fanout``), re-measures the commit stage decomposition, and —
with ``MOCHI_AB_BASELINE_TREE`` pointing at a worktree of the r08 commit
— runs an interleaved same-host paired A/B against the pre-early-quorum
tree (``tree_ab_vs_r08``), the evidence behind the ≥25% write-p50 /
≥30% write-p99.9 acceptance bars.
"""

from __future__ import annotations

import asyncio
import json
import math
import os
import statistics
import subprocess
import sys
import time
from typing import Dict, List, Optional

RTT_MS = 13.0
JITTER_MS = 1.0
SEED = 8

# The reference's WAN table (PAPER.md; SURVEY.md §6): real 5-machine
# deployment, ~13 ms RTT, 5 clients × 40 keys.  p999 == p99.9.
REFERENCE = {
    "read_ms": {"p50": 26.6, "p95": 31.1, "p999": 33.9},
    "write_ms": {"p50": 56.0, "p95": 98.0, "p999": 145.0},
    "provenance": (
        "reference paper's real 5-machine WAN run (~13 ms RTT), its only "
        "published performance table; this config is the repo's first "
        "counterpart (VERDICT r5 missing #1/#2)"
    ),
}

# The committed round-8 capture this round's write-path work attacks
# (early-quorum fan-out + pipelined Write1->Write2 + native-C Ed25519):
# the acceptance bars are ≥25% write-p50 / ≥30% write-p99.9 off these.
R08_PRIOR = {
    "read_ms": {"p50": 21.23, "p95": 35.54, "p999": 70.9},
    "write_ms": {"p50": 84.63, "p95": 136.84, "p999": 199.24},
    "source": "benchmarks/results_r08.json (pure-python host engine)",
}


def _pcts(samples: List[float]) -> Dict[str, float]:
    if not samples:
        return {"p50": float("nan"), "p95": float("nan"), "p999": float("nan")}
    s = sorted(samples)

    def at(q: float) -> float:
        # nearest-rank: ceil(q*n)-1 (int(q*n) overshoots by one rank)
        return round(s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))] * 1e3, 2)

    return {"p50": at(0.50), "p95": at(0.95), "p999": at(0.999)}


async def _wan_run(
    n_clients: int,
    keys_per_client: int,
    sweeps: int,
    fast_path: Optional[bool] = None,
) -> Dict:
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.netsim import NetSim
    from mochi_tpu.testing.virtual_cluster import VirtualCluster
    from mochi_tpu.utils.runtime import reset_gc_debt

    sim = NetSim.mesh(seed=SEED, rtt_ms=RTT_MS, jitter_ms=JITTER_MS)
    async with VirtualCluster(5, rf=4, netsim=sim, fast_path=fast_path) as vc:
        read_lat: List[float] = []
        write_lat: List[float] = []
        clients = []

        async def populate(ci: int):
            client = vc.client()
            clients.append(client)
            # populate off the clock (sessions + first-contact handshakes)
            for k in range(keys_per_client):
                await client.execute_write_transaction(
                    TransactionBuilder().write(f"wan-{ci}-{k}", b"seed").build()
                )

        await asyncio.gather(*[populate(i) for i in range(n_clients)])
        # The populate phase built the long-lived graph (sessions,
        # connections, the stores' seed certificates); collect-and-freeze
        # it so the timed phase's GC passes trace only its own transient
        # garbage — without this, collections over the live cluster graph
        # land as 100-400 ms samples in exactly the tail columns this
        # config publishes (measured: write p999 ~180 -> ~75 ms quiet-host)
        reset_gc_debt()

        async def worker(ci: int):
            client = clients[ci]
            for s in range(sweeps):
                for k in range(keys_per_client):
                    key = f"wan-{ci}-{k}"
                    t0 = time.perf_counter()
                    await client.execute_write_transaction(
                        TransactionBuilder().write(key, b"v%d" % s).build()
                    )
                    write_lat.append(time.perf_counter() - t0)
                for k in range(keys_per_client):
                    t0 = time.perf_counter()
                    res = await client.execute_read_transaction(
                        TransactionBuilder().read(f"wan-{ci}-{k}").build()
                    )
                    read_lat.append(time.perf_counter() - t0)
                    assert res.operations[0].value == b"v%d" % s

        t0 = time.perf_counter()
        await asyncio.gather(*[worker(i) for i in range(n_clients)])
        wall = time.perf_counter() - t0
        totals = sim.totals()
        fanout = _aggregate_fanout(clients)
        breakdown = _commit_breakdown(clients)
        trace_evidence = _trace_evidence(vc, clients)

    rec = {
        "read_ms": _pcts(read_lat),
        "write_ms": _pcts(write_lat),
        "read_samples": len(read_lat),
        "write_samples": len(write_lat),
        "wall_s": round(wall, 2),
        "netsim_totals": totals,
        # Early-quorum evidence: how often fan-outs returned at quorum,
        # and what the left-behind stragglers looked like per replica —
        # the same shape the admin surfaces export (admin/http._fanout_*).
        "fanout": fanout,
        "commit_breakdown_ms": breakdown,
    }
    if trace_evidence is not None:
        rec["trace"] = trace_evidence
    return rec


def _trace_evidence(vc, clients) -> Optional[Dict]:
    """Per-transaction causal evidence when the leg ran traced (round 15):
    the TRACE-derived commit breakdown (p50 of per-card stage durations —
    cross-checked against the stage TIMERS measured in the same run and
    against the committed r09 decomposition), plus the cost-card rollup
    (verifies unique/memoized per txn, wire bytes, RTTs, queue wait)."""
    from mochi_tpu.obs import trace as obs_trace

    if not any(c.tracer.enabled for c in clients):
        return None
    events = []
    for c in clients:
        events.extend(c.tracer.events())
    for r in vc.replicas:
        events.extend(r.tracer.events())
    cards = obs_trace.cost_cards(events)
    # complete write cards only: every client stage present (ring-aged or
    # partially-sampled traces would skew the stage medians)
    wanted = ("txn.write", "write1-phase", "write2-fanout-wait", "write2-tally")
    write_cards = [
        c for c in cards.values() if all(s in c["stages_us"] for s in wanted)
    ]
    if not write_cards:
        return {"sampled_write_cards": 0}

    def p50(vals: List[float]) -> float:
        s = sorted(vals)
        return s[len(s) // 2]

    stage_p50_ms = {
        stage: round(p50([c["stages_us"][stage] for c in write_cards]) / 1e3, 3)
        for stage in wanted
    }
    return {
        "sampled_write_cards": len(write_cards),
        "spans_total": len(events),
        # the trace-derived commit breakdown (vs the hand stage timers)
        "commit_breakdown_ms": {
            k: v for k, v in stage_p50_ms.items() if k != "txn.write"
        },
        "txn_write_p50_ms": stage_p50_ms["txn.write"],
        "cost_card_p50": {
            "verify_items": p50([c["verify_items"] for c in write_cards]),
            "verify_unique": round(
                p50([c["verify_unique"] for c in write_cards]), 2
            ),
            "verify_memoized": round(
                p50([c["verify_memoized"] for c in write_cards]), 2
            ),
            "wire_bytes": p50([c["wire_bytes"] for c in write_cards]),
            "rtt": p50([c["rtt"] for c in write_cards]),
            "queue_us": round(p50([c["queue_us"] for c in write_cards]), 1),
            "fsyncs": p50([c["fsyncs"] for c in write_cards]),
        },
    }


def _aggregate_fanout(clients) -> Dict:
    """Sum the per-client fan-out straggler evidence (admin/http's
    _fanout_stats shape, aggregated across the workload's SDK clients)."""
    from mochi_tpu.admin.http import _fanout_stats

    total: Dict = {"early_returns": 0, "peers": {}}
    for c in clients:
        st = _fanout_stats(c.metrics)
        total["early_returns"] += st["early_returns"]
        for peer, stats in st["peers"].items():
            agg = total["peers"].setdefault(
                peer,
                {"late_responses": 0, "straggler_timeouts": 0,
                 "straggler_errors": 0, "straggler_ms_count": 0,
                 "straggler_ms_sum": 0.0},
            )
            agg["late_responses"] += stats.get("late_response", 0)
            agg["straggler_timeouts"] += stats.get("straggler_timeout", 0)
            agg["straggler_errors"] += stats.get("straggler_error", 0)
            h = stats.get("straggler_ms")
            if h:
                agg["straggler_ms_count"] += h["count"]
                agg["straggler_ms_sum"] += h["sum"]
    for agg in total["peers"].values():
        ms_sum = agg.pop("straggler_ms_sum")
        agg["straggler_ms_mean"] = (
            round(ms_sum / agg["straggler_ms_count"], 3)
            if agg["straggler_ms_count"]
            else None
        )
    return total


def _commit_breakdown(clients) -> Dict:
    """p50 of the client stage timers, pooled across clients — the r07
    decomposition's stages, re-measured under the early-quorum path."""
    stages = {}
    for name in ("write1-phase", "write2-fanout-wait", "write2-tally",
                 "envelope-encode-sign"):
        samples: List[float] = []
        for c in clients:
            t = c.metrics.timers.get(name)
            if t is not None:
                samples.extend(t.samples)
        if samples:
            samples.sort()
            stages[name] = round(samples[len(samples) // 2] * 1e3, 2)
    return stages


# ----------------------------------------------------------- tree A/B
#
# Interleaved paired A/B of THIS tree vs a baseline checkout (the r08
# tree): same host, same netsim seed, legs alternating order so host
# tenancy drift cancels.  The probe only touches APIs both trees share
# (transport.RTT_FLOOR_S + config7_wan._wan_run), so it runs unmodified
# from inside the old checkout.

_LEG_PROBE = r'''
import asyncio, json, logging, sys
logging.disable(logging.WARNING)
sys.path.insert(0, sys.argv[1])
from mochi_tpu.utils.runtime import tune_gc_for_server
tune_gc_for_server()  # the posture run() gives the headline leg — BOTH
# trees get it, or default-threshold GC pauses land as random 100-400 ms
# tail samples and the p999 ratio measures gc luck, not the write path
from mochi_tpu.net import transport
transport.RTT_FLOOR_S = float(sys.argv[2]) / 1e3
from benchmarks import config7_wan
wan = asyncio.run(config7_wan._wan_run(5, 40, 2))
print("LEG_JSON " + json.dumps({"write_ms": wan["write_ms"], "read_ms": wan["read_ms"]}))
'''


def _tree_leg(tree: str) -> Dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", _LEG_PROBE, tree, str(RTT_MS)],
        cwd=tree, capture_output=True, text=True, env=env, timeout=300,
    )
    for line in proc.stdout.splitlines():
        if line.startswith("LEG_JSON "):
            return json.loads(line[len("LEG_JSON "):])
    raise RuntimeError(
        f"tree leg in {tree} produced no record: "
        f"{proc.stdout[-300:]} {proc.stderr[-300:]}"
    )


def run_tree_ab(baseline_tree: str, pairs: int = 5) -> Dict:
    """Paired write-latency A/B vs a baseline checkout.  One full-shape
    WAN leg per tree per pair (sweeps=2: 400 write samples each — p999 of
    a shorter leg is a single-sample coin flip), order alternating — the
    per-pair RATIO is the statistic this host's tenancy drift leaves
    trustworthy, exactly the discipline of every committed A/B since
    r06."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rows = []
    for i in range(pairs):
        if i % 2 == 0:
            base = _tree_leg(baseline_tree)
            new = _tree_leg(here)
        else:
            new = _tree_leg(here)
            base = _tree_leg(baseline_tree)
        rows.append(
            {
                "baseline_write_ms": base["write_ms"],
                "new_write_ms": new["write_ms"],
                "p50_ratio": round(new["write_ms"]["p50"] / base["write_ms"]["p50"], 4),
                "p999_ratio": round(
                    new["write_ms"]["p999"] / base["write_ms"]["p999"], 4
                ),
            }
        )
    p50_ratios = sorted(r["p50_ratio"] for r in rows)
    p999_ratios = sorted(r["p999_ratio"] for r in rows)
    return {
        "pairs": pairs,
        "baseline_tree": baseline_tree,
        "per_pair": rows,
        "median_write_p50_ratio": round(statistics.median(p50_ratios), 4),
        "median_write_p999_ratio": round(statistics.median(p999_ratios), 4),
    }


# ------------------------------------------------------- passthrough A/B


async def _throughput_leg(netsim_disabled: bool, keys: int = 10) -> float:
    """One small config-1-shaped leg; returns txn/s.  ``netsim_disabled``
    attaches a NetSim with enabled=False (policy objects never handed
    out); False runs a tree with no netsim object at all.  ``keys`` sizes
    the leg: longer legs average intra-leg host noise, tightening the
    per-pair ratio distribution the passthrough bound is built from."""
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.netsim import NetSim
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    sim = (
        NetSim.mesh(seed=SEED, rtt_ms=RTT_MS, jitter_ms=JITTER_MS, enabled=False)
        if netsim_disabled
        else None
    )
    async with VirtualCluster(5, rf=4, netsim=sim) as vc:
        ops = 0

        async def worker(ci: int):
            nonlocal ops
            client = vc.client()
            for k in range(keys):
                key = f"pt-{ci}-{k}"
                await client.execute_write_transaction(
                    TransactionBuilder().write(key, b"v").build()
                )
                res = await client.execute_read_transaction(
                    TransactionBuilder().read(key).build()
                )
                assert res.operations[0].value == b"v"
                await client.execute_write_transaction(
                    TransactionBuilder().delete(key).build()
                )
                ops += 3

        t0 = time.perf_counter()
        await asyncio.gather(*[worker(i) for i in range(4)])
        wall = time.perf_counter() - t0
    if sim is not None:
        assert sim.totals()["frames"] == 0, "disabled netsim touched a frame"
    return ops / wall


def _median_ci95(sorted_vals: List[float]):
    """Nonparametric 95% CI for the median from order statistics: the
    widest (lo, hi) ranks whose binomial(n, 1/2) tail mass is <= 2.5%
    each side.  No distributional assumption — exactly what per-pair
    ratios on a drifting shared host call for.  Returns None when even
    the full (min, max) range cannot reach 95% coverage (n < 6: coverage
    of the extremes is 1 - 2*0.5^n < 95%) — small-pair runs must not
    publish an overconfident bound."""
    n = len(sorted_vals)
    if n < 6:
        return None
    tail = 0.0
    k = 0
    while k < n // 2:
        tail += math.comb(n, k) * 0.5**n
        if tail > 0.025:
            break
        k += 1
    lo = max(0, k - 1)
    return sorted_vals[lo], sorted_vals[n - 1 - lo]


def run_passthrough_ab(pairs: int = 15, keys: int = 24) -> Dict:
    """Interleaved paired A/B (one disabled-netsim leg + one absent leg
    per pair, leg ORDER alternating pair to pair): interleaving absorbs
    host tenancy drift, alternation cancels any warmup/ordering bias.
    The passthrough must be free — reports the median of per-pair ratios
    plus an order-statistic 95% CI on that median, so the record carries
    a real resolved BOUND ("overhead <= X% at 95%") instead of a prose
    caveat when a window's median lands below zero overhead (r09's
    sub-resolution annotation, re-measured this round at 15 pairs)."""
    ratios = []
    disabled = []
    absent = []
    for i in range(pairs):
        if i % 2 == 0:
            d = asyncio.run(_throughput_leg(netsim_disabled=True, keys=keys))
            a = asyncio.run(_throughput_leg(netsim_disabled=False, keys=keys))
        else:
            a = asyncio.run(_throughput_leg(netsim_disabled=False, keys=keys))
            d = asyncio.run(_throughput_leg(netsim_disabled=True, keys=keys))
        disabled.append(round(d, 1))
        absent.append(round(a, 1))
        ratios.append(d / a)
    median_ratio = statistics.median(ratios)
    ci = _median_ci95(sorted(ratios))
    slower = sum(1 for r in ratios if r < 1.0)
    rec = {
        "pairs": pairs,
        "disabled_txn_s": disabled,
        "absent_txn_s": absent,
        "per_pair_ratio": [round(r, 4) for r in ratios],
        "median_ratio_disabled_over_absent": round(median_ratio, 4),
        "median_overhead_pct": round((1.0 - median_ratio) * 100.0, 2),
        "pairs_disabled_slower": slower,
        "acceptance_le_2pct": abs(1.0 - median_ratio) <= 0.02,
    }
    if ci is not None:
        # The bound: overhead <= (1 - ci_lo) x 100% at 95% confidence.
        rec["median_ratio_ci95"] = [round(ci[0], 4), round(ci[1], 4)]
        rec["overhead_pct_upper_bound_95"] = round((1.0 - ci[0]) * 100.0, 2)
    else:
        rec["ci_note"] = "pairs < 6: no 95% CI is publishable at this n"
    return rec


# ------------------------------------------------------- tracing A/B (r15)


def run_trace_ab(pairs: int = 7) -> Dict:
    """Interleaved paired A/B of the config-7 WAN leg with causal tracing
    ON at the DEFAULT sample rate vs OFF entirely — the committed bound on
    what the round-15 tracer costs the write path.  Same discipline as
    every committed A/B since r06: full-shape legs, order alternating pair
    to pair, the per-pair write-p50 RATIO as the statistic."""
    import os as _os

    from mochi_tpu.obs.trace import DEFAULT_SAMPLE_RATE

    def _leg(traced: bool) -> Dict:
        prev = {
            k: _os.environ.get(k)
            for k in ("MOCHI_TRACE", "MOCHI_TRACE_SAMPLE", "MOCHI_TRACE_SEED")
        }
        try:
            if traced:
                _os.environ["MOCHI_TRACE"] = "1"  # the default sample rate
                _os.environ["MOCHI_TRACE_SEED"] = str(SEED)
                _os.environ.pop("MOCHI_TRACE_SAMPLE", None)
            else:
                for k in prev:
                    _os.environ.pop(k, None)
            return asyncio.run(_wan_run(5, 40, 2))
        finally:
            for k, v in prev.items():
                if v is None:
                    _os.environ.pop(k, None)
                else:
                    _os.environ[k] = v

    rows = []
    for i in range(pairs):
        if i % 2 == 0:
            on = _leg(True)
            off = _leg(False)
        else:
            off = _leg(False)
            on = _leg(True)
        rows.append(
            {
                "on_write_ms": on["write_ms"],
                "off_write_ms": off["write_ms"],
                "p50_ratio": round(
                    on["write_ms"]["p50"] / off["write_ms"]["p50"], 4
                ),
            }
        )
    ratios = sorted(r["p50_ratio"] for r in rows)
    median_ratio = statistics.median(ratios)
    rec = {
        "pairs": pairs,
        "sample_rate": DEFAULT_SAMPLE_RATE,
        "per_pair": rows,
        "median_write_p50_ratio": round(median_ratio, 4),
        "overhead_pct": round((median_ratio - 1.0) * 100.0, 2),
        "acceptance_le_3pct": median_ratio <= 1.03,
    }
    ci = _median_ci95(ratios)
    if ci is not None:
        rec["ratio_ci95"] = [round(ci[0], 4), round(ci[1], 4)]
        rec["overhead_pct_upper_bound_95"] = round((ci[1] - 1.0) * 100.0, 2)
    return rec


# ---------------------------------------------- fast-path on/off A/B (r18)


def run_fastpath_ab(
    pairs: int = 3,
    n_clients: int = 5,
    keys_per_client: int = 40,
    sweeps: int = 2,
) -> Dict:
    """Round-18 interleaved paired A/B: the full-shape config-7 WAN leg
    with the session MAC fast path ON (MAC'd envelopes + signed
    checkpoints + one-attestation certificates) vs OFF (per-message
    Ed25519, grant-by-grant certificates — the pre-r18 wire).  Same
    discipline as every committed A/B since r06: leg order alternating
    pair to pair, the per-pair write-p50 RATIO as the statistic, plus the
    commit-breakdown stage deltas the tentpole predicts (write1-phase and
    write2-fanout-wait shed their verify CPU; the RTT share stays)."""
    from mochi_tpu.net import transport
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()
    prev_floor = transport.RTT_FLOOR_S
    transport.RTT_FLOOR_S = max(prev_floor, RTT_MS / 1e3)

    def _leg(fp: bool) -> Dict:
        return asyncio.run(_wan_run(n_clients, keys_per_client, sweeps, fast_path=fp))

    rows = []
    try:
        for i in range(pairs):
            if i % 2 == 0:
                on = _leg(True)
                off = _leg(False)
            else:
                off = _leg(False)
                on = _leg(True)
            rows.append(
                {
                    "on_write_ms": on["write_ms"],
                    "off_write_ms": off["write_ms"],
                    "on_read_ms": on["read_ms"],
                    "off_read_ms": off["read_ms"],
                    "on_commit_breakdown_ms": on["commit_breakdown_ms"],
                    "off_commit_breakdown_ms": off["commit_breakdown_ms"],
                    "write_p50_ratio": round(
                        on["write_ms"]["p50"] / off["write_ms"]["p50"], 4
                    ),
                    "write_p999_ratio": round(
                        on["write_ms"]["p999"] / off["write_ms"]["p999"], 4
                    ),
                }
            )
    finally:
        transport.RTT_FLOOR_S = prev_floor

    med = lambda xs: statistics.median(sorted(xs))  # noqa: E731
    on_p50 = med([r["on_write_ms"]["p50"] for r in rows])
    off_p50 = med([r["off_write_ms"]["p50"] for r in rows])
    stages = sorted(
        set().union(*(r["on_commit_breakdown_ms"] for r in rows))
        & set().union(*(r["off_commit_breakdown_ms"] for r in rows))
    )
    breakdown_delta = {
        stage: round(
            med([r["on_commit_breakdown_ms"][stage] for r in rows
                 if stage in r["on_commit_breakdown_ms"]])
            - med([r["off_commit_breakdown_ms"][stage] for r in rows
                   if stage in r["off_commit_breakdown_ms"]]),
            2,
        )
        for stage in stages
    }
    rec = {
        "pairs": pairs,
        "shape": {
            "clients": n_clients, "keys_per_client": keys_per_client,
            "sweeps": sweeps, "mesh_rtt_ms": RTT_MS,
            "mesh_jitter_ms": JITTER_MS, "netsim_seed": SEED,
        },
        "per_pair": rows,
        "on_write_p50_ms_median": round(on_p50, 2),
        "off_write_p50_ms_median": round(off_p50, 2),
        "median_write_p50_ratio_on_over_off": round(
            med([r["write_p50_ratio"] for r in rows]), 4
        ),
        "median_write_p999_ratio_on_over_off": round(
            med([r["write_p999_ratio"] for r in rows]), 4
        ),
        # the stage deltas (on minus off, ms at p50): where the fast path
        # actually took its time from
        "commit_breakdown_delta_ms_on_minus_off": breakdown_delta,
        # acceptance: RTT-bound means ~2 RTT ≈ 27 ms; the bar is ≤ 33 ms
        "acceptance_on_write_p50_le_33ms": on_p50 <= 33.0,
    }
    ci = _median_ci95(sorted(r["write_p50_ratio"] for r in rows))
    if ci is not None:
        rec["write_p50_ratio_ci95"] = [round(ci[0], 4), round(ci[1], 4)]
    return rec


# ----------------------------------------------- live verifies/txn meter


def run_verify_meter(
    n: int = 64, writes: int = 4, fast_path: Optional[bool] = None
) -> Dict:
    """The live 43-checks/txn meter at the BASELINE shape: an n=64 rf=n
    cluster (f=21, quorum=43) with ONE shared caching verifier standing in
    for the config-6/8 verifier-service posture, writes serialized so the
    cost cards' unique-vs-memoized split is exact.  BASELINE
    ``published.6`` derives 43 unique verifies/txn by hand (~2752
    submitted grant checks collapsing under memoization); this measures
    the same number from the causal record instead of a constant."""
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.obs import trace as obs_trace
    from mochi_tpu.testing.virtual_cluster import VirtualCluster
    from mochi_tpu.verifier.spi import CachingVerifier, CpuVerifier

    import os as _os

    prev = {
        k: _os.environ.get(k)
        for k in ("MOCHI_TRACE", "MOCHI_TRACE_SAMPLE", "MOCHI_TRACE_SEED",
                  "MOCHI_TRACE_RING")
    }
    _os.environ["MOCHI_TRACE_SAMPLE"] = "1.0"
    _os.environ["MOCHI_TRACE_SEED"] = str(SEED)
    _os.environ["MOCHI_TRACE_RING"] = "16384"
    shared = CachingVerifier(CpuVerifier())

    async def body() -> Dict:
        async with VirtualCluster(
            n, rf=n, verifier_factory=lambda: shared, fast_path=fast_path
        ) as vc:
            client = vc.client()
            cards = []
            for i in range(writes):
                await client.execute_write_transaction(
                    TransactionBuilder().write("meter-key", b"v%d" % i).build()
                )
            events = list(client.tracer.events())
            for r in vc.replicas:
                events.extend(r.tracer.events())
            for tid, card in obs_trace.cost_cards(events).items():
                if "txn.write" in card["stages_us"]:
                    cards.append(card)
            return {"cards": cards, "quorum": vc.config.quorum}

    try:
        out = asyncio.run(body())
    finally:
        for k, v in prev.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
    cards = out["cards"]
    # steady-state txns only: the FIRST write populates the shared cache
    # cluster-wide and warms sessions — the meter is the repeat-traffic
    # figure (BASELINE's 43-unique derivation is steady-state too)
    steady = cards[1:] if len(cards) > 1 else cards
    uniq = [c["verify_unique"] for c in steady]
    memo = [c["verify_memoized"] for c in steady]
    items = [c["verify_items"] for c in steady]
    from mochi_tpu.crypto.session import fast_path_enabled

    mean = lambda xs: sum(xs) / len(xs) if xs else float("nan")  # noqa: E731
    rec = {
        "cluster": {"n": n, "rf": n, "f": (n - 1) // 3, "quorum": out["quorum"]},
        "fast_path": fast_path_enabled(fast_path),
        "writes": writes,
        "txns_metered": len(steady),
        "verify_items_per_txn_mean": round(mean(items), 2),
        "verifies_unique_per_txn_mean": round(mean(uniq), 2),
        "verifies_memoized_per_txn_mean": round(mean(memo), 2),
        "baseline_unique_checks": out["quorum"],  # 2f+1 = 43 at n=64
        "matches_baseline_43": (
            abs(mean(uniq) - out["quorum"]) <= 0.15 * out["quorum"]
        ),
    }
    return rec


def run(
    n_clients: int = 5,
    keys_per_client: int = 40,
    sweeps: int = 2,
    ab_pairs: int = 9,  # the committed results_r08.json record's count
    tree_ab_pairs: int = 5,
) -> Dict:
    from mochi_tpu.crypto.keys import host_crypto_engine
    from mochi_tpu.net import transport
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()
    # RTT-aware timeout budget for the conditioned run (the satellite
    # knob MOCHI_RTT_FLOOR_MS wired in net/transport.py): no Write1 may
    # spuriously time out and double-send under the 13 ms links.
    prev_floor = transport.RTT_FLOOR_S
    transport.RTT_FLOOR_S = max(prev_floor, RTT_MS / 1e3)
    try:
        wan = asyncio.run(_wan_run(n_clients, keys_per_client, sweeps))
    finally:
        transport.RTT_FLOOR_S = prev_floor
    # ab_pairs=0: the --smoke harness pass skips the passthrough A/B legs
    ab = (
        run_passthrough_ab(pairs=ab_pairs)
        if ab_pairs > 0
        else {"skipped": "ab_pairs=0 (smoke pass)"}
    )
    if isinstance(ab.get("median_overhead_pct"), float) and ab["median_overhead_pct"] < 0:
        # A disabled-netsim leg measuring FASTER than no netsim at all is
        # mechanically impossible (it does strictly more work): the host's
        # tenancy noise exceeded the point estimate's resolution in this
        # window.  The order-statistic CI above is the real artifact — the
        # bound "overhead <= overhead_pct_upper_bound_95" holds regardless
        # of which side of zero the median lands on.
        ab["note"] = (
            "negative point-estimate overhead = tenancy noise; read "
            "overhead_pct_upper_bound_95 for the resolved 95% bound "
            "(the `link is None` seam is unchanged)"
        )
    engine = host_crypto_engine()
    rec = {
        "metric": "wan_shaped_latency_5replica_f1",
        "value": wan["write_ms"]["p50"],
        "unit": "ms (write p50 at 13 ms RTT)",
        "host_crypto_engine": engine,
        "topology": {
            "replicas": 5,
            "rf": 4,
            "f": 1,
            "clients": n_clients,
            "keys_per_client": keys_per_client,
            "sweeps": sweeps,
            "mesh_rtt_ms": RTT_MS,
            "mesh_jitter_ms": JITTER_MS,
            "netsim_seed": SEED,
            "rtt_floor_ms": RTT_MS,
        },
        **wan,
        "reference": REFERENCE,
        "prior_r08": {
            **R08_PRIOR,
            "write_p50_vs_r08": round(
                wan["write_ms"]["p50"] / R08_PRIOR["write_ms"]["p50"], 4
            ),
            "write_p999_vs_r08": round(
                wan["write_ms"]["p999"] / R08_PRIOR["write_ms"]["p999"], 4
            ),
        },
        "passthrough_ab": ab,
        "environment_caveat": (
            f"host engine: {engine}. "
            + (
                "native-C Ed25519 (hbatch.c) serves sign+verify — the "
                "~20x pure-python penalty that caveated r06-r08 write "
                "rows is retired; residual gap to an OpenSSL host is "
                "~3-5x per op, far below the RTT share of every row."
                if engine == "native-c"
                else "OpenSSL wheel present; rows comparable as-is."
                if engine == "openssl"
                else "pure-python Ed25519 (~650 us/op, ~20x OpenSSL) "
                "inflates the write rows; rerun on a host with a C "
                "toolchain or the cryptography wheel before quoting "
                "the write comparison."
            )
        ),
    }
    # Interleaved paired A/B vs the r08 tree (MOCHI_AB_BASELINE_TREE, a
    # git worktree of the parent commit): the same-host evidence behind
    # the ≥25%/≥30% write-latency acceptance bars.
    baseline_tree = os.environ.get("MOCHI_AB_BASELINE_TREE")
    if baseline_tree and tree_ab_pairs > 0:
        try:
            rec["tree_ab_vs_r08"] = run_tree_ab(baseline_tree, tree_ab_pairs)
        except Exception as exc:  # record, don't crash the battery
            rec["tree_ab_vs_r08"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
    return rec


if __name__ == "__main__":
    import json

    print(json.dumps(run(), indent=2))

"""Config 7: WAN-shaped latency — the reference's only published table,
finally apples-to-apples.

The reference's numbers come from a real 5-machine WAN deployment at
~13 ms RTT (read p50/p95/p99.9 = 26.6/31.1/33.9 ms, write = 56/98/145 ms);
every cluster benchmark in this repo was single-host loopback until now.
This config reruns the reference workload shape — 5 clients × 40 keys on a
5-replica signed cluster — under netsim's seeded 13 ms ± 1 ms full mesh
(``NetSim.mesh(seed=8, rtt_ms=13, jitter_ms=1)``), publishing the first
side-by-side p50/p95/p99.9 table.  The conditioning plan (per-frame
delay/drop/reorder draws) is fully deterministic given the seed.

Also carries the passthrough bound the tentpole promises: an interleaved
paired A/B of the same throughput workload with netsim
attached-but-disabled vs absent entirely — the ``link is None`` fast path
must be free (acceptance: ≤2% median delta).
"""

from __future__ import annotations

import asyncio
import math
import statistics
import time
from typing import Dict, List, Optional

RTT_MS = 13.0
JITTER_MS = 1.0
SEED = 8

# The reference's WAN table (PAPER.md; SURVEY.md §6): real 5-machine
# deployment, ~13 ms RTT, 5 clients × 40 keys.  p999 == p99.9.
REFERENCE = {
    "read_ms": {"p50": 26.6, "p95": 31.1, "p999": 33.9},
    "write_ms": {"p50": 56.0, "p95": 98.0, "p999": 145.0},
    "provenance": (
        "reference paper's real 5-machine WAN run (~13 ms RTT), its only "
        "published performance table; this config is the repo's first "
        "counterpart (VERDICT r5 missing #1/#2)"
    ),
}


def _pcts(samples: List[float]) -> Dict[str, float]:
    if not samples:
        return {"p50": float("nan"), "p95": float("nan"), "p999": float("nan")}
    s = sorted(samples)

    def at(q: float) -> float:
        # nearest-rank: ceil(q*n)-1 (int(q*n) overshoots by one rank)
        return round(s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))] * 1e3, 2)

    return {"p50": at(0.50), "p95": at(0.95), "p999": at(0.999)}


async def _wan_run(n_clients: int, keys_per_client: int, sweeps: int) -> Dict:
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.netsim import NetSim
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    sim = NetSim.mesh(seed=SEED, rtt_ms=RTT_MS, jitter_ms=JITTER_MS)
    async with VirtualCluster(5, rf=4, netsim=sim) as vc:
        read_lat: List[float] = []
        write_lat: List[float] = []

        async def worker(ci: int):
            client = vc.client()
            # populate off the clock (sessions + first-contact handshakes)
            for k in range(keys_per_client):
                await client.execute_write_transaction(
                    TransactionBuilder().write(f"wan-{ci}-{k}", b"seed").build()
                )
            for s in range(sweeps):
                for k in range(keys_per_client):
                    key = f"wan-{ci}-{k}"
                    t0 = time.perf_counter()
                    await client.execute_write_transaction(
                        TransactionBuilder().write(key, b"v%d" % s).build()
                    )
                    write_lat.append(time.perf_counter() - t0)
                for k in range(keys_per_client):
                    t0 = time.perf_counter()
                    res = await client.execute_read_transaction(
                        TransactionBuilder().read(f"wan-{ci}-{k}").build()
                    )
                    read_lat.append(time.perf_counter() - t0)
                    assert res.operations[0].value == b"v%d" % s

        t0 = time.perf_counter()
        await asyncio.gather(*[worker(i) for i in range(n_clients)])
        wall = time.perf_counter() - t0
        totals = sim.totals()

    return {
        "read_ms": _pcts(read_lat),
        "write_ms": _pcts(write_lat),
        "read_samples": len(read_lat),
        "write_samples": len(write_lat),
        "wall_s": round(wall, 2),
        "netsim_totals": totals,
    }


# ------------------------------------------------------- passthrough A/B


async def _throughput_leg(netsim_disabled: bool) -> float:
    """One small config-1-shaped leg; returns txn/s.  ``netsim_disabled``
    attaches a NetSim with enabled=False (policy objects never handed
    out); False runs a tree with no netsim object at all."""
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.netsim import NetSim
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    sim = (
        NetSim.mesh(seed=SEED, rtt_ms=RTT_MS, jitter_ms=JITTER_MS, enabled=False)
        if netsim_disabled
        else None
    )
    async with VirtualCluster(5, rf=4, netsim=sim) as vc:
        ops = 0

        async def worker(ci: int):
            nonlocal ops
            client = vc.client()
            for k in range(10):
                key = f"pt-{ci}-{k}"
                await client.execute_write_transaction(
                    TransactionBuilder().write(key, b"v").build()
                )
                res = await client.execute_read_transaction(
                    TransactionBuilder().read(key).build()
                )
                assert res.operations[0].value == b"v"
                await client.execute_write_transaction(
                    TransactionBuilder().delete(key).build()
                )
                ops += 3

        t0 = time.perf_counter()
        await asyncio.gather(*[worker(i) for i in range(4)])
        wall = time.perf_counter() - t0
    if sim is not None:
        assert sim.totals()["frames"] == 0, "disabled netsim touched a frame"
    return ops / wall


def run_passthrough_ab(pairs: int = 9) -> Dict:
    """Interleaved paired A/B (one disabled-netsim leg + one absent leg
    per pair, leg ORDER alternating pair to pair): interleaving absorbs
    host tenancy drift, alternation cancels any warmup/ordering bias.
    The passthrough must be free — reports the median of per-pair ratios,
    the statistic this host's ±10% run-to-run noise leaves trustworthy."""
    ratios = []
    disabled = []
    absent = []
    for i in range(pairs):
        if i % 2 == 0:
            d = asyncio.run(_throughput_leg(netsim_disabled=True))
            a = asyncio.run(_throughput_leg(netsim_disabled=False))
        else:
            a = asyncio.run(_throughput_leg(netsim_disabled=False))
            d = asyncio.run(_throughput_leg(netsim_disabled=True))
        disabled.append(round(d, 1))
        absent.append(round(a, 1))
        ratios.append(d / a)
    median_ratio = statistics.median(ratios)
    return {
        "pairs": pairs,
        "disabled_txn_s": disabled,
        "absent_txn_s": absent,
        "per_pair_ratio": [round(r, 4) for r in ratios],
        "median_ratio_disabled_over_absent": round(median_ratio, 4),
        "median_overhead_pct": round((1.0 - median_ratio) * 100.0, 2),
        "acceptance_le_2pct": abs(1.0 - median_ratio) <= 0.02,
    }


def run(
    n_clients: int = 5,
    keys_per_client: int = 40,
    sweeps: int = 2,
    ab_pairs: int = 9,  # the committed results_r08.json record's count
) -> Dict:
    from mochi_tpu.net import transport
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()
    # RTT-aware timeout budget for the conditioned run (the satellite
    # knob MOCHI_RTT_FLOOR_MS wired in net/transport.py): no Write1 may
    # spuriously time out and double-send under the 13 ms links.
    prev_floor = transport.RTT_FLOOR_S
    transport.RTT_FLOOR_S = max(prev_floor, RTT_MS / 1e3)
    try:
        wan = asyncio.run(_wan_run(n_clients, keys_per_client, sweeps))
    finally:
        transport.RTT_FLOOR_S = prev_floor
    ab = run_passthrough_ab(pairs=ab_pairs)
    return {
        "metric": "wan_shaped_latency_5replica_f1",
        "value": wan["write_ms"]["p50"],
        "unit": "ms (write p50 at 13 ms RTT)",
        "topology": {
            "replicas": 5,
            "rf": 4,
            "f": 1,
            "clients": n_clients,
            "keys_per_client": keys_per_client,
            "sweeps": sweeps,
            "mesh_rtt_ms": RTT_MS,
            "mesh_jitter_ms": JITTER_MS,
            "netsim_seed": SEED,
            "rtt_floor_ms": RTT_MS,
        },
        **wan,
        "reference": REFERENCE,
        "passthrough_ab": ab,
        "environment_caveat": (
            "host without the `cryptography` wheel: grant/cert Ed25519 "
            "rides the pure-Python fallback (~650 us/op, ~20x OpenSSL), "
            "inflating the write rows and tails over the reference's "
            "native-crypto deployment (r7 anchors: 3187.5 us/txn "
            "wheel-less vs 295-319 OpenSSL).  The read row and the RTT "
            "share of every row are comparable as-is; rerun on an "
            "OpenSSL-wheel host before quoting the write comparison."
        ),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run(), indent=2))

"""Config 11: Byzantine clients — what a hostile coordinator costs, priced.

Config 10 priced Byzantine REPLICAS; this config prices Byzantine CLIENTS
(``testing/byzantine_client.py``) at the same config-7 WAN shape (5-replica
rf=4 f=1 signed cluster, ``NetSim.mesh(seed=8, rtt_ms=13, jitter_ms=1)``,
native-C host crypto): one adversarial coordinator with real keys attacks
the keyspace honest writers are working, once per strategy, with the
round-13 defenses ON (per-client grant quota + grant-TTL reclamation).
Three artifacts per attack:

* **honest-writer cost** — read/write p50/p95/p999 and ratios vs the
  in-run honest leg;
* **safety verdict** — the InvariantChecker report in-record (now
  including the reclaimed-slot rule and the wedge liveness metric);
* **defense evidence** — what the defenses did: reclaim counts, quota
  refusals, the attacker's own per-replica ledger rows, and what the
  attacker managed (grants held, partial commits).

**Headline** — time-to-conflicting-commit under ``withhold``: the
attacker sweeps EVERY subEpoch seed of a key's epoch (the HQ-replication
contention hole: the epoch only advances on apply, nothing applies, every
conflicting Write1 is refused at any seed), then an honest writer races to
commit a conflicting transaction.  With the TTL ON the wedge is bounded
near the TTL (acceptance: p95 <= 2x ``MOCHI_GRANT_TTL_MS``); with it OFF
the wedge is unbounded (the probe's deadline floor is recorded).  The
quota is off in BOTH wedge legs so the probe isolates the TTL defense —
the quota alone already prevents the full sweep (measured in the
grant-hoard leg).
"""

from __future__ import annotations

import asyncio
import contextlib
import time
from typing import Dict, List, Optional

from .config7_wan import JITTER_MS, RTT_MS, SEED, _pcts
from .config10_byzantine import _crash_recover_probe, _durable_posture_summary

CLIENT_ATTACKS = ("withhold", "partial-write2", "seed-bias", "grant-hoard")


def _defenses(ttl_ms: float, quota: int):
    """Pin the round-13 store knobs for one leg (the shared helper from
    the harness; restores on exit)."""
    from mochi_tpu.testing.byzantine_client import defense_knobs

    return defense_knobs(ttl_ms=ttl_ms, quota=quota)


async def _leg(
    attack: Optional[str],
    n_clients: int,
    keys_per_client: int,
    sweeps: int,
    timeout_s: float,
    ttl_ms: float,
    quota: int,
    wedge_seeds: int,
    durable: bool = True,
) -> Dict:
    """One honest-writer workload leg (config-10 shape), optionally with a
    Byzantine client attacking the same keys throughout the timed phase."""
    import tempfile

    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.netsim import NetSim
    from mochi_tpu.testing.invariants import InvariantChecker
    from mochi_tpu.testing.virtual_cluster import VirtualCluster
    from mochi_tpu.utils.runtime import reset_gc_debt

    sim = NetSim.mesh(seed=SEED, rtt_ms=RTT_MS, jitter_ms=JITTER_MS)
    storage_ctx = (
        tempfile.TemporaryDirectory() if durable else contextlib.nullcontext()
    )
    with storage_ctx as storage_dir, _defenses(ttl_ms, quota):
        async with VirtualCluster(
            5, rf=4, netsim=sim, storage_dir=storage_dir
        ) as vc:
            checker = InvariantChecker(vc.replicas)
            read_lat: List[float] = []
            write_lat: List[float] = []
            write_failures = 0
            read_failures = 0
            clients = []
            all_keys = [
                f"byzc-{ci}-{k}"
                for ci in range(n_clients)
                for k in range(keys_per_client)
            ]

            async def populate(ci: int):
                client = vc.client(timeout_s=timeout_s)
                clients.append(client)
                for k in range(keys_per_client):
                    key = f"byzc-{ci}-{k}"
                    for attempt in range(4):
                        try:
                            await client.execute_write_transaction(
                                TransactionBuilder().write(key, b"seed").build()
                            )
                            break
                        except Exception:
                            if attempt == 3:
                                raise
                    checker.record_ack(key, b"seed")

            await asyncio.gather(*[populate(i) for i in range(n_clients)])
            reset_gc_debt()
            checker.start(0.05)

            byz = None
            byz_task = None
            if attack:
                byz = vc.byzantine_client(attack, seed=7, timeout_s=timeout_s)
                byz_task = asyncio.ensure_future(
                    byz.run(
                        all_keys,
                        duration_s=3600.0,  # cancelled at workload end
                        interval_s=0.05,
                        wedge_seeds=wedge_seeds,
                    )
                )

            async def worker(ci: int):
                nonlocal write_failures, read_failures
                client = clients[ci]
                for s in range(sweeps):
                    for k in range(keys_per_client):
                        key = f"byzc-{ci}-{k}"
                        val = b"v%d" % s
                        t0 = time.perf_counter()
                        try:
                            await client.execute_write_transaction(
                                TransactionBuilder().write(key, val).build()
                            )
                        except Exception:
                            # liveness cost, counted honestly; the attack
                            # CAN refuse individual transactions — safety
                            # and durability are the checker's department
                            checker.record_attempt(key, val)
                            write_failures += 1
                            continue
                        write_lat.append(time.perf_counter() - t0)
                        checker.record_ack(key, val)
                    for k in range(keys_per_client):
                        t0 = time.perf_counter()
                        try:
                            await client.execute_read_transaction(
                                TransactionBuilder()
                                .read(f"byzc-{ci}-{k}")
                                .build()
                            )
                        except Exception:
                            read_failures += 1
                            continue
                        read_lat.append(time.perf_counter() - t0)

            t0 = time.perf_counter()
            await asyncio.gather(*[worker(i) for i in range(n_clients)])
            wall = time.perf_counter() - t0

            if byz_task is not None:
                byz_task.cancel()
                try:
                    await byz_task
                except asyncio.CancelledError:
                    pass

            # Durable posture (round 16): kill-and-recover-with-state for
            # one honest replica inside this adversarial leg, conviction
            # counters in-record, before the acked-durability final check.
            durability = (
                await _crash_recover_probe(vc, checker, storage_dir)
                if durable
                else None
            )

            await checker.final_check(clients[0])
            await checker.stop()

            # Defense evidence across the honest stores + the attacker's
            # own tally of what it managed.
            reclaims = sum(r.store.reclaims for r in vc.replicas)
            quota_refused = sum(r.store.quota_refusals for r in vc.replicas)
            attacker_rows = {}
            if byz is not None:
                for r in vc.replicas:
                    row = (
                        r.store.client_stats()["per_client"].get(byz.client_id)
                    )
                    if row:
                        attacker_rows[r.server_id] = row
            return {
                "attack": attack or "honest",
                "defenses": {"ttl_ms": ttl_ms, "quota": quota},
                "read_ms": _pcts(read_lat),
                "write_ms": _pcts(write_lat),
                "read_samples": len(read_lat),
                "write_samples": len(write_lat),
                "write_failures": write_failures,
                "read_failures": read_failures,
                "wall_s": round(wall, 2),
                "durability": durability,
                "invariants": checker.report(),
                "evidence": {
                    "grant_reclaims": reclaims,
                    "quota_refused": quota_refused,
                    "attacker_stats": dict(byz.stats) if byz else None,
                    "attacker_ledger_by_replica": attacker_rows or None,
                },
            }


async def _wedge_probe(
    ttl_ms: float,
    trials: int,
    timeout_s: float,
    deadline_s: float,
    wedge_seeds: int,
) -> Dict:
    """The headline measurement: arm the full-seed withhold wedge, then
    time an honest writer's conflicting commit.  ``ttl_ms=0`` is the
    pre-round-13 posture — the probe's deadline is the only bound, and a
    trial that hits it is recorded as wedged (time >= deadline).  Quota is
    OFF in both postures so the probe isolates the TTL defense."""
    from mochi_tpu.client.errors import RequestRefused
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.netsim import NetSim
    from mochi_tpu.testing.invariants import InvariantChecker
    from mochi_tpu.testing.virtual_cluster import VirtualCluster

    sim = NetSim.mesh(seed=SEED, rtt_ms=RTT_MS, jitter_ms=JITTER_MS)
    with _defenses(ttl_ms, 0):
        async with VirtualCluster(5, rf=4, netsim=sim) as vc:
            checker = InvariantChecker(vc.replicas)
            checker.start(0.05)
            # the attacker tolerates slow sweeps (a 2 s per-RPC budget on a
            # thousand-message sweep would leave timed-out holes an honest
            # seed draw slips through — the wedge must be COMPLETE for the
            # probe to measure the defense, not the attacker's impatience)
            byz = vc.byzantine_client("withhold", timeout_s=max(timeout_s, 15.0))
            honest = vc.client(timeout_s=timeout_s, write_attempts=6)
            times_s: List[float] = []
            wedged = 0
            arm_s: List[float] = []
            held_per_trial: List[int] = []
            for trial in range(trials):
                key = f"wedge-{trial}"
                t_arm = time.perf_counter()
                held = await byz.wedge(key, seeds=range(wedge_seeds))
                arm_s.append(time.perf_counter() - t_arm)
                held_per_trial.append(held)
                t0 = time.perf_counter()
                committed = False
                while time.perf_counter() - t0 < deadline_s:
                    try:
                        await honest.execute_write_transaction(
                            TransactionBuilder().write(key, b"contender").build()
                        )
                        committed = True
                        break
                    except RequestRefused:
                        await asyncio.sleep(0.02)
                    except Exception:
                        # a tenancy-stalled timeout or a reclaim-race
                        # tally split is a failed ATTEMPT, not a reason
                        # to abort the whole config's record
                        await asyncio.sleep(0.05)
                elapsed = time.perf_counter() - t0
                times_s.append(elapsed)
                if committed:
                    checker.record_ack(key, b"contender")
                else:
                    wedged += 1
            await checker.final_check(honest)
            await checker.stop()
            reclaims = sum(r.store.reclaims for r in vc.replicas)
            pcts = _pcts(times_s)
            return {
                "ttl_ms": ttl_ms,
                "quota": 0,
                "trials": trials,
                "wedge_seeds": wedge_seeds,
                "probe_deadline_s": deadline_s,
                "wedge_arm_s": [round(a, 2) for a in arm_s],
                # 4 in-set replicas x wedge_seeds when the sweep is
                # complete; a shortfall means timed-out holes the honest
                # writer can slip through — read the leg accordingly
                "slots_held_per_trial": held_per_trial,
                "time_to_conflicting_commit_ms": pcts,
                "samples_ms": [round(t * 1e3, 1) for t in times_s],
                "trials_wedged_past_deadline": wedged,
                "grant_reclaims": reclaims,
                "invariants": checker.report(),
            }


def run(
    n_clients: int = 3,
    keys_per_client: int = 10,
    sweeps: int = 3,
    attacks=CLIENT_ATTACKS,
    timeout_s: float = 2.0,
    # defenses-on posture for the attack-cost legs: the quota default and
    # a leg-scale TTL (the production default is 5 s; a WAN leg lasting
    # ~30-60 s wants reclaim activity visible within it)
    ttl_ms: float = 1000.0,
    quota: int = 128,
    wedge_seeds_cost: int = 192,
    # the headline wedge duel: full-seed sweep, quota off, TTL on vs off
    wedge_trials: int = 3,
    wedge_ttl_ms: float = 3000.0,
    wedge_deadline_s: float = 8.0,
    wedge_seeds: int = 1000,
) -> Dict:
    from mochi_tpu.net import transport
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()
    prev_floor = transport.RTT_FLOOR_S
    transport.RTT_FLOOR_S = max(prev_floor, RTT_MS / 1e3)

    def _vs_honest(leg: Dict, honest: Dict) -> Dict:
        return {
            "write_p50_ratio": _ratio(
                leg["write_ms"]["p50"], honest["write_ms"]["p50"]
            ),
            "write_p95_ratio": _ratio(
                leg["write_ms"]["p95"], honest["write_ms"]["p95"]
            ),
            "read_p50_ratio": _ratio(
                leg["read_ms"]["p50"], honest["read_ms"]["p50"]
            ),
            "read_p95_ratio": _ratio(
                leg["read_ms"]["p95"], honest["read_ms"]["p95"]
            ),
        }

    try:
        honest = asyncio.run(
            _leg(
                None, n_clients, keys_per_client, sweeps, timeout_s,
                ttl_ms, quota, wedge_seeds_cost,
            )
        )
        per_attack: Dict[str, Dict] = {}
        for attack in attacks:
            leg = asyncio.run(
                _leg(
                    attack, n_clients, keys_per_client, sweeps, timeout_s,
                    ttl_ms, quota, wedge_seeds_cost,
                )
            )
            leg["vs_honest"] = _vs_honest(leg, honest)
            per_attack[attack] = leg
        wedge_on = asyncio.run(
            _wedge_probe(
                wedge_ttl_ms, wedge_trials, timeout_s, wedge_deadline_s,
                wedge_seeds,
            )
        )
        wedge_off = asyncio.run(
            _wedge_probe(
                0.0, max(1, wedge_trials - 1), timeout_s, wedge_deadline_s,
                wedge_seeds,
            )
        )
    finally:
        transport.RTT_FLOOR_S = prev_floor

    all_safe = honest["invariants"]["ok"] and all(
        leg["invariants"]["ok"] for leg in per_attack.values()
    ) and wedge_on["invariants"]["ok"] and wedge_off["invariants"]["ok"]
    durable_posture = _durable_posture_summary((honest, *per_attack.values()))
    p95_ms = wedge_on["time_to_conflicting_commit_ms"]["p95"]
    bounded = bool(p95_ms == p95_ms and p95_ms <= 2 * wedge_ttl_ms)
    unbounded_off = (
        wedge_off["trials_wedged_past_deadline"] == wedge_off["trials"]
    )
    return {
        "metric": "byzantine_client_wedge_bound_ms",
        # Headline: how long a full-seed withholding wedge can block a
        # conflicting honest commit WITH reclamation on (p95) — off, the
        # probe only ever observes its own deadline.
        "value": p95_ms,
        "unit": (
            "ms to conflicting commit under withhold (p95, TTL "
            f"{wedge_ttl_ms:g} ms; TTL off = unbounded)"
        ),
        "safety_invariants_hold_under_all_attacks": all_safe,
        "durable_posture": durable_posture,
        "acceptance": {
            "ttl_on_p95_bounded_2x_ttl": bounded,
            "ttl_off_unbounded_at_probe_deadline": unbounded_off,
        },
        "topology": {
            "replicas": 5,
            "rf": 4,
            "f": 1,
            "clients": n_clients,
            "byzantine_clients": 1,
            "keys_per_client": keys_per_client,
            "sweeps": sweeps,
            "client_timeout_s": timeout_s,
            "mesh_rtt_ms": RTT_MS,
            "mesh_jitter_ms": JITTER_MS,
            "netsim_seed": SEED,
        },
        "defenses": {"ttl_ms": ttl_ms, "quota": quota},
        "honest": honest,
        "attacks": per_attack,
        "wedge_ttl_on": wedge_on,
        "wedge_ttl_off": wedge_off,
        "notes": (
            "attack legs run the round-13 defenses ON (quota + TTL "
            "reclamation); the wedge duel runs quota OFF in both legs to "
            "isolate the TTL bound — the quota alone already caps the "
            "full-seed sweep (grant-hoard leg evidence).  invariants.ok="
            "false in ANY leg is a safety failure, not a latency "
            "regression; every leg's report carries the reclaimed-slot "
            "invariant and the max-wedge liveness metric."
        ),
    }


def _ratio(a: float, b: float) -> Optional[float]:
    if not a or not b or a != a or b != b:  # NaN-safe (empty sample sets)
        return None
    return round(a / b, 4)


if __name__ == "__main__":
    import json

    print(json.dumps(run(), indent=2))

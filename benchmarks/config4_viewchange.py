"""Config 4: n=64 f=21 view-change storm (leader-failover signature burst).

Worst-case burst: every replica broadcasts a signed view-change vouching
message and every replica must validate a 2f+1 = 43 quorum certificate from
every other — n * (2f+1) = 2752 signatures arriving at once, the
BASELINE.json "n=64, f=21" shape.  Measures time-to-validate the full storm
and the implied signed-ops/sec (the >=100k target's stress shape).

Round 11 adds the ``wan_reconfig`` leg — config 4 run WAN-SHAPED for the
first time: a live reconfiguration (the paper's view-change analog,
mochiDB.tex:184-199) committed on a 5-replica cluster under the config-7
netsim mesh (13 ms ± 1 ms RTT) WHILE one replica is partitioned away and
writers keep running.  Published per round: the reconfiguration's own
commit latency, the partitioned replica's time-to-converge after heal
(the "configstamp ahead" → background config resync path), and the write
latency/failure cost the churn imposes on concurrent traffic — with an
honest read of the regressions in-record.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List


async def _wan_reconfig(rounds: int, n_clients: int, keys_per_client: int) -> Dict:
    """Reconfiguration under WAN conditioning + partition, writers live.

    Per round: partition server-4, commit an evolve()d config (same
    membership, next configstamp — the minimal view change) through the
    standard 2-phase write while the partition holds, heal, then wait for
    the partitioned replica to converge via its own "configstamp ahead" →
    config-resync path.  Writers hold STALE configs throughout (the
    realistic posture: applications don't pause for reconfigurations).
    """
    from mochi_tpu.client.txn import TransactionBuilder
    from mochi_tpu.netsim import NetSim
    from mochi_tpu.testing.virtual_cluster import VirtualCluster
    from mochi_tpu.utils.runtime import reset_gc_debt

    sim = NetSim.mesh(seed=8, rtt_ms=13.0, jitter_ms=1.0)
    victim = "server-4"
    async with VirtualCluster(5, rf=4, netsim=sim) as vc:
        admin = vc.client(timeout_s=5.0)
        write_lat: List[float] = []
        write_failures = 0
        clients = []

        async def populate(ci: int):
            c = vc.client(timeout_s=2.0)
            clients.append(c)
            for k in range(keys_per_client):
                await c.execute_write_transaction(
                    TransactionBuilder().write(f"vc-{ci}-{k}", b"seed").build()
                )

        await asyncio.gather(*[populate(i) for i in range(n_clients)])
        # Warm the admin's sessions/connections BEFORE the partition:
        # reconfig_commit_ms must measure the reconfiguration, not a cold
        # client's first-contact handshake timing out against the victim.
        await admin.execute_write_transaction(
            TransactionBuilder().write("vc-admin-warm", b"w").build()
        )
        reset_gc_debt()
        stop_writers = asyncio.Event()

        async def writer(ci: int):
            nonlocal write_failures
            c = clients[ci]
            s = 0
            while not stop_writers.is_set():
                s += 1
                for k in range(keys_per_client):
                    if stop_writers.is_set():
                        return
                    t0 = time.perf_counter()
                    try:
                        await c.execute_write_transaction(
                            TransactionBuilder()
                            .write(f"vc-{ci}-{k}", b"s%d" % s)
                            .build()
                        )
                        write_lat.append(time.perf_counter() - t0)
                    except Exception:
                        write_failures += 1

        writers = [asyncio.ensure_future(writer(i)) for i in range(n_clients)]
        round_records = []
        try:
            for _ in range(rounds):
                await asyncio.sleep(0.4)  # steady-state traffic window
                for ev in NetSim.partition(victim, 0.0):
                    sim.apply_event(ev)
                new_cfg = admin.config.evolve(
                    {sid: s.url for sid, s in admin.config.servers.items()},
                    public_keys=admin.config.public_keys,
                )
                t0 = time.perf_counter()
                await admin.reconfigure_cluster(new_cfg)
                commit_s = time.perf_counter() - t0
                await asyncio.sleep(0.3)  # hold the partition post-commit
                for ev in NetSim.heal(victim):
                    sim.apply_event(ev)
                # Convergence: the partitioned replica learns the new
                # config from post-heal traffic ("configstamp ahead" →
                # background config resync), with no operator action.
                t0 = time.perf_counter()
                deadline = t0 + 15.0
                while time.perf_counter() < deadline:
                    if all(
                        r.config.configstamp == new_cfg.configstamp
                        for r in vc.replicas
                    ):
                        break
                    await asyncio.sleep(0.025)
                converged = all(
                    r.config.configstamp == new_cfg.configstamp
                    for r in vc.replicas
                )
                round_records.append(
                    {
                        "configstamp": new_cfg.configstamp,
                        "reconfig_commit_ms": round(commit_s * 1e3, 2),
                        "partitioned_replica_converged": converged,
                        "convergence_after_heal_ms": (
                            round((time.perf_counter() - t0) * 1e3, 2)
                            if converged
                            else None
                        ),
                    }
                )
        finally:
            stop_writers.set()
            for w in writers:
                try:
                    await asyncio.wait_for(w, timeout=10.0)
                except Exception:
                    w.cancel()
            # reap anything cancelled above: a still-pending task at loop
            # teardown would die with "Task was destroyed but it is
            # pending" and cut its cleanup short
            await asyncio.gather(*writers, return_exceptions=True)

        from .config7_wan import _pcts

        return {
            "rounds": round_records,
            "write_ms_during_churn": _pcts(write_lat),
            "write_samples": len(write_lat),
            "write_failures": write_failures,
            "topology": {
                "replicas": 5, "rf": 4, "f": 1, "clients": n_clients,
                "keys_per_client": keys_per_client,
                "mesh_rtt_ms": 13.0, "mesh_jitter_ms": 1.0, "netsim_seed": 8,
                "partitioned": victim,
            },
            "honest_read": (
                "write percentiles here INCLUDE the partition + "
                "reconfiguration windows: p95/p999 carry the retry cost of "
                "commits attempted against a 4-reachable cluster and the "
                "post-heal mixed-configstamp retries of stale-config "
                "writers — compare p50 against the quiet config-7 r09 "
                "capture (46.07 ms) before quoting"
            ),
        }


def run(
    n: int = 64,
    f: int = 21,
    rounds: int = 4,
    wan_rounds: int = 2,
    wan_clients: int = 2,
    wan_keys: int = 4,
) -> Dict:
    import numpy as np

    import jax

    from mochi_tpu.crypto import batch_verify, keys
    from mochi_tpu.crypto.curve import verify_prepared
    from mochi_tpu.verifier.spi import VerifyItem

    assert n >= 3 * f + 1
    quorum = 2 * f + 1
    server_keys = [keys.generate_keypair() for _ in range(n)]

    # view-change storm: each of n new-view certificates carries 2f+1
    # signed view-change votes
    items = []
    group_ids = []
    for view_holder in range(n):
        payload = b"view-change|new-view=7|holder=%d" % view_holder
        for s in range(quorum):
            items.append(
                VerifyItem(
                    server_keys[s].public_key, payload, server_keys[s].sign(payload)
                )
            )
            group_ids.append(view_holder)

    prep = batch_verify.prepare(items)
    dev = jax.devices()[0]
    args = tuple(jax.device_put(a, dev) for a in prep[:6])
    fn = jax.jit(verify_prepared)
    out = jax.block_until_ready(fn(*args))  # compile
    assert np.asarray(out).all()

    best = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        # readback inside the timed region: true sync through the axon relay
        bitmap = np.asarray(fn(*args))
        best = min(best, time.perf_counter() - t0)

    # quorum tally on host (tiny): every holder must reach 2f+1
    counts = np.bincount(group_ids, weights=bitmap.astype(np.int64), minlength=n)
    assert (counts >= quorum).all()

    # Comb leg: the production posture — the n replica identities are a
    # KNOWN signer set, so the storm takes the doubling-free comb path
    # (crypto/comb.py).  Same verdict contract, ~3x fewer device FLOPs.
    from mochi_tpu.crypto import comb

    reg = comb.SignerRegistry()
    reg.register_all([kp.public_key for kp in server_keys])
    key_idx = np.asarray(
        [reg.index_of(it.public_key) for it in items], dtype=np.int32
    )
    comb_prep = comb._prepare_comb(items, key_idx, None)
    comb_best = float("inf")
    launched = comb._dispatch_comb(comb_prep, reg, None)  # compile
    assert all(
        np.logical_and(np.asarray(launched[0])[: len(items)], launched[1])
    )
    for _ in range(rounds):
        t0 = time.perf_counter()
        launched = comb._dispatch_comb(comb_prep, reg, None)
        np.asarray(launched[0])
        comb_best = min(comb_best, time.perf_counter() - t0)

    rec = {
        "metric": "view_change_storm_validate",
        "value": round(best * 1e3, 2),
        "unit": "ms",
        "sigs": len(items),
        "sigs_per_sec": round(len(items) / best, 1),
        "comb_ms": round(comb_best * 1e3, 2),
        "comb_sigs_per_sec": round(len(items) / comb_best, 1),
        "n": n,
        "f": f,
        "quorum": quorum,
    }
    if wan_rounds > 0:
        # Round-11 satellite: config 4 run WAN-shaped — a real
        # reconfiguration under netsim partition with writers live.
        rec["wan_reconfig"] = asyncio.run(
            _wan_reconfig(wan_rounds, wan_clients, wan_keys)
        )
    return rec


if __name__ == "__main__":
    import json

    print(json.dumps(run()))

"""Config 4: n=64 f=21 view-change storm (leader-failover signature burst).

Worst-case burst: every replica broadcasts a signed view-change vouching
message and every replica must validate a 2f+1 = 43 quorum certificate from
every other — n * (2f+1) = 2752 signatures arriving at once, the
BASELINE.json "n=64, f=21" shape.  Measures time-to-validate the full storm
and the implied signed-ops/sec (the >=100k target's stress shape).
"""

from __future__ import annotations

import time
from typing import Dict


def run(n: int = 64, f: int = 21, rounds: int = 4) -> Dict:
    import numpy as np

    import jax

    from mochi_tpu.crypto import batch_verify, keys
    from mochi_tpu.crypto.curve import verify_prepared
    from mochi_tpu.verifier.spi import VerifyItem

    assert n >= 3 * f + 1
    quorum = 2 * f + 1
    server_keys = [keys.generate_keypair() for _ in range(n)]

    # view-change storm: each of n new-view certificates carries 2f+1
    # signed view-change votes
    items = []
    group_ids = []
    for view_holder in range(n):
        payload = b"view-change|new-view=7|holder=%d" % view_holder
        for s in range(quorum):
            items.append(
                VerifyItem(
                    server_keys[s].public_key, payload, server_keys[s].sign(payload)
                )
            )
            group_ids.append(view_holder)

    prep = batch_verify.prepare(items)
    dev = jax.devices()[0]
    args = tuple(jax.device_put(a, dev) for a in prep[:6])
    fn = jax.jit(verify_prepared)
    out = jax.block_until_ready(fn(*args))  # compile
    assert np.asarray(out).all()

    best = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        # readback inside the timed region: true sync through the axon relay
        bitmap = np.asarray(fn(*args))
        best = min(best, time.perf_counter() - t0)

    # quorum tally on host (tiny): every holder must reach 2f+1
    counts = np.bincount(group_ids, weights=bitmap.astype(np.int64), minlength=n)
    assert (counts >= quorum).all()

    # Comb leg: the production posture — the n replica identities are a
    # KNOWN signer set, so the storm takes the doubling-free comb path
    # (crypto/comb.py).  Same verdict contract, ~3x fewer device FLOPs.
    from mochi_tpu.crypto import comb

    reg = comb.SignerRegistry()
    reg.register_all([kp.public_key for kp in server_keys])
    key_idx = np.asarray(
        [reg.index_of(it.public_key) for it in items], dtype=np.int32
    )
    comb_prep = comb._prepare_comb(items, key_idx, None)
    comb_best = float("inf")
    launched = comb._dispatch_comb(comb_prep, reg, None)  # compile
    assert all(
        np.logical_and(np.asarray(launched[0])[: len(items)], launched[1])
    )
    for _ in range(rounds):
        t0 = time.perf_counter()
        launched = comb._dispatch_comb(comb_prep, reg, None)
        np.asarray(launched[0])
        comb_best = min(comb_best, time.perf_counter() - t0)

    return {
        "metric": "view_change_storm_validate",
        "value": round(best * 1e3, 2),
        "unit": "ms",
        "sigs": len(items),
        "sigs_per_sec": round(len(items) / best, 1),
        "comb_ms": round(comb_best * 1e3, 2),
        "comb_sigs_per_sec": round(len(items) / comb_best, 1),
        "n": n,
        "f": f,
        "quorum": quorum,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run()))

"""Config 13: deterministic scenario-engine soak — the composed generator, priced.

Configs 1-12 each hand-compose ONE adversary or fault shape; config 13
drives the round-16 scenario engine (``mochi_tpu/testing/scenario.py``),
where a single integer seed draws the whole scenario — topology (replica
count, durable-storage posture), netsim shape, an ordered fault schedule
across all eight families (crash+restart-with-state, partition+heal,
uplink degrade, Byzantine replica, Byzantine client, load spike, live
reconfig, SIGKILL-the-processes), and the workload mix — and runs it on
the deterministic ``ExplorerLoop`` with the ``InvariantChecker`` sampling
throughout.  Three artifacts:

* **soak verdict** — N seeds (the committed record runs ≥500), zero
  invariant violations, zero acked-write loss, zero harness errors, with
  per-family draw counts proving the fault coverage is NON-VACUOUS (a
  generator that never draws Byzantine clients would pass vacuously);
* **determinism probe** — one seed run twice back to back must produce
  byte-identical canonical records (drawn spec, executed schedule, acked
  map, invariant verdict) — the replay-from-seed-alone contract, live;
* **violation-path probe** — one seed run with an injected store-level
  conflicting commit must be DETECTED, must replay byte-identically from
  the seed alone, and the greedy minimizer must emit a strictly smaller
  spec that still reproduces it (the detector and the reproducer
  tooling, proven in-record, so a zero-violation soak is evidence of
  safety rather than of a blind checker).

``scripts/soak.sh`` wraps the long-running posture (thousands of seeds,
multi-process); ``python -m mochi_tpu.testing.scenario repro --seed N``
replays any failure this config ever reports.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, Optional


def _determinism_probe(seed: int, runs: int) -> Dict:
    from mochi_tpu.testing import scenario

    records = []
    wall = []
    for _ in range(max(2, runs)):
        t0 = time.perf_counter()
        result = scenario.run_scenario(seed)
        wall.append(round(time.perf_counter() - t0, 2))
        records.append(result.canonical_bytes())
    return {
        "seed": seed,
        "runs": len(records),
        "byte_identical": all(r == records[0] for r in records),
        "canonical_bytes": len(records[0]),
        "wall_s": wall,
    }


def _violation_probe(seed: int) -> Dict:
    from mochi_tpu.testing import scenario

    spec = dataclasses.replace(
        scenario.draw_spec(seed), inject_violation=True
    )
    first = scenario.run_scenario(spec)
    replay = scenario.run_scenario(
        dataclasses.replace(scenario.draw_spec(seed), inject_violation=True)
    )
    detected = bool(first.violations)
    minimized: Optional[Dict] = None
    if detected:
        mini = scenario.minimize(spec)
        still = scenario.run_scenario(mini.spec)
        minimized = {
            "weight_before": spec.weight(),
            "weight_after": mini.spec.weight(),
            "strictly_smaller": mini.spec.weight() < spec.weight(),
            "minimizer_runs": mini.runs,
            "still_reproduces": bool(still.violations),
        }
    return {
        "seed": seed,
        "spec_hash": spec.spec_hash(),
        "detected": detected,
        "violations": list(first.violations)[:2],
        "replays_from_seed_alone": first.canonical_bytes()
        == replay.canonical_bytes(),
        "minimize": minimized,
    }


def run(
    count: int = 512,
    start: int = 0,
    workers: int = 2,
    profile: str = "soak",
    determinism_seed: int = 3,
    determinism_runs: int = 2,
    violation_seed: int = 4,
) -> Dict:
    from mochi_tpu.testing import scenario
    from mochi_tpu.utils.runtime import tune_gc_for_server

    tune_gc_for_server()
    determinism = _determinism_probe(determinism_seed, determinism_runs)
    violation = _violation_probe(violation_seed)
    summary = scenario.soak(
        range(start, start + count), profile=profile, workers=workers
    )
    families = summary["fault_family_draws"]
    acceptance = {
        "zero_invariant_violations": summary["violations"] == 0,
        "zero_harness_errors": summary["harness_errors"] == 0,
        "determinism_byte_identical": determinism["byte_identical"],
        "injected_violation_detected_replayed_minimized": bool(
            violation["detected"]
            and violation["replays_from_seed_alone"]
            and violation["minimize"]
            and violation["minimize"]["strictly_smaller"]
            and violation["minimize"]["still_reproduces"]
        ),
        # non-vacuous coverage: every fault family actually drawn (the
        # seed range must be wide enough — ~64 seeds covers all eight)
        "all_fault_families_drawn": all(
            families.get(fam, 0) > 0 for fam in scenario.FAMILIES
        ),
    }
    return {
        "metric": "scenario_soak_seeds",
        "value": summary["seeds_run"],
        "unit": (
            f"seeds soaked across {sum(1 for v in families.values() if v)} "
            f"fault families, {summary['violations']} invariant violations"
        ),
        "acceptance": acceptance,
        "generator_version": scenario.GENERATOR_VERSION,
        "profile": profile,
        "workers": workers,
        "determinism": determinism,
        "violation_probe": violation,
        "soak": summary,
        "notes": (
            "soak seeds draw full scenarios (topology incl. durable WAL "
            "posture + netsim mesh + ordered fault legs + workload) and "
            "run on the seeded ExplorerLoop; a failing seed replays with "
            "`python -m mochi_tpu.testing.scenario repro --seed N` and "
            "minimizes with `--minimize out.json` (docs/OPERATIONS.md "
            "§4k).  The violation probe proves the detector+reproducer "
            "arc in-record; without it a zero-violation soak could be a "
            "blind checker."
        ),
    }


if __name__ == "__main__":
    print(json.dumps(run(), indent=2))

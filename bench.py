"""Headline benchmark: batched Ed25519 verify throughput on the JAX device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The metric is device signature-verification throughput (sigs/sec), peak over
several batch sizes (BASELINE.json config 2 range).  ``vs_baseline`` is the
speedup over the reference-analog CPU path measured in the same run — one
OpenSSL (via ``cryptography``) Ed25519 verify per signature on this host,
single-thread, the stand-in for the reference's intended BouncyCastle
verifier (the reference itself never signs: ``MochiProtocol.proto:123`` TODO,
SURVEY.md preamble).

Robustness: device discovery/compile runs under a watchdog; if the TPU
plugin wedges (tunnel loss), the benchmark re-executes itself on the CPU
backend so the driver still gets a measurement (flagged via "platform").
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

WATCHDOG_ENV = "MOCHI_BENCH_CPU_FALLBACK"


def _measure() -> dict:
    import numpy as np

    import jax

    from mochi_tpu.crypto import batch_verify, keys
    from mochi_tpu.crypto.curve import verify_prepared
    from mochi_tpu.verifier.spi import VerifyItem

    dev = jax.devices()[0]
    fn = jax.jit(verify_prepared)
    kp = keys.generate_keypair()

    best_rate = 0.0
    best = None
    for batch in (1024, 4096, 16384):
        items = []
        for i in range(batch):
            msg = b"bench message %d" % i
            items.append(VerifyItem(kp.public_key, msg, kp.sign(msg)))
        y_a, sign_a, y_r, sign_r, s_bits, h_bits, pre_ok = batch_verify.prepare(items)
        assert pre_ok.all()
        args = tuple(
            jax.device_put(a, dev) for a in (y_a, sign_a, y_r, sign_r, s_bits, h_bits)
        )
        out = jax.block_until_ready(fn(*args))  # compile + warmup
        assert np.asarray(out).all()
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            times.append(time.perf_counter() - t0)
        rate = batch / min(times)
        if rate > best_rate:
            best_rate = rate
            best = {"batch": batch, "ms": round(min(times) * 1e3, 2)}

    # CPU baseline: sequential OpenSSL verifies (sampled, extrapolated)
    sample = items[:256]
    t0 = time.perf_counter()
    for it in sample:
        assert keys.verify(it.public_key, it.message, it.signature)
    cpu_rate = len(sample) / (time.perf_counter() - t0)

    return {
        "metric": "ed25519_batch_verify_throughput",
        "value": round(best_rate, 1),
        "unit": "sigs/sec",
        "vs_baseline": round(best_rate / cpu_rate, 3),
        "platform": dev.platform,
        "best_batch": best["batch"],
        "best_ms": best["ms"],
        "cpu_openssl_sigs_per_sec": round(cpu_rate, 1),
    }


def _device_alive(timeout_s: float = 90.0) -> bool:
    """True if jax backend initialization completes within the watchdog."""
    result = {}

    def probe():
        try:
            import jax

            result["n"] = len(jax.devices())
        except Exception:
            result["n"] = 0

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    return result.get("n", 0) > 0


def main() -> None:
    if os.environ.get(WATCHDOG_ENV) != "1" and not _device_alive():
        # TPU plugin wedged (e.g. tunnel down): re-exec on the CPU backend so
        # the driver still gets a number.  Can't be done in-process — the
        # hung backend initialization poisons this interpreter.
        env = dict(os.environ)
        env.update(
            {
                WATCHDOG_ENV: "1",
                "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": "",
            }
        )
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)], env=env)
        sys.exit(proc.returncode)
    print(json.dumps(_measure()))


if __name__ == "__main__":
    main()

"""Headline benchmark: batched Ed25519 verify throughput on the JAX device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The metric is device signature-verification throughput (sigs/sec) on the
north-star batch size (BASELINE.json config 2 range).  ``vs_baseline`` is the
speedup over the reference-analog CPU path measured in the same run — one
OpenSSL (via ``cryptography``) Ed25519 verify per signature on this host,
single-thread, the stand-in for the reference's intended BouncyCastle
verifier (the reference itself never signs: ``MochiProtocol.proto:123`` TODO,
SURVEY.md preamble).
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax

    from mochi_tpu.crypto import batch_verify, keys
    from mochi_tpu.crypto.curve import verify_prepared
    from mochi_tpu.verifier.spi import VerifyItem

    batch = 4096
    rng = np.random.default_rng(244)

    items = []
    for i in range(batch):
        kp = keys.keypair_from_seed(rng.bytes(32))
        msg = b"bench message %d" % i + rng.bytes(32)
        items.append(VerifyItem(kp.public_key, msg, kp.sign(msg)))

    y_a, sign_a, y_r, sign_r, s_bits, h_bits, pre_ok = batch_verify.prepare(items)
    assert pre_ok.all()
    dev = jax.devices()[0]
    args = tuple(
        jax.device_put(a, dev) for a in (y_a, sign_a, y_r, sign_r, s_bits, h_bits)
    )
    fn = jax.jit(verify_prepared)

    # warmup / compile
    out = jax.block_until_ready(fn(*args))
    assert np.asarray(out).all()

    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    t1 = time.perf_counter()
    device_sigs_per_sec = batch * iters / (t1 - t0)

    # CPU baseline: sequential OpenSSL verifies (sampled, extrapolated)
    sample = items[:256]
    t0 = time.perf_counter()
    for it in sample:
        assert keys.verify(it.public_key, it.message, it.signature)
    t1 = time.perf_counter()
    cpu_sigs_per_sec = len(sample) / (t1 - t0)

    print(
        json.dumps(
            {
                "metric": "ed25519_batch_verify_throughput",
                "value": round(device_sigs_per_sec, 1),
                "unit": "sigs/sec",
                "vs_baseline": round(device_sigs_per_sec / cpu_sigs_per_sec, 3),
            }
        )
    )


if __name__ == "__main__":
    main()

"""Headline benchmark: batched Ed25519 verify throughput on the JAX device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

The metric is device signature-verification throughput (sigs/sec), peak over
several batch sizes (BASELINE.json config 2 range) and over both device
implementations (XLA and the Pallas kernel — the per-impl table ships in the
"impls" key), then re-measured with 4-8 batches in flight at the best size
(steady-state pipelining: overlaps the dispatch/tunnel round trip with
device execution, as the loaded BatchingVerifier does).  ``vs_baseline`` is the speedup over the reference-analog CPU
path measured in the same run — one OpenSSL (via ``cryptography``) Ed25519
verify per signature on this host, single-thread, the stand-in for the
reference's intended BouncyCastle verifier (the reference itself never
signs: ``MochiProtocol.proto:123`` TODO, SURVEY.md preamble).  The honest
fleet denominator — all host cores verifying in parallel — is also reported
(``cpu_allcores_sigs_per_sec`` / ``vs_cpu_allcores``), per VERDICT.md round-1
weak #6.

Robustness (VERDICT.md round-1 weak #1): the measurement always runs in a
fresh subprocess; the parent retries N times with backend re-init before
falling back to the CPU backend, and a fallback is flagged LOUDLY
(``tpu_unreachable: true``) instead of being passed off as the headline.
The persistent XLA compilation cache (.jax_cache) is wired in so driver
re-runs skip the 20-60 s per-bucket compiles.

MFU accounting (VERDICT.md round-1 weak #4): ops/signature from XLA's own
``cost_analysis`` on the compiled executable, peak utilization against a
documented nominal VPU peak.

Comb leg (every platform): the known-signer comb program — the engine the
replica hot path routes to by default — is measured alongside the ladder
with its own cost-analysis ops/sig (``ops_per_sig_comb_cost_analysis``),
an interleaved paired A/B vs the ladder, and the chain-vs-tree COMB_IMPL
comparison.  See the ``comb`` key of the record.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
_CACHE_DIR = os.path.join(_REPO, ".jax_cache")

# Nominal per-chip vector-unit peak for MFU accounting, int32 ops/s.
# TPU v5e: 8 VPU sublanes x 128 lanes x ~1.74 GHz x ~2 ALUs ~= 3.6e12; we use
# the conservative single-issue figure 1.8e12 (so reported MFU is an upper
# bound on how much headroom remains, not a flattering lower one).  The
# Ed25519 verifier is pure int32 VPU work — the MXU plays no part — so VPU
# peak is the right denominator.  FALLBACK ONLY: when the battery's
# scripts/vpu_peak.py has measured the actual device (benchmarks/
# vpu_peak.json), that number replaces this folklore figure (VERDICT r4 #3).
VPU_PEAK_INT_OPS = 1.8e12


def _measured_vpu_peak():
    """(peak, source) — the battery-measured device peak when available."""
    try:
        with open(os.path.join(_REPO, "benchmarks", "vpu_peak.json")) as fh:
            doc = json.load(fh)
        if doc.get("platform") == "tpu" and doc.get("value", 0) > 0:
            return float(doc["value"]), "measured (benchmarks/vpu_peak.json)"
    except Exception:
        pass
    return VPU_PEAK_INT_OPS, "assumed (v5e datasheet figure; never measured)"


def _tunnel_rtt_ms(dev) -> float:
    """Median tiny-op device round trip, ms — the dispatch+relay floor.

    Pins the r02->r04 headline-delta question (VERDICT r4 #5): sequential
    rates divide by (exec + this RTT), so a fatter tunnel alone moves the
    headline between rounds with no code change.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    x = jax.device_put(jnp.zeros((8,), jnp.int32), dev)
    f = jax.jit(lambda v: v + 1)
    np.asarray(f(x))  # compile outside the timed region
    times = []
    for _ in range(21):
        t0 = time.perf_counter()
        np.asarray(f(x))
        times.append(time.perf_counter() - t0)
    times.sort()
    return round(times[len(times) // 2] * 1e3, 3)


def time_rates(call, batch, depths=(4, 8)):
    """(sequential rate, {depth: pipelined rate}) with the D2H readback
    discipline: np.asarray per batch is the only trustworthy sync through
    the axon relay.  ONE implementation shared by the headline and comb
    legs here AND by scripts/tpu_flash.py's comb capture — measurement-
    discipline fixes apply everywhere at once."""
    import numpy as np

    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(call())
        times.append(time.perf_counter() - t0)
    seq = batch / min(times)
    pipe = {}
    for depth in depths:
        rates = []
        for _ in range(3):
            t0 = time.perf_counter()
            outs = [call() for _ in range(depth)]
            for o in outs:
                np.asarray(o)
            rates.append(depth * batch / (time.perf_counter() - t0))
        pipe[depth] = round(max(rates), 1)
    return seq, pipe


def cost_analysis_ops_per_item(jitted, n_items, *args, **static_kwargs):
    """flops/item from XLA's cost analysis of the compiled executable, or
    None when the backend doesn't expose it — the one extraction shared by
    the ladder, comb and tree legs (and tpu_flash's comb capture)."""
    try:
        cost = jitted.lower(*args, **static_kwargs).compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return float(cost.get("flops", 0.0)) / n_items
    except Exception:
        return None


def _measure() -> dict:
    import numpy as np

    import jax

    from mochi_tpu.crypto import batch_verify, keys
    from mochi_tpu.crypto.curve import verify_prepared
    from mochi_tpu.verifier.spi import VerifyItem

    dev = jax.devices()[0]
    kp = keys.generate_keypair()

    def prepared(batch):
        items = []
        for i in range(batch):
            msg = b"bench message %d" % i
            items.append(VerifyItem(kp.public_key, msg, kp.sign(msg)))
        y_a, sign_a, y_r, sign_r, s_bits, h_bits, pre_ok = batch_verify.prepare(items)
        assert pre_ok.all()
        args = tuple(
            jax.device_put(a, dev)
            for a in (y_a, sign_a, y_r, sign_r, s_bits, h_bits)
        )
        return items, args

    # 4096 is the VMEM-residency peak (batch_verify.MAX_BUCKET); 8192/16384
    # document the spill regression the production path avoids by chunking.
    # The CPU-backend fallback (TPU unreachable) keeps batches small: XLA-CPU
    # runs ~75x slower per signature and the full sweep would blow the
    # fallback's time budget for a number that is only a loud failure flag.
    batches = (1024, 2048, 4096, 8192, 16384) if dev.platform == "tpu" else (256, 1024)
    impls = {}

    # ---- XLA path -------------------------------------------------------
    fn = jax.jit(verify_prepared)
    xla = {"per_batch": {}}
    flops_per_sig = None
    for batch in batches:
        items, args = prepared(batch)
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn(*args))  # compile + warmup
        compile_s = time.perf_counter() - t0
        assert np.asarray(out).all()
        if flops_per_sig is None:
            flops_per_sig = cost_analysis_ops_per_item(fn, batch, *args) or 0.0
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            # np.asarray forces the D2H readback: the only reliable sync
            # point through the axon relay (block_until_ready can return
            # before execution completes there, yielding absurd rates).
            np.asarray(fn(*args))
            times.append(time.perf_counter() - t0)
        rate = batch / min(times)
        xla["per_batch"][batch] = {
            "sigs_per_sec": round(rate, 1),
            "ms": round(min(times) * 1e3, 2),
            "compile_s": round(compile_s, 1),
        }
    xla["best"] = max(
        ((b, v["sigs_per_sec"]) for b, v in xla["per_batch"].items()),
        key=lambda kv: kv[1],
    )
    impls["xla"] = xla

    # ---- Pallas kernel --------------------------------------------------
    # Shelved after measurement: Mosaic compile exceeded 15 min vs XLA's
    # 40 s for a slower-or-equal program (pallas_verify.py docstring has the
    # full verdict).  Re-enable explicitly to re-test on newer toolchains.
    if dev.platform == "tpu" and os.environ.get("MOCHI_BENCH_PALLAS") == "1":
        try:
            from mochi_tpu.crypto.pallas_verify import verify_prepared_pallas

            pal = {"per_batch": {}}
            for batch in batches:
                items, args = prepared(batch)
                t0 = time.perf_counter()
                out = jax.block_until_ready(verify_prepared_pallas(*args))
                compile_s = time.perf_counter() - t0
                assert np.asarray(out).all()
                times = []
                for _ in range(5):
                    t0 = time.perf_counter()
                    np.asarray(verify_prepared_pallas(*args))
                    times.append(time.perf_counter() - t0)
                rate = batch / min(times)
                pal["per_batch"][batch] = {
                    "sigs_per_sec": round(rate, 1),
                    "ms": round(min(times) * 1e3, 2),
                    "compile_s": round(compile_s, 1),
                }
            pal["best"] = max(
                ((b, v["sigs_per_sec"]) for b, v in pal["per_batch"].items()),
                key=lambda kv: kv[1],
            )
            impls["pallas"] = pal
        except Exception as exc:  # prove-or-kill: record, don't crash
            impls["pallas"] = {"error": f"{type(exc).__name__}: {exc}"[:500]}
    elif dev.platform == "tpu":
        impls["pallas"] = {
            "skipped": "shelved after measurement: Mosaic compile >15min at "
            "block 128/256 vs 40s XLA compile; XLA path already uses the "
            "limbs-on-lanes layout (pallas_verify.py docstring)"
        }

    best_impl, (best_batch, best_rate) = max(
        ((name, i["best"]) for name, i in impls.items() if "best" in i),
        key=lambda kv: kv[1][1],
    )

    # ---- pipelined steady-state at the best batch -----------------------
    # Sequential timing charges every batch the full dispatch + tunnel
    # round trip; a loaded verifier keeps several batches in flight (JAX
    # dispatch is async), overlapping RTT with device execution.  This is
    # the rate the BatchingVerifier/service sustains under load, and the
    # honest headline for a throughput metric (scripts/pipeline_bench.py
    # measured 118.6k sigs/s at depth 8 vs 63.6-92k sequential on v5e).
    _time_rates = time_rates  # module-level shared helper (see its docstring)

    pipeline = None
    if best_impl == "xla" and dev.platform == "tpu":
        _, args = prepared(best_batch)
        jax.block_until_ready(fn(*args))
        _, pipeline = _time_rates(lambda: fn(*args), best_batch)
        pipe_best = max(pipeline.values())
        if pipe_best > best_rate:
            best_rate = pipe_best

    # ---- known-signer comb path at the best batch -----------------------
    # The cluster's production verify traffic is signed by REGISTERED
    # identities (crypto/comb.py: doubling-free per-signer tables, ~3x
    # fewer field muls than the ladder), and since the comb-first routing
    # landed it IS the engine that carries the replica hot path — so this
    # leg runs on EVERY backend (the CPU platform is where the verdict
    # lives while the TPU tunnel is dead), with:
    #   * XLA cost-analysis ops/sig for the comb program, published next to
    #     the ladder's figure (ops_per_sig_xla_cost_analysis) — the op-count
    #     claim made auditable;
    #   * an INTERLEAVED same-host paired A/B vs the ladder (alternating
    #     launches, per-pair ratios, median) so drift cannot masquerade as
    #     speedup;
    #   * the chain-vs-tree COMB_IMPL A/B (fewest-ops vs shallowest-chain
    #     accumulation) at the same batch.
    # The headline `value` stays the general-path (arbitrary-key) rate.
    comb_rec = None
    comb_flops_per_sig = None
    try:
        from mochi_tpu.crypto import comb as comb_mod

        reg = comb_mod.SignerRegistry(device=dev)
        # no side effects inside asserts: python -O strips them, and a
        # stripped register() would time an empty zero table
        registered = reg.register(kp.public_key)
        if registered is None:
            raise RuntimeError("signer registration failed")
        items, largs = prepared(best_batch)  # same workload as the headline
        (ckey, cy_r, csign_r, cs_sc, ch_sc), cpre_ok = comb_mod._prepare_comb(
            items, np.zeros(len(items), np.int32), None
        )
        if not cpre_ok.all():
            raise RuntimeError("comb prechecks rejected bench items")
        table = reg.device_table(dev)
        cargs = tuple(
            jax.device_put(a, dev)
            for a in (ckey, cy_r, csign_r, cs_sc, ch_sc)
        )
        t0 = time.perf_counter()
        out = np.asarray(comb_mod._verify_comb_jit(table, *cargs))
        comb_compile_s = time.perf_counter() - t0
        if not out.all():
            raise RuntimeError("comb verdicts wrong on valid signatures")
        comb_flops_per_sig = cost_analysis_ops_per_item(
            comb_mod._verify_comb_jit, best_batch, table, *cargs
        )
        comb_seq, cpipe = _time_rates(
            lambda: comb_mod._verify_comb_jit(table, *cargs), best_batch
        )
        comb_best = max(comb_seq, max(cpipe.values()))
        # Interleaved paired A/B against the general ladder: alternating
        # launches in one process on one host, per-pair time ratios —
        # the same discipline the config-1 cluster A/B uses, so thermal /
        # scheduler drift shows up as ratio variance, not as a bogus win.
        ratios = []
        for _ in range(7):
            t0 = time.perf_counter()
            np.asarray(fn(*largs))
            t_ladder = time.perf_counter() - t0
            t0 = time.perf_counter()
            np.asarray(comb_mod._verify_comb_jit(table, *cargs))
            t_comb = time.perf_counter() - t0
            ratios.append(t_ladder / t_comb)
        ranked = sorted(ratios)
        paired = {
            "pairs": len(ratios),
            # launch order, NOT sorted: a monotone drift (host heating,
            # background load) must stay visible in the committed record
            "per_pair_speedup": [round(r, 3) for r in ratios],
            "median_speedup": round(ranked[len(ranked) // 2], 3),
            "discipline": "interleaved same-process launches, per-batch "
            "np.asarray readback, ladder/comb time ratio per pair "
            "(published in launch order; median over the sorted copy)",
        }
        # chain-vs-tree accumulation A/B (static `impl` jit arg — distinct
        # compiled programs; see crypto/comb.py COMB_IMPL).
        tree_rec = None
        try:
            t0 = time.perf_counter()
            tout = np.asarray(
                comb_mod._verify_comb_jit(table, *cargs, impl="tree")
            )
            tree_compile_s = time.perf_counter() - t0
            if not tout.all():
                raise RuntimeError("tree verdicts wrong on valid signatures")
            tree_flops = cost_analysis_ops_per_item(
                comb_mod._verify_comb_jit, best_batch, table, *cargs, impl="tree"
            )
            tree_times = []
            for _ in range(5):
                t0 = time.perf_counter()
                np.asarray(comb_mod._verify_comb_jit(table, *cargs, impl="tree"))
                tree_times.append(time.perf_counter() - t0)
            tree_rec = {
                "sigs_per_sec_sequential": round(best_batch / min(tree_times), 1),
                "ops_per_sig_xla_cost_analysis": (
                    round(tree_flops) if tree_flops else None
                ),
                "compile_s": round(tree_compile_s, 1),
            }
        except Exception as exc:
            tree_rec = {"error": f"{type(exc).__name__}: {exc}"[:300]}
        comb_rec = {
            "sigs_per_sec_sequential": round(comb_seq, 1),
            "pipelined_sigs_per_sec_by_depth": cpipe,
            "best_sigs_per_sec": round(comb_best, 1),
            "speedup_vs_ladder": round(comb_best / best_rate, 2),
            "paired_ab_vs_ladder": paired,
            "impl": comb_mod.COMB_IMPL,
            "ops_per_sig_xla_cost_analysis": (
                round(comb_flops_per_sig) if comb_flops_per_sig else None
            ),
            "ops_per_sig_ladder": round(flops_per_sig or 0.0),
            "tree_impl": tree_rec,
            "compile_s": round(comb_compile_s, 1),
            # single signer = best-case gather locality; the K=16/64
            # cluster-shaped sweep is scripts/comb_bench.py (battery 3f)
            "registered_signers": 1,
            "posture": "registered-signer (cluster cert traffic; the "
            "replica hot path routes here by default since the comb-first "
            "engine landed)",
        }
    except Exception as exc:  # never let the extra leg break the headline
        comb_rec = {"error": f"{type(exc).__name__}: {exc}"[:300]}

    # ---- CPU baselines --------------------------------------------------
    items, _ = prepared(1024)
    sample = items[:256]
    t0 = time.perf_counter()
    for it in sample:
        assert keys.verify(it.public_key, it.message, it.signature)
    cpu_rate = len(sample) / (time.perf_counter() - t0)

    ncores = os.cpu_count() or 1
    cpu_allcores = _allcores_baseline(sample, ncores)

    # Compare against the best committed prior capture at the same batch
    # (VERDICT r4 item 5): a cross-round regression should be visible in
    # the record itself, not discovered by a later audit.
    prior_cmp = None
    if dev.platform == "tpu":
        try:
            import glob

            prior = None
            for path in sorted(
                glob.glob(os.path.join(_REPO, "benchmarks", "results_r*_tpu.json"))
            ):
                with open(path) as fh:
                    h = json.load(fh).get("headline", {})
                if (
                    h.get("platform") == "tpu"
                    and h.get("best_batch") == best_batch
                    and (prior is None or h.get("value", 0) > prior[1])
                ):
                    prior = (os.path.basename(path), h.get("value", 0))
            if prior is not None and prior[1]:
                prior_cmp = {
                    "ratio": round(best_rate / prior[1], 3),
                    "prior_value": prior[1],
                    "prior_source": prior[0],
                }
        except Exception:
            pass

    vpu_peak, vpu_peak_source = _measured_vpu_peak()
    mfu = None
    if flops_per_sig:
        mfu = best_rate * flops_per_sig / vpu_peak

    rtt_ms = None
    if dev.platform == "tpu":
        try:
            rtt_ms = _tunnel_rtt_ms(dev)
        except Exception:
            pass

    return {
        "metric": "ed25519_batch_verify_throughput",
        "value": round(best_rate, 1),
        "unit": "sigs/sec",
        "vs_baseline": round(best_rate / cpu_rate, 3),
        "platform": dev.platform,
        # Which HOST engine produced cpu_*_sigs_per_sec (and every replica-
        # inline verify on this machine): openssl / native-c / pure-python.
        # Machine-readable provenance for the standing wheel-less-host
        # caveat — the "CPU baseline" of a record is not comparable across
        # engines (ISSUE 5 satellite).
        "host_crypto_engine": keys.host_crypto_engine(),
        "impl": best_impl,
        "best_batch": best_batch,
        "pipelined_sigs_per_sec_by_depth": pipeline,
        "comb": comb_rec,
        "impls": impls,
        "cpu_openssl_sigs_per_sec": round(cpu_rate, 1),
        "cpu_allcores_sigs_per_sec": round(cpu_allcores, 1),
        "vs_cpu_allcores": round(best_rate / cpu_allcores, 3) if cpu_allcores else None,
        "cpu_cores": ncores,
        "ops_per_sig_xla_cost_analysis": round(flops_per_sig or 0.0),
        # the comb program's op count published NEXT TO the ladder's: the
        # known-signer engine the replica hot path routes to by default
        "ops_per_sig_comb_cost_analysis": (
            round(comb_flops_per_sig) if comb_flops_per_sig else None
        ),
        "mfu_vs_vpu_peak": round(mfu, 4) if mfu is not None else None,
        "vpu_peak_int_ops": vpu_peak,
        "vpu_peak_source": vpu_peak_source,
        "tunnel_rtt_ms": rtt_ms,
        "vs_best_prior_capture": prior_cmp,
    }


def _allcores_baseline(sample, ncores: int) -> float:
    """OpenSSL verify rate with every host core busy (process pool)."""
    import multiprocessing as mp

    payload = [(it.public_key, it.message, it.signature) for it in sample]
    try:
        ctx = mp.get_context("fork")
        with ctx.Pool(ncores) as pool:
            t0 = time.perf_counter()
            pool.map(_verify_chunk, [payload] * ncores)
            dt = time.perf_counter() - t0
        return len(payload) * ncores / dt
    except Exception:
        return 0.0


def _verify_chunk(payload):
    from mochi_tpu.crypto import keys

    for pk, msg, sig in payload:
        keys.verify(pk, msg, sig)
    return len(payload)


def _child() -> None:
    import jax

    cache_dir = _CACHE_DIR
    if os.environ.get("MOCHI_BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
        # CPU backend: host-fingerprint-keyed cache — a shared cache dir
        # from another machine can feed SIGILL-prone AOT code (VERDICT r4
        # item 6; same guard as __graft_entry__._dryrun_child)
        from mochi_tpu.utils.runtime import host_cache_dir

        cache_dir = host_cache_dir(_CACHE_DIR)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    # Liveness marker: backend init is where a wedged TPU plugin hangs
    # (round-1 failure mode).  The parent gives init a short deadline and
    # only grants the long compile+measure budget once this line appears.
    n = len(jax.devices())
    print(f"BENCH_ALIVE devices={n}", flush=True)
    print("BENCH_JSON " + json.dumps(_measure()), flush=True)


def _run_child(force_cpu: bool, timeout_s: float, alive_timeout_s: float = 120.0):
    env = dict(os.environ)
    if force_cpu:
        env.update({"MOCHI_BENCH_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                    "PALLAS_AXON_POOL_IPS": ""})
    proc = subprocess.Popen(
        [sys.executable, "-u", os.path.abspath(__file__), "--child"],
        env=env, cwd=_REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, errors="replace",
    )
    lines: list = []
    done = threading.Event()

    def pump():
        for line in proc.stdout:
            lines.append(line)
        done.set()

    t = threading.Thread(target=pump, daemon=True)
    t.start()

    def scan(prefix):
        return next((l for l in list(lines) if l.startswith(prefix)), None)

    alive = False
    deadline = time.monotonic() + alive_timeout_s
    try:
        while True:
            line = scan("BENCH_JSON ")
            if line is not None:
                proc.wait()
                return json.loads(line[len("BENCH_JSON "):]), None
            if not alive and scan("BENCH_ALIVE") is not None:
                alive = True
                deadline = time.monotonic() + timeout_s
            if done.is_set() and proc.poll() is not None:
                break
            if time.monotonic() > deadline:
                proc.kill()
                proc.wait()
                return None, ("backend-init watchdog expired" if not alive
                              else "measurement timeout")
            time.sleep(0.25)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    final = scan("BENCH_JSON ")  # may have landed between last scan and exit
    if final is not None:
        return json.loads(final[len("BENCH_JSON "):]), None
    return None, f"rc={proc.returncode} tail={''.join(lines)[-1500:]}"


def _attach_live_capture_pointers(result: dict) -> None:
    """Point a CPU-fallback record at committed live captures.

    Prefers WITNESSED captures (battery-produced; the watchdog log
    corroborates the live window) over raw max-value (VERDICT r4 weak #1);
    the overall max is reported alongside when it differs.  Labeled with
    round provenance either way.
    """
    import glob
    import re

    candidates = []
    for path in sorted(glob.glob(os.path.join(_REPO, "benchmarks", "results_r*_tpu.json"))):
        try:
            with open(path) as fh:
                live = json.load(fh).get("headline", {})
        except Exception:
            continue
        if live.get("platform") != "tpu":
            continue
        m = re.search(r"results_r(\w+)_tpu", path)
        candidates.append({
            "sigs_per_sec": live.get("value"),
            "vs_baseline": live.get("vs_baseline"),
            "round": m.group(1) if m else "?",
            "witnessed": bool(live.get("witnessed")),
            "source": f"{os.path.relpath(path, _REPO)} (committed live capture)",
        })
    if not candidates:
        return
    witnessed = [c for c in candidates if c["witnessed"]]
    pool = witnessed or candidates
    best = max(pool, key=lambda c: c["sigs_per_sec"] or 0)
    result["last_live_tpu_capture"] = best
    overall = max(candidates, key=lambda c: c["sigs_per_sec"] or 0)
    if overall["source"] != best["source"]:
        result["max_live_tpu_capture_any_round"] = overall


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        _child()
        return
    errors = []
    # Two TPU attempts (fresh backend init each) before conceding the chip.
    for attempt in range(2):
        result, err = _run_child(force_cpu=False, timeout_s=1200)
        if result is not None:
            print(json.dumps(result))
            return
        errors.append(f"attempt{attempt}: {err}")
    result, err = _run_child(force_cpu=True, timeout_s=1800)
    if result is None:
        print(json.dumps({
            "metric": "ed25519_batch_verify_throughput", "value": 0,
            "unit": "sigs/sec", "vs_baseline": 0, "error": "; ".join(errors + [str(err)]),
        }))
        sys.exit(1)
    # LOUD: this number is a CPU-backend fallback, not the TPU story.
    result["tpu_unreachable"] = True
    result["tpu_errors"] = errors
    # Point at the best committed LIVE capture so the evidence is one hop
    # away even when the tunnel is dead at snapshot time (clearly labeled —
    # the headline "value" above stays the honest CPU number).  Scan every
    # round's results file: the driver invokes bench.py without
    # MOCHI_BENCH_ROUND, and the newest round may predate its first live
    # window.
    try:
        _attach_live_capture_pointers(result)
    except Exception:
        pass
    print(json.dumps(result))


if __name__ == "__main__":
    main()

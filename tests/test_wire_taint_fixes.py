"""Regression pins for the wire-taint pass (PR 16).

Three layers:

1. **The true positive, fixed** — the pass convicted exactly one live
   flow: WAL reclaim records replayed into ``store.reclaimed`` (and the
   epoch bump) with only CRC framing between the attacker and the ledger.
   CRCs are torn-tail detection, not authentication: an adversary who
   rewrites its own log recomputes them trivially (the exact threat model
   test_storage.py's tamper suite pins for *commit* records, which
   re-verify their certificates — reclaims had nothing).  The fix gives
   every reclaim record a node-keyed HMAC bound to its log position,
   re-verified at replay through ``_reclaim_auth_ok`` — the sanctioned
   ``wal``-class verifier edge in the registry.  These tests pin the
   round trip, the conviction of tampered/relocated/forged records, and
   the legacy-acceptance ratchet.

2. **Non-vacuity of the registry** — deleting any single sanctioned
   verifier edge must convict the downstream sink: a seeded mutation
   sweep over the good fixture (every sanitizer site covered), plus live-
   tree mutations that strip ``_grant_ok`` / ``_auth_mac`` /
   ``_check_certificate`` from the real client/replica and require the
   full-tree scan to turn red.  This is what makes "the tree scans clean"
   meaningful.

3. **Machinery** — fast-path edge registration (ROADMAP item 1 contract),
   suppression + hygiene interaction, ``--changed-only`` gating, and the
   per-file cache (warm-run identity, mtime invalidation, worker-pool
   equivalence).
"""

from __future__ import annotations

import asyncio
import os
import random
import re
import shutil
import subprocess
import sys
import textwrap

import pytest

from mochi_tpu.analysis import core, wire_taint
from mochi_tpu.cluster import ClusterConfig
from mochi_tpu.server.store import DataStore
from mochi_tpu.storage import wal
from mochi_tpu.storage.durable import RECLAIM_KEY_FILE, DurableStorage

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "analysis_fixtures")
SID = "server-0"


def make_store(sid: str = SID) -> DataStore:
    cfg = ClusterConfig.build(
        {f"server-{i}": f"127.0.0.1:{8001 + i}" for i in range(4)}, rf=4
    )
    return DataStore(sid, cfg)


def _rewrite_segment(directory: str, server_id: str, mutate) -> None:
    """Adversarial rewrite with CORRECT CRCs (an attacker recomputes them
    trivially — framing is not the integrity argument)."""
    _index, path = wal.list_segments(directory)[-1]
    with open(path, "rb") as fh:
        data = fh.read()
    start = wal.read_segment_header(data, server_id)
    scan = wal.scan_segment(data, server_id)
    assert not scan.torn
    records = [[r.seq, r.rtype, r.body] for r in scan.records]
    mutate(records)
    with open(path, "wb") as fh:
        fh.write(
            data[:start]
            + b"".join(wal.encode_record(s, t, b) for s, t, b in records)
        )


async def _staged_reclaim_dir(td: str) -> str:
    """A storage dir whose WAL holds one MAC'd reclaim record."""
    d = os.path.join(td, SID)
    eng = DurableStorage(d, SID)
    await eng.start()
    eng.stage_reclaim("k1", 7, b"h" * 32, 3)
    await eng.flush()
    await eng.close()
    return d


# ------------------------------------------------- 1. the fixed seam


def test_reclaim_roundtrip_replays_with_mac(tmp_path):
    async def body():
        d = await _staged_reclaim_dir(str(tmp_path))
        eng = DurableStorage(d, SID)
        assert not eng._reclaim_key_created  # key survived the restart
        store = make_store()
        report = await eng.recover(store)
        assert report["convicted"] == 0, report
        assert report["reclaims"] == 1
        assert report.get("legacy_reclaims", 0) == 0
        assert store.reclaimed[("k1", 7)] == b"h" * 32
        assert store._get_or_create("k1").current_epoch == 3

    asyncio.run(asyncio.wait_for(body(), timeout=60))


def test_tampered_reclaim_body_convicted(tmp_path):
    """Mutate the granted hash inside the logged reclaim (CRC recomputed):
    the MAC no longer covers the bytes, replay convicts, and NOTHING of
    the record — ledger entry, epoch bump — is adopted."""

    async def body():
        d = await _staged_reclaim_dir(str(tmp_path))

        def mutate(records):
            assert records[-1][1] == wal.RT_RECLAIM
            records[-1][2][2] = b"EVIL" * 8  # granted_hash slot

        _rewrite_segment(d, SID, mutate)
        store = make_store()
        report = await DurableStorage(d, SID).recover(store)
        assert report["convicted"] == 1, report
        assert any(
            "reclaim MAC mismatch" in c["reason"]
            for c in report["convictions"]
        ), report
        assert store.reclaimed == {}
        assert store._get_or_create("k1").current_epoch == 0

    asyncio.run(asyncio.wait_for(body(), timeout=60))


def test_relocated_reclaim_convicted(tmp_path):
    """The MAC binds the record's sequence number: replaying the SAME
    valid body at a different log position (a splice/reorder attack) is
    convicted even though the body bytes are untouched."""

    async def body():
        d = await _staged_reclaim_dir(str(tmp_path))

        def mutate(records):
            records[-1][0] = records[-1][0] + 1  # shift the seq, keep body

        _rewrite_segment(d, SID, mutate)
        store = make_store()
        report = await DurableStorage(d, SID).recover(store)
        assert any(
            "reclaim MAC mismatch" in c["reason"]
            for c in report["convictions"]
        ), report
        assert store.reclaimed == {}

    asyncio.run(asyncio.wait_for(body(), timeout=60))


def test_forged_reclaim_without_node_key_convicted(tmp_path):
    """An attacker who can write the log but has no node key cannot mint
    an acceptable reclaim: a fresh 5-ary record with a guessed MAC is
    convicted."""

    async def body():
        d = await _staged_reclaim_dir(str(tmp_path))

        def mutate(records):
            records.append(
                [records[-1][0] + 1, wal.RT_RECLAIM,
                 ["k2", 9, b"g" * 32, 5, b"\x00" * 32]]
            )

        _rewrite_segment(d, SID, mutate)
        store = make_store()
        report = await DurableStorage(d, SID).recover(store)
        assert report["reclaims"] == 1  # the genuine record still lands
        assert ("k2", 9) not in store.reclaimed
        assert any(
            "reclaim MAC mismatch" in c["reason"]
            for c in report["convictions"]
        ), report

    asyncio.run(asyncio.wait_for(body(), timeout=60))


def test_legacy_reclaim_ratchet(tmp_path):
    """Pre-MAC logs stay replayable exactly once: a 4-ary legacy body is
    accepted (and counted) when no reclaim key predated this boot — the
    log necessarily predates the upgrade — but the moment a key exists,
    bare bodies are tampering and convict."""

    def write_legacy_segment(d: str) -> None:
        os.makedirs(d, exist_ok=True)
        w = wal.SegmentWriter(os.path.join(d, wal.segment_name(1)), SID, 1)
        w.append(wal.encode_record(1, wal.RT_RECLAIM, ["old", 4, b"x" * 32, 2]))
        w.close()

    async def body():
        # leg 1: fresh dir, no key on disk -> key minted this boot ->
        # legacy record accepted and counted
        d1 = str(tmp_path / "fresh")
        write_legacy_segment(d1)
        assert not os.path.exists(os.path.join(d1, RECLAIM_KEY_FILE))
        eng = DurableStorage(d1, SID)
        assert eng._reclaim_key_created
        store = make_store()
        report = await eng.recover(store)
        assert report["convicted"] == 0, report
        assert report.get("legacy_reclaims") == 1, report
        assert store.reclaimed[("old", 4)] == b"x" * 32

        # leg 2: the key now exists -> the SAME legacy body is convicted
        store2 = make_store()
        eng2 = DurableStorage(d1, SID)
        assert not eng2._reclaim_key_created
        report2 = await eng2.recover(store2)
        assert any(
            "reclaim missing MAC" in c["reason"]
            for c in report2["convictions"]
        ), report2
        assert store2.reclaimed == {}

    asyncio.run(asyncio.wait_for(body(), timeout=60))


def test_reclaim_key_file_permissions(tmp_path):
    d = str(tmp_path / SID)
    eng = DurableStorage(d, SID)
    path = os.path.join(d, RECLAIM_KEY_FILE)
    assert os.path.exists(path)
    assert os.stat(path).st_mode & 0o077 == 0, "key must be owner-only"
    assert len(eng._reclaim_key) >= 16


# --------------------------------------- 2. non-vacuity of the registry


def run_cli(*args: str, cwd: str = REPO, env_extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-m", "mochi_tpu.analysis", *args],
        cwd=cwd, env=env, capture_output=True, text=True, timeout=180,
    )


_SANITIZER_CALL = re.compile(
    r"self\.(_auth_mac|_authentic|_reclaim_auth_ok|_grant_ok)\(([^)]*)\)"
)


def _good_fixture_sites():
    with open(os.path.join(FIXTURES, "wire_taint_good.py")) as fh:
        src = fh.read()
    sites = list(_SANITIZER_CALL.finditer(src))
    assert len(sites) >= 5, "good fixture lost its sanitizer sites"
    return src, sites


def _drop_site(src: str, m: re.Match) -> str:
    """Replace one sanitizer call with a taint-free stand-in (``bool`` is a
    registered clean call), preserving syntax — the verifier edge is gone,
    the control flow stays."""
    first = m.group(2).split(",")[0].strip()
    return src[: m.start()] + f"bool({first})" + src[m.end():]


@pytest.mark.parametrize("seed", range(8))
def test_seeded_sanitizer_drop_convicts(seed, tmp_path):
    src, sites = _good_fixture_sites()
    rng = random.Random(seed)
    m = sites[rng.randrange(len(sites))]
    target = tmp_path / "mutated.py"
    target.write_text(_drop_site(src, m))
    result = core.run([str(target)], rules=["wire-taint"], scoped=False,
                      cache=False)
    assert result.new, f"seed {seed}: dropping {m.group(1)} did not convict"
    assert all(f.rule == "wire-taint" for f in result.new)


def test_every_fixture_sanitizer_site_is_load_bearing(tmp_path):
    """Exhaustive companion to the seeded sweep: EVERY sanitizer call in
    the good fixture convicts its sink when dropped — no edge in the
    corpus is decorative."""
    src, sites = _good_fixture_sites()
    for i, m in enumerate(sites):
        target = tmp_path / f"mut{i}.py"
        target.write_text(_drop_site(src, m))
        result = core.run([str(target)], rules=["wire-taint"], scoped=False,
                          cache=False)
        assert result.new, f"site {i} ({m.group(1)}) is vacuous"


LIVE_MUTATIONS = [
    # (file, original, replacement, sink expected to convict)
    ("mochi_tpu/client/client.py",
     "and self._grant_ok(p.multi_grant, txn_hash)",
     "and p.multi_grant is not None",
     "grant-subset"),
    ("mochi_tpu/server/replica.py",
     "if not self._auth_mac(env):",
     "if not bool(env):",
     "-apply"),
    # round 18: sync-adopt checks the aggregate fast edge first, then the
    # attributing per-grant audit — dropping BOTH must convict the sink
    # (the memo warm-up gather above the loop discards its result, so it
    # alone cannot launder the entry)
    ("mochi_tpu/server/replica.py",
     "checked = await self._check_certificate_fast(\n"
     "                        entry.certificate\n"
     "                    )\n"
     "                    if checked is None:\n"
     "                        # fast path off, aggregate ineligible, or a failed\n"
     "                        # aggregate: the attributing per-grant audit\n"
     "                        checked = await self._check_certificate(\n"
     "                            entry.certificate\n"
     "                        )",
     "checked = entry.certificate",
     "sync-adopt"),
    # round 17: the paged engine's fault path — drop the per-entry recheck
    # between read_page_entry (taint source) and apply_sync_entry (CERT
    # sink) and the disk-tainted entry reaches adoption unsanctioned
    ("mochi_tpu/storage/paged.py",
     "if not self._page_entry_admissible(store, key, txn, cert, ent):",
     "if txn is None and cert is None:",
     "sync-adopt"),
]


@pytest.mark.parametrize("path,old,new,sink", LIVE_MUTATIONS)
def test_live_tree_verifier_edge_is_load_bearing(path, old, new, sink,
                                                 tmp_path):
    """Strip one sanctioned verifier call from the REAL tree: the full
    scan must convict the downstream sink.  This is the acceptance
    criterion's non-vacuity proof on live code, not fixtures."""
    root = tmp_path / "tree"
    shutil.copytree(
        os.path.join(REPO, "mochi_tpu"), root / "mochi_tpu",
        ignore=shutil.ignore_patterns("__pycache__", "*.so"),
    )
    target = root / path
    src = target.read_text()
    assert old in src, f"mutation anchor drifted: {old!r} not in {path}"
    target.write_text(src.replace(old, new))
    proc = run_cli("mochi_tpu/", "--rules", "wire-taint", cwd=str(root),
                   env_extra={"MOCHI_ANALYSIS_CACHE": "0"})
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "wire-taint" in proc.stdout
    assert sink in proc.stdout, proc.stdout


def test_live_tree_copy_scans_clean(tmp_path):
    """Harness control for the mutation tests: the UNMUTATED copy scans
    clean, so the convictions above are caused by the mutation alone."""
    root = tmp_path / "tree"
    shutil.copytree(
        os.path.join(REPO, "mochi_tpu"), root / "mochi_tpu",
        ignore=shutil.ignore_patterns("__pycache__", "*.so"),
    )
    proc = run_cli("mochi_tpu/", "--rules", "wire-taint", cwd=str(root),
                   env_extra={"MOCHI_ANALYSIS_CACHE": "0"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ----------------------------------------------------- 3. the machinery


FAST_PATH_SRC = textwrap.dedent(
    """
    class FastReplica:
        def on_frame(self, frame, store):
            env = decode_env(frame)
            if not self._fast_mac_ok(env):
                return None
            return store.process_write1(env)
    """
)


def test_fast_path_must_register_verifier_edge(tmp_path):
    """ROADMAP item 1 contract: an unregistered fast-path check is NOT a
    verifier — the sink downstream convicts until the edge is registered
    via register_verifier_edge (and registration clears it)."""
    target = tmp_path / "fast.py"
    target.write_text(FAST_PATH_SRC)
    before = core.run([str(target)], rules=["wire-taint"], scoped=False,
                      cache=False)
    assert len(before.new) == 1 and before.new[0].rule == "wire-taint"
    edge = wire_taint.register_verifier_edge(
        "fast-mac", "_fast_mac_ok", [wire_taint.CLS_ENV],
        note="test fast path",
    )
    try:
        after = core.run([str(target)], rules=["wire-taint"], scoped=False,
                         cache=False)
        assert after.new == [], [f.render() for f in after.new]
    finally:
        wire_taint._RUNTIME_EDGES.remove(edge)


def test_wire_taint_suppression_and_hygiene(tmp_path):
    bad = (
        "class R:\n"
        "    def f(self, frame, store):\n"
        "        env = decode_env(frame)\n"
        "        # mochi-lint: disable=wire-taint -- byzantine harness, "
        "unverified by design\n"
        "        return store.process_write1(env)\n"
    )
    target = tmp_path / "supp.py"
    target.write_text(bad)
    result = core.run([str(target)], rules=["wire-taint"], scoped=False,
                      cache=False)
    assert result.new == [] and len(result.suppressed) == 1
    # hygiene: the same comment with the finding fixed is itself a finding
    clean = bad.replace("env = decode_env(frame)", "env = frame")
    target.write_text(clean)
    result2 = core.run([str(target)], scoped=False, hygiene=True, cache=False)
    assert any(f.rule == core.HYGIENE_RULE for f in result2.new), [
        f.render() for f in result2.new
    ]


def test_changed_only_gates_wire_taint(tmp_path):
    """A PR adding an unverified flow fails --changed-only; pre-existing
    debt in untouched files only warns."""

    def git(*args):
        return subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
            cwd=str(repo), capture_output=True, text=True, timeout=30,
        )

    repo = tmp_path / "repo"
    (repo / "pkg").mkdir(parents=True)
    subprocess.run(["git", "init", "-q", str(repo)], cwd=str(tmp_path),
                   capture_output=True, timeout=30)
    shutil.copy(os.path.join(FIXTURES, "wire_taint_bad.py"),
                repo / "pkg" / "old.py")
    git("add", "-A")
    assert git("commit", "-q", "-m", "seed").returncode == 0
    shutil.copy(os.path.join(FIXTURES, "wire_taint_bad.py"),
                repo / "pkg" / "new.py")
    proc = run_cli("pkg", "--changed-only", "HEAD", "--no-path-filter",
                   cwd=str(repo), env_extra={"MOCHI_ANALYSIS_CACHE": "0"})
    assert proc.returncode == 1, proc.stdout + proc.stderr
    lines = proc.stdout.splitlines()
    assert any(ln.startswith("pkg/new.py") and "[wire-taint" in ln
               for ln in lines), proc.stdout
    assert any(ln.startswith("warning") and "pkg/old.py" in ln
               for ln in lines), proc.stdout


def test_cache_warm_run_identical(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    for name in ("wire_taint_bad.py", "unbounded_growth_bad.py",
                 "await_races_bad.py"):
        shutil.copy(os.path.join(FIXTURES, name), pkg / name)
    cdir = str(tmp_path / "cache")
    env = {"MOCHI_ANALYSIS_CACHE_DIR": cdir, "MOCHI_ANALYSIS_CACHE": "1"}
    cold = run_cli(str(pkg), "--no-path-filter", cwd=str(tmp_path),
                   env_extra=env)
    assert os.listdir(cdir), "cold run populated no cache records"
    warm = run_cli(str(pkg), "--no-path-filter", cwd=str(tmp_path),
                   env_extra=env)
    assert cold.returncode == warm.returncode == 1
    assert cold.stdout == warm.stdout, (
        "warm (cached) run diverged from cold run"
    )


def test_cache_invalidated_on_edit(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    target = pkg / "mod.py"
    target.write_text("def f(frame):\n    return frame\n")
    cdir = str(tmp_path / "cache")
    env = {"MOCHI_ANALYSIS_CACHE_DIR": cdir, "MOCHI_ANALYSIS_CACHE": "1"}
    first = run_cli(str(pkg), "--no-path-filter", cwd=str(tmp_path),
                    env_extra=env)
    assert first.returncode == 0, first.stdout + first.stderr
    target.write_text(
        "def f(frame, store):\n"
        "    env = decode_env(frame)\n"
        "    return store.process_write1(env)\n"
    )
    # ensure the mtime moves even on coarse filesystem clocks
    st = os.stat(target)
    os.utime(target, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
    second = run_cli(str(pkg), "--no-path-filter", cwd=str(tmp_path),
                     env_extra=env)
    assert second.returncode == 1, (
        "stale cache served: the edited file's finding was dropped\n"
        + second.stdout + second.stderr
    )
    assert "[wire-taint" in second.stdout


def test_worker_pool_matches_serial():
    paths = [os.path.join(FIXTURES, n) for n in sorted(os.listdir(FIXTURES))
             if n.endswith(".py")]
    serial = core.run(paths, scoped=False, cache=False, jobs=1)
    pooled = core.run(paths, scoped=False, cache=False, jobs=4)

    def key(result):
        return sorted(f.fingerprint for f in result.new)

    assert key(serial) == key(pooled)
    assert serial.files_scanned == pooled.files_scanned

"""Datastore state-machine unit tests: grants, certificates, apply, epochs.

Drives 4 DataStore instances as a simulated RF=4 replica set (no networking),
checking the protocol semantics SURVEY.md §2.5 catalogues: grant issuance,
idempotent retry, refusal on contention, quorum/hash enforcement at Write2,
stale read-back, epoch advancement, and the grant GC the reference never
wired up.
"""

import pytest

from mochi_tpu.cluster import ClusterConfig
from mochi_tpu.protocol import (
    Action,
    FailType,
    MultiGrant,
    Operation,
    RequestFailedFromServer,
    Status,
    Transaction,
    Write1OkFromServer,
    Write1RefusedFromServer,
    Write1ToServer,
    Write2AnsFromServer,
    Write2ToServer,
    WriteCertificate,
    transaction_hash,
)
from mochi_tpu.server.store import EPOCH_UNIT, GRANT_GC_EPOCHS, DataStore


def make_cluster(n=4, rf=4):
    cfg = ClusterConfig.build(
        {f"server-{i}": f"127.0.0.1:{8001 + i}" for i in range(n)}, rf=rf
    )
    return cfg, [DataStore(f"server-{i}", cfg) for i in range(n)]


def wtxn(key: str, value: bytes) -> Transaction:
    return Transaction((Operation(Action.WRITE, key, value),))


def write1_everywhere(stores, txn, seed=42, client="client-1"):
    """Run Write1 on each store for txn's keys; return responses."""
    blind = Transaction(tuple(Operation(Action.WRITE, op.key, None) for op in txn.operations))
    req = Write1ToServer(client, blind, seed, transaction_hash(txn))
    return [s.process_write1(req) for s in stores]


def certificate_from(responses) -> WriteCertificate:
    return WriteCertificate(
        {r.multi_grant.server_id: r.multi_grant for r in responses if isinstance(r, Write1OkFromServer)}
    )


def commit_everywhere(stores, txn, wc):
    return [s.process_write2(Write2ToServer(wc, txn)) for s in stores]


def test_write1_grants_and_write2_apply():
    _, stores = make_cluster()
    txn = wtxn("alpha", b"v1")
    responses = write1_everywhere(stores, txn, seed=123)
    assert all(isinstance(r, Write1OkFromServer) for r in responses)
    for r in responses:
        grant = r.multi_grant.grants["alpha"]
        assert grant.status == Status.OK
        assert grant.timestamp == 123  # epoch 0 + seed

    wc = certificate_from(responses)
    answers = commit_everywhere(stores, txn, wc)
    for ans in answers:
        assert isinstance(ans, Write2AnsFromServer)
        (op_res,) = ans.result.operations
        assert op_res.value == b"v1"
        assert op_res.status == Status.OK
        assert op_res.existed is False  # fresh key

    for s in stores:
        sv = s.data["alpha"]
        assert sv.value == b"v1" and sv.exists
        assert sv.current_epoch == EPOCH_UNIT  # advanced past applied ts
        assert sv.grant_at(123) is None  # consumed


def test_write1_idempotent_retry_same_hash():
    _, stores = make_cluster()
    txn = wtxn("k", b"v")
    r1 = write1_everywhere(stores, txn, seed=7)
    r2 = write1_everywhere(stores, txn, seed=7)  # retry, same hash
    for a, b in zip(r1, r2):
        assert isinstance(b, Write1OkFromServer)
        assert a.multi_grant.grants["k"] == b.multi_grant.grants["k"]


def test_write1_refused_on_contention():
    _, stores = make_cluster()
    t1, t2 = wtxn("k", b"v1"), wtxn("k", b"v2")
    write1_everywhere(stores, t1, seed=7, client="client-1")
    responses = write1_everywhere(stores, t2, seed=7, client="client-2")
    for r in responses:
        assert isinstance(r, Write1RefusedFromServer)
        assert r.multi_grant.grants["k"].status == Status.REFUSED


def test_different_seed_avoids_contention():
    _, stores = make_cluster()
    write1_everywhere(stores, wtxn("k", b"v1"), seed=7, client="client-1")
    responses = write1_everywhere(stores, wtxn("k", b"v2"), seed=8, client="client-2")
    assert all(isinstance(r, Write1OkFromServer) for r in responses)


def test_write2_rejects_thin_certificate():
    cfg, stores = make_cluster()
    txn = wtxn("k", b"v")
    responses = write1_everywhere(stores, txn)
    wc = certificate_from(responses[:2])  # 2 < quorum (3)
    ans = stores[0].process_write2(Write2ToServer(wc, txn))
    assert isinstance(ans, RequestFailedFromServer)
    assert ans.fail_type == FailType.BAD_CERTIFICATE


def test_write2_rejects_hash_mismatch():
    _, stores = make_cluster()
    txn = wtxn("k", b"v")
    responses = write1_everywhere(stores, txn)
    wc = certificate_from(responses)
    other_txn = wtxn("k", b"DIFFERENT")  # hash differs from granted hash
    ans = stores[0].process_write2(Write2ToServer(wc, other_txn))
    assert isinstance(ans, RequestFailedFromServer)
    assert ans.fail_type == FailType.BAD_CERTIFICATE


def test_write2_rejects_timestamp_disagreement():
    _, stores = make_cluster()
    txn = wtxn("k", b"v")
    responses = write1_everywhere(stores, txn, seed=5)
    wc = certificate_from(responses)
    # Forge one server's grant to a different timestamp.
    sid = "server-1"
    mg = wc.grants[sid]
    bad_grant = mg.grants["k"]
    from dataclasses import replace

    forged = MultiGrant(
        grants={"k": replace(bad_grant, timestamp=bad_grant.timestamp + 1)},
        client_id=mg.client_id,
        server_id=sid,
    )
    wc = WriteCertificate({**wc.grants, sid: forged})
    ans = stores[0].process_write2(Write2ToServer(wc, txn))
    assert isinstance(ans, RequestFailedFromServer)
    assert ans.fail_type == FailType.BAD_CERTIFICATE


def test_stale_write2_reads_back():
    _, stores = make_cluster()
    t_new = wtxn("k", b"new")
    t_old = wtxn("k", b"old")
    r_old = write1_everywhere(stores, t_old, seed=10, client="c1")
    r_new = write1_everywhere(stores, t_new, seed=900, client="c2")
    # Commit the higher-timestamp txn first...
    commit_everywhere(stores, t_new, certificate_from(r_new))
    # ...then the stale one: replicas must answer with current state, not clobber.
    answers = commit_everywhere(stores, t_old, certificate_from(r_old))
    for ans in answers:
        assert isinstance(ans, Write2AnsFromServer)
        (op_res,) = ans.result.operations
        assert op_res.value == b"new"
    assert all(s.data["k"].value == b"new" for s in stores)


def test_read_roundtrip_and_missing_key():
    _, stores = make_cluster()
    txn = wtxn("k", b"v")
    commit_everywhere(stores, txn, certificate_from(write1_everywhere(stores, txn)))
    read = stores[0].process_read(Transaction((Operation(Action.READ, "k"), Operation(Action.READ, "nope"))))
    assert read.operations[0].value == b"v" and read.operations[0].existed
    assert read.operations[1].value is None and not read.operations[1].existed


def test_delete_clears_value():
    _, stores = make_cluster()
    txn = wtxn("k", b"v")
    commit_everywhere(stores, txn, certificate_from(write1_everywhere(stores, txn)))
    dtxn = Transaction((Operation(Action.DELETE, "k"),))
    responses = write1_everywhere(stores, dtxn, seed=99)
    answers = commit_everywhere(stores, dtxn, certificate_from(responses))
    for ans in answers:
        (op_res,) = ans.result.operations
        assert op_res.existed is True  # existed before delete
    read = stores[0].process_read(Transaction((Operation(Action.READ, "k"),)))
    assert read.operations[0].existed is False


def test_multikey_transaction_atomic_grants():
    _, stores = make_cluster()
    txn = Transaction(
        (Operation(Action.WRITE, "a", b"1"), Operation(Action.WRITE, "b", b"2"))
    )
    responses = write1_everywhere(stores, txn)
    for r in responses:
        assert set(r.multi_grant.grants) == {"a", "b"}
    answers = commit_everywhere(stores, txn, certificate_from(responses))
    for ans in answers:
        assert [o.value for o in ans.result.operations] == [b"1", b"2"]


def test_grant_gc_on_epoch_advance():
    _, stores = make_cluster()
    store = stores[0]
    # Issue a grant, then advance the epoch far past the GC horizon.
    txn = wtxn("k", b"v")
    write1_everywhere([store], txn, seed=1)
    sv = store.data["k"]
    assert sv.grants
    sv.advance_epoch(sv.current_epoch + GRANT_GC_EPOCHS + 5 * EPOCH_UNIT)
    assert not sv.grants  # stale epochs collected (ref never called its GC)


def test_wrong_shard_status_for_unowned_keys():
    cfg, stores = make_cluster(n=6, rf=4)
    # find a key and a server outside its replica set
    key, outsider = None, None
    for i in range(1000):
        candidate = f"key-{i}"
        rs = set(cfg.replica_set_for_key(candidate))
        others = set(cfg.servers) - rs
        if others:
            key, outsider = candidate, sorted(others)[0]
            break
    assert key is not None
    store = next(s for s in stores if s.server_id == outsider)
    read = store.process_read(Transaction((Operation(Action.READ, key),)))
    assert read.operations[0].status == Status.WRONG_SHARD
    w1 = store.process_write1(
        Write1ToServer("c", Transaction((Operation(Action.WRITE, key, None),)), 5, b"h")
    )
    assert w1.multi_grant.grants[key].status == Status.WRONG_SHARD


def test_duplicate_key_transaction_last_write_wins():
    """Ops on the same key apply SEQUENTIALLY — the reference's per-op
    applyOperation loop (InMemoryDataStore.java:521-554) makes the last
    write win; round-1 behavior (first-op-wins short-circuit) diverged
    (VERDICT r1 weak #8)."""
    _, stores = make_cluster()
    txn = Transaction(
        (
            Operation(Action.WRITE, "dup", b"first"),
            Operation(Action.WRITE, "dup", b"second"),
        )
    )
    responses = write1_everywhere(stores, txn)
    wc = certificate_from(responses)
    answers = commit_everywhere(stores, txn, wc)
    for ans in answers:
        assert isinstance(ans, Write2AnsFromServer)
        assert len(ans.result.operations) == 2
    for store in stores:
        assert store.data["dup"].value == b"second"

    # write-then-delete in one txn: the delete lands last
    txn2 = Transaction(
        (
            Operation(Action.WRITE, "dup2", b"x"),
            Operation(Action.DELETE, "dup2"),
        )
    )
    wc2 = certificate_from(write1_everywhere(stores, txn2, seed=7))
    for ans in commit_everywhere(stores, txn2, wc2):
        assert isinstance(ans, Write2AnsFromServer)
    for store in stores:
        sv = store.data["dup2"]
        assert not sv.exists and sv.value is None

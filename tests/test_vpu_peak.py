"""CPU dry-run of the battery's VPU-peak step (scripts/vpu_peak.py).

The measured peak becomes the MFU denominator for every subsequent bench
record (bench._measured_vpu_peak), and the step runs unattended in a live
TPU window — a bug found on-chip wastes the window (same rationale as
test_flash_dryrun).  Checks: the record shape bench.py consumes, the
RTT-domination guard (a flagged config must never set the headline), and
that a CPU run never writes benchmarks/vpu_peak.json (a host-core number
must not become the chip's denominator).
"""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load():
    spec = importlib.util.spec_from_file_location(
        "vpu_peak", os.path.join(REPO, "scripts", "vpu_peak.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_vpu_peak_cpu_dryrun(tmp_path, monkeypatch):
    mod = _load()
    monkeypatch.setattr(mod, "_REPO", str(tmp_path))
    os.makedirs(tmp_path / "benchmarks")
    rec = mod.measure(allow_cpu=True)
    assert rec["metric"] == "vpu_int32_madd_peak"
    assert rec["value"] > 0
    assert rec["platform"] == "cpu"
    assert rec["unit"] == "int_ops/sec"
    assert isinstance(rec["tunnel_rtt_ms"], float)
    for cfg in rec["table"].values():
        assert cfg["int_ops_per_sec_raw"] <= cfg["int_ops_per_sec"] * 1.001
    # bench.py's consumer contract: these are the keys it reads
    assert set(rec) >= {"value", "platform", "table", "measured_over_assumed"}
    # CPU runs must NOT write the file the MFU accounting prefers
    assert not os.path.exists(tmp_path / "benchmarks" / "vpu_peak.json")


def test_bench_prefers_measured_peak(tmp_path, monkeypatch):
    import json

    import bench

    monkeypatch.setattr(bench, "_REPO", str(tmp_path))
    os.makedirs(tmp_path / "benchmarks")
    # no file -> assumed figure
    peak, src = bench._measured_vpu_peak()
    assert peak == bench.VPU_PEAK_INT_OPS and "assumed" in src
    # tpu-measured file -> preferred
    with open(tmp_path / "benchmarks" / "vpu_peak.json", "w") as fh:
        json.dump({"platform": "tpu", "value": 2.5e12}, fh)
    peak, src = bench._measured_vpu_peak()
    assert peak == 2.5e12 and "measured" in src
    # a cpu-platform file must be ignored
    with open(tmp_path / "benchmarks" / "vpu_peak.json", "w") as fh:
        json.dump({"platform": "cpu", "value": 9.9e12}, fh)
    peak, _ = bench._measured_vpu_peak()
    assert peak == bench.VPU_PEAK_INT_OPS


def test_live_capture_pointer_prefers_witnessed(tmp_path, monkeypatch):
    """The driver-visible fallback pointer must rank a watchdog-witnessed
    capture above a larger unwitnessed one, reporting the overall max
    alongside (VERDICT r4 weak #1)."""
    import json

    import bench

    monkeypatch.setattr(bench, "_REPO", str(tmp_path))
    bdir = tmp_path / "benchmarks"
    os.makedirs(bdir)
    with open(bdir / "results_r02_tpu.json", "w") as fh:
        json.dump({"headline": {"platform": "tpu", "value": 111300.0}}, fh)
    with open(bdir / "results_r04_tpu.json", "w") as fh:
        json.dump({"headline": {
            "platform": "tpu", "value": 105099.5, "witnessed": True,
        }}, fh)
    rec = {}
    bench._attach_live_capture_pointers(rec)
    assert rec["last_live_tpu_capture"]["sigs_per_sec"] == 105099.5
    assert rec["last_live_tpu_capture"]["witnessed"] is True
    assert rec["last_live_tpu_capture"]["round"] == "04"
    assert rec["max_live_tpu_capture_any_round"]["sigs_per_sec"] == 111300.0

    # no witnessed captures at all -> plain max, no duplicate second key
    with open(bdir / "results_r04_tpu.json", "w") as fh:
        json.dump({"headline": {"platform": "tpu", "value": 105099.5}}, fh)
    rec = {}
    bench._attach_live_capture_pointers(rec)
    assert rec["last_live_tpu_capture"]["sigs_per_sec"] == 111300.0
    assert "max_live_tpu_capture_any_round" not in rec

"""Chaos scenario: concurrent traffic + replica restart + live reconfig.

The reference's suite tests each behavior in isolation (SURVEY.md §4); the
failure modes that kill real systems come from combinations.  One cluster
goes through everything at once: five clients stream writes while a
replica of the hot keys' set is restarted empty (resync re-hydrates) and
the administrator commits a membership change (configstamp 1 -> 2) — every
acknowledged write must be readable afterwards under the new config.
"""

from __future__ import annotations

import asyncio

from mochi_tpu.client.txn import TransactionBuilder
from mochi_tpu.netsim import LinkEvent, LinkSpec, NetSim
from mochi_tpu.testing.virtual_cluster import VirtualCluster


def run(coro):
    asyncio.run(coro)


def test_traffic_survives_restart_plus_reconfig():
    async def main():
        async with VirtualCluster(6, rf=4) as vc:
            committed: dict = {}
            stop = asyncio.Event()
            errors: list = []

            async def writer(ci: int):
                client = vc.client()
                i = 0
                while not stop.is_set():
                    key = f"chaos-{ci}-{i}"
                    val = b"v%d" % i
                    try:
                        await client.execute_write_transaction(
                            TransactionBuilder().write(key, val).build()
                        )
                        committed[key] = val
                    except Exception as exc:
                        # During the chaos window an individual txn may be
                        # refused (retryable); losing an ACKED write is the
                        # only real failure, checked below.
                        errors.append((key, repr(exc)))
                    i += 1
                    await asyncio.sleep(0)
                await client.close()

            writers = [asyncio.create_task(writer(i)) for i in range(5)]
            await asyncio.sleep(0.3)

            # restart one replica empty, with resync-on-boot
            await vc.restart_replica("server-2", resync=True)

            await asyncio.sleep(0.2)

            # live reconfiguration: drop the LAST server (ring shrinks,
            # keys migrate) while traffic continues
            admin = vc.client()
            servers = {
                sid: f"{info.host}:{info.port}"
                for sid, info in vc.config.servers.items()
                if sid != "server-5"
            }
            new_cfg = vc.config.evolve(servers)
            await admin.reconfigure_cluster(new_cfg)

            await asyncio.sleep(0.3)
            stop.set()
            await asyncio.gather(*writers)

            assert committed, "no write ever committed"
            # every acknowledged write must be readable under the new config
            reader = vc.client()
            for key, val in committed.items():
                res = await reader.execute_read_transaction(
                    TransactionBuilder().read(key).build()
                )
                assert res.operations[0].value == val, key
            await reader.close()
            await admin.close()
            # the run must have made real progress through the chaos window
            assert len(committed) >= 20, (len(committed), errors[:5])

    run(main())


def test_early_quorum_commit_bounded_by_quorum_not_straggler():
    """PR-5 tentpole pin: one replica of the write set pinned at +200 ms
    must not gate commits — the early-quorum write path waits for the
    2f+1st consistent verified answer, not the slowest replica.  The
    straggler still APPLIES every acked write (Write2 went to the full
    set; only the client's wait is quorum-bound), so after its links heal
    the acked values are present locally and readable cluster-wide, and
    the left-behind responses show up in the straggler metrics instead of
    vanishing."""

    async def main():
        sim = NetSim.mesh(seed=21, rtt_ms=13.0, jitter_ms=1.0)
        async with VirtualCluster(5, rf=4, netsim=sim) as vc:
            client = vc.client()
            # establish sessions before degrading anything
            await client.execute_write_transaction(
                TransactionBuilder().write("straggle-warm", b"w").build()
            )
            # pick a key whose replica set includes the straggler
            key = next(
                f"straggle-{i}"
                for i in range(64)
                if "server-3" in vc.config.replica_set_for_key(f"straggle-{i}")
            )
            # degrade server-3 both ways: ~+200 ms RTT on top of the mesh
            for src, dst in (("*", "server-3"), ("server-3", "*")):
                sim.apply_event(
                    LinkEvent(0.0, "set", src, dst,
                              LinkSpec(delay_ms=106.5, jitter_ms=0.5))
                )
            lat_ms = []
            for i in range(8):
                t0 = asyncio.get_running_loop().time()
                await client.execute_write_transaction(
                    TransactionBuilder().write(key, b"v%d" % i).build()
                )
                lat_ms.append((asyncio.get_running_loop().time() - t0) * 1e3)
            lat_ms.sort()
            # quorum-bound: median far below the straggler's ~213 ms RTT
            # (generous margin for this host's scheduler noise)
            assert lat_ms[len(lat_ms) // 2] < 150.0, lat_ms
            # stragglers were drained, not dropped: their late answers fed
            # the per-replica metrics
            counters = client.metrics.counters
            assert counters.get("fanout.early-return", 0) > 0, counters
            late = sum(
                n for name, n in counters.items()
                if name.startswith("fanout.late-response.")
            )
            timed_out = sum(
                n for name, n in counters.items()
                if name.startswith("fanout.straggler-timeout.")
            )
            assert late + timed_out > 0, counters
            assert any(
                name.startswith("fanout-straggler-ms.")
                for name in client.metrics.histograms
            ) or late == 0
            # heal, let the in-flight Write2 + responses land
            sim.apply_event(
                LinkEvent(0.0, "set", "*", "*",
                          LinkSpec(delay_ms=6.5, jitter_ms=0.5))
            )
            await asyncio.sleep(0.6)
            # the acked write reached the straggler's own store
            sv = vc.replica("server-3").store._get(key)
            assert sv is not None and sv.exists and bytes(sv.value) == b"v7"
            # ...and is readable cluster-wide after heal
            res = await client.execute_read_transaction(
                TransactionBuilder().read(key).build()
            )
            assert res.operations[0].value == b"v7"

    run(asyncio.wait_for(main(), timeout=120))


def test_acked_writes_survive_lossy_wan_partition_heal():
    """The restart+reconfig scenario above runs on a perfect loopback; real
    deployments lose the acked-write guarantee (or don't) under loss and
    partitions.  Same invariant, WAN-shaped: a 13 ms ± 1 ms lossy mesh
    (2% frame drop), one replica partitioned mid-traffic and healed — every
    write acked through that window must be readable after the heal (the
    client's nudge+resync recovery carries the healed replica back)."""

    async def main():
        sim = NetSim.mesh(
            seed=13,
            rtt_ms=13.0,
            jitter_ms=1.0,
            drop=0.02,
            schedule=NetSim.partition("server-1", at_s=0.6, heal_at_s=1.4),
        )
        async with VirtualCluster(5, rf=4, netsim=sim) as vc:
            committed: dict = {}
            errors: list = []
            stop = asyncio.Event()

            async def writer(ci: int):
                # Tight timeout: a dropped frame must cost 0.4 s, not the
                # 10 s default — the retry loop is the loss recovery.
                client = vc.client(timeout_s=0.4, write_attempts=12)
                i = 0
                while not stop.is_set():
                    key = f"wan-{ci}-{i}"
                    val = b"v%d" % i
                    try:
                        await client.execute_write_transaction(
                            TransactionBuilder().write(key, val).build()
                        )
                        committed[key] = val
                    except Exception as exc:
                        errors.append((key, repr(exc)))
                    i += 1
                    await asyncio.sleep(0)
                await client.close()

            writers = [asyncio.create_task(writer(i)) for i in range(4)]
            await asyncio.sleep(2.0)  # spans partition (0.6 s) + heal (1.4 s)
            stop.set()
            await asyncio.gather(*writers)

            assert committed, "no write ever committed through the WAN chaos"
            assert len(committed) >= 8, (len(committed), errors[:5])
            totals = sim.totals()
            assert totals["dropped"] > 0, "lossy mesh never dropped a frame"
            assert totals["delayed"] > 0

            # The durability invariant is about the DATA, not about any
            # single RPC surviving ongoing 2% loss: stop the loss
            # injection for the readback (links stay at WAN delay), and
            # allow one app-level retry — a lossy WAN legitimately fails
            # individual calls even after the client's internal ladder.
            sim.apply_event(
                LinkEvent(0.0, "set", "*", "*",
                          LinkSpec(delay_ms=6.5, jitter_ms=0.5))
            )
            reader = vc.client(timeout_s=2.0)
            for key, val in committed.items():
                try:
                    res = await reader.execute_read_transaction(
                        TransactionBuilder().read(key).build()
                    )
                except Exception:
                    await asyncio.sleep(0.3)  # resync still settling
                    res = await reader.execute_read_transaction(
                        TransactionBuilder().read(key).build()
                    )
                assert res.operations[0].value == val, key
            await reader.close()

    run(asyncio.wait_for(main(), timeout=240))


def test_slow_honest_client_survives_grant_reclaim_race():
    """Round-13 regression: grant-TTL reclamation must never cost a
    merely-SLOW honest client its write.  The client acquires its full
    grant set, then stalls past the TTL mid-Write2 (a WAN-delayed or
    GC-paused coordinator); a contender's conflicting Write1 reclaims the
    now-aged slots.  When the slow client's Write2 finally lands, it must
    still apply — the certificate is self-certifying; reclamation only
    touches the grant book — and the reclaimed-slot invariant must hold
    (the committed certificate IS the original grantee's)."""
    from mochi_tpu.protocol import (
        Write1OkFromServer,
        Write1ToServer,
        WriteCertificate,
        transaction_hash,
    )
    from mochi_tpu.server import store as store_mod
    from mochi_tpu.testing.invariants import InvariantChecker

    async def main():
        saved = store_mod.GRANT_TTL_MS
        store_mod.GRANT_TTL_MS = 200.0
        try:
            sim = NetSim.mesh(seed=5, rtt_ms=4.0, jitter_ms=0.5)
            async with VirtualCluster(4, rf=4, netsim=sim) as vc:
                slow = vc.client(timeout_s=2.0)
                key = "slowk"
                txn = TransactionBuilder().write(key, b"slow-v").build()
                txn_hash = transaction_hash(txn)
                write1_txn = slow._write1_transaction(txn)
                seed = 123
                w1 = Write1ToServer(slow.client_id, write1_txn, seed, txn_hash)
                responses = await slow._fan_out(write1_txn, lambda: w1)
                oks = [
                    p.multi_grant
                    for p in responses.values()
                    if isinstance(p, Write1OkFromServer)
                    and slow._grant_ok(p.multi_grant, txn_hash)
                ]
                chosen = slow._quorum_grant_subset(txn, oks)
                assert chosen is not None
                certificate = WriteCertificate(
                    {mg.server_id: mg for mg in chosen}
                )
                # ...the coordinator stalls past the TTL mid-write...
                await asyncio.sleep(0.35)
                # ...while a contender's conflicting Write1 (same slot)
                # triggers reclamation of the aged grants
                contender = vc.byzantine_client("withhold")
                await contender.acquire(key, seed)
                assert sum(r.store.reclaims for r in vc.replicas) > 0, (
                    "the race never happened — nothing was reclaimed"
                )
                # the slow client's Write2 lands AFTER its grants were
                # reclaimed: reclaim races resolve toward the certificate
                result = await slow._write2(txn, certificate)
                assert result.operations[0].status.name == "OK"
                res = await slow.execute_read_transaction(
                    TransactionBuilder().read(key).build()
                )
                assert res.operations[0].value == b"slow-v"
                checker = InvariantChecker(vc.replicas)
                checker.record_ack(key, b"slow-v")
                checker.check_now()
                await checker.final_check(slow)
                assert checker.ok, checker.report()["violations"]
        finally:
            store_mod.GRANT_TTL_MS = saved

    run(asyncio.wait_for(main(), timeout=120))


def test_byzantine_adversary_under_loss_invariants_and_acked_writes_hold():
    """ROADMAP item 4 remainder (config-10 legs run a CLEAN mesh): a live
    adversary AND 2% frame loss together — the storm strategy's refusal
    floods now compound with genuine retransmission retries.  Safety must
    not depend on a clean network: the invariant checker samples the
    honest stores throughout and every acked write reads back after the
    storm."""
    from mochi_tpu.testing.invariants import InvariantChecker

    async def main():
        sim = NetSim.mesh(seed=21, rtt_ms=13.0, jitter_ms=1.0, drop=0.02)
        async with VirtualCluster(
            5, rf=4, netsim=sim, byzantine={"server-1": "storm"}
        ) as vc:
            checker = InvariantChecker(vc.honest_replicas(), ["server-1"])
            checker.start(0.05)
            committed: dict = {}
            errors: list = []
            clients = [
                vc.client(timeout_s=0.4, write_attempts=12) for _ in range(3)
            ]

            async def writer(ci: int):
                client = clients[ci]
                for i in range(6):
                    key = f"byzloss-{ci}-{i}"
                    val = b"v%d" % i
                    try:
                        await client.execute_write_transaction(
                            TransactionBuilder().write(key, val).build()
                        )
                        committed[key] = val
                        checker.record_ack(key, val)
                    except Exception as exc:
                        # liveness may degrade under adversary+loss;
                        # counted, and safety is checked below
                        errors.append((key, repr(exc)))

            await asyncio.gather(*(writer(i) for i in range(3)))
            assert committed, f"nothing committed: {errors[:5]}"
            totals = sim.totals()
            assert totals["dropped"] > 0, "lossy mesh never dropped a frame"

            # loss off (WAN delay stays) for the durability readback: the
            # invariant is about the data surviving, not one RPC beating
            # ongoing 2% loss on its first try
            sim.apply_event(
                LinkEvent(0.0, "set", "*", "*",
                          LinkSpec(delay_ms=6.5, jitter_ms=0.5))
            )
            await checker.stop()
            await checker.final_check(clients[0])
            report = checker.report()
            assert report["ok"], report["violations"]
            assert report["acked_writes"] >= len(committed)

    run(asyncio.wait_for(main(), timeout=240))

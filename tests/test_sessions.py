"""Session-MAC transport auth (``crypto/session.py``): handshake, speed
path, and the adversarial/recovery cases that keep it as safe as the
signature scheme it replaces on the envelope hop.
"""

import asyncio
from dataclasses import replace

from mochi_tpu.client import TransactionBuilder
from mochi_tpu.crypto import session as session_crypto
from mochi_tpu.protocol import (
    Envelope,
    FailType,
    HelloToServer,
    RequestFailedFromServer,
)
from mochi_tpu.testing import VirtualCluster


def run(coro):
    asyncio.run(asyncio.wait_for(coro, timeout=60))


def test_sessions_established_and_used_for_traffic():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("sk", b"v").build()
            )
            # handshakes happened with every contacted replica
            assert len(client._sessions) == 4
            for r in vc.replicas:
                assert client.client_id in r._sessions
                # both sides derived the SAME key
                assert r._sessions[client.client_id] == client._sessions[r.server_id]
            # traffic after handshake is MAC'd, not signed: check by sending
            # a MAC'd hello directly
            sid = "server-0"
            env = client._envelope(HelloToServer("hi"), "m-mac", sid)
            assert env.mac is not None and env.signature is None

    run(main())


def test_forged_mac_rejected():
    async def main():
        async with VirtualCluster(4, rf=4, require_client_auth=True) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("sk", b"v").build()
            )
            sid = "server-0"
            env = client._envelope(HelloToServer("hi"), "m-bad", sid)
            bad = replace(env, mac=bytes(32))
            resp = await client.pool.send_and_receive(vc.config.servers[sid], bad)
            assert isinstance(resp.payload, RequestFailedFromServer)
            assert resp.payload.fail_type == FailType.BAD_SIGNATURE

    run(main())


def test_mac_without_session_rejected_even_in_open_mode():
    """A MAC'd envelope from a sender with no established session must NOT
    ride the open-mode (unknown-sender) acceptance path."""

    async def main():
        async with VirtualCluster(4, rf=4) as vc:  # require_client_auth=False
            client = vc.client()
            sid = "server-0"
            env = Envelope(HelloToServer("hi"), "m-x", "client-nobody")
            env = env.with_mac(bytes(32))
            resp = await client.pool.send_and_receive(vc.config.servers[sid], env)
            assert isinstance(resp.payload, RequestFailedFromServer)
            assert resp.payload.fail_type == FailType.BAD_SIGNATURE

    run(main())


def test_client_recovers_after_replica_restart_loses_session():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("rk", b"v1").build()
            )
            assert len(client._sessions) == 4
            # replica restarts -> its session table is gone
            await vc.restart_replica("server-1", resync=True)
            # client's next write bounces on server-1 (BAD_SIGNATURE), drops
            # the stale session, re-handshakes, and still commits
            await client.execute_write_transaction(
                TransactionBuilder().write("rk", b"v2").build()
            )
            r = await client.execute_read_transaction(
                TransactionBuilder().read("rk").build()
            )
            assert r.operations[0].value == b"v2"
            assert client.client_id in vc.replica("server-1")._sessions

    run(main())


def test_key_derivation_is_directional_and_nonce_bound():
    a = session_crypto.new_handshake()
    b = session_crypto.new_handshake()
    k_ab = session_crypto.derive_key(a, b.public_bytes, b.nonce, "c", "s", True)
    k_ba = session_crypto.derive_key(b, a.public_bytes, a.nonce, "c", "s", False)
    assert k_ab == k_ba  # both sides agree
    # different nonce -> different key
    k2 = session_crypto.derive_key(a, b.public_bytes, b"\x00" * 16, "c", "s", True)
    assert k2 != k_ab
    # identity binding
    k3 = session_crypto.derive_key(a, b.public_bytes, b.nonce, "c2", "s", True)
    assert k3 != k_ab
    # MAC round trip
    tag = session_crypto.mac(k_ab, b"payload")
    assert session_crypto.mac_ok(k_ba, b"payload", tag)
    assert not session_crypto.mac_ok(k_ba, b"payload2", tag)


def test_evict_client_bans_rehandshake_but_keeps_signed_path():
    """The policy-eviction hook (replica.evict_client, the ROADMAP item-4
    disconnect-policy seam): the MAC session drops, re-handshakes are
    refused with a TYPED signed failure, and the client transparently
    stays on signed envelopes — eviction bounds the MAC discount, it does
    not (yet) refuse service."""

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            await client.execute_write_transaction(
                TransactionBuilder().write("ek", b"v1").build()
            )
            r0 = vc.replica("server-0")
            assert client.client_id in r0._sessions
            out = r0.evict_client(client.client_id)
            assert out["session"] == "evicted" and out["banned"] is True
            assert out["outstanding_grants"] == 0  # committed write: no debt
            assert client.client_id not in r0._sessions
            assert r0.client_grant_stats()["banned_clients"] == 1
            # drop the client's own half so the next fan-out re-knocks
            client._sessions.pop("server-0", None)
            # next write: server-0 refuses the handshake (typed, signed,
            # BAD_REQUEST — must NOT trip the lost-session teardown) and the
            # fan-out still commits over signed envelopes
            await client.execute_write_transaction(
                TransactionBuilder().write("ek", b"v2").build()
            )
            assert client.client_id not in r0._sessions  # ban held
            assert r0.metrics.counters.get("replica.handshake-banned", 0) >= 1
            r = await client.execute_read_transaction(
                TransactionBuilder().read("ek").build()
            )
            assert r.operations[0].value == b"v2"
            # idempotent re-evict: session already gone, ban already booked
            assert r0.evict_client(client.client_id)["session"] == "absent"

    run(main())

"""Production posture smoke: real OS processes end-to-end.

Everything else in tests/ runs the in-process virtual cluster; this is the
one test that exercises the deployment surface itself — ``gen_cluster`` →
``python -m mochi_tpu.server`` × N + one shared verifier service process →
client SDK from this process — so regressions in the CLI entry points,
boot sequence, or remote-verifier wiring can't hide behind the in-process
harness (round-3 verification ran exactly this topology by hand).
"""

from __future__ import annotations

import asyncio
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_multiprocess_cluster_end_to_end():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    procs = []
    with tempfile.TemporaryDirectory(prefix="mochi-test-mp-") as out:
        subprocess.run(
            [
                sys.executable, "-m", "mochi_tpu.tools.gen_cluster",
                "--out-dir", out, "--servers", "5", "--rf", "4",
                "--base-port", "19701",
            ],
            check=True, env=env, capture_output=True,
        )
        cfg = os.path.join(out, "cluster_config.json")
        try:
            vport = 19901
            procs.append(subprocess.Popen(
                [
                    sys.executable, "-m", "mochi_tpu.verifier.service",
                    "--port", str(vport), "--backend", "cpu", "--warmup", "",
                ],
                env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            ))
            for i in range(5):
                procs.append(subprocess.Popen(
                    [
                        sys.executable, "-m", "mochi_tpu.server",
                        "--config", cfg,
                        "--server-id", f"server-{i}",
                        "--seed-file", os.path.join(out, f"server-{i}.seed"),
                        "--verifier", f"remote:127.0.0.1:{vport}",
                    ],
                    env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                ))
            from mochi_tpu.server.__main__ import load_config

            config = load_config(cfg)
            deadline = time.time() + 60
            for info in config.servers.values():
                while time.time() < deadline:
                    try:
                        with socket.create_connection((info.host, info.port), 0.5):
                            break
                    except OSError:
                        time.sleep(0.2)
                else:
                    raise AssertionError("cluster did not come up in 60s")

            async def drive():
                from mochi_tpu.client.client import MochiDBClient
                from mochi_tpu.client.txn import TransactionBuilder

                c = MochiDBClient(config, timeout_s=8.0)
                try:
                    await c.execute_write_transaction(
                        TransactionBuilder().write("mp-k", b"mp-v").build()
                    )
                    res = await c.execute_read_transaction(
                        TransactionBuilder().read("mp-k").build()
                    )
                    assert res.operations[0].value == b"mp-v"
                    cert = res.operations[0].current_certificate
                    assert cert is not None and len(cert.grants) >= config.quorum
                    await c.execute_write_transaction(
                        TransactionBuilder().delete("mp-k").build()
                    )
                    res = await c.execute_read_transaction(
                        TransactionBuilder().read("mp-k").build()
                    )
                    assert not res.operations[0].existed
                finally:
                    await c.close()

            asyncio.run(drive())
        finally:
            for p in procs:
                p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


def test_sigterm_flushes_final_snapshot():
    """Durability-on-TERM: a supervisor's SIGTERM must run the graceful
    close path — the final snapshot lands on disk and a fresh boot serves
    the committed keys from it (state is in-memory; the snapshot IS the
    durability story)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    with tempfile.TemporaryDirectory(prefix="mochi-test-st-") as out:
        subprocess.run(
            [
                sys.executable, "-m", "mochi_tpu.tools.gen_cluster",
                "--out-dir", out, "--servers", "4", "--rf", "4",
                "--base-port", "19751",
            ],
            check=True, env=env, capture_output=True,
        )
        cfg = os.path.join(out, "cluster_config.json")
        data = os.path.join(out, "data")
        os.makedirs(data)
        procs = []
        try:
            for i in range(4):
                procs.append(subprocess.Popen(
                    [
                        sys.executable, "-m", "mochi_tpu.server",
                        "--config", cfg,
                        "--server-id", f"server-{i}",
                        "--seed-file", os.path.join(out, f"server-{i}.seed"),
                        "--data-dir", data,
                        # long interval: only the final-close snapshot can
                        # explain the file appearing after SIGTERM
                        "--snapshot-interval", "3600",
                    ],
                    env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                ))
            from mochi_tpu.client.client import MochiDBClient
            from mochi_tpu.client.txn import TransactionBuilder
            from mochi_tpu.cluster.config import ClusterConfig

            config = ClusterConfig.from_json(open(cfg).read())
            deadline = time.time() + 30
            for info in config.servers.values():
                while time.time() < deadline:
                    try:
                        with socket.create_connection((info.host, info.port), 0.5):
                            break
                    except OSError:
                        time.sleep(0.2)
                else:
                    raise RuntimeError("cluster did not come up")

            async def drive():
                c = MochiDBClient(config, timeout_s=8.0)
                try:
                    await c.execute_write_transaction(
                        TransactionBuilder().write("st-k", b"st-v").build()
                    )
                finally:
                    await c.close()

            asyncio.run(drive())
            for p in procs:
                p.send_signal(signal.SIGTERM)
            for p in procs:
                p.wait(timeout=15)
            snaps = [f for f in os.listdir(data) if f.endswith(".snapshot")]
            assert len(snaps) == 4, snaps
            # the committed key is really IN the snapshots: load each into
            # a fresh store and look for it (written with rf=4, so every
            # owning replica — here all 4 — should hold it)
            from mochi_tpu.server.persistence import load_snapshot
            from mochi_tpu.server.store import DataStore

            found = 0
            for i in range(4):
                st = DataStore(f"server-{i}", config)
                n = load_snapshot(st, os.path.join(data, f"server-{i}.snapshot"))
                sv = st._get("st-k") if n else None
                if sv is not None and sv.exists and sv.value == b"st-v":
                    found += 1
            assert found >= config.quorum, found
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()

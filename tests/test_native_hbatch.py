"""Native batched-h (native/hbatch.c): differential contract vs hashlib +
python bignum, including SHA-512 block boundaries and mod-L edge values.
"""

from __future__ import annotations

import hashlib
import os
import random

import numpy as np
import pytest

from mochi_tpu.crypto import batch_verify, keys
from mochi_tpu.crypto import field as F
from mochi_tpu.native import get_hbatch
from mochi_tpu.verifier.spi import VerifyItem

hb = get_hbatch()
pytestmark = pytest.mark.skipif(hb is None, reason="no native toolchain")


def test_sha512_matches_hashlib_across_block_boundaries():
    rng = random.Random(42)
    # lengths around the 128-byte block and 112-byte padding boundaries,
    # plus a multi-block tail
    lengths = sorted({0, 1, 63, 64, 110, 111, 112, 113, 127, 128, 129,
                      239, 240, 255, 256, 1000, 5000})
    for n in lengths:
        data = bytes(rng.randrange(256) for _ in range(n))
        assert hb.sha512(data) == hashlib.sha512(data).digest(), n


def test_reduce512_matches_python_mod():
    L = F.L_INT
    for probe in (0, 1, L - 1, L, L + 1, 2 * L, 3 * L - 1,
                  (1 << 252) - 1, (1 << 256) - 1, (1 << 512) - 1):
        d = probe.to_bytes(64, "little")
        assert int.from_bytes(hb.reduce512(d), "little") == probe % L, probe
    for _ in range(500):
        d = os.urandom(64)
        assert (
            int.from_bytes(hb.reduce512(d), "little")
            == int.from_bytes(d, "little") % L
        )


def test_h_batch_matches_per_item_hashlib():
    rng = random.Random(7)
    n = 64
    rs = os.urandom(32 * n)
    as_ = os.urandom(32 * n)
    msgs = [bytes(rng.randrange(256) for _ in range(rng.choice([0, 5, 64, 111, 128, 300])))
            for _ in range(n)]
    lens = np.asarray([len(m) for m in msgs], dtype=np.uint64)
    out = hb.h_batch(rs, as_, b"".join(msgs), lens.tobytes())
    for i in range(n):
        expect = (
            int.from_bytes(
                hashlib.sha512(
                    rs[32 * i : 32 * i + 32] + as_[32 * i : 32 * i + 32] + msgs[i]
                ).digest(),
                "little",
            )
            % F.L_INT
        )
        got = int.from_bytes(out[32 * i : 32 * i + 32], "little")
        assert got == expect, i


def test_h_batch_rejects_inconsistent_buffers():
    with pytest.raises(ValueError):
        hb.h_batch(b"\x00" * 32, b"\x00" * 32, b"", (5).to_bytes(8, "little"))
    with pytest.raises(ValueError):
        hb.h_batch(b"\x00" * 31, b"\x00" * 32, b"", (0).to_bytes(8, "little"))
    # uint64 wraparound: two 2^63 lengths sum to 0 == len(b"") — must be
    # rejected, not read out of bounds (code-review r4)
    wrap = (1 << 63).to_bytes(8, "little") * 2
    with pytest.raises(ValueError):
        hb.h_batch(b"\x00" * 64, b"\x00" * 64, b"", wrap)


def test_prepare_packed_native_equals_pure_python():
    kp = keys.generate_keypair()
    other = keys.generate_keypair()
    items = []
    for i in range(40):
        msg = b"np-%d" % i * (i % 7)
        sig = kp.sign(msg)
        if i % 9 == 4:
            sig = sig[:63]  # malformed length
        elif i % 9 == 7:
            sig = other.sign(msg)  # wrong key (valid encoding)
        items.append(VerifyItem(kp.public_key, msg, sig))
    native = batch_verify.prepare_packed(items)
    os.environ["MOCHI_NO_NATIVE"] = "1"
    try:
        import mochi_tpu.native as N

        N._cached.clear()
        pure = batch_verify.prepare_packed(items)
    finally:
        del os.environ["MOCHI_NO_NATIVE"]
        N._cached.clear()
    for a, b in zip(native, pure):
        np.testing.assert_array_equal(a, b)
    # end-to-end verdicts through the native prepare
    got = batch_verify.verify_batch(items)
    expect = [keys.verify(it.public_key, it.message, it.signature) for it in items]
    assert got == expect

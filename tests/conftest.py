"""Test configuration: force an 8-device virtual CPU mesh.

Tests never require real TPU hardware; sharding tests exercise
``jax.sharding.Mesh`` semantics over 8 virtual CPU devices
(``--xla_force_host_platform_device_count=8``).

The axon TPU plugin (when present) registers itself at interpreter start via
sitecustomize and force-sets ``jax_platforms=axon,cpu``, overriding the
``JAX_PLATFORMS`` env var — so env mutation alone is not enough; we override
the config knob back to plain ``cpu`` before any backend initializes.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (after env mutation, before any backend init)

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the Ed25519 verify program takes minutes to
# compile on CPU; cache it across test processes/runs.
_CACHE_DIR = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_CACHE_DIR))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

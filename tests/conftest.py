"""Test configuration: force an 8-device virtual CPU mesh.

Tests never require real TPU hardware; sharding tests exercise
``jax.sharding.Mesh`` semantics over 8 virtual CPU devices
(``--xla_force_host_platform_device_count=8``).  Must run before any jax
import, hence environment mutation at conftest import time.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

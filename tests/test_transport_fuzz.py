"""Hostile-bytes fuzzing of a LIVE replica's listening socket.

The reference never tests its wire surface against garbage (SURVEY.md §4
gap list); here a real replica (virtual cluster, real loopback TCP) is
fed adversarial frames — oversized length prefixes, truncated frames,
random bytes, valid frames wrapping undecodable payloads, and a valid
envelope with a corrupted MAC — and must (a) never crash, (b) keep
serving well-formed traffic afterwards.
"""

from __future__ import annotations

import asyncio
import os
import struct

import numpy as np

from mochi_tpu.protocol import (
    HelloFromServer,
    HelloToServer,
    RequestFailedFromServer,
    encode_envelope,
)
from mochi_tpu.testing.virtual_cluster import VirtualCluster


def run(coro):
    asyncio.run(coro)


async def _open(info):
    return await asyncio.open_connection("127.0.0.1", info.port)


async def _hostile(info, payload: bytes) -> None:
    """Send raw bytes on a fresh connection; server may close on us."""
    try:
        reader, writer = await _open(info)
        writer.write(payload)
        try:
            await asyncio.wait_for(writer.drain(), timeout=2)
        except Exception:
            pass
        writer.close()
    except (ConnectionResetError, BrokenPipeError, OSError):
        pass  # server dropping a hostile peer is correct behavior


async def _alive(vc, info) -> None:
    """The replica must still answer a clean hello round trip."""
    client = vc.client()
    env = client._envelope(HelloToServer("still-there?"), os.urandom(8).hex())
    resp = await asyncio.wait_for(client.pool.send_and_receive(info, env), 10)
    assert isinstance(resp.payload, (HelloFromServer, RequestFailedFromServer))
    await client.close()


def test_replica_survives_hostile_frames():
    rng = np.random.default_rng(0xBAD)

    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            info = vc.config.servers["server-0"]

            # 1. oversized length prefix (attempted allocation attack)
            await _hostile(info, struct.pack(">I", 0xFFFFFFFF) + b"x" * 64)
            # 2. truncated frame: promises 1000 bytes, sends 3, hangs up
            await _hostile(info, struct.pack(">I", 1000) + b"abc")
            # 3. pure garbage, various sizes
            for size in (1, 3, 4, 17, 256, 4096):
                await _hostile(info, rng.bytes(size))
            # 4. well-framed undecodable payloads
            for size in (0, 1, 64, 1024):
                blob = rng.bytes(size)
                await _hostile(info, struct.pack(">I", len(blob)) + blob)
            # 5. valid envelope bytes with flipped tail (corrupt signature/MAC
            #    region) inside a correct frame
            client = vc.client()
            env = client._envelope(HelloToServer("x"), os.urandom(8).hex())
            data = bytearray(encode_envelope(env))
            data[-1] ^= 0xFF
            await _hostile(info, struct.pack(">I", len(data)) + bytes(data))
            await client.close()

            await _alive(vc, info)

            # 6. sustained random-frame storm, then service check again
            for _ in range(50):
                blob = rng.bytes(int(rng.integers(0, 128)))
                await _hostile(info, struct.pack(">I", len(blob)) + blob)
            await _alive(vc, info)

    run(main())

"""Differential suite for the native-C X25519 ladder (native/hbatch.c
x25519) vs the pure-Python reference (crypto/hostfallback) and, when the
wheel is installed, OpenSSL — the round-18 handshake fast path.

The contract is *agreement*: both sides of a session handshake must derive
the same MAC key whatever engine each runs (mixed clusters are supported by
design — crypto/session.Handshake dispatches per side), and the rejection
policy for malformed or small-order peer points must be identical, or a
Byzantine peer could craft a handshake one engine accepts and the other
refuses.
"""

from __future__ import annotations

import random

import pytest

from mochi_tpu.crypto import hostfallback as hf
from mochi_tpu.crypto import session as session_crypto
from mochi_tpu.native import get_hbatch

hb = get_hbatch()
pytestmark = pytest.mark.skipif(
    hb is None or not hasattr(hb, "x25519"),
    reason="no native toolchain / x25519 engine",
)

try:  # optional third engine: OpenSSL via the cryptography wheel
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )

    def openssl_x25519(private: bytes, peer_public: bytes) -> bytes:
        return X25519PrivateKey.from_private_bytes(private).exchange(
            X25519PublicKey.from_public_bytes(peer_public)
        )

except ImportError:
    openssl_x25519 = None

# RFC 7748 §5.2 scalar-multiplication vectors
VEC1 = (
    "a546e36bf0527c9d3b16154b82465edd62144c0ac1fc5a18506a2244ba449ac4",
    "e6db6867583030db3594c1a424b15f7c726624ec26b3353b10a903a6d0ab1c4c",
    "c3da55379de9c6908e94ea4df28d084f32eccf03491c71f754b4075577a28552",
)
VEC2 = (
    "4b66e9d4d1b4673c5ad22691957d6af5c11b6421e0ea01d42ca4169e7918ba0d",
    "e5210f12786811d3f4b7959d0538ae2c31dbe7106fc03c3efc4cd549c715a493",
    "95cbde9476e8907d7aade45cb4b873f88b595a68799fa152e6f8f7647aac7957",
)
# RFC 7748 §6.1 Diffie-Hellman vector
DH_ALICE = "77076d0a7318a57d3c16c17251b26645df4c2f87ebc0992ab177fba51db92c2a"
DH_BOB = "5dab087e624a8a4b79e17f8b83800ee66f3bb1292618b6fd1c2f8b27ff88e0eb"
DH_ALICE_PUB = "8520f0098930a754748b7ddcb43ef75a0dbf3a0d26381af4eba4a98eaa9b4e6a"
DH_BOB_PUB = "de9edb7d7b7dc1b4d35b61c2ece435373f8343c85b78674dadfc7e146f882b4f"
DH_K = "4a5d9d5ba4ce2de1728e3bf480350f25e07e21c947d19e3376f09b3c1e161742"

BASEPOINT = (9).to_bytes(32, "little")


def pure_x25519(private: bytes, peer_public: bytes) -> bytes:
    """The pure-Python ladder, bypassing hostfallback's native routing."""
    saved = hf._native
    hf._native = None
    try:
        return hf.x25519(private, peer_public)
    finally:
        hf._native = saved


def test_rfc7748_scalarmult_vectors_all_engines():
    for k_hex, u_hex, want_hex in (VEC1, VEC2):
        k, u, want = (bytes.fromhex(h) for h in (k_hex, u_hex, want_hex))
        assert hb.x25519(k, u) == want
        assert pure_x25519(k, u) == want
        assert hf.x25519(k, u) == want  # the routed entry point
        if openssl_x25519 is not None:
            assert openssl_x25519(k, u) == want


def test_rfc7748_diffie_hellman_vector():
    alice, bob = bytes.fromhex(DH_ALICE), bytes.fromhex(DH_BOB)
    assert hb.x25519(alice, BASEPOINT) == bytes.fromhex(DH_ALICE_PUB)
    assert hb.x25519(bob, BASEPOINT) == bytes.fromhex(DH_BOB_PUB)
    k = bytes.fromhex(DH_K)
    assert hb.x25519(alice, bytes.fromhex(DH_BOB_PUB)) == k
    assert hb.x25519(bob, bytes.fromhex(DH_ALICE_PUB)) == k


def test_rfc7748_iterated_ladder():
    """§5.2 iteration test, 1 and 1000 rounds (1000 is ~70 ms native;
    the 1M-round variant stays out of tier 1)."""
    k = u = BASEPOINT
    k, u = hb.x25519(k, u), k
    assert k == bytes.fromhex(
        "422c8e7a6227d7bca1350b3e2bb7279f7897b87bb6854b783c60e80311ae3079"
    )
    for _ in range(999):
        k, u = hb.x25519(k, u), k
    assert k == bytes.fromhex(
        "684cf59ba83309552800ef566f2f4d3c1c3887c49360e3875f2eb94d99532c51"
    )


def test_hundred_seed_random_agreement():
    """100 random (scalar, point) pairs: native == pure Python (== OpenSSL
    when present) bit for bit — including points with bit 255 set, which
    RFC 7748 masks."""
    rng = random.Random(20260807)
    for i in range(100):
        priv = rng.randbytes(32)
        peer = bytearray(hb.x25519(rng.randbytes(32), BASEPOINT))
        if i % 3 == 0:
            peer[31] |= 0x80  # the masked bit must not change the result
        peer = bytes(peer)
        n = hb.x25519(priv, peer)
        assert n == pure_x25519(priv, peer), i
        if openssl_x25519 is not None:
            assert n == openssl_x25519(priv, peer), i


def test_rejection_policy_identical():
    """Both engines refuse the same bytes: wrong lengths raise ValueError
    at the shared wrapper seam, and a small-order peer point (all-zero
    shared secret) raises on the native-routed path exactly as on the
    pure-Python path."""
    with pytest.raises(ValueError):
        hf.x25519(b"\x00" * 31, BASEPOINT)
    with pytest.raises(ValueError):
        hf.x25519(b"\x00" * 32, b"\x00" * 33)
    zero_point = (0).to_bytes(32, "little")  # order-1/2 family: K = 0
    with pytest.raises(ValueError):
        hf.x25519(b"\x42" * 32, zero_point)
    with pytest.raises(ValueError):
        pure_x25519(b"\x42" * 32, zero_point)


def test_mixed_engine_handshake_derives_same_key():
    """One side native, one side pure Python: crypto/session.derive_key
    must produce the SAME MAC key — the mixed-cluster contract the
    session layer documents."""
    hs_a = session_crypto.new_handshake()
    hs_b = session_crypto.new_handshake()
    key_native = session_crypto.derive_key(
        hs_a, hs_b.public_bytes, hs_b.nonce, "client-1", "server-1", True
    )
    saved = hf._native
    hf._native = None
    try:
        key_pure = session_crypto.derive_key(
            hs_b, hs_a.public_bytes, hs_a.nonce, "client-1", "server-1", False
        )
    finally:
        hf._native = saved
    assert key_native == key_pure


def test_native_handshake_speedup_in_record():
    """The acceptance gate's in-record measurement: the native ladder must
    cut per-operation handshake CPU >= 5x vs the pure-Python ladder this
    host otherwise runs (the r18 benchmark record repeats this at
    handshake-storm shape)."""
    import time

    k, u = bytes.fromhex(VEC1[0]), bytes.fromhex(VEC1[1])
    t0 = time.perf_counter()
    for _ in range(100):
        hb.x25519(k, u)
    native_s = (time.perf_counter() - t0) / 100
    t0 = time.perf_counter()
    for _ in range(10):
        pure_x25519(k, u)
    pure_s = (time.perf_counter() - t0) / 10
    assert pure_s / native_s >= 5.0, (native_s, pure_s)

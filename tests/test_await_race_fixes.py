"""Regression pins for the true positives the await-races checker found.

PR 10's static pass (docs/ANALYSIS.md §await-races) flagged these sites on
the pre-PR tree; each test here drives the actual interleaving the checker
predicted and would fail against the pre-fix code:

* ``RpcClientPool.close`` blanket-``clear()``ed the straggler set after an
  await — a straggler registered DURING the shutdown gather was orphaned
  un-cancelled (and untracked, so "Task was destroyed but it is pending").
* ``RpcClientPool.close`` iterated the live ``_connections`` dict with an
  await in the body — a connection registered mid-close raised
  ``RuntimeError: dictionary changed size during iteration``.
* ``MochiDBClient._ensure_session`` captured the server's public key BEFORE
  the handshake round trip — a reconfiguration rotating the key mid-flight
  left the ack verifying against the rotated-OUT identity.
"""

import asyncio

from mochi_tpu.net.transport import RpcClientPool


def test_pool_close_drains_straggler_registered_mid_close():
    async def main():
        pool = RpcClientPool()
        spawned = {}

        async def late():
            await asyncio.sleep(30)

        async def early():
            try:
                await asyncio.sleep(30)
            except asyncio.CancelledError:
                # a concurrent fan-out registering its drain task exactly
                # while close() awaits the first cancellation round
                t2 = asyncio.get_running_loop().create_task(late())
                pool._track_straggler(t2)
                spawned["t2"] = t2
                raise

        t1 = asyncio.get_running_loop().create_task(early())
        pool._track_straggler(t1)
        await asyncio.sleep(0)  # let t1 reach its await
        await pool.close()
        assert not pool._straggler_tasks, "close() must drain to quiescence"
        assert spawned["t2"].cancelled(), (
            "the mid-close straggler was orphaned (pre-PR-10 clear() bug)"
        )

    asyncio.run(main())


def test_pool_close_closes_connection_registered_mid_close():
    class FakeConn:
        def __init__(self, pool=None):
            self.closed = False
            self._pool = pool

        async def close(self):
            await asyncio.sleep(0)
            self.closed = True
            if self._pool is not None:
                # a request racing shutdown registers one more connection
                # while close() is suspended inside OUR close()
                self._pool._connections.setdefault("late", FakeConn())
                self._pool = None

    async def main():
        pool = RpcClientPool()
        first = FakeConn(pool)
        pool._connections["first"] = first
        await pool.close()  # pre-fix: RuntimeError (dict changed size)
        assert first.closed
        assert not pool._connections, "the late connection must be closed too"

    asyncio.run(main())


def test_pool_close_drains_straggler_registered_during_conn_close():
    class FakeConn:
        def __init__(self, pool):
            self.closed = False
            self._pool = pool

        async def close(self):
            await asyncio.sleep(0)
            self.closed = True
            # a fan-out's pending futures fail as this connection tears
            # down, and its drain task registers while close() is already
            # past the straggler phase
            t = asyncio.get_running_loop().create_task(asyncio.sleep(30))
            self._pool._track_straggler(t)
            self._pool.spawned = t

    async def main():
        pool = RpcClientPool()
        pool._connections["only"] = FakeConn(pool)
        await pool.close()
        assert not pool._straggler_tasks, "close() must drain to quiescence"
        assert pool.spawned.cancelled(), (
            "a straggler registered during the connection-close phase was "
            "orphaned un-cancelled"
        )

    asyncio.run(main())


def test_ensure_session_rereads_rotated_key_after_handshake():
    from mochi_tpu.client.client import MochiDBClient
    from mochi_tpu.cluster.config import ClusterConfig
    from mochi_tpu.crypto import session as session_crypto
    from mochi_tpu.crypto.keys import generate_keypair
    from mochi_tpu.net.transport import new_msg_id
    from mochi_tpu.protocol import Envelope, SessionAckFromServer

    old_kp, new_kp = generate_keypair(), generate_keypair()

    peers = {f"server-{i}": generate_keypair() for i in range(1, 4)}

    def build_cfg(kp):
        return ClusterConfig.build(
            {f"server-{i}": f"127.0.0.1:{i + 1}" for i in range(4)}, rf=4,
            public_keys={"server-0": kp.public_key}
            | {sid: p.public_key for sid, p in peers.items()},
        )

    def fake_server(rotate_to=None):
        """send_and_receive double: optionally rotates the client's config
        mid-flight, always acks signed with the ORIGINAL key."""

        async def send_and_receive(info, env, timeout_s=None):
            if rotate_to is not None:
                client.config = rotate_to
            hs = session_crypto.new_handshake()
            ack = Envelope(
                payload=SessionAckFromServer(hs.public_bytes, hs.nonce),
                msg_id=new_msg_id(),
                sender_id="server-0",
                timestamp_ms=0,
            )
            return ack.with_signature(old_kp.sign(ack.signing_bytes()))

        return send_and_receive

    async def main():
        global client
        # control: no rotation -> the handshake establishes a session
        client = MochiDBClient(config=build_cfg(old_kp))
        client.pool.send_and_receive = fake_server()
        await client._ensure_session("server-0", client.config.servers["server-0"])
        assert "server-0" in client._sessions, "control handshake must succeed"
        await client.close()

        # rotation mid-flight: the ack is signed by the rotated-OUT key and
        # must be rejected against the key the CURRENT config trusts
        client = MochiDBClient(config=build_cfg(old_kp))
        client.pool.send_and_receive = fake_server(rotate_to=build_cfg(new_kp))
        await client._ensure_session("server-0", client.config.servers["server-0"])
        assert "server-0" not in client._sessions, (
            "session sealed against a rotated-out server identity "
            "(pre-PR-10 stale server_key)"
        )
        await client.close()

        # rotation that REMOVES the key entirely: no crash, no session
        removed = ClusterConfig.build(
            {f"server-{i}": f"127.0.0.1:{i + 1}" for i in range(4)}, rf=4,
            public_keys={sid: p.public_key for sid, p in peers.items()},
        )
        client = MochiDBClient(config=build_cfg(old_kp))
        client.pool.send_and_receive = fake_server(rotate_to=removed)
        await client._ensure_session("server-0", client.config.servers["server-0"])
        assert "server-0" not in client._sessions
        await client.close()

    asyncio.run(main())

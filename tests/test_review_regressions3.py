"""Regressions for the round-3 advisor findings (ADVICE.md r3).

1. ``fan_out`` runs its slow path (unconnected targets) concurrently with
   the fast-path wait — one down replica must not stretch a fan-out to
   ~2x the timeout budget.
2. An explicit ``timeout_s=0`` means "no waiting", not "use the default".
3. ``decode_envelope`` rejects a non-canonical multi-byte varint header
   (would silently shift the signed-prefix slice and surface as a
   confusing BAD_SIGNATURE instead of a decode error).
"""

from __future__ import annotations

import asyncio
import time

import pytest

from mochi_tpu.cluster.config import ServerInfo
from mochi_tpu.net.transport import RpcClientPool, fan_out
from mochi_tpu.protocol import Envelope, HelloToServer, decode_envelope, encode_envelope


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=30))


async def _black_hole_server():
    """Accepts connections and reads frames but never responds."""

    async def handle(reader, writer):
        try:
            while await reader.read(65536):
                pass
        except Exception:
            pass

    server = await asyncio.start_server(handle, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    return server, port


def _env(msg_id: str) -> Envelope:
    return Envelope(HelloToServer("probe"), msg_id, "test-client")


def test_fan_out_slow_path_shares_the_timeout_budget():
    """One never-connected target + one connected-but-silent target: the
    fan-out must finish in ~1x the timeout, not slow-then-fast serial 2x."""

    async def main():
        server, port = await _black_hole_server()
        fast_info = ServerInfo("fast", "127.0.0.1", port)
        # Dead port: connect fails fast, but ensure_connected retries
        # 3x100ms inside the slow path — still well under one timeout.
        server2, port2 = await _black_hole_server()
        slow_info = ServerInfo("slow", "127.0.0.1", port2)

        pool = RpcClientPool(default_timeout_s=1.0)
        # Pre-connect the fast target so it takes the fast path.
        await pool._conn(fast_info).ensure_connected()
        assert pool._conn(fast_info).connected
        assert not pool._conn(slow_info).connected

        t0 = time.perf_counter()
        out = await fan_out(
            pool,
            [("fast", fast_info), ("slow", slow_info)],
            lambda msg_id, sid: _env(msg_id),
            timeout_s=1.0,
        )
        elapsed = time.perf_counter() - t0
        assert isinstance(out["fast"], Exception)
        assert isinstance(out["slow"], Exception)
        # Serial slow-then-fast would be ~2.0s; concurrent is ~1.0s.
        assert elapsed < 1.7, f"fan_out took {elapsed:.2f}s — slow path serialized"
        await pool.close()
        server.close()
        server2.close()

    run(main())


def test_fan_out_explicit_zero_timeout_is_not_the_default():
    async def main():
        server, port = await _black_hole_server()
        info = ServerInfo("s", "127.0.0.1", port)
        pool = RpcClientPool(default_timeout_s=30.0)
        await pool._conn(info).ensure_connected()
        t0 = time.perf_counter()
        out = await fan_out(
            pool, [("s", info)], lambda msg_id, sid: _env(msg_id), timeout_s=0
        )
        elapsed = time.perf_counter() - t0
        assert isinstance(out["s"], Exception)
        assert elapsed < 5.0, "timeout_s=0 fell back to the 30s default"
        await pool.close()
        server.close()

    run(main())


def test_decode_envelope_rejects_noncanonical_header():
    env = Envelope(HelloToServer("hi"), "m1", "s1")
    wire = encode_envelope(env)
    assert wire[:2] == b"\x07\x08"
    assert decode_envelope(wire).msg_id == "m1"
    # Same frame with varint(8) spelled as the two-byte form 88 00: must be
    # a loud decode error, not a shifted signed-prefix slice.
    bad = wire[:1] + b"\x88\x00" + wire[2:]
    with pytest.raises(ValueError):
        decode_envelope(bad)


def test_coalescing_verifier_merges_concurrent_calls():
    """Concurrent verify_batch calls must share inner round trips (one RPC
    per round trip, not per certificate) with per-caller correct bitmaps."""
    from mochi_tpu.verifier.spi import CoalescingVerifier, SignatureVerifier, VerifyItem

    class SlowInner(SignatureVerifier):
        def __init__(self):
            self.calls = 0

        async def verify_batch(self, items):
            self.calls += 1
            await asyncio.sleep(0.02)  # the "round trip"
            return [it.message.startswith(b"ok") for it in items]

    async def main():
        inner = SlowInner()
        cv = CoalescingVerifier(inner)

        async def one(i):
            items = [
                VerifyItem(b"k" * 32, b"ok %d" % i, b"s" * 64),
                VerifyItem(b"k" * 32, b"bad %d" % i, b"s" * 64),
            ]
            return await cv.verify_batch(items)

        results = await asyncio.gather(*(one(i) for i in range(10)))
        assert all(r == [True, False] for r in results)
        # call 0 flushes alone; calls 1..9 arrive during its round trip and
        # must ride ONE combined flush => 2 inner calls, not 10.
        assert inner.calls <= 3, f"no coalescing: {inner.calls} inner calls"
        await cv.close()

    run(main())


def test_coalescing_verifier_propagates_inner_failure():
    from mochi_tpu.verifier.spi import CoalescingVerifier, SignatureVerifier, VerifyItem

    class BoomInner(SignatureVerifier):
        async def verify_batch(self, items):
            raise RuntimeError("boom")

    async def main():
        cv = CoalescingVerifier(BoomInner())
        items = [VerifyItem(b"k" * 32, b"m", b"s" * 64)]
        try:
            await cv.verify_batch(items)
        except RuntimeError as exc:
            assert "boom" in str(exc)
        else:
            raise AssertionError("inner failure swallowed")

    run(main())


def test_fan_out_cancellation_cancels_slow_path():
    """Cancelling a fan-out mid-wait must cancel the detached slow-path
    task too — no background dials/sends after the caller gave up."""

    async def main():
        server, port = await _black_hole_server()
        fast_info = ServerInfo("fast", "127.0.0.1", port)
        server2, port2 = await _black_hole_server()
        slow_info = ServerInfo("slow", "127.0.0.1", port2)

        pool = RpcClientPool(default_timeout_s=5.0)
        await pool._conn(fast_info).ensure_connected()

        task = asyncio.ensure_future(
            fan_out(
                pool,
                [("fast", fast_info), ("slow", slow_info)],
                lambda msg_id, sid: _env(msg_id),
                timeout_s=5.0,
            )
        )
        await asyncio.sleep(0.1)  # fan-out is parked in the fast-path wait
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
        await asyncio.sleep(0.1)
        # the slow-path connection must not be left pending a request
        slow_conn = pool._conn(slow_info)
        assert not slow_conn.pending, "slow-path request survived cancellation"
        await pool.close()
        server.close()
        server2.close()

    run(main())

"""Regressions for the resync/persistence review findings.

1. A stored certificate carrying extra validly-signed non-OK or minority
   grants must not poison ``certificate_timestamp`` (would brick the key for
   every later Write2 and crash resync).
2. State transfer pages through stores larger than one page.
3. Snapshots from another server id are refused.
"""

import asyncio

from mochi_tpu.client import TransactionBuilder
from mochi_tpu.protocol import Grant, MultiGrant, Status, WriteCertificate
from mochi_tpu.server.store import StoreValue
from mochi_tpu.testing import VirtualCluster


def run(coro):
    asyncio.run(asyncio.wait_for(coro, timeout=120))


def test_certificate_timestamp_survives_byzantine_extras():
    ok = lambda sid, ts: MultiGrant(
        {"k": Grant("k", ts, 1, b"h", Status.OK)}, "c", sid
    )
    refused = MultiGrant({"k": Grant("k", 777, 1, b"h", Status.REFUSED)}, "c", "s4")
    minority = MultiGrant({"k": Grant("k", 999, 1, b"h", Status.OK)}, "c", "s5")
    sv = StoreValue("k")
    sv.current_certificate = WriteCertificate(
        {"s1": ok("s1", 500), "s2": ok("s2", 500), "s3": ok("s3", 500),
         "s4": refused, "s5": minority}
    )
    # majority OK timestamp wins; no exception despite disagreeing grants
    assert sv.certificate_timestamp() == 500

    # all-non-OK degenerates to None, not a crash
    sv2 = StoreValue("k")
    sv2.current_certificate = WriteCertificate({"s4": refused})
    assert sv2.certificate_timestamp() is None


def test_certificate_timestamp_ignores_out_of_set_signers():
    """Out-of-set signers must not out-vote the in-set quorum (ADVICE r1 #2).

    With replica_set given, only in-set servers vote — one vote each — so a
    Byzantine client embedding many validly-signed out-of-set grants at a
    bogus timestamp cannot flip the stored timestamp and poison the
    staleness check in ``process_write2``.
    """
    ok = lambda sid, ts: MultiGrant(
        {"k": Grant("k", ts, 1, b"h", Status.OK)}, "c", sid
    )
    in_set = {"s1", "s2", "s3", "s4"}
    grants = {f"s{i}": ok(f"s{i}", 500) for i in range(1, 5)}
    # five out-of-set colluders all vote timestamp 9000 (more raw votes)
    grants.update({f"x{i}": ok(f"x{i}", 9000) for i in range(5)})
    # plus a duplicated in-set server id under a different dict key
    grants["dup"] = ok("s1", 9000)
    sv = StoreValue("k")
    sv.current_certificate = WriteCertificate(grants)
    assert sv.certificate_timestamp(in_set) == 500
    # unrestricted view would have been poisoned — documents why the
    # server path always passes the replica set
    assert sv.certificate_timestamp() == 9000


def test_resync_pages_through_large_store():
    async def main():
        async with VirtualCluster(4, rf=4) as vc:
            client = vc.client()
            # more keys than one sync page (use a small page to keep it fast)
            for batch_start in range(0, 30, 10):
                tb = TransactionBuilder()
                for i in range(batch_start, batch_start + 10):
                    tb.write(f"pg-{i:03d}", b"v%d" % i)
                await client.execute_write_transaction(tb.build())

            donor = vc.replica("server-1")
            page = donor.store.export_sync_entries(max_entries=7)
            assert len(page) == 7
            collected = {e.key for e in page}
            while True:
                nxt = donor.store.export_sync_entries(
                    max_entries=7, after_key=page[-1].key
                )
                if not nxt:
                    break
                collected |= {e.key for e in nxt}
                if len(nxt) < 7:
                    break
                page = nxt
            assert {f"pg-{i:03d}" for i in range(30)} <= collected

            # end-to-end: restarted replica recovers everything via resync
            fresh = await vc.restart_replica("server-0", resync=True)
            have = {
                k for k, sv in fresh.store.data.items() if sv.current_certificate
            }
            assert {f"pg-{i:03d}" for i in range(30)} <= have

    run(main())


def test_snapshot_wrong_server_refused(tmp_path):
    from mochi_tpu.cluster.config import ClusterConfig
    from mochi_tpu.server import persistence
    from mochi_tpu.server.store import DataStore

    cfg = ClusterConfig.build(
        {f"server-{i}": f"127.0.0.1:{9100+i}" for i in range(4)}, rf=4
    )
    donor = DataStore("server-1", cfg)
    path = str(tmp_path / "s1.snapshot")
    persistence.write_snapshot(donor, path)
    other = DataStore("server-0", cfg)
    try:
        persistence.load_snapshot(other, path)
        raise AssertionError("expected ValueError")
    except ValueError as exc:
        assert "server-1" in str(exc)


def test_batcher_close_while_flusher_awaits_inflight_slot():
    """close() while the flusher is blocked on the in-flight semaphore must
    cancel the still-queued items (round-2 review: the chunk used to be
    popped BEFORE the acquire, so cancelling the flusher there stranded the
    popped chunk's futures forever)."""
    import asyncio
    import time

    from mochi_tpu.crypto import keys
    from mochi_tpu.verifier.spi import BatchingVerifier, VerifyItem

    kp = keys.generate_keypair()

    async def main():
        def slow_backend(chunk):
            time.sleep(0.2)
            return [True] * len(chunk)

        bv = BatchingVerifier(
            slow_backend, max_batch=4, max_delay_s=0.0, max_inflight=2
        )
        items = [
            VerifyItem(kp.public_key, b"m%d" % i, kp.sign(b"m%d" % i))
            for i in range(40)
        ]
        tasks = [
            asyncio.create_task(bv.verify_batch(items[i * 4 : (i + 1) * 4]))
            for i in range(10)
        ]
        await asyncio.sleep(0.05)  # saturate in-flight, flusher parks on acquire
        await asyncio.wait_for(bv.close(), timeout=5)
        hung = 0
        for t in tasks:
            try:
                res = await asyncio.wait_for(t, timeout=5)
                assert all(res)
            except (asyncio.CancelledError, RuntimeError):
                pass
            except asyncio.TimeoutError:
                hung += 1
        assert hung == 0, f"{hung} verify_batch callers hung after close()"

    asyncio.run(main())


def test_caching_verifier_waiter_survives_dispatcher_failure():
    """Single-flight: if the caller that dispatched a key fails (error or
    cancellation), a concurrent waiter on that key must re-verify
    independently, not inherit the dispatcher's failure (round-2 review)."""
    import asyncio

    from mochi_tpu.crypto import keys
    from mochi_tpu.verifier.spi import CachingVerifier, SignatureVerifier, VerifyItem

    class FlakyVerifier(SignatureVerifier):
        def __init__(self):
            self.calls = 0

        async def verify_batch(self, items):
            self.calls += 1
            await asyncio.sleep(0.05)
            if self.calls == 1:
                raise RuntimeError("first dispatch dies")
            return [
                keys.verify(it.public_key, it.message, it.signature)
                for it in items
            ]

    async def main():
        cv = CachingVerifier(FlakyVerifier())
        kp = keys.generate_keypair()
        it = VerifyItem(kp.public_key, b"g", kp.sign(b"g"))
        t1 = asyncio.create_task(cv.verify_batch([it]))
        await asyncio.sleep(0.01)
        t2 = asyncio.create_task(cv.verify_batch([it]))
        (r1,) = await asyncio.gather(t1, return_exceptions=True)
        assert isinstance(r1, RuntimeError)
        assert await t2 == [True]

    asyncio.run(main())


def test_batcher_runs_chunks_concurrently_up_to_max_inflight():
    """The max_inflight semaphore must deliver real overlap: a backlog of
    4 chunks against a slow backend with max_inflight=2 must reach 2
    concurrent backend calls — and never exceed the cap."""
    import asyncio
    import threading
    import time

    from mochi_tpu.crypto import keys
    from mochi_tpu.verifier.spi import BatchingVerifier, VerifyItem

    kp = keys.generate_keypair()
    lock = threading.Lock()
    state = {"now": 0, "peak": 0}

    def slow_backend(chunk):
        with lock:
            state["now"] += 1
            state["peak"] = max(state["peak"], state["now"])
        time.sleep(0.15)
        with lock:
            state["now"] -= 1
        return [True] * len(chunk)

    async def main():
        bv = BatchingVerifier(
            slow_backend, max_batch=4, max_delay_s=0.0, max_inflight=2
        )
        items = [
            VerifyItem(kp.public_key, b"p%d" % i, kp.sign(b"p%d" % i))
            for i in range(16)
        ]
        tasks = [
            asyncio.create_task(bv.verify_batch(items[i * 4 : (i + 1) * 4]))
            for i in range(4)
        ]
        results = await asyncio.gather(*tasks)
        assert all(all(r) for r in results)
        await bv.close()
        assert state["peak"] == 2, f"peak concurrency {state['peak']}, want 2"

    asyncio.run(main())


def test_caching_verifier_rejects_short_bitmap():
    """A misbehaving inner verifier that returns fewer verdicts than items
    must fail loudly AND resolve the in-flight futures with the retry
    sentinel — a truncated zip would otherwise leave concurrent waiters on
    the tail keys hanging forever (round-3 advice)."""
    import asyncio

    from mochi_tpu.crypto import keys
    from mochi_tpu.verifier.spi import CachingVerifier, SignatureVerifier, VerifyItem

    class ShortVerifier(SignatureVerifier):
        def __init__(self):
            self.calls = 0

        async def verify_batch(self, items):
            self.calls += 1
            await asyncio.sleep(0.05)
            if self.calls == 1:
                return [True] * (len(items) - 1)  # short!
            return [
                keys.verify(it.public_key, it.message, it.signature)
                for it in items
            ]

    async def main():
        cv = CachingVerifier(ShortVerifier())
        kp = keys.generate_keypair()
        items = [
            VerifyItem(kp.public_key, m, kp.sign(m)) for m in (b"a", b"b")
        ]
        t1 = asyncio.create_task(cv.verify_batch(items))
        await asyncio.sleep(0.01)
        # waiter joins the in-flight futures for the same keys
        t2 = asyncio.create_task(cv.verify_batch(items))
        (r1,) = await asyncio.gather(t1, return_exceptions=True)
        assert isinstance(r1, RuntimeError)
        # the waiter must complete (not hang) with correct verdicts
        assert await asyncio.wait_for(t2, timeout=5) == [True, True]
        assert not cv._inflight

    asyncio.run(main())

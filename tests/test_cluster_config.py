"""Cluster config: token ring, replica sets, quorum math, properties parsing.

Mirrors ``server/ClusterConfigurationTest.java``: token arithmetic (``:21-45``)
and key distribution over a bootstrap ring (``:47-103``) — and additionally
asserts keys spread across *distinct* replica sets, the behavior the
reference's ring-lookup bug (``ClusterConfiguration.java:215``) destroyed.
"""

import os

import pytest

from mochi_tpu.cluster import ClusterConfig, round_robin_token_assignment
from mochi_tpu.cluster.config import SHARD_TOKENS


def make_config(n=5, rf=4) -> ClusterConfig:
    return ClusterConfig.build(
        {f"server-{i}": f"127.0.0.1:{8001 + i}" for i in range(n)}, rf=rf
    )


def test_round_robin_assignment_covers_ring():
    ids = [f"s{i}" for i in range(4)]
    assignment = round_robin_token_assignment(ids)
    all_tokens = sorted(t for tokens in assignment.values() for t in tokens)
    assert all_tokens == list(range(SHARD_TOKENS))
    assert all(len(v) == SHARD_TOKENS // 4 for v in assignment.values())


def test_quorum_math():
    cfg = make_config(5, 4)
    assert cfg.f == 1
    assert cfg.quorum == 3
    cfg7 = make_config(7, 7)
    assert cfg7.f == 2
    assert cfg7.quorum == 5


def test_replica_set_size_and_uniqueness():
    cfg = make_config(5, 4)
    for key in [f"key-{i}" for i in range(200)]:
        rs = cfg.replica_set_for_key(key)
        assert len(rs) == 4
        assert len(set(rs)) == 4


def test_keys_distribute_across_servers():
    # ref: ClusterConfigurationTest.java:47-103 — 200 random keys, each server
    # should serve a healthy share.
    cfg = make_config(5, 4)
    counts = {sid: 0 for sid in cfg.servers}
    for i in range(200):
        for sid in cfg.replica_set_for_key(f"random-key-{i}"):
            counts[sid] += 1
    assert all(c >= 20 for c in counts.values()), counts


def test_distinct_replica_sets_exist():
    # The fixed ring walk must produce >1 distinct replica set (the reference
    # bug collapsed all keys onto one).
    cfg = make_config(5, 4)
    sets = {tuple(sorted(cfg.replica_set_for_key(f"key-{i}"))) for i in range(100)}
    assert len(sets) > 1


def test_config_keys_owned_everywhere():
    cfg = make_config(5, 4)
    assert cfg.replica_set_for_key("_CONFIG_epoch") == sorted(cfg.servers)
    assert cfg.owns_key("server-3", "_CONFIG_epoch")


def test_validation_rejects_bad_rf():
    with pytest.raises(ValueError):
        make_config(5, rf=3)
    with pytest.raises(ValueError):
        make_config(3, rf=4)


def test_properties_roundtrip():
    cfg = make_config(5, 4)
    cfg.public_keys["server-0"] = b"\x07" * 32
    parsed = ClusterConfig.from_properties(cfg.to_properties())
    assert parsed.rf == cfg.rf
    assert parsed.token_owners == cfg.token_owners
    assert {s.url for s in parsed.servers.values()} == {s.url for s in cfg.servers.values()}
    assert parsed.public_keys == {"server-0": b"\x07" * 32}


def test_reference_properties_file_parses():
    # The reference's shipped config loads unmodified (capability parity).
    path = "/root/reference/config/sample_config"
    if not os.path.exists(path):
        pytest.skip("reference checkout not present on this machine")
    with open(path) as fh:
        cfg = ClusterConfig.from_properties(fh.read())
    assert cfg.n_servers == 5
    assert cfg.rf == 4
    assert cfg.quorum == 3


def test_json_roundtrip():
    cfg = make_config(6, 4)
    parsed = ClusterConfig.from_json(cfg.to_json())
    assert parsed == cfg


# ------------------------------------------------ token-ring ownership edges
#
# The shard-per-core scale-out (config 8) leans on exactly these properties:
# the ring walk at the top of the ring, non-dividing round-robin deals,
# ownership consistency across reconfiguration, and routing determinism
# across client restarts (same key -> same replica set in every process).


def test_replica_set_wraps_at_top_of_ring():
    cfg = make_config(6, 4)
    rs = cfg.replica_set_for_token(SHARD_TOKENS - 1)
    assert len(rs) == 4 and len(set(rs)) == 4
    # round-robin deal: token t is owned by server-(t % n); the walk from
    # 1023 must WRAP to tokens 0,1,... to collect its rf distinct owners.
    # 1024 % 6 != 0, so the owner sequence restarts at the wrap — the
    # expected set follows the RING position, not the modular pattern.
    expected = [
        f"server-{((SHARD_TOKENS - 1 + i) % SHARD_TOKENS) % 6}" for i in range(4)
    ]
    assert rs == expected  # ['server-3', 'server-0', 'server-1', 'server-2']
    # and the wrapped walk agrees with the unwrapped one structurally:
    # every token's set is rf distinct CONSECUTIVE ring owners
    for token in (0, 1, SHARD_TOKENS // 2, SHARD_TOKENS - 2, SHARD_TOKENS - 1):
        rs = cfg.replica_set_for_token(token)
        assert len(set(rs)) == cfg.rf


def test_round_robin_with_non_dividing_server_count():
    # 1024 % n != 0 for n in (5, 6, 7): the deal must still cover every
    # token exactly once with per-server counts within one of each other
    for n in (5, 6, 7):
        ids = [f"s{i}" for i in range(n)]
        assignment = round_robin_token_assignment(ids)
        all_tokens = sorted(t for tokens in assignment.values() for t in tokens)
        assert all_tokens == list(range(SHARD_TOKENS)), n
        counts = {sid: len(tokens) for sid, tokens in assignment.items()}
        assert max(counts.values()) - min(counts.values()) <= 1, counts
        # early ids get the extra token (deal order), never a hole
        assert counts[ids[0]] == -(-SHARD_TOKENS // n)


def test_owns_key_consistent_after_evolve():
    cfg = make_config(6, 4)
    grown = cfg.evolve(
        {
            **{sid: s.url for sid, s in cfg.servers.items()},
            "server-6": "127.0.0.1:8007",
            "server-7": "127.0.0.1:8008",
        },
        public_keys={"server-6": b"\x01" * 32, "server-7": b"\x02" * 32},
    )
    assert grown.configstamp == cfg.configstamp + 1
    keys = [f"evolve-key-{i}" for i in range(200)]
    for c in (cfg, grown):
        for key in keys:
            rset = set(c.replica_set_for_key(key))
            # owns_key must agree with replica-set membership for EVERY
            # server — replicas enforce WRONG_SHARD with owns_key while
            # clients route with replica_set_for_key; divergence would
            # make correctly-routed requests bounce
            for sid in c.servers:
                assert c.owns_key(sid, key) == (sid in rset), (key, sid)
    # minimal movement: the vast majority of keys keep at least one member
    # of their old replica set (the consistent-hash property evolve()
    # exists for; stolen tokens cluster at the top of donors' lists, so a
    # FEW keys can land on fully-fresh sets — a full re-deal would move
    # essentially all of them)
    moved = sum(
        1
        for key in keys
        if not set(cfg.replica_set_for_key(key)) & set(grown.replica_set_for_key(key))
    )
    assert moved <= len(keys) * 0.1, f"evolve() fully moved {moved}/{len(keys)} keys"


def test_shard_routing_deterministic_across_restarts():
    # Same key -> same replica set from two independently-parsed config
    # objects (a client restart re-parses the committed document; routing
    # must not depend on process state, dict order, or cache warmth)
    cfg = make_config(7, 4)
    doc = cfg.to_json()
    a = ClusterConfig.from_json(doc)
    b = ClusterConfig.from_json(doc)
    for i in range(300):
        key = f"route-{i}"
        assert a.replica_set_for_key(key) == b.replica_set_for_key(key)
        assert a.token_for_key(key) == b.token_for_key(key)
    # and the hash itself is pinned (process-independent SHA-512 prefix):
    # a silent change here would strand every existing deployment's data
    assert a.token_for_key("route-0") == b.token_for_key("route-0")
    from mochi_tpu.cluster.config import stable_key_hash

    assert stable_key_hash("mochi") == int.from_bytes(
        __import__("hashlib").sha512(b"mochi").digest()[:8], "big"
    )

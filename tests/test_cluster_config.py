"""Cluster config: token ring, replica sets, quorum math, properties parsing.

Mirrors ``server/ClusterConfigurationTest.java``: token arithmetic (``:21-45``)
and key distribution over a bootstrap ring (``:47-103``) — and additionally
asserts keys spread across *distinct* replica sets, the behavior the
reference's ring-lookup bug (``ClusterConfiguration.java:215``) destroyed.
"""

import os

import pytest

from mochi_tpu.cluster import ClusterConfig, round_robin_token_assignment
from mochi_tpu.cluster.config import SHARD_TOKENS


def make_config(n=5, rf=4) -> ClusterConfig:
    return ClusterConfig.build(
        {f"server-{i}": f"127.0.0.1:{8001 + i}" for i in range(n)}, rf=rf
    )


def test_round_robin_assignment_covers_ring():
    ids = [f"s{i}" for i in range(4)]
    assignment = round_robin_token_assignment(ids)
    all_tokens = sorted(t for tokens in assignment.values() for t in tokens)
    assert all_tokens == list(range(SHARD_TOKENS))
    assert all(len(v) == SHARD_TOKENS // 4 for v in assignment.values())


def test_quorum_math():
    cfg = make_config(5, 4)
    assert cfg.f == 1
    assert cfg.quorum == 3
    cfg7 = make_config(7, 7)
    assert cfg7.f == 2
    assert cfg7.quorum == 5


def test_replica_set_size_and_uniqueness():
    cfg = make_config(5, 4)
    for key in [f"key-{i}" for i in range(200)]:
        rs = cfg.replica_set_for_key(key)
        assert len(rs) == 4
        assert len(set(rs)) == 4


def test_keys_distribute_across_servers():
    # ref: ClusterConfigurationTest.java:47-103 — 200 random keys, each server
    # should serve a healthy share.
    cfg = make_config(5, 4)
    counts = {sid: 0 for sid in cfg.servers}
    for i in range(200):
        for sid in cfg.replica_set_for_key(f"random-key-{i}"):
            counts[sid] += 1
    assert all(c >= 20 for c in counts.values()), counts


def test_distinct_replica_sets_exist():
    # The fixed ring walk must produce >1 distinct replica set (the reference
    # bug collapsed all keys onto one).
    cfg = make_config(5, 4)
    sets = {tuple(sorted(cfg.replica_set_for_key(f"key-{i}"))) for i in range(100)}
    assert len(sets) > 1


def test_config_keys_owned_everywhere():
    cfg = make_config(5, 4)
    assert cfg.replica_set_for_key("_CONFIG_epoch") == sorted(cfg.servers)
    assert cfg.owns_key("server-3", "_CONFIG_epoch")


def test_validation_rejects_bad_rf():
    with pytest.raises(ValueError):
        make_config(5, rf=3)
    with pytest.raises(ValueError):
        make_config(3, rf=4)


def test_properties_roundtrip():
    cfg = make_config(5, 4)
    cfg.public_keys["server-0"] = b"\x07" * 32
    parsed = ClusterConfig.from_properties(cfg.to_properties())
    assert parsed.rf == cfg.rf
    assert parsed.token_owners == cfg.token_owners
    assert {s.url for s in parsed.servers.values()} == {s.url for s in cfg.servers.values()}
    assert parsed.public_keys == {"server-0": b"\x07" * 32}


def test_reference_properties_file_parses():
    # The reference's shipped config loads unmodified (capability parity).
    path = "/root/reference/config/sample_config"
    if not os.path.exists(path):
        pytest.skip("reference checkout not present on this machine")
    with open(path) as fh:
        cfg = ClusterConfig.from_properties(fh.read())
    assert cfg.n_servers == 5
    assert cfg.rf == 4
    assert cfg.quorum == 3


def test_json_roundtrip():
    cfg = make_config(6, 4)
    parsed = ClusterConfig.from_json(cfg.to_json())
    assert parsed == cfg
